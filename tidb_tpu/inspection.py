"""Automatic inspection rules: SQL-queryable health findings over the
metrics time series.

Reference: TiDB's inspection framework
(information_schema.inspection_result, executor/inspection_result.go) —
a fixed rule set evaluates cluster metrics and emits (rule, item,
severity, value, reference, details) rows, so "is something wrong?" is
one SELECT instead of a dashboard crawl. The seed even carries a
vestigial `inspectkv` package pointing the same way.

Here each rule reads the metrics recorder's trailing window
(metrics.timeseries) — deltas for monotonic series, levels for gauges —
and fires with the offending window and the metric evidence attached.
Rules CLEAR on recovery by construction: the window slides, so once the
burst ages out of it the delta drops under threshold and the rule stops
firing. Each rule is chaos-tested by driving it with the failpoint (or
the real saturation mechanism) that produces its pathology.
"""

from __future__ import annotations

# rule thresholds: per-deployment tunables behind GLOBAL-only persisted
# tidb_tpu_inspection_* sysvars (SET GLOBAL applies live through
# set_threshold; bootstrap hydrates persisted values like the PR 9/10
# knobs). DEFAULTS is the one place tests and docs cite; the live values
# sit in _thresholds.
DEFAULTS: dict[str, float] = {
    # evaluation window: trailing samples of the recorder ring (at the
    # default 1 s interval ≈ the last half minute). Small enough that a
    # recovered incident ages out quickly; rules re-fire if it returns.
    "window_samples": 30,
    "degraded_burst": 5,        # tier fallbacks in the window
    "cache_min_lookups": 16,    # plane-cache traffic floor for the ratio
    "cache_hit_ratio": 0.5,     # below this, the cache collapsed
    "queue_timeouts": 1,        # admission-queue deadline rejections
    "pool_depth": 1.0,          # queue depth >= size × this
    "batch_expiries": 3,        # gather-window deadline expiries
    "mesh_skew": 2.0,           # max/mean per-shard rows
    "mesh_skew_rows": 256,      # ignore skew on trivial row counts
    # HBM governance: (pinned + reserved) / budget above this fires
    # hbm-pressure (any over-budget reservation in the window fires
    # regardless — the ledger let it through, but it is evidence)
    "hbm_pressure_ratio": 0.85,
    # kernel profiler: jit retraces of a SINGLE signature in the window
    # above this fires retrace-storm (a hot signature is churning the
    # jit cache — shape buckets too fine, or a cache cap too small)
    "retrace_burst": 4,
}

SYSVAR_PREFIX = "tidb_tpu_inspection_"

# sysvar defaults (string-valued, MySQL-style) — merged into
# sessionctx.SYSVAR_DEFAULTS so the whole family persists/hydrates
SYSVAR_DEFAULTS = {SYSVAR_PREFIX + k: (str(int(v))
                                       if float(v).is_integer()
                                       else str(v))
                   for k, v in DEFAULTS.items()}

_thresholds: dict[str, float] = dict(DEFAULTS)


def threshold(key: str) -> float:
    return _thresholds[key]


def set_threshold(name: str, value) -> None:
    """Apply one tidb_tpu_inspection_* sysvar (bare key accepted too).
    Raises ValueError on an unknown key or non-numeric/negative value —
    the SET handler surfaces it typed."""
    key = name.lower()
    if key.startswith(SYSVAR_PREFIX):
        key = key[len(SYSVAR_PREFIX):]
    if key not in DEFAULTS:
        raise ValueError(f"unknown inspection threshold {name!r}")
    v = float(str(value).strip())
    if v < 0:
        raise ValueError(f"{name} must be >= 0")
    if key == "window_samples":
        v = max(2.0, v)
    _thresholds[key] = v


def reset_thresholds() -> None:
    _thresholds.clear()
    _thresholds.update(DEFAULTS)


def _severity(value: float, threshold: float) -> str:
    """warning at the threshold, critical at 4x it."""
    return "critical" if value >= 4 * threshold else "warning"


def _result(rule: str, item: str, severity: str, value, reference: str,
            details: str, begin: float, end: float) -> dict:
    return {"rule": rule, "item": item, "severity": severity,
            "value": value, "reference": reference, "details": details,
            "window_begin": begin, "window_end": end}


def _rule_degradation_burst(d: dict, begin: float, end: float) -> list:
    """A burst of tier fallbacks (device→CPU, join→numpy, combine→host,
    mesh→single-device, batch→solo, columnar→rows) inside the window:
    answers stayed correct, but the fast tier is not holding. Driven by
    the device/* and device/mesh_collective failpoints."""
    out = []
    for name, delta in sorted(d.items()):
        if not name.startswith("copr.degraded_") or \
                delta < threshold("degraded_burst"):
            continue
        kind = name[len("copr.degraded_"):]
        out.append(_result(
            "degradation-burst", kind,
            _severity(delta, threshold("degraded_burst")), int(delta),
            f">= {threshold('degraded_burst'):g} fallbacks/window",
            f"{name} rose {int(delta)} in the window — the "
            f"{kind} tier is degrading instead of serving",
            begin, end))
    return out


def _rule_cache_collapse(d: dict, begin: float, end: float) -> list:
    """Plane-cache hit ratio collapsed under real traffic: repeat
    fan-outs are re-packing every region (version churn, epoch churn,
    or a byte budget too small). Driven by the cache/no_admit
    failpoint."""
    hits = d.get("copr.plane_cache.hits", 0.0)
    misses = d.get("copr.plane_cache.misses", 0.0)
    total = hits + misses
    if total < threshold("cache_min_lookups"):
        return []
    ratio = hits / total
    if ratio >= threshold("cache_hit_ratio"):
        return []
    evs = int(d.get("copr.plane_cache.evictions", 0.0))
    return [_result(
        "plane-cache-collapse", "hit-ratio",
        "critical" if ratio < threshold("cache_hit_ratio") / 2 else "warning",
        round(ratio, 3), f">= {threshold('cache_hit_ratio'):g} hit ratio",
        f"{int(hits)} hits / {int(total)} lookups in the window"
        f" ({evs} evictions) — repeat scans are re-packing",
        begin, end)]


def _rule_admission_saturation(d: dict, begin: float, end: float) -> list:
    """The admission front doors are shedding or stacking load: queued
    wire connections died on the queue deadline (server gate), or the
    shared drain pool's backlog outgrew its worker bound."""
    out = []
    timeouts = d.get("server.conn_queue_timeouts", 0.0)
    rejected = d.get("server.rejected_connections", 0.0)
    shed = timeouts + rejected
    if shed >= threshold("queue_timeouts"):
        out.append(_result(
            "admission-saturation", "conn-queue",
            _severity(shed, max(threshold("queue_timeouts"), 4)), int(shed),
            f"< {threshold('queue_timeouts'):g} typed rejections/window",
            f"{int(timeouts)} queue-deadline timeouts + "
            f"{int(rejected)} queue-full rejections (ER 1040) in the "
            "window — raise max_connections/queue depth or shed load",
            begin, end))
    depth = d.get("copr.drain_pool.queue_depth", 0.0)
    size = d.get("copr.drain_pool.size", 0.0)
    if size > 0 and depth >= max(1.0, size * threshold("pool_depth")):
        out.append(_result(
            "admission-saturation", "drain-pool",
            "critical" if depth >= 4 * size else "warning", int(depth),
            f"queue depth < pool size ({int(size)})",
            f"{int(depth)} region drains queued behind "
            f"{int(size)} workers — fan-outs are waiting on the pool, "
            "not on data", begin, end))
    return out


def _rule_batch_expiry_spike(d: dict, begin: float, end: float) -> list:
    """Statement deadlines expiring inside the micro-batch gather
    window: the window (or a stalled leader) is eating the latency
    budget of below-floor statements. Driven by the sched/batch_window
    failpoint under tidb_tpu_max_execution_time."""
    n = d.get("sched.window_expiries", 0.0)
    if n < threshold("batch_expiries"):
        return []
    return [_result(
        "batch-expiry-spike", "gather-window",
        _severity(n, threshold("batch_expiries")), int(n),
        f"< {threshold('batch_expiries'):g} expiries/window",
        f"{int(n)} statement deadlines expired inside the shared batch "
        "gather window — shrink tidb_tpu_batch_window_ms or raise the "
        "statement deadline", begin, end)]


def _rule_mesh_shard_skew(d: dict, begin: float, end: float) -> list:
    """One shard is dragging the mesh collective: the per-shard row
    imbalance of the last mesh dispatch exceeds the skew bound at a
    non-trivial row count (region placement is hash-uniform over
    regions, not over ROWS — a hot region skews its home shard)."""
    if d.get("copr.mesh.dispatches", 0.0) < 1:
        return []    # no mesh traffic in the window: a stale skew gauge
        #              from long-gone dispatches is not a live finding
    skew = d.get("copr.mesh.shard_skew", 0.0)
    mx = d.get("copr.mesh.shard_rows_max", 0.0)
    if skew < threshold("mesh_skew") or mx < threshold("mesh_skew_rows"):
        return []
    return [_result(
        "mesh-shard-skew", "placement",
        "critical" if skew >= 2 * threshold("mesh_skew") else "warning",
        round(skew, 3), f"max/mean < {threshold('mesh_skew'):g}",
        f"fullest shard holds {int(mx)} rows at {skew:.2f}x the mean — "
        "collectives wait on one shard (hot region or placement skew)",
        begin, end)]


def _rule_hbm_pressure(d: dict, begin: float, end: float) -> list:
    """Device memory is running out of headroom: pinned planes plus
    in-flight reservations sit above the pressure ratio of the
    configured budget, or a reservation crossed the budget outright
    (device.hbm.over_budget rose). Under sustained pressure the join
    tier is partitioning into passes and the plane cache is skipping
    device pins — correct, but slower than a budget raise or a smaller
    pinned working set. Only fires with an explicit budget
    (tidb_tpu_hbm_budget_bytes > 0); driven by the ledger itself under
    a tiny budget."""
    budget = d.get("device.hbm.budget", 0.0)
    if budget <= 0:
        return []
    used = d.get("device.hbm.pinned", 0.0) + d.get("device.hbm.reserved",
                                                   0.0)
    over = d.get("device.hbm.over_budget", 0.0)
    ratio = used / budget
    if ratio < threshold("hbm_pressure_ratio") and over < 1:
        return []
    peak = d.get("device.hbm.hw.total", used)
    return [_result(
        "hbm-pressure", "ledger",
        "critical" if ratio >= 1.0 or over >= 1 else "warning",
        round(ratio, 3),
        f"(pinned + reserved) / budget < "
        f"{threshold('hbm_pressure_ratio'):g}",
        f"{int(used)} of {int(budget)} budgeted HBM bytes in use "
        f"(peak {int(peak)}, {int(over)} over-budget reservations in "
        "the window) — "
        "oversized joins are partitioning into passes and the plane "
        "cache is skipping device pins; raise "
        "tidb_tpu_hbm_budget_bytes or shrink the pinned working set",
        begin, end)]


def _rule_retrace_storm(d: dict, begin: float, end: float) -> list:
    """One kernel signature is retracing over and over inside the
    window: its jit cache entry keeps missing (an unstable shape leaking
    past the capacity buckets, or a cache cap churning hot entries), so
    the device pays compilation instead of execution. Evidence comes
    from the profiler's per-signature metric families — the trace_us
    share says how much of the signature's device time went to
    retracing."""
    from tidb_tpu import profiler
    out = []
    pre = profiler.METRIC_PREFIX
    for name, delta in sorted(d.items()):
        if not name.startswith(pre + "jit_misses."):
            continue
        if delta < threshold("retrace_burst"):
            continue
        label = name[len(pre + "jit_misses."):]
        dev = d.get(f"{pre}device_us.{label}", 0.0)
        trc = d.get(f"{pre}trace_us.{label}", 0.0)
        share = (trc / dev) if dev > 0 else 0.0
        out.append(_result(
            "retrace-storm", label,
            _severity(delta, threshold("retrace_burst")), int(delta),
            f"< {threshold('retrace_burst'):g} retraces/window/signature",
            f"signature {label} retraced {int(delta)}x in the window — "
            f"{int(trc)}us of its {int(dev)}us device time "
            f"({share:.0%}) went to tracing, not executing; stabilize "
            "the shape buckets or raise the kernel cache caps",
            begin, end))
    return out


RULES = (_rule_degradation_burst, _rule_cache_collapse,
         _rule_admission_saturation, _rule_batch_expiry_spike,
         _rule_mesh_shard_skew, _rule_hbm_pressure,
         _rule_retrace_storm)


def inspect(window: int | None = None) -> list[dict]:
    """Evaluate every rule over the recorder's trailing window, ended
    at a fresh registry walk (one walk serves both the history bucket
    and the rules — and findings always judge CURRENT state); returns
    findings most-severe first (stable within severity)."""
    from tidb_tpu.metrics.timeseries import recorder
    if window is None:
        window = int(threshold("window_samples"))
    deltas, begin, end = recorder.sample_window(window)
    if not deltas:
        return []
    out: list[dict] = []
    for rule in RULES:
        out.extend(rule(deltas, begin, end))
    out.sort(key=lambda r: (r["severity"] != "critical", r["rule"],
                            r["item"]))
    return out
