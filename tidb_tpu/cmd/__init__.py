"""Workload drivers (reference: cmd/benchdb, cmd/benchkv)."""
