"""benchdb: SQL workload driver, wall-clock per job.

Reference: cmd/benchdb/main.go:36-50 — a comma-separated job list
(create, truncate, insert:lo_hi, update-random:lo_hi:n,
update-range:lo_hi:n, select:lo_hi:n, query:<sql>:n, gc) runs in order
against a store, printing the wall time of each. The reference drives a
live PD/TiKV cluster; here the same jobs run against any engine URL
(memory/local/cluster) or over the wire with --addr host:port.

Run:  python -m tidb_tpu.cmd.benchdb --store memory --run \
          create,insert:0_10000,select:0_10000:10,gc
"""

from __future__ import annotations

import argparse
import random
import sys
import time


DEFAULT_JOBS = ("create,truncate,insert:0_10000,update-random:0_10000:1000,"
                "select:0_10000:10,update-range:5000_5100:100,"
                "select:0_10000:10,gc,select:0_10000:10")


class _WireRunner:
    def __init__(self, addr: str):
        from tidb_tpu.server import Client
        host, _, port = addr.rpartition(":")
        self.c = Client(host or "127.0.0.1", int(port))
        self.c.query("create database if not exists bench")
        self.c.query("use bench")

    def run(self, sql: str):
        return self.c.query(sql)


class _LibRunner:
    def __init__(self, url: str):
        from tidb_tpu.session import Session, new_store
        self.store = new_store(url)
        self.s = Session(self.store)
        self.s.execute("create database if not exists bench")
        self.s.execute("use bench")

    def run(self, sql: str):
        return self.s.execute(sql)


class BenchDB:
    def __init__(self, runner, table: str, batch: int, blob: int):
        self.r = runner
        self.table = table
        self.batch = batch
        self.blob_val = "x" * blob
        self.rng = random.Random(0)

    # ---- jobs (cmd/benchdb main.go job dispatch) ----

    def create(self):
        self.r.run(f"create table if not exists {self.table} "
                   "(id bigint primary key, name varchar(32), "
                   "exp bigint, data blob)")

    def truncate(self):
        self.r.run(f"truncate table {self.table}")

    def insert(self, lo: int, hi: int):
        ids = list(range(lo, hi))
        for i in range(0, len(ids), self.batch):
            chunk = ids[i:i + self.batch]
            vals = ", ".join(f"({j}, 'name{j}', {j * 10}, "
                             f"'{self.blob_val}')" for j in chunk)
            self.r.run(f"insert into {self.table} values {vals}")

    def update_random(self, lo: int, hi: int, n: int):
        for i in range(0, n, self.batch):
            stmts = []
            for _ in range(min(self.batch, n - i)):
                rid = self.rng.randint(lo, hi - 1)
                stmts.append(f"update {self.table} set exp = exp + 1 "
                             f"where id = {rid}")
            self.r.run("; ".join(stmts))

    def update_range(self, lo: int, hi: int, n: int):
        for _ in range(n):
            self.r.run(f"update {self.table} set exp = exp + 1 "
                       f"where id >= {lo} and id < {hi}")

    def select(self, lo: int, hi: int, n: int):
        for _ in range(n):
            self.r.run(f"select id, name, exp from {self.table} "
                       f"where id >= {lo} and id < {hi}")

    def query(self, sql: str, n: int):
        for _ in range(n):
            self.r.run(sql)

    def gc(self):
        store = getattr(self.r, "store", None)
        if store is None:
            return  # wire mode: GC runs inside the server's workers
        if hasattr(store, "run_gc"):
            store.run_gc()
        elif hasattr(store, "compact"):
            store.compact(max_age_ms=0)

    def run_job(self, spec: str):
        name, _, rest = spec.partition(":")
        t0 = time.time()
        if name == "create":
            self.create()
        elif name == "truncate":
            self.truncate()
        elif name == "insert":
            lo, hi = rest.split("_")
            self.insert(int(lo), int(hi))
        elif name == "update-random":
            rng, n = rest.split(":")
            lo, hi = rng.split("_")
            self.update_random(int(lo), int(hi), int(n))
        elif name == "update-range":
            rng, n = rest.split(":")
            lo, hi = rng.split("_")
            self.update_range(int(lo), int(hi), int(n))
        elif name == "select":
            rng, n = rest.split(":")
            lo, hi = rng.split("_")
            self.select(int(lo), int(hi), int(n))
        elif name == "query":
            sql, _, n = rest.rpartition(":")
            self.query(sql, int(n))
        elif name == "gc":
            self.gc()
        else:
            raise SystemExit(f"unknown job {name!r}")
        print(f"{spec}: {time.time() - t0:.3f}s")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchdb")
    ap.add_argument("--store", default="memory://benchdb",
                    help="engine URL (memory:// | local:// | cluster://N/)")
    ap.add_argument("--addr", default="",
                    help="host:port of a running server (wire mode)")
    ap.add_argument("--table", default="bench_db")
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--blob", type=int, default=32)
    ap.add_argument("--run", default=DEFAULT_JOBS)
    args = ap.parse_args(argv)
    runner = _WireRunner(args.addr) if args.addr else _LibRunner(args.store)
    bench = BenchDB(runner, args.table, args.batch, args.blob)
    for job in args.run.split(","):
        bench.run_job(job.strip())
    return 0


if __name__ == "__main__":
    sys.exit(main())
