"""benchkv: raw transactional-KV throughput (TPS).

Reference: cmd/benchkv/main.go:35-38,84-113 — N keys split across C
workers, each worker committing batched set-transactions, TPS logged.
Runs against any engine URL; the cluster engine exercises the full 2PC
path.

Run:  python -m tidb_tpu.cmd.benchkv --store cluster://3/bench -N 100000
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def worker(store, keys: list[int], value: bytes, batch: int,
           stats: dict, lock: threading.Lock) -> None:
    done = failed = 0
    for i in range(0, len(keys), batch):
        chunk = keys[i:i + batch]
        try:
            txn = store.begin()
            for k in chunk:
                txn.set(b"bkv_%012d" % k, value)
            txn.commit()
            done += len(chunk)
        except Exception:
            try:
                txn.rollback()
            except Exception:
                pass
            failed += len(chunk)
    with lock:
        stats["done"] += done
        stats["failed"] += failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="benchkv")
    ap.add_argument("--store", default="memory://benchkv")
    ap.add_argument("-N", type=int, default=100_000, help="key count")
    ap.add_argument("-C", type=int, default=8, help="worker threads")
    ap.add_argument("-V", type=int, default=5, help="value size bytes")
    ap.add_argument("--batch", type=int, default=100,
                    help="keys per transaction")
    args = ap.parse_args(argv)

    from tidb_tpu.session import new_store
    store = new_store(args.store)
    value = b"v" * args.V
    per = (args.N + args.C - 1) // args.C
    stats = {"done": 0, "failed": 0}
    lock = threading.Lock()
    threads = []
    t0 = time.time()
    for w in range(args.C):
        keys = list(range(w * per, min((w + 1) * per, args.N)))
        t = threading.Thread(target=worker,
                             args=(store, keys, value, args.batch, stats,
                                   lock))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    dt = time.time() - t0
    print(f"N={args.N} C={args.C} batch={args.batch}: "
          f"{stats['done']} keys committed, {stats['failed']} failed, "
          f"{dt:.2f}s, {stats['done'] / dt:,.0f} keys/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
