"""Transaction retry helpers.

Reference: kv/txn.go (RunInNewTxn, BackOff with exponential jitter).
The sleep itself routes through kv.backoff's injectable RNG/sleeper
hooks (set_test_hooks), so chaos/failpoint tests assert exact backoff
schedules instead of sleeping wall-clock, and an ambient statement
deadline (tidb_tpu_max_execution_time) bounds meta-txn retries typed.
"""

from __future__ import annotations

import logging
from typing import Callable, TypeVar

from tidb_tpu import errors
from tidb_tpu.kv.backoff import txn_retry_sleep

log = logging.getLogger(__name__)

MAX_RETRY_CNT = 10
RETRY_BACKOFF_BASE_MS = 1
RETRY_BACKOFF_CAP_MS = 100

T = TypeVar("T")


def backoff(attempts: int) -> float:
    """Sleep with capped exponential backoff + jitter; returns slept
    seconds. Deterministic under kv.backoff.set_test_hooks."""
    upper = min(RETRY_BACKOFF_CAP_MS,
                RETRY_BACKOFF_BASE_MS * (1 << min(attempts, 20)))
    return txn_retry_sleep(upper)


def run_in_new_txn(store, retryable: bool, fn: Callable[[object], T],
                   max_retries: int = MAX_RETRY_CNT) -> T:
    """Run fn(txn) in a fresh transaction, retrying on write conflict.

    Reference: kv/txn.go RunInNewTxn — used by DDL/meta operations that must
    win eventually. Callers whose txns conflict with EVERY concurrent
    write (DDL reorg batches) pass a larger max_retries, matching the
    reference's ~100-attempt meta-txn budget.
    """
    last_err: BaseException | None = None
    for attempt in range(max_retries):
        txn = store.begin()
        try:
            result = fn(txn)
            txn.commit()
            return result
        except BaseException as e:
            try:
                txn.rollback()
            except errors.TiDBError:
                pass
            if not (retryable and errors.is_retryable(e)):
                raise
            last_err = e
            log.debug("run_in_new_txn retry %d: %s", attempt, e)
            from tidb_tpu import metrics
            metrics.counter("kv.txn_retries").inc()
            backoff(attempt)
    from tidb_tpu import metrics
    metrics.counter("kv.txn_retry_exhausted").inc()
    raise last_err  # type: ignore[misc]
