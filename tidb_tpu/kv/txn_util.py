"""Transaction retry helpers.

Reference: kv/txn.go (RunInNewTxn, BackOff with exponential jitter).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, TypeVar

from tidb_tpu import errors

log = logging.getLogger(__name__)

MAX_RETRY_CNT = 10
RETRY_BACKOFF_BASE_MS = 1
RETRY_BACKOFF_CAP_MS = 100

T = TypeVar("T")


def backoff(attempts: int) -> float:
    """Sleep with capped exponential backoff + jitter; returns slept seconds."""
    upper = min(RETRY_BACKOFF_CAP_MS, RETRY_BACKOFF_BASE_MS * (1 << min(attempts, 20)))
    ms = random.uniform(0, upper)
    time.sleep(ms / 1000.0)
    return ms / 1000.0


def run_in_new_txn(store, retryable: bool, fn: Callable[[object], T],
                   max_retries: int = MAX_RETRY_CNT) -> T:
    """Run fn(txn) in a fresh transaction, retrying on write conflict.

    Reference: kv/txn.go RunInNewTxn — used by DDL/meta operations that must
    win eventually. Callers whose txns conflict with EVERY concurrent
    write (DDL reorg batches) pass a larger max_retries, matching the
    reference's ~100-attempt meta-txn budget.
    """
    last_err: BaseException | None = None
    for attempt in range(max_retries):
        txn = store.begin()
        try:
            result = fn(txn)
            txn.commit()
            return result
        except BaseException as e:
            try:
                txn.rollback()
            except errors.TiDBError:
                pass
            if not (retryable and errors.is_retryable(e)):
                raise
            last_err = e
            log.debug("run_in_new_txn retry %d: %s", attempt, e)
            backoff(attempt)
    raise last_err  # type: ignore[misc]
