"""In-memory sorted write buffer.

Reference: kv/memdb_buffer.go (goleveldb memdb-backed). Python version: a
dict plus a lazily-resorted key list — writes are O(1), the sorted view is
rebuilt only when iteration follows a write. Deletions are tombstones
(empty value) so UnionStore can shadow snapshot keys, matching the
reference's convention (kv/union_store.go len(v)==0 ⇒ ErrNotExist).
"""

from __future__ import annotations

import bisect
from typing import Iterator

from tidb_tpu import errors
from tidb_tpu.kv.kv import Mutator, Retriever

TOMBSTONE = b""


class MemBuffer(Retriever, Mutator):
    __slots__ = ("_data", "_sorted", "_dirty")

    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._sorted: list[bytes] = []
        self._dirty = False

    def __len__(self) -> int:
        return len(self._data)

    def get(self, key: bytes) -> bytes:
        try:
            v = self._data[key]
        except KeyError:
            raise errors.KeyNotExistsError(f"key not exist: {key!r}") from None
        if v == TOMBSTONE:
            raise errors.KeyNotExistsError(f"key deleted: {key!r}")
        return v

    def get_raw(self, key: bytes) -> bytes | None:
        """Tombstone-visible get (None = never written, b'' = deleted)."""
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._data:
            self._dirty = True
        self._data[key] = value

    def set_many(self, pairs) -> None:
        """Bulk write (iterable of (key, value)): one dict.update instead
        of a Python call per key — the bulk-load hot path."""
        self._data.update(pairs)
        self._dirty = True

    def delete(self, key: bytes) -> None:
        self.set(key, TOMBSTONE)

    def _view(self) -> list[bytes]:
        if self._dirty:
            self._sorted = sorted(self._data)
            self._dirty = False
        return self._sorted

    def iterate(self, start: bytes = b"", end: bytes | None = None,
                include_tombstones: bool = False) -> Iterator[tuple[bytes, bytes]]:
        view = self._view()
        i = bisect.bisect_left(view, start)
        while i < len(view):
            k = view[i]
            if end is not None and k >= end:
                return
            v = self._data[k]
            if include_tombstones or v != TOMBSTONE:
                yield k, v
            i += 1

    def iterate_reverse(self, start: bytes = b"", end: bytes | None = None,
                        include_tombstones: bool = False) -> Iterator[tuple[bytes, bytes]]:
        """Descending over [start, end) — mirrors localstore reverse seek."""
        view = self._view()
        i = (bisect.bisect_left(view, end) if end is not None else len(view)) - 1
        while i >= 0:
            k = view[i]
            if k < start:
                return
            v = self._data[k]
            if include_tombstones or v != TOMBSTONE:
                yield k, v
            i -= 1
