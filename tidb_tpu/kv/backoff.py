"""Unified backoff budget + statement deadline.

Reference: store/tikv/backoff.go — a Backoffer is ONE object per
operation carrying per-error-kind exponential schedules, a shared sleep
budget, and (here) an absolute deadline derived from
`tidb_tpu_max_execution_time`. Every retry ladder in the cluster tier
(region RPC, coprocessor worklist, lock resolution, 2PC, optimistic
statement replay) sleeps against the SAME statement-scoped instance, so
a fault storm exhausts one typed budget instead of N independent 2-second
ladders, and exhaustion surfaces a DeadlineExceededError carrying the
full retry history.

Scope plumbing: the session attaches a statement Backoffer to this
module's thread-local at the top of each statement; the coprocessor
fan-out re-attaches it on its worker threads (cluster/store.py run()),
so sleeps on ANY thread of the statement draw from the one budget and
observe the one deadline. Code that retries outside a statement
(GC, DDL job queue) uses a standalone instance.

Determinism hooks: `set_test_hooks(rng=..., sleeper=...)` swaps the
module RNG and sleeper so chaos/failpoint tests assert EXACT backoff
schedules without sleeping wall-clock; kv.txn_util routes through the
same hooks.
"""

from __future__ import annotations

import random
import threading
import time

from tidb_tpu import errors

# per-kind exponential bases (ms) — store/tikv/backoff.go's typed configs
BASES_MS = {"rpc": 2, "txn_lock": 10, "region_miss": 1,
            "server_busy": 20, "pd": 5, "txn_retry": 1}
CAPS_MS = {"txn_retry": 100}
DEFAULT_BASE_MS = 5
DEFAULT_CAP_MS = 200

DEFAULT_BUDGET_MS = 2000        # standalone ladders (GC, background)
DEFAULT_STMT_BUDGET_MS = 10_000  # the per-statement shared budget

HISTORY_CAP = 64

# ---- injectable determinism hooks (kv/txn_util routes through these) ----

_default_rng = random.Random()
_rng = _default_rng
_sleep = time.sleep


def set_test_hooks(rng=None, sleeper=None) -> None:
    """Swap the RNG and/or sleeper module-wide (pass None to keep one).
    Tests assert exact schedules with rng=random.Random(seed) and a
    recording sleeper; ALWAYS pair with reset_test_hooks()."""
    global _rng, _sleep
    if rng is not None:
        _rng = rng
    if sleeper is not None:
        _sleep = sleeper


def reset_test_hooks() -> None:
    global _rng, _sleep
    _rng = _default_rng
    _sleep = time.sleep


def compute_sleep_ms(kind: str, attempt: int) -> float:
    """The jittered exponential sleep for one retry — the single formula
    every ladder (Backoffer and kv.txn_util's legacy helper) uses."""
    base = BASES_MS.get(kind, DEFAULT_BASE_MS)
    cap = CAPS_MS.get(kind, DEFAULT_CAP_MS)
    return min(base * (2 ** min(attempt, 30)), cap) \
        * (0.5 + _rng.random() / 2)


class Backoffer:
    """Exponential backoff with per-kind schedules, one shared budget,
    an optional absolute deadline, and an attached retry history.

    Thread-safe: the fan-out's worker threads share the statement's
    instance (that IS the unified budget). `budget_ms=None` disables the
    budget (deadline-only ladders, e.g. DDL meta retries)."""

    BASES_MS = BASES_MS   # back-compat alias (older call sites read it)

    def __init__(self, budget_ms: int | None = DEFAULT_BUDGET_MS,
                 deadline: float | None = None):
        self.budget_ms = budget_ms
        self.deadline = deadline          # absolute time.monotonic() secs
        self.spent_ms = 0.0
        self.attempts: dict[str, int] = {}
        self.history: list[tuple] = []    # (kind, attempt, sleep_ms, err)
        self._dropped = 0
        self._lock = threading.Lock()

    def fork(self) -> "Backoffer":
        """Worker-thread handle sharing THIS budget/deadline/history —
        all state is lock-protected, so the instance itself is the
        shared ledger (tikv's Fork, with a genuinely shared budget)."""
        return self

    # ---- deadline ----

    def remaining_s(self) -> float | None:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def check_deadline(self, what: str = "") -> None:
        """Raise DeadlineExceededError when the statement deadline has
        passed — cheap enough for per-attempt loop headers."""
        if self.deadline is not None and time.monotonic() >= self.deadline:
            raise self.deadline_error(what)

    def deadline_error(self, what: str = "",
                       cause: BaseException | None = None):
        err = errors.DeadlineExceededError(
            "statement deadline exceeded"
            + (f" during {what}" if what else "")
            + f"; retries: [{self.history_summary()}]")
        err.history = list(self.history)
        if cause is not None:
            err.__cause__ = cause
        return err

    def history_summary(self) -> str:
        with self._lock:
            ents = list(self.history)
            dropped = self._dropped
        parts = [f"{kind}#{attempt}:{sleep_ms:.1f}ms({msg})"
                 for kind, attempt, sleep_ms, msg in ents]
        if dropped:
            parts.append(f"... +{dropped} more")
        return ", ".join(parts)

    # ---- the ladder ----

    def backoff(self, kind: str, err: Exception) -> float:
        """Record one retry of `kind`, sleep its jittered exponential
        slot against the shared budget/deadline, and return the slept
        milliseconds. Exhaustion (budget OR deadline) raises
        DeadlineExceededError with the ladder history attached."""
        with self._lock:
            n = self.attempts.get(kind, 0)
            self.attempts[kind] = n + 1
            sleep_ms = compute_sleep_ms(kind, n)
            over_budget = self.budget_ms is not None \
                and self.spent_ms + sleep_ms > self.budget_ms
            if not over_budget:
                self.spent_ms += sleep_ms
            if len(self.history) < HISTORY_CAP:
                self.history.append((kind, n, round(sleep_ms, 2),
                                     str(err)[:120]))
            else:
                self._dropped += 1
        from tidb_tpu import metrics, tracing
        if over_budget:
            metrics.counter("kv.backoff_exhausted").inc()
            e = errors.DeadlineExceededError(
                f"backoff budget {self.budget_ms}ms exhausted at {kind}: "
                f"{err}; retries: [{self.history_summary()}]")
            e.history = list(self.history)
            raise e from err
        remaining = self.remaining_s()
        if remaining is not None:
            if remaining <= 0:
                metrics.counter("kv.backoff_exhausted").inc()
                raise self.deadline_error(f"{kind} backoff", err)
            sleep_ms = min(sleep_ms, remaining * 1000.0)
        metrics.counter(f"kv.backoff.{kind}").inc()
        tracing.count("backoff_retries")
        tracing.count("backoff_ms", int(round(sleep_ms)))
        # span attribution: on a fan-out worker the current span is its
        # region_task, so the trace shows which task slept how long
        sp = tracing.current()
        if not sp.is_noop:
            sp.inc("backoff_retries")
            sp.inc("backoff_ms", int(round(sleep_ms)))
        _sleep(sleep_ms / 1000.0)
        if self.deadline is not None and time.monotonic() >= self.deadline:
            metrics.counter("kv.backoff_exhausted").inc()
            raise self.deadline_error(f"{kind} backoff", err)
        return sleep_ms


def txn_retry_sleep(upper_ms: float) -> float:
    """kv/txn_util's uniform backoff slot, routed through this module's
    determinism hooks (set_test_hooks makes the schedule exact under
    test) and the AMBIENT statement deadline. Budget-EXEMPT on purpose:
    meta/DDL retries must win eventually, so they never draw down the
    statement's shared sleep budget — but a statement deadline still
    bounds them typed. Returns slept seconds."""
    ms = _rng.uniform(0, upper_ms)
    bo = current()
    if bo is not None and bo.deadline is not None:
        remaining = bo.remaining_s()
        if remaining <= 0:
            from tidb_tpu import metrics
            metrics.counter("kv.backoff_exhausted").inc()
            raise bo.deadline_error("txn retry backoff")
        ms = min(ms, remaining * 1000.0)
    from tidb_tpu import metrics, tracing
    metrics.counter("kv.backoff.txn_retry").inc()
    tracing.count("backoff_retries")
    tracing.count("backoff_ms", int(round(ms)))
    _sleep(ms / 1000.0)
    return ms / 1000.0


# ---------------------------------------------------------------------------
# statement scope: thread-local ambient Backoffer
# ---------------------------------------------------------------------------

_tls = threading.local()


def attach(bo: Backoffer | None):
    """Make `bo` the thread's ambient Backoffer; returns a token for
    detach(). The session attaches per statement; fan-out workers attach
    the statement's instance handed to them."""
    prev = getattr(_tls, "bo", None)
    _tls.bo = bo
    return prev


def detach(token) -> None:
    _tls.bo = token


def current() -> Backoffer | None:
    return getattr(_tls, "bo", None)


def current_or(budget_ms: int | None = DEFAULT_BUDGET_MS) -> Backoffer:
    """The ambient statement Backoffer — every ladder of one statement
    shares its budget — or a fresh standalone one outside a statement
    (background work: GC, domain reloads)."""
    bo = current()
    return bo if bo is not None else Backoffer(budget_ms=budget_ms)


def ambient_deadline() -> float | None:
    bo = current()
    return bo.deadline if bo is not None else None
