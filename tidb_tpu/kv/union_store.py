"""UnionStore: private write buffer overlaid on a snapshot.

Reference: kv/union_store.go:24-203 (unionStore, lazyMemBuffer,
PresumeKeyNotExists condition pairs) and kv/union_iter.go (merged
dirty+snapshot iteration).
"""

from __future__ import annotations

import heapq
from typing import Iterator

from tidb_tpu import errors
from tidb_tpu.kv.kv import Mutator, Retriever, Snapshot
from tidb_tpu.kv.membuffer import MemBuffer, TOMBSTONE

OPT_PRESUME_KEY_NOT_EXISTS = "presume_key_not_exists"


class UnionStore(Retriever, Mutator):
    def __init__(self, snapshot: Snapshot):
        self.snapshot = snapshot
        self.buffer = MemBuffer()
        # key → expected-error marker for lazily-checked existence assumptions
        # (kv/union_store.go markLazyConditionPair). INSERT uses this to skip
        # a read per unique key and batch-check at commit.
        self._lazy_conditions: dict[bytes, errors.TiDBError | None] = {}
        self._presume_not_exists = False

    # ---- options ----
    def set_option(self, opt: str, val=True) -> None:
        if opt == OPT_PRESUME_KEY_NOT_EXISTS:
            self._presume_not_exists = bool(val)

    def del_option(self, opt: str) -> None:
        if opt == OPT_PRESUME_KEY_NOT_EXISTS:
            self._presume_not_exists = False

    # ---- retriever/mutator ----
    def get(self, key: bytes) -> bytes:
        v = self.buffer.get_raw(key)
        if v is not None:
            if v == TOMBSTONE:
                raise errors.KeyNotExistsError(f"key deleted: {key!r}")
            return v
        if self._presume_not_exists:
            # assume absent; record the assumption for commit-time verification
            self._lazy_conditions[key] = errors.KeyExistsError(
                _dup_entry_message(key))
            raise errors.KeyNotExistsError(f"key presumed not exist: {key!r}")
        return self.snapshot.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self.buffer.set(key, value)

    def set_many(self, pairs) -> None:
        self.buffer.set_many(pairs)

    def delete(self, key: bytes) -> None:
        self.buffer.delete(key)

    def iterate(self, start: bytes = b"", end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Merged ascending iteration; buffer shadows snapshot (union_iter.go)."""
        return _merge(self.buffer.iterate(start, end, include_tombstones=True),
                      self.snapshot.iterate(start, end))

    def iterate_reverse(self, start: bytes = b"", end: bytes | None = None):
        snap_rev = getattr(self.snapshot, "iterate_reverse", None)
        snap_it = snap_rev(start, end) if snap_rev else iter(())
        return _merge(self.buffer.iterate_reverse(start, end, include_tombstones=True),
                      snap_it, reverse=True)

    # ---- commit-time checks ----
    def check_lazy_conditions(self) -> None:
        """Verify PresumeKeyNotExists assumptions against the snapshot
        (kv/union_store.go CheckLazyConditionPairs)."""
        if not self._lazy_conditions:
            return
        found = self.snapshot.batch_get(list(self._lazy_conditions))
        for key, err in self._lazy_conditions.items():
            if key in found and err is not None:
                raise err
        self._lazy_conditions.clear()

    def walk_buffer(self) -> Iterator[tuple[bytes, bytes]]:
        """All buffered mutations including tombstones (for commit)."""
        return self.buffer.iterate(include_tombstones=True)


def _merge(dirty_it, snap_it, reverse: bool = False) -> Iterator[tuple[bytes, bytes]]:
    """Two-way ordered merge where the dirty side wins on equal keys and
    tombstones suppress snapshot entries."""
    sentinel = object()

    def nxt(it):
        return next(it, sentinel)

    d, s = nxt(dirty_it), nxt(snap_it)
    while d is not sentinel or s is not sentinel:
        if s is sentinel:
            take_dirty = True
        elif d is sentinel:
            take_dirty = False
        else:
            if d[0] == s[0]:
                s = nxt(snap_it)  # shadowed
                continue
            take_dirty = (d[0] < s[0]) != reverse
        if take_dirty:
            k, v = d
            d = nxt(dirty_it)
            if v != TOMBSTONE:
                yield k, v
        else:
            yield s
            s = nxt(snap_it)


def _dup_entry_message(key: bytes) -> str:
    """Human MySQL-1062 message for a duplicate key: decode the row key to
    its handle (or an index key to its datums) instead of leaking raw
    bytes over the wire (executor_write.go dup-entry formatting)."""
    try:
        from tidb_tpu import tablecodec as tc
        _tid, handle = tc.decode_row_key(key)   # raises if not a row key
        return f"Duplicate entry '{handle}' for key 'PRIMARY'"
    except Exception:
        pass
    return f"Duplicate entry for key {key!r}"
