"""Core KV interfaces.

Reference: kv/kv.go:37-181. The Client/Request/Response trio is the
coprocessor boundary (kv/kv.go:94-137): the executor marshals a SelectRequest
into Request.data, the storage backend fans it out per region, and Response
streams one region's partial result per next() call. This is exactly where
the TPU execution tier plugs in (ops.TpuClient) without the executor knowing.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterator

from tidb_tpu import errors

# request types (kv/kv.go:103-111)
REQ_TYPE_SELECT = 101
REQ_TYPE_INDEX = 102

REQ_SUB_TYPE_BASIC = 0
REQ_SUB_TYPE_DESC = 10000
REQ_SUB_TYPE_GROUP_BY = 10001
REQ_SUB_TYPE_TOPN = 10002
REQ_SUB_TYPE_SIGNATURE = 10003  # expression capability probes carry the op name


@dataclass(frozen=True)
class KeyRange:
    """[start, end) over encoded keys. Reference: kv/key.go KeyRange."""
    start: bytes
    end: bytes

    def is_point(self) -> bool:
        return len(self.end) == len(self.start) + 1 and self.end[:-1] == self.start \
            and self.end[-1] == 0


class Retriever(abc.ABC):
    @abc.abstractmethod
    def get(self, key: bytes) -> bytes:
        """Raise KeyNotExistsError if absent."""

    @abc.abstractmethod
    def iterate(self, start: bytes, end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Ascending (key, value) pairs in [start, end)."""

    def get_or_none(self, key: bytes) -> bytes | None:
        try:
            return self.get(key)
        except errors.KeyNotExistsError:
            return None


class Mutator(abc.ABC):
    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...


class Snapshot(Retriever):
    def batch_get(self, keys) -> dict[bytes, bytes]:
        out = {}
        for k in keys:
            v = self.get_or_none(k)
            if v is not None:
                out[k] = v
        return out


class Transaction(Retriever, Mutator, abc.ABC):
    """Reference: kv/kv.go:140-153 — snapshot-isolated, buffered writes."""

    @abc.abstractmethod
    def commit(self) -> None: ...

    @abc.abstractmethod
    def rollback(self) -> None: ...

    @abc.abstractmethod
    def start_ts(self) -> int: ...

    def valid(self) -> bool:
        return True

    def lock_keys(self, *keys: bytes) -> None:
        """SELECT FOR UPDATE support; optimistic backends may no-op."""

    # options (kv/kv.go SetOption): PresumeKeyNotExists etc.
    def set_option(self, opt: str, val: Any = True) -> None:
        pass

    def del_option(self, opt: str) -> None:
        pass


@dataclass
class Request:
    """Coprocessor request. Reference: kv/kv.go:113-127."""
    tp: int
    data: Any                      # SelectRequest (copr.select) — in-proc object
    key_ranges: list[KeyRange] = field(default_factory=list)
    keep_order: bool = False
    desc: bool = False
    concurrency: int = 1


class Response(abc.ABC):
    """Reference: kv/kv.go:129-137 — one region's result bytes per next()."""

    @abc.abstractmethod
    def next(self) -> Any | None:
        """Next partial result (SelectResponse) or None when exhausted."""

    def close(self) -> None:
        """Release fan-out resources; consumers that stop early (LIMIT)
        MUST call this so pipelined workers are not parked forever."""


class Client(abc.ABC):
    """Reference: kv/kv.go:94-100."""

    @abc.abstractmethod
    def send(self, req: Request) -> Response: ...

    @abc.abstractmethod
    def support_request_type(self, req_type: int, sub_type: Any) -> bool:
        """Capability probe gating pushdown planning (plan/expr_to_pb.go:92)."""


class Storage(abc.ABC):
    """Reference: kv/kv.go:155-170."""

    @abc.abstractmethod
    def begin(self) -> Transaction: ...

    @abc.abstractmethod
    def get_snapshot(self, version: int | None = None) -> Snapshot: ...

    @abc.abstractmethod
    def get_client(self) -> Client: ...

    @abc.abstractmethod
    def current_version(self) -> int: ...

    def uuid(self) -> str:
        return f"store-{id(self):x}"

    def close(self) -> None:
        pass


class Driver(abc.ABC):
    """Reference: kv/kv.go:147 kv.Driver + tidb.go:172-187 URL registry."""

    @abc.abstractmethod
    def open(self, path: str) -> Storage: ...


_drivers: dict[str, Driver] = {}
_stores: dict[str, Storage] = {}


def register_driver(scheme: str, driver: Driver) -> None:
    if scheme in _drivers:
        raise errors.KVError(f"driver {scheme!r} already registered")
    _drivers[scheme] = driver


def open_store(url: str) -> Storage:
    """'scheme://path' → cached Storage (tidb.go NewStore/domain-per-store)."""
    if "://" not in url:
        raise errors.KVError(f"malformed store url {url!r}")
    scheme, path = url.split("://", 1)
    if scheme not in _drivers:
        raise errors.KVError(f"unknown store scheme {scheme!r}")
    key = f"{scheme}://{path}"
    if path and key in _stores:
        return _stores[key]
    store = _drivers[scheme].open(path)
    if path:
        _stores[key] = store
    return store


def ms_to_version(ms: int) -> int:
    """Wall-clock milliseconds → TSO version (physical-ms << 18 | logical);
    the single owner of the version bit layout shared by both stores'
    oracles (store/tikv/oracle scheme)."""
    return ms << 18


class ActiveReads:
    """Thread-safe weak registry of live snapshots/transactions. GC
    workers clamp their safepoint to oldest() so a long-running reader can
    never have the versions it is reading reclaimed mid-scan."""

    def __init__(self):
        import threading
        import weakref
        self._set = weakref.WeakSet()
        self._lock = threading.Lock()

    def add(self, obj) -> None:
        with self._lock:
            self._set.add(obj)

    def oldest(self) -> int | None:
        """Smallest start version among live, unfinished readers."""
        with self._lock:
            objs = list(self._set)
        ts = [getattr(o, "version", None) or getattr(o, "_start_ts", None)
              for o in objs
              if getattr(o, "_valid", True)]   # finished txns don't pin
        ts = [t for t in ts if t is not None]
        return min(ts) if ts else None


def close_store(url: str) -> None:
    """Close and evict a cached store (server shutdown / restart tests —
    the next open_store on the same URL recovers from the engine)."""
    store = _stores.pop(url, None)
    if store is not None:
        store.close()
