"""Transactional KV abstraction.

Reference: kv/kv.go (Retriever/Mutator/Transaction/Snapshot/Storage/Client),
kv/union_store.go, kv/memdb_buffer.go, kv/txn.go.
"""

from tidb_tpu.kv.kv import (  # noqa: F401
    Retriever,
    Mutator,
    Transaction,
    Snapshot,
    Storage,
    Client,
    Request,
    Response,
    KeyRange,
    Driver,
    register_driver,
    open_store,
    REQ_TYPE_SELECT,
    REQ_TYPE_INDEX,
    REQ_SUB_TYPE_BASIC,
    REQ_SUB_TYPE_DESC,
    REQ_SUB_TYPE_GROUP_BY,
    REQ_SUB_TYPE_TOPN,
)
from tidb_tpu.kv.membuffer import MemBuffer  # noqa: F401
from tidb_tpu.kv.union_store import UnionStore  # noqa: F401
# NOTE: txn_util.backoff (the function) is deliberately NOT re-exported —
# `tidb_tpu.kv.backoff` is the unified-Backoffer MODULE; the package attr
# must resolve to it unambiguously
from tidb_tpu.kv.txn_util import run_in_new_txn  # noqa: F401
