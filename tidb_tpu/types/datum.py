"""Datum: the tagged-union SQL value.

Reference: util/types/datum.go:53 (Datum struct with Kind* constants) and
util/types/compare.go (cross-type comparison). Unlike the Go original, which
packs small values into x/b fields, this is a two-slot Python object; the hot
path (the coprocessor) does not use Datums at all — it runs columnar (see
tidb_tpu.ops), so Datum stays simple and correct rather than micro-optimized.
"""

from __future__ import annotations

import enum
from decimal import Decimal
from typing import Any

from tidb_tpu import errors


class Kind(enum.IntEnum):
    NULL = 0
    INT64 = 1
    UINT64 = 2
    FLOAT64 = 3
    STRING = 4
    BYTES = 5
    DECIMAL = 6
    DURATION = 7
    TIME = 8
    INTERFACE = 9        # row tuples in some executors (rare)
    ENUM = 10            # KindMysqlEnum (util/types/enum.go)
    SET = 11             # KindMysqlSet (util/types/set.go)
    BIT = 12             # KindMysqlBit (util/types/bit.go)
    HEX = 13             # KindMysqlHex (util/types/hex.go)
    MIN_NOT_NULL = 100   # range boundary sentinels (util/types/datum.go KindMinNotNull)
    MAX_VALUE = 101


class Datum:
    __slots__ = ("kind", "val")

    def __init__(self, kind: Kind, val: Any = None):
        self.kind = kind
        self.val = val

    # ---- constructors ----
    @staticmethod
    def null() -> "Datum":
        return NULL

    @staticmethod
    def i64(v: int) -> "Datum":
        v = int(v)
        if not (-(1 << 63) <= v < (1 << 63)):
            raise errors.OverflowError_(f"int64 out of range: {v}")
        return Datum(Kind.INT64, v)

    @staticmethod
    def u64(v: int) -> "Datum":
        v = int(v)
        if not (0 <= v < (1 << 64)):
            raise errors.OverflowError_(f"uint64 out of range: {v}")
        return Datum(Kind.UINT64, v)

    @staticmethod
    def f64(v: float) -> "Datum":
        return Datum(Kind.FLOAT64, float(v))

    @staticmethod
    def string(v: str) -> "Datum":
        return Datum(Kind.STRING, v)

    @staticmethod
    def bytes_(v: bytes) -> "Datum":
        return Datum(Kind.BYTES, v)

    @staticmethod
    def dec(v) -> "Datum":
        if not isinstance(v, Decimal):
            v = Decimal(str(v))
        return Datum(Kind.DECIMAL, v)

    # ---- predicates ----
    def is_null(self) -> bool:
        return self.kind == Kind.NULL

    # ---- accessors (raise on kind mismatch like GetInt64 would panic) ----
    def get_int(self) -> int:
        if self.kind in (Kind.INT64, Kind.UINT64):
            return self.val
        raise errors.TypeError_(f"datum kind {self.kind!r} is not an int")

    def get_float(self) -> float:
        if self.kind == Kind.FLOAT64:
            return self.val
        raise errors.TypeError_(f"datum kind {self.kind!r} is not a float")

    def get_string(self) -> str:
        if self.kind == Kind.STRING:
            return self.val
        if self.kind == Kind.BYTES:
            return self.val.decode("utf-8", "replace")
        if self.kind in (Kind.ENUM, Kind.SET):
            return self.val.name
        if self.kind in (Kind.BIT, Kind.HEX):
            return self.val.to_bytes().decode("utf-8", "replace")
        raise errors.TypeError_(f"datum kind {self.kind!r} is not a string")

    def get_bytes(self) -> bytes:
        if self.kind == Kind.BYTES:
            return self.val
        if self.kind == Kind.STRING:
            return self.val.encode("utf-8")
        if self.kind in (Kind.BIT, Kind.HEX):
            return self.val.to_bytes()
        if self.kind in (Kind.ENUM, Kind.SET):
            return self.val.name.encode("utf-8")
        raise errors.TypeError_(f"datum kind {self.kind!r} is not bytes")

    # ---- numeric view used by comparison/arith coercion ----
    def as_number(self):
        """Return a Python number preserving exactness where possible."""
        k = self.kind
        if k in (Kind.INT64, Kind.UINT64):
            return self.val
        if k == Kind.FLOAT64:
            return self.val
        if k == Kind.DECIMAL:
            return self.val
        if k == Kind.STRING:
            return _str_to_number(self.val)
        if k == Kind.BYTES:
            return _str_to_number(self.val.decode("utf-8", "replace"))
        if k == Kind.DURATION:
            return self.val.to_number()
        if k == Kind.TIME:
            return self.val.to_number()
        if k in (Kind.ENUM, Kind.SET, Kind.BIT, Kind.HEX):
            return self.val.value   # exact int (enum index / bitmask)
        raise errors.TypeError_(f"cannot coerce {k!r} to number")

    def __repr__(self):  # pragma: no cover - debug aid
        if self.kind == Kind.NULL:
            return "Datum(NULL)"
        return f"Datum({self.kind.name}, {self.val!r})"

    def __eq__(self, other):
        """Structural equality within a kind-class (numeric / string / time).

        NB: deliberately narrower than compare_datum's MySQL coercion (which
        would make "12" == 12 and break the hash/eq contract). SQL equality
        goes through compare_datum; this is for sets/dicts in tests and plans.
        """
        if not isinstance(other, Datum):
            return NotImplemented
        a, b = self.kind, other.kind
        if a == Kind.NULL or b == Kind.NULL:
            return a == b
        if a in _NUM_KINDS and b in _NUM_KINDS:
            return _cmp_num(self.val, other.val) == 0
        if a in _STR_KINDS and b in _STR_KINDS:
            return self.get_bytes() == other.get_bytes()
        return a == b and self.val == other.val

    def __hash__(self):
        # Python's numeric hash is cross-type consistent (hash(1) == hash(1.0)
        # == hash(Decimal(1))), so numeric kinds hash by value directly.
        if self.kind in _NUM_KINDS:
            return hash(self.val)
        if self.kind in _STR_KINDS:
            return hash(self.get_bytes())
        return hash((int(self.kind), self.val))


_STR_KINDS = (Kind.STRING, Kind.BYTES)

NULL = Datum(Kind.NULL)
MIN_NOT_NULL = Datum(Kind.MIN_NOT_NULL)
MAX_VALUE = Datum(Kind.MAX_VALUE)


def datum_from_py(v: Any) -> Datum:
    """Lift a Python value into a Datum (test/datagen convenience)."""
    if v is None:
        return NULL
    if isinstance(v, Datum):
        return v
    if isinstance(v, bool):
        return Datum.i64(int(v))
    if isinstance(v, int):
        if v > (1 << 63) - 1:
            return Datum.u64(v)
        return Datum.i64(v)
    if isinstance(v, float):
        return Datum.f64(v)
    if isinstance(v, Decimal):
        return Datum.dec(v)
    if isinstance(v, str):
        return Datum.string(v)
    if isinstance(v, (bytes, bytearray)):
        return Datum.bytes_(bytes(v))
    from tidb_tpu.types.time_types import Duration, Time
    if isinstance(v, (Duration, Time)):
        return Datum(Kind.DURATION if isinstance(v, Duration) else Kind.TIME, v)
    from tidb_tpu.types.enumset import Bit, Enum, Hex, SetVal
    if isinstance(v, Enum):
        return Datum(Kind.ENUM, v)
    if isinstance(v, SetVal):
        return Datum(Kind.SET, v)
    if isinstance(v, Bit):
        return Datum(Kind.BIT, v)
    if isinstance(v, Hex):
        return Datum(Kind.HEX, v)
    raise errors.TypeError_(f"cannot make datum from {type(v)!r}")


_NUM_PREFIX_RE = __import__("re").compile(
    r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")


def _str_to_number(s: str):
    """MySQL-ish lenient string→number: longest numeric prefix, else 0."""
    m = _NUM_PREFIX_RE.match(s.strip())
    if not m:
        return 0
    text = m.group(0)
    if "." in text or m.group(2):
        return float(text)
    return int(text)


_NUM_KINDS = (Kind.INT64, Kind.UINT64, Kind.FLOAT64, Kind.DECIMAL)


def compare_datum(a: Datum, b: Datum) -> int:
    """Three-way compare with MySQL cross-type coercion.

    Reference: util/types/datum.go CompareDatum / compare.go. NULL sorts before
    everything; MIN_NOT_NULL/MAX_VALUE are range-boundary sentinels.
    """
    ak, bk = a.kind, b.kind
    if ak == Kind.NULL:
        return 0 if bk == Kind.NULL else -1
    if bk == Kind.NULL:
        return 1
    if ak == Kind.MIN_NOT_NULL:
        return 0 if bk == Kind.MIN_NOT_NULL else -1
    if bk == Kind.MIN_NOT_NULL:
        return 1
    if ak == Kind.MAX_VALUE:
        return 0 if bk == Kind.MAX_VALUE else 1
    if bk == Kind.MAX_VALUE:
        return -1

    # same-class fast paths
    if ak in (Kind.STRING, Kind.BYTES) and bk in (Kind.STRING, Kind.BYTES):
        # binary collation over utf-8 bytes (the 2016 reference is binary-collation only)
        x, y = a.get_bytes(), b.get_bytes()
        return -1 if x < y else (0 if x == y else 1)
    if ak == Kind.TIME and bk == Kind.TIME:
        return a.val.compare(b.val)
    if ak == Kind.DURATION and bk == Kind.DURATION:
        return (a.val.nanos > b.val.nanos) - (a.val.nanos < b.val.nanos)

    # temporal vs string: coerce the string to the temporal type (MySQL
    # comparison coercion; util/types/compare.go). Falling through to the
    # numeric path would take the string's numeric PREFIX ('1998-09-02' →
    # 1998) and silently mis-compare date filters.
    if ak == Kind.TIME and bk in (Kind.STRING, Kind.BYTES):
        t = _parse_time_or_none(b.get_string())
        if t is not None:
            return a.val.compare(t)
    elif bk == Kind.TIME and ak in (Kind.STRING, Kind.BYTES):
        t = _parse_time_or_none(a.get_string())
        if t is not None:
            return -b.val.compare(t)

    # enum/set/bit/hex vs string: string semantics (enum compares by item
    # NAME against strings, by index against numbers — MySQL's dual nature;
    # util/types/compare.go coerce rules)
    _ESBH = (Kind.ENUM, Kind.SET, Kind.BIT, Kind.HEX)
    if (ak in _ESBH and bk in (Kind.STRING, Kind.BYTES)) or \
            (bk in _ESBH and ak in (Kind.STRING, Kind.BYTES)):
        # raw bytes both sides: bit/hex are BINARY strings (0xFF = CHAR(255))
        x, y = a.get_bytes(), b.get_bytes()
        return -1 if x < y else (0 if x == y else 1)

    x, y = a.as_number(), b.as_number()
    return _cmp_num(x, y)


def _parse_time_or_none(s: str):
    from tidb_tpu.types.time_types import parse_time
    try:
        return parse_time(s)
    except Exception:
        return None


def _cmp_num(x, y) -> int:
    # int/Decimal compare exactly; float comparisons go through float
    if isinstance(x, float) or isinstance(y, float):
        xf, yf = float(x), float(y)
        return (xf > yf) - (xf < yf)
    return (x > y) - (x < y)
