"""FieldType: column type metadata.

Reference: util/types/field_type.go (FieldType struct) and
evaluator type-merge rules used by plan/typeinferer.go.
"""

from __future__ import annotations

from tidb_tpu import mysqldef as my


UNSPECIFIED_LENGTH = -1


class FieldType:
    __slots__ = ("tp", "flag", "flen", "decimal", "charset", "collate", "elems")

    def __init__(self, tp: int = my.TypeNull, flag: int = 0,
                 flen: int = UNSPECIFIED_LENGTH, decimal: int = UNSPECIFIED_LENGTH,
                 charset: str = "utf8", collate: str = "utf8_bin", elems=None):
        self.tp = tp
        self.flag = flag
        self.flen = flen
        self.decimal = decimal
        self.charset = charset
        self.collate = collate
        self.elems = elems or []  # enum/set literals

    # ---- predicates ----
    def is_unsigned(self) -> bool:
        return my.has_unsigned_flag(self.flag)

    def is_string(self) -> bool:
        return self.tp in my.STRING_TYPES

    def is_integer(self) -> bool:
        return self.tp in my.INTEGER_TYPES

    def is_float(self) -> bool:
        return self.tp in my.FLOAT_TYPES

    def is_decimal(self) -> bool:
        return self.tp in (my.TypeNewDecimal, my.TypeDecimal)

    def is_time(self) -> bool:
        return self.tp in my.TIME_TYPES

    def is_numeric(self) -> bool:
        return self.is_integer() or self.is_float() or self.is_decimal()

    def is_ci_collation(self) -> bool:
        """Case-insensitive string column (utf8_general_ci etc.): compare/
        group/sort casefolded; binary key order is NOT value order."""
        from tidb_tpu import charset as _cs
        return self.is_string() and _cs.is_ci_collation(self.collate)

    def clone(self) -> "FieldType":
        ft = FieldType(self.tp, self.flag, self.flen, self.decimal,
                       self.charset, self.collate, list(self.elems))
        return ft

    def __repr__(self):  # pragma: no cover
        return f"FieldType(tp=0x{self.tp:02x}, flag={self.flag}, flen={self.flen}, dec={self.decimal})"

    def __eq__(self, other):
        return (isinstance(other, FieldType) and self.tp == other.tp
                and self.flag == other.flag and self.flen == other.flen
                and self.decimal == other.decimal)

    def compact_str(self) -> str:
        names = {
            my.TypeTiny: "tinyint", my.TypeShort: "smallint", my.TypeInt24: "mediumint",
            my.TypeLong: "int", my.TypeLonglong: "bigint", my.TypeFloat: "float",
            my.TypeDouble: "double", my.TypeNewDecimal: "decimal", my.TypeVarchar: "varchar",
            my.TypeString: "char", my.TypeBlob: "text", my.TypeDate: "date",
            my.TypeDatetime: "datetime", my.TypeTimestamp: "timestamp",
            my.TypeDuration: "time", my.TypeYear: "year", my.TypeBit: "bit",
            my.TypeNull: "null", my.TypeEnum: "enum", my.TypeSet: "set",
        }
        s = names.get(self.tp, f"type({self.tp})")
        if self.tp in (my.TypeEnum, my.TypeSet) and self.elems:
            items = ",".join("'" + e.replace("'", "''") + "'"
                             for e in self.elems)
            s += f"({items})"
        elif self.tp == my.TypeBit and self.flen and self.flen > 0:
            s += f"({self.flen})"
        elif self.flen >= 0 and self.tp in (my.TypeVarchar, my.TypeString, my.TypeNewDecimal):
            if self.decimal >= 0 and self.tp == my.TypeNewDecimal:
                s += f"({self.flen},{self.decimal})"
            else:
                s += f"({self.flen})"
        if self.is_unsigned():
            s += " unsigned"
        return s

    def type_name(self) -> str:
        """Bare type word (information_schema DATA_TYPE column)."""
        return self.compact_str().split("(")[0].split(" ")[0]


def new_field_type(tp: int) -> FieldType:
    ft = FieldType(tp)
    ft.flen = my.default_field_length(tp)
    return ft


# merge order for binary-operation result types (simplified
# util/types/field_type.go MergeFieldType / evaluator numeric rules)
_MERGE_ORDER = [
    my.TypeDouble, my.TypeFloat, my.TypeNewDecimal, my.TypeLonglong, my.TypeLong,
    my.TypeInt24, my.TypeShort, my.TypeTiny,
]


def merge_numeric(a: FieldType, b: FieldType) -> FieldType:
    """Result type of an arithmetic op over a and b."""
    if a.tp == my.TypeNull:
        return b.clone()
    if b.tp == my.TypeNull:
        return a.clone()
    for tp in _MERGE_ORDER:
        if a.tp == tp or b.tp == tp:
            ft = new_field_type(tp)
            if tp == my.TypeNewDecimal:
                ft.decimal = max(a.decimal if a.decimal >= 0 else 0,
                                 b.decimal if b.decimal >= 0 else 0)
            return ft
    # non-numeric operands (strings/dates) act as double in arithmetic
    return new_field_type(my.TypeDouble)


def agg_field_type(name: str, arg: FieldType) -> FieldType:
    """Result FieldType of an aggregate function.

    Reference: the AggFields synthesis in plan/physical_plans.go:265-283 —
    count→bigint, sum→decimal (exactness!), avg→decimal/double, min/max→arg.
    """
    name = name.lower()
    if name == "count":
        ft = new_field_type(my.TypeLonglong)
        ft.flag |= my.NotNullFlag
        return ft
    if name == "sum":
        if arg.is_float():
            return new_field_type(my.TypeDouble)
        ft = new_field_type(my.TypeNewDecimal)
        ft.decimal = arg.decimal if arg.decimal >= 0 else 0
        return ft
    if name == "avg":
        if arg.is_float():
            return new_field_type(my.TypeDouble)
        ft = new_field_type(my.TypeNewDecimal)
        base = arg.decimal if arg.decimal >= 0 else 0
        ft.decimal = min(base + 4, 30)
        return ft
    if name in ("min", "max", "first", "firstrow", "first_row"):
        return arg.clone()
    if name == "group_concat":
        return new_field_type(my.TypeVarString)
    return new_field_type(my.TypeDouble)
