"""Time and Duration values.

Reference: util/types/time.go, util/types/duration helpers. Backed by Python
datetime; the columnar tier encodes Time as int64 "packed number"
(YYYYMMDDHHMMSS * 1e6 + micros ordering-compatible integer) so date
comparisons vectorize as int64 compares on device — see ops/columnar.py.
"""

from __future__ import annotations

import datetime as _dt
import re

from tidb_tpu import errors, mysqldef as my


class Duration:
    """TIME type: signed duration with fractional-second precision."""

    __slots__ = ("nanos", "fsp")

    def __init__(self, nanos: int, fsp: int = 0):
        self.nanos = int(nanos)
        self.fsp = fsp

    def to_number(self):
        """hhmmss.ffffff numeric form used in numeric contexts."""
        neg = self.nanos < 0
        n = abs(self.nanos)
        secs, frac = divmod(n, 1_000_000_000)
        h, rem = divmod(secs, 3600)
        m, s = divmod(rem, 60)
        v = h * 10000 + m * 100 + s + frac / 1e9
        return -v if neg else v

    def __str__(self):
        neg = "-" if self.nanos < 0 else ""
        n = abs(self.nanos)
        secs, frac = divmod(n, 1_000_000_000)
        h, rem = divmod(secs, 3600)
        m, s = divmod(rem, 60)
        out = f"{neg}{h:02d}:{m:02d}:{s:02d}"
        if self.fsp > 0:
            out += "." + f"{frac:09d}"[: self.fsp]
        return out

    def __repr__(self):  # pragma: no cover
        return f"Duration({self})"

    def __eq__(self, other):
        return isinstance(other, Duration) and self.nanos == other.nanos

    def __hash__(self):
        return hash(self.nanos)


class Time:
    """DATE/DATETIME/TIMESTAMP value."""

    __slots__ = ("dt", "tp", "fsp")

    def __init__(self, dt: _dt.datetime, tp: int = my.TypeDatetime, fsp: int = 0):
        self.dt = dt
        self.tp = tp
        self.fsp = fsp

    def compare(self, other: "Time") -> int:
        return (self.dt > other.dt) - (self.dt < other.dt)

    def to_number(self):
        d = self.dt
        if self.tp == my.TypeDate:
            return d.year * 10000 + d.month * 100 + d.day
        v = (d.year * 10**10 + d.month * 10**8 + d.day * 10**6
             + d.hour * 10**4 + d.minute * 100 + d.second)
        if d.microsecond:
            return v + d.microsecond / 1e6
        return v

    def to_packed_int(self) -> int:
        """Order-preserving int64 encoding (codec + columnar plane format)."""
        d = self.dt
        ymd = (d.year * 13 + d.month) << 5 | d.day
        hms = d.hour << 12 | d.minute << 6 | d.second
        return ((ymd << 17 | hms) << 24) | d.microsecond

    @staticmethod
    def from_packed_int(v: int, tp: int = my.TypeDatetime, fsp: int = 0) -> "Time":
        micro = v & ((1 << 24) - 1)
        ymdhms = v >> 24
        ymd = ymdhms >> 17
        hms = ymdhms & ((1 << 17) - 1)
        day = ymd & 31
        ym = ymd >> 5
        year, month = divmod(ym, 13)
        second = hms & 63
        minute = (hms >> 6) & 63
        hour = hms >> 12
        return Time(_dt.datetime(year, month, day, hour, minute, second, micro), tp, fsp)

    def __str__(self):
        if self.tp == my.TypeDate:
            return self.dt.strftime("%Y-%m-%d")
        s = self.dt.strftime("%Y-%m-%d %H:%M:%S")
        if self.fsp > 0:
            s += f".{self.dt.microsecond:06d}"[: self.fsp + 1]
        return s

    def __repr__(self):  # pragma: no cover
        return f"Time({self})"

    def __eq__(self, other):
        return isinstance(other, Time) and self.dt == other.dt

    def __hash__(self):
        return hash(self.dt)


_TIME_RE = re.compile(
    r"^\s*(\d{4})[-/](\d{1,2})[-/](\d{1,2})"
    r"(?:[T ](\d{1,2}):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,9}))?)?)?\s*$"
)
# 'HH:MM[:SS]' — MySQL reads a two-part duration as hours:minutes, not
# minutes:seconds, so the hour group is mandatory and seconds optional
_DUR_RE = re.compile(r"^\s*(-)?(\d+):(\d{1,2})(?::(\d{1,2}))?(?:\.(\d{1,9}))?\s*$")


def parse_time(s: str, tp: int = my.TypeDatetime, fsp: int = 6) -> Time:
    m = _TIME_RE.match(s)
    if not m:
        # compact forms: YYYYMMDD / YYYYMMDDHHMMSS
        t = s.strip()
        if t.isdigit() and len(t) in (8, 14):
            try:
                if len(t) == 8:
                    d = _dt.datetime.strptime(t, "%Y%m%d")
                else:
                    d = _dt.datetime.strptime(t, "%Y%m%d%H%M%S")
                return Time(d, tp, fsp)
            except ValueError as e:
                raise errors.TypeError_(f"invalid time literal {s!r}") from e
        raise errors.TypeError_(f"invalid time literal {s!r}")
    y, mo, d = int(m.group(1)), int(m.group(2)), int(m.group(3))
    h = int(m.group(4) or 0)
    mi = int(m.group(5) or 0)
    se = int(m.group(6) or 0)
    frac = m.group(7) or ""
    micro = int((frac + "000000")[:6]) if frac else 0
    try:
        dtv = _dt.datetime(y, mo, d, h, mi, se, micro)
    except ValueError as e:
        raise errors.TypeError_(f"invalid time literal {s!r}") from e
    if tp == my.TypeDate:
        dtv = dtv.replace(hour=0, minute=0, second=0, microsecond=0)
    return Time(dtv, tp, fsp)


def parse_duration(s: str, fsp: int = 6) -> Duration:
    m = _DUR_RE.match(s)
    if not m:
        raise errors.TypeError_(f"invalid duration literal {s!r}")
    neg, hh, mm, ss, frac = m.groups()
    nanos = ((int(hh) * 3600 + int(mm) * 60 + int(ss or 0)) * 1_000_000_000)
    if frac:
        nanos += int((frac + "0" * 9)[:9])
    if neg:
        nanos = -nanos
    return Duration(nanos, fsp)
