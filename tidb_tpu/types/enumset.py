"""ENUM / SET / BIT / HEX value semantics.

Reference: util/types/enum.go (Enum, ParseEnumName/Value), set.go
(Set, ParseSetName/Value), bit.go (Bit, ParseBit), hex.go (Hex, ParseHex).

Storage model follows the reference's flatten/unflatten contract
(tablecodec + types.Flatten): these values travel the codec as plain
uint64/int64 (their .value), and the column's FieldType (elems / flen)
restores the rich object on read — so the memcomparable wire format and
the native C codec stay untouched.
"""

from __future__ import annotations

from tidb_tpu import errors


class Enum:
    """One item of an ENUM('a','b',…) column: name + 1-based index.
    Sorts and computes numerically by index; displays as its name."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: int):
        self.name = name
        self.value = int(value)

    def to_number(self) -> float:
        return float(self.value)

    def __str__(self):
        return self.name

    def __repr__(self):  # pragma: no cover
        return f"Enum({self.name!r}, {self.value})"

    def __eq__(self, other):
        return isinstance(other, Enum) and self.value == other.value \
            and self.name == other.name

    def __hash__(self):
        return hash((self.name, self.value))


class SetVal:
    """A SET('a','b',…) value: comma-joined member names + bitmask over
    the column's element list (bit i ↔ elems[i])."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: int):
        self.name = name
        self.value = int(value)

    def to_number(self) -> float:
        return float(self.value)

    def __str__(self):
        return self.name

    def __repr__(self):  # pragma: no cover
        return f"SetVal({self.name!r}, 0b{self.value:b})"

    def __eq__(self, other):
        return isinstance(other, SetVal) and self.value == other.value

    def __hash__(self):
        return hash(("set", self.value))


class Bit:
    """BIT(width) value: unsigned integer with a display width. Numeric
    contexts use the integer; string contexts use the big-endian bytes
    (MySQL returns bit columns as binary strings)."""

    __slots__ = ("value", "width")

    MIN_WIDTH = 1
    MAX_WIDTH = 64
    UNSPECIFIED_WIDTH = -1

    def __init__(self, value: int, width: int):
        self.value = int(value)
        self.width = width

    def to_number(self) -> float:
        return float(self.value)

    def to_bytes(self) -> bytes:
        nbytes = max((self.width + 7) // 8, 1)
        return self.value.to_bytes(nbytes, "big")

    def __str__(self):
        return f"0b{self.value:0{max(self.width, 1)}b}"

    def __repr__(self):  # pragma: no cover
        return f"Bit({self})"

    def __eq__(self, other):
        return isinstance(other, Bit) and self.value == other.value

    def __hash__(self):
        return hash(("bit", self.value))


class Hex:
    """Hexadecimal literal (0x61, x'61', X'61'): integer in numeric
    contexts, the decoded bytes in string contexts — the dual nature MySQL
    defers until the literal meets an operator. `nbytes` preserves the
    literal's written byte length so x'0041' keeps its leading zero byte
    (and x'' stays empty) in string contexts."""

    __slots__ = ("value", "nbytes")

    def __init__(self, value: int, nbytes: int | None = None):
        self.value = int(value)
        self.nbytes = nbytes

    def to_number(self) -> float:
        return float(self.value)

    def to_bytes(self) -> bytes:
        if self.nbytes is not None:
            return self.value.to_bytes(self.nbytes, "big") if self.nbytes \
                else b""
        s = f"{self.value:x}"
        if len(s) % 2:
            s = "0" + s
        return bytes.fromhex(s)

    def __str__(self):
        s = f"{self.value:X}"
        return "0x0" + s if len(s) % 2 else "0x" + s

    def __repr__(self):  # pragma: no cover
        return f"Hex({self})"

    def __eq__(self, other):
        return isinstance(other, Hex) and self.value == other.value

    def __hash__(self):
        return hash(("hex", self.value))


# ---------------------------------------------------------------------------
# parsing (ParseEnumName/Value, ParseSetName/Value, ParseBit, ParseHex)
# ---------------------------------------------------------------------------

def parse_enum_name(elems: list[str], name: str) -> Enum:
    for i, n in enumerate(elems):
        if n.lower() == name.lower():
            return Enum(n, i + 1)
    # not an item name — maybe a number in string form
    try:
        return parse_enum_value(elems, int(name, 0))
    except ValueError:
        pass
    raise errors.TypeError_(f"item {name!r} is not in enum {elems}")


def parse_enum_value(elems: list[str], number: int) -> Enum:
    if number < 1 or number > len(elems):
        raise errors.TypeError_(
            f"number {number} overflows enum boundary [1, {len(elems)}]")
    return Enum(elems[number - 1], number)


def parse_set_name(elems: list[str], name: str) -> SetVal:
    if not name:
        return SetVal("", 0)
    marked = {s.lower() for s in name.split(",")}
    items, value = [], 0
    for i, n in enumerate(elems):
        if n.lower() in marked:
            marked.discard(n.lower())
            value |= 1 << i
            items.append(n)
    if not marked:
        return SetVal(",".join(items), value)
    try:
        return parse_set_value(elems, int(name, 0))
    except ValueError:
        pass
    raise errors.TypeError_(f"item {name!r} is not in set {elems}")


def parse_set_value(elems: list[str], number: int) -> SetVal:
    if number < 0 or number >= (1 << len(elems)):
        # the reference parses via uint64, so a negative can never reach
        # its bounds check — reject, don't let Python's signed int wrap
        raise errors.TypeError_(
            f"number {number} overflows set {elems}")
    items = [n for i, n in enumerate(elems) if number & (1 << i)]
    return SetVal(",".join(items), number)


def parse_bit(s: str, width: int) -> Bit:
    """b'0101' / B'0101' / 0b0101 → Bit. width == UNSPECIFIED_WIDTH pads
    to the next byte (reference bit.go ParseBit)."""
    raw = s
    if s and s[0] in "bB" and len(s) > 1 and s[1] == "'":
        s = s[1:].strip("'")
    elif s[:2] in ("0b", "0B"):
        s = s[2:]
    else:
        raise errors.TypeError_(f"invalid bit literal {raw!r}")
    if not s or any(c not in "01" for c in s):
        raise errors.TypeError_(f"invalid bit literal {raw!r}")
    if width == Bit.UNSPECIFIED_WIDTH:
        width = (len(s) + 7) & ~7
    width = max(width, Bit.MIN_WIDTH)
    if width > Bit.MAX_WIDTH or len(s) > width:
        raise errors.TypeError_(
            f"bit literal {raw!r} does not fit BIT({width})")
    return Bit(int(s, 2), width)


def parse_hex(s: str) -> Hex:
    """x'1A' / X'1A' / 0x1A → Hex (reference hex.go ParseHex)."""
    raw = s
    if s and s[0] in "xX" and len(s) > 1 and s[1] == "'":
        s = s[1:].strip("'")
        if len(s) % 2:
            raise errors.TypeError_(
                f"hex literal {raw!r} must have an even number of digits")
    elif s[:2] in ("0x", "0X"):
        s = s[2:]
    else:
        raise errors.TypeError_(f"invalid hex literal {raw!r}")
    if not s:
        return Hex(0, 0)
    try:
        return Hex(int(s, 16), (len(s) + 1) // 2)
    except ValueError:
        raise errors.TypeError_(f"invalid hex literal {raw!r}")
