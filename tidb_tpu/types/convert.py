"""Value conversion to column types and arithmetic coercion.

Reference: util/types/convert.go (Convert/ConvertTo), util/types/etc.go
overflow handling, evaluator/arith rules (ComputeArithmetic operand coercion).
"""

from __future__ import annotations

from decimal import Decimal, ROUND_HALF_UP, localcontext

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.types.datum import Datum, Kind, NULL
from tidb_tpu.types.field_type import FieldType
from tidb_tpu.types.time_types import Duration, Time, parse_duration, parse_time


def quantize_decimal(dec: Decimal, frac: int, rounding=ROUND_HALF_UP) -> Decimal:
    """Quantize to `frac` fractional digits with enough context precision
    that wide values never raise InvalidOperation (default context is only
    28 significant digits)."""
    q = Decimal(1).scaleb(-frac)
    with localcontext() as ctx:
        ctx.prec = max(dec.adjusted() + 1 + frac + 2, 28)
        return dec.quantize(q, rounding=rounding)


def convert_datum(d: Datum, ft: FieldType) -> Datum:
    """Convert a datum to a column's FieldType for storage (CAST semantics).

    Raises OverflowError_/TypeError_ on out-of-range or malformed input
    (strict mode; the reference's non-strict truncation warnings are a later
    session-variable feature).
    """
    if d.kind == Kind.NULL:
        return NULL
    tp = ft.tp
    if tp in my.INTEGER_TYPES:
        return _to_int(d, ft)
    if tp in my.FLOAT_TYPES:
        v = _to_float(d)
        return Datum.f64(v)
    if tp in (my.TypeNewDecimal, my.TypeDecimal):
        dec = _to_decimal(d)
        if ft.decimal is not None and ft.decimal >= 0:
            dec = quantize_decimal(dec, ft.decimal)
        return Datum.dec(dec)
    if tp in my.STRING_TYPES:
        s = _to_string(d)
        if ft.flen >= 0 and len(s) > ft.flen:
            if tp in (my.TypeVarchar, my.TypeString):
                raise errors.OverflowError_(
                    f"data too long for column (len {len(s)} > {ft.flen})")
        if my.BlobFlag & ft.flag or tp in (my.TypeBlob, my.TypeTinyBlob,
                                           my.TypeMediumBlob, my.TypeLongBlob):
            return Datum.bytes_(s.encode() if isinstance(s, str) else s)
        return Datum.string(s)
    if tp in my.TIME_TYPES:
        return Datum(Kind.TIME, _to_time(d, tp, ft.decimal if ft.decimal >= 0 else 0))
    if tp == my.TypeDuration:
        return Datum(Kind.DURATION, _to_duration(d, ft.decimal if ft.decimal >= 0 else 0))
    if tp == my.TypeBit:
        from tidb_tpu.types.enumset import Bit
        width = ft.flen if ft.flen and ft.flen > 0 else 1
        if d.kind == Kind.BIT:
            v = d.val.value
        elif d.kind in (Kind.STRING, Kind.BYTES):
            s = d.get_string()
            try:
                from tidb_tpu.types.enumset import parse_bit
                return Datum(Kind.BIT, parse_bit(s, width))
            except errors.TiDBError:
                v = int(_to_int(d, ft).val)
        else:
            v = int(_to_int(d, ft).val)
        if v < 0 or (width < 64 and v >= (1 << width)):
            # BIT holds an unsigned bit pattern: negatives have no
            # representation (and would blow up later encode contexts)
            raise errors.OverflowError_(
                f"value {v} does not fit BIT({width})")
        return Datum(Kind.BIT, Bit(v, width))
    if tp == my.TypeEnum:
        from tidb_tpu.types import enumset as es
        if d.kind == Kind.ENUM:
            return d
        if d.kind in (Kind.STRING, Kind.BYTES):
            return Datum(Kind.ENUM, es.parse_enum_name(ft.elems,
                                                       d.get_string()))
        n = d.as_number()
        return Datum(Kind.ENUM, es.parse_enum_value(ft.elems, int(n)))
    if tp == my.TypeSet:
        from tidb_tpu.types import enumset as es
        if d.kind == Kind.SET:
            return d
        if d.kind in (Kind.STRING, Kind.BYTES):
            return Datum(Kind.SET, es.parse_set_name(ft.elems,
                                                     d.get_string()))
        n = d.as_number()
        return Datum(Kind.SET, es.parse_set_value(ft.elems, int(n)))
    if tp == my.TypeNull:
        return NULL
    raise errors.TypeError_(f"unsupported conversion target type 0x{tp:02x}")


def _round_half_away(x: float) -> int:
    import math
    return int(math.floor(x + 0.5)) if x >= 0 else -int(math.floor(-x + 0.5))


def _to_int(d: Datum, ft: FieldType) -> Datum:
    k = d.kind
    if k in (Kind.INT64, Kind.UINT64):
        v = d.val
    elif k == Kind.FLOAT64:
        v = _round_half_away(d.val)
    elif k == Kind.DECIMAL:
        v = int(d.val.quantize(Decimal(1), rounding=ROUND_HALF_UP))
    elif k in (Kind.STRING, Kind.BYTES):
        n = d.as_number()
        v = _round_half_away(n) if isinstance(n, float) else int(n)
    elif k == Kind.TIME:
        v = int(round(d.val.to_number()))
    elif k == Kind.DURATION:
        v = int(round(d.val.to_number()))
    elif k in (Kind.ENUM, Kind.SET, Kind.BIT, Kind.HEX):
        v = d.val.value
    else:
        raise errors.TypeError_(f"cannot convert {k!r} to integer")
    if ft.is_unsigned():
        ub = my.UNSIGNED_BOUNDS.get(ft.tp, my.MaxUint64)
        if v < 0 or v > ub:
            raise errors.OverflowError_(f"unsigned {ft.compact_str()} out of range: {v}")
        return Datum.u64(v) if ft.tp == my.TypeLonglong else Datum.i64(v)
    lb, ub = my.SIGNED_BOUNDS.get(ft.tp, (my.MinInt64, my.MaxInt64))
    if v < lb or v > ub:
        raise errors.OverflowError_(f"{ft.compact_str()} out of range: {v}")
    return Datum.i64(v)


def _to_float(d: Datum) -> float:
    n = d.as_number()
    return float(n)


def _to_decimal(d: Datum) -> Decimal:
    k = d.kind
    if k == Kind.DECIMAL:
        return d.val
    if k in (Kind.INT64, Kind.UINT64):
        return Decimal(d.val)
    if k == Kind.FLOAT64:
        return Decimal(repr(d.val))
    if k in (Kind.STRING, Kind.BYTES):
        n = d.as_number()
        return Decimal(repr(n)) if isinstance(n, float) else Decimal(n)
    n = d.as_number()
    return Decimal(str(n))


def _to_string(d: Datum) -> str:
    k = d.kind
    if k == Kind.STRING:
        return d.val
    if k == Kind.BYTES:
        return d.val.decode("utf-8", "replace")
    if k in (Kind.INT64, Kind.UINT64):
        return str(d.val)
    if k == Kind.FLOAT64:
        return repr(d.val)
    if k == Kind.DECIMAL:
        return format(d.val, "f")
    if k in (Kind.TIME, Kind.DURATION):
        return str(d.val)
    if k in (Kind.ENUM, Kind.SET):
        return d.val.name
    if k in (Kind.BIT, Kind.HEX):
        return d.val.to_bytes().decode("utf-8", "replace")
    raise errors.TypeError_(f"cannot convert {k!r} to string")


def _to_time(d: Datum, tp: int, fsp: int) -> Time:
    k = d.kind
    if k == Kind.TIME:
        t = d.val
        if tp == my.TypeDate:
            return Time(t.dt.replace(hour=0, minute=0, second=0, microsecond=0), tp, fsp)
        return Time(t.dt, tp, fsp)
    if k in (Kind.STRING, Kind.BYTES):
        return parse_time(d.get_string(), tp, fsp)
    if k in (Kind.INT64, Kind.UINT64):
        return parse_time(str(d.val), tp, fsp)
    raise errors.TypeError_(f"cannot convert {k!r} to time")


def _to_duration(d: Datum, fsp: int) -> Duration:
    k = d.kind
    if k == Kind.DURATION:
        return d.val
    if k in (Kind.STRING, Kind.BYTES):
        return parse_duration(d.get_string(), fsp)
    if k in (Kind.INT64, Kind.UINT64):
        v = d.val
        h, rem = divmod(abs(v), 10000)
        m, s = divmod(rem, 100)
        nanos = (h * 3600 + m * 60 + s) * 1_000_000_000
        return Duration(-nanos if v < 0 else nanos, fsp)
    raise errors.TypeError_(f"cannot convert {k!r} to duration")


def unflatten_datum(d: Datum, ft: FieldType) -> Datum:
    """Restore column-type metadata lost by the flag-only codec decode.

    Reference: tablecodec.DecodeColumnValue / types.Unflatten — the storage
    codec keeps only the value class (TIME decodes with default tp, strings
    decode as BYTES); the column's FieldType restores DATE-vs-DATETIME, fsp,
    and str-vs-bytes before values reach executors.
    """
    k = d.kind
    if k == Kind.NULL:
        return d
    if k == Kind.TIME:
        t: Time = d.val
        tp = ft.tp if ft.is_time() else t.tp
        fsp = ft.decimal if ft.decimal >= 0 else 0
        return Datum(Kind.TIME, Time(t.dt, tp, fsp))
    if k == Kind.DURATION:
        fsp = ft.decimal if ft.decimal >= 0 else 0
        return Datum(Kind.DURATION, Duration(d.val.nanos, fsp))
    if k == Kind.BYTES and bytes_decode_to_string(ft):
        return Datum(Kind.STRING, d.val.decode("utf-8", "replace"))
    if k == Kind.INT64 and ft.is_unsigned() and ft.tp == my.TypeLonglong and d.val >= 0:
        return Datum(Kind.UINT64, d.val)
    if k in (Kind.INT64, Kind.UINT64):
        # enum/set/bit columns flatten to their uint value in storage;
        # rebuild the rich object from the column metadata (types.Unflatten)
        from tidb_tpu.types import enumset as es
        if ft.tp == my.TypeEnum:
            return Datum(Kind.ENUM, es.parse_enum_value(ft.elems, d.val)) \
                if d.val else Datum(Kind.ENUM, es.Enum("", 0))
        if ft.tp == my.TypeSet:
            return Datum(Kind.SET, es.parse_set_value(ft.elems, d.val))
        if ft.tp == my.TypeBit:
            return Datum(Kind.BIT, es.Bit(
                d.val, ft.flen if ft.flen and ft.flen > 0 else 1))
    if k == Kind.DECIMAL and ft.is_decimal() and ft.decimal >= 0:
        # restore display scale (codec canonicalizes trailing zeros)
        quantized = quantize_decimal(d.val, ft.decimal)
        if d.val == quantized:
            return Datum(Kind.DECIMAL, quantized)
    return d


def bytes_decode_to_string(ft: FieldType) -> bool:
    """True when a BYTES storage value unflattens into a STRING datum
    for this column (non-binary, non-blob string type) — THE predicate
    shared by unflatten_datum, unflatten_identity_kinds, and the
    columnar dictionary emit (ops.columnar); byte-parity between the
    row and columnar channels depends on them never drifting."""
    return ft.is_string() and ft.tp not in (
        my.TypeBlob, my.TypeTinyBlob, my.TypeMediumBlob,
        my.TypeLongBlob) and not (ft.flag & my.BinaryFlag)


def unflatten_identity_kinds(ft: FieldType) -> frozenset:
    """Datum kinds for which unflatten_datum(d, ft) is the identity for
    this column type — the per-cell fast path of row decode: a caller may
    skip the call entirely when d.kind is in the returned set. Kinds whose
    unflatten depends on the VALUE (TIME/DURATION fsp rebuild, DECIMAL
    re-quantize) are never in the set."""
    kinds = {Kind.NULL, Kind.FLOAT64, Kind.STRING}
    if not bytes_decode_to_string(ft):
        kinds.add(Kind.BYTES)
    if ft.tp not in (my.TypeEnum, my.TypeSet, my.TypeBit):
        kinds.add(Kind.UINT64)
        if not (ft.is_unsigned() and ft.tp == my.TypeLonglong):
            kinds.add(Kind.INT64)
    return frozenset(kinds)


def cast_to_number(d: Datum):
    """Numeric context coercion returning int | float | Decimal (NULL→None)."""
    if d.kind == Kind.NULL:
        return None
    return d.as_number()


def coerce_arith(a, b):
    """Coerce two Python numbers for arithmetic per MySQL rules:
    float dominates, then Decimal, then int."""
    if isinstance(a, float) or isinstance(b, float):
        return float(a), float(b)
    if isinstance(a, Decimal) or isinstance(b, Decimal):
        return (a if isinstance(a, Decimal) else Decimal(a),
                b if isinstance(b, Decimal) else Decimal(b))
    return a, b
