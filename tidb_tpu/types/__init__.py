"""SQL value types: Datum tagged union, FieldType, conversion/comparison rules.

Reference: util/types/datum.go (Datum), util/types/field_type.go,
util/types/convert.go, util/types/compare.go, mydecimal/time/duration files.
Decimal uses Python's decimal.Decimal (exact); Time/Duration are thin wrappers
over datetime with fsp. The TPU columnar tier (tidb_tpu.ops) maps these to
fixed-point int64 / float64 / dictionary-coded planes — see ops/columnar.py.
"""

from tidb_tpu.types.datum import (  # noqa: F401
    Datum,
    Kind,
    NULL,
    MIN_NOT_NULL,
    MAX_VALUE,
    compare_datum,
    datum_from_py,
)
from tidb_tpu.types.field_type import FieldType, agg_field_type  # noqa: F401
from tidb_tpu.types.time_types import Duration, Time, parse_time, parse_duration  # noqa: F401
from tidb_tpu.types.convert import convert_datum, cast_to_number, coerce_arith, unflatten_datum  # noqa: F401
