"""Region-side append-only delta packs over cached base planes: the HTAP
freshness tier.

The plane cache (copr.plane_cache) made repeat analytical fan-out fast,
but a write to a table used to orphan its cached planes — the next scan
re-packed the whole region from the MVCC store. Under realistic mixed
OLTP/fan-out traffic the cache was cold exactly when it mattered. This
module is the Taurus-style answer (PAPERS: "Near Data Processing in
Taurus Database" — writes land as log appends NEAR the data, readers
merge base+delta at scan time):

* Per-table commit filtering (cluster/mvcc.py data_version_at(ts,
  prefix)) keys cached planes on the TABLE's version, so a commit to
  table B never touches table A's entries at all.
* A commit whose table HAS live cached base planes appends its row
  mutations (inserts/updates/deletes — deletes as tombstones by handle)
  to a bounded per-(region, table) DeltaPack instead of invalidating.
  Every later commit of the table appends too (an empty
  version-continuity entry when its rows belong to another region), so
  a pack provably covers every commit between a cached base's version
  and the present: the merge validity check matches the pack's entry
  commit_ts multiset against the MVCC store's per-table commit log for
  exactly the (base_version, read_version] window — any gap means
  re-pack, never a wrong answer.
* A scan whose lookup misses at the current version but finds a
  protected older base merges base planes + delta at scan time: the
  handle-sorted tombstone mask + appended-plane concat runs as ONE
  device dispatch (ops.kernels.delta_merge_order) at/above the floor,
  host numpy below it or on device fault, and the whole merge path
  degrades to the plain re-pack on the copr/delta_merge failpoint with
  unchanged answers. Snapshot consistency holds exactly as before:
  entries apply only when their commit_ts is visible at the reader's
  snapshot (the per-table version IS that filter), the Percolator lock
  gate still guards the whole cached path, and old-snapshot readers
  keep hitting their own pre-delta generation.
* When a pack's delta exceeds the row budget (SET GLOBAL
  tidb_tpu_delta_budget_rows; kill switch tidb_tpu_delta_pack), the
  next scan FOLDS the delta into a fresh base entry and resets the pack
  (background re-pack, amortized onto the scan that needed it).
"""

from __future__ import annotations

import threading
import weakref
from collections import Counter

import numpy as np

from tidb_tpu import errors, tablecodec as tc
from tidb_tpu.sessionctx import SYSVAR_DEFAULTS
from tidb_tpu.types.datum import NULL

I64_MAX = (1 << 63) - 1

DEFAULT_BUDGET_ROWS = int(SYSVAR_DEFAULTS["tidb_tpu_delta_budget_rows"])

# a pack whose delta outgrows this multiple of the budget is dropped
# outright (the scan that would have folded it never came — re-packing is
# cheaper than carrying an unbounded log)
HARD_CAP_FACTOR = 4

# ENTRY-count budget, independent of the row budget: version-continuity
# entries (other-region / index-only commits of the table) carry zero
# rows but still cost list/Counter weight and per-merge walk time — past
# this the next scan folds the pack (merge + reset) even with few rows,
# and past 4x the pack drops, so sustained foreign traffic can never
# grow a pack without bound
ENTRY_BUDGET = 1024

# rows below which the host numpy merge plan beats a device dispatch —
# the same flat round-trip economics as the other region-side floors
MERGE_DEVICE_FLOOR = 4096

_instances: "weakref.WeakSet[DeltaStore]" = weakref.WeakSet()


def _update_gauges() -> None:
    from tidb_tpu import metrics
    stores = list(_instances)
    metrics.gauge("copr.delta.bytes").set(
        sum(s._bytes for s in stores))
    metrics.gauge("copr.delta.rows").set(
        sum(s._rows for s in stores))
    metrics.gauge("copr.delta.entries").set(
        sum(len(s._packs) for s in stores))


class DeltaPack:
    """Append-only delta of one (region, table): the commits that landed
    since some cached base plane was packed. entries preserve append
    (= application) order; rows are (handle, row_value_bytes|None) with
    None the delete tombstone."""

    __slots__ = ("region_id", "table_id", "entries", "rows", "nbytes",
                 "ts_counts", "gen")

    def __init__(self, region_id: int, table_id: int):
        self.region_id = region_id
        self.table_id = table_id
        self.entries: list[tuple[int, list]] = []   # (commit_ts, rows)
        self.rows = 0
        self.nbytes = 0
        self.ts_counts: Counter = Counter()         # commit_ts → entries
        self.gen = 0        # bumps per append — the pre-decoded plane
        #                     cache's staleness key (decode once per
        #                     pack GENERATION, not per scan)

    def append(self, commit_ts: int, rows: list) -> None:
        self.entries.append((commit_ts, rows))
        self.ts_counts[commit_ts] += 1
        self.rows += len(rows)
        self.gen += 1
        self.nbytes += sum(len(r[1]) + 16 if r[1] is not None else 16
                           for r in rows)


class DeltaStore:
    """Per-store registry of delta packs, fed from the RPC commit path
    (cluster/rpc.py kv_commit) and drained by the region columnar engine
    (copr/columnar_region). Thread-safe; never takes the plane-cache
    lock while holding its own."""

    def __init__(self, cache):
        self.cache = cache                     # copr.plane_cache.PlaneCache
        self.enabled = True
        self.budget_rows = DEFAULT_BUDGET_ROWS
        self._lock = threading.Lock()
        self._packs: dict[tuple[int, int], DeltaPack] = {}
        # pre-decoded delta planes: (region, table, pack gen, window,
        # columns sig, range bounds) → the decoded appended-row planes —
        # repeat scans over an unchanged pack generation skip the
        # host-side row decode entirely (counted copr.delta.decode_reuse)
        self._decoded: dict[tuple, tuple] = {}
        self._rows = 0
        self._bytes = 0
        _instances.add(self)

    # ---- introspection (tests / sysvars) ----

    def __len__(self) -> int:
        return len(self._packs)

    def pack_rows(self, region_id: int, table_id: int) -> int:
        with self._lock:
            pack = self._packs.get((region_id, table_id))
            return pack.rows if pack is not None else 0

    def clear(self) -> None:
        with self._lock:
            self._packs.clear()
            self._decoded.clear()
            self._rows = self._bytes = 0
        _update_gauges()

    def set_enabled(self, on: bool) -> None:
        self.enabled = on
        if not on:
            self.clear()

    # ---- commit side ----

    def on_commit(self, region, keys: list, applied: list,
                  commit_ts: int) -> None:
        """One region's share of a commit just applied to the MVCC store
        (called from kv_commit, after the per-table version bump).
        `keys` are ALL committed keys of this call (they drove the
        version bump, including lock-kind records), `applied` the data
        mutations actually written. Appends clipped row mutations to
        this region's packs and version-continuity entries to sibling
        regions' packs of the same tables; anything unprovable drops the
        affected packs instead of guessing."""
        if not self.enabled:
            return
        if not self._packs and not self.cache._base_tables:
            # write-only workloads (no cached analytical planes) skip
            # the whole pass — lock-free truthiness reads; a stale
            # answer only delays a pack's first entry by one commit,
            # which the merge-validity window turns into a re-pack,
            # never a wrong answer
            return
        from tidb_tpu import metrics
        touched: set[int] = set()
        for k in keys:
            if tc.table_prefix_of(k) != tc.META_BUCKET:
                try:
                    touched.add(tc.decode_table_id(k))
                except (ValueError, errors.TiDBError):  # retryable-ok:
                    pass    # pure key decode, no KV access inside
        if not touched:
            return
        by_table: dict[int, list] = {}
        bad_tables: set[int] = set()
        for key, value in applied:
            if key[:1] != b"t" or key[10:12] != tc.ROW_PREFIX_SEP:
                continue        # index/meta keys: base planes unaffected
            try:
                tid, handle = tc.decode_row_key(key)
            except (ValueError, errors.TiDBError):  # retryable-ok:
                continue    # pure key decode, no KV access inside
            if not region.contains(key) or handle == I64_MAX:
                # a row outside the committing region's bounds (stale
                # grouping edge) — or the merge kernel's sentinel handle:
                # nothing sound to append, drop the table's packs
                bad_tables.add(tid)
                continue
            by_table.setdefault(tid, []).append((handle, value))
        # regions holding live cached bases, read per table BEFORE the
        # delta lock: the scan path nests cache-lock → delta-lock
        # (lookup_with_base's base_ok), so taking the cache lock while
        # holding ours would be an ABBA deadlock
        live_by_table = {tid: set(self.cache.regions_with_table(tid))
                         for tid in touched}
        appended = 0
        with self._lock:
            for tid in touched:
                live_regions = set(live_by_table[tid])
                live_regions.update(
                    rid for (rid, t) in self._packs if t == tid)
                if tid in bad_tables:
                    for rid in list(live_regions):
                        self._drop_locked(rid, tid)
                    continue
                for rid in live_regions:
                    pk = (rid, tid)
                    pack = self._packs.get(pk)
                    rows = by_table.get(tid, []) \
                        if rid == region.region_id else []
                    if rid not in live_by_table[tid]:
                        # no cached base left to merge over (LRU evicted
                        # it, or the region's entries died): the pack can
                        # never serve again — free it
                        if pack is not None:
                            self._drop_locked(rid, tid)
                        continue
                    if pack is None:
                        pack = self._packs[pk] = DeltaPack(rid, tid)
                    before = pack.nbytes
                    pack.append(commit_ts, rows)
                    self._rows += len(rows)
                    self._bytes += pack.nbytes - before
                    if rows:
                        appended += 1
                    if pack.rows > self.budget_rows * HARD_CAP_FACTOR \
                            or len(pack.entries) > \
                            ENTRY_BUDGET * HARD_CAP_FACTOR:
                        self._drop_locked(rid, tid)
                        metrics.counter("copr.delta.drops").inc()
        if appended:
            metrics.counter("copr.delta.appends").inc(appended)
        _update_gauges()

    def _drop_locked(self, region_id: int, table_id: int) -> None:
        pack = self._packs.pop((region_id, table_id), None)
        if pack is not None:
            self._rows -= pack.rows
            self._bytes -= pack.nbytes
        for k in [k for k in self._decoded
                  if k[0] == region_id and k[1] == table_id]:
            del self._decoded[k]

    def reset(self, region_id: int, table_id: int) -> None:
        """Fold complete: the merged batch became the new base entry, the
        delta restarts empty (counted as a re-pack by the caller)."""
        with self._lock:
            self._drop_locked(region_id, table_id)
        _update_gauges()

    # NOTE on split/merge: there is no explicit epoch hook. The cache's
    # epoch sweep kills the old shape's base entries on the next lookup,
    # after which regions_with_table stops reporting the region and the
    # next table commit prunes the orphaned pack in on_commit (and the
    # entry/row hard caps bound it meanwhile). Merge correctness never
    # depended on the hook: bases are epoch-matched by the cache sweep
    # and merge puts clip to the request's (current-epoch) ranges.

    # ---- scan side ----

    def usable(self, region_id: int, table_id: int, base_version: int,
               version: int, mvcc, prefix: bytes) -> bool:
        """Can a cached base at per-table version `base_version` serve a
        reader at `version` through this pack? Yes iff the pack holds an
        entry for EVERY table commit in (base_version, version] — the
        multiset of entry commit_ts must cover the MVCC per-table log
        window (window boundaries always fall on ts boundaries, so a
        same-ts pair is either fully inside or fully outside)."""
        if not self.enabled or version <= base_version:
            return False
        with self._lock:
            pack = self._packs.get((region_id, table_id))
            if pack is None:
                return False
            counts = dict(pack.ts_counts)
        need = Counter(mvcc.table_commits_between(prefix, base_version,
                                                  version))
        return all(counts.get(ts, 0) >= n for ts, n in need.items())

    def repack_due(self, region_id: int, table_id: int) -> bool:
        with self._lock:
            pack = self._packs.get((region_id, table_id))
            return pack is not None and \
                (pack.rows > self.budget_rows
                 or len(pack.entries) > ENTRY_BUDGET)

    def merge(self, base, base_version: int, region_id: int,
              table_id: int, version: int, mvcc, prefix: bytes,
              columns, ranges, defaults):
        """Base planes + delta → a fresh ColumnBatch identical to what a
        re-pack at `version` would produce, or None (caller re-packs).
        The tombstone mask + handle-ordered concat runs as one device
        dispatch at/above MERGE_DEVICE_FLOOR (kernels.delta_merge_order),
        host numpy below it — and the device→host rung of the
        degradation chain on any device fault (copr.degraded_delta_to_host,
        identical order by construction)."""
        from tidb_tpu import metrics, tracing
        need = Counter(mvcc.table_commits_between(prefix, base_version,
                                                  version))
        with self._lock:
            pack = self._packs.get((region_id, table_id))
            if pack is None:
                return None
            gen = pack.gen
            remaining = Counter(need)
            picked: list[list] = []
            for ts, rows in pack.entries:
                if remaining.get(ts, 0) > 0:
                    remaining[ts] -= 1
                    picked.append(rows)
            if any(n > 0 for n in remaining.values()):
                return None     # gap: the pack missed a commit
        # last write wins per handle, in application order
        final: dict[int, bytes | None] = {}
        for rows in picked:
            for handle, value in rows:
                final[handle] = value
        if not final:
            # version-only delta (other-region / index-only commits):
            # the base IS the current pack — serve it unchanged
            metrics.counter("copr.delta.merges").inc()
            return base
        # pre-decoded delta plane cache: the appended rows' decode
        # (tc.decode_row + datum_to_phys per cell) is invariant for a
        # given pack GENERATION × visibility window × schema × ranges —
        # repeat scans (the dashboard shape that hits the merge path
        # every time) reuse it instead of re-decoding per merge
        from tidb_tpu.copr.columnar_region import _columns_sig
        dec_key = (region_id, table_id, gen, base_version, version,
                   _columns_sig(columns),
                   tuple((rg.start, rg.end) for rg in ranges))
        with self._lock:
            dec = self._decoded.get(dec_key)
        if dec is not None:
            metrics.counter("copr.delta.decode_reuse").inc()
            tomb, app_handles, raw, ok = dec
        else:
            row_key = tc.encode_row_key
            in_range = (lambda k: any(rg.start <= k and
                                      (rg.end is None or k < rg.end)
                                      for rg in ranges))
            tomb = np.fromiter(sorted(final), dtype=np.int64,
                               count=len(final))
            puts = sorted((h, v) for h, v in final.items()
                          if v is not None and
                          in_range(row_key(table_id, h)))
            try:
                app_handles, raw, ok = _decode_puts(puts, columns,
                                                    defaults)
            except errors.TypeError_:
                return None     # no exact plane mapping: re-pack tier
            with self._lock:
                self._decoded[dec_key] = (tomb, app_handles, raw, ok)
                while len(self._decoded) > 32:
                    self._decoded.pop(next(iter(self._decoded)))
        try:
            merged = _merge_batch(base, tomb, app_handles, raw, ok,
                                  columns)
        except errors.TypeError_:
            return None     # no exact plane mapping: re-pack → row tier
        if merged is None:
            return None
        metrics.counter("copr.delta.merges").inc()
        tracing.current().set("delta_rows", len(final)) \
            .set("delta_tombstones", len(tomb)) \
            .set("delta_appended", len(app_handles))
        return merged


def _decode_puts(puts: list, columns, defaults):
    """Decode the surviving delta rows → (app_handles, raw per-column
    values, valid flags): the same datum_to_phys contract the pack path
    applies (TypeError_ bails the whole merge to the re-pack tier).
    Runs once per pack generation — DeltaStore.merge caches the result
    and repeat scans reuse it (copr.delta.decode_reuse)."""
    from tidb_tpu.ops import columnar as col
    k = len(puts)
    app_handles = np.fromiter((h for h, _v in puts), dtype=np.int64,
                              count=k)
    col_kinds = {c.column_id: col.column_phys_kind(c) for c in columns}
    pk_col = next((c for c in columns if c.pk_handle), None)
    raw: dict[int, list] = {c.column_id: [] for c in columns}
    ok: dict[int, list] = {c.column_id: [] for c in columns}
    for h, value in puts:
        row = tc.decode_row(value)
        for c in columns:
            cid = c.column_id
            if pk_col is not None and cid == pk_col.column_id:
                raw[cid].append(h)
                ok[cid].append(True)
                continue
            d = row.get(cid)
            if d is None:
                d = defaults.get(cid, NULL)
            scale = c.decimal if col_kinds[cid] == col.K_DEC \
                and c.decimal and c.decimal > 0 else 0
            v, valid = col.datum_to_phys(d, col_kinds[cid], scale)
            raw[cid].append(v)
            ok[cid].append(valid)
    return app_handles, raw, ok


def _merge_batch(base, tomb: np.ndarray, app_handles: np.ndarray,
                 raw: dict, ok: dict, columns):
    """Materialize the merged ColumnBatch from the (possibly cached)
    pre-decoded appended planes: get the handle-sorted merge order
    (device kernel or host plan), gather every plane once."""
    from tidb_tpu.ops import columnar as col
    if getattr(base, "max_handle", 0) == I64_MAX:
        return None   # the kernel's sentinel handle is in play: re-pack
    cap = base.capacity
    k = len(app_handles)
    col_kinds = {c.column_id: col.column_phys_kind(c) for c in columns}

    order = _merge_order(base, tomb, app_handles)
    n = len(order)
    cap_new = col.bucket_capacity(n)
    from_base = order < cap
    base_idx = np.where(from_base, order, 0)
    app_idx = np.where(from_base, 0, order - cap)

    handles = np.full(cap_new, -(1 << 63), dtype=np.int64)
    h_app = np.full(max(k, 1), -(1 << 63), dtype=np.int64)
    h_app[:k] = app_handles
    handles[:n] = np.where(from_base, base.handles[base_idx],
                           h_app[app_idx])
    cols: dict[int, col.ColumnData] = {}
    for c in columns:
        cid = c.column_id
        kind = col_kinds[cid]
        old = base.columns[cid]
        va = np.zeros(cap_new, dtype=bool)
        okv = np.zeros(max(k, 1), dtype=bool)
        okv[:k] = ok[cid]
        va[:n] = np.where(from_base, old.valid[base_idx], okv[app_idx])
        if kind == col.K_STR:
            new_vals = [v if o else None for v, o in zip(raw[cid], ok[cid])]
            merged_dict = sorted(set(old.dictionary)
                                 | {v for v in new_vals if v is not None})
            code_of = {b: i for i, b in enumerate(merged_dict)}
            base_codes = np.full(cap, -1, dtype=np.int64)
            if old.dictionary:
                remap = np.array([code_of[b] for b in old.dictionary],
                                 dtype=np.int64)
                oc = np.clip(old.values, 0, None)
                base_codes = np.where(old.valid, remap[oc], -1)
            app_codes = np.full(max(k, 1), -1, dtype=np.int64)
            app_codes[:k] = [code_of[v] if v is not None else -1
                             for v in new_vals]
            codes = np.full(cap_new, -1, dtype=np.int64)
            codes[:n] = np.where(from_base, base_codes[base_idx],
                                 app_codes[app_idx])
            cols[cid] = col.ColumnData(col.K_STR, codes, va, merged_dict,
                                       tp=c.tp)
        else:
            dtype = np.float64 if kind == col.K_F64 else np.int64
            app_vals = np.zeros(max(k, 1), dtype=dtype)
            if k:
                app_vals[:k] = [x if o else 0
                                for x, o in zip(raw[cid], ok[cid])]
            vals = np.zeros(cap_new, dtype=dtype)
            vals[:n] = np.where(from_base, old.values[base_idx],
                                app_vals[app_idx])
            if kind == col.K_I64:
                col._check_u64_plane(c, vals, va, n)
            scale = c.decimal if kind == col.K_DEC and c.decimal \
                and c.decimal > 0 else 0
            cols[cid] = col.ColumnData(
                kind, vals, va, tp=c.tp, dec_scale=scale,
                max_abs=col._plane_max_abs(vals, n, kind))
    out = col.ColumnBatch(n, cap_new, handles, cols)
    out.max_handle = int(handles[:n].max()) if n else -(1 << 63)
    return out


def _merge_order(base, tomb: np.ndarray,
                 app_handles: np.ndarray) -> np.ndarray:
    """The handle-sorted merge order over [base planes | appended rows]:
    device kernel at/above the floor, host numpy below it or after a
    device fault (counted on copr.degraded_delta_to_host)."""
    import sys
    from tidb_tpu import errors as _errors, tracing
    use_device = base.n_rows >= MERGE_DEVICE_FLOOR \
        and sys.modules.get("jax") is not None
    if use_device:
        from tidb_tpu.ops import kernels
        try:
            return kernels.delta_merge_order(
                base.handles, base.row_mask(), tomb, app_handles)
        except _errors.DeviceError:
            tracing.record_degraded("delta_to_host", tally=False)
    live = base.row_mask()
    pos = np.searchsorted(tomb, base.handles)
    pos_c = np.clip(pos, 0, max(len(tomb) - 1, 0))
    dead = (pos < len(tomb)) & \
        (tomb[pos_c] == base.handles if len(tomb) else False)
    keep = live & ~dead
    all_h = np.concatenate([np.where(keep, base.handles, I64_MAX),
                            app_handles])
    all_live = np.concatenate([keep, np.ones(len(app_handles), bool)])
    order = np.argsort(all_h, kind="stable")
    n_live = int(np.count_nonzero(all_live))
    return order[:n_live].astype(np.int64)


def delta_for(store):
    """The store's delta-pack registry, or None (non-cluster storage) —
    the handle for SET GLOBAL / bootstrap hydration."""
    return getattr(getattr(store, "rpc", None), "delta_store", None)
