"""The pushdown wire IR: SelectRequest / SelectResponse / Expr.

Reference: tipb's select.proto generated Go
(_vendor/src/github.com/pingcap/tipb/go-tipb/select.pb.go:75 SelectRequest,
:254 SelectResponse, expression.pb.go Expr/ExprType) and the proto helpers in
distsql/distsql.go:362-460 (ColumnsToProto, IndexToProto,
FieldTypeFromPBColumn).

Values crossing this boundary are codec-encoded bytes (the storage wire
format), so the engines on the far side — CPU interpreter or TPU kernels —
never see planner objects; this is a real process-boundary-shaped contract
even though round 1 runs it in-proc.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from tidb_tpu import mysqldef as my
from tidb_tpu.codec import codec
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum
from tidb_tpu.types.field_type import FieldType


class ExprType(enum.IntEnum):
    """Mirrors tipb.ExprType's shape: value leaves, column ref, operators by
    Op code, named control/string funcs, aggregates."""
    # leaves
    NULL = 0
    VALUE = 1         # any literal; datum in Expr.val
    COLUMN_REF = 2    # column id in Expr.val (int datum)
    # composite
    OPERATOR = 10     # Expr.op holds the opcode; 1-2 children
    LIKE = 20         # children: [target, pattern]; val: escape str
    NOT_LIKE = 21
    IN = 22           # children: [target, item...]
    NOT_IN = 23
    IS_NULL = 24
    IS_NOT_NULL = 25
    IF = 30
    IFNULL = 31
    NULLIF = 32
    COALESCE = 33
    CASE = 34         # flattened case args (expression.builtin._case layout)
    SCALAR_FUNC = 40  # generic builtin; name in Expr.val
    # aggregates (tipb ExprType 3001-3008 family)
    AGG_COUNT = 3001
    AGG_SUM = 3002
    AGG_AVG = 3003
    AGG_MIN = 3004
    AGG_MAX = 3005
    AGG_FIRST = 3006
    AGG_GROUP_CONCAT = 3007
    AGG_DISTINCT = 3010  # wraps another agg; distinct marker


AGG_TYPES = frozenset((ExprType.AGG_COUNT, ExprType.AGG_SUM, ExprType.AGG_AVG,
                       ExprType.AGG_MIN, ExprType.AGG_MAX, ExprType.AGG_FIRST,
                       ExprType.AGG_GROUP_CONCAT))

AGG_NAME = {
    ExprType.AGG_COUNT: "count", ExprType.AGG_SUM: "sum",
    ExprType.AGG_AVG: "avg", ExprType.AGG_MIN: "min",
    ExprType.AGG_MAX: "max", ExprType.AGG_FIRST: "first_row",
    ExprType.AGG_GROUP_CONCAT: "group_concat",
}
AGG_TYPE_BY_NAME = {v: k for k, v in AGG_NAME.items()}

# aggregates whose EXPRESSION arguments the arg-plane compiler
# (ops.exprc.compile_arg_plane) can lower into the batched states
# dispatch — and the arithmetic grammar it takes. Shared by the planner
# (don't push an aggregate whose arg no region could answer columnar)
# and the region handler (pre-pack structural gate): both sides agreeing
# on the shape rule is what keeps a pushed statement at zero fallbacks.
ARG_PLANE_AGGS = ("count", "sum", "avg", "min", "max")

_ARG_PLANE_BINOPS = (Op.Plus, Op.Minus, Op.Mul, Op.Div, Op.IntDiv, Op.Mod)
_ARG_PLANE_UNOPS = (Op.UnaryMinus, Op.UnaryPlus)


def arg_plane_shape_ok(name: str, e: "Expr") -> bool:
    """Structural (jax-free) gate for EXPRESSION aggregate arguments:
    arithmetic over column refs / constants, reduced by a
    plane-expressible aggregate. The full contextual rules (kind typing,
    overflow bounds, float-context restrictions) need the packed batch
    and run in exprc.compile_arg_plane at prepare time."""
    if name not in ARG_PLANE_AGGS:
        return False
    if e.tp in (ExprType.VALUE, ExprType.COLUMN_REF):
        return True
    if e.tp != ExprType.OPERATOR or not e.children:
        return False
    if len(e.children) == 1:
        ok = e.op in _ARG_PLANE_UNOPS
    elif len(e.children) == 2:
        ok = e.op in _ARG_PLANE_BINOPS
    else:
        ok = False
    return ok and all(arg_plane_shape_ok(name, c) for c in e.children)


@dataclass
class Expr:
    tp: ExprType
    val: Datum | int | str | None = None
    op: Op | None = None
    children: list["Expr"] = field(default_factory=list)
    distinct: bool = False  # aggregates only

    def __repr__(self):
        if self.tp == ExprType.VALUE:
            return repr(self.val)
        if self.tp == ExprType.COLUMN_REF:
            return f"col#{self.val}"
        if self.tp == ExprType.OPERATOR:
            if len(self.children) == 2:
                return f"({self.children[0]!r} {self.op.sql()} {self.children[1]!r})"
            return f"({self.op.sql()} {self.children[0]!r})"
        name = AGG_NAME.get(self.tp) or (self.val if self.tp == ExprType.SCALAR_FUNC
                                         else self.tp.name.lower())
        d = "distinct " if self.distinct else ""
        return f"{name}({d}{', '.join(map(repr, self.children))})"


def expr_value(d: Datum) -> Expr:
    return Expr(ExprType.VALUE, val=d)


def expr_column(col_id: int) -> Expr:
    return Expr(ExprType.COLUMN_REF, val=col_id)


def expr_op(op: Op, *children: Expr) -> Expr:
    return Expr(ExprType.OPERATOR, op=op, children=list(children))


def expr_agg(name: str, children: list[Expr], distinct: bool = False) -> Expr:
    return Expr(AGG_TYPE_BY_NAME[name], children=children, distinct=distinct)


@dataclass
class PBColumnInfo:
    """tipb.ColumnInfo — column metadata the coprocessor needs to decode and
    type rows (distsql.ColumnToProto, distsql/distsql.go:404-421)."""
    column_id: int
    tp: int
    flag: int = 0
    flen: int = -1
    decimal: int = -1
    pk_handle: bool = False    # this column IS the integer handle
    elems: list[str] = field(default_factory=list)
    # value for rows written before this column existed (tipb
    # ColumnInfo.DefaultVal; model.ColumnInfo original_default)
    default_val: Datum | None = None


@dataclass
class PBTableInfo:
    table_id: int
    columns: list[PBColumnInfo]


@dataclass
class PBIndexInfo:
    table_id: int
    index_id: int
    columns: list[PBColumnInfo]  # indexed columns, in index order
    unique: bool = False


@dataclass
class ByItem:
    expr: Expr
    desc: bool = False


@dataclass
class SelectRequest:
    """tipb.SelectRequest (select.pb.go:75). Exactly one of table_info /
    index_info is set; that chooses row-key vs index-key interpretation of
    the attached KeyRanges (kv.Request carries those)."""
    start_ts: int
    table_info: PBTableInfo | None = None
    index_info: PBIndexInfo | None = None
    where: Expr | None = None
    group_by: list[ByItem] = field(default_factory=list)
    having: Expr | None = None
    order_by: list[ByItem] = field(default_factory=list)
    limit: int | None = None
    aggregates: list[Expr] = field(default_factory=list)
    desc: bool = False                    # scan direction
    time_zone_offset: int = 0
    flags: int = 0
    # TPU-tier extension (not in tipb): planner-estimated scan row count
    # from ANALYZE histograms (None when only pseudo stats were available).
    # The device engine uses it to price the dispatch round trip against
    # the CPU engine's per-row cost BEFORE packing a batch — the same role
    # as netWorkFactor/cpuFactor in the reference's calculateCost
    # (plan/physical_plans.go:70-84), applied at the engine boundary.
    est_rows: float | None = None
    # TPU-tier extension: the consumer understands column planes — a
    # capable responder may answer with SelectResponse.columnar (the
    # scan's ColumnBatch + selection index) instead of chunk rows, the
    # "return-format-aware pushdown" of arXiv:2312.15405. Responders that
    # don't (CPU engine, below-floor routes) ignore it and send rows.
    columnar_hint: bool = False

    def is_agg(self) -> bool:
        return bool(self.aggregates) or bool(self.group_by)


@dataclass
class RowMeta:
    handle: int
    length: int


@dataclass
class Chunk:
    """tipb.Chunk: rows packed as codec-encoded bytes + per-row meta.
    The coprocessor emits ~64 rows per chunk (local_region.go getChunk)."""
    rows_data: bytes = b""
    rows_meta: list[RowMeta] = field(default_factory=list)


@dataclass
class SelectResponse:
    chunks: list[Chunk] = field(default_factory=list)
    error: str | None = None
    # columnar fast path (requests with columnar_hint): the scan's
    # planes + selection index (ops.columnar.ColumnarScanResult),
    # bypassing row-chunk encode/decode entirely — plane-aware consumers
    # (device join, fused aggregates, TopN) read columns straight off it.
    # The in-proc TPU engine answers ONE columnar response per request;
    # a cluster fan-out answers one columnar PARTIAL per region task and
    # the client stacks them (ops.columnar.ColumnarPartialSet), so this
    # field is per-partial, not per-request. None → use chunks.
    columnar: object | None = None
    # in-proc row fast path (CPU engine scans): (handle, datums) pairs in
    # scan order, skipping the per-row encode_value/decode_all round trip
    # chunks exist for — the datums are exactly what decoding the chunk
    # bytes would produce (storage-flattened kinds). None → use chunks.
    raw: list | None = None

    def row_count(self) -> int:
        if self.columnar is not None:
            return len(self.columnar)
        if self.raw is not None:
            return len(self.raw)
        return sum(len(c.rows_meta) for c in self.chunks)


class ChunkWriter:
    """Packs datum rows into Chunks of `rows_per_chunk` rows."""

    def __init__(self, rows_per_chunk: int = 64):
        self.chunks: list[Chunk] = []
        self._cur_data = bytearray()
        self._cur_meta: list[RowMeta] = []
        self.rows_per_chunk = rows_per_chunk

    def append_row(self, handle: int, datums: list[Datum]) -> None:
        data = codec.encode_value(datums)
        self._cur_data.extend(data)
        self._cur_meta.append(RowMeta(handle, len(data)))
        if len(self._cur_meta) >= self.rows_per_chunk:
            self._flush()

    def append_encoded(self, handle: int, data: bytes) -> None:
        self._cur_data.extend(data)
        self._cur_meta.append(RowMeta(handle, len(data)))
        if len(self._cur_meta) >= self.rows_per_chunk:
            self._flush()

    def _flush(self) -> None:
        if self._cur_meta:
            self.chunks.append(Chunk(bytes(self._cur_data), self._cur_meta))
            self._cur_data = bytearray()
            self._cur_meta = []

    def finish(self) -> list[Chunk]:
        self._flush()
        return self.chunks


def iter_response_rows(resp: SelectResponse):
    """Yield (handle, datums) decoded from chunks — partialResult.Next's
    chunk-wise decode (distsql/distsql.go:192,253). In-proc responses
    carry the rows directly (SelectResponse.raw) and skip the codec;
    columnar responses materialize the same flattened datums from their
    planes (the safety net for a consumer that iterates rows anyway)."""
    if resp.columnar is not None:
        yield from resp.columnar.iter_raw_with_handles()
        return
    if resp.raw is not None:
        yield from resp.raw
        return
    for chunk in resp.chunks:
        pos = 0
        mv = memoryview(chunk.rows_data)
        for meta in chunk.rows_meta:
            row_bytes = bytes(mv[pos:pos + meta.length])
            pos += meta.length
            yield meta.handle, codec.decode_all(row_bytes)


# ---- proto helpers (distsql/distsql.go:362-460) ----

def column_to_proto(col, pk_is_handle: bool = False) -> PBColumnInfo:
    """model.ColumnInfo → PBColumnInfo."""
    ft = col.field_type
    default = col.original_default_datum()
    return PBColumnInfo(
        column_id=col.id, tp=ft.tp, flag=ft.flag, flen=ft.flen,
        decimal=ft.decimal, elems=list(ft.elems),
        pk_handle=pk_is_handle and my.has_pri_key_flag(ft.flag),
        default_val=default)


def columns_to_proto(columns, pk_is_handle: bool = False) -> list[PBColumnInfo]:
    return [column_to_proto(c, pk_is_handle) for c in columns]


def index_to_proto(tbl_info, idx_info) -> PBIndexInfo:
    cols_by_name = {c.name.lower(): c for c in tbl_info.columns}
    pb_cols = [column_to_proto(cols_by_name[ic.name.lower()])
               for ic in idx_info.columns]
    return PBIndexInfo(table_id=tbl_info.id, index_id=idx_info.id,
                       columns=pb_cols, unique=idx_info.unique)


def field_type_from_pb_column(col: PBColumnInfo) -> FieldType:
    return FieldType(tp=col.tp, flag=col.flag, flen=col.flen,
                     decimal=col.decimal, elems=list(col.elems))
