"""Per-region columnar plane cache: repeat fan-out queries skip the repack.

Every execution of a columnar_hint scan used to re-pack each region's rows
into planes from the MVCC store and re-ship them host→device. For repeat
queries — the dominant shape of dashboard/serving traffic — that repack is
pure waste: the visible row set of a region is fully determined by
(region epoch, visible data version), both of which the infrastructure
already tracks. This cache keys the post-pack, pre-filter/pre-TopN
ColumnBatch of one region's clipped ranges by

    (region_id, region epoch, data_version_at(start_ts),
     table_id, column set, range bounds)

so a hit is provably snapshot-consistent:

* `DistStore.data_version_at(start_ts)` (cluster/mvcc.py) counts commit
  events visible at start_ts — equal versions imply identical visible
  data, and ANY commit bumps it, so a cached batch can never hide a
  write. Two snapshots at different start_ts map to different versions
  and to different entries — an older reader never sees a newer
  version's planes (and vice versa).
* The region `epoch()` (cluster/topology.py) bumps on split/merge, so a
  topology change orphans every entry packed under the old shape; the
  worklist retry re-packs under the new epoch.

Entries are byte-budget LRU (SET GLOBAL tidb_tpu_plane_cache_bytes) with
a kill switch (SET GLOBAL tidb_tpu_plane_cache = 0). When the TPU tier is
live in the process, inserted batches are pinned DEVICE-resident
(ops.client.pin_batch_device): a repeat query then skips the host→device
transfer too — the join/aggregate tier reads the planes straight out of
HBM (ColumnarScanResult.device_plane / ColumnarPartialSet.device_plane).

Caching materialized pushdown state near the compute is the core lever in
near-data-processing systems (PAPERS: "Near Data Processing in Taurus
Database", "Enhancing Computation Pushdown for Cloud OLAP Databases").
"""

from __future__ import annotations

import sys
import threading
import weakref
from collections import OrderedDict

from tidb_tpu import errors, failpoint
from tidb_tpu.sessionctx import SYSVAR_DEFAULTS

DEFAULT_BUDGET_BYTES = int(SYSVAR_DEFAULTS["tidb_tpu_plane_cache_bytes"])

# counter names exported through metrics/ (Prometheus) and, per statement,
# through the thread-local tallies in the slow-query log (prefixed
# plane_cache_), in display order
COUNTER_NAMES = ("hits", "misses", "evictions", "invalidations_epoch",
                 "invalidations_version", "kept_active")


def _metric(name: str):
    from tidb_tpu import metrics
    return metrics.counter(f"copr.plane_cache.{name}")


# live caches in this process (one per cluster store): the byte/entry
# gauges are process-wide, so they SUM across instances — a per-instance
# absolute set would be last-writer-wins when several stores coexist
_instances: "weakref.WeakSet[PlaneCache]" = weakref.WeakSet()


def _update_gauges() -> None:
    from tidb_tpu import metrics
    caches = list(_instances)
    metrics.gauge("copr.plane_cache.bytes").set(
        sum(c._bytes for c in caches))
    metrics.gauge("copr.plane_cache.bytes_pinned").set(
        sum(c._bytes_pinned for c in caches))
    metrics.gauge("copr.plane_cache.entries").set(
        sum(len(c._entries) for c in caches))
    # HBM attribution for the device-utilization profiler: which table's
    # cached planes hold the most device memory right now, SUMMED per
    # table across caches (a table split over stores must not lose to a
    # single-cache table). Each cache republishes an immutable snapshot
    # tuple under its OWN lock (this sweep may run while a sibling holds
    # its lock — only attribute reads are safe here, never a lock
    # acquisition; a tuple read is atomic)
    by_table: dict[int, int] = {}
    for c in caches:
        for tid, n in c._pinned_snapshot:
            by_table[tid] = by_table.get(tid, 0) + n
    top = max(by_table.items(), key=lambda kv: kv[1], default=(0, 0))
    metrics.gauge("copr.plane_cache.top_pinned_table").set(int(top[0]))
    metrics.gauge("copr.plane_cache.top_pinned_bytes").set(int(top[1]))


def batch_nbytes(batch) -> int:
    """Byte footprint of one cached ColumnBatch (host planes + string
    dictionaries; device pins mirror the numeric plane bytes)."""
    n = int(batch.handles.nbytes)
    for cd in batch.columns.values():
        n += int(cd.values.nbytes) + int(cd.valid.nbytes)
        if cd.dictionary:
            # bytes payload + per-entry object overhead estimate
            n += sum(len(b) for b in cd.dictionary) + 64 * len(cd.dictionary)
    return n


class _Entry:
    __slots__ = ("batch", "nbytes", "epoch", "version", "pinned",
                 "table_id")

    def __init__(self, batch, nbytes: int, epoch, version: int,
                 pinned: bool, table_id: int = 0):
        self.batch = batch
        self.nbytes = nbytes
        self.epoch = epoch
        self.version = version
        self.pinned = pinned
        self.table_id = table_id


class PlaneCache:
    """Byte-budget LRU of per-region packed ColumnBatches.

    base_key = (region_id, table_id, column ids, clipped range bounds);
    full key = base_key + (epoch, version). Lookups sweep the queried
    REGION's entries for provably-dead generations — a different epoch
    (split/merge moved the region's bounds) or a strictly older data
    version (a commit made those planes invisible to every future
    reader) — and count the sweep per cause. Entries at a NEWER version
    than the lookup survive: an old-snapshot reader must not evict the
    planes current readers are hitting (snapshot isolation works both
    ways). Thread-safe: fan-out workers for different regions hit it
    concurrently."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 enabled: bool = True):
        self.enabled = enabled
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._by_region: dict[int, set] = {}   # region_id → {full_key}
        # BASE-TABLE entry counts per (table_id → region_id) — the delta
        # tier asks "which regions hold live cached base planes for this
        # table" on every commit (copr.delta); index entries (tuple pack
        # key) don't count, they cannot merge row deltas
        self._base_tables: dict[int, dict[int, int]] = {}
        self._bytes = 0
        self._bytes_pinned = 0
        self._pinned_tables: dict[int, int] = {}
        self._pinned_snapshot: tuple = ()
        _instances.add(self)

    # ---- introspection (tests / gauges) ----

    @property
    def bytes_cached(self) -> int:
        return self._bytes

    @property
    def bytes_pinned(self) -> int:
        return self._bytes_pinned

    def pinned_by_table(self) -> dict[int, int]:
        """HBM-pinned cached bytes per table id (base_key[1]) — the
        profiler's bytes-pinned attribution."""
        with self._lock:
            return dict(self._pinned_tables)

    def _account_pin_locked(self, table_id: int, nbytes: int) -> None:
        """Maintain the per-table pinned-bytes map and republish it as
        an immutable snapshot tuple the module-level gauge sweep can
        read WITHOUT taking this lock."""
        n = self._pinned_tables.get(table_id, 0) + nbytes
        if n > 0:
            self._pinned_tables[table_id] = n
        else:
            self._pinned_tables.pop(table_id, None)
        self._pinned_snapshot = tuple(self._pinned_tables.items())

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------------

    def lookup(self, base_key: tuple, epoch, version: int):
        """(batch, attribution) — batch is None on a miss. attribution is
        the per-response counter dict the client rolls into the
        statement's thread-local tallies (same monotonic-diff contract
        as distsql.columnar_hits); process metrics count here, at the
        cache, so they stay exact even when a response is abandoned."""
        batch, info, _base = self.lookup_with_base(base_key, epoch,
                                                   version, None)
        return batch, info

    def lookup_with_base(self, base_key: tuple, epoch, version: int,
                         base_ok, keep_version: int | None = None):
        """lookup() plus the HTAP delta tier's base resolution:
        (batch, attribution, delta_base).

        `base_ok(entry_version)` — when given — judges whether an
        OLDER-version same-base entry can still serve as the base of a
        device base+delta merge (a live delta pack covers the version
        gap, copr.delta). The NEWEST such entry is protected from the
        version sweep and comes back as delta_base = (batch,
        entry_version); every OTHER older generation dies — a hot table
        under steady writes holds current + one base, never one
        generation per commit. Without `base_ok` the sweep is PR 5's:
        any strictly-older same-base generation dies.

        `keep_version` — when given — is the visible-data version of the
        OLDEST ACTIVE reader (store.oldest_active_ts through the per-
        table commit filter): older same-base generations at or above it
        can still serve a live old-snapshot reader VERBATIM, so the
        sweep keeps them (counted `kept_active`) instead of forcing that
        reader to re-pack on every read. With only current-version
        readers, keep_version == version and behavior is unchanged."""
        full_key = base_key + (epoch, version)
        region_id = base_key[0]
        with self._lock:
            ent = self._entries.get(full_key)
            if ent is not None:
                self._entries.move_to_end(full_key)
                _metric("hits").inc()
                return ent.batch, {"hits": 1}, None
            info = {"misses": 1}
            _metric("misses").inc()
            # invalidation sweep for THIS region: entries whose epoch
            # moved (split/merge) or whose data version is strictly
            # older than the querying reader's can never serve again —
            # except the newest delta-mergeable base (base_ok)
            swept = 0
            stale: list = []
            for fk in list(self._by_region.get(region_id, ())):
                e = self._entries.get(fk)
                if e is None:
                    continue
                same_base = fk[:-2] == base_key
                if e.epoch != epoch:
                    self._remove(fk, e)
                    swept += 1
                    info["invalidations_epoch"] = \
                        info.get("invalidations_epoch", 0) + 1
                    _metric("invalidations_epoch").inc()
                elif same_base and e.version < version:
                    stale.append((fk, e))
            base_ent: _Entry | None = None
            if base_ok is not None:
                for _fk, e in stale:
                    if (base_ent is None or e.version > base_ent.version) \
                            and base_ok(e.version):
                        base_ent = e
            for fk, e in stale:
                if e is base_ent:
                    continue
                if keep_version is not None and e.version >= keep_version:
                    # a live reader whose snapshot sits at or above this
                    # generation can still hit it exactly — sweeping it
                    # would re-pack that snapshot on every read
                    info["kept_active"] = info.get("kept_active", 0) + 1
                    _metric("kept_active").inc()
                    continue
                self._remove(fk, e)
                swept += 1
                info["invalidations_version"] = \
                    info.get("invalidations_version", 0) + 1
                _metric("invalidations_version").inc()
            if swept:
                self._update_gauges()   # once per sweep, not per entry
            base = (base_ent.batch, base_ent.version) \
                if base_ent is not None else None
            return None, info, base

    def insert(self, base_key: tuple, epoch, version: int, batch,
               info: dict | None = None) -> None:
        """Admit a freshly packed batch (device-pinning it when the TPU
        tier is live); LRU-evicts to the byte budget. `info`, when given,
        accumulates the evictions this insert caused (per-statement
        attribution for the statement that packed)."""
        if failpoint._active and \
                failpoint.eval("cache/no_admit") is not None:
            # admission seam: a dropped insert only costs a repack next
            # time — correctness never depends on the cache admitting
            return
        nbytes = batch_nbytes(batch)
        full_key = base_key + (epoch, version)
        with self._lock:
            # admission BEFORE the device pin: a rejected entry (kill
            # switch raced the pack, or batch beyond the whole budget)
            # must not pay a dead host→device transfer
            if not self.enabled or nbytes > self.budget_bytes:
                return
        pinned = _maybe_pin_device(batch)   # H2D outside the lock
        with self._lock:
            if not self.enabled or nbytes > self.budget_bytes:
                return      # re-check: the switch/budget may have moved
            old = self._entries.pop(full_key, None)
            if old is not None:
                self._account_remove(old)
            # index entries key on ("idx", table_id, index_id): their
            # pinned bytes attribute to the BASE table's id, so the
            # profiler's top-pinned-table view stays an int table id
            tid = base_key[1][1] if isinstance(base_key[1], tuple) \
                else base_key[1]
            self._entries[full_key] = _Entry(batch, nbytes, epoch, version,
                                             pinned, tid)
            self._by_region.setdefault(base_key[0], set()).add(full_key)
            if old is None and not isinstance(base_key[1], tuple):
                # re-admits at the same full key keep their count (the
                # pop above skipped _unindex)
                regs = self._base_tables.setdefault(tid, {})
                regs[base_key[0]] = regs.get(base_key[0], 0) + 1
            self._bytes += nbytes
            if pinned:
                self._bytes_pinned += nbytes
                self._account_pin_locked(tid, nbytes)
            while self._bytes > self.budget_bytes and self._entries:
                fk, ent = self._entries.popitem(last=False)
                self._unindex(fk)
                self._account_remove(ent)
                _metric("evictions").inc()
                if info is not None:
                    info["evictions"] = info.get("evictions", 0) + 1
            self._update_gauges()

    def rekey(self, base_key: tuple, epoch, old_version: int,
              new_version: int) -> bool:
        """MOVE an entry to a new version under the same base key — the
        version-only delta case (other-region / index-only commits of
        the table): the visible planes are IDENTICAL, so re-admitting
        the same batch would double-count its bytes and re-pin it; a
        rekey costs nothing and keeps the accounting exact. Returns
        False when the old entry is gone (caller inserts normally)."""
        full_old = base_key + (epoch, old_version)
        full_new = base_key + (epoch, new_version)
        with self._lock:
            ent = self._entries.pop(full_old, None)
            if ent is None:
                return False
            self._unindex(full_old)
            dup = self._entries.pop(full_new, None)
            if dup is not None:
                self._unindex(full_new)
                self._account_remove(dup)
            ent.version = new_version
            self._entries[full_new] = ent
            self._entries.move_to_end(full_new)
            self._by_region.setdefault(base_key[0], set()).add(full_new)
            if not isinstance(base_key[1], tuple):
                regs = self._base_tables.setdefault(ent.table_id, {})
                regs[base_key[0]] = regs.get(base_key[0], 0) + 1
            self._update_gauges()
            return True

    def set_budget(self, budget_bytes: int) -> None:
        with self._lock:
            self.budget_bytes = budget_bytes
            while self._bytes > self.budget_bytes and self._entries:
                fk, ent = self._entries.popitem(last=False)
                self._unindex(fk)
                self._account_remove(ent)
                _metric("evictions").inc()
            self._update_gauges()

    def regions_with_table(self, table_id: int) -> list[int]:
        """Region ids currently holding live cached BASE-TABLE entries
        for table_id — the delta tier appends a commit's rows only where
        a base exists to merge over (no base ⇒ nothing to keep fresh)."""
        with self._lock:
            return list(self._base_tables.get(table_id, ()))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_region.clear()
            self._base_tables.clear()
            self._bytes = self._bytes_pinned = 0
            self._pinned_tables.clear()
            self._pinned_snapshot = ()
            self._update_gauges()

    # ---- internals (lock held) ----

    def _remove(self, full_key: tuple, ent: _Entry) -> None:
        # gauge refresh is the CALLER's job (batched once per sweep)
        self._entries.pop(full_key, None)
        self._unindex(full_key)
        self._account_remove(ent)

    def _unindex(self, full_key: tuple) -> None:
        keys = self._by_region.get(full_key[0])
        if keys is not None:
            keys.discard(full_key)
            if not keys:
                self._by_region.pop(full_key[0], None)
        if not isinstance(full_key[1], tuple):
            regs = self._base_tables.get(full_key[1])
            if regs is not None:
                n = regs.get(full_key[0], 0) - 1
                if n > 0:
                    regs[full_key[0]] = n
                else:
                    regs.pop(full_key[0], None)
                    if not regs:
                        self._base_tables.pop(full_key[1], None)

    def _account_remove(self, ent: _Entry) -> None:
        self._bytes -= ent.nbytes
        if ent.pinned:
            self._bytes_pinned -= ent.nbytes
            self._account_pin_locked(ent.table_id, -ent.nbytes)

    def _update_gauges(self) -> None:
        _update_gauges()


def _maybe_pin_device(batch) -> bool:
    """Pin the batch's planes device-resident when the TPU tier is live
    in this process — the H2D happens once, at insert, and every repeat
    query reads HBM. A jax-free deployment never pays (or imports)
    anything here. Pinned planes are what the near-data batched kernels
    read directly: the deferred filter (kernels.region_filter_batched
    via _PendingFilter.filter_seg) and the batched states dispatch
    (_PendingStates.device_reductions) both swap host planes for these
    device twins, so a cached+pinned region's filter+states pipeline
    moves only bit-packed masks and per-group states over PCIe — never
    rows.

    HBM governance (ops.membudget): a pin that would cross the
    configured `tidb_tpu_hbm_budget_bytes` is SKIPPED — the entry still
    caches host-side (repeat queries skip the repack, they just pay the
    H2D again), counted on `copr.plane_cache.pin_skipped`. The ledger
    charge itself rides kernels.batch_planes, so pinned bytes un-charge
    exactly when the device buffers die."""
    if sys.modules.get("jax") is None:
        return False
    try:
        from tidb_tpu.ops import membudget
        from tidb_tpu.ops.client import pin_batch_device
        dev_bytes = sum(int(cd.values.nbytes) + int(cd.valid.nbytes)
                        for cd in batch.columns.values()) + batch.capacity
        if membudget.would_exceed_pin(dev_bytes) \
                and getattr(batch, "_device_planes", None) is None:
            _metric("pin_skipped").inc()
            return False
        pin_batch_device(batch)
        return True
    except errors.RetryableError:
        raise       # a retryable fault must reach the client ladder
    except Exception:
        return False            # device tier broken ≠ cache broken


def cache_for(store):
    """The store's region plane cache, or None (non-cluster storage) —
    the supported handle for SET GLOBAL / bootstrap hydration."""
    return getattr(getattr(store, "rpc", None), "plane_cache", None)
