"""Cluster-wide versioned string dictionaries + join-domain unification:
the device dictionary execution tier.

String join/group keys used to be the dict path's exclusive territory:
the device join kernels take int64/f64 key planes, so any string (or
multi-column) equi-join fell back to the per-row hash build/probe. But a
packed string column already IS integers — the batch-local ordered
dictionary codes of ops.columnar — and pushing string predicates and
joins down as integer codes is the classic computation-pushdown win
(PAPERS: "Enhancing Computation Pushdown for Cloud OLAP Databases").
What was missing is a shared CODE DOMAIN: two sides' batch-local
dictionaries assign different codes to the same bytes, and two regions'
partials of one table do too.

This module provides both halves:

* A per-(table, column) VERSIONED dictionary registry living beside the
  plane cache on each region server (cluster RpcHandler) and on the
  in-proc TpuClient. Low-NDV string columns register their batch
  dictionaries at pack time (NDV gate: SET GLOBAL tidb_tpu_dict_max_ndv,
  a distinct/rows ratio); the global dictionary is APPEND-ONLY, so codes
  are stable across data versions and across every region's partials —
  a commit that adds strings extends the dictionary instead of
  invalidating it, and a response ships only the DELTA entries the
  consumer hasn't seen (counted on copr.dict.delta_entries /
  copr.dict.wire_bytes). Invalidation follows the PR 13 discipline: a
  schema-signature change rebuilds the dictionary outright, and a
  version advance that left the append-only union far above the live
  NDV rebuilds it too (copr.dict.rebuilds) so deleted strings cannot
  grow it without bound.

* Join-domain unification: for a string/multi-key equi-join, each key
  column pair maps both sides into ONE shared integer domain (cached
  remaps between registered global dictionaries — repeat joins skip the
  union — or a per-query sorted union for unregistered sides), numeric
  key columns map through a per-query value domain (np.unique +
  searchsorted), and the composite key is the mixed-radix KEY-TUPLE
  code over the per-column domains (the MULTICHIP r05 dryrun shape).
  The tuple codes feed the EXISTING device build/probe kernels
  unchanged — including the mesh-sharded probe — and the host numpy
  twin (host_keys) is bit-identical integer arithmetic, so the
  below-floor route and the device route cannot disagree.

Ordering survives encoding: batch-local dictionaries are sorted, and a
GlobalDict exposes ranks() (code → position in byte order), so a TopN
above a join orders string keys by dictionary RANK without ever
materializing the bytes (executors.TopNExec plane path).

High-NDV columns and non-binary (ci) collations bail to the existing
dict path, counted on copr.degraded_dict; SET GLOBAL
tidb_tpu_device_dict = 0 is the kill switch — the parity oracle.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from tidb_tpu.sessionctx import SYSVAR_DEFAULTS

DEFAULT_MAX_NDV_RATIO = float(SYSVAR_DEFAULTS["tidb_tpu_dict_max_ndv"])

# columns whose distinct count sits under this never trip the NDV ratio
# gate: tiny batches make any ratio meaningless (3 distinct values over
# 4 rows is 0.75 — and exactly the shape the tier exists for)
NDV_RATIO_FLOOR = 64

# a registered dictionary whose append-only union outgrew the live NDV
# by this factor rebuilds on the next registration at a newer version
# (deleted strings must not grow the domain without bound)
REBUILD_FACTOR = 4

# composite key-tuple codes must fit int64 with headroom (the device
# kernels' sentinel arithmetic): past this the dict path answers
RADIX_LIMIT = 1 << 62


class DictBail(Exception):
    """Join shape outside the dictionary tier: `counted` marks the bails
    the ROADMAP wants accounted (high NDV, radix overflow) on
    copr.degraded_dict — structurally ineligible shapes (no plane
    mapping) bail silently, like the single-key numeric path."""

    def __init__(self, reason: str, counted: bool = False):
        super().__init__(reason)
        self.counted = counted


class GlobalDict:
    """One (table, column)'s cluster-wide dictionary: APPEND-ONLY entries
    (code = first-registration index, stable across versions/regions),
    plus a lazily built rank view (code → position in byte order) for
    order-by-dictionary-rank consumers. Thread-safe through the owning
    registry's lock; readers see immutable prefixes (extend only
    appends, and the caches invalidate under the lock)."""

    __slots__ = ("table_id", "column_id", "schema_sig", "version",
                 "entries", "_code_of", "_ranks", "gen")

    def __init__(self, table_id: int, column_id: int, schema_sig,
                 version: int):
        self.table_id = table_id
        self.column_id = column_id
        self.schema_sig = schema_sig
        self.version = version
        self.entries: list[bytes] = []
        self._code_of: dict[bytes, int] = {}
        self._ranks: np.ndarray | None = None
        self.gen = 0            # bumps on extend — unify-cache key part

    def __len__(self) -> int:
        return len(self.entries)

    def extend(self, values) -> int:
        """Append unseen values; returns how many were new (the DELTA a
        response ships — everything before it the consumer already
        holds)."""
        new = 0
        for b in values:
            if b not in self._code_of:
                self._code_of[b] = len(self.entries)
                self.entries.append(b)
                new += 1
        if new:
            self._ranks = None
            self.gen += 1
        return new

    def remap_from(self, local_dict: list[bytes]) -> np.ndarray:
        """local (batch) code → global code. Every local entry must be
        registered already (extend runs first)."""
        code_of = self._code_of
        return np.fromiter((code_of[b] for b in local_dict),
                           dtype=np.int64, count=len(local_dict))

    def ranks(self) -> np.ndarray:
        """code → rank in byte order — the sort key that makes global
        (append-order) codes orderable like the batch-local sorted
        dictionaries are by construction."""
        r = self._ranks
        if r is None or len(r) != len(self.entries):
            order = sorted(range(len(self.entries)),
                           key=self.entries.__getitem__)
            r = np.empty(len(self.entries), dtype=np.int64)
            r[order] = np.arange(len(self.entries), dtype=np.int64)
            self._ranks = r
        return r


class LocalDomain:
    """A batch-local SORTED dictionary wrapped in the same domain
    protocol a GlobalDict speaks — codes are already rank-ordered, so
    ranks() is the identity."""

    __slots__ = ("entries",)

    def __init__(self, entries: list[bytes]):
        self.entries = entries

    def __len__(self) -> int:
        return len(self.entries)

    def ranks(self) -> np.ndarray:
        return np.arange(len(self.entries), dtype=np.int64)


_instances: "weakref.WeakSet[DictRegistry]" = weakref.WeakSet()


def _update_gauges() -> None:
    from tidb_tpu import metrics
    regs = list(_instances)
    metrics.gauge("copr.dict.entries").set(
        sum(sum(len(d) for d in r._dicts.values()) for r in regs))
    metrics.gauge("copr.dict.dictionaries").set(
        sum(len(r._dicts) for r in regs))


class DictRegistry:
    """Per-store registry of GlobalDicts, fed at pack time (region
    columnar engine / TpuClient batch build) and consumed by the join /
    TopN / group-code tiers through ColumnData attributes (_gdict, the
    dictionary; _gmap, local→global code remap). Registration is
    idempotent per batch (batches are immutable once packed)."""

    def __init__(self):
        self.enabled = True
        self.max_ndv_ratio = DEFAULT_MAX_NDV_RATIO
        self._lock = threading.Lock()
        self._dicts: dict[tuple[int, int], GlobalDict] = {}
        _instances.add(self)

    def __len__(self) -> int:
        return len(self._dicts)

    def get(self, table_id: int, column_id: int) -> GlobalDict | None:
        with self._lock:
            return self._dicts.get((table_id, column_id))

    def clear(self) -> None:
        with self._lock:
            self._dicts.clear()
        _update_gauges()

    def register_batch(self, batch, columns, table_id: int,
                       version: int) -> None:
        """Register every low-NDV K_STR column of a freshly packed (or
        cache-hit, not-yet-registered) batch: extend the global
        dictionary with the batch's values and attach the local→global
        remap to the ColumnData. High-NDV columns are refused (counted
        copr.dict.rejected_ndv) — joins on them take the per-query
        bytes-union path or bail to the dict path."""
        if not self.enabled:
            return
        from tidb_tpu import metrics
        from tidb_tpu.ops import columnar as col
        changed = False
        for c in columns:
            cd = batch.columns.get(c.column_id)
            if cd is None or cd.kind != col.K_STR:
                continue
            gd = getattr(cd, "_gdict", None)
            if gd is not None and getattr(cd, "_gmap", None) is not None:
                continue    # batch already registered (immutable planes)
            # the invalidation signature is the COLUMN's own shape (type,
            # flags, precision, enum elems) — never the requesting
            # statement's column SET, which varies per query and must not
            # churn the dictionary
            col_sig = (c.tp, c.flag, c.flen, c.decimal,
                       tuple(c.elems or ()))
            ndv = len(cd.dictionary)
            if ndv > NDV_RATIO_FLOOR and \
                    ndv > self.max_ndv_ratio * max(batch.n_rows, 1):
                metrics.counter("copr.dict.rejected_ndv").inc()
                continue
            with self._lock:
                key = (table_id, c.column_id)
                gd = self._dicts.get(key)
                if gd is not None and gd.schema_sig != col_sig:
                    # DDL changed the column's shape: codes built over
                    # the old signature must never mix with the new —
                    # rebuild outright (the PR 13 invalidation rule)
                    gd = None
                    metrics.counter("copr.dict.rebuilds").inc()
                if gd is not None and version > gd.version and \
                        len(gd.entries) > max(REBUILD_FACTOR * max(ndv, 1),
                                              NDV_RATIO_FLOOR):
                    # the append-only union outgrew the live NDV across
                    # versions (deletes/updates retired strings): rebuild
                    # at the new version so the domain tracks the data
                    gd = None
                    metrics.counter("copr.dict.rebuilds").inc()
                if gd is None:
                    gd = GlobalDict(table_id, c.column_id, col_sig,
                                    version)
                    self._dicts[key] = gd
                new = gd.extend(cd.dictionary)
                gd.version = max(gd.version, version)
                remap = gd.remap_from(cd.dictionary)
            cd._gdict = gd
            cd._gmap = remap
            changed = True
            metrics.counter("copr.dict.registered").inc()
            if new:
                # the DELTA a response actually ships: entries the
                # consumer's mirror has not seen yet (append-only codes
                # make the known prefix implicit)
                metrics.counter("copr.dict.delta_entries").inc(new)
                metrics.counter("copr.dict.wire_bytes").inc(
                    sum(len(b) for b in gd.entries[-new:]) + 8 * new)
        if changed:
            _update_gauges()


def registry_for(store):
    """The store's dictionary registry (cluster RpcHandler or in-proc
    TpuClient), or None — the handle for SET GLOBAL / hydration."""
    rpc = getattr(store, "rpc", None)
    reg = getattr(rpc, "dict_registry", None)
    if reg is not None:
        return reg
    client = store.get_client() if hasattr(store, "get_client") else None
    return getattr(client, "dict_registry", None)


# ---------------------------------------------------------------------------
# join-domain unification: map both sides' per-column codes/values into
# one shared integer domain per key column, then mixed-radix them into a
# single int64 key-tuple code per row
# ---------------------------------------------------------------------------

# (domain identity → union remaps) LRU: repeat joins between the same
# registered dictionaries skip the sorted union entirely (the remap is
# invariant until either dictionary extends — gen is in the key)
_unify_cache: dict = {}
_unify_lock = threading.Lock()


def _dom_key(dom) -> tuple:
    return (id(dom), len(dom), getattr(dom, "gen", 0))


def unify_domains(doms: list):
    """One shared byte domain over several dictionaries: returns
    (union entries sorted, [remap int64[len(dom_i)] per dom]). Cached by
    dictionary identity+generation; counted on copr.dict.remaps /
    copr.dict.remap_reuse."""
    from tidb_tpu import metrics
    key = tuple(_dom_key(d) for d in doms)
    with _unify_lock:
        ent = _unify_cache.get(key)
    if ent is not None:
        metrics.counter("copr.dict.remap_reuse").inc()
        return ent[0], ent[1]
    union = sorted(set().union(*(d.entries for d in doms)))
    pos = {b: i for i, b in enumerate(union)}
    remaps = [np.fromiter((pos[b] for b in d.entries), dtype=np.int64,
                          count=len(d)) for d in doms]
    metrics.counter("copr.dict.remaps").inc()
    with _unify_lock:
        # doms held strongly in the value: ids in the key stay valid
        _unify_cache[key] = (union, remaps, doms)
        while len(_unify_cache) > 128:
            _unify_cache.pop(next(iter(_unify_cache)))
    return union, remaps


class KeySpec:
    """One join key column lowered to its shared-domain pieces, one per
    SIDE: `mode` is "codes" (values already domain codes, -1 = NULL),
    "remap" (batch-local codes through `table`, an int64 local→domain
    map) or "domain" (raw i64/f64 values through `table`, the sorted
    per-query value domain, via searchsorted). `size` is the domain
    cardinality; the composite builder assigns `stride`."""

    __slots__ = ("mode", "values", "valid", "table", "size", "stride")

    def __init__(self, mode: str, values, valid, table, size: int):
        self.mode = mode
        self.values = values
        self.valid = valid
        self.table = table
        self.size = size
        self.stride = 1


def _norm_f64(vals: np.ndarray) -> np.ndarray:
    # -0.0 joins/groups with +0.0 (the codec key normalizes it)
    return np.where(vals == 0.0, 0.0, vals)


def _str_specs(lside, rside, lj: int, rj: int, n_rows: int,
               max_ndv_ratio: float):
    """Shared-domain specs for one STRING key column pair: registered
    global dictionaries unify through the cached remap; unregistered
    sides fall back to a per-query union over the emitted bytes planes
    (exactly the bytes the dict path's codec keys carry). High NDV bails
    counted."""
    lcp = getattr(lside, "dict_code_plane", None)
    rcp = getattr(rside, "dict_code_plane", None)
    lent = lcp(lj) if lcp is not None else None
    rent = rcp(rj) if rcp is not None else None
    if lent is not None and rent is not None:
        lcodes, lvalid, ldom = lent
        rcodes, rvalid, rdom = rent
        if len(ldom) + len(rdom) > \
                max(2 * NDV_RATIO_FLOOR, max_ndv_ratio * max(n_rows, 1) * 2):
            raise DictBail("string NDV above tidb_tpu_dict_max_ndv",
                           counted=True)
        if ldom is rdom:
            size = len(ldom)
            return (KeySpec("codes", lcodes, lvalid, None, size),
                    KeySpec("codes", rcodes, rvalid, None, size))
        _union, (lmap, rmap) = unify_domains([ldom, rdom])
        size = len(_union)
        return (KeySpec("remap", lcodes, lvalid, lmap, size),
                KeySpec("remap", rcodes, rvalid, rmap, size))
    # bytes-union fallback: works for RowsSide drains too — the object
    # planes carry the SAME emitted bytes the codec keys encode
    lkind, lvals, lvalid = lside.column_plane(lj)
    rkind, rvals, rvalid = rside.column_plane(rj)
    if lkind != "str" or rkind != "str":
        return None     # vacuous/mismatched side: never-match (caller)
    luniq = {v for v, ok in zip(lvals.tolist(), lvalid.tolist()) if ok}
    runiq = {v for v, ok in zip(rvals.tolist(), rvalid.tolist()) if ok}
    union = sorted(luniq | runiq)
    if len(union) > NDV_RATIO_FLOOR and \
            len(union) > max_ndv_ratio * max(n_rows, 1):
        raise DictBail("string NDV above tidb_tpu_dict_max_ndv",
                       counted=True)
    pos = {b: i for i, b in enumerate(union)}

    def codes_of(vals, valid):
        return np.fromiter(
            (pos[v] if ok else -1
             for v, ok in zip(vals.tolist(), valid.tolist())),
            dtype=np.int64, count=len(vals))

    size = len(union)
    return (KeySpec("codes", codes_of(lvals, lvalid), lvalid, None, size),
            KeySpec("codes", codes_of(rvals, rvalid), rvalid, None, size))


def build_join_specs(lside, rside, pairs, max_ndv_ratio: float):
    """Lower every eq-condition column pair into shared-domain KeySpecs:
    returns (l_specs, r_specs) with strides assigned, or None when some
    pair can NEVER match (cross-kind sides — the codec keys differ by
    construction, so the join matches nothing; the caller emits the
    empty/outer-padded result directly). Raises DictBail for shapes the
    tier does not take (counted=True for the accounted reasons)."""
    n_rows = len(lside) + len(rside)
    l_specs: list[KeySpec] = []
    r_specs: list[KeySpec] = []
    for lj, rj, is_str in pairs:
        if is_str:
            ent = _str_specs(lside, rside, lj, rj, n_rows, max_ndv_ratio)
            if ent is None:
                return None     # vacuous side: no matches possible
            ls, rs = ent
        else:
            lkind, lvals, lvalid = lside.column_plane(lj)
            rkind, rvals, rvalid = rside.column_plane(rj)
            if lkind not in ("i64", "f64") or rkind not in ("i64", "f64"):
                raise DictBail(f"no plane mapping for key pair "
                               f"({lkind}, {rkind})")
            if lkind != rkind:
                # int side vs float side never match under the dict
                # path's codec keys (i64(5) != f64(5.0))
                return None
            if lkind == "f64":
                lvals, rvals = _norm_f64(lvals), _norm_f64(rvals)
            dom = np.unique(np.concatenate([lvals[lvalid], rvals[rvalid]]))
            size = len(dom)
            ls = KeySpec("domain", lvals, lvalid, dom, size)
            rs = KeySpec("domain", rvals, rvalid, dom, size)
        l_specs.append(ls)
        r_specs.append(rs)
    # mixed-radix strides, least-significant last (declaration order is
    # most-significant first — any consistent order is correct, equality
    # is all the join reads)
    prod = 1
    for s in l_specs:
        prod *= max(s.size, 1)
        if prod >= RADIX_LIMIT:
            raise DictBail("key-tuple radix exceeds int64", counted=True)
    stride = 1
    for ls, rs in zip(reversed(l_specs), reversed(r_specs)):
        ls.stride = rs.stride = stride
        stride *= max(ls.size, 1)
    return l_specs, r_specs


def host_keys(specs: list[KeySpec], n: int):
    """Composite key-tuple codes on the HOST: (key int64[n], valid
    bool[n]) — bit-identical to the device remap kernel (same integer
    arithmetic, same clip semantics), the below-floor route and the
    parity anchor for kernels.dict_remap_keys."""
    key = np.zeros(n, dtype=np.int64)
    valid = np.ones(n, dtype=bool)
    for s in specs:
        if s.mode == "codes":
            codes = np.clip(s.values, 0, max(s.size - 1, 0))
        elif s.mode == "remap":
            codes = s.table[np.clip(s.values, 0, len(s.table) - 1)] \
                if len(s.table) else np.zeros(n, dtype=np.int64)
        else:
            codes = np.searchsorted(s.table, s.values).astype(np.int64)
            np.clip(codes, 0, max(s.size - 1, 0), out=codes)
        key += codes * s.stride
        valid &= s.valid
    return key, valid
