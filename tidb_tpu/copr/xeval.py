"""Interpreted evaluation of pushdown Expr trees over a row.

Reference: distsql/xeval/eval.go:38 (Evaluator with row map[int64]Datum),
Eval (:49) and the per-family files eval_compare_ops.go etc. Delegates all
scalar semantics to expression.ops — the single compute core shared with the
SQL-side evaluator — so pushdown cannot change results.

This is the CPU reference engine the TPU kernels are differentially tested
against ("result parity vs CPU xeval").
"""

from __future__ import annotations

from tidb_tpu import errors
from tidb_tpu.copr.proto import Expr, ExprType
from tidb_tpu.expression import ops as xops
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL


class Evaluator:
    """Evaluates Expr trees; `row` maps column-id → Datum."""

    __slots__ = ("row",)

    def __init__(self):
        self.row: dict[int, Datum] = {}

    def eval(self, e: Expr) -> Datum:
        tp = e.tp
        if tp == ExprType.VALUE:
            return e.val
        if tp == ExprType.NULL:
            return NULL
        if tp == ExprType.COLUMN_REF:
            try:
                return self.row[e.val]
            except KeyError:
                raise errors.ExecError(f"column {e.val} not found in row")
        if tp == ExprType.OPERATOR:
            if len(e.children) == 1:
                return xops.compute_unary(e.op, self.eval(e.children[0]))
            from tidb_tpu.sqlast.opcode import Op
            a = self.eval(e.children[0])
            if e.op == Op.AndAnd and xops.datum_truth(a) is False:
                return xops.FALSE
            if e.op == Op.OrOr and xops.datum_truth(a) is True:
                return xops.TRUE
            return xops.compute_binary(e.op, a, self.eval(e.children[1]))
        if tp in (ExprType.LIKE, ExprType.NOT_LIKE):
            target = self.eval(e.children[0])
            pattern = self.eval(e.children[1])
            escape = e.val if isinstance(e.val, str) else "\\"
            return xops.compute_like(target, pattern, escape,
                                     negated=(tp == ExprType.NOT_LIKE))
        if tp in (ExprType.IN, ExprType.NOT_IN):
            v = self.eval(e.children[0])
            items = [self.eval(c) for c in e.children[1:]]
            return xops.compute_in(v, items, negated=(tp == ExprType.NOT_IN))
        if tp == ExprType.IS_NULL:
            return xops.bool_datum(self.eval(e.children[0]).is_null())
        if tp == ExprType.IS_NOT_NULL:
            return xops.bool_datum(not self.eval(e.children[0]).is_null())
        if tp in _CONTROL:
            return self._eval_named(_CONTROL[tp], e)
        if tp == ExprType.CASE:
            return self._eval_named("case", e)
        if tp == ExprType.SCALAR_FUNC:
            return self._eval_named(e.val, e)
        raise errors.ExecError(f"xeval: unsupported expr type {tp!r}")

    def _eval_named(self, name: str, e: Expr) -> Datum:
        from tidb_tpu.expression import builtin
        args = [_BoundChild(self, c) for c in e.children]
        return builtin.call(name, args, None)


_CONTROL = {
    ExprType.IF: "if",
    ExprType.IFNULL: "ifnull",
    ExprType.NULLIF: "nullif",
    ExprType.COALESCE: "coalesce",
}


class _BoundChild:
    """Adapter presenting an Expr as an expression.Expression so builtin
    control funcs can lazily evaluate arguments."""

    __slots__ = ("ev", "expr")

    def __init__(self, ev: Evaluator, expr: Expr):
        self.ev = ev
        self.expr = expr

    def eval(self, row=None) -> Datum:
        return self.ev.eval(self.expr)


# capability probe — which expr shapes this engine supports
# (store/localstore/local_client.go:39-90 SupportRequestType/supportExpr)
def supported_expr(e: Expr) -> bool:
    tp = e.tp
    if tp in (ExprType.VALUE, ExprType.NULL, ExprType.COLUMN_REF):
        return True
    if tp == ExprType.OPERATOR:
        return all(supported_expr(c) for c in e.children)
    if tp in (ExprType.LIKE, ExprType.NOT_LIKE, ExprType.IN, ExprType.NOT_IN,
              ExprType.IS_NULL, ExprType.IS_NOT_NULL, ExprType.IF,
              ExprType.IFNULL, ExprType.NULLIF, ExprType.COALESCE,
              ExprType.CASE):
        return all(supported_expr(c) for c in e.children)
    if tp == ExprType.SCALAR_FUNC:
        from tidb_tpu.expression import builtin
        return builtin.exists(e.val) and all(supported_expr(c)
                                             for c in e.children)
    from tidb_tpu.copr.proto import AGG_TYPES
    if tp in AGG_TYPES:
        # distinct aggregates are never pushed down: per-region distinct
        # sets can't be merged by the FinalMode sum
        # (plan/physical_plan_builder.go:797-809 has the same rule)
        if e.distinct:
            return False
        return all(supported_expr(c) for c in e.children)
    return False
