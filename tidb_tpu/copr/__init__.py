"""Coprocessor protocol + execution engines.

This package is the pushdown boundary of the framework — the equivalent of
the reference's tipb protocol (_vendor .../tipb/go-tipb/select.pb.go) plus
the engines that execute pushed-down requests:

  proto.py          SelectRequest/SelectResponse/Expr — the wire IR
  xeval.py          interpreted Expr evaluation over rows (distsql/xeval)
  region_handler.py CPU engine: scan+filter+topn+partial agg per key range
                    (store/localstore/local_region.go Handle)

The TPU engine (tidb_tpu.ops) consumes the same proto IR but compiles Expr
trees to vectorized JAX/Pallas kernels over columnar batches instead of
interpreting them per row — the CPU engine here is the parity oracle.
"""

from tidb_tpu.copr.proto import (
    Expr, ExprType, SelectRequest, SelectResponse, Chunk, ByItem,
    PBColumnInfo, PBTableInfo, PBIndexInfo,
    columns_to_proto, index_to_proto, field_type_from_pb_column,
    expr_value, expr_column, expr_op, expr_agg,
)

__all__ = [
    "Expr", "ExprType", "SelectRequest", "SelectResponse", "Chunk", "ByItem",
    "PBColumnInfo", "PBTableInfo", "PBIndexInfo",
    "columns_to_proto", "index_to_proto", "field_type_from_pb_column",
    "expr_value", "expr_column", "expr_op", "expr_agg",
]
