"""CPU coprocessor engine: executes a SelectRequest over key ranges.

Reference: store/localstore/local_region.go:189 (localRegion.Handle) and
local_aggregate.go (partial aggregation). Pipeline per request:

    range scan → row decode → xeval where-filter → (topn | aggregate | emit)

Aggregate output rows are `[groupKey, cnt?, val?...]` partials
(local_region.go:357-391) — the upper FinalMode aggregate merges them. The
same handler serves table requests (row keys) and index requests (index
keys). The TPU engine (tidb_tpu.ops) implements this same contract over
columnar batches; this module is its parity oracle.
"""

from __future__ import annotations

import heapq

from tidb_tpu import errors, mysqldef as my, tablecodec
from tidb_tpu.codec import codec
from tidb_tpu.copr.proto import (
    AGG_NAME, ByItem, ChunkWriter, Expr, PBColumnInfo, SelectRequest,
    SelectResponse,
)
from tidb_tpu.copr.xeval import Evaluator, _BoundChild
from tidb_tpu.expression.aggregation import AggregationFunction
from tidb_tpu.expression import ops as xops
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import compare_datum


def handle_request(snapshot, req: SelectRequest,
                   ranges: list[KeyRange]) -> SelectResponse:
    """Entry point — one region's share of a coprocessor request."""
    ctx = _SelectContext(snapshot, req)
    try:
        if req.table_info is not None:
            for rg in ranges:
                ctx.scan_table_range(rg)
        elif req.index_info is not None:
            for rg in ranges:
                ctx.scan_index_range(rg)
        else:
            raise errors.ExecError("SelectRequest has neither table nor index info")
        return ctx.finish()
    except errors.RetryableError:
        # pending locks (KeyIsLockedError) and region errors drive the
        # CLIENT's resolve-and-retry ladder (DistCoprClient._exec_range)
        # — stringifying them into an error response used to strand the
        # statement with "coprocessor error: key ... locked by txn"
        # instead of resolving the lock (seed bug, surfaced by the plane
        # cache's hit-side lock gate tests)
        raise
    except errors.TiDBError as e:
        return SelectResponse(error=str(e))


class _SelectContext:
    """Reference: selectContext (local_region.go:165)."""

    def __init__(self, snapshot, req: SelectRequest):
        self.snap = snapshot
        self.req = req
        self.ev = Evaluator()
        self.writer = ChunkWriter()
        self.count = 0
        self.limit = req.limit

        cols = (req.table_info.columns if req.table_info
                else req.index_info.columns)
        self.columns: list[PBColumnInfo] = cols
        self.pk_col: PBColumnInfo | None = next(
            (c for c in cols if c.pk_handle), None)
        # fill values for columns absent from a stored row (written before
        # an ADD COLUMN): the column's original default, else NULL — so
        # pushed filters see the same value _output_row would emit.
        # Defaults round-trip through the codec ONCE here so the raw row
        # fast path emits byte-identical kinds to what chunk decode would
        # (e.g. a STRING default flattens to BYTES like every stored value)
        from tidb_tpu.types.datum import NULL as _NULL

        def _norm(d: Datum) -> Datum:
            return codec.decode_all(codec.encode_value([d]))[0]

        self.fill_cols: list[tuple[int, Datum]] = [
            (c.column_id, _norm(c.default_val)
             if c.default_val is not None else _NULL)
            for c in cols if not c.pk_handle]
        self.raw_rows: list[tuple[int, list[Datum]]] = []

        self.aggs: list[AggregationFunction] = []
        self.agg_ctxs: dict[bytes, list] = {}
        self.group_keys: list[bytes] = []  # insertion-ordered
        if req.is_agg():
            for e in req.aggregates:
                name = AGG_NAME[e.tp]
                args = [_BoundChild(self.ev, c) for c in e.children]
                self.aggs.append(AggregationFunction(name, args,
                                                     distinct=e.distinct))

        # TopN state: heap of (inverted sort key, seq, row) keeping the best
        # `limit` rows (topnHeap, local_region.go:97)
        self.topn = bool(req.order_by) and req.limit is not None \
            and not req.is_agg()
        self._heap: list = []
        self._seq = 0

    # ---- scans ----

    def scan_table_range(self, rg: KeyRange) -> None:
        it = (self.snap.iterate_reverse(rg.start, rg.end) if self.req.desc
              else self.snap.iterate(rg.start, rg.end))
        for key, value in it:
            if self._done():
                return
            try:
                _, handle = tablecodec.decode_row_key(key)
            except errors.TiDBError:  # retryable-ok: pure key decode,
                continue              # no KV access inside the try
            row = tablecodec.decode_row(value)
            self._fill_handle(row, handle)
            for cid, dv in self.fill_cols:
                if cid not in row:
                    row[cid] = dv
            self._process_row(handle, row)

    def scan_index_range(self, rg: KeyRange) -> None:
        n_vals = len(self.columns)
        has_pk = self.pk_col is not None
        n_idx_vals = n_vals - 1 if has_pk else n_vals
        it = (self.snap.iterate_reverse(rg.start, rg.end) if self.req.desc
              else self.snap.iterate(rg.start, rg.end))
        for key, value in it:
            if self._done():
                return
            values, suffix = tablecodec.cut_index_key(key, n_idx_vals)
            if suffix:
                handle = tablecodec.decode_handle_from_index_suffix(suffix)
            else:
                # unique index: handle lives in the value (table.Index.create)
                handle = int(value)
            row = {c.column_id: v
                   for c, v in zip(self.columns, values)}
            if has_pk:
                self._fill_handle(row, handle)
            self._process_row(handle, row)

    def _fill_handle(self, row: dict[int, Datum], handle: int) -> None:
        if self.pk_col is not None:
            d = Datum.u64(handle) if my.has_unsigned_flag(self.pk_col.flag) \
                else Datum.i64(handle)
            row[self.pk_col.column_id] = d

    def _done(self) -> bool:
        return (self.limit is not None and not self.topn
                and not self.req.is_agg() and self.count >= self.limit)

    # ---- per-row pipeline ----

    def _process_row(self, handle: int, row: dict[int, Datum]) -> None:
        self.ev.row = row
        if self.req.where is not None:
            if xops.datum_truth(self.ev.eval(self.req.where)) is not True:
                return
        if self.req.is_agg():
            self._aggregate_row(row)
            return
        if self.topn:
            self._topn_row(handle, row)
            return
        self.count += 1
        # in-proc fast path: hand the decoded datums straight to the
        # consumer (SelectResponse.raw) — the chunk encode/decode round
        # trip per row exists for a wire this embedded handler never
        # crosses (round-5: plain scans were double-codec bound). Peak
        # memory is unchanged: the SQL-side executor materializes these
        # same Datum objects anyway, and raw shares references where the
        # chunk path held encoded bytes ALONGSIDE the consumer's datums.
        self.raw_rows.append((handle, self._output_row(row)))

    def _output_row(self, row: dict[int, Datum]) -> list[Datum]:
        from tidb_tpu.types.datum import NULL
        return [row.get(c.column_id, NULL) for c in self.columns]

    # ---- aggregation (local_aggregate.go) ----

    def _group_key(self) -> bytes:
        if not self.req.group_by:
            return b""
        vals = [self.ev.eval(item.expr) for item in self.req.group_by]
        return codec.encode_value(vals)

    def _aggregate_row(self, row: dict[int, Datum]) -> None:
        gk = self._group_key()
        ctxs = self.agg_ctxs.get(gk)
        if ctxs is None:
            ctxs = [a.create_context() for a in self.aggs]
            self.agg_ctxs[gk] = ctxs
            self.group_keys.append(gk)
        for agg, ctx in zip(self.aggs, ctxs):
            # args are bound to self.ev which already points at `row`
            agg.update(ctx, None)

    # ---- topn ----

    def _sort_key(self, row: dict[int, Datum]) -> list:
        return [self.ev.eval(item.expr) for item in self.req.order_by]

    def _topn_row(self, handle: int, row: dict[int, Datum]) -> None:
        key = self._sort_key(row)
        entry = _TopNEntry(key, [d.desc for d in self.req.order_by])
        item = (entry, self._seq, handle, self._output_row(row))
        self._seq += 1
        if len(self._heap) < self.limit:
            heapq.heappush(self._heap, _Inverted(item))
        elif self._heap and _Inverted(item) > self._heap[0]:
            heapq.heapreplace(self._heap, _Inverted(item))

    # ---- output ----

    def finish(self) -> SelectResponse:
        if self.req.is_agg():
            for gk in self.group_keys:
                ctxs = self.agg_ctxs[gk]
                out = [Datum.bytes_(gk)]
                for agg, ctx in zip(self.aggs, ctxs):
                    out.extend(agg.get_partial_result(ctx))
                self.writer.append_row(0, out)
            chunks = self.writer.finish()
            # partial-row wire footprint of this aggregate response —
            # the denominator of the states-vs-rows bytes figure the
            # columnar STATES channel (copr.columnar_region) is
            # measured against (bench measure_q1_pushdown)
            from tidb_tpu import metrics
            metrics.counter("copr.agg_rows.wire_bytes").inc(
                sum(len(c.rows_data) for c in chunks))
            return SelectResponse(chunks=chunks)
        if self.topn:
            # ties break by scan order (seq) so output is deterministic and
            # engine-independent (TPU top_k is stable by row index)
            items = sorted((inv.item for inv in self._heap),
                           key=lambda it: (it[0], it[1]))
            for entry, _, handle, out in items:
                self.raw_rows.append((handle, out))
        return SelectResponse(raw=self.raw_rows)


class _TopNEntry:
    """Sort key with per-column desc flags; orders ascending in 'better
    first' terms so the heap keeps the top-N."""

    __slots__ = ("vals", "descs")

    def __init__(self, vals: list[Datum], descs: list[bool]):
        self.vals = vals
        self.descs = descs

    def compare(self, other: "_TopNEntry") -> int:
        for a, b, desc in zip(self.vals, other.vals, self.descs):
            c = compare_datum(a, b)
            if c != 0:
                return -c if desc else c
        return 0

    def __lt__(self, other):
        return self.compare(other) < 0

    def __gt__(self, other):
        return self.compare(other) > 0

    def __eq__(self, other):
        return self.compare(other) == 0


class _Inverted:
    """Max-heap adapter over heapq's min-heap: 'worst kept row at top'."""

    __slots__ = ("item",)

    def __init__(self, item):
        self.item = item

    def _key(self):
        return self.item[0], self.item[1]

    def __lt__(self, other):          # self is "less" when it sorts LATER
        a, sa = self._key()
        b, sb = other._key()
        c = a.compare(b)
        if c != 0:
            return c > 0
        return sa > sb

    def __gt__(self, other):
        return other.__lt__(self)
