"""Per-region COLUMNAR coprocessor results: the region-side half of the
columnar channel across the cluster store's fan-out.

A scan request carrying columnar_hint used to be answered columnar only
by the in-proc TpuClient (one response for the whole scan). Here each
REGION answers the hint itself: its share of the key ranges packs into a
ColumnBatch (the same native-C row→plane decode the TPU tier uses), the
pushed filter evaluates vectorized over the planes (ops.exprc — the same
lowering the device kernels trace), and the response ships the planes +
selection index as a ColumnarScanResult PARTIAL. The client stacks the
per-region partials (ops.columnar.ColumnarPartialSet) so a multi-region
scan→join→agg stays columnar end to end, and the SQL-side fused
aggregate merges per-region partial states with the mesh combine algebra
(executor.fused_agg). Reference: the per-region coprocessor tasks of
store/tikv/coprocessor.go:305 — with planes instead of chunk rows.

Anything this engine cannot express EXACTLY returns None and the row
handler (copr.region_handler) answers that region instead — including
TypeError_ packs (unsigned bigint above the int64 plane, out-of-scale
decimals): per-region fallback, counted per PARTIAL by the client.
"""

from __future__ import annotations

import threading
from decimal import Decimal

import numpy as np

from tidb_tpu import errors, failpoint, mysqldef as my, tablecodec as tc
from tidb_tpu.codec import codec
from tidb_tpu.copr.proto import (
    AGG_NAME, ExprType, SelectRequest, SelectResponse,
)
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.ops import columnar as col
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL

I64_MAX = (1 << 63) - 1
I64_MIN = -(1 << 63)

# rows below which the host numpy states beat a device dispatch for the
# region-side grouped partial-aggregate pass (the same flat round-trip
# economics as the client dispatch floor, applied inside one region).
# Both paths compute the identical monoid states; tests monkeypatch this
# to 0 to force the device kernel + its failpoint seams.
STATES_DEVICE_FLOOR = 4096

# near-data batched states (PR 16): when on, each region DEFERS its
# device states pass — the fan-out workers ship payloads with the
# segment reductions still pending, and the drain's statement-level
# finisher (finish_states_batch) computes EVERY region's states in ONE
# ragged segmented dispatch (shard-owned on a mesh). Off → the serial
# per-region dispatch of PR 11, which is also the degradation rung.
# Tests monkeypatch this for the differential suites.
BATCH_STATES_ENABLED = True

# near-data batched FILTER (PR 17): when on, a pushed-down aggregate
# region with a lowerable WHERE defers the filter pass too — the payload
# ships with mask AND states pending, and the statement finisher
# evaluates every region's predicate over the device-resident cached
# planes in ONE ragged dispatch (kernels.region_filter_batched, bit-
# packed survivor masks back), then feeds the masks straight into the
# batched states dispatch: filter+states in ≤ 2 flat round trips, no
# host row materialization. Deferral happens only when the region-time
# probe (_states_probe) PROVES the finish-time states prep cannot fall
# back to rows — the RPC has already answered by then. Off → the
# eager host exprc filter of PR 11, which is also the degradation rung.
BATCH_FILTER_ENABLED = True


def handle_columnar_scan(snapshot, sel: SelectRequest,
                         ranges: list[KeyRange], region=None,
                         cache=None, delta=None, dicts=None,
                         oldest_ts=None) -> SelectResponse | None:
    """One region's share of a columnar_hint request as a columnar
    partial, or None → the caller runs the row handler for this region.

    Three request shapes answer columnar here: plain/TopN TABLE scans
    ship their packed planes + selection index (ColumnarScanResult),
    INDEX scans ship the decoded index-key planes + handle plane the
    same way (pack_index_ranges — index order IS key order, so the
    keep-order contract survives), and pushed-down AGGREGATES ship
    grouped partial STATES (ColumnarAggStates: per-group monoid states
    computed by scatter-free segment reductions over the packed planes —
    device kernel at/above STATES_DEVICE_FLOOR, host numpy below or on
    device fault) instead of partial rows.

    With `region` ((region_id, epoch), as validated by the RPC epoch
    check) and a `cache` (copr.plane_cache.PlaneCache), the post-pack
    pre-filter planes for the clipped ranges are served from / admitted
    to the per-region plane cache keyed by (region_id, epoch,
    data_version_at(start_ts), table/index identity, column set, range
    bounds) — a repeat fan-out query skips the native repack (and, with
    pinned planes, the host→device transfer). The filter/TopN/aggregate
    work still evaluates per request; only the snapshot-determined pack
    is shared."""
    is_index = sel.table_info is None
    if is_index and sel.index_info is None:
        return None
    agg_specs = None
    if sel.is_agg():
        # index requests carrying pushed-down aggregates answer with
        # grouped partial STATES too (PR 11 residual b): the index-key
        # planes hold every referenced column, so the same monoid pass
        # applies — decimal-valued aggregates stay on the row handler
        # for index scans (comparable-key decimal decode could disagree
        # with the record codec's scale), gated in _agg_states_response
        agg_specs = _states_specs(sel)
        if agg_specs is None:
            return None
    if sel.order_by and (is_index or sel.desc or sel.limit is None):
        return None
    from tidb_tpu import tracing
    if failpoint._active and \
            failpoint.eval("copr/drop_columnar") is not None:
        # corrupt-partial seam, made SAFE by construction: instead of
        # shipping damaged planes, the injected fault drops this region's
        # columnar partial entirely — the row handler answers (the last
        # tier of the degradation chain), so parity is preserved and the
        # client counts a fallback for exactly this partial
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    if is_index:
        columns = sel.index_info.columns
        defaults = {}
        pack_key = ("idx", sel.index_info.table_id,
                    sel.index_info.index_id)
    else:
        columns = sel.table_info.columns
        defaults = {c.column_id: c.default_val for c in columns
                    if c.default_val is not None}
        pack_key = sel.table_info.table_id
    batch = None
    cache_info = None
    base_key = version = prefix = None
    mvcc = getattr(snapshot, "mvcc", None)
    if cache is not None and cache.enabled and region is not None \
            and mvcc is not None \
            and not any(mvcc.has_blocking_lock(snapshot.read_ts,
                                               rg.start, rg.end)
                        for rg in ranges):
        # Percolator lock gate: a pending lock with start_ts <= read_ts
        # can resolve to a commit whose commit_ts was allocated BEFORE
        # read_ts — the scan path blocks on it, resolves, and includes
        # the write; a cached hit would silently skip that lock check
        # and serve a snapshot missing it (two reads at the same
        # read_ts could then disagree). Any blocking lock in range
        # forces the pack path, whose scan raises KeyIsLockedError into
        # the client's resolver ladder exactly like the row handler.
        # The version key is the TABLE's (per-table commit filtering):
        # record and index keys share the 10-byte table prefix, and a
        # region pack only ever reads inside it, so a commit to an
        # unrelated table no longer moves this entry's version at all.
        table_id = pack_key[1] if is_index else pack_key
        prefix = tc.table_prefix(table_id)
        version = mvcc.data_version_at(snapshot.read_ts, prefix)
        # the column part of the key is the full SCHEMA SIGNATURE, not
        # just the ids: DDL (MODIFY COLUMN type/default) commits only
        # meta keys, which the per-table version deliberately ignores —
        # a schema change must map to a fresh entry, never a stale pack
        base_key = (region[0], pack_key, _columns_sig(columns),
                    tuple((r.start, r.end) for r in ranges))
        base_ok = None
        if delta is not None and not is_index and delta.enabled:
            base_ok = (lambda v0: delta.usable(
                region[0], table_id, v0, version, mvcc, prefix))
        # HTAP keep set: generations at/above the OLDEST active reader's
        # visible version survive the sweep — an old snapshot below the
        # kept base stops re-packing on every read. Only-current-readers
        # ⇒ keep_version == version ⇒ the sweep is unchanged.
        keep_version = (mvcc.data_version_at(oldest_ts, prefix)
                        if oldest_ts is not None else None)
        batch, cache_info, dbase = cache.lookup_with_base(
            base_key, region[1], version, base_ok,
            keep_version=keep_version)
        # cache_hit / cache_miss land on the region_task span the fan-out
        # worker attached (NOOP when untraced)
        tracing.current().inc("cache_hit" if batch is not None
                              else "cache_miss")
        if batch is None and dbase is not None:
            batch = _delta_merge(delta, dbase, region, table_id, version,
                                 mvcc, prefix, sel, ranges, cache,
                                 base_key, columns, defaults, cache_info,
                                 snapshot)
    try:
        if batch is None:
            with tracing.trace("pack") as psp:
                if failpoint._active:
                    # pack-tier fault: the typed TypeError_ takes the
                    # same no-exact-plane-mapping exit a real unsigned
                    # overflow does — this region degrades to rows
                    failpoint.eval("copr/pack", lambda: errors.TypeError_(
                        "injected region pack fault"))
                if is_index:
                    batch = col.pack_index_ranges(snapshot,
                                                  sel.index_info, ranges)
                else:
                    batch = col.pack_ranges(snapshot,
                                            sel.table_info.table_id,
                                            columns, ranges, defaults)
                psp.set("rows", batch.n_rows)
            if base_key is not None:
                # sound only if the visible version held still across the
                # pack (lock resolution can land commits below start_ts
                # mid-scan — same stabilization rule as TpuClient's
                # batch cache); a churned version serves uncached
                if mvcc.data_version_at(snapshot.read_ts,
                                        prefix) == version:
                    cache.insert(base_key, region[1], version, batch,
                                 cache_info)
        if dicts is not None:
            # device dictionary execution tier (copr.dictionary): every
            # low-NDV string column registers its batch dictionary into
            # the per-(table, column) versioned GLOBAL dictionary at
            # pack time — codes become stable across regions/versions,
            # responses ship only dictionary DELTAS, and the join/TopN/
            # group tiers read shared code domains instead of bytes.
            # Invalidation keys on each COLUMN's own shape signature +
            # the per-table version (a MODIFY COLUMN rebuilds; a
            # version advance extends append-only).
            table_id = pack_key[1] if is_index else pack_key
            dicts.register_batch(batch, columns, table_id,
                                 version if version is not None else 0)
        with tracing.trace("filter") as fsp:
            if failpoint._active:
                failpoint.eval("copr/filter", lambda: errors.TypeError_(
                    "injected region filter fault"))
                if agg_specs is not None:
                    # the agg-states seam fires at region time in BOTH
                    # modes (deferral would otherwise skip it): a typed
                    # fault degrades this region to rows exactly as the
                    # eager path does
                    failpoint.eval("copr/agg_states",
                                   lambda: errors.TypeError_(
                                       "injected agg-states fault"))
            if agg_specs is not None:
                resp = _deferred_filter_response(sel, batch, agg_specs,
                                                 region, cache_info,
                                                 columns, is_index)
                if resp is not None:
                    fsp.set("deferred", 1)
                    return resp
            mask = _filter_mask(sel, batch)
            if mask is not None:
                fsp.set("rows_out", int(np.count_nonzero(mask)))
        if agg_specs is not None and mask is not None:
            resp = _agg_states_response(sel, batch, mask, agg_specs,
                                        region, cache_info, columns,
                                        is_index)
            if resp is None:
                tracing.record_degraded("region_to_rows", tally=False)
            return resp
    except errors.TypeError_:
        # no exact plane mapping (or an injected pack/filter/states
        # fault): this region degrades to the row protocol — the bottom
        # tier of the degradation chain, counted so every fallback is
        # accounted
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    except errors.RetryableError:
        raise   # pending lock mid-pack: the client ladder resolves it
    except errors.TiDBError:
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    if mask is None:
        return None
    if sel.order_by:
        with tracing.trace("topn") as tsp:
            idx = _topn_select(sel, batch, mask)
            if idx is not None:
                tsp.set("rows_out", len(idx))
        if idx is None:
            return None
    else:
        idx = np.nonzero(mask)[0]
        if sel.desc:
            idx = idx[::-1]
        if sel.limit is not None:
            idx = idx[: sel.limit]
    res = col.ColumnarScanResult(batch, np.asarray(idx, dtype=np.int64),
                                 list(columns))
    # per-response attribution: the client rolls these into the
    # statement thread's monotonic tallies (slow-log / perfschema)
    res.cache_info = cache_info
    if region is not None:
        # origin (region id, epoch): the mesh tier's region→shard
        # placement key (ops.mesh.RegionPlacement) — epoch bumps
        # (split/merge) re-place the region
        res.region_id = region[0]
        res.region_epoch = region[1]
    return SelectResponse(columnar=res)


def _columns_sig(columns) -> tuple:
    """Schema signature of the requested columns — the cache-key part
    that changes when DDL changes a column's shape (type, flags,
    precision, enum elems, fill default) without touching any table
    key: per-table versions ignore meta commits, so the signature is
    what keeps a MODIFY COLUMN from ever serving a pre-DDL pack."""
    return tuple(
        (c.column_id, c.tp, c.flag, c.flen, c.decimal, c.pk_handle,
         tuple(c.elems or ()),
         repr(c.default_val) if c.default_val is not None else None)
        for c in columns)


def _delta_merge(delta, dbase, region, table_id: int, version: int,
                 mvcc, prefix: bytes, sel, ranges, cache, base_key,
                 columns, defaults, cache_info, snapshot):
    """The scan-time base+delta merge (HTAP freshness tier): a protected
    older-generation base plus its region's delta pack reconstruct the
    batch a fresh pack at `version` would produce — device tombstone
    mask + handle-ordered concat (kernels.delta_merge_order), host numpy
    below the floor. Returns the merged batch (admitted at the current
    version, with the pack FOLDED and reset when its delta outgrew the
    budget — the background re-pack), or None → the plain pack path.
    The copr/delta_merge failpoint degrades exactly there, with
    unchanged answers (counted on copr.degraded_delta_to_repack)."""
    from tidb_tpu import metrics, tracing
    if failpoint._active and \
            failpoint.eval("copr/delta_merge") is not None:
        tracing.record_degraded("delta_to_repack", tally=False)
        return None
    base_batch, base_version = dbase
    with tracing.trace("delta_merge") as dsp:
        try:
            merged = delta.merge(base_batch, base_version, region[0],
                                 table_id, version, mvcc, prefix, columns,
                                 ranges, defaults)
        except errors.RetryableError:
            raise   # pending-lock class faults reach the client ladder
        except errors.TiDBError:
            # any typed merge fault degrades to the plain re-pack (same
            # answers from the MVCC scan) — never a statement error
            tracing.record_degraded("delta_to_repack", tally=False)
            dsp.set("error", "fault")
            return None
        if merged is None:
            dsp.set("error", "gap")
            return None
        dsp.set("rows_base", base_batch.n_rows).set("rows", merged.n_rows)
    # attribution: the statement's tallies see a delta merge (the repack
    # was avoided — the freshness tier's hit), per the same monotonic
    # contract as plane_cache_hits
    if cache_info is not None:
        cache_info["delta_merges"] = cache_info.get("delta_merges", 0) + 1
    # admit the merged batch as the CURRENT generation (repeat scans at
    # this version then exact-hit), under the same version-stabilization
    # rule as the pack path; fold-and-reset when the delta outgrew its
    # budget — that admission IS the background re-pack. A version-only
    # merge (merged IS the base: the delta held no rows for these
    # planes) REKEYS the existing entry instead of re-inserting the same
    # batch — identical planes, zero byte-accounting churn, no re-pin.
    if mvcc.data_version_at(snapshot.read_ts, prefix) == version:
        if not (merged is base_batch
                and cache.rekey(base_key, region[1], base_version,
                                version)):
            # real merge — or the base entry was concurrently evicted
            # (rekey returns False): admit normally
            cache.insert(base_key, region[1], version, merged, cache_info)
        if delta.repack_due(region[0], table_id):
            delta.reset(region[0], table_id)
            metrics.counter("copr.delta.repacks").inc()
            if cache_info is not None:
                cache_info["delta_repacks"] = \
                    cache_info.get("delta_repacks", 0) + 1
    return merged


# cross-statement cache of compiled region filters (PR 5 residual):
# keyed by the EXPRESSION SHAPE + per-column lowering signature, never by
# the statement — the same WHERE clause re-issued by a later statement
# (dashboards, prepared re-execution, repeat fan-outs) skips the exprc
# re-lower on every region. jit_hits/jit_misses count across statements
# through tracing.record_jit_cache (ops.jit_cache_* metrics).
_filter_cache: dict = {}
_filter_lock = threading.Lock()


def _where_cids(e, out: set) -> None:
    if e.tp == ExprType.COLUMN_REF:
        out.add(e.val)
    for c in e.children or ():
        _where_cids(c, out)


def _compiled_filter(sel: SelectRequest, batch: col.ColumnBatch):
    return _compiled_filter_ent(sel, batch)[0]


def _compiled_filter_ent(sel: SelectRequest, batch: col.ColumnBatch):
    """(compiled, pinned dictionaries, structural key) of the pushed
    where-filter for this batch — compiled fresh or reused.

    Reuse is sound only when every lowering input matches: the Expr tree
    itself (repr — constants are baked into the closures), and each
    referenced column's (kind, MySQL type, fixed-point scale, max-abs
    overflow bound, dictionary identity). Dictionaries pin in the cache
    entry so their ids cannot be recycled while the entry lives — a
    plane-cache hit serves the SAME batch object, so repeat statements
    over cached regions reuse string-filter lowerings too; numeric-only
    filters reuse across fresh packs as long as the guard bounds agree."""
    from tidb_tpu import tracing
    from tidb_tpu.ops.exprc import compile_expr
    cids: set = set()
    _where_cids(sel.where, cids)
    sig = []
    dicts = []
    for cid in sorted(cids):
        cd = batch.columns.get(cid)
        if cd is None:
            sig.append((cid, None))
            continue
        dict_key = None
        if cd.dictionary is not None:
            dict_key = id(cd.dictionary)
            dicts.append(cd.dictionary)
        sig.append((cid, cd.kind, cd.tp, cd.dec_scale, cd.max_abs,
                    dict_key))
    key = (repr(sel.where), tuple(sig))
    # fan-out worker threads share this cache: lookup/insert/evict under
    # the lock (a concurrent duplicate compile is harmless; a dict
    # mutated mid-eviction-iteration is not)
    with _filter_lock:
        ent = _filter_cache.get(key)
    tracing.record_jit_cache(hit=ent is not None)
    if ent is None:
        compiled = compile_expr(sel.where, batch)
        ent = (compiled, dicts)
        with _filter_lock:
            _filter_cache[key] = ent
            while len(_filter_cache) > 512:
                _filter_cache.pop(next(iter(_filter_cache)))
    return ent[0], ent[1], key


def _filter_mask(sel: SelectRequest, batch: col.ColumnBatch):
    """Live-row mask with the pushed where-filter applied vectorized, or
    None when the filter does not lower (row handler answers)."""
    mask = batch.row_mask()
    if sel.where is None:
        return mask
    try:
        from tidb_tpu.ops.exprc import Unsupported
    except ImportError:      # jax-free deployment: rows answer
        return None
    try:
        compiled = _compiled_filter(sel, batch)
    except (Unsupported, errors.TypeError_):
        return None
    planes = {cid: (cd.values, cd.valid)
              for cid, cd in batch.columns.items()}
    wv, wva = compiled(planes)
    wv, wva = np.asarray(wv), np.asarray(wva)
    truth = wv if wv.dtype == np.bool_ else (wv != 0)
    return mask & wva & truth


def _states_probe(batch: col.ColumnBatch, agg_specs, colpb: dict,
                  is_index: bool) -> bool:
    """Can _prepare_states possibly return None for ANY survivor mask of
    this batch? Evaluated at region time, BEFORE the filter runs — the
    deferred-filter payload promises states, so the row fallback must be
    provably unreachable. Mirrors every None exit of _prepare_states:
    the structural ones are mask-independent; the two mask-dependent
    guards (-0.0 presence in a float min/max plane, the int-sum wrap
    bound) are MONOTONE — checked against the SUPERSET mask (all packed
    rows), they hold for every subset the real filter can produce."""
    specs, gcids = agg_specs
    for cid in gcids:
        cd = batch.columns.get(cid)
        c = colpb.get(cid)
        if cd is None or c is None:
            return False
        if not _group_plane(cd, c):
            return False
    sup = batch.row_mask()
    for name, arg in specs:
        if arg is None or arg.tp == ExprType.VALUE:
            continue    # count over a literal: always expressible
        if arg.tp != ExprType.COLUMN_REF:
            if not _probe_arg_plane(name, arg, batch, colpb, sup):
                return False
            continue
        cd = batch.columns.get(arg.val)
        c = colpb.get(arg.val)
        if cd is None or c is None:
            return False
        if name == "count":
            continue
        if name == "first_row":
            # same admission as group keys: first_row datums decode
            # through _flat_datum, which handles every _group_plane kind
            # (temporal included via plane_datum)
            if not _group_plane(cd, c):
                return False
            continue
        if cd.kind == col.K_F64:
            if name in ("sum", "avg"):
                continue
            contrib = sup & cd.valid
            if bool(np.any((cd.values == 0.0) & np.signbit(cd.values)
                           & contrib)):
                return False
            continue
        if cd.kind == col.K_STR:
            if name not in ("min", "max"):
                return False
            continue
        if not (cd.kind == col.K_DEC or _int_plane(cd, c)):
            return False
        if name in ("sum", "avg"):
            n_sup = int(np.count_nonzero(sup & cd.valid))
            mx = cd.max_abs
            if mx and n_sup and mx * n_sup >= (1 << 63):
                return False
    return True


def _probe_arg_plane(name: str, arg, batch: col.ColumnBatch, colpb: dict,
                     sup: np.ndarray) -> bool:
    """Mirror of _prepare_states' EXPRESSION-argument exits, against the
    superset mask: the compile rejects are mask-independent (expression
    shape + whole-batch column metadata), and the int/decimal sum wrap
    bound is monotone (a filter can only shrink the contributing set)."""
    try:
        from tidb_tpu.ops import exprc
    except ImportError:
        return False
    try:
        prog = exprc.compile_arg_plane(arg, batch, colpb)
    except exprc.Unsupported:
        return False
    except errors.TypeError_:
        return False
    if name == "count":
        return True
    if prog.kind == col.K_F64:
        return name in ("sum", "avg")   # derived-plane min/max: row path
    if name in ("sum", "avg"):
        n_sup = int(np.count_nonzero(sup))
        mx = prog.max_abs
        if mx and n_sup and mx * n_sup >= (1 << 63):
            return False
    return True


def _deferred_filter_response(sel: SelectRequest, batch: col.ColumnBatch,
                              agg_specs, region, cache_info, columns,
                              is_index: bool) -> SelectResponse | None:
    """A pushed-down aggregate region's payload with the FILTER deferred
    too (the batched filter channel), or None → the eager path decides
    as before. Deferral requires: the flag, a WHERE that lowers (no
    WHERE → the states channel already covers it; unsupported shapes —
    raw-byte LIKE, u64 edge — keep the host path untouched), a
    jax-backed process, and _states_probe's proof that the finish-time
    states prep can never need the row fallback."""
    if not BATCH_FILTER_ENABLED or sel.where is None:
        return None
    try:
        import jax  # noqa: F401

        from tidb_tpu.ops.exprc import Unsupported
    except ImportError:
        return None
    try:
        compiled, pins, fkey = _compiled_filter_ent(sel, batch)
    except (Unsupported, errors.TypeError_):
        return None
    colpb = {c.column_id: c for c in columns}
    if not _states_probe(batch, agg_specs, colpb, is_index):
        return None
    cids: set = set()
    _where_cids(sel.where, cids)
    pending = _PendingFilter(
        batch, agg_specs, colpb, is_index, compiled, fkey, pins,
        sorted(c for c in cids if c in batch.columns))
    payload = col.ColumnarAggStates(None, None, list(sel.aggregates),
                                    colpb, pending=pending)
    pending.payload = payload
    payload.cache_info = cache_info
    if region is not None:
        payload.region_id = region[0]
        payload.region_epoch = region[1]
    return SelectResponse(columnar=payload)


def _topn_select(sel: SelectRequest, batch: col.ColumnBatch,
                 mask: np.ndarray):
    """Per-region top-`limit` row indices for a pushed TopN, sorted by
    the by-items with scan-position tiebreak — the same bounded candidate
    set (and the same tie semantics) the row handler's heap keeps, so the
    SQL-side merge sees identical partials. None → row handler."""
    sort_keys = []       # least-significant first (np.lexsort order)
    for item in reversed(sel.order_by):
        e = item.expr
        if e.tp != ExprType.COLUMN_REF:
            return None
        cd = batch.columns.get(e.val)
        if cd is None:
            return None
        vals, va = cd.values, cd.valid
        if cd.kind == col.K_F64:
            vo = np.where(vals == 0.0, 0.0, vals)   # -0.0 ties +0.0
            if item.desc:
                vo = -vo
        else:
            # int64 planes (ints, times, durations, dict codes, scaled
            # decimals) order directly; desc via bitwise-not (exact at
            # I64_MIN, where unary minus would wrap)
            vo = ~vals if item.desc else vals
        # NULL ordering: asc → NULLs first, desc → NULLs last (MySQL)
        nullk = va.astype(np.int8) if not item.desc \
            else (~va).astype(np.int8)
        sort_keys.append(np.where(va, vo, np.zeros_like(vo)))
        sort_keys.append(nullk)
    sort_keys.append(~mask)   # dead rows last; stable sort keeps
    #                           scan-position order among ties
    order = np.lexsort(sort_keys)
    n_live = int(np.count_nonzero(mask))
    return order[: min(sel.limit, n_live)]


# ---------------------------------------------------------------------------
# region-side grouped partial-aggregate STATES (the aggregate half of the
# columnar channel): instead of running the per-row interpreter and
# shipping partial chunk rows, the region computes every aggregate's
# per-group monoid state vectorized over the packed planes — group codes
# via the batch's pack/dictionary machinery (tuple_codes: NULL keys get
# their reserved slot), counts/sums/mins/maxes as segment reductions
# (device SegCtx kernel at/above STATES_DEVICE_FLOOR, host numpy below or
# after a device fault) — and ships a ColumnarAggStates payload. Float
# SUM/AVG always accumulate on the host in row order (np.add.at), so the
# per-region partial carries the exact left-to-right rounding sequence
# the row handler's accumulator produces.
# ---------------------------------------------------------------------------

_STATES_NAMES = ("count", "sum", "avg", "min", "max", "first_row")


def _states_specs(sel: SelectRequest):
    """Structural gate for the grouped-states channel, evaluated BEFORE
    any pack: (agg specs, group column ids) when every aggregate and
    group item is expressible as exact per-group monoid states, else
    None → the row handler answers this region with partial rows."""
    if sel.having is not None or sel.order_by or sel.limit is not None \
            or sel.desc:
        return None
    specs = []
    for e in sel.aggregates:
        name = AGG_NAME.get(e.tp)
        if name not in _STATES_NAMES or e.distinct or len(e.children) > 1:
            return None
        arg = e.children[0] if e.children else None
        if arg is None or arg.tp == ExprType.VALUE:
            if name != "count":
                return None   # sum(const)/first_row(const): row handler
        elif arg.tp != ExprType.COLUMN_REF:
            if not _arg_expr_shape_ok(name, arg):
                return None   # shapes the arg-plane compiler can't take
        specs.append((name, arg))
    gcids = []
    for item in sel.group_by:
        if item.expr.tp != ExprType.COLUMN_REF:
            return None
        gcids.append(item.expr.val)
    return specs, gcids




def _arg_expr_shape_ok(name: str, e) -> bool:
    """Structural pre-pack gate for EXPRESSION aggregate arguments
    (PR 18) — the shared planner/region rule (proto.arg_plane_shape_ok):
    arithmetic over column refs / constants, reduced by a
    plane-expressible aggregate. The full contextual rules (kind typing,
    overflow bounds, float-context restrictions) need the packed batch
    and run in exprc.compile_arg_plane at prepare time; every deeper
    reject there is mask-independent and mirrored by _states_probe."""
    from tidb_tpu.copr.proto import arg_plane_shape_ok
    return arg_plane_shape_ok(name, e)


def _int_plane(cd: col.ColumnData, c) -> bool:
    """A plain-integer int64 plane (times/durations/bits excluded: their
    flattened codec forms are not safely reconstructible from the plane
    value alone, so those shapes stay on the row handler)."""
    return cd.kind == col.K_I64 and c.tp in my.INTEGER_TYPES


def _temporal_plane(cd: col.ColumnData, c) -> bool:
    """A time/duration int64 plane: packed time words / duration nanos
    are CANONICAL comparable codes (equal SQL values → equal plane
    words), and col.plane_datum reconstructs the exact flattened storage
    datum — good enough to GROUP by (PR 18), while arithmetic over them
    stays on the row handler (_int_plane keeps excluding them)."""
    return cd.kind == col.K_I64 and (c.tp in my.TIME_TYPES
                                     or c.tp == my.TypeDuration)


def _group_plane(cd: col.ColumnData, c) -> bool:
    """GROUP-key plane kinds: strings (sorted dict codes), floats, plain
    ints — and, since PR 18, decimals and times/durations: their plane
    values are scale-canonical / packed integer codes, so tuple_codes
    groups them structurally and _flat_datum reconstructs group keys
    that merge byte-identically with row-protocol partials."""
    return (cd.kind in (col.K_STR, col.K_F64, col.K_DEC)
            or _int_plane(cd, c) or _temporal_plane(cd, c))


def _flat_datum(cd: col.ColumnData, c, i: int) -> Datum:
    """Plane cell i → the FLATTENED storage datum the row handler's
    decoded row carries (what group keys and partial rows are built
    from). Delegates to col.plane_datum with two deliberate overrides:
    unsigned integer columns keep their storage kind (UINT64 — the
    codec key bytes differ from INT64's, and group keys must merge
    byte-identically with row-protocol partials), and decimals keep the
    column scale via scaleb (plane_datum's division canonicalizes
    trailing zeros; partial-row value slots carry the scale the row
    accumulator's Decimals carry). Callers gate kinds via _group_plane /
    _int_plane / K_F64 / K_STR / K_DEC first — times/durations (group
    keys since PR 18) take the plane_datum decode below."""
    if cd.valid[i]:
        if cd.kind == col.K_I64 and my.has_unsigned_flag(c.flag):
            return Datum.u64(int(cd.values[i]))
        if cd.kind == col.K_DEC:
            return Datum.dec(
                Decimal(int(cd.values[i])).scaleb(-cd.dec_scale))
    return col.plane_datum(cd, c, i)


class ArgPlaneSpec:
    """The VALUE slot of one EXPRESSION-argument reduction (PR 18): the
    compiled arg-plane program plus the batch whose column planes feed
    it. The states kernels recognize it via `is_arg_plane` and evaluate
    the program INSIDE the fused dispatch (validity folds into the
    contrib mask in-trace); `host_eval` is the next ladder rung — the
    SAME compiled closure eagerly over the host planes, bit-identical by
    construction. `cell` is the float-SUM/AVG builder's side channel:
    whichever rung ran fills the per-group row-order sums exactly
    once."""

    is_arg_plane = True

    __slots__ = ("prog", "batch", "cell", "_host")

    def __init__(self, prog, batch: col.ColumnBatch):
        self.prog = prog
        self.batch = batch
        self.cell: dict = {}
        self._host = None

    def device_planes(self) -> dict:
        """{cid: (values, valid)} feeding the fused dispatch — PINNED
        device twins preferred so the kernel reads HBM directly (the
        same discipline as _PendingFilter.filter_seg)."""
        dev = getattr(self.batch, "_device_planes", None)
        planes = {}
        for cid in self.prog.cids:
            cd = self.batch.columns[cid]
            if dev is not None and cid in dev:
                planes[cid] = dev[cid]
            else:
                planes[cid] = (cd.values, cd.valid)
        return planes

    def host_eval(self) -> tuple:
        """(values, valid) of the program over the host planes — the
        per-region host exprc rung (memoized: lowering and the float
        builder may both ask)."""
        if self._host is None:
            planes = {cid: (self.batch.columns[cid].values,
                            self.batch.columns[cid].valid)
                      for cid in self.prog.cids}
            v, va = self.prog(planes)
            self._host = (np.asarray(v), np.asarray(va).astype(bool))
        return self._host


def _has_arg_planes(reductions) -> bool:
    return any(getattr(v, "is_arg_plane", False)
               for _op, v, _ok in reductions)


def _lower_arg_planes(gid: np.ndarray, reductions: list, G: int) -> list:
    """The rung between the fused kernel and the row protocol: evaluate
    each arg-plane program host-side (exprc eager — bit-identical to the
    traced form) and rewrite its reductions into plain (op, vals, ok)
    shape. ARITY-PRESERVING: builder output indices stay valid — float
    plane slots become dummy count reductions after their row-order sums
    precompute into the builder's cell."""
    out = []
    for op, v, ok in reductions:
        if not getattr(v, "is_arg_plane", False):
            out.append((op, v, ok))
            continue
        pv, pva = v.host_eval()
        okv = np.asarray(ok, bool) & pva
        if op == "cnt":
            out.append(("sum", None, okv))
        elif op == "plane":
            if "sums" not in v.cell:
                sums = np.zeros(G, np.float64)
                np.add.at(sums, gid[okv], pv[okv])
                v.cell["sums"] = sums
            out.append(("sum", None, okv))
        elif op == "pvalid":
            out.append(("sum", None, okv))
        else:
            vals = pv if pv.dtype == np.float64 else pv.astype(np.int64)
            out.append((op, vals, okv))
    return out


def _run_states(batch: col.ColumnBatch, gid: np.ndarray, reductions: list,
                G: int) -> list:
    """Run the device-safe segment reductions: ONE device dispatch
    at/above the floor, host numpy below it — and the device→host rung
    of the degradation chain on any device fault (counted on
    copr.degraded_states_to_host; answers identical by the monoid
    algebra)."""
    if not reductions or G == 0:
        return [np.zeros(G, np.int64) for _ in reductions]
    use_device = batch.n_rows >= STATES_DEVICE_FLOOR
    if use_device:
        try:
            import jax  # noqa: F401
        except ImportError:
            use_device = False
    if use_device:
        from tidb_tpu import tracing
        from tidb_tpu.ops import kernels
        try:
            return kernels.region_agg_states(gid, reductions, G)
        except errors.DeviceError:
            tracing.record_degraded("states_to_host", tally=False)
            if _has_arg_planes(reductions):
                tracing.record_degraded("arg_plane", tally=False)
    if _has_arg_planes(reductions):
        # below the floor (routine) or after a device fault (counted
        # above): the host exprc rung materializes the arg planes and
        # the plain numpy reductions below answer identically
        reductions = _lower_arg_planes(gid, reductions, G)
    outs = []
    for op, vals, ok in reductions:
        if vals is None:
            vals = np.ones(len(gid), dtype=np.int64)
        if op == "sum":
            acc = np.zeros(G, vals.dtype)
            np.add.at(acc, gid[ok], vals[ok])
        elif op == "min":
            init = np.inf if vals.dtype == np.float64 else I64_MAX
            acc = np.full(G, init, vals.dtype)
            np.minimum.at(acc, gid[ok], vals[ok])
        else:
            init = -np.inf if vals.dtype == np.float64 else I64_MIN
            acc = np.full(G, init, vals.dtype)
            np.maximum.at(acc, gid[ok], vals[ok])
        outs.append(acc)
    return outs


def _agg_states_response(sel: SelectRequest, batch: col.ColumnBatch,
                         mask: np.ndarray, agg_specs, region,
                         cache_info, columns=None,
                         is_index: bool = False) -> SelectResponse | None:
    """One region's pushed aggregate as grouped partial states, or None
    → the row handler answers (a column kind without an exact state
    mapping, or an int-sum overflow guard). Serves TABLE and INDEX
    requests alike (the index-key planes carry every referenced column);
    since PR 18 that includes DECIMAL-valued index aggregates — the
    comparable-key decode and the record codec both land on the scaled
    int64 plane at the COLUMN scale, so _flat_datum reconstructs the
    same digits either way and merged results stay numerically exact."""
    if columns is None:
        columns = sel.table_info.columns
    colpb = {c.column_id: c for c in columns}
    prepared = _prepare_states(batch, mask, agg_specs, colpb, is_index)
    if prepared is None:
        return None
    group_keys, pending = prepared
    if BATCH_STATES_ENABLED and pending.reductions and pending.G > 0:
        # DEFER the states pass: the payload ships with its segment
        # reductions pending, and the drain's statement-level finisher
        # (finish_states_batch) runs every region's states in ONE
        # batched dispatch — or any consumer touching .aggs first
        # resolves this region serially (identical answers)
        payload = col.ColumnarAggStates(group_keys, None,
                                        list(sel.aggregates), colpb,
                                        pending=pending)
    else:
        payload = col.ColumnarAggStates(group_keys, pending.resolve(),
                                        list(sel.aggregates), colpb)
    payload.cache_info = cache_info
    if region is not None:
        payload.region_id = region[0]
        payload.region_epoch = region[1]
    return SelectResponse(columnar=payload)


def _prepare_states(batch: col.ColumnBatch, mask: np.ndarray, agg_specs,
                    colpb: dict, is_index: bool):
    """Everything between the survivor mask and the device dispatch:
    group discovery in first-appearance scan order, codec-encoded group
    keys, device-safe segment reductions and the state builders —
    returns (group_keys, _PendingStates), or None when a column kind has
    no exact state mapping / an int-sum could wrap (the row handler must
    answer). Every None exit is either mask-INDEPENDENT or MONOTONE
    under mask subsets — the contract _states_probe relies on to prove a
    deferred-filter region can never need the row fallback after its RPC
    already answered."""
    from tidb_tpu import metrics
    specs, gcids = agg_specs
    live_idx = np.nonzero(mask)[0]
    for cid in gcids:
        cd = batch.columns.get(cid)
        c = colpb.get(cid)
        if cd is None or c is None:
            return None
        if not _group_plane(cd, c):
            return None
    if gcids:
        codes, _percol = batch.tuple_codes(gcids)
        lg = codes[mask]
    else:
        lg = np.zeros(len(live_idx), dtype=np.int64)
    uniq, first_idx, inv = np.unique(lg, return_index=True,
                                     return_inverse=True)
    G = len(uniq)
    # region-local groups in FIRST-APPEARANCE scan order — the partial
    # emission order of the row handler, which the client's group
    # unification preserves across regions
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(G, np.int64)
    rank[order] = np.arange(G, dtype=np.int64)
    rep_rows = live_idx[first_idx[order]]       # group's first live row
    gid = np.full(batch.capacity, G, dtype=np.int64)   # dead-row sink
    if G:
        gid[mask] = rank[np.reshape(inv, -1)]
    group_keys = []
    for r in rep_rows.tolist():
        gvals = [_flat_datum(batch.columns[cid], colpb[cid], int(r))
                 for cid in gcids]
        group_keys.append(codec.encode_value(gvals))

    reductions: list = []       # (op, vals|None|ArgPlaneSpec, contrib)
    builders: list = []         # idx layout → AggStateCol
    has_arg_planes = False

    def red(op, vals, ok) -> int:
        reductions.append((op, vals, ok))
        return len(reductions) - 1

    for name, arg in specs:
        if arg is None or arg.tp == ExprType.VALUE:
            # count over a literal: count(*) lowers to count(1)
            const = arg.val if arg is not None else Datum.i64(1)
            contrib = np.zeros(batch.capacity, bool) if const.is_null() \
                else mask
            ci = red("sum", None, contrib)
            builders.append(lambda outs, ci=ci: col.AggStateCol(
                "count", outs[ci].astype(np.int64)))
            continue
        if arg.tp != ExprType.COLUMN_REF:
            # EXPRESSION argument (PR 18): lower into an arg-plane
            # program the states kernel evaluates INSIDE the fused
            # dispatch — no extra device round trip. Every reject
            # mirrors into _probe_arg_plane (mask-independent compile,
            # or a bound monotone under the superset mask).
            try:
                from tidb_tpu.ops import exprc
            except ImportError:
                return None
            try:
                prog = exprc.compile_arg_plane(arg, batch, colpb)
            except exprc.Unsupported:
                return None
            except errors.TypeError_:
                return None
            spec = ArgPlaneSpec(prog, batch)
            has_arg_planes = True
            metrics.counter("copr.arg_plane.specs").inc()
            if name == "count":
                ci = red("cnt", spec, mask)
                builders.append(lambda outs, ci=ci: col.AggStateCol(
                    "count", outs[ci].astype(np.int64)))
                continue
            if prog.kind == col.K_F64:
                if name in ("min", "max"):
                    # a derived float plane can surface -0.0 ties whose
                    # first-seen row semantics a combine can't reproduce
                    return None
                # float SUM/AVG: the plane computes ON DEVICE inside the
                # fused dispatch but reads back ROW-SPACE, so the sums
                # accumulate host-side in row order (np.add.at is
                # unbuffered) — the same left-to-right rounding sequence
                # the row accumulator produces
                ci = red("cnt", spec, mask)
                pi = red("plane", spec, mask)
                qi = red("pvalid", spec, mask)

                def fbuild(outs, ci=ci, pi=pi, qi=qi, name=name,
                           cell=spec.cell, gid=gid, G=G):
                    counts = outs[ci].astype(np.int64)
                    sums = cell.get("sums")
                    if sums is None:
                        sums = np.zeros(G, np.float64)
                        if G:
                            pok = np.asarray(outs[qi]).astype(bool)
                            pv = np.asarray(outs[pi], np.float64)
                            np.add.at(sums, gid[pok], pv[pok])
                    return col.AggStateCol(name, counts, values=sums,
                                           op="sum", kind="f64")
                builders.append(fbuild)
                continue
            kind = "dec" if prog.kind == col.K_DEC else "i64"
            scale = prog.scale
            if name in ("sum", "avg"):
                n_contrib = int(np.count_nonzero(mask))
                mx = prog.max_abs
                if mx and n_contrib and mx * n_contrib >= (1 << 63):
                    return None   # could wrap: Decimal row path answers
                ci = red("cnt", spec, mask)
                vi = red("sum", spec, mask)
            else:
                ci = red("cnt", spec, mask)
                vi = red("min" if name == "min" else "max", spec, mask)
            op = "sum" if name in ("sum", "avg") else name
            builders.append(
                lambda outs, ci=ci, vi=vi, name=name, op=op, kind=kind,
                scale=scale:
                col.AggStateCol(name, outs[ci].astype(np.int64),
                                values=outs[vi], op=op, kind=kind,
                                dec_scale=scale))
            continue
        cd = batch.columns.get(arg.val)
        c = colpb.get(arg.val)
        if cd is None or c is None:
            return None
        contrib = mask & cd.valid
        if name == "count":
            ci = red("sum", None, contrib)
            builders.append(lambda outs, ci=ci: col.AggStateCol(
                "count", outs[ci].astype(np.int64)))
            continue
        if name == "first_row":
            if not _group_plane(cd, c):
                return None
            datums = [_flat_datum(cd, c, int(r)) for r in rep_rows.tolist()]
            ci = red("sum", None, mask)
            builders.append(lambda outs, ci=ci, datums=datums, name=name:
                            col.AggStateCol(name,
                                            outs[ci].astype(np.int64),
                                            datums=datums))
            continue
        if cd.kind == col.K_F64:
            vals = cd.values
            if name in ("sum", "avg"):
                # float partial sums accumulate HOST-side in row order:
                # np.add.at is unbuffered, so the state carries the same
                # left-to-right rounding sequence the row accumulator
                # produces (a device segment sum could re-associate)
                sums = np.zeros(G, np.float64)
                np.add.at(sums, gid[contrib], vals[contrib])
                ci = red("sum", None, contrib)
                builders.append(
                    lambda outs, ci=ci, sums=sums, name=name:
                    col.AggStateCol(name, outs[ci].astype(np.int64),
                                    values=sums, op="sum", kind="f64"))
                continue
            # min/max: -0.0 keeps first-seen-tie semantics on the row
            # path that a numeric combine cannot reproduce
            if bool(np.any((vals == 0.0) & np.signbit(vals) & contrib)):
                return None
            ci = red("sum", None, contrib)
            vi = red("min" if name == "min" else "max", vals, contrib)
            builders.append(
                lambda outs, ci=ci, vi=vi, name=name:
                col.AggStateCol(name, outs[ci].astype(np.int64),
                                values=outs[vi], op=name, kind="f64"))
            continue
        if cd.kind == col.K_STR:
            if name not in ("min", "max"):
                return None   # sum over strings: row handler casts
            # dictionary codes are sorted by bytes, so the code extremum
            # IS the bytes extremum; decode per group afterwards
            ci = red("sum", None, contrib)
            vi = red("min" if name == "min" else "max",
                     cd.values.astype(np.int64), contrib)
            dic = cd.dictionary
            builders.append(
                lambda outs, ci=ci, vi=vi, name=name, dic=dic:
                col.AggStateCol(
                    name, outs[ci].astype(np.int64),
                    datums=[NULL if int(n) == 0
                            else Datum.bytes_(dic[int(v)])
                            for n, v in zip(outs[ci], outs[vi])]))
            continue
        if not (cd.kind == col.K_DEC or _int_plane(cd, c)):
            return None       # time/duration/bit aggregates: row handler
        kind = "dec" if cd.kind == col.K_DEC else "i64"
        scale = cd.dec_scale
        vals = cd.values
        if name in ("sum", "avg"):
            n_contrib = int(np.count_nonzero(contrib))
            mx = cd.max_abs
            if mx and n_contrib and mx * n_contrib >= (1 << 63):
                return None   # could wrap: the Decimal row path answers
            ci = red("sum", None, contrib)
            vi = red("sum", vals, contrib)
        else:
            ci = red("sum", None, contrib)
            vi = red("min" if name == "min" else "max", vals, contrib)
        op = "sum" if name in ("sum", "avg") else name
        builders.append(
            lambda outs, ci=ci, vi=vi, name=name, op=op, kind=kind,
            scale=scale, c=c:
            col.AggStateCol(name, outs[ci].astype(np.int64),
                            values=outs[vi], op=op, kind=kind,
                            dec_scale=scale, pb_col=c))

    pending = _PendingStates(batch, gid, reductions, G, builders,
                             len(live_idx), group_keys)
    metrics.counter("copr.agg_states.partials").inc()
    metrics.counter("copr.agg_states.rows").inc(len(live_idx))
    if has_arg_planes:
        metrics.counter("copr.arg_plane.rows").inc(len(live_idx))
    return group_keys, pending


class _PendingStates:
    """One region's DEFERRED grouped-states pass: everything
    `_agg_states_response` prepared host-side (group ids, device-safe
    reductions, state builders) minus the device dispatch itself — the
    unit the statement-level finisher batches. `resolve()` is the serial
    per-region path (device at/above STATES_DEVICE_FLOOR, host numpy
    below or on fault) — both the BATCH_STATES_ENABLED=False behavior
    and the bottom degradation rung of the batched dispatch."""

    __slots__ = ("batch", "gid", "reductions", "G", "builders", "n_live",
                 "group_keys")

    def __init__(self, batch, gid, reductions, G, builders, n_live,
                 group_keys):
        self.batch = batch
        self.gid = gid
        self.reductions = reductions
        self.G = G
        self.builders = builders
        self.n_live = n_live
        self.group_keys = group_keys

    def signature(self) -> tuple:
        """The statement's aggregate shape — regions sharing it share
        one ragged dispatch (kernels.region_agg_states_batched's cache
        key domain). Arg-plane reductions contribute their program's
        STRUCTURAL signature: same expression shape + column layout →
        same trace."""
        sig = []
        for op, v, _ok in self.reductions:
            if v is None:
                sig.append((op, "c"))
            elif getattr(v, "is_arg_plane", False):
                sig.append((op, "x") + v.prog.sig)
            else:
                sig.append((op, np.dtype(v.dtype).char))
        return tuple(sig)

    def has_arg_planes(self) -> bool:
        return _has_arg_planes(self.reductions)

    def lower_arg_planes(self) -> None:
        """Force the host exprc rung for every arg-plane program (the
        copr/arg_plane failpoint's seam; arity-preserving — see
        _lower_arg_planes)."""
        if self.has_arg_planes():
            self.reductions = _lower_arg_planes(self.gid, self.reductions,
                                                self.G)

    def device_reductions(self) -> list:
        """Reductions with value planes swapped for their PINNED device
        twins where the batch's planes are device-resident (plane-cache
        pinning): the batched dispatch then reads HBM directly — the
        host touches group offsets and masks, not row values. Arg-plane
        specs pass through: they resolve their own device planes at
        marshal time (ArgPlaneSpec.device_planes)."""
        planes = getattr(self.batch, "_device_planes", None)
        if planes is None:
            return self.reductions
        by_id = {id(cd.values): cid
                 for cid, cd in self.batch.columns.items()}
        out = []
        for op, vals, ok in self.reductions:
            if vals is not None and not getattr(vals, "is_arg_plane",
                                                False):
                cid = by_id.get(id(vals))
                if cid is not None and cid in planes:
                    vals = planes[cid][0]
            out.append((op, vals, ok))
        return out

    def finish(self, outs) -> list:
        """Per-spec state arrays → AggStateCols (+ the wire-bytes tally,
        which needs the materialized states)."""
        from tidb_tpu import metrics
        aggs = [build(outs) for build in self.builders]
        wire = sum(len(k) for k in self.group_keys)
        for st in aggs:
            wire += int(st.counts.nbytes)
            if st.values is not None:
                wire += int(st.values.nbytes)
            if st.datums is not None:
                wire += 16 * len(st.datums)   # flattened datum estimate
        metrics.counter("copr.agg_states.wire_bytes").inc(wire)
        return aggs

    def resolve(self) -> list:
        from tidb_tpu import tracing
        with tracing.trace("agg_states_pass") as ssp:
            outs = _run_states(self.batch, self.gid, self.reductions,
                               self.G)
            ssp.set("groups", self.G).set("rows", self.n_live)
        return self.finish(outs)


class _PendingFilter:
    """One region's DEFERRED filter+states pass: the compiled predicate
    plus everything _prepare_states needs once the survivor mask exists.
    The statement finisher evaluates every deferred region's predicate
    in ONE batched device dispatch (kernels.region_filter_batched, bit-
    packed masks back — rows never transit the host); `resolve()` is the
    serial rung: host exprc mask, then the serial states ladder — both
    what a consumer touching the payload early gets and the bottom of
    the batched filter's degradation ladder. Answers are bit-identical
    at every rung (the device kernel traces the SAME compiled closure
    the host rung evaluates eagerly)."""

    __slots__ = ("batch", "agg_specs", "colpb", "is_index", "compiled",
                 "fkey", "pins", "cids", "payload")

    is_filter = True    # ColumnarAggStates.filter_pending's marker

    def __init__(self, batch, agg_specs, colpb, is_index, compiled,
                 fkey, pins, cids):
        self.batch = batch
        self.agg_specs = agg_specs
        self.colpb = colpb
        self.is_index = is_index
        self.compiled = compiled
        self.fkey = fkey
        self.pins = pins
        self.cids = cids
        self.payload = None    # back-ref, set at payload construction

    def filter_seg(self) -> tuple:
        """This region's kernels.region_filter_batched segment — device-
        resident planes preferred (pinned plane-cache planes ride the
        dispatch without a fresh H2D)."""
        dev = getattr(self.batch, "_device_planes", None)
        planes = {}
        for cid in self.cids:
            cd = self.batch.columns[cid]
            if dev is not None and cid in dev:
                planes[cid] = dev[cid]
            else:
                planes[cid] = (cd.values, cd.valid)
        return (self.fkey, self.compiled, planes, self.batch.capacity,
                self.batch.n_rows, self.pins)

    def host_mask(self) -> np.ndarray:
        """The host exprc rung: the same compiled closure over the numpy
        planes — bit-identical to the device kernel's mask."""
        planes = {cid: (cd.values, cd.valid)
                  for cid, cd in self.batch.columns.items()}
        wv, wva = self.compiled(planes)
        wv, wva = np.asarray(wv), np.asarray(wva)
        truth = wv if wv.dtype == np.bool_ else (wv != 0)
        return self.batch.row_mask() & wva & truth

    def fulfill_mask(self, mask: np.ndarray) -> None:
        """Survivor mask → group keys + states reductions on the
        payload: it joins the statement's states batch, or resolves on
        the spot when no batched-shape work remains (G == 0, or the
        states channel is off)."""
        prepared = _prepare_states(self.batch, mask, self.agg_specs,
                                   self.colpb, self.is_index)
        # _states_probe proved every None exit unreachable under any
        # subset of the probed superset mask
        assert prepared is not None, "deferred filter lost its states"
        group_keys, pending = prepared
        p = self.payload
        p.group_keys = group_keys
        if BATCH_STATES_ENABLED and pending.reductions and pending.G > 0:
            p._pending = pending
        else:
            p.fulfill_states(pending.resolve())

    def resolve(self) -> list:
        from tidb_tpu import tracing
        with tracing.trace("filter_pass") as fsp:
            mask = self.host_mask()
            fsp.set("rows_out", int(np.count_nonzero(mask)))
        prepared = _prepare_states(self.batch, mask, self.agg_specs,
                                   self.colpb, self.is_index)
        assert prepared is not None, "deferred filter lost its states"
        group_keys, pending = prepared
        self.payload.group_keys = group_keys
        return pending.resolve()


def _finish_filter_batch(group) -> None:
    """Phase A of the statement finisher: every deferred-FILTER payload
    gets its survivor mask — ONE batched device dispatch over the
    device-resident planes at/above the statement floor
    (kernels.region_filter_batched), the host exprc rung below it or on
    any device fault (counted on copr.degraded_filter_batch; the
    copr/filter_batched failpoint degrades exactly there) — then each
    payload's group keys + states reductions build from its mask and the
    payload joins phase B's states batch."""
    from tidb_tpu import tracing
    pends = [p._pending for p in group]
    total_rows = sum(pe.batch.n_rows for pe in pends)
    use_device = total_rows >= STATES_DEVICE_FLOOR
    if use_device and failpoint._active and \
            failpoint.eval("copr/filter_batched") is not None:
        tracing.record_degraded("filter_batch")
        use_device = False
    masks = None
    if use_device:
        from tidb_tpu.ops import kernels
        try:
            masks = kernels.region_filter_batched(
                [pe.filter_seg() for pe in pends])
        except errors.DeviceError:
            tracing.record_degraded("filter_batch")
    for i, pe in enumerate(pends):
        pe.fulfill_mask(masks[i] if masks is not None
                        else pe.host_mask())


def finish_states_batch(payloads) -> None:
    """The statement-level finisher of the deferred states channel: the
    drain hands over every states payload of one statement; regions
    sharing an aggregate shape fulfill from ONE ragged segmented device
    dispatch (kernels.region_agg_states_batched) — routed shard-owned
    through the mesh (ops.mesh.region_states_sharded) when the mesh tier
    is up — instead of one dispatch per region. Per-statement floor: the
    statement's TOTAL packed rows decide device vs host, so many small
    regions that individually sit under STATES_DEVICE_FLOOR still
    amortize into one dispatch. Degradation ladder (answers unchanged at
    every rung): mesh → single-device batched (copr.degraded_near_data)
    → serial per-region (copr.degraded_states_batch) → host numpy.

    Phase A (PR 17): payloads whose FILTER deferred too get their
    survivor masks first — one batched filter dispatch feeding straight
    into phase B's states batch, so a fully-deferred statement costs
    ≤ 2 device round trips. Phase B also lifts below-floor groups into
    the cross-STATEMENT gather window (ops.sched.states_gather):
    concurrent small statements share one states dispatch instead of
    each resolving host-serial."""
    from tidb_tpu import tracing
    pend = [p for p in payloads
            if getattr(p, "states_pending", None) is not None
            and p.states_pending()]
    if not pend:
        return
    fgroup = [p for p in pend if isinstance(p._pending, _PendingFilter)]
    if fgroup:
        _finish_filter_batch(fgroup)
        pend = [p for p in pend if p.states_pending()]
        if not pend:
            return
    if failpoint._active and failpoint.eval("copr/arg_plane") is not None:
        # certified mid-ladder seam: force every arg-plane program down
        # to the per-region host exprc rung (copr.degraded_arg_plane) —
        # the now-plain reductions ride the normal states ladder, and
        # the differential suite pins the answers bit-identical
        lowered = False
        for p in pend:
            pe = p._pending
            if getattr(pe, "has_arg_planes", None) is not None \
                    and pe.has_arg_planes():
                pe.lower_arg_planes()
                lowered = True
        if lowered:
            tracing.record_degraded("arg_plane")
    groups: dict = {}
    for p in pend:
        groups.setdefault(p._pending.signature(), []).append(p)
    for sig, group in groups.items():
        pends = [p._pending for p in group]
        total_rows = sum(pe.batch.n_rows for pe in pends)
        try:
            import jax  # noqa: F401
            jax_ok = True
        except ImportError:
            jax_ok = False
        use_device = jax_ok and total_rows >= STATES_DEVICE_FLOOR
        if use_device:
            from tidb_tpu.ops import extsort, kernels
            from tidb_tpu.ops import mesh as mesh_mod
            # spilling trumps shard placement: a states table over the
            # HBM headroom takes the radix-partitioned passes no matter
            # where the shards live (the estimate reads lengths only,
            # so arg-plane specs are fine here)
            spill = extsort.states_over_headroom(
                [(pe.gid, pe.reductions, pe.G) for pe in pends])
            mesh = None if spill else mesh_mod.get_mesh()
            if mesh is not None and any(pe.has_arg_planes()
                                        for pe in pends):
                # the shard-owned mesh kernel reads raw (op, vals, ok)
                # specs; arg-plane statements take the single-device
                # fused dispatch below instead of half-lowering here
                mesh = None
            if mesh is not None:
                try:
                    outs = mesh_mod.region_states_sharded(
                        mesh,
                        [(pe.gid, pe.reductions, pe.G) for pe in pends],
                        region_ids=[p.region_id for p in group],
                        epochs=[p.region_epoch for p in group])
                    for p, pe, o in zip(group, pends, outs):
                        p.fulfill_states(pe.finish(o))
                    continue
                except errors.DeviceError:
                    tracing.record_degraded("near_data")
            try:
                if spill:
                    # states table over headroom: lower any arg-plane
                    # programs to the host exprc rung (row-aligned
                    # planes cannot partition by group), then
                    # radix-partition the group codes and run the SAME
                    # batched kernel per partition in passes
                    # (ops.extsort), each charged against
                    # device.hbm.reserved, answers unchanged
                    for pe in pends:
                        pe.lower_arg_planes()
                    try:
                        outs = extsort.region_states_spill(
                            [(pe.gid, pe.reductions, pe.G)
                             for pe in pends])
                    except errors.DeviceError:
                        tracing.record_degraded("spill_groupby")
                        raise
                else:
                    outs = kernels.region_agg_states_batched(
                        [(pe.gid, pe.device_reductions(), pe.G)
                         for pe in pends])
                for p, pe, o in zip(group, pends, outs):
                    p.fulfill_states(pe.finish(o))
                continue
            except errors.DeviceError:
                tracing.record_degraded("states_batch")
        elif jax_ok:
            # below the per-statement floor: offer the segments to the
            # cross-STATEMENT gather window (PR 16 residual c) — when
            # concurrent statements' segments combine past the floor,
            # one shared batched dispatch fulfills all of them; solo
            # traffic falls straight through to the serial path
            from tidb_tpu.ops import sched
            try:
                outs = sched.states_gather.submit(
                    sig,
                    [(pe.gid, pe.device_reductions(), pe.G)
                     for pe in pends],
                    total_rows, STATES_DEVICE_FLOOR)
            except errors.DeviceError:
                tracing.record_degraded("states_batch")
                outs = None
            if outs is not None:
                for p, pe, o in zip(group, pends, outs):
                    p.fulfill_states(pe.finish(o))
                continue
        for p in group:
            if p.states_pending():
                p.aggs   # serial resolution (device→host ladder inside)
