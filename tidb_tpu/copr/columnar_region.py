"""Per-region COLUMNAR coprocessor results: the region-side half of the
columnar channel across the cluster store's fan-out.

A scan request carrying columnar_hint used to be answered columnar only
by the in-proc TpuClient (one response for the whole scan). Here each
REGION answers the hint itself: its share of the key ranges packs into a
ColumnBatch (the same native-C row→plane decode the TPU tier uses), the
pushed filter evaluates vectorized over the planes (ops.exprc — the same
lowering the device kernels trace), and the response ships the planes +
selection index as a ColumnarScanResult PARTIAL. The client stacks the
per-region partials (ops.columnar.ColumnarPartialSet) so a multi-region
scan→join→agg stays columnar end to end, and the SQL-side fused
aggregate merges per-region partial states with the mesh combine algebra
(executor.fused_agg). Reference: the per-region coprocessor tasks of
store/tikv/coprocessor.go:305 — with planes instead of chunk rows.

Anything this engine cannot express EXACTLY returns None and the row
handler (copr.region_handler) answers that region instead — including
TypeError_ packs (unsigned bigint above the int64 plane, out-of-scale
decimals): per-region fallback, counted per PARTIAL by the client.
"""

from __future__ import annotations

import threading

import numpy as np

from tidb_tpu import errors, failpoint
from tidb_tpu.copr.proto import ExprType, SelectRequest, SelectResponse
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.ops import columnar as col


def handle_columnar_scan(snapshot, sel: SelectRequest,
                         ranges: list[KeyRange], region=None,
                         cache=None) -> SelectResponse | None:
    """One region's share of a columnar_hint scan as a columnar partial,
    or None → the caller runs the row handler for this region.

    With `region` ((region_id, epoch), as validated by the RPC epoch
    check) and a `cache` (copr.plane_cache.PlaneCache), the post-pack
    pre-filter planes for the clipped ranges are served from / admitted
    to the per-region plane cache keyed by (region_id, epoch,
    data_version_at(start_ts), table_id, column set, range bounds) — a
    repeat fan-out query skips the native repack (and, with pinned
    planes, the host→device transfer). The filter/TopN selection still
    evaluates per request; only the snapshot-determined pack is shared."""
    if sel.table_info is None or sel.is_agg():
        # index scans and pushed aggregates keep the row/partial-row
        # protocol (columnar index results are a ROADMAP open item)
        return None
    if sel.order_by and (sel.desc or sel.limit is None):
        return None
    from tidb_tpu import tracing
    if failpoint._active and \
            failpoint.eval("copr/drop_columnar") is not None:
        # corrupt-partial seam, made SAFE by construction: instead of
        # shipping damaged planes, the injected fault drops this region's
        # columnar partial entirely — the row handler answers (the last
        # tier of the degradation chain), so parity is preserved and the
        # client counts a fallback for exactly this partial
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    columns = sel.table_info.columns
    defaults = {c.column_id: c.default_val for c in columns
                if c.default_val is not None}
    batch = None
    cache_info = None
    base_key = version = None
    mvcc = getattr(snapshot, "mvcc", None)
    if cache is not None and cache.enabled and region is not None \
            and mvcc is not None \
            and not any(mvcc.has_blocking_lock(snapshot.read_ts,
                                               rg.start, rg.end)
                        for rg in ranges):
        # Percolator lock gate: a pending lock with start_ts <= read_ts
        # can resolve to a commit whose commit_ts was allocated BEFORE
        # read_ts — the scan path blocks on it, resolves, and includes
        # the write; a cached hit would silently skip that lock check
        # and serve a snapshot missing it (two reads at the same
        # read_ts could then disagree). Any blocking lock in range
        # forces the pack path, whose scan raises KeyIsLockedError into
        # the client's resolver ladder exactly like the row handler.
        version = mvcc.data_version_at(snapshot.read_ts)
        base_key = (region[0], sel.table_info.table_id,
                    tuple(c.column_id for c in columns),
                    tuple((r.start, r.end) for r in ranges))
        batch, cache_info = cache.lookup(base_key, region[1], version)
        # cache_hit / cache_miss land on the region_task span the fan-out
        # worker attached (NOOP when untraced)
        tracing.current().inc("cache_hit" if batch is not None
                              else "cache_miss")
    try:
        if batch is None:
            with tracing.trace("pack") as psp:
                if failpoint._active:
                    # pack-tier fault: the typed TypeError_ takes the
                    # same no-exact-plane-mapping exit a real unsigned
                    # overflow does — this region degrades to rows
                    failpoint.eval("copr/pack", lambda: errors.TypeError_(
                        "injected region pack fault"))
                batch = col.pack_ranges(snapshot, sel.table_info.table_id,
                                        columns, ranges, defaults)
                psp.set("rows", batch.n_rows)
            if base_key is not None:
                # sound only if the visible version held still across the
                # pack (lock resolution can land commits below start_ts
                # mid-scan — same stabilization rule as TpuClient's
                # batch cache); a churned version serves uncached
                if mvcc.data_version_at(snapshot.read_ts) == version:
                    cache.insert(base_key, region[1], version, batch,
                                 cache_info)
        with tracing.trace("filter") as fsp:
            if failpoint._active:
                failpoint.eval("copr/filter", lambda: errors.TypeError_(
                    "injected region filter fault"))
            mask = _filter_mask(sel, batch)
            if mask is not None:
                fsp.set("rows_out", int(np.count_nonzero(mask)))
    except errors.TypeError_:
        # no exact plane mapping (or an injected pack/filter fault): this
        # region degrades to the row protocol — the bottom tier of the
        # degradation chain, counted so every fallback is accounted
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    except errors.RetryableError:
        raise   # pending lock mid-pack: the client ladder resolves it
    except errors.TiDBError:
        tracing.record_degraded("region_to_rows", tally=False)
        return None
    if mask is None:
        return None
    if sel.order_by:
        with tracing.trace("topn") as tsp:
            idx = _topn_select(sel, batch, mask)
            if idx is not None:
                tsp.set("rows_out", len(idx))
        if idx is None:
            return None
    else:
        idx = np.nonzero(mask)[0]
        if sel.desc:
            idx = idx[::-1]
        if sel.limit is not None:
            idx = idx[: sel.limit]
    res = col.ColumnarScanResult(batch, np.asarray(idx, dtype=np.int64),
                                 list(columns))
    # per-response attribution: the client rolls these into the
    # statement thread's monotonic tallies (slow-log / perfschema)
    res.cache_info = cache_info
    if region is not None:
        # origin (region id, epoch): the mesh tier's region→shard
        # placement key (ops.mesh.RegionPlacement) — epoch bumps
        # (split/merge) re-place the region
        res.region_id = region[0]
        res.region_epoch = region[1]
    return SelectResponse(columnar=res)


# cross-statement cache of compiled region filters (PR 5 residual):
# keyed by the EXPRESSION SHAPE + per-column lowering signature, never by
# the statement — the same WHERE clause re-issued by a later statement
# (dashboards, prepared re-execution, repeat fan-outs) skips the exprc
# re-lower on every region. jit_hits/jit_misses count across statements
# through tracing.record_jit_cache (ops.jit_cache_* metrics).
_filter_cache: dict = {}
_filter_lock = threading.Lock()


def _where_cids(e, out: set) -> None:
    if e.tp == ExprType.COLUMN_REF:
        out.add(e.val)
    for c in e.children or ():
        _where_cids(c, out)


def _compiled_filter(sel: SelectRequest, batch: col.ColumnBatch):
    """Compile (or reuse) the pushed where-filter for this batch.

    Reuse is sound only when every lowering input matches: the Expr tree
    itself (repr — constants are baked into the closures), and each
    referenced column's (kind, MySQL type, fixed-point scale, max-abs
    overflow bound, dictionary identity). Dictionaries pin in the cache
    entry so their ids cannot be recycled while the entry lives — a
    plane-cache hit serves the SAME batch object, so repeat statements
    over cached regions reuse string-filter lowerings too; numeric-only
    filters reuse across fresh packs as long as the guard bounds agree."""
    from tidb_tpu import tracing
    from tidb_tpu.ops.exprc import compile_expr
    cids: set = set()
    _where_cids(sel.where, cids)
    sig = []
    dicts = []
    for cid in sorted(cids):
        cd = batch.columns.get(cid)
        if cd is None:
            sig.append((cid, None))
            continue
        dict_key = None
        if cd.dictionary is not None:
            dict_key = id(cd.dictionary)
            dicts.append(cd.dictionary)
        sig.append((cid, cd.kind, cd.tp, cd.dec_scale, cd.max_abs,
                    dict_key))
    key = (repr(sel.where), tuple(sig))
    # fan-out worker threads share this cache: lookup/insert/evict under
    # the lock (a concurrent duplicate compile is harmless; a dict
    # mutated mid-eviction-iteration is not)
    with _filter_lock:
        ent = _filter_cache.get(key)
    tracing.record_jit_cache(hit=ent is not None)
    if ent is None:
        compiled = compile_expr(sel.where, batch)
        ent = (compiled, dicts)
        with _filter_lock:
            _filter_cache[key] = ent
            while len(_filter_cache) > 512:
                _filter_cache.pop(next(iter(_filter_cache)))
    return ent[0]


def _filter_mask(sel: SelectRequest, batch: col.ColumnBatch):
    """Live-row mask with the pushed where-filter applied vectorized, or
    None when the filter does not lower (row handler answers)."""
    mask = batch.row_mask()
    if sel.where is None:
        return mask
    try:
        from tidb_tpu.ops.exprc import Unsupported
    except ImportError:      # jax-free deployment: rows answer
        return None
    try:
        compiled = _compiled_filter(sel, batch)
    except (Unsupported, errors.TypeError_):
        return None
    planes = {cid: (cd.values, cd.valid)
              for cid, cd in batch.columns.items()}
    wv, wva = compiled(planes)
    wv, wva = np.asarray(wv), np.asarray(wva)
    truth = wv if wv.dtype == np.bool_ else (wv != 0)
    return mask & wva & truth


def _topn_select(sel: SelectRequest, batch: col.ColumnBatch,
                 mask: np.ndarray):
    """Per-region top-`limit` row indices for a pushed TopN, sorted by
    the by-items with scan-position tiebreak — the same bounded candidate
    set (and the same tie semantics) the row handler's heap keeps, so the
    SQL-side merge sees identical partials. None → row handler."""
    sort_keys = []       # least-significant first (np.lexsort order)
    for item in reversed(sel.order_by):
        e = item.expr
        if e.tp != ExprType.COLUMN_REF:
            return None
        cd = batch.columns.get(e.val)
        if cd is None:
            return None
        vals, va = cd.values, cd.valid
        if cd.kind == col.K_F64:
            vo = np.where(vals == 0.0, 0.0, vals)   # -0.0 ties +0.0
            if item.desc:
                vo = -vo
        else:
            # int64 planes (ints, times, durations, dict codes, scaled
            # decimals) order directly; desc via bitwise-not (exact at
            # I64_MIN, where unary minus would wrap)
            vo = ~vals if item.desc else vals
        # NULL ordering: asc → NULLs first, desc → NULLs last (MySQL)
        nullk = va.astype(np.int8) if not item.desc \
            else (~va).astype(np.int8)
        sort_keys.append(np.where(va, vo, np.zeros_like(vo)))
        sort_keys.append(nullk)
    sort_keys.append(~mask)   # dead rows last; stable sort keeps
    #                           scan-position order among ties
    order = np.lexsort(sort_keys)
    n_live = int(np.count_nonzero(mask))
    return order[: min(sel.limit, n_live)]
