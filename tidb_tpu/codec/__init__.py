"""Order-preserving binary codec — the storage wire format.

Reference: util/codec/ (number.go, bytes.go, float.go, decimal.go, codec.go).
Invariant: for datums a, b of comparable kinds,
    compare_datum(a, b) == cmp(encode_key([a]), encode_key([b]))
(memcmp order). Key encoding is order-preserving; value encoding uses the
compact variants (smaller, not order-preserving).
"""

from tidb_tpu.codec.codec import (  # noqa: F401
    encode_key,
    encode_value,
    decode_one,
    decode_all,
    encode_datum,
    NIL_FLAG,
    BYTES_FLAG,
    COMPACT_BYTES_FLAG,
    INT_FLAG,
    UINT_FLAG,
    FLOAT_FLAG,
    DECIMAL_FLAG,
    DURATION_FLAG,
    TIME_FLAG,
    MAX_FLAG,
)
from tidb_tpu.codec.number import (  # noqa: F401
    encode_int_to_cmp_uint,
    decode_cmp_uint_to_int,
    encode_u64,
    decode_u64,
    encode_varint,
    decode_varint,
    encode_uvarint,
    decode_uvarint,
)
from tidb_tpu.codec.bytes_codec import (  # noqa: F401
    encode_bytes,
    decode_bytes,
    encode_bytes_desc,
)
