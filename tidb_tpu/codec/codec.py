"""Flagged compound datum encoding.

Reference: util/codec/codec.go:119-156 (EncodeKey/EncodeValue/DecodeOne) and
util/codec/decimal.go. Each datum = 1 flag byte + payload. Key encoding is
memcomparable; value encoding uses compact (varint) forms. Flag values follow
the reference's ordering so NULL < MinNotNull < typed values < MaxValue holds
under memcmp.

Decimal layout (order-preserving, this project's own design — the reference's
digit-pair packing is not required for parity since both sides here share this
codec): sign byte (0=neg, 1=zero, 2=pos); for nonzero: 8-byte comparable
exponent then digits+1 bytes terminated by 0x00, all bitwise-flipped when
negative (terminator 0xFF).
"""

from __future__ import annotations

import struct
from decimal import Decimal

from tidb_tpu.types.datum import Datum, Kind, NULL, MIN_NOT_NULL, MAX_VALUE
from tidb_tpu.types.time_types import Duration, Time
from tidb_tpu.codec import number as num
from tidb_tpu.codec import bytes_codec as bc
from tidb_tpu.native import codecx as _cx

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
COMPACT_BYTES_FLAG = 0x02
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05
DECIMAL_FLAG = 0x06
DURATION_FLAG = 0x07
TIME_FLAG = 0x08
VARINT_FLAG = 0x09
UVARINT_FLAG = 0x0A
MAX_FLAG = 0xFA


def encode_datum(buf: bytearray, d: Datum, comparable: bool) -> None:
    k = d.kind
    if k == Kind.NULL:
        buf.append(NIL_FLAG)
    elif k == Kind.MIN_NOT_NULL:
        buf.append(BYTES_FLAG)
    elif k == Kind.MAX_VALUE:
        buf.append(MAX_FLAG)
    elif k == Kind.INT64:
        if comparable:
            buf.append(INT_FLAG)
            num.encode_u64(buf, num.encode_int_to_cmp_uint(d.val))
        else:
            buf.append(VARINT_FLAG)
            num.encode_varint(buf, d.val)
    elif k == Kind.UINT64:
        if comparable:
            buf.append(UINT_FLAG)
            num.encode_u64(buf, d.val)
        else:
            buf.append(UVARINT_FLAG)
            num.encode_uvarint(buf, d.val)
    elif k == Kind.FLOAT64:
        buf.append(FLOAT_FLAG)
        num.encode_u64(buf, num.encode_float_to_cmp_u64(d.val))
    elif k in (Kind.STRING, Kind.BYTES):
        data = d.get_bytes()
        if comparable:
            buf.append(BYTES_FLAG)
            bc.encode_bytes(buf, data)
        else:
            buf.append(COMPACT_BYTES_FLAG)
            bc.encode_compact_bytes(buf, data)
    elif k == Kind.DECIMAL:
        buf.append(DECIMAL_FLAG)
        _encode_decimal(buf, d.val)
    elif k == Kind.DURATION:
        buf.append(DURATION_FLAG)
        num.encode_u64(buf, num.encode_int_to_cmp_uint(d.val.nanos))
    elif k == Kind.TIME:
        buf.append(TIME_FLAG)
        num.encode_u64(buf, d.val.to_packed_int())
    elif k in (Kind.ENUM, Kind.SET, Kind.BIT):
        # flatten to the uint value (types.Flatten); the column FieldType
        # restores the rich object on read (convert.unflatten_datum)
        encode_datum(buf, Datum(Kind.UINT64, d.val.value), comparable)
    elif k == Kind.HEX:
        encode_datum(buf, Datum(Kind.INT64, d.val.value), comparable)
    else:
        raise ValueError(f"cannot encode datum kind {k!r}")


def encode_key(datums, buf: bytearray | None = None) -> bytes:
    if buf is None and _cx is not None:
        if not isinstance(datums, (list, tuple)):
            datums = list(datums)  # keep the fallback's input intact
        try:
            return _cx.encode_datums(datums, True)
        except _cx.Unsupported:
            pass
    buf = bytearray() if buf is None else buf
    for d in datums:
        encode_datum(buf, d, comparable=True)
    return bytes(buf)


def encode_value(datums, buf: bytearray | None = None) -> bytes:
    if buf is None and _cx is not None:
        if not isinstance(datums, (list, tuple)):
            datums = list(datums)  # keep the fallback's input intact
        try:
            return _cx.encode_datums(datums, False)
        except _cx.Unsupported:
            pass
    buf = bytearray() if buf is None else buf
    for d in datums:
        encode_datum(buf, d, comparable=False)
    return bytes(buf)


def decode_one(data: memoryview, pos: int = 0) -> tuple[Datum, int]:
    try:
        return _decode_one(data, pos)
    except (IndexError, struct.error) as e:
        raise ValueError(f"truncated or malformed encoded datum at {pos}: {e}") from e


def _decode_one(data: memoryview, pos: int) -> tuple[Datum, int]:
    flag = data[pos]
    pos += 1
    if flag == NIL_FLAG:
        return NULL, pos
    if flag == MAX_FLAG:
        return MAX_VALUE, pos
    if flag == INT_FLAG:
        u, pos = num.decode_u64(data, pos)
        return Datum.i64(num.decode_cmp_uint_to_int(u)), pos
    if flag == VARINT_FLAG:
        v, pos = num.decode_varint(data, pos)
        return Datum.i64(v), pos
    if flag == UINT_FLAG:
        u, pos = num.decode_u64(data, pos)
        return Datum.u64(u), pos
    if flag == UVARINT_FLAG:
        u, pos = num.decode_uvarint(data, pos)
        return Datum.u64(u), pos
    if flag == FLOAT_FLAG:
        u, pos = num.decode_u64(data, pos)
        return Datum.f64(num.decode_cmp_u64_to_float(u)), pos
    if flag == BYTES_FLAG:
        # MIN_NOT_NULL is a bare flag only at range boundaries; here, a
        # following group must exist for real values. Distinguish by length.
        if pos >= len(data):
            return MIN_NOT_NULL, pos
        b, pos = bc.decode_bytes(data, pos)
        return Datum.bytes_(b), pos
    if flag == COMPACT_BYTES_FLAG:
        b, pos = bc.decode_compact_bytes(data, pos)
        return Datum.bytes_(b), pos
    if flag == DECIMAL_FLAG:
        dec, pos = _decode_decimal(data, pos)
        return Datum.dec(dec), pos
    if flag == DURATION_FLAG:
        u, pos = num.decode_u64(data, pos)
        return Datum(Kind.DURATION, Duration(num.decode_cmp_uint_to_int(u))), pos
    if flag == TIME_FLAG:
        u, pos = num.decode_u64(data, pos)
        return Datum(Kind.TIME, Time.from_packed_int(u)), pos
    raise ValueError(f"invalid encoded datum flag {flag}")


def decode_all(data: bytes) -> list[Datum]:
    mv = memoryview(data)
    pos = 0
    out = []
    while pos < len(mv):
        d, pos = decode_one(mv, pos)
        out.append(d)
    return out


# ---- decimal ----

def _encode_decimal(buf: bytearray, dec: Decimal) -> None:
    # NB: not Decimal.normalize() — that rounds to context precision (28
    # significant digits by default) and would silently corrupt long decimals.
    sign, digits, exponent = dec.as_tuple()
    # strip trailing zeros so equal values share one canonical encoding
    dl = list(digits)
    while len(dl) > 1 and dl[-1] == 0:
        dl.pop()
        exponent += 1
    if dl == [0]:
        buf.append(0x01)
        return
    exp = exponent + len(dl)  # value = 0.d1..dn * 10^exp
    if sign == 0:
        buf.append(0x02)
        num.encode_u64(buf, num.encode_int_to_cmp_uint(exp))
        buf += bytes(d + 1 for d in dl)
        buf.append(0x00)
    else:
        buf.append(0x00)
        start = len(buf)
        num.encode_u64(buf, num.encode_int_to_cmp_uint(exp))
        buf += bytes(d + 1 for d in dl)
        buf.append(0x00)
        for i in range(start, len(buf)):
            buf[i] ^= 0xFF


def _decode_decimal(data: memoryview, pos: int) -> tuple[Decimal, int]:
    sign_byte = data[pos]
    pos += 1
    if sign_byte == 0x01:
        return Decimal(0), pos
    neg = sign_byte == 0x00
    term = 0xFF if neg else 0x00
    end = pos + 8  # skip the fixed-width exponent, which may contain term bytes
    while data[end] != term:
        end += 1
    if neg:
        raw = bytes(b ^ 0xFF for b in data[pos : end + 1])
    else:
        raw = bytes(data[pos : end + 1])
    u = int.from_bytes(raw[:8], "big")
    exp = num.decode_cmp_uint_to_int(u)
    digit_bytes = raw[8:-1]
    digits = tuple(b - 1 for b in digit_bytes)
    # construct from the tuple directly: Decimal arithmetic (scaleb, unary -)
    # would round to context precision and corrupt long mantissas
    val = Decimal((1 if neg else 0, digits, exp - len(digits)))
    return val, end + 1
