"""Integer/float order-preserving encodings.

Reference: util/codec/number.go:34-148 (EncodeInt/EncodeUint/EncodeFloat and
comparable transforms), util/codec/float.go. int64 maps to uint64 by flipping
the sign bit so memcmp order equals numeric order; floats use the IEEE trick
(non-negative: set sign bit; negative: flip all bits).
"""

from __future__ import annotations

import struct

SIGN_MASK = 0x8000000000000000
U64_MASK = 0xFFFFFFFFFFFFFFFF

_u64 = struct.Struct(">Q")
_f64 = struct.Struct(">d")


def encode_int_to_cmp_uint(v: int) -> int:
    return (v & U64_MASK) ^ SIGN_MASK


def decode_cmp_uint_to_int(u: int) -> int:
    u ^= SIGN_MASK
    if u & SIGN_MASK:
        return u - (1 << 64)
    return u


def encode_u64(buf: bytearray, v: int) -> None:
    buf += _u64.pack(v & U64_MASK)


def decode_u64(data: memoryview, pos: int) -> tuple[int, int]:
    return _u64.unpack_from(data, pos)[0], pos + 8


def encode_u64_desc(buf: bytearray, v: int) -> None:
    buf += _u64.pack((v & U64_MASK) ^ U64_MASK)


def encode_float_to_cmp_u64(f: float) -> int:
    if f == 0.0:
        f = 0.0  # normalize -0.0 so equal floats share one encoding
    (u,) = _u64.unpack(_f64.pack(f))
    if u & SIGN_MASK:
        u = (~u) & U64_MASK
    else:
        u |= SIGN_MASK
    return u


def decode_cmp_u64_to_float(u: int) -> float:
    if u & SIGN_MASK:
        u &= ~SIGN_MASK & U64_MASK
    else:
        u = (~u) & U64_MASK
    return _f64.unpack(_u64.pack(u))[0]


# ---- varints (value encoding; protobuf zig-zag style, number.go EncodeVarint) ----

def encode_uvarint(buf: bytearray, v: int) -> None:
    v &= U64_MASK
    while v >= 0x80:
        buf.append((v & 0x7F) | 0x80)
        v >>= 7
    buf.append(v)


def decode_uvarint(data: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & U64_MASK, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint too long")


def encode_varint(buf: bytearray, v: int) -> None:
    # zig-zag
    encode_uvarint(buf, ((v << 1) ^ (v >> 63)) & U64_MASK)


def decode_varint(data: memoryview, pos: int) -> tuple[int, int]:
    u, pos = decode_uvarint(data, pos)
    v = (u >> 1) ^ (-(u & 1) & U64_MASK)
    if v & SIGN_MASK:
        v -= 1 << 64
    return v, pos
