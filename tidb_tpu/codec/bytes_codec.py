"""Memcomparable bytes encoding.

Reference: util/codec/bytes.go:46,119 (EncodeBytes/DecodeBytes). Layout:
the input is split into 8-byte groups; each group is padded with 0x00 to 8
bytes and followed by a marker byte = 0xFF - pad_count, so that shorter
prefixes sort before longer strings while preserving memcmp order.
"""

from __future__ import annotations

ENC_GROUP_SIZE = 8
ENC_MARKER = 0xFF
ENC_PAD = 0x00


def encode_bytes(buf: bytearray, data: bytes) -> None:
    n = len(data)
    for i in range(0, n + 1, ENC_GROUP_SIZE):
        group = data[i : i + ENC_GROUP_SIZE]
        pad = ENC_GROUP_SIZE - len(group)
        buf += group
        if pad:
            buf += bytes(pad)
            buf.append(ENC_MARKER - pad)
            return
        buf.append(ENC_MARKER)
    # n % 8 == 0 handled by the loop's final empty group (i == n)


def decode_bytes(data: memoryview, pos: int) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        group = data[pos : pos + ENC_GROUP_SIZE + 1]
        if len(group) < ENC_GROUP_SIZE + 1:
            raise ValueError("insufficient bytes to decode")
        marker = group[ENC_GROUP_SIZE]
        pos += ENC_GROUP_SIZE + 1
        if marker == ENC_MARKER:
            out += group[:ENC_GROUP_SIZE]
            continue
        pad = ENC_MARKER - marker
        if pad > ENC_GROUP_SIZE:
            raise ValueError(f"invalid bytes marker {marker}")
        real = ENC_GROUP_SIZE - pad
        out += group[:real]
        for b in group[real:ENC_GROUP_SIZE]:
            if b != ENC_PAD:
                raise ValueError("invalid padding byte")
        return bytes(out), pos


def encode_bytes_desc(buf: bytearray, data: bytes) -> None:
    """Descending variant (bitwise-flipped) for DESC index columns.

    The matching decoder will land with descending index support; until then
    only the encoder exists so key-layout decisions stay order-complete.
    """
    start = len(buf)
    encode_bytes(buf, data)
    for i in range(start, len(buf)):
        buf[i] ^= 0xFF


# ---- compact (value) encoding: varint length + raw bytes ----

from tidb_tpu.codec.number import encode_varint, decode_varint  # noqa: E402


def encode_compact_bytes(buf: bytearray, data: bytes) -> None:
    encode_varint(buf, len(data))
    buf += data


def decode_compact_bytes(data: memoryview, pos: int) -> tuple[bytes, int]:
    n, pos = decode_varint(data, pos)
    if n < 0 or pos + n > len(data):
        raise ValueError("insufficient bytes for compact decode")
    return bytes(data[pos : pos + n]), pos + n
