"""Redis-like string/hash/list structures encoded onto KV pairs.

Reference: structure/ (structure.go TxStructure, hash.go, list.go). The meta
layer stores schema metadata, ID counters, and DDL job queues through these
primitives so everything rides ordinary transactions.

Key layout (mirrors structure/structure.go encoding):
  string: prefix + enc_bytes(key) + enc_uint(TYPE_STRING)
  hash:   prefix + enc_bytes(key) + enc_uint(TYPE_HASH) + enc_bytes(field)
  list:   prefix + enc_bytes(key) + enc_uint(TYPE_LIST) + enc_uint(index)
Hash/list metadata (counts, bounds) live at the bare type key.
"""

from __future__ import annotations

import json
from typing import Iterator

from tidb_tpu import errors
from tidb_tpu.codec import bytes_codec as bc
from tidb_tpu.codec import number as num
from tidb_tpu.utils import prefix_next

TYPE_STRING = 1
TYPE_HASH = 2
TYPE_LIST = 3

_LIST_META_INIT = {"left": 0, "right": 0}  # elements live at [left, right)


class TxStructure:
    def __init__(self, retriever, mutator, prefix: bytes = b"m"):
        self._r = retriever
        self._w = mutator
        self.prefix = prefix

    # ---- key encoding ----
    def _type_key(self, key: bytes, tp: int) -> bytes:
        buf = bytearray(self.prefix)
        bc.encode_bytes(buf, key)
        num.encode_u64(buf, tp)
        return bytes(buf)

    def _hash_data_key(self, key: bytes, field: bytes) -> bytes:
        buf = bytearray(self._type_key(key, TYPE_HASH))
        bc.encode_bytes(buf, field)
        return bytes(buf)

    def _list_item_key(self, key: bytes, index: int) -> bytes:
        buf = bytearray(self._type_key(key, TYPE_LIST))
        num.encode_u64(buf, num.encode_int_to_cmp_uint(index))
        return bytes(buf)

    # ---- strings ----
    def set(self, key: bytes, value: bytes) -> None:
        self._w.set(self._type_key(key, TYPE_STRING), value)

    def get(self, key: bytes) -> bytes | None:
        return self._r.get_or_none(self._type_key(key, TYPE_STRING))

    def inc(self, key: bytes, step: int = 1) -> int:
        k = self._type_key(key, TYPE_STRING)
        cur = self._r.get_or_none(k)
        val = (int(cur) if cur else 0) + step
        if step != 0:  # step=0 is a pure read: don't turn it into a write
            self._w.set(k, str(val).encode())
        return val

    def clear(self, key: bytes) -> None:
        self._w.delete(self._type_key(key, TYPE_STRING))

    # ---- hashes ----
    def hset(self, key: bytes, field: bytes, value: bytes) -> None:
        self._w.set(self._hash_data_key(key, field), value)

    def hget(self, key: bytes, field: bytes) -> bytes | None:
        return self._r.get_or_none(self._hash_data_key(key, field))

    def hdel(self, key: bytes, field: bytes) -> None:
        self._w.delete(self._hash_data_key(key, field))

    def hgetall(self, key: bytes) -> Iterator[tuple[bytes, bytes]]:
        prefix = self._type_key(key, TYPE_HASH)
        end = prefix_next(prefix)
        for k, v in self._r.iterate(prefix, end):
            field, _ = bc.decode_bytes(memoryview(k), len(prefix))
            yield field, v

    def hkeys(self, key: bytes) -> list[bytes]:
        return [f for f, _ in self.hgetall(key)]

    # ---- lists (DDL job queues: ddl/ddl_worker.go fifo) ----
    def _list_meta(self, key: bytes) -> dict:
        raw = self._r.get_or_none(self._type_key(key, TYPE_LIST))
        return json.loads(raw) if raw else dict(_LIST_META_INIT)

    def _save_list_meta(self, key: bytes, meta: dict) -> None:
        mk = self._type_key(key, TYPE_LIST)
        if meta["left"] == meta["right"]:
            self._w.delete(mk)
        else:
            self._w.set(mk, json.dumps(meta).encode())

    def rpush(self, key: bytes, value: bytes) -> None:
        meta = self._list_meta(key)
        self._w.set(self._list_item_key(key, meta["right"]), value)
        meta["right"] += 1
        self._save_list_meta(key, meta)

    def lpop(self, key: bytes) -> bytes | None:
        meta = self._list_meta(key)
        if meta["left"] == meta["right"]:
            return None
        k = self._list_item_key(key, meta["left"])
        v = self._r.get_or_none(k)
        self._w.delete(k)
        meta["left"] += 1
        self._save_list_meta(key, meta)
        return v

    def lindex(self, key: bytes, index: int) -> bytes | None:
        meta = self._list_meta(key)
        if not (0 <= index < meta["right"] - meta["left"]):
            return None
        return self._r.get_or_none(self._list_item_key(key, meta["left"] + index))

    def lset(self, key: bytes, index: int, value: bytes) -> None:
        meta = self._list_meta(key)
        if not (0 <= index < meta["right"] - meta["left"]):
            raise errors.KVError("list index out of range")
        self._w.set(self._list_item_key(key, meta["left"] + index), value)

    def llen(self, key: bytes) -> int:
        meta = self._list_meta(key)
        return meta["right"] - meta["left"]
