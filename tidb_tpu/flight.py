"""Slow-statement flight recorder: always-on-but-cheap statement traces,
retained only when a statement turns out to matter.

Reference: TiDB's slow-query memory buffer (infoschema SLOW_QUERY reads
the slow log back) plus the "continuous profiling" idea from its
diagnostics lineage — you want the FULL hierarchical trace of the
statement that was slow five minutes ago, not the ability to re-run it
with tracing enabled (the re-run hits a warm cache and tells you
nothing). So:

* Every top-level statement builds its span tree unconditionally (the
  session layer attaches a root even when tidb_trace_enabled = 0; span
  construction is a perf_counter read + two container allocs — the
  extended PR 4 guard bounds the whole statement overhead < 2 ms).
* At statement end the tree is RETAINED only when the statement crossed
  the slow-log threshold, died on its deadline, or degraded through any
  tier (degraded_* tallies) — everything else drops the tree on the
  floor, so the fast path retains nothing (zero live Span objects after
  a burst of healthy statements; the guard asserts exactly that).
* Retained traces land in a bounded per-store ring queryable through
  information_schema.TIDB_TPU_SLOW_TRACES: the serialized span tree
  (region tasks, kernel dispatches, batch/mesh attribution), the
  statement's resource deltas, and why it was kept.

Knobs (GLOBAL-only, persisted + hydrated like the plane-cache pair):
SET GLOBAL tidb_tpu_flight_recorder = 0|1 (off clears the ring and
stops building spans), SET GLOBAL tidb_tpu_slow_trace_cap = N.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from tidb_tpu.sessionctx import SYSVAR_DEFAULTS

DEFAULT_CAP = int(SYSVAR_DEFAULTS["tidb_tpu_slow_trace_cap"])
DEFAULT_MAX_SPANS = int(SYSVAR_DEFAULTS["tidb_tpu_slow_trace_max_spans"])


class FlightRecorder:
    """Bounded ring of retained statement traces for one store."""

    def __init__(self, cap: int = DEFAULT_CAP,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.enabled = True
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max(1, cap))

    # ---- configuration (sysvar appliers) ----

    def set_enabled(self, on: bool) -> None:
        with self._lock:
            self.enabled = on
            if not on:
                self._ring.clear()

    def set_cap(self, n: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(n)))

    def set_max_spans(self, n: int) -> None:
        """Per-ENTRY retained span budget (0 = unbounded): the cap
        bounds how many traces the ring keeps, this bounds how big each
        one may be — a pathological fan-out (thousands of region tasks ×
        kernel spans) must not bloat TIDB_TPU_SLOW_TRACES."""
        self.max_spans = max(0, int(n))

    @property
    def cap(self) -> int:
        return self._ring.maxlen or 0

    # ------------------------------------------------------------------

    def record(self, *, conn_id: int, digest: str, sql_text: str,
               duration_ms: float, reason: str, root,
               resources: dict, error: str = "") -> None:
        """Retain one statement's trace. The span tree is serialized
        HERE (root.to_dict() snapshots attrs/children), so an abandoned
        fan-out worker still mutating a span cannot corrupt a retained
        entry, and the ring holds plain dicts — no live Span objects."""
        from tidb_tpu import metrics
        doc = root.to_dict()
        _truncate_doc(doc, self.max_spans)
        entry = {
            "ts": time.time(),
            "conn_id": conn_id,
            "digest": digest,
            "sql": sql_text[:2048],
            "duration_ms": round(duration_ms, 3),
            "reason": reason,
            "error": error[:512],
            "span_count": _count_spans(doc),
            "resources": dict(resources),
            "trace": doc,
        }
        with self._lock:
            if not self.enabled:
                return      # a statement racing the kill switch
            self._ring.append(entry)
        metrics.counter("tracing.slow_traces_retained").inc()

    def entries(self) -> list[dict]:
        """Oldest-first snapshot of the retained traces."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


def _count_spans(doc: dict) -> int:
    n = 1
    for c in doc.get("children", ()):
        n += _count_spans(c)
    return n


def _truncate_doc(doc: dict, budget: int) -> bool:
    """Prune a serialized span tree to ≤ `budget` spans, keeping the
    ROOT plus the SLOWEST subtrees (a span survives only with its whole
    ancestor chain, so the retained tree stays well-formed — the slow
    statement's dominant paths are exactly what the operator reads).
    Stamps truncated=true + dropped_spans on the root so TRACE_JSON
    says it is partial. Returns whether anything was dropped."""
    if budget <= 0:
        return False
    nodes: list[tuple[float, dict]] = []
    parent_of: dict[int, dict] = {}

    def walk(d: dict) -> None:
        for c in d.get("children", ()):
            nodes.append((float(c.get("duration_us", 0.0)), c))
            parent_of[id(c)] = d
            walk(c)

    walk(doc)
    total = len(nodes) + 1
    if total <= budget:
        return False
    keep: set[int] = {id(doc)}
    budget_left = budget - 1
    for _dur, c in sorted(nodes, key=lambda t: -t[0]):
        if budget_left <= 0:
            break
        chain = []
        n = c
        while id(n) not in keep:
            chain.append(n)
            n = parent_of[id(n)]
        if len(chain) <= budget_left:
            keep.update(id(m) for m in chain)
            budget_left -= len(chain)

    def prune(d: dict) -> None:
        kids = d.get("children")
        if not kids:
            return
        kept = [c for c in kids if id(c) in keep]
        for c in kept:
            prune(c)
        if kept:
            d["children"] = kept
        else:
            d.pop("children", None)

    prune(doc)
    doc["truncated"] = True
    doc["dropped_spans"] = total - len(keep)
    return True


def retain_reason(elapsed_ms: float, threshold_ms: float,
                  resources: dict, deadline: bool) -> str | None:
    """Why (if at all) a finished statement's trace must be retained —
    THE retention policy, shared by the success and error paths:
    deadline death first (the most specific), then any tier
    degradation, then the slow-log threshold (<= 0 disables the slow
    leg exactly like the slow log itself)."""
    if deadline:
        return "deadline"
    for key, v in resources.items():
        if v and key.startswith("degraded_"):
            return key
    if threshold_ms > 0 and elapsed_ms >= threshold_ms:
        return "slow"
    return None


# ---------------------------------------------------------------------------
# per-store registry (perfschema.perf_for discipline: bounded, keyed by
# store uuid so tests' short-lived stores don't pin recorders forever)
# ---------------------------------------------------------------------------

from collections import OrderedDict as _OrderedDict

_recorders: "_OrderedDict[str, FlightRecorder]" = _OrderedDict()
_lock = threading.Lock()


def recorder_for(store) -> FlightRecorder:
    with _lock:
        uuid = store.uuid()
        fr = _recorders.get(uuid)
        if fr is None:
            fr = _recorders[uuid] = FlightRecorder()
        # true LRU (perf_for discipline): evict the least-recently USED
        # store, never a live one — FIFO would drop a long-lived server
        # store's retained traces (and its kill-switch state) the
        # moment enough short-lived stores churned past the cap
        _recorders.move_to_end(uuid)
        while len(_recorders) > 64:
            _recorders.popitem(last=False)
        return fr


def trace_json(entry: dict) -> str:
    """The TRACE_JSON cell: the full span tree, compact."""
    return json.dumps(entry["trace"], sort_keys=True,
                      separators=(",", ":"))


def trace_event_json(entry: dict) -> str:
    """The TRACE_EVENT_JSON cell: the statement's cross-thread timeline
    in Chrome trace-event form (Perfetto-loadable) — the span tree as
    per-thread slices plus the dispatch-serial lock hold intervals."""
    from tidb_tpu import profiler
    return profiler.trace_event_json(entry)
