"""Error taxonomy.

Reference: terror/terror.go (error class/code registry with MySQL code mapping)
and kv/error.go (retryable-error detection driving session.Retry).
"""

from __future__ import annotations

from tidb_tpu import mysqldef as my


class TiDBError(Exception):
    """Base engine error carrying a MySQL error code for the wire protocol."""

    code: int = my.ErrUnknown

    def __init__(self, msg: str = "", code: int | None = None):
        super().__init__(msg)
        if code is not None:
            self.code = code


class ParseError(TiDBError):
    code = my.ErrParse


class PlanError(TiDBError):
    pass


class ExecError(TiDBError):
    pass


class UnknownFieldError(TiDBError):
    code = my.ErrBadField


class NoSuchTableError(TiDBError):
    code = my.ErrNoSuchTable


class TableExistsError(TiDBError):
    code = my.ErrTableExists


class BadDBError(TiDBError):
    code = my.ErrBadDB


class DBExistsError(TiDBError):
    code = my.ErrDBCreateExists


class DupEntryError(TiDBError):
    code = my.ErrDupEntry


class TypeError_(TiDBError):
    code = my.ErrTruncated


class OverflowError_(TiDBError):
    code = my.ErrDataTooLong


class DivByZeroError(TiDBError):
    code = my.ErrDivisionByZero


# ---- KV-layer errors (kv/error.go) ----

class KVError(TiDBError):
    pass


class KeyNotExistsError(KVError):
    """kv.ErrNotExist"""


class KeyExistsError(KVError, DupEntryError):
    """kv.ErrKeyExists — unique constraint violation surfaced as 1062."""
    code = my.ErrDupEntry

    def __init__(self, msg: str = "", existing_handle: int | None = None):
        super().__init__(msg)
        # the conflicting row's handle when the checker knows it (eager
        # unique-index / row-key checks) — ON DUPLICATE KEY UPDATE and
        # REPLACE locate the row to touch through this
        self.existing_handle = existing_handle


class DeadlineExceededError(KVError):
    """Backoff budget or statement deadline exhausted — the typed,
    NON-retryable surface of the unified Backoffer (kv/backoff.py).
    Carries the retry ladder history in `.history` as
    (kind, attempt, sleep_ms, err) tuples. MySQL 3024 ER_QUERY_TIMEOUT."""

    code = 3024

    def __init__(self, msg: str = "", code: int | None = None):
        super().__init__(msg, code)
        self.history: list = []


class DeviceError(TiDBError):
    """Device-tier fault (kernel compile failure, device OOM, readback
    failure — real or failpoint-injected). Recoverable by construction:
    every device route has a certified host fallback, so this class is
    caught at the degradation seams (ops/client.send, HashJoinExec,
    fused_agg's region combine) and never becomes a statement error
    while a lower tier exists."""


class RetryableError(KVError):
    """kv.ErrRetryable / write-conflict class: session may replay the txn.

    Reference: kv/error.go IsRetryableError + session.Retry (session.go:274).
    """


class WriteConflictError(RetryableError):
    pass


class LockedError(RetryableError):
    """localstore ErrLockConflict (store/localstore/kv.go tryLock)."""


def is_retryable(err: BaseException) -> bool:
    return isinstance(err, RetryableError)
