"""DDL test hooks.

Reference: ddl/callback.go — tests interpose between schema states to assert
mid-DDL invariants (column_change_test.go, index_change_test.go).
"""

from __future__ import annotations


class Callback:
    def on_changed(self, err: Exception | None) -> None:
        """After every schema-version bump (one state transition)."""

    def on_job_updated(self, job) -> None:
        """After a job's state is persisted."""
