"""DDL statement implementation + job worker.

Reference: ddl/ddl.go (DDL interface :92, buildTableInfo), ddl/ddl_worker.go
(addDDLJob :152, handleDDLJobQueue :234, runDDLJob :328), ddl/index.go
(onCreateIndex :113, addTableIndex backfill :378), ddl/column.go,
ddl/table.go, ddl/schema.go, ddl/bg_worker.go.
"""

from __future__ import annotations

import json
import threading
import time
import uuid as uuidlib
from dataclasses import dataclass, field as dc_field
from typing import Any

from tidb_tpu import errors, mysqldef as my, tablecodec as tc
from tidb_tpu.ddl.callback import Callback
from tidb_tpu.kv import run_in_new_txn
from tidb_tpu.meta import Meta
from tidb_tpu.model import (
    ActionType, ColumnInfo, DBInfo, DDLJob, FKInfo, IndexColumn, IndexInfo,
    JobState, SchemaState, TableInfo,
)
from tidb_tpu.table import Table
from tidb_tpu.types.field_type import FieldType

REORG_BATCH_SIZE = 256

# a silent owner is replaced after this long (ddl_worker.go maxOwnerTimeout)
OWNER_TIMEOUT_MS = 4000
# how long an enqueuing server waits for SOME owner to finish its job
JOB_WAIT_TIMEOUT_S = 30.0


@dataclass
class ColumnSpec:
    name: str
    field_type: FieldType
    default_value: Any = None
    has_default: bool = False
    comment: str = ""


@dataclass
class IndexSpec:
    name: str
    columns: list[str] = dc_field(default_factory=list)
    unique: bool = False
    primary: bool = False


@dataclass
class FKSpec:
    """Foreign-key definition (reference ddl/ddl.go buildFKInfo :1240).
    Metadata-only, matching the reference's 2016 semantics."""
    name: str
    cols: list[str] = dc_field(default_factory=list)
    ref_table: str = ""
    ref_cols: list[str] = dc_field(default_factory=list)
    on_delete: str = ""
    on_update: str = ""


class DDL:
    """Owns the job queue. Every server may enqueue; only the OWNER — a
    lease on the meta DDLOwner key, renewed per state step and taken over
    after OWNER_TIMEOUT_MS of silence — processes (ddl_worker.go:97
    checkOwner). The enqueuing server drives the queue inline when it can
    own, else polls job history until the real owner finishes. A
    background worker (start_worker) gives idle servers the reference's
    onDDLWorker loop; drop-table data deletion rides the bg job queue
    under its own owner key (bg_worker.go)."""

    def __init__(self, store, handle, callback: Callback | None = None,
                 schema_lease_s: float = 0.0):
        self.store = store
        self.handle = handle  # infoschema.Handle
        self.callback = callback or Callback()
        self.uuid = uuidlib.uuid4().hex[:12]
        # >0 emulates the reference's 2×lease waitSchemaChanged barrier
        # (ddl_worker.go:397): other servers get 2 lease periods to load
        # the bumped version before the next state transition
        self.schema_lease_s = schema_lease_s
        self._lock = threading.Lock()
        self._worker_stop: threading.Event | None = None

    # each state transition pauses 2x this when live PEER servers share
    # the store but no explicit lease is configured — the reference
    # ALWAYS waits 2xlease (ddl_worker.go:397); embedded single-server
    # stores skip the barrier for latency, but real peers must get their
    # reload window (round-4 weak #6)
    EMBEDDED_PEER_LEASE_S = 0.05

    def _effective_lease(self) -> float:
        if self.schema_lease_s > 0:
            return self.schema_lease_s
        try:
            peers = run_in_new_txn(
                self.store, False,
                lambda txn: Meta(txn).live_servers())
        except errors.TiDBError:
            return 0.0
        others = [p for p in peers if p != self.uuid]
        return self.EMBEDDED_PEER_LEASE_S if others else 0.0

    # ---- owner lease (ddl_worker.go:97) ----

    def _take_owner(self, m: Meta, bg: bool = False) -> bool:
        now = int(time.time() * 1000)
        raw = m.get_owner(bg=bg)
        if raw:
            o = json.loads(raw)
            if o["id"] != self.uuid and o["ts"] + OWNER_TIMEOUT_MS > now:
                return False  # someone else holds a live lease
            if o["id"] == self.uuid and \
                    now - o["ts"] < OWNER_TIMEOUT_MS // 2:
                return True  # fresh enough: skip the renewal write
        m.set_owner(json.dumps({"id": self.uuid, "ts": now}).encode(),
                    bg=bg)
        return True

    def _release_owner(self, bg: bool = False) -> None:
        """Expire our own lease so the next server's DDL doesn't stall
        waiting out OWNER_TIMEOUT_MS against an idle holder."""
        def rel(txn):
            m = Meta(txn)
            raw = m.get_owner(bg=bg)
            if raw and json.loads(raw)["id"] == self.uuid:
                m.set_owner(json.dumps({"id": self.uuid, "ts": 0}).encode(),
                            bg=bg)
        try:
            run_in_new_txn(self.store, True, rel)
        except Exception:
            pass  # worst case: the lease times out naturally

    def _renew_owner(self) -> None:
        def renew(txn):
            self._take_owner(Meta(txn))
        try:
            run_in_new_txn(self.store, True, renew)
        except Exception:
            pass

    # ---- background worker (ddl_worker.go onDDLWorker loop) ----

    def start_worker(self, interval_s: float = 0.25) -> None:
        if self._worker_stop is not None:
            return
        self._worker_stop = threading.Event()
        stop = self._worker_stop  # capture: stop()+start() must not leave
        # the old thread polling the NEW event (it would never exit)

        def loop():
            while not stop.wait(interval_s):
                try:
                    with self._lock:
                        self._handle_job_queue(None)
                        self._handle_bg_queue()
                except Exception:
                    pass  # next tick retries; jobs survive in the queue

        threading.Thread(target=loop, name="tidb-ddl-worker",
                         daemon=True).start()

    def stop_worker(self) -> None:
        if self._worker_stop is not None:
            self._worker_stop.set()
            self._worker_stop = None

    # ================= public API (ddl/ddl.go DDL interface) =================

    @staticmethod
    def _check_not_virtual(db) -> None:
        """Virtual schemas (performance_schema, reserved negative ids) have
        no meta representation — DDL against them must error, not queue a
        job that silently no-ops."""
        if db is not None and db.id < 0:
            raise errors.ExecError(
                f"DDL is not allowed on system database '{db.name}'")

    def create_schema(self, name: str, charset: str = "utf8",
                      collate: str = "utf8_bin") -> None:
        schema = self.handle.get()
        if schema.schema_exists(name):
            raise errors.DBExistsError(f"Can't create database '{name}'; database exists")
        job = self._new_job(ActionType.CREATE_SCHEMA, 0, 0,
                            [name, charset, collate])
        self._run_job(job)

    def drop_schema(self, name: str) -> None:
        schema = self.handle.get()
        db = schema.schema_by_name(name)
        if db is None:
            raise errors.BadDBError(f"Can't drop database '{name}'; database doesn't exist")
        self._check_not_virtual(db)
        job = self._new_job(ActionType.DROP_SCHEMA, db.id, 0, [])
        self._run_job(job)

    def create_table(self, db_name: str, table_name: str, cols: list[ColumnSpec],
                     indexes: list[IndexSpec], charset: str = "utf8",
                     collate: str = "utf8_bin",
                     fks: list[FKSpec] = ()) -> None:
        schema = self.handle.get()
        db = schema.schema_by_name(db_name)
        if db is None:
            raise errors.BadDBError(f"Unknown database '{db_name}'")
        self._check_not_virtual(db)
        if schema.table_exists(db_name, table_name):
            raise errors.TableExistsError(f"Table '{table_name}' already exists")
        tbl_json = self._build_table_info(table_name, cols, indexes,
                                          charset, collate, fks).to_json()
        job = self._new_job(ActionType.CREATE_TABLE, db.id, 0, [tbl_json])
        self._run_job(job)

    def create_foreign_key(self, db_name: str, table_name: str,
                           spec: FKSpec) -> None:
        """ALTER TABLE ADD FOREIGN KEY through the online-DDL queue
        (reference ddl/ddl.go:1268 CreateForeignKey → foreign_key.go:23
        onCreateForeignKey, none→public in one step)."""
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        fk = self._build_fk_info(tbl.info, spec)
        job = self._new_job(ActionType.ADD_FOREIGN_KEY, db.id, tbl.id,
                            [fk.to_json()])
        self._run_job(job)

    def drop_foreign_key(self, db_name: str, table_name: str,
                         fk_name: str) -> None:
        """Reference ddl/ddl.go:1299 DropForeignKey → foreign_key.go:76
        onDropForeignKey (public→none)."""
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        if not any(f.name.lower() == fk_name.lower()
                   for f in tbl.info.foreign_keys):
            raise errors.TiDBError(
                f"Can't DROP '{fk_name}'; check that column/key exists",
                code=my.ErrCantDropFieldOrKey)
        job = self._new_job(ActionType.DROP_FOREIGN_KEY, db.id, tbl.id,
                            [fk_name])
        self._run_job(job)

    def drop_table(self, db_name: str, table_name: str) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        self._check_not_virtual(db)
        job = self._new_job(ActionType.DROP_TABLE, db.id, tbl.id, [])
        self._run_job(job)

    def truncate_table(self, db_name: str, table_name: str) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        self._check_not_virtual(db)
        job = self._new_job(ActionType.TRUNCATE_TABLE, db.id, tbl.id, [])
        self._run_job(job)

    def create_index(self, db_name: str, table_name: str, index_name: str,
                     col_names: list[str], unique: bool = False) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        if tbl.info.find_index(index_name) is not None:
            raise errors.TiDBError(f"Duplicate key name '{index_name}'",
                                   code=my.ErrDupKeyName)
        for cn in col_names:
            if tbl.info.find_column(cn) is None:
                raise errors.UnknownFieldError(f"Key column '{cn}' doesn't exist")
        job = self._new_job(ActionType.ADD_INDEX, db.id, tbl.id,
                            [index_name, col_names, unique])
        self._run_job(job)

    def drop_index(self, db_name: str, table_name: str, index_name: str) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        if tbl.info.find_index(index_name) is None:
            raise errors.TiDBError(f"Can't DROP '{index_name}'; check that it exists",
                                   code=my.ErrCantDropFieldOrKey)
        job = self._new_job(ActionType.DROP_INDEX, db.id, tbl.id, [index_name])
        self._run_job(job)

    def add_column(self, db_name: str, table_name: str, spec: ColumnSpec) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        if tbl.info.find_column(spec.name) is not None:
            raise errors.TiDBError(f"Duplicate column name '{spec.name}'", code=1060)
        col_json = ColumnInfo(0, spec.name, 0, spec.field_type, spec.default_value,
                              spec.has_default,
                              spec.default_value if spec.has_default else None,
                              spec.comment).to_json()
        job = self._new_job(ActionType.ADD_COLUMN, db.id, tbl.id, [col_json])
        self._run_job(job)

    def modify_column(self, db_name: str, table_name: str,
                      spec: ColumnSpec) -> None:
        """ALTER TABLE MODIFY COLUMN: metadata-only field-type change,
        restricted to widenings the stored encoding already satisfies
        (ddl/ddl.go:1070 modifiable; ddl/column.go:421 onModifyColumn)."""
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        old = tbl.info.find_column(spec.name)
        if old is None or old.state != SchemaState.PUBLIC:
            raise errors.UnknownFieldError(
                f"column {spec.name} doesn't exist")
        if not _modifiable(old.field_type, spec.field_type):
            raise errors.TiDBError(
                f"unsupported modify column {spec.name}", code=8200)
        # MODIFY only changes the TYPE: structural flags (pk-handle
        # detection, NOT NULL, auto_increment, key markers) carry over
        new_ft = spec.field_type.clone()
        struct = (my.PriKeyFlag | my.NotNullFlag | my.AutoIncrementFlag |
                  my.UniqueKeyFlag | my.MultipleKeyFlag)
        new_ft.flag = (new_ft.flag & ~struct) | (old.field_type.flag & struct)
        new_col = ColumnInfo(old.id, old.name, old.offset, new_ft,
                             old.default_value, old.has_default,
                             old.original_default, old.comment,
                             state=old.state)
        job = self._new_job(ActionType.MODIFY_COLUMN, db.id, tbl.id,
                            [new_col.to_json()])
        self._run_job(job)

    def drop_column(self, db_name: str, table_name: str, col_name: str) -> None:
        schema = self.handle.get()
        tbl = schema.table_by_name(db_name, table_name)
        db = schema.schema_by_name(db_name)
        col = tbl.info.find_column(col_name)
        if col is None:
            raise errors.TiDBError(f"Can't DROP '{col_name}'; check that it exists",
                                   code=my.ErrCantDropFieldOrKey)
        if any(col_name.lower() == ic.name.lower()
               for idx in tbl.info.indices for ic in idx.columns):
            raise errors.TiDBError(
                f"Can't DROP '{col_name}'; it is referenced by an index",
                code=my.ErrCantDropFieldOrKey)
        if tbl.info.pk_handle_column() is col:
            raise errors.TiDBError("Can't DROP the primary key handle column",
                                   code=my.ErrCantDropFieldOrKey)
        job = self._new_job(ActionType.DROP_COLUMN, db.id, tbl.id, [col_name])
        self._run_job(job)

    # ================= table-info construction =================

    def _build_fk_info(self, info: TableInfo, spec: FKSpec,
                       fk_id: int = 0) -> FKInfo:
        """Validate + build FKInfo against a table's columns (reference
        ddl/ddl.go:744 buildTableInfo FK branch + :1240 buildFKInfo)."""
        if not spec.cols:
            raise errors.TiDBError(
                "foreign key should have one key at least", code=1215)
        if len(spec.cols) != len(spec.ref_cols):
            raise errors.TiDBError(
                f"foreign key not match keys len {len(spec.cols)}, "
                f"refkeys len {len(spec.ref_cols)}", code=1215)
        for cn in spec.cols:
            if info.find_column(cn) is None:
                raise errors.UnknownFieldError(
                    f"Key column '{cn}' doesn't exist in table")
        name = spec.name or f"fk_{spec.cols[0].lower()}"
        taken = {f.name.lower() for f in info.foreign_keys}
        if name.lower() in taken:
            if spec.name:
                raise errors.TiDBError(
                    f"duplicate foreign key {spec.name}", code=1826)
            i = 1
            while f"{name}_{i}".lower() in taken:
                i += 1
            name = f"{name}_{i}"
        return FKInfo(id=fk_id, name=name, cols=list(spec.cols),
                      ref_table=spec.ref_table,
                      ref_cols=list(spec.ref_cols),
                      on_delete=spec.on_delete, on_update=spec.on_update,
                      state=SchemaState.PUBLIC)

    def _build_table_info(self, name: str, cols: list[ColumnSpec],
                          indexes: list[IndexSpec], charset: str = "utf8",
                          collate: str = "utf8_bin",
                          fks: list[FKSpec] = ()) -> TableInfo:
        """Reference: ddl/ddl.go buildTableInfo + buildColumnsAndConstraints."""
        seen = set()
        columns = []
        for i, spec in enumerate(cols):
            if spec.name.lower() in seen:
                raise errors.TiDBError(f"Duplicate column name '{spec.name}'", code=1060)
            seen.add(spec.name.lower())
            columns.append(ColumnInfo(
                id=i + 1, name=spec.name, offset=i, field_type=spec.field_type,
                default_value=spec.default_value, has_default=spec.has_default,
                comment=spec.comment, state=SchemaState.PUBLIC))
        info = TableInfo(id=0, name=name, columns=columns,
                         charset=charset, collate=collate)

        offsets = {c.lower_name: c.offset for c in columns}
        idx_id = 1
        for spec in indexes:
            icols = []
            for cn in spec.columns:
                if cn.lower() not in offsets:
                    raise errors.UnknownFieldError(f"Key column '{cn}' doesn't exist")
                icols.append(IndexColumn(cn, offsets[cn.lower()]))
            if spec.primary:
                # single int pk → handle column (pk_is_handle fast path)
                if len(icols) == 1:
                    col = columns[icols[0].offset]
                    # signed only: handles are int64; a BIGINT UNSIGNED pk
                    # >= 2^63 would wrap and mis-sort as a handle
                    if col.field_type.is_integer() and not col.field_type.is_unsigned():
                        col.field_type.flag |= my.PriKeyFlag | my.NotNullFlag
                        info.pk_is_handle = True
                        continue
                for ic in icols:
                    columns[ic.offset].field_type.flag |= my.NotNullFlag
            columns_flag = my.UniqueKeyFlag if spec.unique else my.MultipleKeyFlag
            columns[icols[0].offset].field_type.flag |= columns_flag
            info.indices.append(IndexInfo(
                id=idx_id, name=spec.name or f"idx_{idx_id}", columns=icols,
                unique=spec.unique or spec.primary, primary=spec.primary,
                state=SchemaState.PUBLIC))
            idx_id += 1
        # record the allocation high-water mark: without it, dropping the
        # last CREATE TABLE-inline index would let alloc_index_id hand
        # the dead id to the next CREATE INDEX (same reuse corruption)
        info.max_index_id = idx_id - 1
        for i, fspec in enumerate(fks, 1):
            info.foreign_keys.append(self._build_fk_info(info, fspec, i))
        return info

    # ================= job machinery =================

    def _new_job(self, tp: ActionType, schema_id: int, table_id: int,
                 args: list) -> DDLJob:
        def alloc(txn):
            return Meta(txn).gen_global_id()

        job_id = run_in_new_txn(self.store, True, alloc)
        return DDLJob(id=job_id, tp=tp, schema_id=schema_id, table_id=table_id,
                      args=args)

    def _run_job(self, job: DDLJob) -> None:
        """Enqueue then wait for the job to finish: drive the queue when
        this server can own, else poll history while the owner works.
        Reference: ddl_worker.go addDDLJob + handleDDLJobQueue."""
        with self._lock:
            def enqueue(txn):
                Meta(txn).enqueue_ddl_job(job)
            run_in_new_txn(self.store, True, enqueue)
            deadline = time.time() + JOB_WAIT_TIMEOUT_S
            finished = None
            while finished is None:
                finished = self._handle_job_queue(wait_for=job.id)
                if finished is None:
                    # queue empty (another server took it) or not owner
                    finished = self._history_job(job.id)
                    if finished is None:
                        if time.time() > deadline:
                            # the queue offers no mid-list removal, so the
                            # job may STILL execute once the owner
                            # recovers — the error must say so
                            raise errors.TiDBError(
                                f"DDL job {job.id} not processed within "
                                f"{JOB_WAIT_TIMEOUT_S}s (owner stuck?); "
                                "the job remains queued and may apply "
                                "later")
                        time.sleep(0.02)
            self._handle_bg_queue()
            self._release_owner()
            self._release_owner(bg=True)
        self.handle.load()  # converge this server even when not owner
        if finished.error:
            raise errors.TiDBError(finished.error,
                                   code=finished.error_code or None)

    def _history_job(self, job_id: int) -> DDLJob | None:
        txn = self.store.begin()
        try:
            return Meta(txn).history_ddl_job(job_id)
        finally:
            txn.rollback()

    def _handle_job_queue(self, wait_for: int | None = None) -> DDLJob | None:
        """Drive the queue while owner; returns the finished job matching
        wait_for, or None when the queue is empty / owned elsewhere."""
        while True:
            done_job: DDLJob | None = None

            def step(txn):
                nonlocal done_job
                m = Meta(txn)
                cur = m.get_ddl_job(0)
                if cur is None:
                    return False  # empty: don't even write a lease
                if not self._take_owner(m):
                    return False
                changed = self._run_one_state(txn, m, cur)
                if cur.is_finished():
                    m.dequeue_ddl_job()
                    m.add_history_ddl_job(cur)
                    done_job = cur
                else:
                    m.update_ddl_job(cur, 0)
                if changed:
                    m.bump_schema_version()
                return True

            # a state transition must win EVENTUALLY: a reorg batch
            # conflicts with every concurrent write txn, so it gets the
            # reference's ~100-attempt meta-txn budget — giving up after
            # the default 10 would strand the job mid-flight with earlier
            # states already public (a re-issued ADD INDEX then fails on
            # its own partial work: "Duplicate key name"). Ordinary txns
            # it conflicts with always make progress, so this converges.
            progressed = run_in_new_txn(self.store, True, step,
                                        max_retries=100)
            if not progressed:
                return None
            # every version bump is visible to other servers here; with a
            # schema lease configured, give them 2 lease periods to load
            # it before the next state (waitSchemaChanged, :397)
            self.handle.load()
            lease_s = self._effective_lease()
            if lease_s > 0:
                # renew the lease while sleeping — a 2×lease barrier longer
                # than OWNER_TIMEOUT must not let another server steal the
                # job mid-state
                remaining = 2 * lease_s
                slice_s = OWNER_TIMEOUT_MS / 1000.0 / 4
                while remaining > 0:
                    time.sleep(min(slice_s, remaining))
                    remaining -= slice_s
                    if remaining > 0:
                        self._renew_owner()
            self.callback.on_changed(None)
            if done_job is not None:
                self.callback.on_job_updated(done_job)
                if wait_for is not None and done_job.id == wait_for:
                    return done_job

    # ---- background drop-data queue (ddl/bg_worker.go) ----

    def _handle_bg_queue(self) -> None:
        """Process queued drop-table data deletions under the bg owner
        lease; every server's worker competes, exactly one wins each."""
        while True:
            def step(txn):
                m = Meta(txn)
                job = m.get_ddl_job(0, bg=True)
                if job is None:
                    return False  # empty: no lease write
                if not self._take_owner(m, bg=True):
                    return False
                self._delete_table_data(txn, job.table_id)
                m.dequeue_ddl_job(bg=True)
                return True

            if not run_in_new_txn(self.store, True, step):
                return

    def _run_one_state(self, txn, m: Meta, job: DDLJob) -> bool:
        """One state transition of one job; returns True if schema changed.
        Reference: ddl_worker.go runDDLJob."""
        try:
            handler = {
                ActionType.CREATE_SCHEMA: self._on_create_schema,
                ActionType.DROP_SCHEMA: self._on_drop_schema,
                ActionType.CREATE_TABLE: self._on_create_table,
                ActionType.DROP_TABLE: self._on_drop_table,
                ActionType.TRUNCATE_TABLE: self._on_truncate_table,
                ActionType.ADD_INDEX: self._on_add_index,
                ActionType.DROP_INDEX: self._on_drop_index,
                ActionType.ADD_FOREIGN_KEY: self._on_add_foreign_key,
                ActionType.DROP_FOREIGN_KEY: self._on_drop_foreign_key,
                ActionType.ADD_COLUMN: self._on_add_column,
                ActionType.MODIFY_COLUMN: self._on_modify_column,
                ActionType.DROP_COLUMN: self._on_drop_column,
            }[job.tp]
        except KeyError:
            job.state = JobState.CANCELLED
            job.error = f"invalid ddl job type {job.tp}"
            return False
        try:
            return handler(txn, m, job)
        except errors.TiDBError as e:
            job.state = JobState.CANCELLED
            job.error = str(e)
            job.error_code = e.code
            # roll back half-built schema objects so no orphaned
            # non-public column/index survives a cancelled job
            # (reference: ddl_worker.go job rollback on error)
            changed = False
            if job.tp == ActionType.ADD_INDEX:
                changed = self._rollback_add_index(txn, m, job)
            elif job.tp == ActionType.ADD_COLUMN:
                changed = self._rollback_add_column(txn, m, job)
            return changed

    def _rollback_add_index(self, txn, m: Meta, job: DDLJob) -> bool:
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            return False
        index_name = job.args[0]
        idx = info.find_index(index_name)
        if idx is None or idx.state == SchemaState.PUBLIC:
            return False
        prefix = tc.encode_index_seek_key(info.id, idx.id)
        for k, _v in list(txn.iterate(prefix, prefix + b"\xff" * 9)):
            txn.delete(k)
        info.indices = [i for i in info.indices if i.id != idx.id]
        m.update_table(job.schema_id, info)
        return True

    def _rollback_add_column(self, txn, m: Meta, job: DDLJob) -> bool:
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            return False
        col_name = ColumnInfo.from_json(job.args[0]).name
        col = info.find_column(col_name)
        if col is None or col.state == SchemaState.PUBLIC:
            return False
        info.columns.remove(col)
        m.update_table(job.schema_id, info)
        return True

    # ---- schema ops ----

    def _on_create_schema(self, txn, m: Meta, job: DDLJob) -> bool:
        name = job.args[0]
        cs = job.args[1] if len(job.args) > 1 else "utf8"
        co = job.args[2] if len(job.args) > 2 else "utf8_bin"
        for db in m.list_databases():
            if db.name.lower() == name.lower():
                raise errors.DBExistsError(f"database {name} exists")
        db_id = m.gen_global_id()
        m.create_database(DBInfo(id=db_id, name=name, charset=cs, collate=co))
        job.schema_id = db_id
        job.state = JobState.DONE
        return True

    def _enqueue_bg_drop(self, m: Meta, schema_id: int,
                         table_id: int) -> None:
        """Defer data deletion to the bg queue (ddl/bg_worker.go): the
        schema change commits fast, the keyspace drains asynchronously."""
        m.enqueue_ddl_job(DDLJob(id=m.gen_global_id(),
                                 tp=ActionType.DROP_TABLE,
                                 schema_id=schema_id, table_id=table_id),
                          bg=True)

    def _on_drop_schema(self, txn, m: Meta, job: DDLJob) -> bool:
        for tbl in m.list_tables(job.schema_id):
            self._enqueue_bg_drop(m, job.schema_id, tbl.id)
            m.clear_table_stats(tbl.id)
        m.drop_database(job.schema_id)
        job.state = JobState.DONE
        return True

    # ---- table ops ----

    def _on_create_table(self, txn, m: Meta, job: DDLJob) -> bool:
        info = TableInfo.from_json(job.args[0])
        for t in m.list_tables(job.schema_id):
            if t.name.lower() == info.name.lower():
                raise errors.TableExistsError(f"table {info.name} exists")
        info.id = m.gen_global_id()
        info.state = SchemaState.PUBLIC
        m.create_table(job.schema_id, info)
        job.table_id = info.id
        job.state = JobState.DONE
        return True

    def _on_drop_table(self, txn, m: Meta, job: DDLJob) -> bool:
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        if info.state == SchemaState.PUBLIC:
            info.state = SchemaState.WRITE_ONLY
        elif info.state == SchemaState.WRITE_ONLY:
            info.state = SchemaState.DELETE_ONLY
        else:
            self._enqueue_bg_drop(m, job.schema_id, info.id)
            m.clear_table_stats(info.id)
            m.drop_table(job.schema_id, info.id)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, info)
        return True

    def _on_truncate_table(self, txn, m: Meta, job: DDLJob) -> bool:
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        self._enqueue_bg_drop(m, job.schema_id, info.id)
        m.clear_table_stats(info.id)
        m.drop_table(job.schema_id, info.id)
        info.id = m.gen_global_id()
        m.create_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    def _delete_table_data(self, txn, table_id: int) -> None:
        start = tc.table_prefix(table_id)
        end = start + b"\xff" * 12
        for k, _v in list(txn.iterate(start, end)):
            txn.delete(k)

    # ---- index ops (the online state machine) ----

    def _on_add_index(self, txn, m: Meta, job: DDLJob) -> bool:
        index_name, col_names, unique = job.args
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        idx = info.find_index(index_name)
        if idx is None:
            cols = []
            for cn in col_names:
                c = info.find_column(cn)
                if c is None:
                    raise errors.UnknownFieldError(f"column {cn} doesn't exist")
                cols.append(IndexColumn(c.name, c.offset))
            # alloc_index_id, never max(existing)+1: reusing a dropped
            # index's id would adopt entries a stale-schema writer
            # orphaned under it after the drop's delete pass (surfaced
            # as an ADMIN CHECK index/row type mismatch in test_chaos)
            idx = IndexInfo(id=info.alloc_index_id(),
                            name=index_name, columns=cols, unique=unique,
                            state=SchemaState.NONE)
            info.indices.append(idx)

        if idx.state == SchemaState.NONE:
            idx.state = SchemaState.DELETE_ONLY
        elif idx.state == SchemaState.DELETE_ONLY:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.WRITE_REORG
            job.reorg_handle = None
        elif idx.state == SchemaState.WRITE_REORG:
            done = self._backfill_index(txn, info, idx, job)
            if not done:
                m.update_table(job.schema_id, info)
                return False  # more batches; stay in WRITE_REORG
            idx.state = SchemaState.PUBLIC
            job.state = JobState.DONE
        m.update_table(job.schema_id, info)
        return True

    def _backfill_index(self, txn, info: TableInfo, idx: IndexInfo,
                        job: DDLJob) -> bool:
        """One batch of index backfill inside the job txn; checkpoint in
        job.reorg_handle. Reference: ddl/index.go backfillTableIndex:489."""
        tbl = Table(info)
        index = next(i for i in tbl.indices if i.info.id == idx.id)
        start_handle = job.reorg_handle
        if start_handle is None:
            start, end = tc.encode_record_range(info.id)
        else:
            start, _ = tc.handle_range_keys(info.id, start_handle + 1, (1 << 63) - 1)
            _, end = tc.encode_record_range(info.id)
        count = 0
        last_handle = None
        for k, v in txn.iterate(start, end):
            if count >= REORG_BATCH_SIZE:
                job.reorg_handle = last_handle
                return False
            _tid, handle = tc.decode_row_key(k)
            data = tc.decode_row(v)
            values = []
            from tidb_tpu.types.datum import NULL
            from tidb_tpu.types import unflatten_datum
            pk_col = info.pk_handle_column()
            for ic in idx.columns:
                col = info.columns[ic.offset]
                if pk_col is not None and col.id == pk_col.id:
                    from tidb_tpu.types import Datum
                    values.append(Datum.i64(handle))
                else:
                    values.append(unflatten_datum(data[col.id], col.field_type)
                                  if col.id in data else NULL)
            index.create(txn, values, handle, backfill=True)
            last_handle = handle
            count += 1
        return True

    def _on_drop_index(self, txn, m: Meta, job: DDLJob) -> bool:
        index_name = job.args[0]
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        idx = info.find_index(index_name)
        if idx is None:
            raise errors.TiDBError(f"index {index_name} doesn't exist",
                                   code=my.ErrCantDropFieldOrKey)
        if idx.state == SchemaState.PUBLIC:
            idx.state = SchemaState.WRITE_ONLY
        elif idx.state == SchemaState.WRITE_ONLY:
            idx.state = SchemaState.DELETE_ONLY
        else:
            # delete index data, then remove from schema
            prefix = tc.encode_index_seek_key(info.id, idx.id)
            for k, _v in list(txn.iterate(prefix, prefix + b"\xff" * 9)):
                txn.delete(k)
            # pin the dead id into the high-water mark — covers tables
            # persisted before max_index_id existed (deserialized as 0)
            info.max_index_id = max(info.max_index_id, idx.id)
            info.indices = [i for i in info.indices if i.id != idx.id]
            m.update_table(job.schema_id, info)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, info)
        return True

    # ---- foreign key ops (reference ddl/foreign_key.go) ----

    def _on_add_foreign_key(self, txn, m: Meta, job: DDLJob) -> bool:
        """none→public in one step: FKs are recorded, never enforced
        (foreign_key.go:46 "We just support record the foreign key")."""
        fk = FKInfo.from_json(job.args[0])
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        if any(f.name.lower() == fk.name.lower()
               for f in info.foreign_keys):
            raise errors.TiDBError(f"duplicate foreign key {fk.name}",
                                   code=1826)
        fk.id = max([f.id for f in info.foreign_keys], default=0) + 1
        fk.state = SchemaState.PUBLIC
        info.foreign_keys.append(fk)
        m.update_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    def _on_drop_foreign_key(self, txn, m: Meta, job: DDLJob) -> bool:
        """public→none in one step (foreign_key.go:76 onDropForeignKey)."""
        fk_name = job.args[0]
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        if not any(f.name.lower() == fk_name.lower()
                   for f in info.foreign_keys):
            raise errors.TiDBError(
                f"foreign key {fk_name} doesn't exist",
                code=my.ErrCantDropFieldOrKey)
        info.foreign_keys = [f for f in info.foreign_keys
                             if f.name.lower() != fk_name.lower()]
        m.update_table(job.schema_id, info)
        job.state = JobState.DONE
        return True

    # ---- column ops ----

    def _on_modify_column(self, txn, m: Meta, job: DDLJob) -> bool:
        """Metadata-only swap of the column's FieldType
        (ddl/column.go:421 onModifyColumn)."""
        new_col = ColumnInfo.from_json(job.args[0])
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        old = info.find_column(new_col.name)
        if old is None or old.state != SchemaState.PUBLIC:
            raise errors.UnknownFieldError(
                f"column {new_col.name} doesn't exist")
        old.field_type = new_col.field_type
        m.update_table(job.schema_id, info)
        m.bump_schema_version()
        job.state = JobState.DONE
        return True

    def _on_add_column(self, txn, m: Meta, job: DDLJob) -> bool:
        col = ColumnInfo.from_json(job.args[0])
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        existing = info.find_column(col.name)
        if existing is None:
            col.id = max((c.id for c in info.columns), default=0) + 1
            col.offset = len(info.columns)
            col.state = SchemaState.NONE
            info.columns.append(col)
            existing = col
        if existing.state == SchemaState.NONE:
            existing.state = SchemaState.DELETE_ONLY
        elif existing.state == SchemaState.DELETE_ONLY:
            existing.state = SchemaState.WRITE_ONLY
        elif existing.state == SchemaState.WRITE_ONLY:
            # no reorg needed: original_default covers old rows
            existing.state = SchemaState.PUBLIC
            job.state = JobState.DONE
        m.update_table(job.schema_id, info)
        return True

    def _on_drop_column(self, txn, m: Meta, job: DDLJob) -> bool:
        col_name = job.args[0]
        info = m.get_table(job.schema_id, job.table_id)
        if info is None:
            raise errors.NoSuchTableError("table dropped concurrently")
        col = info.find_column(col_name)
        if col is None:
            raise errors.TiDBError(f"column {col_name} doesn't exist",
                                   code=my.ErrCantDropFieldOrKey)
        if col.state == SchemaState.PUBLIC:
            col.state = SchemaState.WRITE_ONLY
        elif col.state == SchemaState.WRITE_ONLY:
            col.state = SchemaState.DELETE_ONLY
        else:
            info.columns.remove(col)
            for i, c in enumerate(sorted(info.columns, key=lambda c: c.offset)):
                c.offset = i
            info.columns.sort(key=lambda c: c.offset)
            # fix index column offsets by name
            by_name = {c.lower_name: c.offset for c in info.columns}
            for idx in info.indices:
                for ic in idx.columns:
                    ic.offset = by_name[ic.name.lower()]
            m.update_table(job.schema_id, info)
            job.state = JobState.DONE
            return True
        m.update_table(job.schema_id, info)
        return True


_INT_WIDTH = {}  # storage-width rank, NOT display flen


def _modifiable(origin, to) -> bool:
    """ddl/ddl.go:1070: a MODIFY may only widen — same type class, no
    flen/decimal/storage-width shrink, same charset/collation, same
    signedness."""
    from tidb_tpu import mysqldef as my
    if not _INT_WIDTH:
        _INT_WIDTH.update({my.TypeTiny: 1, my.TypeShort: 2, my.TypeInt24: 3,
                           my.TypeLong: 4, my.TypeLonglong: 5})
    if to.flen >= 0 and to.flen < (origin.flen or 0):
        return False
    if to.decimal >= 0 and to.decimal < max(origin.decimal, 0):
        return False
    if origin.tp in my.STRING_TYPES:
        if (origin.charset, origin.collate) != (to.charset, to.collate):
            return False
    if my.has_unsigned_flag(origin.flag) != my.has_unsigned_flag(to.flag):
        return False
    if origin.tp in _INT_WIDTH:
        # integers widen by STORAGE width (tinyint < ... < bigint); the
        # display flen says nothing about what values the rows hold
        return to.tp in _INT_WIDTH and \
            _INT_WIDTH[to.tp] >= _INT_WIDTH[origin.tp]
    if origin.tp in my.STRING_TYPES:
        return to.tp in my.STRING_TYPES
    return origin.tp == to.tp
