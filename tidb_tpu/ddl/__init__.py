"""Online schema change (F1-style job queue).

Reference: ddl/ (ddl.go DDL interface, ddl_worker.go queue/owner protocol,
column.go, index.go backfill, table.go, schema.go, bg_worker.go).

Statements enqueue model.DDLJob records in the meta queue inside their own
txn; the worker pops jobs and steps schema objects through
DELETE_ONLY → WRITE_ONLY → WRITE_REORG → PUBLIC (add) or the reverse (drop),
bumping the schema version each step. ADD INDEX reorg backfills index
entries in batched transactions with a progress checkpoint on the job
(ddl/index.go addTableIndex / backfillTableIndex).

Single-process deployment runs the worker inline after enqueue; the
multi-server owner-lease protocol drives the same state machine.
"""

from tidb_tpu.ddl.ddl import DDL, ColumnSpec, IndexSpec  # noqa: F401
from tidb_tpu.ddl.callback import Callback  # noqa: F401
