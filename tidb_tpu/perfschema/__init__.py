"""performance_schema: bounded in-memory statement instrumentation,
queryable through the normal SQL path as virtual tables.

Reference: perfschema/init.go:205 (table definitions),
perfschema/perfschema.go:32-50 (StartStatement/EndStatement hooks wired
around each Execute at session.go:454-459). Here a per-store PerfSchema
keeps a fixed-capacity ring of statement events; the infoschema snapshot
attaches the virtual `performance_schema` database whose tables read from
it, so `select * from performance_schema.events_statements_history` runs
through the regular planner with SQL-side filtering (no KV, no pushdown).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque

from tidb_tpu import mysqldef as my
from tidb_tpu.model import ColumnInfo, TableInfo
from tidb_tpu.table.virtual import VirtualTableBase
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL
from tidb_tpu.types.field_type import FieldType

# reserved negative ids: never collide with meta's allocator and never
# reach the KV layer (the planner routes virtual scans to MemTableExec)
DB_ID = -100
T_STMT_CURRENT = -101
T_STMT_HISTORY = -102
T_INSTRUMENTS = -103

HISTORY_CAP = 1024  # stmtEventsHistoryElemMax-style bound


def _col(i: int, name: str, tp: int, flen: int = 64) -> ColumnInfo:
    return ColumnInfo(id=i + 1, name=name, offset=i,
                      field_type=FieldType(tp, 0, flen, -1))


_STMT_COLS = [
    ("THREAD_ID", my.TypeLonglong), ("EVENT_ID", my.TypeLonglong),
    ("EVENT_NAME", my.TypeVarchar), ("SQL_TEXT", my.TypeBlob),
    ("TIMER_START", my.TypeLonglong), ("TIMER_END", my.TypeLonglong),
    ("TIMER_WAIT", my.TypeLonglong), ("ROWS_SENT", my.TypeLonglong),
    ("ROWS_AFFECTED", my.TypeLonglong), ("ERRORS", my.TypeLonglong),
    ("MESSAGE_TEXT", my.TypeVarchar),
    # per-statement execution details: columnar channel attribution +
    # device-kernel tallies (the session's always-on per-thread counters)
    ("EXECUTION_DETAIL", my.TypeBlob),
]


def _stmt_table(tid: int, name: str) -> TableInfo:
    return TableInfo(id=tid, name=name,
                     columns=[_col(i, n, tp)
                              for i, (n, tp) in enumerate(_STMT_COLS)])


def table_infos() -> list[TableInfo]:
    return [
        _stmt_table(T_STMT_CURRENT, "events_statements_current"),
        _stmt_table(T_STMT_HISTORY, "events_statements_history"),
        TableInfo(id=T_INSTRUMENTS, name="setup_instruments", columns=[
            _col(0, "NAME", my.TypeVarchar, 128),
            _col(1, "ENABLED", my.TypeVarchar, 4),
            _col(2, "TIMED", my.TypeVarchar, 4),
        ]),
    ]


class StatementEvent:
    __slots__ = ("thread_id", "event_id", "name", "sql_text", "t_start",
                 "t_end", "rows_sent", "rows_affected", "errors", "message",
                 "detail")

    def __init__(self, thread_id: int, event_id: int, sql_text: str):
        self.thread_id = thread_id
        self.event_id = event_id
        self.name = "statement/sql/execute"
        self.sql_text = sql_text[:1024]
        self.t_start = time.perf_counter_ns()
        self.t_end = 0
        self.rows_sent = 0
        self.rows_affected = 0
        self.errors = 0
        self.message = ""
        self.detail = ""

    def row(self) -> list[Datum]:
        wait = max(0, self.t_end - self.t_start) if self.t_end else 0
        return [Datum.i64(self.thread_id), Datum.i64(self.event_id),
                Datum.bytes_(self.name.encode()),
                Datum.bytes_(self.sql_text.encode()),
                Datum.i64(self.t_start), Datum.i64(self.t_end),
                Datum.i64(wait), Datum.i64(self.rows_sent),
                Datum.i64(self.rows_affected), Datum.i64(self.errors),
                Datum.bytes_(self.message.encode()) if self.message
                else NULL,
                Datum.bytes_(self.detail.encode()) if self.detail
                else NULL]


CURRENT_CAP = 512  # bounded like the history ring: threads come and go


class PerfSchema:
    """Per-store statement event store (perfschema.statementStmts)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._event_ids = itertools.count(1)
        self._history: deque[StatementEvent] = deque(maxlen=HISTORY_CAP)
        # latest statement per thread (MySQL keeps completed statements in
        # *_current until the thread's next one), LRU-bounded
        self._current: "OrderedDict[int, StatementEvent]" = OrderedDict()
        self.enabled = True

    def start_statement(self, thread_id: int,
                        sql_text: str) -> StatementEvent | None:
        if not self.enabled:
            return None
        ev = StatementEvent(thread_id, next(self._event_ids), sql_text)
        with self._lock:
            self._current[thread_id] = ev
            self._current.move_to_end(thread_id)
            while len(self._current) > CURRENT_CAP:
                self._current.popitem(last=False)
        return ev

    def end_statement(self, ev: StatementEvent | None, rows_sent: int = 0,
                      rows_affected: int = 0, error: str = "",
                      detail: str = "") -> None:
        if ev is None:
            return
        # mutate + publish under the lock: rows() may be rendering this
        # very event through _current concurrently
        with self._lock:
            ev.t_end = time.perf_counter_ns()
            ev.rows_sent = rows_sent
            ev.rows_affected = rows_affected
            if error:
                ev.errors = 1
                ev.message = error
            ev.detail = detail[:1024]
            self._history.append(ev)

    def current_sql(self, thread_id: int) -> str | None:
        """Locked accessor for the thread's latest statement text (SHOW
        PROCESSLIST Info column)."""
        with self._lock:
            ev = self._current.get(thread_id)
            return ev.sql_text if ev is not None else None

    # ---- virtual-table row providers ----

    def rows(self, table_id: int) -> list[list[Datum]]:
        if table_id == T_STMT_CURRENT:
            with self._lock:  # render under the lock: no torn rows
                return [e.row() for e in self._current.values()]
        if table_id == T_STMT_HISTORY:
            with self._lock:
                return [e.row() for e in self._history]
        if table_id == T_INSTRUMENTS:
            on = b"YES" if self.enabled else b"NO"
            return [[Datum.bytes_(b"statement/sql/execute"),
                     Datum.bytes_(on), Datum.bytes_(b"YES")]]
        return []


_schemas: "OrderedDict[str, PerfSchema]" = OrderedDict()
_schemas_lock = threading.Lock()


def perf_for(store) -> PerfSchema:
    with _schemas_lock:
        ps = _schemas.get(store.uuid())
        if ps is None:
            ps = _schemas[store.uuid()] = PerfSchema()
        # true LRU: evict the least-recently USED store, never a live one
        _schemas.move_to_end(store.uuid())
        while len(_schemas) > 128:
            _schemas.popitem(last=False)
        return ps


class VirtualTable(VirtualTableBase):
    """performance_schema table bound to its store's event registry."""

    def __init__(self, info: TableInfo, store):
        super().__init__(info, "performance_schema")
        self.store = store

    def rows(self):
        return perf_for(self.store).rows(self.id)
