"""performance_schema: bounded in-memory statement instrumentation,
queryable through the normal SQL path as virtual tables.

Reference: perfschema/init.go:205 (table definitions),
perfschema/perfschema.go:32-50 (StartStatement/EndStatement hooks wired
around each Execute at session.go:454-459). Here a per-store PerfSchema
keeps a fixed-capacity ring of statement events; the infoschema snapshot
attaches the virtual `performance_schema` database whose tables read from
it, so `select * from performance_schema.events_statements_history` runs
through the regular planner with SQL-side filtering (no KV, no pushdown).

Workload aggregation (the layer above per-statement events): a
TiDB-style statement-digest summary —
`events_statements_summary_by_digest` (the CURRENT time window),
`_history` (rotated windows, a bounded ring) and `_evicted` (per-window
eviction accounting, so capped summaries stay reconcilable). Every
top-level statement rolls its latency + the full per-statement resource
tally (device kernels, columnar channel, plane cache, backoff,
degradations) into its digest's entry; the aggregation rides the
existing thread-local tally contract (monotonic diffs, one locked
update at statement end).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque

from tidb_tpu import mysqldef as my
from tidb_tpu.model import ColumnInfo, TableInfo
from tidb_tpu.table.virtual import VirtualTableBase
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL
from tidb_tpu.types.field_type import FieldType

# reserved negative ids: never collide with meta's allocator and never
# reach the KV layer (the planner routes virtual scans to MemTableExec)
DB_ID = -100
T_STMT_CURRENT = -101
T_STMT_HISTORY = -102
T_INSTRUMENTS = -103
T_DIGEST_SUMMARY = -104
T_DIGEST_HISTORY = -105
T_DIGEST_EVICTED = -106

HISTORY_CAP = 1024  # stmtEventsHistoryElemMax-style bound (default; the
#                     tidb_tpu_perfschema_history_cap sysvar re-sizes it)


def _col(i: int, name: str, tp: int, flen: int = 64) -> ColumnInfo:
    return ColumnInfo(id=i + 1, name=name, offset=i,
                      field_type=FieldType(tp, 0, flen, -1))


_STMT_COLS = [
    ("THREAD_ID", my.TypeLonglong), ("EVENT_ID", my.TypeLonglong),
    ("EVENT_NAME", my.TypeVarchar), ("SQL_TEXT", my.TypeBlob),
    ("TIMER_START", my.TypeLonglong), ("TIMER_END", my.TypeLonglong),
    ("TIMER_WAIT", my.TypeLonglong), ("ROWS_SENT", my.TypeLonglong),
    ("ROWS_AFFECTED", my.TypeLonglong), ("ERRORS", my.TypeLonglong),
    ("MESSAGE_TEXT", my.TypeVarchar),
    # per-statement execution details: columnar channel attribution +
    # device-kernel tallies (the session's always-on per-thread counters)
    ("EXECUTION_DETAIL", my.TypeBlob),
]


def _stmt_table(tid: int, name: str) -> TableInfo:
    return TableInfo(id=tid, name=name,
                     columns=[_col(i, n, tp)
                              for i, (n, tp) in enumerate(_STMT_COLS)])


# the per-digest resource vocabulary rolled up from the per-statement
# tallies — column name → tally key. One table drives the summary
# columns, the row rendering, AND the reconciliation contract (each
# column sums the exact per-statement deltas, so per-digest sums equal
# the flat global counters for any workload the store ran alone).
RESOURCE_COLS = (
    ("KERNEL_DISPATCHES", "kernel_dispatches"),
    ("KERNEL_DISPATCH_US", "kernel_dispatch_us"),
    ("READBACKS", "readbacks"),
    ("READBACK_BYTES", "readback_bytes"),
    ("JIT_HITS", "jit_hits"),
    ("JIT_MISSES", "jit_misses"),
    ("COLUMNAR_HITS", "columnar_hits"),
    ("COLUMNAR_FALLBACKS", "columnar_fallbacks"),
    ("COLUMNAR_PARTIALS", "columnar_partials"),
    ("PLANE_CACHE_HITS", "plane_cache_hits"),
    ("PLANE_CACHE_MISSES", "plane_cache_misses"),
    ("BACKOFF_RETRIES", "backoff_retries"),
    ("BACKOFF_MS", "backoff_ms"),
    ("SESSION_RETRIES", "session_retries"),
    ("DEGRADED_DEVICE", "degraded_device"),
    ("DEGRADED_JOIN", "degraded_join"),
    ("DEGRADED_COMBINE", "degraded_combine"),
)


def _digest_table(tid: int, name: str) -> TableInfo:
    cols = [
        ("SUMMARY_BEGIN_TIME", my.TypeLonglong, 21),
        ("SUMMARY_END_TIME", my.TypeLonglong, 21),
        ("DIGEST", my.TypeVarchar, 64),
        ("PLAN_DIGEST", my.TypeVarchar, 64),
        ("DIGEST_TEXT", my.TypeBlob, 1024),
        ("EXEC_COUNT", my.TypeLonglong, 21),
        ("ERRORS", my.TypeLonglong, 21),
        ("SUM_LATENCY_MS", my.TypeDouble, 22),
        ("AVG_LATENCY_MS", my.TypeDouble, 22),
        ("MIN_LATENCY_MS", my.TypeDouble, 22),
        ("MAX_LATENCY_MS", my.TypeDouble, 22),
        ("P95_LATENCY_MS", my.TypeDouble, 22),
        ("ROWS_SENT", my.TypeLonglong, 21),
        ("ROWS_AFFECTED", my.TypeLonglong, 21),
    ] + [(n, my.TypeLonglong, 21) for n, _k in RESOURCE_COLS] + [
        # top kernel signature by accumulated device time — rolled up
        # from the same per-statement kprof.* tallies the columns above
        # come from (kernel profiler, tidb_tpu.profiler)
        ("PROFILE", my.TypeVarchar, 160),
        ("FIRST_SEEN", my.TypeLonglong, 21),
        ("LAST_SEEN", my.TypeLonglong, 21),
        ("QUERY_SAMPLE_TEXT", my.TypeBlob, 1024),
        ("PLAN_SAMPLE", my.TypeBlob, 1024),
    ]
    return TableInfo(id=tid, name=name,
                     columns=[_col(i, n, tp, fl)
                              for i, (n, tp, fl) in enumerate(cols)])


def table_infos() -> list[TableInfo]:
    return [
        _stmt_table(T_STMT_CURRENT, "events_statements_current"),
        _stmt_table(T_STMT_HISTORY, "events_statements_history"),
        TableInfo(id=T_INSTRUMENTS, name="setup_instruments", columns=[
            _col(0, "NAME", my.TypeVarchar, 128),
            _col(1, "ENABLED", my.TypeVarchar, 4),
            _col(2, "TIMED", my.TypeVarchar, 4),
        ]),
        _digest_table(T_DIGEST_SUMMARY,
                      "events_statements_summary_by_digest"),
        _digest_table(T_DIGEST_HISTORY,
                      "events_statements_summary_by_digest_history"),
        TableInfo(id=T_DIGEST_EVICTED,
                  name="events_statements_summary_evicted", columns=[
                      _col(0, "SUMMARY_BEGIN_TIME", my.TypeLonglong, 21),
                      _col(1, "SUMMARY_END_TIME", my.TypeLonglong, 21),
                      _col(2, "EVICTED_DIGESTS", my.TypeLonglong, 21),
                      _col(3, "EVICTED_EXEC_COUNT", my.TypeLonglong, 21),
                  ]),
    ]


class StatementEvent:
    __slots__ = ("thread_id", "event_id", "name", "sql_text", "t_start",
                 "t_end", "rows_sent", "rows_affected", "errors", "message",
                 "detail", "digest")

    def __init__(self, thread_id: int, event_id: int, sql_text: str,
                 digest: str = ""):
        self.thread_id = thread_id
        self.event_id = event_id
        self.name = "statement/sql/execute"
        self.sql_text = sql_text[:1024]
        self.t_start = time.perf_counter_ns()
        self.t_end = 0
        self.rows_sent = 0
        self.rows_affected = 0
        self.errors = 0
        self.message = ""
        self.detail = ""
        self.digest = digest       # statement digest (SHOW PROCESSLIST)

    def row(self) -> list[Datum]:
        wait = max(0, self.t_end - self.t_start) if self.t_end else 0
        return [Datum.i64(self.thread_id), Datum.i64(self.event_id),
                Datum.bytes_(self.name.encode()),
                Datum.bytes_(self.sql_text.encode()),
                Datum.i64(self.t_start), Datum.i64(self.t_end),
                Datum.i64(wait), Datum.i64(self.rows_sent),
                Datum.i64(self.rows_affected), Datum.i64(self.errors),
                Datum.bytes_(self.message.encode()) if self.message
                else NULL,
                Datum.bytes_(self.detail.encode()) if self.detail
                else NULL]


CURRENT_CAP = 512  # bounded like the history ring: threads come and go


# per-digest latency histogram bounds (ms) for the p95 estimate — a
# fixed log2 ladder so every entry costs one small int list, no
# per-observation allocation
_LAT_BOUNDS_MS = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                  128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0, 8192.0)


class DigestEntry:
    """One digest's aggregate within one summary window."""

    __slots__ = ("digest", "plan_digest", "norm_sql", "sample_sql",
                 "sample_plan", "exec_count", "errors", "sum_latency_ms",
                 "min_latency_ms", "max_latency_ms", "lat_buckets",
                 "rows_sent", "rows_affected", "res", "first_seen",
                 "last_seen")

    def __init__(self, digest: str, norm_sql: str, now: float):
        self.digest = digest
        self.plan_digest = ""
        self.norm_sql = norm_sql
        self.sample_sql = ""
        self.sample_plan = ""
        self.exec_count = 0
        self.errors = 0
        self.sum_latency_ms = 0.0
        self.min_latency_ms = float("inf")
        self.max_latency_ms = 0.0
        self.lat_buckets = [0] * (len(_LAT_BOUNDS_MS) + 1)
        self.rows_sent = 0
        self.rows_affected = 0
        self.res: dict[str, int] = {}
        self.first_seen = now
        self.last_seen = now

    def observe(self, latency_ms: float, rows_sent: int,
                rows_affected: int, error: bool, resources: dict,
                now: float) -> None:
        self.exec_count += 1
        if error:
            self.errors += 1
        self.sum_latency_ms += latency_ms
        if latency_ms < self.min_latency_ms:
            self.min_latency_ms = latency_ms
        if latency_ms > self.max_latency_ms:
            self.max_latency_ms = latency_ms
        for i, b in enumerate(_LAT_BOUNDS_MS):
            if latency_ms <= b:
                self.lat_buckets[i] += 1
                break
        else:
            self.lat_buckets[-1] += 1
        self.rows_sent += rows_sent
        self.rows_affected += rows_affected
        if resources:
            res = self.res
            for k, v in resources.items():
                if v:
                    res[k] = res.get(k, 0) + v
        self.last_seen = now

    def p95_latency_ms(self) -> float:
        """Upper bound of the bucket holding the 95th percentile (the
        +Inf bucket reports the observed max — exact for it)."""
        if not self.exec_count:
            return 0.0
        target = self.exec_count * 0.95
        cum = 0
        for i, c in enumerate(self.lat_buckets):
            cum += c
            if cum >= target:
                return _LAT_BOUNDS_MS[i] if i < len(_LAT_BOUNDS_MS) \
                    else self.max_latency_ms
        return self.max_latency_ms

    def device_time_us(self) -> int:
        return self.res.get("kernel_dispatch_us", 0)


class DigestSummary:
    """Windowed per-digest statement summary for one store.

    The CURRENT window aggregates statements since window_begin; when
    the refresh interval elapses the window rotates into a bounded
    history ring (the flush crosses the `summary/flush` failpoint — an
    injected fault DEFERS the rotation, extending the window, so
    accounting never loses a statement). Entry count is capped; evicted
    entries are counted (digests + their exec counts) per window so a
    capped summary still reconciles: recorded = Σ entries + evicted."""

    def __init__(self):
        self.lock = threading.Lock()
        self.enabled = True
        self.max_digests = 512
        self.refresh_interval_s = 1800.0
        self.history_size = 24
        self.window_begin = time.time()
        self.entries: "OrderedDict[str, DigestEntry]" = OrderedDict()
        self.evicted_digests = 0
        self.evicted_exec_count = 0
        # rotated windows: (begin, end, entries dict, evicted_digests,
        # evicted_exec_count)
        self.history: deque = deque(maxlen=self.history_size)

    # ---- configuration (sysvar appliers call these) ----

    def set_enabled(self, on: bool) -> None:
        with self.lock:
            self.enabled = on
            if not on:
                # the documented contract of the kill switch: off stops
                # holding (and a re-enable starts a fresh window)
                self.entries = OrderedDict()
                self.history.clear()
                self.evicted_digests = self.evicted_exec_count = 0
                self.window_begin = time.time()

    def set_max_digests(self, n: int) -> None:
        with self.lock:
            self.max_digests = max(1, n)
            while len(self.entries) > self.max_digests:
                self._evict_locked()

    def set_refresh_interval(self, seconds: float) -> None:
        with self.lock:
            self.refresh_interval_s = max(1.0, seconds)

    def set_history_size(self, n: int) -> None:
        with self.lock:
            self.history_size = max(1, n)
            self.history = deque(self.history, maxlen=self.history_size)

    # ---- recording ----

    def _evict_locked(self) -> None:
        _k, old = self.entries.popitem(last=False)
        self.evicted_digests += 1
        self.evicted_exec_count += old.exec_count
        from tidb_tpu import metrics
        metrics.counter("perfschema.digest_evicted").inc()

    def _maybe_rotate_locked(self, now: float) -> None:
        if now - self.window_begin < self.refresh_interval_s:
            return
        from tidb_tpu import failpoint, metrics
        if failpoint._active:
            try:
                failpoint.eval("summary/flush")
            except Exception:  # noqa: BLE001 — an injected flush fault
                # must never fail a statement or drop a window: defer
                # the rotation (the window extends) and count it
                metrics.counter("perfschema.digest_flush_errors").inc()
                return
        self.history.append((self.window_begin, now, self.entries,
                             self.evicted_digests,
                             self.evicted_exec_count))
        self.entries = OrderedDict()
        self.evicted_digests = self.evicted_exec_count = 0
        self.window_begin = now
        metrics.counter("perfschema.digest_windows_flushed").inc()

    def record(self, digest: str, norm_sql: str, sample_sql: str,
               plan_digest: str, sample_plan: str, latency_ms: float,
               rows_sent: int, rows_affected: int, error: bool,
               resources: dict) -> None:
        if not self.enabled or not digest:
            return
        from tidb_tpu import metrics
        now = time.time()
        with self.lock:
            # re-check under the lock: a statement racing the kill
            # switch must not insert into the just-cleared summary
            # (same discipline as PlaneCache.insert)
            if not self.enabled:
                return
            self._maybe_rotate_locked(now)
            e = self.entries.get(digest)
            if e is None:
                e = self.entries[digest] = DigestEntry(digest, norm_sql,
                                                       now)
                e.sample_sql = sample_sql[:1024]
                while len(self.entries) > self.max_digests:
                    self._evict_locked()
            else:
                self.entries.move_to_end(digest)   # cap evicts true LRU
            if plan_digest:
                e.plan_digest = plan_digest
                if sample_plan:
                    e.sample_plan = sample_plan[:1024]
            e.observe(latency_ms, rows_sent, rows_affected, error,
                      resources, now)
        metrics.counter("perfschema.digest_statements").inc()

    # ---- read surface ----

    def windows(self) -> list[tuple]:
        """(begin, end|None, entries snapshot, evicted_digests,
        evicted_exec) — history oldest-first, then the current window
        (end None). Rotation is applied lazily here too, so a long-idle
        store still rolls its window on read."""
        now = time.time()
        with self.lock:
            self._maybe_rotate_locked(now)
            out = [(b, en, dict(es), ed, ee)
                   for (b, en, es, ed, ee) in self.history]
            out.append((self.window_begin, None, dict(self.entries),
                        self.evicted_digests, self.evicted_exec_count))
        from tidb_tpu import metrics
        metrics.gauge("perfschema.digest_entries").set(
            sum(len(w[2]) for w in out))
        return out


class PerfSchema:
    """Per-store statement event store (perfschema.statementStmts)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._event_ids = itertools.count(1)
        self._history: deque[StatementEvent] = deque(maxlen=HISTORY_CAP)
        # latest statement per thread (MySQL keeps completed statements in
        # *_current until the thread's next one), LRU-bounded
        self._current: "OrderedDict[int, StatementEvent]" = OrderedDict()
        self.enabled = True
        # workload aggregation above the event ring
        self.digest_summary = DigestSummary()

    def set_history_cap(self, cap: int) -> None:
        """Re-bound the events_statements_history ring (the
        tidb_tpu_perfschema_history_cap sysvar): a shrink keeps the most
        recent events, like any ring re-size."""
        with self._lock:
            self._history = deque(self._history, maxlen=max(1, cap))

    def start_statement(self, thread_id: int, sql_text: str,
                        digest: str = "") -> StatementEvent | None:
        if not self.enabled:
            return None
        ev = StatementEvent(thread_id, next(self._event_ids), sql_text,
                            digest)
        with self._lock:
            self._current[thread_id] = ev
            self._current.move_to_end(thread_id)
            while len(self._current) > CURRENT_CAP:
                self._current.popitem(last=False)
        return ev

    def end_statement(self, ev: StatementEvent | None, rows_sent: int = 0,
                      rows_affected: int = 0, error: str = "",
                      detail: str = "") -> None:
        if ev is None:
            return
        # mutate + publish under the lock: rows() may be rendering this
        # very event through _current concurrently
        with self._lock:
            ev.t_end = time.perf_counter_ns()
            ev.rows_sent = rows_sent
            ev.rows_affected = rows_affected
            if error:
                ev.errors = 1
                ev.message = error
            ev.detail = detail[:1024]
            self._history.append(ev)

    def current_info(self, thread_id: int):
        """SHOW PROCESSLIST detail for one connection: (sql_text, digest,
        elapsed_s, running). While the statement runs (t_end unset)
        elapsed counts from its start; once it completed, from its end —
        MySQL's Time column semantics (seconds in the current state)."""
        with self._lock:
            ev = self._current.get(thread_id)
            if ev is None:
                return None, "", 0.0, False
            now = time.perf_counter_ns()
            running = ev.t_end == 0
            anchor = ev.t_start if running else ev.t_end
            return (ev.sql_text, ev.digest,
                    max(0.0, (now - anchor) / 1e9), running)

    # ---- virtual-table row providers ----

    def rows(self, table_id: int) -> list[list[Datum]]:
        if table_id == T_STMT_CURRENT:
            with self._lock:  # render under the lock: no torn rows
                return [e.row() for e in self._current.values()]
        if table_id == T_STMT_HISTORY:
            with self._lock:
                return [e.row() for e in self._history]
        if table_id == T_INSTRUMENTS:
            on = b"YES" if self.enabled else b"NO"
            return [[Datum.bytes_(b"statement/sql/execute"),
                     Datum.bytes_(on), Datum.bytes_(b"YES")]]
        if table_id == T_DIGEST_SUMMARY:
            w = self.digest_summary.windows()[-1]   # the current window
            return _digest_rows([w])
        if table_id == T_DIGEST_HISTORY:
            return _digest_rows(self.digest_summary.windows()[:-1])
        if table_id == T_DIGEST_EVICTED:
            out = []
            for begin, end, _es, ed, ee in self.digest_summary.windows():
                out.append([Datum.i64(int(begin)),
                            Datum.i64(int(end)) if end is not None
                            else NULL,
                            Datum.i64(ed), Datum.i64(ee)])
            return out
        return []


def _digest_rows(windows: list[tuple]) -> list[list[Datum]]:
    """Render digest-summary windows as table rows, hottest-window-order
    preserved (oldest window first, entries by last_seen within)."""
    out: list[list[Datum]] = []

    def _b(s: str) -> Datum:
        return Datum.bytes_(s.encode()) if s else NULL

    for begin, end, entries, _ed, _ee in windows:
        for e in sorted(entries.values(), key=lambda x: x.first_seen):
            row = [Datum.i64(int(begin)),
                   Datum.i64(int(end)) if end is not None else NULL,
                   _b(e.digest), _b(e.plan_digest), _b(e.norm_sql),
                   Datum.i64(e.exec_count), Datum.i64(e.errors),
                   Datum.f64(round(e.sum_latency_ms, 3)),
                   Datum.f64(round(e.sum_latency_ms
                                   / max(e.exec_count, 1), 3)),
                   Datum.f64(round(e.min_latency_ms, 3)
                             if e.exec_count else 0.0),
                   Datum.f64(round(e.max_latency_ms, 3)),
                   Datum.f64(round(e.p95_latency_ms(), 3)),
                   Datum.i64(e.rows_sent), Datum.i64(e.rows_affected)]
            row.extend(Datum.i64(e.res.get(key, 0))
                       for _n, key in RESOURCE_COLS)
            kprof = {k[6:]: v for k, v in e.res.items()
                     if k.startswith("kprof.")}
            if kprof:
                from tidb_tpu import profiler
                row.append(_b(profiler.top_signature(kprof)))
            else:
                row.append(NULL)
            row.extend([Datum.i64(int(e.first_seen)),
                        Datum.i64(int(e.last_seen)),
                        _b(e.sample_sql), _b(e.sample_plan)])
            out.append(row)
    return out


def apply_sysvars(store, values: dict) -> None:
    """Hydrate this store's perfschema knobs from persisted globals
    (bootstrap calls this on every restart path, exactly like the plane
    cache's budget/kill-switch hydration)."""
    from tidb_tpu.sessionctx import parse_bool_sysvar
    ps = perf_for(store)
    ds = ps.digest_summary

    def _int(name: str):
        raw = values.get(name)
        try:
            return int(raw.strip()) if raw else None
        except (ValueError, AttributeError):
            return None

    v = values.get("tidb_tpu_stmt_summary")
    if v is not None:
        ds.set_enabled(parse_bool_sysvar(v))
    n = _int("tidb_tpu_stmt_summary_max_digests")
    if n is not None:
        ds.set_max_digests(n)
    n = _int("tidb_tpu_stmt_summary_refresh_interval")
    if n is not None:
        ds.set_refresh_interval(float(n))
    n = _int("tidb_tpu_stmt_summary_history_size")
    if n is not None:
        ds.set_history_size(n)
    n = _int("tidb_tpu_perfschema_history_cap")
    if n is not None:
        ps.set_history_cap(n)


_schemas: "OrderedDict[str, PerfSchema]" = OrderedDict()
_schemas_lock = threading.Lock()


def perf_for(store) -> PerfSchema:
    with _schemas_lock:
        ps = _schemas.get(store.uuid())
        if ps is None:
            ps = _schemas[store.uuid()] = PerfSchema()
        # true LRU: evict the least-recently USED store, never a live one
        _schemas.move_to_end(store.uuid())
        while len(_schemas) > 128:
            _schemas.popitem(last=False)
        return ps


class VirtualTable(VirtualTableBase):
    """performance_schema table bound to its store's event registry."""

    def __init__(self, info: TableInfo, store):
        super().__init__(info, "performance_schema")
        self.store = store

    def rows(self):
        return perf_for(self.store).rows(self.id)
