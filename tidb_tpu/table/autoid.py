"""Batched auto-increment ID allocator.

Reference: meta/autoid/autoid.go — allocators grab a range of IDs from meta
in one txn (step batching) and hand them out from memory, refetching when
exhausted. Rebase() lifts the cursor when explicit values exceed it.
"""

from __future__ import annotations

import threading

from tidb_tpu.kv import run_in_new_txn
from tidb_tpu.meta import Meta

DEFAULT_STEP = 1000


class Allocator:
    def __init__(self, store, db_id: int, table_id: int, step: int = DEFAULT_STEP):
        self.store = store
        self.db_id = db_id
        self.table_id = table_id
        self.step = step
        self._lock = threading.Lock()
        self._base = 0
        self._end = 0

    def alloc(self) -> int:
        with self._lock:
            if self._base >= self._end:
                self._refill(self.step)
            self._base += 1
            return self._base

    def rebase(self, new_base: int) -> None:
        """Ensure future allocations exceed new_base (explicit INSERT values).

        Reserves a full step of headroom beyond new_base so sequential
        explicit values (bulk loads with ascending PKs) hit meta once per
        step, not once per row (meta/autoid/autoid.go Rebase)."""
        with self._lock:
            if new_base < self._base:
                return
            if new_base < self._end:
                self._base = new_base
                return

            def bump(txn):
                m = Meta(txn)
                cur = m.gen_auto_table_id(self.db_id, self.table_id, 0)
                target = max(new_base, cur)
                return m.gen_auto_table_id(self.db_id, self.table_id,
                                           target + self.step - cur)

            self._end = run_in_new_txn(self.store, True, bump)
            # base resumes at the meta cursor (end - step), NOT new_base:
            # if another allocator already pushed meta past new_base, ids
            # below the cursor may be outstanding elsewhere
            self._base = self._end - self.step

    def _refill(self, step: int) -> None:
        def grab(txn):
            return Meta(txn).gen_auto_table_id(self.db_id, self.table_id, step)

        end = run_in_new_txn(self.store, True, grab)
        self._base, self._end = end - step, end
