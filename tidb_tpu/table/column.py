"""Column helpers: defaults and value casting.

Reference: table/column.go (GetColDefaultValue, CastValue, CheckNotNull).
"""

from __future__ import annotations

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.model import ColumnInfo
from tidb_tpu.types import Datum, convert_datum, datum_from_py
from tidb_tpu.types.datum import Kind, NULL


def cast_value(d: Datum, col: ColumnInfo) -> Datum:
    """Cast a datum to the column type (INSERT/UPDATE path)."""
    return convert_datum(d, col.field_type)


def get_default_value(col: ColumnInfo) -> Datum:
    """Default for a column omitted from an INSERT."""
    if col.has_default:
        if col.default_value is None:
            return NULL
        dv = col.default_value
        if isinstance(dv, str) and dv.upper() == "CURRENT_TIMESTAMP" \
                and col.field_type.tp in (my.TypeTimestamp, my.TypeDatetime):
            import datetime
            from tidb_tpu.types.time_types import Time
            return Datum(Kind.TIME, Time(datetime.datetime.now().replace(microsecond=0),
                                         col.field_type.tp))
        return convert_datum(datum_from_py(dv), col.field_type)
    if my.has_auto_increment_flag(col.field_type.flag):
        return NULL  # filled by the allocator
    if my.has_not_null_flag(col.field_type.flag):
        raise errors.ExecError(
            f"Field '{col.name}' doesn't have a default value",
            code=1364)
    return NULL


def check_not_null(col: ColumnInfo, d: Datum) -> None:
    if d.kind == Kind.NULL and my.has_not_null_flag(col.field_type.flag) \
            and not my.has_auto_increment_flag(col.field_type.flag):
        raise errors.ExecError(f"Column '{col.name}' cannot be null", code=1048)
