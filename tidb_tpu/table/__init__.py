"""Row-level table abstraction over KV.

Reference: table/table.go:62 (Table interface), table/tables/tables.go,
table/tables/index.go (kvIndex), table/column.go, meta/autoid.
"""

from tidb_tpu.table.tables import Table, Index  # noqa: F401
from tidb_tpu.table.column import get_default_value, cast_value  # noqa: F401
from tidb_tpu.table.autoid import Allocator  # noqa: F401
