"""Table and Index implementations over KV.

Reference: table/tables/tables.go (AddRecord/RowWithCols/UpdateRecord/
RemoveRecord/IterRecords) and table/tables/index.go (kvIndex create/delete/
seek). Rows store every writable column except a pk-is-handle column (the
handle lives in the key); NULL columns are stored explicitly so schema-change
backfills can distinguish "missing" from NULL.
"""

from __future__ import annotations

from typing import Iterator

from tidb_tpu import errors, tablecodec as tc
from tidb_tpu.kv.union_store import OPT_PRESUME_KEY_NOT_EXISTS
from tidb_tpu.model import IndexInfo, SchemaState, TableInfo
from tidb_tpu.table.autoid import Allocator
from tidb_tpu.table.column import cast_value, check_not_null
from tidb_tpu.types import Datum, unflatten_datum
from tidb_tpu.types.datum import Kind, NULL


class Index:
    """Secondary index over KV. Unique index value = handle; non-unique key
    embeds the handle (tablecodec layout)."""

    def __init__(self, table: "Table", info: IndexInfo):
        self.table = table
        self.info = info

    def _values_for_row(self, row: list[Datum]) -> list[Datum]:
        return [row[c.offset] for c in self.info.columns]

    def _has_null(self, values: list[Datum]) -> bool:
        return any(v.kind == Kind.NULL for v in values)

    def create(self, txn, values: list[Datum], handle: int,
               backfill: bool = False) -> None:
        if self.info.unique and not self._has_null(values):
            key = tc.encode_index_key(self.table.id, self.info.id, values, None)
            existing = txn.get_or_none(key)
            if existing is not None:
                if backfill and int(existing) == handle:
                    return  # reorg re-scan or row indexed by a concurrent writer
                raise errors.KeyExistsError(
                    f"Duplicate entry for key '{self.info.name}'",
                    existing_handle=int(existing))
            txn.set(key, b"%d" % handle)
        else:
            # NULLs never collide in unique indexes (SQL semantics)
            key = tc.encode_index_key(self.table.id, self.info.id, values, handle)
            txn.set(key, b"0")

    def check_conflict(self, txn, values: list[Datum]) -> None:
        """Raise KeyExistsError (with the existing row's handle) if these
        values collide in a unique index — a pure read, no writes."""
        if not self.info.unique or self._has_null(values):
            return
        key = tc.encode_index_key(self.table.id, self.info.id, values, None)
        existing = txn.get_or_none(key)
        if existing is not None:
            raise errors.KeyExistsError(
                f"Duplicate entry for key '{self.info.name}'",
                existing_handle=int(existing))

    def delete(self, txn, values: list[Datum], handle: int) -> None:
        if self.info.unique and not self._has_null(values):
            key = tc.encode_index_key(self.table.id, self.info.id, values, None)
        else:
            key = tc.encode_index_key(self.table.id, self.info.id, values, handle)
        txn.delete(key)

    def iterate(self, retriever, start_values=None) -> Iterator[tuple[list[Datum], int]]:
        """Yield (column datums, handle) in index order."""
        prefix = tc.encode_index_seek_key(self.table.id, self.info.id)
        start = prefix if start_values is None else \
            tc.encode_index_key(self.table.id, self.info.id, start_values, None)
        end = prefix + b"\xff" * 9
        n = len(self.info.columns)
        for k, v in retriever.iterate(start, end):
            vals, suffix = tc.cut_index_key(k, n)
            if suffix:
                handle = tc.decode_handle_from_index_suffix(suffix)
            else:
                handle = int(v)
            yield vals, handle


class Table:
    """Reference: table/tables/tables.go memory+kv table implementation."""

    def __init__(self, info: TableInfo, store=None, db_id: int = 0):
        self.info = info
        self.id = info.id
        self.store = store
        self.db_id = db_id
        self._alloc = Allocator(store, db_id, info.id) if store is not None else None
        self.indices = [Index(self, ii) for ii in info.indices]
        self._write_layout_cache = None

    def _write_layout(self):
        """Cached (col_ids, offsets) of non-pk writable columns plus the
        encoded row-key prefix — recomputed when any column's schema state
        changes (online DDL mutates states in place mid-job). The bulk
        write path calls this per row; the token check is two tuples."""
        info = self.info
        token = tuple((c.id, c.state) for c in info.columns)
        cached = self._write_layout_cache
        if cached is not None and cached[0] == token:
            return cached[1]
        pk_col = info.pk_handle_column()
        ids, offsets = [], []
        for col in info.writable_columns():
            if pk_col is not None and col.id == pk_col.id:
                continue
            ids.append(col.id)
            offsets.append(col.offset)
        layout = (pk_col, ids, offsets, tc.table_record_prefix(self.id))
        self._write_layout_cache = (token, layout)
        return layout

    # ---- handles / auto id ----
    def alloc_handle(self) -> int:
        if self._alloc is None:
            raise errors.ExecError("table has no allocator (no store bound)")
        return self._alloc.alloc()

    def rebase_auto_id(self, v: int) -> None:
        if self._alloc is not None:
            self._alloc.rebase(v)

    # ---- writes ----
    def add_records(self, txn, rows: list[list[Datum]],
                    skip_unique_check: bool = False) -> int:
        """Bulk insert (LOAD DATA / bench loader / replication): when the
        per-row machinery buys nothing — unique checks skipped, no
        writable secondary index, pk-as-handle — build every KV pair in
        one tight loop and hand them to the buffer in one call. Falls
        back to per-row add_record otherwise. Reference shape:
        tablecodec.EncodeRow (tablecodec.go:113) called from a batched
        loader."""
        pk_col, col_ids, offsets, key_prefix = self._write_layout()
        writable_idx = any(
            i.info.state not in (SchemaState.NONE, SchemaState.DELETE_ONLY)
            for i in self.indices)
        if (not skip_unique_check or writable_idx or pk_col is None
                or not hasattr(txn, "set_many")):
            for row in rows:
                self.add_record(txn, row, skip_unique_check=skip_unique_check)
            return len(rows)
        import struct as _struct
        from tidb_tpu.codec import codec as _cdc
        from tidb_tpu.codec import number as _num
        from tidb_tpu.native import codecx as _cx
        pk_off = pk_col.offset
        # inline the comparable-int key pack and the native row encoder:
        # at bulk-load rates the wrapper layers are the hot path
        pack = _struct.Struct(">BQ").pack
        flag, mask, sign = _cdc.INT_FLAG, _num.U64_MASK, _num.SIGN_MASK
        enc_row = (tc.encode_row if _cx is None
                   else lambda ids, vals: _cx.encode_row(ids, vals))
        max_handle = None
        pairs = []
        try:
            for row in rows:
                h = row[pk_off].get_int()
                if max_handle is None or h > max_handle:
                    max_handle = h
                pairs.append(
                    (key_prefix + pack(flag, (h & mask) ^ sign),
                     enc_row(col_ids, [row[off] for off in offsets])))
        except Exception:
            if _cx is None:
                raise
            # native encoder hit an unsupported datum mid-batch: redo the
            # whole batch through the Python encoder (same bytes)
            pairs = [(key_prefix + pack(flag,
                                        (row[pk_off].get_int() & mask)
                                        ^ sign),
                      tc.encode_row(col_ids,
                                    [row[off] for off in offsets]))
                     for row in rows]
            max_handle = max(row[pk_off].get_int() for row in rows)
        if max_handle is not None:
            self.rebase_auto_id(max_handle)
        txn.set_many(pairs)
        return len(rows)

    def add_record(self, txn, row: list[Datum], handle: int | None = None,
                   skip_unique_check: bool = False,
                   eager_check: bool = False) -> int:
        """Insert a full row (already cast to column types, in column offset
        order including non-public columns as NULL). Returns the handle."""
        pk_col, col_ids, offsets, key_prefix = self._write_layout()
        if handle is None:
            if pk_col is not None:
                handle = row[pk_col.offset].get_int()
            else:
                handle = self.alloc_handle()
        elif self._alloc is not None and pk_col is None:
            self._alloc.rebase(handle)
        if pk_col is not None:
            self.rebase_auto_id(handle)

        # row key with duplicate detection. Default: PresumeKeyNotExists
        # lazy check (executor_write.go + union_store.go
        # markLazyConditionPair) — resolved at commit. eager_check forces a
        # real read NOW: INSERT IGNORE / ON DUPLICATE KEY UPDATE / REPLACE
        # must observe the conflict inside the statement to react to it
        # (executor_write.go:554 batchGetInsertKeys)
        key = key_prefix + tc.enc_handle(handle)
        if not skip_unique_check:
            if not eager_check:
                txn.set_option(OPT_PRESUME_KEY_NOT_EXISTS)
            try:
                txn.get(key)
                raise errors.KeyExistsError(
                    f"Duplicate entry '{handle}' for key 'PRIMARY'",
                    existing_handle=handle)
            except errors.KeyNotExistsError:
                pass
            finally:
                if not eager_check:
                    txn.del_option(OPT_PRESUME_KEY_NOT_EXISTS)
        if eager_check and not skip_unique_check:
            # callers that CATCH the duplicate error (IGNORE / ON
            # DUPLICATE / REPLACE) need the conflict detected before ANY
            # write lands in the txn buffer — otherwise the index entries
            # written before the raising one would commit dangling
            # (executor_write.go batchGetInsertKeys does the same
            # check-all-first pass)
            for idx in self.indices:
                if idx.info.state in (SchemaState.NONE,
                                      SchemaState.DELETE_ONLY):
                    continue
                idx.check_conflict(txn, idx._values_for_row(row))

        # index entries (only indexes in a writable state: online DDL)
        for idx in self.indices:
            if idx.info.state == SchemaState.NONE or idx.info.state == SchemaState.DELETE_ONLY:
                continue
            idx.create(txn, idx._values_for_row(row), handle)

        # pk handle lives in the key; everything else in the value
        values = [row[off] for off in offsets]
        txn.set(key, tc.encode_row(col_ids, values))
        return handle

    def remove_record(self, txn, handle: int, row: list[Datum]) -> None:
        [row] = self._offset_aligned(txn, handle, [row])  # before delete:
        #        hidden-column carry-over reads the stored row
        txn.delete(tc.encode_row_key(self.id, handle))
        for idx in self.indices:
            if idx.info.state == SchemaState.NONE:
                continue
            idx.delete(txn, idx._values_for_row(row), handle)

    def _offset_aligned(self, txn, handle: int, rows):
        """Public-ORDER rows → model-OFFSET-aligned full rows.

        Executor rows carry the statement's visible schema: one value per
        PUBLIC column, in public-list order. The write paths below index
        by model offset, which only coincides in steady state: during
        online DDL a half-added column holds the offset past the public
        width and a half-dropped one leaves a gap mid-row (F1 states;
        model.TableInfo offsets stay stable until the job finishes).
        Hidden writable columns get their STORED value carried through
        (falling back to the original default) — every write must
        preserve what the statement's schema cannot see, or the whole-row
        rewrite would drop it."""
        info = self.info
        # steady-state fast path, cached behind the same (id, state)
        # token _write_layout uses (per-row hot path on bulk writes)
        token = tuple((c.id, c.state) for c in info.columns)
        cached = getattr(self, "_align_cache", None)
        if cached is not None and cached[0] == token:
            pubs, identity = cached[1]
        else:
            pubs = info.public_columns()
            identity = len(pubs) == len(info.columns) and all(
                c.offset == i for i, c in enumerate(pubs))
            self._align_cache = (token, (pubs, identity))
        if identity:
            return rows
        stored = None
        out = []
        for row in rows:
            if len(row) == len(info.columns):
                out.append(row)   # already model-width (INSERT/REPLACE
                continue          # full rows carry non-public columns)
            full: list = [None] * len(info.columns)
            for pos, c in enumerate(pubs):
                full[c.offset] = row[pos]
            for c in info.columns:
                if full[c.offset] is None:
                    if stored is None:
                        try:
                            raw = txn.get(tc.encode_row_key(self.id, handle))
                            stored = tc.decode_row(raw)
                        except errors.KeyNotExistsError:
                            stored = {}   # no row value: defaults apply;
                            # any OTHER storage error must propagate, not
                            # silently rewrite hidden columns to defaults
                    v = stored.get(c.id)
                    full[c.offset] = (
                        unflatten_datum(v, c.field_type) if v is not None
                        else _missing_col_value(c))
            out.append(full)
        return out

    def update_record(self, txn, handle: int, old_row: list[Datum],
                      new_row: list[Datum], touched: list[bool] | None = None) -> None:
        info = self.info
        old_row, new_row = self._offset_aligned(txn, handle,
                                                [old_row, new_row])
        pk = info.pk_handle_column()
        if pk is not None:
            new_handle = new_row[pk.offset].get_int()
            if new_handle != handle:
                # the handle IS the row key: a PK change moves the row
                # (delete + insert, eagerly checked — the target handle
                # may be taken), like the reference's updateRecord
                # delete-then-add path for handle-changing updates
                self.remove_record(txn, handle, old_row)
                self.add_record(txn, new_row, eager_check=True)
                return
        for idx in self.indices:
            if idx.info.state in (SchemaState.NONE,):
                continue
            old_vals = idx._values_for_row(old_row)
            new_vals = idx._values_for_row(new_row)
            if any(a != b for a, b in zip(old_vals, new_vals)):
                idx.delete(txn, old_vals, handle)
                if idx.info.state != SchemaState.DELETE_ONLY:
                    idx.create(txn, new_vals, handle)
        pk_col = info.pk_handle_column()
        col_ids, values = [], []
        for col in info.writable_columns():
            if pk_col is not None and col.id == pk_col.id:
                continue
            col_ids.append(col.id)
            values.append(new_row[col.offset])
        txn.set(tc.encode_row_key(self.id, handle), tc.encode_row(col_ids, values))

    # ---- reads ----
    def row_with_cols(self, retriever, handle: int, cols=None) -> list[Datum]:
        """Decode one row; cols defaults to public columns. Values are
        unflattened to column FieldTypes (DATE vs DATETIME etc.)."""
        info = self.info
        cols = cols if cols is not None else info.public_columns()
        raw = retriever.get(tc.encode_row_key(self.id, handle))
        data = tc.decode_row(raw)
        pk_col = info.pk_handle_column()
        out = []
        for col in cols:
            if pk_col is not None and col.id == pk_col.id:
                out.append(Datum.u64(handle) if col.field_type.is_unsigned()
                           else Datum.i64(handle))
            elif col.id in data:
                out.append(unflatten_datum(data[col.id], col.field_type))
            else:
                out.append(_missing_col_value(col))
        return out

    def iter_records(self, retriever, start_handle: int | None = None,
                     cols=None) -> Iterator[tuple[int, list[Datum]]]:
        info = self.info
        cols = cols if cols is not None else info.public_columns()
        pk_col = info.pk_handle_column()
        if start_handle is None:
            start, end = tc.encode_record_range(self.id)
        else:
            start, end = tc.handle_range_keys(self.id, start_handle, (1 << 63) - 1)
        for k, v in retriever.iterate(start, end):
            _tid, handle = tc.decode_row_key(k)
            data = tc.decode_row(v)
            row = []
            for col in cols:
                if pk_col is not None and col.id == pk_col.id:
                    row.append(Datum.u64(handle) if col.field_type.is_unsigned()
                               else Datum.i64(handle))
                elif col.id in data:
                    row.append(unflatten_datum(data[col.id], col.field_type))
                else:
                    row.append(_missing_col_value(col))
            yield handle, row

def _missing_col_value(col) -> Datum:
    """Value for a row written before col existed: the column's original
    default (captured at ADD COLUMN time), else NULL. Reference:
    table/tables.go RowWithCols missing-column branch + column original
    default — this is what makes ADD COLUMN O(1) instead of a backfill."""
    if col.original_default is not None:
        from tidb_tpu.types import convert_datum, datum_from_py
        return convert_datum(datum_from_py(col.original_default), col.field_type)
    return NULL
