"""Shared base for virtual (in-memory) tables.

Reference: the virtual-table pattern of infoschema/tables.go and
perfschema/ — rows synthesized on every read, no KV behind them, clean
read-only errors on the write surface. The planner routes `virtual = True`
tables to MemTableExec with all filtering SQL-side."""

from __future__ import annotations


class VirtualTableBase:
    virtual = True

    def __init__(self, info, db_name: str):
        self.info = info
        self.id = info.id
        self.db_name = db_name
        self.indices = []

    # subclasses yield rows; retriever/cols are part of the Table read
    # protocol but meaningless here
    def rows(self):  # pragma: no cover - overridden
        return []

    def iter_records(self, retriever, start_handle=None, cols=None):
        for i, row in enumerate(self.rows()):
            yield i + 1, row

    # write surface: one implementation of the read-only contract
    def _read_only(self, *_a, **_k):
        from tidb_tpu import errors
        raise errors.ExecError(
            f"table {self.db_name}.{self.info.name} is read-only")

    add_record = _read_only
    update_record = _read_only
    remove_record = _read_only
