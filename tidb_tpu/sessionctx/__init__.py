"""Session variables: per-connection state + the sysvar table.

Reference: sessionctx/variable/ (SessionVars, sysvar.go's 626-line table,
varsutil). A working subset of the MySQL sysvar table plus the engine's own
tunables (tidb_distsql_scan_concurrency, sessionctx/variable/sysvar.go:591).
"""

from __future__ import annotations

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.types import Datum

# name → default (all values kept as strings, MySQL-style)
SYSVAR_DEFAULTS: dict[str, str] = {
    "autocommit": "1",
    "auto_increment_increment": "1",
    "auto_increment_offset": "1",
    "character_set_client": "utf8",
    "character_set_connection": "utf8",
    "character_set_results": "utf8",
    "character_set_server": "utf8",
    "collation_connection": "utf8_general_ci",
    "collation_database": "utf8_bin",
    "collation_server": "utf8_bin",
    "default_storage_engine": "InnoDB",
    "interactive_timeout": "28800",
    "lower_case_table_names": "2",
    "max_allowed_packet": "67108864",
    "max_connections": "151",
    "net_buffer_length": "16384",
    "net_write_timeout": "60",
    "sql_mode": "",
    "sql_select_limit": "18446744073709551615",
    "time_zone": "SYSTEM",
    "tx_isolation": "REPEATABLE-READ",
    "transaction_isolation": "REPEATABLE-READ",   # MySQL 8 name, same var
    "version_comment": "TiDB-TPU Server",
    "version": my.SERVER_VERSION,
    "wait_timeout": "28800",
    # engine tunables (reference sessionctx/variable/sysvar.go:591-600)
    "tidb_distsql_scan_concurrency": "10",
    "tidb_snapshot": "",
    "tidb_skip_constraint_check": "0",
    # TPU coprocessor routing: cpu | tpu (this build's copr=tpu switch)
    "tidb_copr_backend": "cpu",
    # rows below which a TPU-routable request answers on CPU (device
    # dispatch-cost floor; ops.client.DISPATCH_FLOOR_ROWS derives from
    # this). Default tracks the measured CPU/device crossover on the
    # bench rig (~16k rows after the native row decoder sped the CPU
    # engine ~3x; bench.py measure_crossover re-measures every run).
    # The SAME floor routes executor-layer hash joins: at/above it a
    # single-int/float-key join runs the device build/probe kernels
    # (executors.HashJoinExec), below it the host numpy sort-merge.
    "tidb_tpu_dispatch_floor": "16384",
    # device join kill switch: 0 pins executor joins to the host numpy
    # path while scans/aggregates keep routing to the device
    "tidb_tpu_device_join": "1",
    # columnar result channel kill switch: 0 pins scan responses to the
    # row protocol (plane-aware consumers fall back to row drains) while
    # scans keep routing to the device
    "tidb_tpu_columnar_scan": "1",
    # device dictionary execution tier (copr.dictionary): string-key and
    # multi-key equi-joins route through the device build/probe kernels
    # on composite key-tuple codes over shared dictionary domains. 0 is
    # the kill switch — every such join takes the row-at-a-time dict
    # path (the parity oracle). GLOBAL-only, store-level.
    "tidb_tpu_device_dict": "1",
    # NDV ratio gate for the dictionary tier: a string column whose
    # distinct/rows ratio exceeds this bails to the dict path (counted
    # on copr.degraded_dict) and is refused registry registration
    # (copr.dict.rejected_ndv); columns under 64 distinct values never
    # trip it. GLOBAL-only, store-level.
    "tidb_tpu_dict_max_ndv": "0.5",
    # per-region columnar plane cache (copr.plane_cache) kill switch:
    # 0 re-packs every columnar_hint scan from the MVCC store (and
    # disables the in-proc TpuClient batch cache) — the parity oracle
    # for cache correctness. GLOBAL-only, store-level, like
    # tidb_tpu_columnar_scan.
    "tidb_tpu_plane_cache": "1",
    # plane-cache byte budget (LRU evicts past it); GLOBAL-only
    "tidb_tpu_plane_cache_bytes": "268435456",
    # HTAP freshness tier (copr.delta): region-side append-only delta
    # packs over cached base planes. Kill switch 0 restores the PR-5
    # behavior (any table commit orphans that table's cached planes;
    # per-table commit filtering stays on either way) — the parity
    # oracle for delta-merge correctness. Budget: when a pack's delta
    # exceeds this many rows, the next scan folds base+delta into a
    # fresh base entry and resets the pack (background re-pack).
    # GLOBAL-only, store-level, hydrated on restart.
    "tidb_tpu_delta_pack": "1",
    "tidb_tpu_delta_budget_rows": "4096",
    # mesh execution tier (ops.mesh) kill switch: 0 pins the partial-
    # aggregate combine and the join probe to the single-device kernels
    # (the first degradation rung) while everything else keeps routing.
    # GLOBAL-only and PROCESS-wide — the mesh spans physical chips, so
    # unlike the per-client switches it flips a module flag.
    "tidb_tpu_mesh": "1",
    # HBM governance tier (ops.membudget): the process-wide device
    # memory budget the ledger charges plane pins, dispatch working
    # sets, and join build/probe reservations against. 'auto' derives
    # the budget from the backend's reported memory limit (backends
    # without one — the CPU-XLA rig — resolve to unlimited); 0 is the
    # kill switch (unlimited: joins stay unpartitioned — the parity
    # oracle for the out-of-core route); an explicit byte count caps
    # the ledger and routes oversized join build sides into
    # radix-partitioned passes. GLOBAL-only and PROCESS-wide like
    # tidb_tpu_mesh.
    "tidb_tpu_hbm_budget_bytes": "auto",
    # micro-batch tier (ops.sched) kill switch: 0 pins every below-floor
    # statement to the solo route (CPU engine) — the parity oracle for
    # batched dispatch. GLOBAL-only, store-level, like the other tidb_tpu
    # client switches.
    "tidb_tpu_micro_batch": "1",
    # micro-batch gather window in ms: how long the first below-floor
    # statement of a cycle waits for peers before dispatching. 0 batches
    # only statements already queued. GLOBAL-only.
    "tidb_tpu_batch_window_ms": "2",
    # wire-server admission queue depth: accepted connections past
    # @@max_connections wait here for a free connection worker; past this
    # too they are rejected typed (ER 1040). GLOBAL-only.
    "tidb_tpu_conn_queue_depth": "64",
    # shared fan-out drain pool size (parallel.pool): ONE bounded worker
    # pool drains every statement's per-region coprocessor fan-out —
    # process-wide like tidb_tpu_mesh. GLOBAL-only.
    "tidb_tpu_drain_pool_size": "16",
    "tidb_slow_log_threshold": "300",   # ms; statements slower than this
    #                                     hit the tidb_tpu.slowlog logger
    # statement deadline in ms (0 = unlimited): every retry ladder of a
    # statement — region RPC, coprocessor worklist (including fan-out
    # worker threads), lock resolution, 2PC, txn replay — shares ONE
    # Backoffer (kv.backoff) carrying this deadline; exhaustion raises
    # DeadlineExceededError with the ladder history attached. Session
    # scope overrides per connection; SET GLOBAL changes the default.
    "tidb_tpu_max_execution_time": "0",
    # hierarchical statement tracing (tidb_tpu.tracing): 1 builds a span
    # tree for EVERY statement (slow-log detail gets the span summary);
    # 0 (default) builds spans only under EXPLAIN ANALYZE / TRACE
    "tidb_trace_enabled": "0",
    # statement-digest summary (perfschema
    # events_statements_summary_by_digest + TOP-SQL): kill switch, the
    # per-window digest cap (evictions counted in _summary_evicted), the
    # window length in seconds (TOP-SQL's time-bucket width), and how
    # many rotated windows the _history ring keeps. GLOBAL-only,
    # store-level, hydrated on restart like the plane-cache knobs.
    "tidb_tpu_stmt_summary": "1",
    "tidb_tpu_stmt_summary_max_digests": "512",
    "tidb_tpu_stmt_summary_refresh_interval": "1800",
    "tidb_tpu_stmt_summary_history_size": "24",
    # events_statements_history ring size (bounded; GLOBAL-only)
    "tidb_tpu_perfschema_history_cap": "1024",
    # slow-statement flight recorder (tidb_tpu.flight): 1 records every
    # top-level statement's span tree into a scratch buffer and RETAINS
    # it only when the statement crossed the slow-log threshold, died on
    # its deadline, or degraded through any tier — queryable via
    # information_schema.TIDB_TPU_SLOW_TRACES. 0 stops building spans
    # (tidb_trace_enabled / EXPLAIN ANALYZE still work) and clears the
    # ring. GLOBAL-only, store-level, hydrated on restart.
    "tidb_tpu_flight_recorder": "1",
    # retained slow traces kept per store (bounded ring). GLOBAL-only.
    "tidb_tpu_slow_trace_cap": "64",
    # per-entry span budget for retained traces: a pathological fan-out
    # (thousands of region tasks × kernel spans) is truncated to this
    # many spans — the root plus the slowest subtrees survive, the entry
    # stamps truncated=true in TRACE_JSON. 0 = unbounded. GLOBAL-only.
    "tidb_tpu_slow_trace_max_spans": "512",
    # metrics time-series recorder (metrics.timeseries): sampling
    # interval in ms and samples retained — the history behind
    # information_schema.TIDB_TPU_METRICS_HISTORY and the inspection
    # rules' evaluation windows. Process-wide (the registry is),
    # GLOBAL-only like tidb_tpu_drain_pool_size.
    "tidb_tpu_metrics_interval_ms": "1000",
    "tidb_tpu_metrics_history_cap": "240",
    # kernel-level continuous profiler (tidb_tpu.profiler): 1 publishes
    # every metered dispatch into the per-(kind, signature) registry
    # behind information_schema.TIDB_TPU_KERNEL_PROFILE and the
    # profiler.sig.* metric families; 0 clears the registry and retains
    # nothing. Cardinality bound: past max_signatures new signatures
    # fold into a per-kind ~overflow bucket. Process-wide (the dispatch
    # lock is), GLOBAL-only, hydrated on restart.
    "tidb_tpu_kernel_profile": "1",
    "tidb_tpu_profile_max_signatures": "256",
    # admission-queue wait deadline in ms: a connection queued behind
    # the admission gate is rejected typed (ER 1040, counted on
    # server.conn_queue_timeouts) after this long instead of waiting
    # forever on the client's own connect timeout. 0 = wait forever
    # (the pre-deadline behavior). GLOBAL-only, read live per sweep.
    "tidb_tpu_conn_queue_timeout_ms": "10000",
    "tidb_copr_batch_rows": "1048576",
}

# inspection-rule thresholds (tidb_tpu_inspection_*): per-deployment
# tuning surface over the static rule constants — GLOBAL-only,
# persisted, hydrated on bootstrap like the diagnostics knobs above.
# The inspection module owns the keys/defaults (one source of truth).
from tidb_tpu.inspection import SYSVAR_DEFAULTS as _INSPECTION_DEFAULTS

SYSVAR_DEFAULTS.update(_INSPECTION_DEFAULTS)


def parse_hbm_budget_spec(value) -> "str | int":
    """tidb_tpu_hbm_budget_bytes spec: 'auto' (derive from the
    backend), 0 (kill switch — unlimited), or an explicit byte count.
    THE one validator — the SET applier (which must validate jax-free)
    and ops.membudget.set_budget both resolve through it, so the
    accepted forms cannot drift. Raises ValueError."""
    s = str(value).strip().lower()
    if s == "auto":
        return "auto"
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"tidb_tpu_hbm_budget_bytes must be 'auto' or an integer "
            f">= 0, got {value!r}")
    if n < 0:
        raise ValueError("tidb_tpu_hbm_budget_bytes must be >= 0")
    return n


def parse_bool_sysvar(value: str) -> bool:
    """MySQL-style boolean sysvar parse ('1'/'on'/'true' → True) — the
    single parser for every consumer of a boolean global (client init,
    SET handling, bootstrap hydration must never drift apart)."""
    return value.strip().lower() in ("1", "on", "true")


def _store_sysvar_raw(store, name: str) -> str:
    """Store-level sysvar as a freshly constructed CLIENT must resolve
    it: the persisted/hydrated global when a session has bound this
    store, else the default. The session module is reached through
    sys.modules so client constructors (TpuClient, DistCoprClient) never
    import it — the one place the circular-import workaround lives."""
    import sys
    val = None
    sess_mod = sys.modules.get("tidb_tpu.session")
    if sess_mod is not None:
        val = sess_mod.store_global_var(store, name)
    return val if val is not None else SYSVAR_DEFAULTS[name]


def store_bool_sysvar(store, name: str) -> bool:
    return parse_bool_sysvar(_store_sysvar_raw(store, name))


def store_int_sysvar(store, name: str) -> int:
    """Clients resolve routing floors and budgets through this so a
    restart never silently reverts them."""
    try:
        return int(_store_sysvar_raw(store, name).strip())
    except ValueError:
        return int(SYSVAR_DEFAULTS[name])


def store_float_sysvar(store, name: str) -> float:
    """Ratio-shaped knobs (tidb_tpu_dict_max_ndv) resolve like the int
    floors: persisted global if set, else the default."""
    try:
        return float(_store_sysvar_raw(store, name).strip())
    except ValueError:
        return float(SYSVAR_DEFAULTS[name])


class SessionVars:
    """Reference: sessionctx/variable.SessionVars."""

    def __init__(self):
        self.systems: dict[str, str] = {}       # session-scope overrides
        self._globals: "GlobalVars | None" = None  # bound by the session
        self.users: dict[str, Datum] = {}       # @user_vars
        # statement-scoped diagnostics area: (level, code, message) rows
        # for SHOW WARNINGS; cleared at the start of each non-diagnostic
        # statement like MySQL's diagnostics area
        self.warnings: list[tuple[str, int, str]] = []
        self.current_db = ""
        self.autocommit = True
        self.in_txn = False                     # explicit BEGIN active
        self.connection_id = 0
        self.user = ""
        self.client_host = "localhost"  # peer address (privilege matching)
        self.last_insert_id = 0
        self.affected_rows = 0
        self.found_rows = 0
        self.status_flags = 0
        self.prepared: dict = {}                # name/id → prepared stmt
        self.prepared_id_gen = 0
        self.snapshot_ts: int | None = None     # tidb_snapshot time travel
        self.retry_limit = 10
        self.last_plan_from_cache = False       # prepared-stmt plan cache hit

    def get_system(self, name: str, globals_: "GlobalVars") -> str | None:
        name = name.lower()
        if name in self.systems:
            return self.systems[name]
        return globals_.get(name)

    def set_system(self, name: str, value: str) -> None:
        name = name.lower()
        self.systems[name] = value
        if name == "autocommit":
            self.autocommit = value.lower() in ("1", "on", "true")

    def distsql_concurrency(self) -> int:
        v = self.systems.get("tidb_distsql_scan_concurrency") \
            or (self._globals.get("tidb_distsql_scan_concurrency")
                if self._globals is not None else None)
        return int(v) if v else int(
            SYSVAR_DEFAULTS["tidb_distsql_scan_concurrency"])


class GlobalVars:
    """Global sysvar cache; persisted to mysql.global_variables once the
    bootstrap tables exist (session.go globalSysVar cache equivalent)."""

    def __init__(self):
        self.values = dict(SYSVAR_DEFAULTS)

    def get(self, name: str) -> str | None:
        return self.values.get(name.lower())

    def set(self, name: str, value: str) -> None:
        name = name.lower()
        if name not in self.values:
            raise errors.ExecError(f"Unknown system variable '{name}'")
        self.values[name] = value
