"""Metrics: process-wide counters and histograms, surfaced via SHOW STATUS.

Reference: the Prometheus instrumentation spread through metrics.go:20-45
(session phase histograms), distsql/metrics.go (query histogram + error
counters), executor/metrics.go, server/metrics.go. This registry keeps the
same shape (counters + bucketed histograms, dot-separated names) without
the Prometheus client dependency; SHOW STATUS is the pull endpoint.
"""

from __future__ import annotations

import threading

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """A value that goes up AND down (cache bytes, pinned bytes, entry
    counts) — Prometheus gauge semantics, SHOW STATUS renders the
    current value."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot_buckets(self) -> tuple[list[float], list[int], float, int]:
        """(upper bounds, CUMULATIVE counts per bound incl. +Inf, sum,
        count) — a consistent view taken under the lock, in the shape the
        Prometheus text exposition wants."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return list(self.buckets), cum, total_sum, total_count


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name)
            return m  # type: ignore[return-value]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name)
            return m  # type: ignore[return-value]

    def histogram(self, name: str, buckets=_DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(name, buckets)
            return m  # type: ignore[return-value]

    def snapshot(self) -> list[tuple[str, str]]:
        """Stable (name, value) rows for SHOW STATUS; histograms expand to
        _count / _sum / _avg."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: list[tuple[str, str]] = []
        for name, m in items:
            if isinstance(m, (Counter, Gauge)):
                out.append((name, str(m.value)))
            else:
                out.append((f"{name}_count", str(m.count)))
                out.append((f"{name}_sum", f"{m.sum:.6f}"))
                avg = m.sum / m.count if m.count else 0.0
                out.append((f"{name}_avg", f"{avg:.6f}"))
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# the process-wide default registry (metrics.go package-level collectors)
registry = Registry()


def counter(name: str) -> Counter:
    return registry.counter(name)


def _fmt_le(b: float) -> str:
    """Bucket bound label: integral bounds render bare ('1' not '1.0'),
    like the Prometheus client libraries."""
    return str(int(b)) if float(b) == int(b) else repr(float(b))


def _prom_name(name: str) -> tuple[str, str]:
    """(exposition name, label block) for one registry name. Names that
    sanitize cleanly ('.' → '_') keep their historical flat form —
    copr_degraded_mesh stays copr_degraded_mesh. Names whose dynamic
    suffix is not metric-name-safe (the profiler's kind|signature
    labels carry '|' and '/') split through the catalog's label model
    instead: profiler.sig.device_us.<label> renders as
    profiler_sig_device_us{kind="<label>"}. A non-family name with bad
    characters hard-sanitizes as the last resort."""
    import re
    pname = name.replace(".", "_")
    if re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", pname):
        return pname, ""
    from tidb_tpu.metrics import catalog
    fam, labels = catalog.split_labels(name)
    if labels and fam != name and '"' not in labels[len('kind="'):-1]:
        return fam.replace(".", "_"), "{" + labels + "}"
    return re.sub(r"[^a-zA-Z0-9_:]", "_", pname), ""


def render_text() -> str:
    """Prometheus text exposition of the default registry (the status
    HTTP port's /metrics; tidb-server/main.go:181 push-gateway analogue).
    Metric names sanitize '.' → '_' per the Prometheus data model.

    Counters emit one sample line; histograms emit the full conformant
    series per the text format: cumulative `_bucket{le="..."}` lines
    (one per configured bound plus the mandatory le="+Inf" == _count),
    then `_sum` and `_count`. The legacy `_avg` convenience line stays
    for SHOW STATUS parity but is emitted as its own gauge-style sample.
    """
    lines = []
    with registry._lock:
        items = sorted(registry._metrics.items())
    typed: set[str] = set()
    for name, m in items:
        pname, lbl = _prom_name(name)
        if isinstance(m, Counter):
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname}{lbl} {m.value}")
            continue
        if isinstance(m, Gauge):
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname}{lbl} {m.value}")
            continue
        bounds, cum, total_sum, total_count = m.snapshot_buckets()
        lines.append(f"# TYPE {pname} histogram")
        for b, c in zip(bounds, cum[:-1]):
            lines.append(f'{pname}_bucket{{le="{_fmt_le(b)}"}} {c}')
        lines.append(f'{pname}_bucket{{le="+Inf"}} {cum[-1]}')
        lines.append(f"{pname}_sum {total_sum:.6f}")
        lines.append(f"{pname}_count {total_count}")
        avg = total_sum / total_count if total_count else 0.0
        lines.append(f"{pname}_avg {avg:.6f}")
    return "\n".join(lines) + "\n"


def histogram(name: str) -> Histogram:
    return registry.histogram(name)


def quantile(hist: Histogram, q: float) -> float:
    """Approximate quantile from a histogram's cumulative buckets
    (linear interpolation inside the bucket, Prometheus
    histogram_quantile-style). 0.0 on an empty histogram; observations
    past the last bound clamp to it."""
    bounds, cum, _sum, count = hist.snapshot_buckets()
    if count == 0:
        return 0.0
    target = q * count
    lo_bound = 0.0
    lo_cum = 0
    for b, c in zip(bounds, cum[:-1]):
        if c >= target:
            span = c - lo_cum
            frac = (target - lo_cum) / span if span else 1.0
            return lo_bound + (b - lo_bound) * frac
        lo_bound, lo_cum = b, c
    return bounds[-1] if bounds else 0.0


def gauge(name: str) -> Gauge:
    return registry.gauge(name)
