"""Prometheus push client.

Reference: tidb-server/main.go:175-199 — pushMetric spawns
prometheusPushClient, which loops `push.AddFromGatherer(job, grouping,
addr, DefaultGatherer); sleep(interval)` forever, logging (never
raising) on push errors. Same contract here: a daemon thread PUTs the
registry's text exposition to the Pushgateway path
`/metrics/job/<job>/instance/<instance>` on a fixed interval; a zero
interval or empty address disables the client (main.go:177-180).

The transport is injectable so tests run against an in-process HTTP
server (this image has no network egress).
"""

from __future__ import annotations

import logging
import threading

from tidb_tpu import metrics

_log = logging.getLogger("tidb_tpu.metrics.push")


def _default_transport(url: str, body: bytes) -> None:
    import urllib.request
    req = urllib.request.Request(
        url, data=body, method="PUT",
        headers={"Content-Type": "text/plain; version=0.0.4"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        if resp.status >= 300:
            raise IOError(f"pushgateway returned {resp.status}")


def push_once(addr: str, job: str = "tidb-tpu",
              instance: str | None = None, transport=None) -> bool:
    """One push; returns success. Errors are logged, not raised
    (prometheusPushClient logs and keeps looping)."""
    if instance is None:
        import socket
        instance = socket.gethostname()
    url = f"http://{addr}/metrics/job/{job}/instance/{instance}"
    body = metrics.render_text().encode()
    try:
        (transport or _default_transport)(url, body)
        return True
    except Exception as e:  # noqa: BLE001 — push must never take the db down
        _log.error("could not push metrics to Prometheus Pushgateway: %s",
                   e)
        return False


def start_push_client(addr: str, interval_s: float,
                      job: str = "tidb-tpu", transport=None,
                      stop_event: threading.Event | None = None):
    """Spawn the push loop (pushMetric, main.go:175). Returns the thread,
    or None when disabled (empty addr / non-positive interval)."""
    if not addr or interval_s <= 0:
        _log.info("disable Prometheus push client")
        return None
    stop = stop_event or threading.Event()

    def loop():
        while not stop.is_set():
            push_once(addr, job=job, transport=transport)
            stop.wait(interval_s)

    t = threading.Thread(target=loop, name="metrics-push", daemon=True)
    t.stop_event = stop
    t.start()
    _log.info("start Prometheus push client with server addr %s and "
              "interval %.1fs", addr, interval_s)
    return t
