"""In-process metrics time series: the queryable history behind
information_schema.TIDB_TPU_METRICS_HISTORY.

Reference: TiDB's metrics schema (infoschema/metrics_schema.go +
metrics_table.go) answers `SELECT` over Prometheus range queries so
operators diagnose through SQL; here there is no Prometheus server, so a
lock-cheap recorder samples the process registry itself on a fixed
interval into a bounded ring of (timestamp, {name: value}) snapshots.
`SELECT` over the history table then replaces eyeballing two /metrics
scrapes and diffing by hand — the rate/delta columns are computed
between adjacent samples at read time.

Design rules:

* NO background thread in library mode. Sampling is lazy:
  `maybe_sample()` is one monotonic-clock compare on the fast path
  (statement end calls it), and the diagnostics tables force a sample
  at read time so a SELECT always sees a fresh bucket. A quiesced
  LIBRARY process holds no timer. DAEMON mode is the one exception: a
  serving wire server registers with `ticker_attach()` and a single
  background sampler thread keeps the ring warm between statements —
  an idle server still accrues TIDB_TPU_METRICS_HISTORY buckets, so
  "what happened while nothing ran" is answerable. The thread exits as
  soon as the last server detaches (ticker_detach at Server.close).
* Bounded: the ring keeps `cap` samples (SET GLOBAL
  tidb_tpu_metrics_history_cap); one sample is a plain dict of
  ~a-few-hundred floats, so the whole history is a few MB at worst.
* Histograms sample as two numeric series (`name_count`, `name_sum`) —
  both monotonic, so rate/delta work the same as for counters.
* Derived gauges: some utilization figures only exist BETWEEN two
  samples (device busy fraction = Δbusy_us / Δwall). The recorder
  computes them at sample time and publishes them as real registry
  gauges too, so /metrics and the SQL surface agree.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tidb_tpu import metrics
from tidb_tpu.metrics import Counter, Gauge, Histogram

DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAP = 240


class _Sample:
    __slots__ = ("ts", "mono", "values")

    def __init__(self, ts: float, mono: float, values: dict):
        self.ts = ts          # wall clock (rendered in the SQL surface)
        self.mono = mono      # monotonic (rate denominators)
        self.values = values  # name → (type_char, float)


# type chars kept per sampled series: c=counter, g=gauge, h=histogram
# (histogram _count/_sum series carry 'h' so the SQL surface can show
# their family type while still rating them like counters)
_MONOTONIC = ("c", "h")


def _registry_values() -> dict:
    """One consistent-enough walk of the process registry: each metric's
    own lock makes its value internally consistent; cross-metric skew is
    inherent to any scrape and fine for diagnostics."""
    with metrics.registry._lock:
        items = list(metrics.registry._metrics.items())
    out: dict = {}
    for name, m in items:
        if isinstance(m, Counter):
            out[name] = ("c", float(m.value))
        elif isinstance(m, Gauge):
            out[name] = ("g", float(m.value))
        elif isinstance(m, Histogram):
            out[name + "_count"] = ("h", float(m.count))
            out[name + "_sum"] = ("h", float(m.sum))
    return out


class MetricsRecorder:
    """Bounded ring of registry snapshots with lazy interval sampling."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 cap: int = DEFAULT_CAP):
        self.interval_s = max(0.01, float(interval_s))
        self._lock = threading.Lock()
        self._ring: deque[_Sample] = deque(maxlen=max(2, int(cap)))
        self._last_mono = 0.0

    # ---- configuration (sysvar appliers) ----

    def set_interval(self, seconds: float) -> None:
        with self._lock:
            self.interval_s = max(0.01, float(seconds))

    def set_cap(self, n: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(2, int(n)))

    @property
    def cap(self) -> int:
        return self._ring.maxlen or 0

    # ---- sampling ----

    def maybe_sample(self) -> bool:
        """Take a sample if the interval elapsed. The miss path is one
        monotonic read + one float compare — cheap enough for every
        statement end."""
        now = time.monotonic()
        if now - self._last_mono < self.interval_s:
            return False
        return self.sample(now)

    def sample(self, mono: float | None = None,
               min_interval_s: float = 0.001) -> bool:
        """Force a sample. `min_interval_s` is the spacing floor below
        which the call coalesces into the previous sample: direct
        callers (tests, inspection) keep the 1 ms default; READ-TIME
        forcing (the history table) passes the configured interval, so
        an operator polling the diagnostics tables during an incident
        refreshes the ring at the designed cadence instead of
        compressing the sample-count windows (and evicting real
        history) with every SELECT."""
        mono = time.monotonic() if mono is None else mono
        with self._lock:
            if mono - self._last_mono < min_interval_s:
                return False        # coalesce
            prev = self._ring[-1] if self._ring else None
            self._last_mono = mono
        # the registry walk and derived-gauge math run OUTSIDE the
        # recorder lock: sampling must never serialize statement ends
        values = _registry_values()
        _apply_derived(prev, mono, values)
        sample = _Sample(time.time(), mono, values)
        with self._lock:
            if self._ring and self._ring[-1].mono >= mono:
                # a concurrent sampler with a NEWER reservation finished
                # its walk first: appending this older snapshot would
                # put the ring out of monotonic order (negative DELTA
                # rows, inverted inspection windows) — drop it
                return False
            self._ring.append(sample)
        return True

    def sample_window(self, window: int) -> tuple[dict, float, float]:
        """Force a sample AND return (deltas, begin_ts, end_ts) over the
        trailing window ending at that fresh registry walk — ONE walk
        serves both, and the window's end is always CURRENT state (a
        sub-ms-coalesced forced sample can never hide a just-fired
        burst). The inspection rules read this."""
        mono = time.monotonic()
        with self._lock:
            prev = self._ring[-1] if self._ring else None
            # a new RING bucket only at the configured cadence (an
            # inspection poll loop must not compress the windows); the
            # deltas below always ride the fresh walk regardless
            fresh = mono - self._last_mono >= self.interval_s
            if fresh:
                self._last_mono = mono
        values = _registry_values()
        _apply_derived(prev, mono, values)
        if fresh:
            with self._lock:
                if not self._ring or self._ring[-1].mono < mono:
                    self._ring.append(_Sample(time.time(), mono, values))
        samples = self.samples()[-max(2, window):]
        if not samples:
            return {}, 0.0, 0.0
        return (self._deltas_from(samples[0], values), samples[0].ts,
                time.time())

    @staticmethod
    def _deltas_from(first: _Sample, last_values: dict) -> dict:
        """Monotonic series: increase first→last. Gauges: the LAST
        value (a saturation gauge is meaningful as a level, not a
        delta)."""
        out: dict = {}
        for name, (tc, v) in last_values.items():
            if tc in _MONOTONIC:
                out[name] = v - first.values.get(name, (tc, 0.0))[1]
            else:
                out[name] = v
        return out

    # ---- read surface ----

    def samples(self) -> list[_Sample]:
        with self._lock:
            return list(self._ring)

    def series(self, name: str) -> list[tuple[float, float]]:
        """(wall ts, value) for one sampled series, oldest first."""
        return [(s.ts, s.values[name][1]) for s in self.samples()
                if name in s.values]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_mono = 0.0


def _apply_derived(prev: _Sample | None, mono: float,
                   values: dict) -> None:
    """Between-sample utilization gauges, published into both the new
    sample and the live registry:

    * device.busy_fraction — Δdevice.busy_us over the wall interval:
      the fraction of the window the (serialized) device executed a
      program, i.e. "device saturated" vs "host stalled".
    * copr.drain_pool.worker_utilization — Δcopr.drain_pool.busy_us
      over interval × pool size: how busy the shared fan-out drain
      workers were.
    """
    if prev is None:
        return
    dt_us = (mono - prev.mono) * 1e6
    if dt_us <= 0:
        return

    def delta(name: str) -> float:
        cur = values.get(name)
        if cur is None:
            return 0.0
        return cur[1] - prev.values.get(name, (cur[0], 0.0))[1]

    busy = min(1.0, max(0.0, delta("device.busy_us") / dt_us))
    metrics.gauge("device.busy_fraction").set(round(busy, 6))
    values["device.busy_fraction"] = ("g", round(busy, 6))

    size = values.get("copr.drain_pool.size", ("g", 0.0))[1]
    if size > 0:
        util = min(1.0, max(
            0.0, delta("copr.drain_pool.busy_us") / (dt_us * size)))
        metrics.gauge("copr.drain_pool.worker_utilization").set(
            round(util, 6))
        values["copr.drain_pool.worker_utilization"] = ("g",
                                                        round(util, 6))


# the process recorder (the registry it samples is process-wide too)
recorder = MetricsRecorder()


# ---------------------------------------------------------------------------
# daemon-mode ticker: gated on a wire server being up. Library embeds
# keep the zero-thread contract; a serving process samples on the
# configured cadence even while fully idle, so the history ring (and the
# inspection windows judged over it) never goes dark between statements.
# ---------------------------------------------------------------------------

_ticker_lock = threading.Lock()
_ticker_sources: set = set()          # live wire servers (by id token)
_ticker_thread: threading.Thread | None = None


def ticker_attach(source) -> None:
    """Register a serving wire server; starts the sampler thread on the
    first attach. Idempotent per source."""
    global _ticker_thread
    with _ticker_lock:
        _ticker_sources.add(id(source))
        if _ticker_thread is not None and _ticker_thread.is_alive():
            return
        _ticker_thread = threading.Thread(
            target=_ticker_loop, name="tidb-metrics-ticker", daemon=True)
        _ticker_thread.start()


def ticker_detach(source) -> None:
    """Deregister a server; the sampler thread exits on its next tick
    once no server remains (a library process returns to zero threads)."""
    with _ticker_lock:
        _ticker_sources.discard(id(source))


def ticker_active() -> bool:
    with _ticker_lock:
        return bool(_ticker_sources) and _ticker_thread is not None \
            and _ticker_thread.is_alive()


def _ticker_loop() -> None:
    while True:
        with _ticker_lock:
            if not _ticker_sources:
                return
            interval = recorder.interval_s
        recorder.maybe_sample()
        # wake at most 4x per interval so a SET GLOBAL
        # tidb_tpu_metrics_interval_ms shrink takes effect promptly
        # without busy-spinning at long cadences
        time.sleep(max(0.01, min(interval / 4, 0.25)))


def history_rows() -> list[tuple]:
    """(ts, name, type_char, value, delta, rate_per_sec) rows, sample-
    major oldest-first — the TIDB_TPU_METRICS_HISTORY row source. Delta
    is vs the previous sample carrying the series (None for the first
    occurrence); gauges get value-to-value deltas too — what you want
    when eyeballing a queue-depth series — but rate stays NULL for
    them (rate is a monotonic-series notion)."""
    out: list[tuple] = []
    prev: _Sample | None = None
    for s in recorder.samples():
        for name in sorted(s.values):
            tc, v = s.values[name]
            delta = rate = None
            if prev is not None and name in prev.values:
                delta = v - prev.values[name][1]
                dt = s.mono - prev.mono
                if dt > 0 and tc in _MONOTONIC:
                    # rate only for monotonic series: a level gauge's
                    # value-to-value slope reads as nonsense next to
                    # counter rates
                    rate = delta / dt
            out.append((s.ts, name, tc, v, delta, rate))
        prev = s
    return out
