"""The metrics catalog: every metric this engine emits, with its type
and help text.

Reference: TiDB registers every collector centrally (metrics/metrics.go)
so the Grafana dashboards and docs can enumerate them; here the catalog
is the single source of truth three consumers share:

* information_schema.TIDB_TPU_METRICS renders TYPE/HELP from it,
* README.md's observability tables must list every entry (the
  conformance test cross-checks), and
* tests/test_metrics_catalog.py walks the source tree for
  metrics.counter/gauge/histogram call sites and fails on any name
  missing here — so a new metric cannot land silently undocumented.

Dynamic families (per-kind counters built with f-strings) register a
PREFIX entry; `lookup()` resolves exact names first, then the longest
matching prefix.
"""

from __future__ import annotations

# name → (type, help). Types: "counter" | "gauge" | "histogram".
CATALOG: dict[str, tuple[str, str]] = {
    # ---- coprocessor / columnar channel ----
    "copr.tpu.requests": ("counter", "Select requests routed to the device engine."),
    "copr.tpu.cpu_fallbacks": ("counter", "Device-routable requests answered by the CPU engine instead."),
    "copr.tpu.small_batched": ("counter", "Below-floor requests answered through a shared micro-batched dispatch."),
    "copr.tpu.small_to_cpu": ("counter", "Below-floor requests answered solo by the CPU engine."),
    "distsql.errors": ("counter", "Distsql select requests that errored."),
    "distsql.send_seconds": ("histogram", "Latency of one distsql select round trip."),
    "distsql.queries.": ("counter", "Distsql select requests by kind (select/index/topn...)."),
    "distsql.columnar_": ("counter", "Columnar-channel results by outcome (hits/fallbacks/partials, counted per region partial)."),
    # ---- plane cache ----
    "copr.plane_cache.hits": ("counter", "Region plane-cache lookups served from a cached pack."),
    "copr.plane_cache.misses": ("counter", "Region plane-cache lookups that had to re-pack."),
    "copr.plane_cache.evictions": ("counter", "Plane-cache entries evicted by the LRU byte budget."),
    "copr.plane_cache.invalidations_epoch": ("counter", "Plane-cache entries invalidated by a region epoch bump (split/merge)."),
    "copr.plane_cache.invalidations_version": ("counter", "Plane-cache entries invalidated by a newer visible data version."),
    "copr.plane_cache.kept_active": ("counter", "Stale-version entries the sweep KEPT because a live reader's snapshot (oldest_active_ts) still reads them verbatim."),
    "copr.plane_cache.bytes": ("gauge", "Bytes currently held by the region plane caches."),
    "copr.plane_cache.bytes_pinned": ("gauge", "Cached bytes currently pinned device-resident (HBM)."),
    "copr.plane_cache.entries": ("gauge", "Entries currently held by the region plane caches."),
    "copr.plane_cache.top_pinned_table": ("gauge", "Table id holding the most HBM-pinned cached bytes."),
    "copr.plane_cache.top_pinned_bytes": ("gauge", "HBM-pinned cached bytes of the top pinned table."),
    # ---- HTAP freshness tier (region delta packs) ----
    "copr.delta.appends": ("counter", "Commit row-sets appended to region delta packs instead of invalidating cached planes."),
    "copr.delta.merges": ("counter", "Scans answered by a device base+delta merge over cached planes."),
    "copr.delta.repacks": ("counter", "Delta packs folded into a fresh base entry after exceeding tidb_tpu_delta_budget_rows."),
    "copr.delta.drops": ("counter", "Delta packs dropped at the hard cap (no scan came to fold them)."),
    "copr.delta.bytes": ("gauge", "Bytes currently held by region delta packs."),
    "copr.delta.rows": ("gauge", "Delta rows currently held by region delta packs."),
    "copr.delta.entries": ("gauge", "Live region delta packs."),
    # ---- aggregate pushdown (columnar STATES channel) ----
    "copr.delta.decode_reuse": ("counter", "Delta merges that reused the pre-decoded appended-row planes of an unchanged pack generation."),
    # ---- device dictionary execution tier (copr.dictionary) ----
    "copr.dict.registered": ("counter", "Low-NDV string columns registered into a per-(table, column) global dictionary at pack time."),
    "copr.dict.rejected_ndv": ("counter", "String columns refused registry registration by the tidb_tpu_dict_max_ndv ratio gate."),
    "copr.dict.rebuilds": ("counter", "Global dictionaries rebuilt (schema-signature change, or the append-only union outgrew the live NDV across versions)."),
    "copr.dict.delta_entries": ("counter", "Dictionary entries shipped as response DELTAS (append-only codes make the known prefix implicit)."),
    "copr.dict.wire_bytes": ("counter", "Wire bytes of dictionary delta entries shipped in columnar responses."),
    "copr.dict.remaps": ("counter", "Join-domain unifications built (sorted union + per-dictionary remap tables)."),
    "copr.dict.remap_reuse": ("counter", "Join-domain unifications served from the cached remap (repeat joins skip the union)."),
    "copr.dict.device_remaps": ("counter", "Code-remap kernel dispatches: composite key-tuple codes built on device."),
    "copr.dict.join_keys": ("counter", "String/multi-key equi-joins routed through composite key-tuple codes."),
    "copr.dict.topn_plane": ("counter", "join-to-TopN orderings answered from planes by dictionary rank without materializing rows."),
    "copr.dict.distinct_plane": ("counter", "DISTINCT dedups answered over code planes without per-row codec keys."),
    "copr.dict.entries": ("gauge", "Entries currently held across all global dictionaries."),
    "copr.dict.dictionaries": ("gauge", "Live per-(table, column) global dictionaries."),
    # ---- micro-batch aggregate slot kind ----
    "sched.batched_agg_statements": ("counter", "Below-floor scalar-aggregate statements answered through a shared per-slot masked-reduction dispatch."),
    "sched.batched_topn_statements": ("counter", "Below-floor TopN statements answered through a shared per-slot lexsort dispatch (desc/limit lowered into the slot kernel)."),
    "copr.agg_states.partials": ("counter", "Region partials that answered a pushed-down aggregate as grouped partial STATES."),
    "copr.agg_states.rows": ("counter", "Rows aggregated region-side into grouped partial states."),
    "copr.agg_states.wire_bytes": ("counter", "Wire bytes of grouped partial-STATES payloads (group keys + state arrays)."),
    "copr.agg_rows.wire_bytes": ("counter", "Wire bytes of row-protocol partial-aggregate chunk responses."),
    # ---- near-data region execution (batched segmented states) ----
    "copr.states_batch.dispatches": ("counter", "Batched segmented states dispatches: all of a statement's region partials computed in one ragged kernel."),
    "copr.states_batch.serial_dispatches": ("counter", "Per-region states kernel dispatches (the serial path: below the per-statement floor, or degraded)."),
    "copr.states_batch.regions": ("counter", "Region segments computed by batched segmented states dispatches."),
    "copr.states_batch.rows": ("counter", "Rows aggregated through batched segmented states dispatches."),
    "copr.filter.batched_dispatches": ("counter", "Batched device filter dispatches: every deferred region's WHERE evaluated over cached planes in one ragged kernel (bit-packed masks read back)."),
    "copr.filter.batched_regions": ("counter", "Region segments filtered by batched device filter dispatches."),
    "copr.filter.batched_rows": ("counter", "Rows filtered on device through batched filter dispatches."),
    "copr.mesh.near_data_dispatches": ("counter", "Shard-owned near-data states dispatches: each region's segment computed on its RegionPlacement home shard in one mesh dispatch."),
    "copr.mesh.near_data_regions": ("counter", "Region segments computed by shard-owned near-data dispatches."),
    "copr.mesh.near_data_rows": ("counter", "Rows aggregated through shard-owned near-data dispatches."),
    # ---- expression pushdown (aggregate-argument planes) ----
    "copr.arg_plane.specs": ("counter", "Aggregate specs whose argument is an EXPRESSION lowered to a jitted arg-plane program inside the states dispatch."),
    "copr.arg_plane.rows": ("counter", "Rows aggregated through arg-plane programs (expression evaluated on device, never materialized as rows)."),
    # ---- degradation chain ----
    "copr.degraded_": ("counter", "Tier fallbacks by kind (device_to_cpu, join_to_numpy, combine_to_host, mesh, batch, states_to_host, rows...)."),
    "copr.degraded_filter_batch": ("counter", "Deferred-filter groups that fell off the batched device filter kernel onto the per-region host exprc rung (answers stay bit-identical)."),
    "copr.degraded_arg_plane": ("counter", "Statements whose arg-plane programs fell off the fused states kernel onto the per-region host exprc rung (answers stay bit-identical)."),
    # ---- mesh tier ----
    "copr.mesh.placements": ("counter", "Region-to-shard placements computed."),
    "copr.mesh.replacements": ("counter", "Region re-placements after an epoch bump."),
    "copr.mesh.dispatches": ("counter", "Mesh dispatches that published a shard-balance layout."),
    "copr.mesh.shard_rows_max": ("gauge", "Rows on the fullest shard of the last mesh combine."),
    "copr.mesh.shard_rows_mean": ("gauge", "Mean rows per shard of the last mesh combine."),
    "copr.mesh.shard_skew": ("gauge", "Max/mean per-shard row ratio of the last mesh combine (1.0 = balanced)."),
    # ---- region heat ----
    "copr.region_heat.read_rows": ("counter", "Rows read across all regions (heat tracker total)."),
    "copr.region_heat.read_bytes": ("counter", "Bytes read across all regions (heat tracker total)."),
    "copr.region_heat.write_rows": ("counter", "Rows written across all regions (heat tracker total)."),
    "copr.region_heat.write_bytes": ("counter", "Bytes written across all regions (heat tracker total)."),
    "copr.region_heat.regions": ("gauge", "Regions currently carrying access heat."),
    "copr.region_heat.top_region": ("gauge", "Region id with the highest decayed heat score."),
    "copr.region_heat.top_score": ("gauge", "Highest decayed region heat score."),
    # ---- shared drain pool ----
    "copr.drain_pool.tasks": ("counter", "Region drain tasks submitted to the shared pool."),
    "copr.drain_pool.queue_depth": ("gauge", "Drain tasks queued waiting for a pool worker."),
    "copr.drain_pool.size": ("gauge", "Configured worker bound of the shared drain pool."),
    "copr.drain_pool.workers": ("gauge", "Live drain-pool worker threads."),
    "copr.drain_pool.busy_us": ("counter", "Cumulative microseconds drain-pool workers spent running tasks."),
    "copr.drain_pool.queue_wait_seconds": ("histogram", "Time a drain task waited in the pool queue before a worker picked it up."),
    "copr.drain_pool.task_seconds": ("histogram", "Run time of one pooled region drain task."),
    "copr.drain_pool.worker_utilization": ("gauge", "Busy fraction of the drain pool over the last metrics-recorder window."),
    # ---- device / kernels ----
    "ops.kernel_dispatches": ("counter", "Device kernel dispatches."),
    "ops.kernel_dispatch_us": ("counter", "Cumulative host-observed device dispatch time (µs)."),
    "ops.readbacks": ("counter", "Device-to-host readbacks."),
    "ops.readback_bytes": ("counter", "Bytes read back device-to-host."),
    "ops.jit_cache_hits": ("counter", "Compiled-kernel cache hits."),
    "ops.jit_cache_misses": ("counter", "Compiled-kernel cache misses (trace+compile paid)."),
    "ops.kernel_seconds": ("histogram", "Wall time of one device dispatch + readback."),
    "device.busy_us": ("counter", "Cumulative microseconds the serialized device executed a program (metered inside kernels.dispatch_serial)."),
    "device.busy_fraction": ("gauge", "Fraction of the last metrics-recorder window the device was executing (device saturated vs host stalled)."),
    # ---- kernel-level continuous profiler (tidb_tpu.profiler) ----
    "profiler.sig.dispatches.": ("counter", "Kernel profiler: dispatches per (kind|signature) label."),
    "profiler.sig.device_us.": ("counter", "Kernel profiler: metered device microseconds per (kind|signature) label (sums to device.busy_us)."),
    "profiler.sig.trace_us.": ("counter", "Kernel profiler: device microseconds spent on dispatches that paid a jit trace+compile, per (kind|signature) label."),
    "profiler.sig.jit_misses.": ("counter", "Kernel profiler: jit-cache misses (retraces) per (kind|signature) label — the retrace-storm rule's evidence."),
    "profiler.sig.readback_bytes.": ("counter", "Kernel profiler: D2H readback bytes per (kind|signature) label."),
    "profiler.sig.h2d_bytes.": ("counter", "Kernel profiler: H2D transfer bytes per (kind|signature) label."),
    "profiler.sig.rows.": ("counter", "Kernel profiler: rows processed per (kind|signature) label."),
    # ---- HBM governance tier (ops.membudget) ----
    "device.hbm.budget": ("gauge", "Resolved HBM budget in bytes (tidb_tpu_hbm_budget_bytes; 0 = unlimited/kill switch)."),
    "device.hbm.reserved": ("gauge", "Bytes currently reserved by in-flight dispatch working sets (joins, batched dispatches, kernel inputs)."),
    "device.hbm.pinned": ("gauge", "Bytes of device-resident pinned planes charged to the ledger (plane cache + batch planes)."),
    "device.hbm.headroom": ("gauge", "Bytes a new reservation may take before crossing the budget (0 when unlimited)."),
    "device.hbm.over_budget": ("counter", "Reservations that proceeded past the configured HBM budget (the hbm-pressure rule's evidence)."),
    "device.hbm.hw.": ("gauge", "HBM ledger high-water marks by reservation kind (join/dispatch/...; 'pinned' tracks the pin ledger, 'total' the reserved+pinned combined peak)."),
    "device.hbm.estimate_error_ratio": ("gauge", "Allocator reconciliation: measured memory_stats() delta over the ledger estimate for the last reservation (0 when no backend stats)."),
    "copr.partitioned_joins": ("counter", "Joins whose build side exceeded the HBM headroom and took the radix-partitioned out-of-core route."),
    "copr.partitioned_passes": ("counter", "Partition executions of out-of-core joins (single-device passes, or per-shard partitions of the key-partitioned mesh probe)."),
    "copr.plane_cache.pin_skipped": ("counter", "Plane-cache admissions that skipped the device pin because pinning would cross the HBM budget."),
    # ---- out-of-core execution (ops.extsort + executor.window) ----
    "copr.spill.sorts": ("counter", "ORDER BY / window sorts whose key planes exceeded the HBM headroom and took the range-partitioned external sort."),
    "copr.spill.sort_passes": ("counter", "Device sort-pass dispatches of partitioned external sorts (each pass charges device.hbm.reserved)."),
    "copr.spill.plane_sorts": ("counter", "ORDER BY statements answered through the columnar plane sort (ops.extsort) instead of the row comparator."),
    "copr.spill.groupbys": ("counter", "Group-by statements whose states table exceeded the HBM headroom and ran as key-radix-partitioned states passes."),
    "copr.spill.groupby_passes": ("counter", "Per-partition states dispatches of spilling group-bys (each pass charges device.hbm.reserved)."),
    "copr.spill.windows": ("counter", "Window calls computed by the device segment-scan kernel over extsort-ordered planes."),
    "copr.spill.window_passes": ("counter", "window_scan dispatches (over-headroom scans split into spans of whole partitions; each pass charges device.hbm.reserved)."),
    "copr.spill.escalations": ("counter", "Mid-pass device/oom faults that escalated a partitioned operator to finer partitions (P*2) or a salted split."),
    "copr.spill.checkpoint_hits": ("counter", "Completed partitions whose recorded results were REPLAYED (not re-run) across an escalation — pass-level checkpointing."),
    "copr.spill.salted_splits": ("counter", "Two-level salted splits of partitions a key-disjoint split cannot shrink (single hot key / fully tied sort job)."),
    # ---- micro-batch scheduler ----
    "sched.batched_dispatches": ("counter", "Shared micro-batched device dispatches."),
    "sched.batched_statements": ("counter", "Statements answered through a shared batched dispatch."),
    "sched.batch_size": ("histogram", "Statements per shared batched dispatch."),
    "sched.slot_occupancy": ("histogram", "Filled fraction of the padded slot bucket per batched dispatch."),
    "sched.padding_waste": ("histogram", "Padded-slot fraction wasted per batched dispatch."),
    "sched.queue_depth": ("gauge", "Statements currently queued in the micro-batch gather window."),
    "sched.window_expiries": ("counter", "Statement deadlines that expired inside a micro-batch gather window or shared dispatch."),
    "sched.cross_stmt_states_batches": ("counter", "Segmented states dispatches that combined ≥ 2 concurrent below-floor statements through the gather window."),
    # ---- kv / backoff / txn ----
    "kv.backoff.": ("counter", "Backoffer sleeps by retry kind (plus kv.backoff.txn_retry for optimistic replays)."),
    "kv.backoff_exhausted": ("counter", "Statements whose backoff budget or deadline was exhausted."),
    "kv.txn_retries": ("counter", "Transaction-level optimistic retries."),
    "kv.txn_retry_exhausted": ("counter", "Transactions that exhausted the optimistic retry budget."),
    # ---- session / server ----
    "session.parse_seconds": ("histogram", "SQL parse phase latency."),
    "session.compile_seconds": ("histogram", "Plan build + optimize phase latency."),
    "session.run_seconds": ("histogram", "Execution phase latency."),
    "session.retries": ("counter", "Statement-history replays after a retryable commit conflict."),
    "session.retry_exhausted": ("counter", "Optimistic replays that exhausted the retry limit."),
    "session.statements.": ("counter", "Executed statements by AST type."),
    "server.connections_total": ("counter", "Wire connections served."),
    "server.queued_connections": ("counter", "Connections that waited in the admission queue."),
    "server.rejected_connections": ("counter", "Connections rejected typed (ER 1040) at the admission gate."),
    "server.conn_queue_timeouts": ("counter", "Queued connections rejected typed (ER 1040) after tidb_tpu_conn_queue_timeout_ms."),
    "server.conn_queue_depth": ("gauge", "Accepted connections currently waiting in the admission queue."),
    "server.slow_queries": ("counter", "Statements over tidb_slow_log_threshold."),
    # ---- perfschema / digest summary ----
    "perfschema.digest_statements": ("counter", "Statements rolled into the digest summary."),
    "perfschema.digest_entries": ("gauge", "Digest entries currently held (current + history windows)."),
    "perfschema.digest_evicted": ("counter", "Digest entries evicted by the per-window cap."),
    "perfschema.digest_windows_flushed": ("counter", "Digest summary windows rotated into history."),
    "perfschema.digest_flush_errors": ("counter", "Digest window rotations deferred by an injected flush fault."),
    # ---- flight recorder ----
    "tracing.slow_traces_retained": ("counter", "Statement traces retained by the flight recorder (slow / deadline / degraded)."),
    # ---- gc / compaction ----
    "gc.runs": ("counter", "MVCC garbage-collection runs."),
    "gc.versions_removed": ("counter", "MVCC versions removed by GC."),
    "gc.tick_errors": ("counter", "GC ticks that errored."),
    "gc.lease_lost": ("counter", "GC leader leases lost mid-run."),
    "compactor.runs": ("counter", "Background compaction runs."),
    "compactor.versions_removed": ("counter", "Versions removed by compaction."),
    # ---- failpoints ----
    "failpoint.triggers.": ("counter", "Failpoint activations by site name."),
}

# dynamic-family prefixes (f-string call sites register these)
PREFIXES = tuple(sorted((n for n in CATALOG if n.endswith(".")
                         or n.endswith("_")), key=len, reverse=True))


def split_labels(name: str) -> tuple[str, str]:
    """(family name, labels) for one emitted metric name — the label
    model of the SQL metrics surface: a dynamic-family member like
    `copr.degraded_mesh` renders as NAME `copr.degraded` with LABELS
    `kind="mesh"`, so TIDB_TPU_METRICS_HISTORY can aggregate across
    kinds (`GROUP BY NAME`). Exact catalog names (and names the catalog
    does not know) keep their full name and empty labels. Histogram
    series sampled as `_count`/`_sum` keep the stat suffix on the NAME —
    their stat already rides LABELS in the current-metrics table. An
    exact catalog entry that is ALSO a dynamic-family member (a
    documented kind like `copr.degraded_filter_batch`) still splits —
    the exact entry exists for its specific help text (`lookup`), not
    to exempt the kind from family aggregation."""
    if name in CATALOG and not any(
            name.startswith(p) and len(name) > len(p) for p in PREFIXES):
        return name, ""
    base = name
    for suffix in ("_count", "_sum"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            break
    for p in PREFIXES:
        if base.startswith(p) and len(base) > len(p):
            fam = p.rstrip("._")
            kind = base[len(p):]
            if base is not name:            # histogram stat suffix
                return name, f'kind="{kind}"'
            return fam, f'kind="{kind}"'
    return name, ""


def lookup(name: str) -> tuple[str, str] | None:
    """(type, help) for a metric name — exact first, then the longest
    matching dynamic-family prefix. Histogram series sampled as
    `name_count`/`name_sum` resolve to their family."""
    hit = CATALOG.get(name)
    if hit is not None:
        return hit
    for suffix in ("_count", "_sum"):
        if name.endswith(suffix):
            fam = CATALOG.get(name[: -len(suffix)])
            if fam is not None and fam[0] == "histogram":
                return fam
    for p in PREFIXES:
        if name.startswith(p):
            return CATALOG[p]
    return None
