"""SQL frontend: lexer + parser.

Reference: parser/ (lexer.go + parser.y goyacc grammar). Hand-written
recursive-descent/Pratt implementation; see parser/parser.py.
"""

from tidb_tpu.parser.parser import Parser, parse, parse_one  # noqa: F401
