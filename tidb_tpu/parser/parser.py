"""Recursive-descent / Pratt SQL parser.

Reference: parser/parser.y (goyacc LALR grammar, 5.3k lines) + parser/yy_parser.go.
This is a hand-written equivalent covering the engine's dialect: DDL
(CREATE/DROP/ALTER/TRUNCATE), DML (SELECT with joins/group/order/limit,
INSERT/REPLACE, UPDATE, DELETE), txn control, SET/USE/SHOW/EXPLAIN/ADMIN.
Operator precedence follows MySQL. Unsupported constructs raise ParseError
with the offending token position.
"""

from __future__ import annotations

from decimal import Decimal

from tidb_tpu import errors, mysqldef as my
from tidb_tpu import sqlast as ast
from tidb_tpu.parser import lexer as lx
from tidb_tpu.sqlast import Op
from tidb_tpu.types import Datum, datum_from_py
from tidb_tpu.types.datum import NULL, Kind as DKind
from tidb_tpu.types.field_type import FieldType, new_field_type

AGG_FUNCS = frozenset(("count", "sum", "avg", "min", "max", "group_concat",
                       "first_row"))

# window functions the engine executes (parser.y WindowFuncCall subset):
# rankings plus the frame reductions that ride the plane pipeline
WINDOW_FUNCS = frozenset(("row_number", "rank", "dense_rank",
                          "sum", "count", "min", "max"))


def _split_sysvar_scope(name: str) -> tuple[bool, str]:
    """'global.x' → (True, 'x'); 'session.x' → (False, 'x'); else (False, name)."""
    low = name.lower()
    if low.startswith("global."):
        return True, name[7:]
    if low.startswith("session."):
        return False, name[8:]
    return False, name


class Parser:
    """parser.New().Parse() equivalent; instances are reusable."""

    def parse(self, sql: str) -> list[ast.StmtNode]:
        self.sql = sql
        self.toks = lx.tokenize(sql)
        self.pos = 0
        self.param_markers: list[ast.ParamMarker] = []
        stmts: list[ast.StmtNode] = []
        while not self._at(lx.EOF):
            if self._try_op(";"):
                continue
            start = self.pos
            stmt = self._parse_statement()
            stmt.text = self._text_between(start)
            stmts.append(stmt)
            if not self._at(lx.EOF) and not self._try_op(";"):
                self._fail("expected ';' between statements")
        return stmts

    def parse_one(self, sql: str) -> ast.StmtNode:
        stmts = self.parse(sql)
        if len(stmts) != 1:
            raise errors.ParseError(f"expected a single statement, got {len(stmts)}")
        return stmts[0]

    # ---- token helpers ----
    def _cur(self) -> lx.Token:
        return self.toks[self.pos]

    def _next(self) -> lx.Token:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def _at(self, tp: str) -> bool:
        return self._cur().tp == tp

    def _at_kw(self, *kws: str) -> bool:
        return self._cur().is_kw(*kws)

    def _try_kw(self, *kws: str) -> bool:
        if self._at_kw(*kws):
            self.pos += 1
            return True
        return False

    def _expect_kw(self, *kws: str) -> str:
        if not self._at_kw(*kws):
            self._fail(f"expected {'/'.join(kws)}")
        return self._next().val  # type: ignore[return-value]

    # non-reserved words (lex as IDENT or KEYWORD depending on the list)
    def _at_word(self, *words: str) -> bool:
        t = self._cur()
        return t.tp in (lx.KEYWORD, lx.IDENT) \
            and str(t.val).upper() in words

    def _try_word(self, *words: str) -> bool:
        if self._at_word(*words):
            self.pos += 1
            return True
        return False

    def _expect_word(self, *words: str) -> str:
        if not self._at_word(*words):
            self._fail(f"expected {'/'.join(words)}")
        return str(self._next().val).upper()

    def _at_op(self, *ops: str) -> bool:
        t = self._cur()
        return t.tp == lx.OP and t.val in ops

    def _try_op(self, *ops: str) -> bool:
        if self._at_op(*ops):
            self.pos += 1
            return True
        return False

    def _expect_op(self, op: str) -> None:
        if not self._try_op(op):
            self._fail(f"expected {op!r}")

    def _ident(self, what: str = "identifier") -> str:
        t = self._cur()
        if t.tp == lx.IDENT:
            self.pos += 1
            return t.val  # type: ignore[return-value]
        # most keywords double as identifiers in practice (non-reserved)
        if t.tp == lx.KEYWORD and t.val not in ("SELECT", "FROM", "WHERE"):
            self.pos += 1
            return t.val.lower()  # type: ignore[union-attr]
        self._fail(f"expected {what}")

    def _fail(self, msg: str):
        t = self._cur()
        raise errors.ParseError(
            f"{msg} near {t.val!r} (token {self.pos}, byte {t.pos})")

    def _text_between(self, start_tok: int) -> str:
        start = self.toks[start_tok].pos
        end = self.toks[self.pos].pos if self.pos < len(self.toks) else len(self.sql)
        return self.sql[start:end].strip()

    # ---- statement dispatch ----
    def _parse_statement(self) -> ast.StmtNode:
        t = self._cur()
        if t.tp == lx.OP and t.val == "(":
            # (SELECT ...) [UNION ...] as a top-level statement
            return self._parse_select_or_union()
        if t.tp == lx.IDENT and str(t.val).upper() in ("BINLOG", "LOCK",
                                                       "UNLOCK"):
            return self._parse_ignored_stmt()
        if t.tp == lx.IDENT and str(t.val).upper() == "TRACE":
            # TRACE dispatches on the bare identifier (not a lexer
            # keyword) so columns/tables named `trace` keep parsing in
            # expressions — same pattern as BINLOG/LOCK above
            return self._parse_trace()
        if t.tp != lx.KEYWORD:
            self._fail("expected statement keyword")
        kw = t.val
        handlers = {
            "SELECT": self._parse_select_or_union,
            "INSERT": self._parse_insert,
            "REPLACE": self._parse_insert,
            "UPDATE": self._parse_update,
            "DELETE": self._parse_delete,
            "CREATE": self._parse_create,
            "DROP": self._parse_drop,
            "ALTER": self._parse_alter,
            "TRUNCATE": self._parse_truncate,
            "BEGIN": self._parse_begin,
            "START": self._parse_begin,
            "COMMIT": lambda: (self._next(), ast.CommitStmt())[1],
            "ROLLBACK": lambda: (self._next(), ast.RollbackStmt())[1],
            "USE": self._parse_use,
            "SET": self._parse_set,
            "SHOW": self._parse_show,
            "EXPLAIN": self._parse_explain,
            "DESCRIBE": self._parse_explain,
            "DESC": self._parse_explain,
            "ADMIN": self._parse_admin,
            "ANALYZE": self._parse_analyze,
            "LOAD": self._parse_load_data,
            "DO": self._parse_do,
            "KILL": self._parse_kill,
            "FLUSH": self._parse_flush,
            "GRANT": self._parse_grant,
            "REVOKE": self._parse_revoke,
            "PREPARE": self._parse_prepare,
            "EXECUTE": self._parse_execute,
            "DEALLOCATE": self._parse_deallocate,
            "BINLOG": self._parse_ignored_stmt,
            "LOCK": self._parse_ignored_stmt,
            "UNLOCK": self._parse_ignored_stmt,
        }
        h = handlers.get(kw)  # type: ignore[arg-type]
        if h is None:
            self._fail(f"unsupported statement {kw}")
        return h()

    # ================= SELECT =================

    def _parse_select_or_union(self) -> ast.StmtNode:
        """SELECT or (SELECT) [UNION [ALL] ...] with a trailing ORDER BY /
        LIMIT belonging to the whole union (parser.y UnionStmt / SubSelect
        productions, reference parser/parser.y)."""
        term, paren = self._parse_union_term()
        if not self._at_kw("UNION"):
            if paren:
                # (SELECT ...) [ORDER BY ...] [LIMIT ...] without UNION
                if self._try_kw("ORDER"):
                    self._expect_kw("BY")
                    term.order_by = self._parse_by_items()
                lim = self._parse_limit()
                if lim is not None:
                    term.limit = lim
            return term
        terms: list[tuple[ast.StmtNode, bool]] = [(term, paren)]
        seps: list[bool] = []  # distinct flag per UNION separator
        while self._try_kw("UNION"):
            if self._try_kw("ALL"):
                seps.append(False)
            else:
                self._try_kw("DISTINCT")
                seps.append(True)
            terms.append(self._parse_union_term())
        order_by: list[ast.ByItem] = []
        limit = None
        for i, (t, was_paren) in enumerate(terms):
            last = i == len(terms) - 1
            if isinstance(t, ast.SelectStmt) and not was_paren \
                    and (t.order_by or t.limit is not None):
                if not last:
                    self._fail("ORDER BY/LIMIT inside a UNION operand "
                               "requires parentheses")
                # trailing ORDER BY / LIMIT binds to the whole union
                order_by, limit = t.order_by, t.limit
                t.order_by, t.limit = [], None
        stmts = [t for t, _ in terms]
        # MySQL mixed ALL/DISTINCT: a DISTINCT separator dedups every
        # operand to its left — nest so operands after the LAST DISTINCT
        # keep duplicates
        if any(seps):
            k = max(i for i, d in enumerate(seps) if d)  # last distinct sep
            inner = ast.UnionStmt(selects=stmts[:k + 2], distinct=True)
            if k + 2 < len(stmts):
                u = ast.UnionStmt(selects=[inner] + stmts[k + 2:],
                                  distinct=False)
            else:
                u = inner
        else:
            u = ast.UnionStmt(selects=stmts, distinct=False)
        if not order_by and self._try_kw("ORDER"):
            self._expect_kw("BY")
            order_by = self._parse_by_items()
        if limit is None:
            limit = self._parse_limit()
        u.order_by = order_by
        u.limit = limit
        return u

    def _parse_union_term(self) -> tuple[ast.StmtNode, bool]:
        if self._at_op("("):
            self.pos += 1
            inner = self._parse_select_or_union()
            self._expect_op(")")
            return inner, True
        return self._parse_select(), False

    def _parse_select(self) -> ast.SelectStmt:
        self._expect_kw("SELECT")
        stmt = ast.SelectStmt()
        # select options may appear in any order (parser.y SelectStmtOpts),
        # but ALL and DISTINCT conflict (MySQL ER_WRONG_USAGE 1221)
        saw_all = False
        while True:
            if self._try_kw("STRAIGHT_JOIN"):
                stmt.straight_join = True   # keep the written join order
            elif self._try_kw("DISTINCT"):
                stmt.distinct = True
            elif self._try_kw("ALL"):
                saw_all = True
            else:
                break
        if saw_all and stmt.distinct:
            raise errors.TiDBError(
                "Incorrect usage of ALL and DISTINCT", code=1221)
        stmt.fields = self._parse_select_fields()
        if self._try_kw("FROM"):
            stmt.from_ = self._parse_table_refs()
        if self._try_kw("WHERE"):
            stmt.where = self._parse_expr()
        if self._try_kw("GROUP"):
            self._expect_kw("BY")
            stmt.group_by = self._parse_by_items()
        if self._try_kw("HAVING"):
            stmt.having = self._parse_expr()
        if self._try_kw("ORDER"):
            self._expect_kw("BY")
            stmt.order_by = self._parse_by_items()
        stmt.limit = self._parse_limit()
        if self._try_kw("FOR"):
            self._expect_kw("UPDATE")
            stmt.for_update = True
        elif self._try_kw("LOCK"):
            self._expect_kw("IN")
            self._expect_kw("SHARE")
            self._expect_kw("MODE")
            stmt.lock_in_share_mode = True
        return stmt

    def _parse_select_fields(self) -> list[ast.SelectField]:
        fields = []
        while True:
            if self._at_op("*"):
                self.pos += 1
                fields.append(ast.SelectField(wild_table=""))
            else:
                # qualified wildcard t.*
                save = self.pos
                if self._cur().tp == lx.IDENT and \
                        self.toks[self.pos + 1].tp == lx.OP and \
                        self.toks[self.pos + 1].val == "." and \
                        self.toks[self.pos + 2].tp == lx.OP and \
                        self.toks[self.pos + 2].val == "*":
                    tname = self._ident()
                    self.pos += 2
                    fields.append(ast.SelectField(wild_table=tname))
                else:
                    self.pos = save
                    expr = self._parse_expr()
                    as_name = ""
                    if self._try_kw("AS"):
                        as_name = self._ident_or_string()
                    elif self._cur().tp == lx.IDENT:
                        as_name = self._ident()
                    fields.append(ast.SelectField(expr=expr, as_name=as_name))
            if not self._try_op(","):
                return fields

    def _ident_or_string(self) -> str:
        if self._at(lx.STRING):
            return self._next().val  # type: ignore[return-value]
        return self._ident()

    def _parse_table_refs(self) -> ast.Join:
        left = self._parse_table_factor()
        node = ast.Join(left=left)
        while True:
            if self._try_op(","):
                right = self._parse_table_factor()
                node = ast.Join(left=node, right=right, tp="cross")
                continue
            tp = None
            if self._try_kw("JOIN") or (self._try_kw("INNER") and self._expect_kw("JOIN")):
                tp = "inner"
            elif self._try_kw("STRAIGHT_JOIN"):
                tp = "straight"
            elif self._at_kw("LEFT", "RIGHT"):
                side = self._next().val
                self._try_kw("OUTER")
                self._expect_kw("JOIN")
                tp = side.lower()  # type: ignore[union-attr]
            elif self._try_kw("CROSS"):
                self._expect_kw("JOIN")
                tp = "cross"
            if tp is None:
                return node
            right = self._parse_table_factor()
            on = None
            if self._try_kw("ON"):
                on = self._parse_expr()
            node = ast.Join(left=node, right=right, tp=tp, on=on)

    def _parse_table_factor(self) -> ast.Node:
        if self._try_op("("):
            if self._at_kw("SELECT"):
                # derived table: (SELECT ...) [AS] alias
                sub = self._parse_select_or_union()
                self._expect_op(")")
                as_name = ""
                if self._try_kw("AS"):
                    as_name = self._ident()
                elif self._cur().tp == lx.IDENT:
                    as_name = self._ident()
                return ast.TableSource(source=sub, as_name=as_name)
            inner = self._parse_table_refs()
            self._expect_op(")")
            return inner
        name = self._ident("table name")
        db = ""
        if self._try_op("."):
            db, name = name, self._ident("table name")
        tn = ast.TableName(name=name, db=db)
        as_name = ""
        if self._try_kw("AS"):
            as_name = self._ident()
        elif self._cur().tp == lx.IDENT:
            as_name = self._ident()
        # index hints: USE/FORCE/IGNORE INDEX|KEY (i1[, i2...])
        # (parser.y IndexHint :505-507); repeated hints accumulate
        while self._at_kw("USE", "FORCE", "IGNORE"):
            kind = self._next().val
            self._expect_kw("INDEX", "KEY")
            self._expect_op("(")
            names = []
            while True:
                names.append(self._ident("index name").lower())
                if not self._try_op(","):
                    break
            self._expect_op(")")
            if kind == "IGNORE":
                tn.ignore_index.extend(names)
            else:
                tn.use_index.extend(names)
        return ast.TableSource(source=tn, as_name=as_name)

    def _parse_by_items(self) -> list[ast.ByItem]:
        items = []
        while True:
            expr = self._parse_expr()
            desc = False
            if self._try_kw("DESC"):
                desc = True
            else:
                self._try_kw("ASC")
            items.append(ast.ByItem(expr=expr, desc=desc))
            if not self._try_op(","):
                return items

    def _parse_limit(self) -> ast.Limit | None:
        if not self._try_kw("LIMIT"):
            return None
        first = self._int_literal()
        if self._try_op(","):
            return ast.Limit(count=self._int_literal(), offset=first)
        if self._try_kw("OFFSET"):
            return ast.Limit(count=first, offset=self._int_literal())
        return ast.Limit(count=first)

    def _int_literal(self) -> int:
        t = self._cur()
        if t.tp != lx.INT:
            self._fail("expected integer literal")
        self.pos += 1
        return t.val  # type: ignore[return-value]

    # ================= INSERT / UPDATE / DELETE =================

    def _parse_insert(self) -> ast.InsertStmt:
        stmt = ast.InsertStmt()
        if self._try_kw("REPLACE"):
            stmt.is_replace = True
        else:
            self._expect_kw("INSERT")
        if self._try_kw("IGNORE"):
            stmt.ignore = True
        self._try_kw("INTO")
        stmt.table = self._parse_table_name()
        if self._try_kw("SET"):
            stmt.setlist = self._parse_assignments()
            self._parse_on_duplicate(stmt)
            return stmt
        if self._at_op("("):
            # could be a column list or a parenthesized SELECT
            save = self.pos
            self.pos += 1
            if self._at_kw("SELECT"):
                stmt.select = self._parse_select_or_union()
                self._expect_op(")")
                self._parse_on_duplicate(stmt)
                return stmt
            else:
                cols = []
                while True:
                    cols.append(self._ident("column name"))
                    if not self._try_op(","):
                        break
                self._expect_op(")")
                stmt.columns = cols
        if self._at_kw("SELECT"):
            stmt.select = self._parse_select_or_union()
        else:
            self._expect_kw("VALUES", "VALUE")
            while True:
                self._expect_op("(")
                row: list[ast.ExprNode] = []
                if not self._at_op(")"):
                    while True:
                        if self._try_kw("DEFAULT"):
                            row.append(ast.DefaultExpr())
                        else:
                            row.append(self._parse_expr())
                        if not self._try_op(","):
                            break
                self._expect_op(")")
                stmt.values.append(row)
                if not self._try_op(","):
                    break
        self._parse_on_duplicate(stmt)
        return stmt

    def _parse_on_duplicate(self, stmt: ast.InsertStmt) -> None:
        if self._try_kw("ON"):
            self._expect_kw("DUPLICATE")
            self._expect_kw("KEY")
            self._expect_kw("UPDATE")
            stmt.on_duplicate = self._parse_assignments()

    def _parse_column_name(self) -> ast.ColumnName:
        name = self._ident("column name")
        table = db = ""
        if self._try_op("."):
            table, name = name, self._ident("column name")
            if self._try_op("."):
                db, table, name = table, name, self._ident("column name")
        return ast.ColumnName(name=name, table=table, db=db)

    def _parse_assignments(self) -> list[ast.Assignment]:
        out = []
        while True:
            col = self._parse_column_name()
            self._expect_op("=")
            expr = self._parse_expr()
            out.append(ast.Assignment(column=col, expr=expr))
            if not self._try_op(","):
                return out

    def _parse_update(self) -> ast.UpdateStmt:
        self._expect_kw("UPDATE")
        stmt = ast.UpdateStmt()
        stmt.table = self._parse_table_name()
        self._expect_kw("SET")
        stmt.assignments = self._parse_assignments()
        if self._try_kw("WHERE"):
            stmt.where = self._parse_expr()
        if self._try_kw("ORDER"):
            self._expect_kw("BY")
            stmt.order_by = self._parse_by_items()
        stmt.limit = self._parse_limit()
        return stmt

    def _parse_delete(self) -> ast.DeleteStmt:
        self._expect_kw("DELETE")
        self._expect_kw("FROM")
        stmt = ast.DeleteStmt()
        stmt.table = self._parse_table_name()
        if self._try_kw("WHERE"):
            stmt.where = self._parse_expr()
        if self._try_kw("ORDER"):
            self._expect_kw("BY")
            stmt.order_by = self._parse_by_items()
        stmt.limit = self._parse_limit()
        return stmt

    def _parse_table_name(self) -> ast.TableName:
        name = self._ident("table name")
        db = ""
        if self._try_op("."):
            db, name = name, self._ident("table name")
        return ast.TableName(name=name, db=db)

    # ================= DDL =================

    def _parse_create(self) -> ast.StmtNode:
        self._expect_kw("CREATE")
        if self._at(lx.IDENT) and self._cur().val.lower() == "user":
            self._next()
            ine = self._parse_if_not_exists()
            return ast.CreateUserStmt(users=self._parse_user_specs(),
                                      if_not_exists=ine)
        if self._try_kw("DATABASE", "SCHEMA"):
            ine = self._parse_if_not_exists()
            stmt = ast.CreateDatabaseStmt(name=self._ident(),
                                          if_not_exists=ine)
            cs_name, co_name = None, None
            while True:
                self._try_kw("DEFAULT")
                if self._try_kw("CHARSET") or (self._try_kw("CHARACTER")
                                               and self._try_kw("SET")):
                    self._try_op("=")
                    cs_name = self._ident_or_string()
                elif self._try_kw("COLLATE"):
                    self._try_op("=")
                    co_name = self._ident_or_string()
                else:
                    break
            if cs_name is not None or co_name is not None:
                from tidb_tpu import charset as _cs
                stmt.charset, stmt.collate = \
                    _cs.validate_column_charset(cs_name, co_name)
            return stmt
        if self._at_kw("UNIQUE", "INDEX"):
            unique = self._try_kw("UNIQUE")
            self._expect_kw("INDEX")
            iname = self._ident("index name")
            self._expect_kw("ON")
            table = self._parse_table_name()
            self._expect_op("(")
            cols = []
            while True:
                cols.append(self._ident("column name"))
                if not self._try_op(","):
                    break
            self._expect_op(")")
            return ast.CreateIndexStmt(index_name=iname, table=table,
                                       columns=cols, unique=unique)
        self._expect_kw("TABLE")
        ine = self._parse_if_not_exists()
        table = self._parse_table_name()
        stmt = ast.CreateTableStmt(table=table, if_not_exists=ine)
        self._expect_op("(")
        while True:
            if self._at_kw("PRIMARY", "UNIQUE", "INDEX", "KEY", "CONSTRAINT") \
                    or self._at_word("FOREIGN"):
                stmt.constraints.append(self._parse_constraint())
            else:
                stmt.cols.append(self._parse_column_def())
            if not self._try_op(","):
                break
        self._expect_op(")")
        # table options: [DEFAULT] CHARSET/CHARACTER SET and COLLATE are
        # captured and validated; the rest (ENGINE=, COMMENT=...) parse
        # and are ignored
        cs_name, co_name = None, None
        while self._cur().tp in (lx.KEYWORD, lx.IDENT) and not self._at(lx.EOF) \
                and not self._at_op(";"):
            if self._try_kw("DEFAULT"):
                continue
            if self._try_kw("CHARSET") or (self._try_kw("CHARACTER")
                                           and self._try_kw("SET")):
                self._try_op("=")
                cs_name = self._ident_or_string()
                continue
            if self._try_kw("COLLATE"):
                self._try_op("=")
                co_name = self._ident_or_string()
                continue
            self._next()
            if self._try_op("="):
                self._next()
        if cs_name is not None or co_name is not None:
            from tidb_tpu import charset as _cs
            stmt.charset, stmt.collate = \
                _cs.validate_column_charset(cs_name, co_name)
            stmt.charset_explicit = True
            # table default applies to string columns without their own
            # CHARACTER SET/COLLATE (MySQL inheritance)
            for cd in stmt.cols:
                if cd.tp.is_string() and not cd.charset_explicit:
                    cd.tp.charset, cd.tp.collate = stmt.charset, stmt.collate
        return stmt

    def _parse_if_not_exists(self) -> bool:
        if self._try_kw("IF"):
            self._expect_kw("NOT")
            self._expect_kw("EXISTS")
            return True
        return False

    def _parse_constraint(self) -> ast.Constraint:
        symbol = ""
        if self._try_kw("CONSTRAINT"):
            if self._cur().tp == lx.IDENT:
                symbol = self._ident()  # constraint symbol (FK name)
        if self._try_word("FOREIGN"):
            # FOREIGN KEY [name] (cols) ReferDef (parser.y:1171)
            self._expect_kw("KEY")
            name = symbol
            if self._cur().tp == lx.IDENT and not self._at_op("("):
                name = self._ident("foreign key name")
            keys = self._parse_paren_name_list()
            return ast.Constraint(tp=ast.ConstraintType.FOREIGN_KEY,
                                  name=name, keys=keys,
                                  refer=self._parse_refer_def())
        if self._try_kw("PRIMARY"):
            self._expect_kw("KEY")
            tp = ast.ConstraintType.PRIMARY_KEY
            name = "primary"
        elif self._try_kw("UNIQUE"):
            self._try_kw("KEY", "INDEX")
            tp = ast.ConstraintType.UNIQUE
            name = self._ident("index name") if self._cur().tp == lx.IDENT else ""
        else:
            self._expect_kw("INDEX", "KEY")
            tp = ast.ConstraintType.INDEX
            name = self._ident("index name") if self._cur().tp == lx.IDENT else ""
        keys = self._parse_paren_name_list()
        return ast.Constraint(tp=tp, name=name, keys=keys)

    def _parse_paren_name_list(self) -> list[str]:
        self._expect_op("(")
        keys = []
        while True:
            keys.append(self._ident("column name"))
            if self._try_op("("):  # prefix length — parsed, ignored for now
                self._int_literal()
                self._expect_op(")")
            if not self._try_op(","):
                break
        self._expect_op(")")
        return keys

    def _parse_refer_def(self) -> ast.ReferenceDef:
        """REFERENCES tbl (cols) [ON DELETE opt] [ON UPDATE opt]
        (parser.y:1181 ReferDef / OnDeleteOpt / OnUpdateOpt)."""
        self._expect_word("REFERENCES")
        refer = ast.ReferenceDef(table=self._parse_table_name())
        refer.columns = self._parse_paren_name_list()
        while self._try_kw("ON"):
            which = self._expect_word("DELETE", "UPDATE")
            if self._try_word("RESTRICT"):
                opt = "RESTRICT"
            elif self._try_word("CASCADE"):
                opt = "CASCADE"
            elif self._try_word("NO"):
                self._expect_word("ACTION")
                opt = "NO ACTION"
            else:
                self._expect_kw("SET")
                opt = "SET " + self._expect_word("NULL", "DEFAULT")
            if which == "DELETE":
                refer.on_delete = opt
            else:
                refer.on_update = opt
        return refer

    def _parse_column_def(self) -> ast.ColumnDef:
        name = self._ident("column name")
        ftype = self._parse_field_type()
        col = ast.ColumnDef(name=name, tp=ftype)
        cs_name, co_name = None, None
        while True:
            if self._try_kw("NOT"):
                self._expect_kw("NULL")
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.NOT_NULL))
            elif self._try_kw("NULL"):
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.NULL))
            elif self._try_kw("DEFAULT"):
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.DEFAULT,
                                                    expr=self._parse_expr()))
            elif self._try_kw("AUTO_INCREMENT"):
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.AUTO_INCREMENT))
            elif self._try_kw("PRIMARY"):
                self._expect_kw("KEY")
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.PRIMARY_KEY))
            elif self._try_kw("UNIQUE"):
                self._try_kw("KEY")
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.UNIQUE_KEY))
            elif self._try_kw("COMMENT"):
                t = self._next()
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.COMMENT,
                                                    comment=str(t.val)))
            elif self._try_kw("ON"):
                self._expect_kw("UPDATE")
                self._next()  # CURRENT_TIMESTAMP etc.
                col.options.append(ast.ColumnOption(ast.ColumnOptionType.ON_UPDATE))
            elif self._try_kw("CHARACTER", "CHARSET"):
                self._try_kw("SET")
                cs_name = self._ident_or_string()
            elif self._try_kw("COLLATE"):
                co_name = self._ident_or_string()
            else:
                if cs_name is not None or co_name is not None:
                    from tidb_tpu import charset as _cs
                    ftype.charset, ftype.collate = \
                        _cs.validate_column_charset(cs_name, co_name)
                    col.charset_explicit = True
                return col

    _TYPE_MAP = {
        "TINYINT": my.TypeTiny, "SMALLINT": my.TypeShort, "MEDIUMINT": my.TypeInt24,
        "INT": my.TypeLong, "INTEGER": my.TypeLong, "BIGINT": my.TypeLonglong,
        "FLOAT": my.TypeFloat, "DOUBLE": my.TypeDouble, "REAL": my.TypeDouble,
        "DECIMAL": my.TypeNewDecimal, "NUMERIC": my.TypeNewDecimal,
        "CHAR": my.TypeString, "VARCHAR": my.TypeVarchar,
        "BINARY": my.TypeString, "VARBINARY": my.TypeVarchar,
        "TEXT": my.TypeBlob, "TINYTEXT": my.TypeTinyBlob,
        "MEDIUMTEXT": my.TypeMediumBlob, "LONGTEXT": my.TypeLongBlob,
        "BLOB": my.TypeBlob, "TINYBLOB": my.TypeTinyBlob,
        "MEDIUMBLOB": my.TypeMediumBlob, "LONGBLOB": my.TypeLongBlob,
        "DATE": my.TypeDate, "TIME": my.TypeDuration, "DATETIME": my.TypeDatetime,
        "TIMESTAMP": my.TypeTimestamp, "YEAR": my.TypeYear, "BIT": my.TypeBit,
        "ENUM": my.TypeEnum, "SET": my.TypeSet,
    }

    def _parse_field_type(self) -> FieldType:
        t = self._cur()
        if t.tp != lx.KEYWORD or t.val not in self._TYPE_MAP:
            self._fail("expected column type")
        self.pos += 1
        tp = self._TYPE_MAP[t.val]  # type: ignore[index]
        ft = new_field_type(tp)
        if t.val in ("BINARY", "VARBINARY"):
            ft.flag |= my.BinaryFlag
        if self._try_op("("):
            if tp in (my.TypeEnum, my.TypeSet):
                elems = []
                while True:
                    elems.append(self._next().val)
                    if not self._try_op(","):
                        break
                ft.elems = elems
            else:
                ft.flen = self._int_literal()
                if self._try_op(","):
                    ft.decimal = self._int_literal()
                elif tp == my.TypeNewDecimal:
                    ft.decimal = 0
            self._expect_op(")")
        elif tp == my.TypeNewDecimal:
            ft.flen, ft.decimal = 10, 0
        while True:
            if self._try_kw("UNSIGNED"):
                ft.flag |= my.UnsignedFlag
            elif self._try_kw("SIGNED"):
                pass
            elif self._try_kw("ZEROFILL"):
                ft.flag |= my.ZerofillFlag | my.UnsignedFlag
            elif self._try_kw("BINARY"):
                ft.flag |= my.BinaryFlag
            else:
                return ft

    def _parse_drop(self) -> ast.StmtNode:
        self._expect_kw("DROP")
        if self._at(lx.IDENT) and self._cur().val.lower() == "user":
            self._next()
            ie = self._parse_if_exists()
            return ast.DropUserStmt(users=self._parse_user_specs(),
                                    if_exists=ie)
        if self._try_kw("DATABASE", "SCHEMA"):
            ie = self._parse_if_exists()
            return ast.DropDatabaseStmt(name=self._ident(), if_exists=ie)
        if self._try_kw("INDEX"):
            iname = self._ident("index name")
            self._expect_kw("ON")
            return ast.DropIndexStmt(index_name=iname, table=self._parse_table_name())
        if self._try_word("VIEW"):
            # DROP VIEW IF EXISTS list → no-op, exactly the reference's
            # production (parser.y:1534 returns an empty DoStmt): there
            # are no views to drop, but mysqldump scripts emit this
            self._expect_kw("IF")
            self._expect_kw("EXISTS")
            self._parse_table_name()
            while self._try_op(","):
                self._parse_table_name()
            return ast.DoStmt()   # empty DO = the reference's no-op
        self._expect_kw("TABLE")
        ie = self._parse_if_exists()
        tables = [self._parse_table_name()]
        while self._try_op(","):
            tables.append(self._parse_table_name())
        return ast.DropTableStmt(tables=tables, if_exists=ie)

    def _parse_if_exists(self) -> bool:
        if self._try_kw("IF"):
            self._expect_kw("EXISTS")
            return True
        return False

    def _parse_alter(self) -> ast.AlterTableStmt:
        self._expect_kw("ALTER")
        self._expect_kw("TABLE")
        stmt = ast.AlterTableStmt(table=self._parse_table_name())
        while True:
            if self._try_kw("ADD"):
                if self._try_kw("COLUMN"):
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.ADD_COLUMN,
                        column=self._parse_column_def()))
                elif self._at_kw("PRIMARY", "UNIQUE", "INDEX", "KEY",
                                 "CONSTRAINT") or self._at_word("FOREIGN"):
                    c = self._parse_constraint()
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.ADD_FOREIGN_KEY
                        if c.tp == ast.ConstraintType.FOREIGN_KEY
                        else ast.AlterTableType.ADD_CONSTRAINT,
                        constraint=c))
                else:
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.ADD_COLUMN,
                        column=self._parse_column_def()))
            elif self._try_kw("DROP"):
                if self._try_kw("COLUMN"):
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.DROP_COLUMN, name=self._ident()))
                elif self._try_kw("INDEX", "KEY"):
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.DROP_INDEX, name=self._ident()))
                elif self._try_kw("PRIMARY"):
                    self._expect_kw("KEY")
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.DROP_PRIMARY_KEY))
                elif self._try_word("FOREIGN"):
                    self._expect_kw("KEY")
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.DROP_FOREIGN_KEY,
                        name=self._ident("foreign key name")))
                else:
                    stmt.specs.append(ast.AlterTableSpec(
                        ast.AlterTableType.DROP_COLUMN, name=self._ident()))
            elif self._at(lx.IDENT) and \
                    self._cur().val.lower() == "modify":
                self._next()
                self._try_kw("COLUMN")
                stmt.specs.append(ast.AlterTableSpec(
                    ast.AlterTableType.MODIFY_COLUMN,
                    column=self._parse_column_def()))
            else:
                self._fail("expected ADD/DROP/MODIFY in ALTER TABLE")
            if not self._try_op(","):
                return stmt

    def _parse_ignored_stmt(self) -> ast.DoStmt:
        """BINLOG 'base64' / LOCK TABLES tbl READ|WRITE, ... / UNLOCK
        TABLES: the reference parses all three and ignores them
        (parser.y:928 BinlogStmt + executor_simple.go:83 "We just ignore
        it"; parser.y LockTablesStmt/UnlockTablesStmt produce nothing).
        An empty DoStmt is the no-op the reference returns."""
        w = self._expect_word("BINLOG", "LOCK", "UNLOCK")
        if w == "BINLOG":
            if not self._at(lx.STRING):
                self._fail("expected string after BINLOG")
            self._next()
        elif w == "LOCK":
            self._expect_kw("TABLES")
            while True:
                self._parse_table_name()
                lt = self._expect_word("READ", "WRITE")
                if lt == "READ":
                    self._try_word("LOCAL")
                if not self._try_op(","):
                    break
        else:
            self._expect_kw("TABLES")
        return ast.DoStmt()   # empty DO = the reference's no-op shape

    def _parse_truncate(self) -> ast.TruncateTableStmt:
        self._expect_kw("TRUNCATE")
        self._try_kw("TABLE")
        return ast.TruncateTableStmt(table=self._parse_table_name())

    # ================= misc =================

    def _parse_begin(self) -> ast.BeginStmt:
        if self._try_kw("START"):
            self._expect_kw("TRANSACTION")
        else:
            self._expect_kw("BEGIN")
        return ast.BeginStmt()

    def _parse_use(self) -> ast.UseStmt:
        self._expect_kw("USE")
        return ast.UseStmt(db=self._ident("database name"))

    def _parse_set(self) -> ast.SetStmt:
        self._expect_kw("SET")
        # SET NAMES x / SET CHARACTER SET x: connection charset selection —
        # the engine is utf8-only, so these parse and no-op (parser.y
        # SetNamesStmt); drivers send them right after the handshake
        if self._at(lx.IDENT) and self._cur().val.lower() == "names":
            self._next()
            cs_name = self._ident_or_string()
            co_name = None
            if self._try_kw("COLLATE"):
                co_name = self._ident_or_string()
            from tidb_tpu import charset as _cs
            _cs.validate_column_charset(cs_name, co_name)  # 1115/1273/1253
            return ast.SetStmt()
        if self._at_kw("CHARACTER"):
            self._next()
            self._expect_kw("SET")
            from tidb_tpu import charset as _cs
            _cs.get_charset_info(self._ident_or_string())   # 1115 on unknown
            return ast.SetStmt()
        # SET [GLOBAL|SESSION] TRANSACTION TransactionChars (parser.y
        # :3792-3814; the reference parses-and-ignores — here the isolation
        # level maps onto @@tx_isolation with validation, because JDBC/ORMs
        # issue this at connection setup and must not get a parse error)
        save = self.pos
        txn_global = bool(self._try_kw("GLOBAL"))
        if not txn_global:
            self._try_kw("SESSION")
        if self._try_kw("TRANSACTION"):
            return self._parse_set_transaction(txn_global)
        self.pos = save
        stmt = ast.SetStmt()
        while True:
            is_global, is_system = False, False
            if self._try_kw("GLOBAL"):
                is_global, is_system = True, True
            elif self._try_kw("SESSION"):
                is_system = True
            t = self._cur()
            if t.tp == lx.SYS_VAR:
                self.pos += 1
                is_system = True
                scoped_global, name = _split_sysvar_scope(t.val)
                is_global = is_global or scoped_global
            elif t.tp == lx.USER_VAR:
                self.pos += 1
                name, is_system = t.val, False  # type: ignore[assignment]
            else:
                name = self._ident("variable name")
                is_system = True
            if not self._try_op("="):
                self._expect_op(":=")
            value = self._parse_expr()
            stmt.variables.append(ast.VariableAssignment(
                name=name, value=value, is_global=is_global, is_system=is_system))
            if not self._try_op(","):
                return stmt

    def _parse_set_transaction(self, is_global: bool) -> ast.SetStmt:
        """TransactionChars: ISOLATION LEVEL <level> | READ WRITE |
        READ ONLY, comma-separated (parser.y:3801-3814). Access-mode
        chars parse and no-op (the engine has no read-only txns);
        isolation levels become @@tx_isolation assignments."""
        stmt = ast.SetStmt()
        while True:
            if self._try_word("ISOLATION"):
                self._expect_word("LEVEL")
                if self._try_word("REPEATABLE"):
                    self._expect_word("READ")
                    level = "REPEATABLE-READ"
                elif self._try_word("SERIALIZABLE"):
                    level = "SERIALIZABLE"
                else:
                    self._expect_word("READ")
                    level = "READ-" + self._expect_word("COMMITTED",
                                                        "UNCOMMITTED")
                stmt.variables.append(ast.VariableAssignment(
                    name="tx_isolation",
                    value=ast.Literal(datum_from_py(level)),
                    is_global=is_global, is_system=True))
            elif self._try_word("READ"):
                self._expect_word("WRITE", "ONLY")
            else:
                self._fail("expected ISOLATION LEVEL or READ WRITE/ONLY")
            if not self._try_op(","):
                return stmt

    def _parse_show(self) -> ast.ShowStmt:
        self._expect_kw("SHOW")
        full = self._try_kw("FULL")
        if self._try_kw("DATABASES", "SCHEMAS"):
            return ast.ShowStmt(tp=ast.ShowType.DATABASES, full=full)
        if self._try_kw("TABLES"):
            db = ""
            if self._try_kw("FROM", "IN"):
                db = self._ident()
            return ast.ShowStmt(tp=ast.ShowType.TABLES, db=db, full=full)
        if self._try_kw("COLUMNS", "FIELDS"):
            self._expect_kw("FROM", "IN")
            table = self._parse_table_name()
            return ast.ShowStmt(tp=ast.ShowType.COLUMNS, table=table, full=full)
        # GLOBAL/SESSION qualifier applies to VARIABLES and STATUS (the
        # registry/sysvar table is process-wide either way)
        self._try_kw("GLOBAL", "SESSION")
        if self._try_kw("VARIABLES"):
            pattern = ""
            if self._try_kw("LIKE"):
                pattern = str(self._next().val)
            return ast.ShowStmt(tp=ast.ShowType.VARIABLES, pattern=pattern)
        if self._try_kw("WARNINGS"):
            return ast.ShowStmt(tp=ast.ShowType.WARNINGS)
        if self._at(lx.IDENT) and self._cur().val.lower() == "status":
            self._next()
            pattern = ""
            if self._try_kw("LIKE"):
                pattern = str(self._next().val)
            return ast.ShowStmt(tp=ast.ShowType.STATUS, pattern=pattern)
        if self._at(lx.IDENT) and self._cur().val.lower() == "processlist":
            self._next()
            return ast.ShowStmt(tp=ast.ShowType.PROCESSLIST, full=full)
        if self._try_kw("CHARSET") or self._try_kw("CHARACTER"):
            self._try_kw("SET")
            pattern = ""
            if self._try_kw("LIKE"):
                pattern = str(self._next().val)
            return ast.ShowStmt(tp=ast.ShowType.CHARSET, pattern=pattern)
        if self._at(lx.IDENT) and self._cur().val.lower() == "collation":
            self._next()
            pattern = ""
            if self._try_kw("LIKE"):
                pattern = str(self._next().val)
            return ast.ShowStmt(tp=ast.ShowType.COLLATION, pattern=pattern)
        if self._at(lx.IDENT) and self._cur().val.lower() == "grants":
            self._next()
            user = ""
            host = ""
            if self._try_kw("FOR"):
                user = self._ident_or_string()
                if self._at(lx.USER_VAR):  # 'u'@'h' — the identity's host
                    t = self._next()
                    host = str(t.val) if t.val else self._ident_or_string()
            return ast.ShowStmt(tp=ast.ShowType.GRANTS, pattern=user,
                                host=host)
        if self._try_kw("CREATE"):
            self._expect_kw("TABLE")
            return ast.ShowStmt(tp=ast.ShowType.CREATE_TABLE,
                                table=self._parse_table_name())
        if self._try_kw("INDEX"):
            self._expect_kw("FROM", "IN")
            return ast.ShowStmt(tp=ast.ShowType.INDEXES,
                                table=self._parse_table_name())
        self._fail("unsupported SHOW")

    def _parse_explain(self) -> ast.StmtNode:
        self._next()  # EXPLAIN/DESCRIBE/DESC
        if self._at_kw("ANALYZE"):
            # EXPLAIN ANALYZE <stmt>: runs the statement, annotates the
            # plan with actual per-operator stats. Disambiguated from
            # `DESCRIBE analyze` (a table named analyze) by requiring a
            # statement keyword after ANALYZE.
            nxt = self.toks[self.pos + 1] if self.pos + 1 < len(self.toks) \
                else None
            if nxt is not None and nxt.tp == lx.KEYWORD and \
                    nxt.val in ("SELECT", "INSERT", "UPDATE", "DELETE",
                                "REPLACE"):
                self._next()  # ANALYZE
                return ast.ExplainStmt(stmt=self._parse_statement(),
                                       analyze=True)
        if self._cur().tp == lx.KEYWORD and self._at_kw("SELECT", "INSERT", "UPDATE",
                                                        "DELETE"):
            return ast.ExplainStmt(stmt=self._parse_statement())
        # DESCRIBE table → SHOW COLUMNS
        return ast.ShowStmt(tp=ast.ShowType.COLUMNS, table=self._parse_table_name())

    def _parse_trace(self) -> ast.TraceStmt:
        """TRACE [FORMAT = 'json'] <stmt> (reference parser.y
        TraceStmt; executor/trace.go). TRACE is dispatched as a bare
        identifier, never a keyword."""
        self._next()  # TRACE
        fmt = "json"
        if self._at_word("FORMAT"):
            self._next()
            self._expect_op("=")
            t = self._cur()
            if t.tp != lx.STRING:
                self._fail("expected format string after FORMAT =")
            self.pos += 1
            fmt = str(t.val).lower()
            if fmt not in ("json", "row"):
                self._fail(f"unsupported TRACE format {fmt!r}")
        if not self._at_kw("SELECT", "INSERT", "UPDATE", "DELETE",
                           "REPLACE"):
            self._fail("TRACE expects a SELECT/INSERT/UPDATE/DELETE/"
                       "REPLACE statement")
        return ast.TraceStmt(stmt=self._parse_statement(), format=fmt)

    def _parse_prepare(self) -> ast.PrepareStmt:
        """PREPARE name FROM 'sql' | @var (reference parser.y PreparedStmt,
        executor/prepared.go)."""
        self._expect_kw("PREPARE")
        name = self._ident("statement name")
        self._expect_kw("FROM")
        t = self._cur()
        if t.tp == lx.STRING:
            self.pos += 1
            return ast.PrepareStmt(name=name, sql_text=t.val)
        if t.tp == lx.USER_VAR:
            self.pos += 1
            return ast.PrepareStmt(name=name, from_var=t.val)
        self._fail("expected string literal or @user_variable after FROM")

    def _parse_execute(self) -> ast.ExecuteStmt:
        self._expect_kw("EXECUTE")
        stmt = ast.ExecuteStmt(name=self._ident("statement name"))
        if self._try_kw("USING"):
            while True:
                t = self._cur()
                if t.tp != lx.USER_VAR:
                    self._fail("expected @user_variable in USING")
                self.pos += 1
                stmt.using.append(t.val)
                if not self._try_op(","):
                    break
        return stmt

    def _parse_deallocate(self) -> ast.DeallocateStmt:
        self._expect_kw("DEALLOCATE")
        self._try_kw("PREPARE")
        return ast.DeallocateStmt(name=self._ident("statement name"))

    def _parse_admin(self) -> ast.AdminStmt:
        self._expect_kw("ADMIN")
        if self._try_kw("SHOW"):
            self._ident("ddl")  # ADMIN SHOW DDL
            return ast.AdminStmt(tp=ast.AdminType.SHOW_DDL)
        if self._try_word("TPU"):
            # ADMIN TPU PROFILE EXPORT: the most recently retained
            # statement trace as Chrome trace-event JSON
            if not (self._try_word("PROFILE")
                    and self._try_word("EXPORT")):
                self._fail("expected PROFILE EXPORT")
            return ast.AdminStmt(tp=ast.AdminType.TPU_PROFILE_EXPORT)
        self._expect_kw("CHECK")
        self._expect_kw("TABLE")
        tables = [self._parse_table_name()]
        while self._try_op(","):
            tables.append(self._parse_table_name())
        return ast.AdminStmt(tp=ast.AdminType.CHECK_TABLE, tables=tables)

    def _parse_analyze(self) -> ast.AnalyzeTableStmt:
        """ANALYZE TABLE t1 [, t2] (parser.y AnalyzeTableStmt)."""
        self._expect_kw("ANALYZE")
        self._expect_kw("TABLE")
        tables = [self._parse_table_name()]
        while self._try_op(","):
            tables.append(self._parse_table_name())
        return ast.AnalyzeTableStmt(tables=tables)

    # ================= LOAD DATA (parser.y LoadDataStmt) =================

    def _parse_load_data(self) -> ast.LoadDataStmt:
        self._expect_kw("LOAD")
        self._expect_kw("DATA")
        stmt = ast.LoadDataStmt()
        stmt.local = self._try_kw("LOCAL")
        self._expect_kw("INFILE")
        stmt.path = self._string_lit("file path")
        self._expect_kw("INTO")
        self._expect_kw("TABLE")
        stmt.table = self._parse_table_name()
        if self._try_kw("FIELDS", "COLUMNS"):
            while True:
                if self._try_kw("TERMINATED"):
                    self._expect_kw("BY")
                    stmt.field_term = self._string_lit("terminator")
                elif self._try_kw("ENCLOSED"):
                    self._expect_kw("BY")
                    stmt.field_enclosed = self._string_lit("encloser")
                elif self._try_kw("ESCAPED"):
                    self._expect_kw("BY")
                    stmt.field_escaped = self._string_lit("escape")
                else:
                    break
        if self._try_kw("LINES"):
            while True:
                if self._try_kw("TERMINATED"):
                    self._expect_kw("BY")
                    stmt.line_term = self._string_lit("terminator")
                elif self._try_kw("STARTING"):
                    self._expect_kw("BY")
                    stmt.line_starting = self._string_lit("prefix")
                else:
                    break
        if self._try_kw("IGNORE"):
            t = self._next()
            stmt.ignore_lines = int(t.val)
            self._expect_kw("LINES")
        if self._try_op("("):
            cols = [self._ident("column name")]
            while self._try_op(","):
                cols.append(self._ident("column name"))
            self._expect_op(")")
            stmt.columns = cols
        return stmt

    def _parse_flush(self) -> ast.FlushStmt:
        """FLUSH PRIVILEGES | TABLES | STATUS (parser.y FlushStmt)."""
        self._expect_kw("FLUSH")
        what = self._ident("flush target").lower()
        return ast.FlushStmt(what=what)

    def _parse_do(self) -> ast.DoStmt:
        """DO expr[, expr…]: evaluate and discard (ast/misc.go DoStmt)."""
        self._expect_kw("DO")
        exprs = [self._parse_expr()]
        while self._try_op(","):
            exprs.append(self._parse_expr())
        return ast.DoStmt(exprs=exprs)

    def _parse_kill(self) -> ast.KillStmt:
        self._expect_kw("KILL")
        query_only = False
        if self._at(lx.IDENT) and self._cur().val.lower() in ("query",
                                                             "connection"):
            query_only = self._next().val.lower() == "query"
        t = self._next()
        return ast.KillStmt(conn_id=int(t.val), query_only=query_only)

    # ================= GRANT / REVOKE (parser.y GrantStmt) =================

    # GRANT keyword → mysql.user/db/tables_priv column stem
    _PRIV_NAMES = {
        "SELECT": "Select", "INSERT": "Insert", "UPDATE": "Update",
        "DELETE": "Delete", "CREATE": "Create", "DROP": "Drop",
        "GRANT": "Grant", "ALTER": "Alter", "INDEX": "Index",
        "EXECUTE": "Execute",
    }

    def _parse_priv_list(self) -> list[str]:
        if self._try_kw("ALL"):
            self._try_kw("PRIVILEGES")
            return ["ALL"]
        privs = []
        while True:
            kw = self._expect_kw(*self._PRIV_NAMES.keys())
            if kw == "GRANT":
                self._ident("option")  # GRANT OPTION as a listed priv
            privs.append(self._PRIV_NAMES[kw])
            if not self._try_op(","):
                return privs

    def _parse_priv_level(self) -> tuple[str, str]:
        """*.* | * | db.* | db.table | table → (db, table); '' = global
        wildcard, db='*' = MySQL's bare-star current-database scope (the
        executor resolves it — it must NOT widen to global)."""
        if self._try_op("*"):
            if self._try_op("."):
                self._expect_op("*")
                return "", ""
            return "*", ""
        name = self._ident_or_string()
        if self._try_op("."):
            if self._try_op("*"):
                return name, ""
            return name, self._ident_or_string()
        return "", name  # bare table name: current db

    def _parse_user_specs(self) -> list[ast.UserSpec]:
        users = []
        while True:
            user = self._ident_or_string()
            host = "%"
            # 'u'@'h': the lexer eats @ as an (empty or named) user-var
            if self._at(lx.USER_VAR):
                t = self._next()
                host = t.val if t.val else self._ident_or_string()
            spec = ast.UserSpec(user=user, host=host)
            if self._try_kw("IDENTIFIED"):
                self._expect_kw("BY")
                spec.password = self._string_lit("password")
            users.append(spec)
            if not self._try_op(","):
                return users

    def _string_lit(self, what: str) -> str:
        if self._at(lx.STRING):
            return self._next().val  # type: ignore[return-value]
        self._fail(f"expected {what} string")

    def _parse_grant(self) -> ast.GrantStmt:
        self._expect_kw("GRANT")
        privs = self._parse_priv_list()
        self._expect_kw("ON")
        db, table = self._parse_priv_level()
        self._expect_kw("TO")
        users = self._parse_user_specs()
        opt = False
        if self._try_kw("WITH"):
            self._expect_kw("GRANT")
            self._ident("option")
            opt = True
        return ast.GrantStmt(privs=privs, db=db, table=table, users=users,
                             grant_option=opt)

    def _parse_revoke(self) -> ast.RevokeStmt:
        self._expect_kw("REVOKE")
        privs = self._parse_priv_list()
        self._expect_kw("ON")
        db, table = self._parse_priv_level()
        self._expect_kw("FROM")
        users = self._parse_user_specs()
        return ast.RevokeStmt(privs=privs, db=db, table=table, users=users)

    # ================= expressions (Pratt) =================
    # binding powers, low → high (MySQL precedence)
    _BP_OR = 10
    _BP_XOR = 15
    _BP_AND = 20
    _BP_NOT = 25
    _BP_CMP = 30       # = != < <= > >= <=> IS LIKE IN BETWEEN
    _BP_BITOR = 40
    _BP_BITAND = 45
    _BP_SHIFT = 50
    _BP_ADD = 55
    _BP_MUL = 60
    _BP_BITXOR = 65
    _BP_UNARY = 70

    def _parse_expr(self, rbp: int = 0) -> ast.ExprNode:
        left = self._parse_prefix()
        while True:
            bp, parse_infix = self._infix(rbp)
            if parse_infix is None:
                return left
            left = parse_infix(left)

    def _infix(self, rbp: int):
        t = self._cur()
        if t.tp == lx.KEYWORD:
            kw = t.val
            if kw == "OR" and rbp < self._BP_OR:
                return self._BP_OR, self._binary(Op.OrOr, self._BP_OR)
            if kw == "XOR" and rbp < self._BP_XOR:
                return self._BP_XOR, self._binary(Op.Xor, self._BP_XOR)
            if kw == "AND" and rbp < self._BP_AND:
                return self._BP_AND, self._binary(Op.AndAnd, self._BP_AND)
            if kw in ("IS", "LIKE", "IN", "BETWEEN", "NOT", "REGEXP",
                      "RLIKE") and rbp < self._BP_CMP:
                return self._BP_CMP, self._cmp_keyword
            if kw == "DIV" and rbp < self._BP_MUL:
                return self._BP_MUL, self._binary(Op.IntDiv, self._BP_MUL)
            if kw == "MOD" and rbp < self._BP_MUL:
                return self._BP_MUL, self._binary(Op.Mod, self._BP_MUL)
            return 0, None
        if t.tp != lx.OP:
            return 0, None
        op = t.val
        table = {
            "||": (self._BP_OR, Op.OrOr), "&&": (self._BP_AND, Op.AndAnd),
            "=": (self._BP_CMP, Op.EQ), "!=": (self._BP_CMP, Op.NE),
            "<>": (self._BP_CMP, Op.NE), "<": (self._BP_CMP, Op.LT),
            "<=": (self._BP_CMP, Op.LE), ">": (self._BP_CMP, Op.GT),
            ">=": (self._BP_CMP, Op.GE), "<=>": (self._BP_CMP, Op.NullEQ),
            "|": (self._BP_BITOR, Op.BitOr), "&": (self._BP_BITAND, Op.BitAnd),
            "<<": (self._BP_SHIFT, Op.LeftShift), ">>": (self._BP_SHIFT, Op.RightShift),
            "+": (self._BP_ADD, Op.Plus), "-": (self._BP_ADD, Op.Minus),
            "*": (self._BP_MUL, Op.Mul), "/": (self._BP_MUL, Op.Div),
            "%": (self._BP_MUL, Op.Mod), "^": (self._BP_BITXOR, Op.BitXor),
        }
        ent = table.get(op)  # type: ignore[arg-type]
        if ent is None or rbp >= ent[0]:
            return 0, None
        return ent[0], self._binary(ent[1], ent[0])

    def _binary(self, op: Op, bp: int):
        def go(left: ast.ExprNode) -> ast.ExprNode:
            self.pos += 1
            right = self._parse_expr(bp)
            return ast.BinaryOp(op=op, left=left, right=right)
        return go

    def _cmp_keyword(self, left: ast.ExprNode) -> ast.ExprNode:
        if self._try_kw("IS"):
            not_ = self._try_kw("NOT")
            if self._try_kw("NULL"):
                return ast.IsNull(expr=left, not_=not_)
            if self._try_kw("TRUE"):
                cmp = ast.BinaryOp(op=Op.EQ, left=left,
                                   right=ast.Literal(Datum.i64(1)))
                return ast.UnaryOp(op=Op.UnaryNot, operand=cmp) if not_ else cmp
            if self._try_kw("FALSE"):
                cmp = ast.BinaryOp(op=Op.EQ, left=left,
                                   right=ast.Literal(Datum.i64(0)))
                return ast.UnaryOp(op=Op.UnaryNot, operand=cmp) if not_ else cmp
            self._fail("expected NULL/TRUE/FALSE after IS")
        not_ = self._try_kw("NOT")
        if self._try_kw("LIKE"):
            pat = self._parse_expr(self._BP_CMP)
            esc = "\\"
            if self._try_kw("ESCAPE"):
                esc = str(self._next().val)
            return ast.PatternLike(expr=left, pattern=pat, not_=not_, escape=esc)
        if self._try_kw("IN"):
            self._expect_op("(")
            if self._at_kw("SELECT"):
                sub = self._parse_select_or_union()
                self._expect_op(")")
                return ast.InExpr(expr=left, sel=sub, not_=not_)
            items = []
            while True:
                items.append(self._parse_expr())
                if not self._try_op(","):
                    break
            self._expect_op(")")
            return ast.InExpr(expr=left, items=items, not_=not_)
        if self._try_kw("BETWEEN"):
            low = self._parse_expr(self._BP_CMP)
            self._expect_kw("AND")
            high = self._parse_expr(self._BP_CMP)
            return ast.Between(expr=left, low=low, high=high, not_=not_)
        if self._try_kw("REGEXP", "RLIKE"):
            pat = self._parse_expr(self._BP_CMP)
            return ast.PatternRegexp(expr=left, pattern=pat, not_=not_)
        self._fail("expected LIKE/IN/BETWEEN/REGEXP")

    def _parse_prefix(self) -> ast.ExprNode:
        t = self._cur()
        # literals
        if t.tp in (lx.INT, lx.FLOAT, lx.STRING):
            self.pos += 1
            return ast.Literal(datum_from_py(t.val))
        if t.tp == lx.DECIMAL:
            self.pos += 1
            return ast.Literal(Datum.dec(t.val))
        if t.tp == lx.HEX:
            # token value is the digit string; written length decides the
            # byte width (x'0041' keeps its zero byte, x'' is empty)
            self.pos += 1
            from tidb_tpu.types.datum import Kind as _K
            from tidb_tpu.types.enumset import Hex
            digits = t.val
            return ast.Literal(Datum(_K.HEX, Hex(
                int(digits, 16) if digits else 0, (len(digits) + 1) // 2)))
        if t.tp == lx.BIT:
            self.pos += 1
            from tidb_tpu import errors as _errs
            from tidb_tpu.types.datum import Kind as _K
            from tidb_tpu.types.enumset import Bit, parse_bit
            try:
                b = parse_bit(f"b'{t.val}'" if t.val else "b'0'",
                              Bit.UNSPECIFIED_WIDTH)
            except _errs.TiDBError as e:
                self._fail(str(e))
            return ast.Literal(Datum(_K.BIT, b))
        if t.tp == lx.PARAM:
            self.pos += 1
            pm = ast.ParamMarker(order=len(self.param_markers))
            self.param_markers.append(pm)
            return pm
        if t.tp == lx.SYS_VAR:
            self.pos += 1
            is_global, name = _split_sysvar_scope(t.val)
            return ast.VariableExpr(name=name, is_global=is_global, is_system=True)
        if t.tp == lx.USER_VAR:
            self.pos += 1
            return ast.VariableExpr(name=t.val, is_system=False)
        if t.tp == lx.KEYWORD:
            if self._try_kw("NULL"):
                return ast.Literal(NULL)
            if self._try_kw("TRUE"):
                return ast.Literal(Datum.i64(1))
            if self._try_kw("FALSE"):
                return ast.Literal(Datum.i64(0))
            if self._try_kw("NOT"):
                return ast.UnaryOp(op=Op.UnaryNot,
                                   operand=self._parse_expr(self._BP_NOT))
            if self._try_kw("CASE"):
                return self._parse_case()
            if self._try_kw("EXISTS"):
                self._expect_op("(")
                sub = self._parse_select_or_union()
                self._expect_op(")")
                return ast.ExistsSubquery(query=sub)
            if self._try_kw("CAST"):
                self._expect_op("(")
                expr = self._parse_expr()
                self._expect_kw("AS")
                ftype = self._parse_cast_type()
                self._expect_op(")")
                return ast.CastExpr(expr=expr, cast_type=ftype)
            if self._try_kw("CONVERT"):
                self._expect_op("(")
                expr = self._parse_expr()
                if self._try_kw("USING"):
                    # CONVERT(expr USING charset) (parser.y:2446): text is
                    # utf8 internally, so this validates the charset and
                    # casts to char
                    from tidb_tpu import charset as _cs
                    _cs.get_charset_info(self._ident_or_string())
                    self._expect_op(")")
                    ftype = new_field_type(my.TypeVarString)
                    return ast.CastExpr(expr=expr, cast_type=ftype)
                self._expect_op(",")
                ftype = self._parse_cast_type()
                self._expect_op(")")
                return ast.CastExpr(expr=expr, cast_type=ftype)
            if self._try_kw("DEFAULT"):
                return ast.DefaultExpr()
            if self._try_kw("INTERVAL"):
                val = self._parse_expr(self._BP_UNARY)
                unit = self._interval_unit()
                return ast.IntervalExpr(value=val, unit=unit)
            if t.val in ("DATE", "TIME", "TIMESTAMP") \
                    and self.toks[self.pos + 1].tp == lx.STRING:
                # typed literal: DATE '1998-12-01' (parser.y DateLiteral)
                kw = self._next().val
                s = self._next().val
                from tidb_tpu import mysqldef as _my
                from tidb_tpu.types.time_types import (
                    parse_duration, parse_time)
                if kw == "TIME":
                    return ast.Literal(
                        Datum(DKind.DURATION, parse_duration(s)))
                tp = _my.TypeDate if kw == "DATE" else _my.TypeTimestamp
                return ast.Literal(Datum(DKind.TIME, parse_time(s, tp)))
            # keyword usable as function name: LEFT(...), RIGHT(...)
            if self.toks[self.pos + 1].tp == lx.OP and self.toks[self.pos + 1].val == "(":
                name = self._next().val.lower()  # type: ignore[union-attr]
                return self._parse_func_call(name)
            self._fail(f"unexpected keyword {t.val} in expression")
        if t.tp == lx.OP:
            if self._try_op("("):
                if self._at_kw("SELECT"):
                    sub = self._parse_select_or_union()
                    self._expect_op(")")
                    return ast.SubqueryExpr(query=sub)
                expr = self._parse_expr()
                if self._try_op(","):
                    row = ast.RowExpr(values=[expr])
                    while True:
                        row.values.append(self._parse_expr())
                        if not self._try_op(","):
                            break
                    self._expect_op(")")
                    return row
                self._expect_op(")")
                return expr
            if self._try_op("-"):
                return ast.UnaryOp(op=Op.UnaryMinus,
                                   operand=self._parse_expr(self._BP_UNARY))
            if self._try_op("+"):
                return ast.UnaryOp(op=Op.UnaryPlus,
                                   operand=self._parse_expr(self._BP_UNARY))
            if self._try_op("!"):
                return ast.UnaryOp(op=Op.UnaryNot,
                                   operand=self._parse_expr(self._BP_UNARY))
            if self._try_op("~"):
                return ast.UnaryOp(op=Op.BitNeg,
                                   operand=self._parse_expr(self._BP_UNARY))
            self._fail("unexpected operator in expression")
        if t.tp == lx.IDENT:
            name = self._ident()
            if self._at_op("("):
                return self._parse_func_call(name.lower())
            # qualified column
            if self._try_op("."):
                second = self._ident()
                if self._try_op("."):
                    third = self._ident()
                    return ast.ColumnName(name=third, table=second, db=name)
                return ast.ColumnName(name=second, table=name)
            return ast.ColumnName(name=name)
        self._fail("unexpected token in expression")

    def _parse_cast_type(self) -> FieldType:
        t = self._cur()
        mapping = {"SIGNED": (my.TypeLonglong, 0),
                   "UNSIGNED": (my.TypeLonglong, my.UnsignedFlag),
                   "CHAR": (my.TypeVarString, 0),
                   "BINARY": (my.TypeVarString, my.BinaryFlag),
                   "DATE": (my.TypeDate, 0), "DATETIME": (my.TypeDatetime, 0),
                   "TIME": (my.TypeDuration, 0),
                   "DECIMAL": (my.TypeNewDecimal, 0)}
        if t.tp == lx.KEYWORD and t.val in mapping:
            self.pos += 1
            tp, flag = mapping[t.val]  # type: ignore[index]
            ft = new_field_type(tp)
            ft.flag |= flag
            if self._try_op("("):
                ft.flen = self._int_literal()
                if self._try_op(","):
                    ft.decimal = self._int_literal()
                self._expect_op(")")
            if t.val == "UNSIGNED":
                self._try_kw("INTEGER")
            if t.val == "SIGNED":
                self._try_kw("INTEGER")
            return ft
        self._fail("unsupported CAST target type")

    def _parse_case(self) -> ast.CaseExpr:
        case = ast.CaseExpr()
        if not self._at_kw("WHEN"):
            case.value = self._parse_expr()
        while self._try_kw("WHEN"):
            when = self._parse_expr()
            self._expect_kw("THEN")
            result = self._parse_expr()
            case.when_clauses.append(ast.WhenClause(when=when, result=result))
        if self._try_kw("ELSE"):
            case.else_clause = self._parse_expr()
        self._expect_kw("END")
        if not case.when_clauses:
            self._fail("CASE requires at least one WHEN clause")
        return case

    _INTERVAL_UNITS = ("MICROSECOND", "SECOND", "MINUTE", "HOUR", "DAY",
                       "WEEK", "MONTH", "QUARTER", "YEAR")

    def _interval_unit(self) -> str:
        t = self._cur()
        name = (t.val or "").upper() if isinstance(t.val, str) else ""
        if name not in self._INTERVAL_UNITS:
            self._fail(f"expected interval unit, got {t.val!r}")
        self._next()
        return name.lower()

    def _parse_func_call(self, name: str) -> ast.ExprNode:
        self._expect_op("(")
        if name == "extract":
            # EXTRACT(unit FROM expr)  (parser.y FunctionCallNonKeyword)
            unit = self._interval_unit()
            self._expect_kw("FROM")
            e = self._parse_expr()
            self._expect_op(")")
            return ast.FuncCall(name="extract",
                                args=[ast.Literal(Datum.string(unit)), e])
        if name in AGG_FUNCS:
            distinct = self._try_kw("DISTINCT")
            args: list[ast.ExprNode] = []
            if self._at_op("*"):
                if name != "count":
                    self._fail("'*' argument only valid in COUNT")
                self.pos += 1
                args = [ast.Literal(Datum.i64(1))]
            elif not self._at_op(")"):
                while True:
                    args.append(self._parse_expr())
                    if not self._try_op(","):
                        break
            self._expect_op(")")
            if self._at_over_clause():
                return self._parse_window_func(name, args, distinct)
            return ast.AggregateFunc(name=name, args=args, distinct=distinct)
        args = []
        if not self._at_op(")"):
            while True:
                args.append(self._parse_expr())
                if not self._try_op(","):
                    break
        self._expect_op(")")
        if self._at_over_clause():
            return self._parse_window_func(name, args, False)
        return ast.FuncCall(name=name, args=args)

    def _at_over_clause(self) -> bool:
        # OVER is not a reserved word: a bare IDENT "over" only starts a
        # window spec when "(" follows (SELECT over FROM t stays legal)
        return self._at_word("OVER") \
            and self.toks[self.pos + 1].tp == lx.OP \
            and self.toks[self.pos + 1].val == "("

    def _parse_window_func(self, name: str, args, distinct: bool) \
            -> ast.WindowFunc:
        """name(args) OVER ([PARTITION BY exprs] [ORDER BY by_items])
        (parser.y WindowFuncCall + WindowSpec, the engine's subset)."""
        self.pos += 1       # OVER
        if name not in WINDOW_FUNCS:
            self._fail(f"unsupported window function {name!r}")
        if distinct:
            self._fail("DISTINCT is not supported in window functions")
        ranking = name in ("row_number", "rank", "dense_rank")
        if ranking and args:
            self._fail(f"{name}() takes no arguments")
        if not ranking and len(args) != 1:
            self._fail(f"window function {name}() takes one argument")
        self._expect_op("(")
        partition_by: list[ast.ExprNode] = []
        order_by: list[ast.ByItem] = []
        if self._try_word("PARTITION"):
            self._expect_kw("BY")
            while True:
                partition_by.append(self._parse_expr())
                if not self._try_op(","):
                    break
        if self._try_kw("ORDER"):
            self._expect_kw("BY")
            order_by = self._parse_by_items()
        self._expect_op(")")
        return ast.WindowFunc(name=name, args=args,
                              partition_by=partition_by, order_by=order_by)


def parse(sql: str) -> list[ast.StmtNode]:
    """Module-level convenience (tidb.Parse equivalent, tidb.go:102)."""
    return Parser().parse(sql)


def parse_one(sql: str) -> ast.StmtNode:
    return Parser().parse_one(sql)
