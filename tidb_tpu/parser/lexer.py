"""SQL lexer.

Reference: parser/lexer.go (hand-written scanner feeding the goyacc grammar).
Produces a token stream: keywords (case-insensitive), identifiers (bare or
`quoted`), string literals with '' and \\ escapes, numeric literals
(int / decimal / float split like the reference: a '.' or exponent makes it
non-int; decimal stays exact), operators, and ? param markers. Comments:
--, #, /* */.
"""

from __future__ import annotations

from dataclasses import dataclass
from decimal import Decimal

from tidb_tpu import errors


# token types
EOF = "eof"
IDENT = "ident"
STRING = "string"
INT = "int"
DECIMAL = "decimal"
FLOAT = "float"
PARAM = "param"
OP = "op"          # punctuation/operators; value is the literal text
KEYWORD = "kw"     # upper-cased keyword
HEX = "hex"
BIT = "bit"
USER_VAR = "uservar"
SYS_VAR = "sysvar"

KEYWORDS = frozenset("""
SELECT FROM WHERE GROUP BY HAVING ORDER LIMIT OFFSET AS DISTINCT ALL
AND OR NOT XOR IS NULL TRUE FALSE BETWEEN IN LIKE ESCAPE EXISTS
INSERT INTO VALUES VALUE REPLACE SET UPDATE DELETE IGNORE DUPLICATE KEY
CREATE TABLE DATABASE SCHEMA INDEX UNIQUE PRIMARY DROP ALTER ADD COLUMN
TRUNCATE IF EXISTS CONSTRAINT DEFAULT AUTO_INCREMENT COMMENT ON
BEGIN START TRANSACTION COMMIT ROLLBACK USE SHOW DATABASES SCHEMAS TABLES
COLUMNS FIELDS VARIABLES WARNINGS FULL DESCRIBE DESC ASC EXPLAIN ADMIN CHECK
JOIN INNER LEFT RIGHT OUTER CROSS USING UNION CASE WHEN THEN ELSE END CAST
CONVERT DIV MOD INTERVAL GLOBAL SESSION FOR SHARE LOCK MODE FORCE
TINYINT SMALLINT MEDIUMINT INT INTEGER BIGINT FLOAT DOUBLE REAL DECIMAL
NUMERIC CHAR VARCHAR BINARY VARBINARY TEXT TINYTEXT MEDIUMTEXT LONGTEXT
BLOB TINYBLOB MEDIUMBLOB LONGBLOB DATE TIME DATETIME TIMESTAMP YEAR BIT
UNSIGNED SIGNED ZEROFILL ENUM CHARACTER COLLATE CHARSET ENGINE ANALYZE
PREPARE EXECUTE DEALLOCATE GRANT REVOKE IDENTIFIED TO PRIVILEGES WITH
LOAD DATA LOCAL INFILE FIELDS TERMINATED ENCLOSED ESCAPED LINES STARTING
KILL FLUSH REGEXP RLIKE STRAIGHT_JOIN DO
""".split())

_MULTI_OPS = ("<=>", "<<", ">>", "<=", ">=", "!=", "<>", "||", "&&", ":=")
_SINGLE_OPS = set("+-*/%(),.;=<>!&|^~@?")


@dataclass
class Token:
    tp: str
    val: object
    pos: int

    def is_kw(self, *kws: str) -> bool:
        return self.tp == KEYWORD and self.val in kws

    def __repr__(self):  # pragma: no cover
        return f"Token({self.tp}, {self.val!r})"


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        # comments
        if c == "#" or (c == "-" and sql[i : i + 3] in ("-- ", "--\t", "--\n", "--\r")) \
                or (c == "-" and sql[i : i + 2] == "--" and i + 2 >= n):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql[i : i + 2] == "/*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise errors.ParseError("unterminated comment")
            i = j + 2
            continue
        # strings
        if c in "'\"":
            start = i
            val, i = _scan_string(sql, i, c)
            toks.append(Token(STRING, val, start))
            continue
        # quoted identifier
        if c == "`":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "`":
                    if sql[j : j + 2] == "``":
                        buf.append("`")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise errors.ParseError("unterminated quoted identifier")
            toks.append(Token(IDENT, "".join(buf), i))
            i = j + 1
            continue
        # hex integer literals 0xNN (HEX token: dual string/number nature,
        # util/types/hex.go)
        if c == "0" and sql[i : i + 2] in ("0x", "0X") and i + 2 < n \
                and sql[i + 2] in "0123456789abcdefABCDEF":
            j = i + 2
            while j < n and sql[j] in "0123456789abcdefABCDEF":
                j += 1
            toks.append(Token(HEX, sql[i + 2 : j], i))
            i = j
            continue
        # bit literals 0bNN / b'0101' (util/types/bit.go ParseBit)
        if c == "0" and sql[i : i + 2] in ("0b", "0B") and i + 2 < n \
                and sql[i + 2] in "01":
            j = i + 2
            while j < n and sql[j] in "01":
                j += 1
            toks.append(Token(BIT, sql[i + 2 : j], i))
            i = j
            continue
        if c in "bB" and sql[i + 1 : i + 2] == "'":
            j = sql.find("'", i + 2)
            if j < 0:
                raise errors.ParseError("unterminated bit literal")
            digits = sql[i + 2 : j]
            if any(ch not in "01" for ch in digits):
                raise errors.ParseError(f"invalid bit literal at {i}")
            toks.append(Token(BIT, digits, i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            tok, i = _scan_number(sql, i)
            toks.append(tok)
            continue
        # hex literal x'4142' (even digit count; token value is the int)
        if c in "xX" and sql[i : i + 2] in ("x'", "X'"):
            j = sql.find("'", i + 2)
            if j < 0:
                raise errors.ParseError("unterminated hex literal")
            digits = sql[i + 2 : j]
            if len(digits) % 2 or any(
                    ch not in "0123456789abcdefABCDEF" for ch in digits):
                raise errors.ParseError(f"invalid hex literal at {i}")
            toks.append(Token(HEX, digits, i))
            i = j + 1
            continue
        # identifiers/keywords
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] == "_" or sql[j] == "$"):
                j += 1
            word = sql[i:j]
            up = word.upper()
            if up in KEYWORDS:
                toks.append(Token(KEYWORD, up, i))
            else:
                toks.append(Token(IDENT, word, i))
            i = j
            continue
        # variables
        if c == "@":
            if sql[i : i + 2] == "@@":
                j = i + 2
                while j < n and (sql[j].isalnum() or sql[j] in "._"):
                    j += 1
                toks.append(Token(SYS_VAR, sql[i + 2 : j], i))
                i = j
                continue
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] in "._"):
                j += 1
            toks.append(Token(USER_VAR, sql[i + 1 : j], i))
            i = j
            continue
        # operators
        for m in _MULTI_OPS:
            if sql.startswith(m, i):
                toks.append(Token(OP, m, i))
                i += len(m)
                break
        else:
            if c == "?":
                toks.append(Token(PARAM, "?", i))
                i += 1
            elif c in _SINGLE_OPS:
                toks.append(Token(OP, c, i))
                i += 1
            else:
                raise errors.ParseError(f"unexpected character {c!r} at {i}")
    toks.append(Token(EOF, None, n))
    return toks


def _scan_string(sql: str, i: int, quote: str) -> tuple[str, int]:
    n = len(sql)
    j = i + 1
    buf: list[str] = []
    while j < n:
        c = sql[j]
        if c == "\\" and j + 1 < n:
            nxt = sql[j + 1]
            buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\x00",
                        "b": "\b", "Z": "\x1a"}.get(nxt, nxt))
            j += 2
            continue
        if c == quote:
            if sql[j : j + 2] == quote * 2:  # doubled quote escape
                buf.append(quote)
                j += 2
                continue
            return "".join(buf), j + 1
        buf.append(c)
        j += 1
    raise errors.ParseError("unterminated string literal")


def _scan_number(sql: str, i: int) -> tuple[Token, int]:
    n = len(sql)
    j = i
    is_float = is_dec = False
    while j < n and sql[j].isdigit():
        j += 1
    if j < n and sql[j] == ".":
        # not range syntax `1..2` (unused) — treat as decimal point
        is_dec = True
        j += 1
        while j < n and sql[j].isdigit():
            j += 1
    if j < n and sql[j] in "eE":
        k = j + 1
        if k < n and sql[k] in "+-":
            k += 1
        if k < n and sql[k].isdigit():
            is_float = True
            j = k
            while j < n and sql[j].isdigit():
                j += 1
    text = sql[i:j]
    if is_float:
        return Token(FLOAT, float(text), i), j
    if is_dec:
        return Token(DECIMAL, Decimal(text), i), j
    return Token(INT, int(text), i), j
