"""Session: the engine's front door — parse → compile → run per statement,
txn lifecycle, optimistic retry, bootstrap.

Reference: tidb.go (Parse :102, Compile :114, runStmt :123), session.go
(Execute :429, GetTxn :566, finishTxn :182, Retry :274), bootstrap.go
(:121 system tables + root user).
"""

from __future__ import annotations

import itertools
import threading

from tidb_tpu import errors, sqlast as ast
from tidb_tpu.executor.builder import ExecutorBuilder
from tidb_tpu.executor.simple import ResultSet, execute_simple, explain_result
from tidb_tpu.kv import backoff as kvbackoff
from tidb_tpu.kv.kv import open_store, register_driver
from tidb_tpu.domain import get_domain
from tidb_tpu.parser.parser import Parser
from tidb_tpu.plan import optimize_plan
from tidb_tpu.plan.builder import PlanBuilder
from tidb_tpu.plan.plans import (
    Deallocate, Delete, Execute, ExplainPlan, Insert, Prepare, ShowPlan,
    SimplePlan, TracePlan, Update,
)
from tidb_tpu.sessionctx import GlobalVars, SessionVars
from tidb_tpu.types import Datum

_conn_id_gen = itertools.count(1)
_global_vars_by_store: dict[str, GlobalVars] = {}


def store_global_var(store, name: str) -> str | None:
    """Hydrated global sysvar value for a store, or None before the
    first session binds it (the supported read for non-session callers
    like TpuClient.__init__)."""
    gv = _global_vars_by_store.get(store.uuid())
    return gv.get(name) if gv is not None else None
_bootstrap_lock = threading.Lock()


def new_store(url: str):
    """'local://path' or 'memory://' → Storage (tidb.go NewStore)."""
    _ensure_drivers()
    return open_store(url)


def _ensure_drivers():
    from tidb_tpu.localstore.store import LocalDriver
    from tidb_tpu.kv import kv as kvmod
    for scheme in ("local", "memory", "goleveldb", "boltdb"):
        if scheme not in kvmod._drivers:
            register_driver(scheme, LocalDriver(scheme))
    if "cluster" not in kvmod._drivers:
        from tidb_tpu.cluster.store import ClusterDriver
        register_driver("cluster", ClusterDriver())


_session_registry: dict[str, dict] = {}   # store uuid → {conn_id: weakref}
_session_registry_lock = __import__("threading").Lock()


def sessions_for(store) -> list["Session"]:
    """Live sessions on a store (SHOW PROCESSLIST / KILL lookup)."""
    with _session_registry_lock:
        d = _session_registry.get(store.uuid(), {})
        out = []
        dead = []
        for cid, ref in d.items():
            s = ref()
            if s is None:
                dead.append(cid)
            else:
                out.append(s)
        for cid in dead:
            d.pop(cid, None)
    return out


def _detail_str(res: dict) -> str:
    """Render one statement's resource-delta dict as the EXECUTION_DETAIL
    string (perfschema) — columnar channel always, then every non-zero
    tally in COUNTER_KEYS display order."""
    from tidb_tpu import tracing
    parts = [f"columnar_hits:{res.get('columnar_hits', 0)}",
             f"columnar_fallbacks:{res.get('columnar_fallbacks', 0)}",
             f"columnar_partials:{res.get('columnar_partials', 0)}"]
    for key in tracing.COUNTER_KEYS:
        if res.get(key):
            parts.append(f"{key}:{res[key]}")
    top = _profile_clause(res)
    if top:
        parts.append(top)
    return " ".join(parts)


def _profile_clause(res: dict) -> str:
    """``profile:<kind>|<sig>:<us>us`` — the statement's top kernel
    signature by device time, read straight from the per-thread kprof
    tally riding the resource dict (no second accounting path)."""
    kprof = {k[6:]: v for k, v in res.items() if k.startswith("kprof.")}
    if not kprof:
        return ""
    from tidb_tpu import profiler
    return f"profile:{profiler.top_signature(kprof)}"


class Session:
    """One connection's state. Reference: session.go session struct."""

    def __init__(self, store, internal: bool = False):
        self.store = store
        self.domain = get_domain(store)
        self.vars = SessionVars()
        self.vars.connection_id = next(_conn_id_gen)
        self.killed = False
        self._exec_depth = 0     # >0 while inside a nested internal execute
        # internal sessions (auth lookups, grant-table edits, stats loads)
        # stay OUT of the processlist/KILL registry: killing the server's
        # auth session would break every subsequent login
        if not internal:
            import weakref
            with _session_registry_lock:
                _session_registry.setdefault(store.uuid(), {})[
                    self.vars.connection_id] = weakref.ref(self)
                # bound across stores: short-lived (test) stores would
                # otherwise pin their dicts forever
                while len(_session_registry) > 64:
                    _session_registry.pop(next(iter(_session_registry)))
        self.global_vars = _global_vars_by_store.setdefault(
            store.uuid(), GlobalVars())
        self.vars._globals = self.global_vars
        self.parser = Parser()
        self._txn = None
        self.history: list[str] = []   # stmt texts for optimistic retry
        self.params: list[Datum] = []
        self.prepared: dict[str, _PreparedStmt] = {}
        # binary-protocol statements: id → entry (server/conn_stmt.go keeps
        # these per connection; one session per connection here)
        self.binary_stmts: dict[int, _PreparedStmt] = {}
        self._next_stmt_id = 0
        self.dirty_tables: set[int] = set()
        self.last_trace = None   # root span of the last traced statement
        # workload digests: the running top-level statement's plan digest
        # (set by _run_plan/_run_instrumented, read at statement end) and
        # whether digesting is live for it (summary enabled, top level)
        self._cur_plan_digest: tuple[str, str] | None = None
        self._digest_on = False
        bootstrap(self)

    @property
    def client(self):
        """Live view of the store's coprocessor client so SET
        tidb_copr_backend (engine swap) affects this session immediately."""
        return self.store.get_client()

    # ------------------------------------------------------------------
    # context surface used by planner/executors (ExecContext duck-type)
    # ------------------------------------------------------------------

    @property
    def current_db(self) -> str:
        return self.vars.current_db

    def info_schema(self):
        return self.domain.info_schema()

    def stats_for(self, table_id: int):
        """Table statistics for the cost-based planner (pseudo until
        ANALYZE TABLE has run; plan/logical_plan_builder.go:884)."""
        return self.domain.stats_for(table_id)

    def txn(self):
        if self._txn is None or not self._txn.valid():
            self._txn = self.store.begin()
            self.dirty_tables = set()
        return self._txn

    def start_ts(self) -> int:
        if self.vars.snapshot_ts is not None:
            return self.vars.snapshot_ts
        return self.txn().start_ts()

    def mark_dirty(self, table_id: int) -> None:
        self.dirty_tables.add(table_id)

    def set_affected_rows(self, n: int) -> None:
        self.vars.affected_rows = n

    def get_sysvar(self, name: str, is_global: bool = False):
        if is_global:
            return self.global_vars.get(name)
        return self.vars.get_system(name, self.global_vars)

    def get_uservar(self, name: str):
        return self.vars.users.get(name.lower())

    def distsql_concurrency(self) -> int:
        return self.vars.distsql_concurrency()

    def plan_ctx(self):
        return self

    # ------------------------------------------------------------------
    # txn control
    # ------------------------------------------------------------------

    def begin_txn(self) -> None:
        self.commit_txn()
        self.txn()  # eager begin so START TRANSACTION pins a snapshot
        self.vars.in_txn = True
        self.history = []

    def commit_txn(self) -> None:
        """Commit with optimistic retry (session.go finishTxn :182)."""
        if self._txn is None:
            self.vars.in_txn = False
            return
        try:
            self._txn.commit()
        except errors.RetryableError:
            self._txn = None
            self._retry()
        finally:
            self._txn = None
            self.vars.in_txn = False
            self.dirty_tables = set()
            self.history = []

    def rollback_txn(self) -> None:
        if self._txn is not None:
            self._txn.rollback()
        self._txn = None
        self.vars.in_txn = False
        self.dirty_tables = set()
        self.history = []

    def _retry(self) -> None:
        """Replay statement history on a fresh snapshot (session.Retry
        :274). History holds the txn's mutating statement texts. Each
        replay is counted (session.retries metric + session_retries
        statement tally) and attributed on a session_retry span;
        exhaustion bumps session.retry_exhausted so optimistic-retry
        storms are visible on /metrics instead of only as errors."""
        from tidb_tpu import metrics, tracing
        stmts = list(self.history)
        last_err = None
        self._in_retry = True
        try:
            for attempt in range(self.vars.retry_limit):
                with tracing.trace("session_retry") as sp:
                    sp.set("attempt", attempt)
                    try:
                        for sql in stmts:
                            self._execute_one(self.parser.parse_one(sql),
                                              sql, record_history=False)
                        if self._txn is not None:
                            self._txn.commit()
                            self._txn = None
                        return
                    except errors.RetryableError as e:
                        metrics.counter("session.retries").inc()
                        tracing.count("session_retries")
                        sp.set("conflict", str(e)[:120])
                        last_err = e
                        if self._txn is not None:
                            self._txn.rollback()
                            self._txn = None
                        continue
        finally:
            self._in_retry = False
        metrics.counter("session.retry_exhausted").inc()
        raise last_err

    # ------------------------------------------------------------------
    # execute
    # ------------------------------------------------------------------

    def execute(self, sql: str) -> list[ResultSet]:
        """Reference: session.Execute (session.go:429)."""
        return [rs for rs in self.execute_each(sql) if rs is not None]

    def execute_each(self, sql: str) -> list[ResultSet | None]:
        """Like execute, but one entry per statement (None for effect-only
        statements) — the wire server needs per-statement results to frame
        one OK/resultset per statement of a multi-statement COM_QUERY."""
        import time as _time
        t0 = _time.perf_counter()
        stmts = self.parser.parse(sql)
        _metric_handles().parse.observe(_time.perf_counter() - t0)
        return [self.execute_stmt(stmt, stmt.text or sql) for stmt in stmts]

    def execute_stmt(self, stmt, sql_text: str) -> ResultSet | None:
        """Execute one already-parsed statement; vars.affected_rows /
        last_insert_id reflect it afterwards (the wire server reads them
        to build the statement's OK packet)."""
        return self._execute_one(stmt, sql_text)

    def _execute_one(self, stmt, sql_text: str,
                     record_history: bool = True) -> ResultSet | None:
        if self.killed:
            # KILL QUERY/CONNECTION semantics, coarse-grained: the flag
            # interrupts the next statement boundary (ER_QUERY_INTERRUPTED)
            self.killed = False
            raise errors.ExecError("Query execution was interrupted",
                                   code=1317)
        from tidb_tpu import perfschema, tracing
        ps = perfschema.perf_for(self.store)
        # statement digest, computed ONCE per top-level statement (the
        # identity every workload surface aggregates on). Internal
        # statements and a disabled summary skip the normalizer — the
        # digest pipeline's cost must be opt-out-able to zero.
        dig = norm = ""
        if self._exec_depth == 0:
            self._cur_plan_digest = None
            self._digest_on = ps.digest_summary.enabled
            if self._digest_on:
                from tidb_tpu import digest as _digest
                dig, norm = _digest.sql_digest(sql_text)
        ev = ps.start_statement(self.vars.connection_id, sql_text, dig)
        import time as _time
        from tidb_tpu.distsql import thread_columnar_counts
        ch0, cf0, cp0 = thread_columnar_counts()
        tally0 = tracing.counters_snapshot()
        kprof0 = tracing.kernel_profile_snapshot()
        t0 = _time.perf_counter()
        from tidb_tpu.sqlast import ShowStmt, ShowType
        if self._exec_depth == 0 and \
                not (isinstance(stmt, ShowStmt)
                     and stmt.tp == ShowType.WARNINGS):
            # new TOP-LEVEL statement resets the diagnostics area; nested
            # internal statements (e.g. persist_global_var's writes to
            # mysql.global_variables) must not wipe the warnings their
            # enclosing statement just produced
            self.vars.warnings = []
        # statement-level span tree: built for every top-level statement
        # while the flight recorder is live (always-on-but-cheap — the
        # tree is RETAINED only when the statement turns out slow,
        # deadline-dead, or degraded) and, as before, when SET
        # tidb_trace_enabled = 1 asked for it explicitly. With both off
        # the path allocates nothing — two dict lookups decide.
        root = None
        trace_tok = None
        trace_on = False
        fr = None
        if self._exec_depth == 0:
            from tidb_tpu import flight
            trace_on = self._tracing_enabled()
            fr = flight.recorder_for(self.store)
            if trace_on or fr.enabled:
                root = tracing.Span("statement")
                root.set("sql", sql_text[:256])
                root.set("conn", self.vars.connection_id)
                trace_tok = tracing.attach(root)
        # the statement's unified Backoffer: ONE budget + deadline
        # (tidb_tpu_max_execution_time) shared by every retry ladder the
        # statement reaches, on this thread and the fan-out workers.
        # Nested internal statements run under the enclosing statement's
        # instance — their retries draw from the same budget.
        bo_attached = self._exec_depth == 0
        bo_tok = kvbackoff.attach(self._statement_backoffer()) \
            if bo_attached else None
        self._exec_depth += 1
        try:
            try:
                rs = self._execute_one_inner(stmt, sql_text, record_history)
            except Exception as e:
                res = self._exec_resources(ch0, cf0, cp0, tally0, kprof0)
                ps.end_statement(ev, error=str(e),
                                 detail=_detail_str(res))
                # errored statements are workload too: their digest rows
                # carry the error count and whatever resources they burned
                self._record_digest(ps, dig, norm, sql_text,
                                    (_time.perf_counter() - t0) * 1e3,
                                    0, 0, True, res)
                self._maybe_flight_record(
                    fr, root, dig, sql_text,
                    (_time.perf_counter() - t0) * 1e3, res,
                    deadline=isinstance(e, errors.DeadlineExceededError),
                    error=str(e))
                raise
        finally:
            self._exec_depth -= 1
            if bo_attached:
                kvbackoff.detach(bo_tok)
            if root is not None:
                tracing.detach(trace_tok)
                root.finish()
                if trace_on:
                    self.last_trace = root
        res = self._exec_resources(ch0, cf0, cp0, tally0, kprof0)
        n_sent = len(rs.rows) if rs is not None else 0
        ps.end_statement(ev, rows_sent=n_sent,
                         rows_affected=self.vars.affected_rows,
                         detail=_detail_str(res))
        self._record_digest(ps, dig, norm, sql_text,
                            (_time.perf_counter() - t0) * 1e3,
                            n_sent, self.vars.affected_rows, False, res)
        self._maybe_flight_record(fr, root, dig, sql_text,
                                  (_time.perf_counter() - t0) * 1e3, res)
        self._maybe_log_slow(sql_text, _time.perf_counter() - t0,
                             res["columnar_hits"],
                             res["columnar_fallbacks"],
                             res["columnar_partials"], res, root, dig)
        if self._exec_depth == 0:
            # metrics time series: lazy interval sampling on statement
            # end — one monotonic read on the miss path
            from tidb_tpu.metrics.timeseries import recorder as _tsrec
            _tsrec.maybe_sample()
        return rs

    def _maybe_flight_record(self, fr, root, dig: str, sql_text: str,
                             elapsed_ms: float, res: dict,
                             deadline: bool = False,
                             error: str = "") -> None:
        """Flight-recorder retention decision for one finished top-level
        statement (success and error paths share it): keep the span tree
        iff the statement crossed the slow-log threshold, died on its
        deadline, or degraded through any tier — otherwise the tree is
        dropped here and the fast path retains nothing."""
        if fr is None or root is None or not fr.enabled:
            return
        from tidb_tpu import flight
        reason = flight.retain_reason(elapsed_ms,
                                      self._slow_threshold_ms(), res,
                                      deadline)
        if reason is None:
            return
        root.finish()   # idempotent; the finally's finish is then a no-op
        fr.record(conn_id=self.vars.connection_id, digest=dig,
                  sql_text=sql_text, duration_ms=elapsed_ms,
                  reason=reason, root=root, resources=res, error=error)

    def _exec_resources(self, ch0: int, cf0: int, cp0: int,
                        tally0: dict, kprof0: dict | None = None) -> dict:
        """One statement's resource deltas — the always-on per-thread
        tallies (columnar channel + device kernels + cache/backoff/
        degradation) diffed over the statement. Computed ONCE at
        statement end; every consumer (perfschema EXECUTION_DETAIL, the
        digest summary, the slow log) reads this same dict, so the
        surfaces cannot disagree. The kernel-profiler per-thread tally
        rides the same dict as int-valued ``kprof.<kind>|<sig>`` keys —
        the statement's profile clause has no second accounting path."""
        from tidb_tpu import tracing
        from tidb_tpu.distsql import thread_columnar_counts
        ch1, cf1, cp1 = thread_columnar_counts()
        res = {"columnar_hits": ch1 - ch0,
               "columnar_fallbacks": cf1 - cf0,
               "columnar_partials": cp1 - cp0}
        res.update(tracing.counters_delta(tally0))
        if kprof0 is not None:
            for label, us in tracing.kernel_profile_delta(kprof0).items():
                res[f"kprof.{label}"] = int(us)
        return res

    def _record_digest(self, ps, dig: str, norm: str, sql_text: str,
                       latency_ms: float, rows_sent: int,
                       rows_affected: int, error: bool,
                       res: dict) -> None:
        """Roll one finished TOP-LEVEL statement into its digest's
        summary entry (no-op for internal statements / disabled
        summary, where `dig` is empty)."""
        if not dig:
            return
        pd, ptext = self._cur_plan_digest or ("", "")
        ps.digest_summary.record(dig, norm, sql_text, pd, ptext,
                                 latency_ms, rows_sent, rows_affected,
                                 error, res)

    def _statement_backoffer(self) -> kvbackoff.Backoffer:
        """One Backoffer per top-level statement: the shared retry-sleep
        budget plus the absolute deadline tidb_tpu_max_execution_time
        prescribes (0/unset = no deadline; session value overrides the
        global default per connection)."""
        import time as _time
        raw = self.vars.get_system("tidb_tpu_max_execution_time",
                                   self.global_vars)
        ms = 0
        if raw:
            try:
                ms = max(0, int(float(raw.strip())))
            except (ValueError, OverflowError):
                ms = 0      # unparseable/inf value must never wedge SET
        deadline = (_time.monotonic() + ms / 1000.0) if ms else None
        return kvbackoff.Backoffer(
            budget_ms=kvbackoff.DEFAULT_STMT_BUDGET_MS, deadline=deadline)

    def _tracing_enabled(self) -> bool:
        """Cheap per-statement check for SET tidb_trace_enabled = 1 —
        two dict lookups, no sysvar machinery."""
        v = self.vars.systems.get("tidb_trace_enabled")
        if v is None:
            v = self.global_vars.values.get("tidb_trace_enabled")
        return v is not None and v.strip().lower() in ("1", "on", "true")

    def _slow_threshold_ms(self) -> float:
        """tidb_slow_log_threshold in ms — the slow log's and the flight
        recorder's shared 'this statement mattered' bound."""
        from tidb_tpu.sessionctx import SYSVAR_DEFAULTS
        raw = self.vars.get_system("tidb_slow_log_threshold",
                                   self.global_vars) \
            or SYSVAR_DEFAULTS["tidb_slow_log_threshold"]
        try:
            return float(raw)
        except ValueError:
            return float(SYSVAR_DEFAULTS["tidb_slow_log_threshold"])

    def _maybe_log_slow(self, sql_text: str, elapsed_s: float,
                        columnar_hits: int = 0,
                        columnar_fallbacks: int = 0,
                        columnar_partials: int = 0,
                        kernel_tally: dict | None = None,
                        root_span=None, digest: str = "") -> None:
        """Slow-query log ([TIME_TABLE_SCAN]-style operator logs,
        executor_distsql.go:849): statements over
        tidb_slow_log_threshold ms go to the 'tidb_tpu.slowlog' logger.
        The detail line carries the statement's device-kernel tallies
        and, when the statement was traced (tidb_trace_enabled), a
        per-region copr summary derived from the span tree."""
        thr_ms = self._slow_threshold_ms()
        if thr_ms > 0 and elapsed_s * 1000 >= thr_ms:
            import logging
            kt = kernel_tally or {}
            detail = (" kernel_dispatches:%d readbacks:%d "
                      "readback_bytes:%d jit_hits:%d jit_misses:%d" % (
                          kt.get("kernel_dispatches", 0),
                          kt.get("readbacks", 0),
                          kt.get("readback_bytes", 0),
                          kt.get("jit_hits", 0),
                          kt.get("jit_misses", 0)))
            # plane-cache tallies (per-partial attribution from the
            # region responses) appear whenever the statement touched
            # the cache — same monotonic-diff contract as columnar_hits.
            # Backoff/degradation/retry tallies follow: a slow statement
            # shows WHERE its time went (retry sleeps) and which tiers
            # it fell back through.
            for key in ("batched", "plane_cache_hits", "plane_cache_misses",
                        "plane_cache_evictions",
                        "plane_cache_invalidations_epoch",
                        "plane_cache_invalidations_version",
                        "backoff_retries", "backoff_ms", "session_retries",
                        "degraded_device", "degraded_join",
                        "degraded_combine", "degraded_batch"):
                if kt.get(key):
                    detail += f" {key}:{kt[key]}"
            if root_span is not None:
                tasks = root_span.find("region_task")
                if tasks:
                    worst = max(tasks,
                                key=lambda t: t.attrs.get("run_us", 0))
                    detail += (" copr_tasks:%d copr_retries:%d "
                               "copr_max_task_ms:%.2f" % (
                                   len(tasks),
                                   sum(t.attrs.get("retries", 0)
                                       for t in tasks),
                                   worst.attrs.get("run_us", 0) / 1e3))
            top = _profile_clause(kt)
            if top:
                # top kernel signature by device time — same per-thread
                # kprof tally EXECUTION_DETAIL renders, not a re-count
                detail += f" {top}"
            if digest:
                # the digest joins slow-log lines to their summary row
                detail += f" digest:{digest}"
            # hits/fallbacks count per PARTIAL: a mixed multi-region
            # response (some regions columnar, some row-fallback) shows
            # both sides on the statement's own line
            logging.getLogger("tidb_tpu.slowlog").warning(
                "[SLOW_QUERY] cost_time:%.3fs conn:%s columnar_hits:%d "
                "columnar_fallbacks:%d columnar_partials:%d%s sql:%s",
                elapsed_s, self.vars.connection_id, columnar_hits,
                columnar_fallbacks, columnar_partials, detail,
                sql_text[:2048])
            from tidb_tpu import metrics
            metrics.counter("server.slow_queries").inc()

    def _execute_one_inner(self, stmt, sql_text: str,
                           record_history: bool = True) -> ResultSet | None:
        import time as _time
        m = _metric_handles()
        # schema-validity kill-switch (session.go:430
        # checkSchemaValidOrRollback): fail fast when the reload loop
        # stalled past the lease
        self.domain.check_schema_valid()
        self.vars.affected_rows = 0
        m.stmt_counter(type(stmt)).inc()
        if self.vars.user:
            # authenticated sessions (wire connections) pass the privilege
            # check; library/internal sessions have no user and skip it
            # (privilege/privilege.go Checker bound per-session)
            from tidb_tpu import privilege
            privilege.check_stmt(self, stmt)
        from tidb_tpu.plan.preprocess import validate as _validate
        _validate(stmt)
        if _is_simple(stmt):
            return execute_simple(self, stmt)

        # phase histograms mirror metrics.go:20-45 (compile/run durations)
        t0 = _time.perf_counter()
        plan = optimize_plan(PlanBuilder(self).build(stmt), self, self.client,
                             self.dirty_tables)
        m.compile.observe(_time.perf_counter() - t0)
        t1 = _time.perf_counter()
        try:
            return self._dispatch_plan(plan, sql_text, record_history)
        finally:
            m.run.observe(_time.perf_counter() - t1)

    def _dispatch_plan(self, plan, sql_text: str,
                       record_history: bool) -> ResultSet | None:
        """Route an optimized plan to its executor — shared by the direct
        path and EXECUTE (so prepared SHOW/SET/EXPLAIN work too)."""
        if isinstance(plan, (ShowPlan, SimplePlan)):
            return execute_simple(self, plan.stmt)
        if isinstance(plan, TracePlan):
            return self._run_traced_plan(plan, sql_text, record_history)
        if isinstance(plan, ExplainPlan):
            if plan.analyze:
                return self._run_explain_analyze(plan, sql_text,
                                                 record_history)
            return explain_result(plan.target)
        if isinstance(plan, Prepare):
            return self._do_prepare(plan)
        if isinstance(plan, Deallocate):
            return self._do_deallocate(plan)
        if isinstance(plan, Execute):
            return self._do_execute(plan, sql_text, record_history)
        return self._run_plan(plan, sql_text, record_history)

    def _note_plan(self, plan) -> None:
        """Plan digest for the running top-level statement — computed at
        dispatch, where the physical tree exists, once per statement
        (nested internal statements run at depth ≥ 2 and are skipped)."""
        if self._digest_on and self._exec_depth == 1:
            from tidb_tpu import digest as _digest
            self._cur_plan_digest = _digest.plan_digest(plan)

    def _run_plan(self, plan, sql_text: str,
                  record_history: bool = True) -> ResultSet | None:
        is_write = isinstance(plan, (Insert, Update, Delete))
        self._note_plan(plan)
        executor = ExecutorBuilder(self).build(plan)
        try:
            if is_write:
                while executor.next() is not None:
                    pass
                rs = None
                if record_history:
                    self.history.append(sql_text)
            else:
                rows = []
                while True:
                    row = executor.next()
                    if row is None:
                        break
                    rows.append(row)
                fields = [(c.col_name, c.ret_type) for c in plan.schema]
                rs = ResultSet(fields, rows)
        except Exception:
            if not self.vars.in_txn:
                self.rollback_txn()
            raise
        finally:
            executor.close()

        # autocommit: commit unless inside an explicit txn or a retry
        # replay. Read statements commit too — their txn must be released
        # or the session pins one snapshot (and its MVCC versions) forever.
        if not self.vars.in_txn and not getattr(self, "_in_retry", False):
            if self.vars.autocommit:
                self.commit_txn()
        return rs

    # ------------------------------------------------------------------
    # EXPLAIN ANALYZE / TRACE (executor/explain.go, executor/trace.go)
    # ------------------------------------------------------------------

    def _run_instrumented(self, target, sql_text: str,
                          record_history: bool):
        """Execute a physical plan to completion under a fresh trace
        root with an instrumented executor tree. Returns (executor,
        root_span, rows_drained); the caller renders either the
        annotated plan (EXPLAIN ANALYZE) or the span tree (TRACE).
        Transaction semantics match _run_plan — write targets really
        write, autocommit applies."""
        from tidb_tpu import tracing
        from tidb_tpu.executor.instrument import instrument_tree
        is_write = isinstance(target, (Insert, Update, Delete))
        self._note_plan(target)
        root = tracing.Span("statement")
        root.set("sql", sql_text[:256])
        root.set("conn", self.vars.connection_id)
        tok = tracing.attach(root)
        executor = ExecutorBuilder(self).build(target)
        instrument_tree(executor)
        n_rows = 0
        try:
            try:
                while executor.next() is not None:
                    n_rows += 1
                if is_write and record_history:
                    self.history.append(sql_text)
            except Exception:
                if not self.vars.in_txn:
                    self.rollback_txn()
                raise
            finally:
                executor.close()
        finally:
            tracing.detach(tok)
            root.finish()
        if not self.vars.in_txn and not getattr(self, "_in_retry", False):
            if self.vars.autocommit:
                self.commit_txn()
        self.last_trace = root
        return executor, root, n_rows

    def _run_explain_analyze(self, plan: ExplainPlan, sql_text: str,
                             record_history: bool) -> ResultSet:
        from tidb_tpu.executor.instrument import analyze_rows
        from tidb_tpu.executor.simple import _str_rs
        executor, root, _ = self._run_instrumented(plan.target, sql_text,
                                                   record_history)
        return _str_rs(["id", "actRows", "loops", "time_ms",
                        "execution info"], analyze_rows(executor, root))

    def _run_traced_plan(self, plan: TracePlan, sql_text: str,
                         record_history: bool) -> ResultSet:
        import json as _json

        from tidb_tpu.executor.instrument import operators_dict
        from tidb_tpu.executor.simple import _str_rs
        executor, root, n_rows = self._run_instrumented(
            plan.target, sql_text, record_history)
        doc = root.to_dict()
        doc["rows_returned"] = n_rows
        doc["operators"] = operators_dict(executor)
        if plan.format == "row":
            rows = []

            def walk(sp, depth):
                rows.append(["  " * depth + sp.name,
                             f"{sp.duration_us():.1f}"])
                for c in sp.children:
                    walk(c, depth + 1)

            walk(root, 0)
            return _str_rs(["operation", "duration_us"], rows)
        return _str_rs(["trace"], [[_json.dumps(doc)]])

    # ------------------------------------------------------------------
    # prepared statements (executor/prepared.go, session.go:478-563)
    # ------------------------------------------------------------------

    def _do_prepare(self, plan: Prepare) -> None:
        text = plan.sql_text
        if plan.from_var:
            v = self.get_uservar(plan.from_var)
            if v is None:
                raise errors.ExecError(
                    f"user variable @{plan.from_var} is not set")
            text = v.get_string() if isinstance(v, Datum) else str(v)
        p = Parser()
        stmts = p.parse(text)
        if len(stmts) != 1:
            raise errors.ExecError(
                "Can not prepare multiple statements")
        inner = stmts[0]
        if isinstance(inner, (ast.PrepareStmt, ast.ExecuteStmt,
                              ast.DeallocateStmt)):
            raise errors.ExecError(
                "This command is not supported in the prepared statement "
                "protocol yet")
        from tidb_tpu.plan.preprocess import validate as _validate
        _validate(inner, in_prepare=True)
        self.prepared[plan.name.lower()] = _PreparedStmt(
            inner, len(p.param_markers), text)
        return None

    def prepare_binary(self, text: str) -> tuple[int, int]:
        """COM_STMT_PREPARE: → (statement id, param count)
        (server/conn_stmt.go:47 handleStmtPrepare)."""
        p = Parser()
        stmts = p.parse(text)
        if len(stmts) != 1:
            raise errors.ExecError("Can not prepare multiple statements")
        inner = stmts[0]
        if isinstance(inner, (ast.PrepareStmt, ast.ExecuteStmt,
                              ast.DeallocateStmt)):
            raise errors.ExecError(
                "This command is not supported in the prepared statement "
                "protocol yet")
        from tidb_tpu.plan.preprocess import validate as _validate
        _validate(inner, in_prepare=True)
        self._next_stmt_id += 1
        sid = self._next_stmt_id
        self.binary_stmts[sid] = _PreparedStmt(inner, len(p.param_markers),
                                               text)
        return sid, len(p.param_markers)

    def execute_binary(self, stmt_id: int, values: list):
        """COM_STMT_EXECUTE with decoded params → ResultSet | None."""
        ent = self.binary_stmts.get(stmt_id)
        if ent is None:
            raise errors.ExecError(
                f"Unknown prepared statement handler ({stmt_id}) "
                "given to EXECUTE", code=1243)
        if self.killed:
            self.killed = False
            raise errors.ExecError("Query execution was interrupted",
                                   code=1317)
        # autocommit is handled inside _run_plan (run_prepared ends there).
        # The binary path bypasses _execute_one, so the statement
        # Backoffer (budget + tidb_tpu_max_execution_time deadline)
        # attaches here — and the depth bump makes nested internal
        # statements (persist_global_var etc.) share THIS instance
        # instead of shadowing it with a fresh deadline. Statement
        # accounting (perfschema event + digest summary) attaches here
        # too: COM_STMT_EXECUTE statements are workload like any other,
        # and the prepared text's digest is computed ONCE per handle
        # (its '?' markers normalize identically to folded literals, so
        # binary and text executions of one shape share a digest).
        import time as _time

        from tidb_tpu import perfschema, tracing
        ps = perfschema.perf_for(self.store)
        self._cur_plan_digest = None
        self._digest_on = ps.digest_summary.enabled
        dig = norm = ""
        if self._digest_on:
            if ent.digest_pair is None:
                from tidb_tpu import digest as _digest
                ent.digest_pair = _digest.sql_digest(ent.text)
            dig, norm = ent.digest_pair
        ev = ps.start_statement(self.vars.connection_id, ent.text, dig)
        from tidb_tpu.distsql import thread_columnar_counts
        ch0, cf0, cp0 = thread_columnar_counts()
        tally0 = tracing.counters_snapshot()
        kprof0 = tracing.kernel_profile_snapshot()
        t0 = _time.perf_counter()
        bo_tok = kvbackoff.attach(self._statement_backoffer())
        self._exec_depth += 1
        try:
            rs = self.run_prepared(ent, values, ent.text)
        except Exception as e:
            res = self._exec_resources(ch0, cf0, cp0, tally0, kprof0)
            ps.end_statement(ev, error=str(e), detail=_detail_str(res))
            self._record_digest(ps, dig, norm, ent.text,
                                (_time.perf_counter() - t0) * 1e3,
                                0, 0, True, res)
            raise
        finally:
            self._exec_depth -= 1
            kvbackoff.detach(bo_tok)
        res = self._exec_resources(ch0, cf0, cp0, tally0, kprof0)
        n_sent = len(rs.rows) if rs is not None else 0
        ps.end_statement(ev, rows_sent=n_sent,
                         rows_affected=self.vars.affected_rows,
                         detail=_detail_str(res))
        self._record_digest(ps, dig, norm, ent.text,
                            (_time.perf_counter() - t0) * 1e3,
                            n_sent, self.vars.affected_rows, False, res)
        return rs

    def close_binary(self, stmt_id: int) -> None:
        self.binary_stmts.pop(stmt_id, None)

    def _do_deallocate(self, plan: Deallocate) -> None:
        if self.prepared.pop(plan.name.lower(), None) is None:
            raise errors.ExecError(
                f"Unknown prepared statement handler ({plan.name}) "
                "given to DEALLOCATE PREPARE")
        return None

    def _do_execute(self, plan: Execute, sql_text: str,
                    record_history: bool) -> ResultSet | None:
        ent = self.prepared.get(plan.name.lower())
        if ent is None:
            raise errors.ExecError(
                f"Unknown prepared statement handler ({plan.name}) "
                "given to EXECUTE")
        values: list[Datum] = []
        for vn in plan.using:
            v = self.get_uservar(vn)
            if isinstance(v, Datum):
                values.append(v)
            elif v is None:
                from tidb_tpu.types.datum import NULL
                values.append(NULL)
            else:
                values.append(Datum.string(str(v)))
        return self.run_prepared(ent, values, sql_text, record_history)

    def run_prepared(self, ent: "_PreparedStmt", values: list,
                     sql_text: str, record_history: bool = False):
        """Execute a prepared entry with bound param Datums — shared by
        text EXECUTE and the binary COM_STMT_EXECUTE path
        (server/conn_stmt.go:104 handleStmtExecute)."""
        if len(values) != ent.param_count:
            raise errors.ExecError("Incorrect arguments to EXECUTE")
        self.params = values
        try:
            # plan cache: reusable because ParamExpr reads live bindings;
            # keyed by schema version + stats version (ANALYZE must evict
            # plans whose access path was costed on older histograms) + the
            # coprocessor client OBJECT (a held reference — id() could be
            # recycled after an engine swap), and bypassed while the txn
            # holds dirty writes (UnionScan wiring is dirty-state-dependent)
            key = (self.domain.info_schema().version,
                   self.domain.stats_version, self.client)
            phys = None
            if ent.plan is not None and ent.plan_key is not None \
                    and ent.plan_key[:2] == key[:2] \
                    and ent.plan_key[2] is key[2] \
                    and not self.dirty_tables:
                phys = ent.plan
                self.vars.last_plan_from_cache = True
            else:
                self.vars.last_plan_from_cache = False
            if self.vars.user:
                # EXECUTE runs the PREPAREd statement — check THAT, not
                # the ExecuteStmt shell (else prepare is a privilege hole)
                from tidb_tpu import privilege
                privilege.check_stmt(self, ent.stmt)
            if phys is None:
                phys = optimize_plan(PlanBuilder(self).build(ent.stmt),
                                     self, self.client, self.dirty_tables)
                if not self.dirty_tables:
                    ent.plan, ent.plan_key = phys, key
            return self._dispatch_plan(phys, sql_text, record_history)
        finally:
            self.params = []

    def apply_copr_backend(self, backend: str) -> None:
        """SET tidb_copr_backend = 'cpu' | 'tpu' — swap the coprocessor
        engine behind kv.Client. The client is a store-level seam (one
        engine serves every session on the storage), mirroring how the
        reference selects its coprocessor implementation per store."""
        backend = backend.strip().lower()
        if not backend:
            raise errors.ExecError(
                "tidb_copr_backend cannot be NULL/empty; "
                "use 'cpu' or 'tpu' (swaps the engine store-wide)")
        # the knob swaps the engine for EVERY session on this store —
        # a store-global action needs the global Grant privilege
        self._require_global_grant("tidb_copr_backend")
        if backend == "tpu":
            from tidb_tpu.ops import TpuClient
            if not isinstance(self.store.get_client(), TpuClient):
                # honor the floor sysvar (session override, then global —
                # the persisted global survives store restarts) so a floor
                # set before the engine swap isn't silently lost
                floor = None
                sval = self.vars.get_system("tidb_tpu_dispatch_floor",
                                            self.global_vars)
                if sval is not None:
                    try:
                        floor = max(0, int(sval.strip()))
                    except ValueError:
                        pass
                # (device_join resolves itself in TpuClient.__init__
                # from this store's hydrated global-var cache)
                self.store.set_client(
                    TpuClient(self.store, dispatch_floor_rows=floor))
        elif backend == "cpu":
            factory = getattr(self.store, "copr_cpu_client", None)
            if factory is not None:
                self.store.set_client(factory())
        else:
            raise errors.ExecError(
                f"unknown tidb_copr_backend {backend!r} (cpu | tpu)")
        # the var mirrors live store state: keep the cache in step with
        # the engine actually installed so @@tidb_copr_backend never lies
        self.global_vars.values["tidb_copr_backend"] = backend

    def apply_tpu_dispatch_floor(self, value: str) -> None:
        """SET tidb_tpu_dispatch_floor = N — rows below which a routable
        request answers on CPU (0 disables the floor). Like the backend
        switch, the floor lives on the store-level client, so it applies
        to every session on this storage."""
        try:
            floor = int(value.strip())
        except ValueError:
            raise errors.ExecError(
                f"tidb_tpu_dispatch_floor must be an integer, got {value!r}")
        if floor < 0:
            raise errors.ExecError(
                "tidb_tpu_dispatch_floor must be >= 0")
        # store-wide blast radius (every session's routing changes):
        # same global Grant gate as the backend switch above
        self._require_global_grant("tidb_tpu_dispatch_floor")
        client = self.store.get_client()
        for target in (client, getattr(client, "cpu", None)):
            # TpuClient, and any fan-out client carrying the floor (the
            # cluster DistCoprClient routes executor joins by it)
            if target is not None and hasattr(target,
                                              "dispatch_floor_rows"):
                target.dispatch_floor_rows = floor

    def _require_global_grant(self, name: str) -> None:
        """Store-level engine knobs change behavior for EVERY session on
        this storage — authenticated sessions need the global Grant
        privilege; library/internal sessions (no user) skip the check."""
        if not self.vars.user:
            return
        from tidb_tpu import privilege
        if not privilege.checker_for(self.store).check(
                self.vars.user, "", "", "Grant",
                host=self.vars.client_host):
            raise privilege.AccessDenied(
                f"user '{self.vars.user}' needs the global GRANT "
                f"privilege to set {name}")

    def _apply_tpu_bool_switch(self, name: str, attr: str,
                               value: str) -> None:
        """Shared SET GLOBAL handler for the store-level client bool
        switches: validate the literal, gate on the global Grant
        privilege (store-wide blast radius, like the dispatch floor),
        then flip the attribute on the installed client — TpuClient or
        the cluster fan-out DistCoprClient, whichever carries it — AND
        on a TpuClient's CPU fallback engine, so a fallback-routed
        request on a cluster store honors the same switch."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"{name} must be 0 or 1, got {value!r}")
        enabled = parse_bool_sysvar(value)
        self._require_global_grant(name)
        client = self.store.get_client()
        for target in (client, getattr(client, "cpu", None)):
            if target is not None and hasattr(target, attr):
                setattr(target, attr, enabled)

    def apply_tpu_device_join(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_device_join = 0|1 — the executor-join
        device-routing kill switch (every session's joins re-route)."""
        self._apply_tpu_bool_switch("tidb_tpu_device_join", "device_join",
                                    value)

    def apply_tpu_columnar_scan(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_columnar_scan = 0|1 — the columnar result
        channel kill switch (every session's scan responses re-route)."""
        self._apply_tpu_bool_switch("tidb_tpu_columnar_scan",
                                    "columnar_scan", value)

    def apply_tpu_device_dict(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_device_dict = 0|1 — the dictionary
        execution tier's kill switch: 0 pins every string/multi-key
        equi-join to the row-at-a-time dict path (the parity oracle).
        Off also disables further registry registration; existing
        dictionaries stay (they are append-only supersets — harmless,
        and re-enable starts warm)."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        self._apply_tpu_bool_switch("tidb_tpu_device_dict", "device_dict",
                                    value)
        from tidb_tpu.copr.dictionary import registry_for
        reg = registry_for(self.store)
        if reg is not None:
            reg.enabled = parse_bool_sysvar(value)

    def apply_tpu_dict_max_ndv(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_dict_max_ndv = R — the distinct/rows
        ratio above which a string join key bails to the dict path
        (counted on copr.degraded_dict) and a column is refused registry
        registration (copr.dict.rejected_ndv)."""
        try:
            ratio = float(value.strip())
        except ValueError:
            raise errors.ExecError(
                f"tidb_tpu_dict_max_ndv must be a number, got {value!r}")
        if not 0.0 < ratio <= 1.0:
            raise errors.ExecError(
                "tidb_tpu_dict_max_ndv must be in (0, 1]")
        self._require_global_grant("tidb_tpu_dict_max_ndv")
        client = self.store.get_client()
        for target in (client, getattr(client, "cpu", None)):
            if target is not None and hasattr(target, "dict_max_ndv"):
                target.dict_max_ndv = ratio
        from tidb_tpu.copr.dictionary import registry_for
        reg = registry_for(self.store)
        if reg is not None:
            reg.max_ndv_ratio = ratio

    def apply_tpu_plane_cache(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_plane_cache = 0|1 — the packed-plane cache
        kill switch: flips the in-proc TpuClient batch cache (client
        attribute) AND the cluster store's per-region plane cache. Off
        re-packs every columnar scan from the MVCC store — the parity
        oracle for cache correctness."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        self._apply_tpu_bool_switch("tidb_tpu_plane_cache",
                                    "plane_cache_enabled", value)
        enabled = parse_bool_sysvar(value)
        if not enabled:
            # a disabled cache must also stop HOLDING: dropping entries
            # frees the budget (and the device pins) and makes re-enable
            # start cold — for the in-proc TpuClient batch cache too,
            # which is the documented contract of this switch
            client = self.store.get_client()
            for target in (client, getattr(client, "cpu", None)):
                bc = getattr(target, "_batch_cache", None)
                if bc is not None:
                    bc.clear()
        from tidb_tpu.copr.plane_cache import cache_for
        pc = cache_for(self.store)
        if pc is not None:
            pc.enabled = enabled
            if not enabled:
                pc.clear()

    def apply_tpu_delta_pack(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_delta_pack = 0|1 — the HTAP freshness
        tier's kill switch: off drops every region delta pack and
        restores invalidate-on-commit (the parity oracle for base+delta
        merges); per-table commit filtering stays on either way."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"tidb_tpu_delta_pack must be 0 or 1, got {value!r}")
        self._require_global_grant("tidb_tpu_delta_pack")
        from tidb_tpu.copr.delta import delta_for
        ds = delta_for(self.store)
        if ds is not None:
            ds.set_enabled(parse_bool_sysvar(value))

    def apply_tpu_delta_budget_rows(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_delta_budget_rows = N — rows a region
        delta pack may accrue before the next scan folds it into a fresh
        base entry (the background re-pack trigger)."""
        n = self._int_sysvar("tidb_tpu_delta_budget_rows", value, 1)
        self._require_global_grant("tidb_tpu_delta_budget_rows")
        from tidb_tpu.copr.delta import delta_for
        ds = delta_for(self.store)
        if ds is not None:
            ds.budget_rows = n

    def apply_slow_trace_max_spans(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_slow_trace_max_spans = N — per-entry span
        budget of the flight recorder (0 = unbounded): oversized trees
        keep the root + slowest subtrees and stamp truncated=true."""
        n = self._int_sysvar("tidb_tpu_slow_trace_max_spans", value)
        self._require_global_grant("tidb_tpu_slow_trace_max_spans")
        from tidb_tpu import flight
        flight.recorder_for(self.store).set_max_spans(n)

    def apply_tpu_micro_batch(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_micro_batch = 0|1 — the micro-batch tier
        kill switch: 0 pins every below-floor statement to the solo
        route (the parity oracle for batched dispatch)."""
        self._apply_tpu_bool_switch("tidb_tpu_micro_batch", "micro_batch",
                                    value)

    def apply_tpu_batch_window(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_batch_window_ms = N — how long the first
        below-floor statement of a gather cycle waits for peers."""
        ms = self._int_sysvar("tidb_tpu_batch_window_ms", value)
        self._require_global_grant("tidb_tpu_batch_window_ms")
        client = self.store.get_client()
        for target in (client, getattr(client, "cpu", None)):
            if target is not None and hasattr(target, "batch_window_ms"):
                target.batch_window_ms = ms

    def apply_conn_queue_depth(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_conn_queue_depth = N — the wire server's
        admission queue depth (read live per accept; no state to flip)."""
        self._int_sysvar("tidb_tpu_conn_queue_depth", value)
        self._require_global_grant("tidb_tpu_conn_queue_depth")

    def apply_drain_pool_size(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_drain_pool_size = N — the shared fan-out
        drain pool's worker bound. Process-wide (every store's fan-outs
        share the pool), like tidb_tpu_mesh."""
        n = self._int_sysvar("tidb_tpu_drain_pool_size", value, 1)
        self._require_global_grant("tidb_tpu_drain_pool_size")
        from tidb_tpu.cluster.pool import set_pool_size
        set_pool_size(n)

    def apply_tpu_kernel_profile(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_kernel_profile = 0|1 — the continuous
        kernel profiler's kill switch. Off clears the per-signature
        registry and the lock-hold ring, so a disabled profiler retains
        nothing (the overhead guard asserts exactly that). Process-wide
        like tidb_tpu_mesh: the dispatch-serial lock is one per process."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"tidb_tpu_kernel_profile must be 0 or 1, got {value!r}")
        self._require_global_grant("tidb_tpu_kernel_profile")
        from tidb_tpu import profiler
        profiler.set_enabled(parse_bool_sysvar(value))

    def apply_tpu_profile_max_signatures(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_profile_max_signatures = N — registry
        cardinality bound: signature N+1 and beyond fold into a per-kind
        ~overflow bucket (device_us totals stay exact)."""
        n = self._int_sysvar("tidb_tpu_profile_max_signatures", value, 1)
        self._require_global_grant("tidb_tpu_profile_max_signatures")
        from tidb_tpu import profiler
        profiler.set_max_signatures(n)

    def apply_tpu_mesh(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_mesh = 0|1 — the mesh execution tier
        (ops.mesh): off pins the partial-aggregate combine and the join
        probe to the single-device kernels. Process-wide (the mesh spans
        physical chips), so this flips the ops.mesh module flag; a
        jax-free process validates and persists but has nothing to
        flip."""
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"tidb_tpu_mesh must be 0 or 1, got {value!r}")
        self._require_global_grant("tidb_tpu_mesh")
        try:
            from tidb_tpu.ops import mesh as mesh_mod
        except ImportError:   # retryable-ok: jax-free process, flag moot
            return
        mesh_mod.set_enabled(parse_bool_sysvar(value))

    def apply_tpu_hbm_budget(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_hbm_budget_bytes = auto|0|N — the HBM
        governance ledger's budget (ops.membudget): 'auto' derives from
        the backend, 0 is the kill switch (unlimited — joins stay
        unpartitioned), N caps the ledger and routes oversized join
        build sides into radix-partitioned passes. Process-wide like
        tidb_tpu_mesh; a jax-free process validates and persists but
        resolves 'auto' to unlimited."""
        from tidb_tpu.sessionctx import parse_hbm_budget_spec
        try:
            parse_hbm_budget_spec(value)
        except ValueError as e:
            raise errors.ExecError(str(e))
        self._require_global_grant("tidb_tpu_hbm_budget_bytes")
        try:
            from tidb_tpu.ops import membudget
        except ImportError:   # retryable-ok: jax-free process, ledger moot
            return
        membudget.set_budget(value)

    def apply_tpu_plane_cache_bytes(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_plane_cache_bytes = N — the plane cache's
        LRU byte budget (evicts immediately when shrunk)."""
        try:
            budget = int(value.strip())
        except ValueError:
            raise errors.ExecError(
                f"tidb_tpu_plane_cache_bytes must be an integer, "
                f"got {value!r}")
        if budget < 0:
            raise errors.ExecError(
                "tidb_tpu_plane_cache_bytes must be >= 0")
        self._require_global_grant("tidb_tpu_plane_cache_bytes")
        from tidb_tpu.copr.plane_cache import cache_for
        pc = cache_for(self.store)
        if pc is not None:
            pc.set_budget(budget)

    def _int_sysvar(self, name: str, value: str, lo: int = 0) -> int:
        try:
            n = int(value.strip())
        except ValueError:
            raise errors.ExecError(
                f"{name} must be an integer, got {value!r}")
        if n < lo:
            raise errors.ExecError(f"{name} must be >= {lo}")
        return n

    def apply_stmt_summary(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_stmt_summary = 0|1 — the statement-digest
        summary kill switch. Off clears the summary (current + history)
        and skips the whole digest pipeline per statement; on starts a
        fresh window."""
        from tidb_tpu import perfschema
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"tidb_tpu_stmt_summary must be 0 or 1, got {value!r}")
        self._require_global_grant("tidb_tpu_stmt_summary")
        perfschema.perf_for(self.store).digest_summary.set_enabled(
            parse_bool_sysvar(value))

    def apply_stmt_summary_max_digests(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_stmt_summary_max_digests = N — the
        summary's per-window entry cap (shrink evicts immediately; every
        eviction is counted in events_statements_summary_evicted)."""
        n = self._int_sysvar("tidb_tpu_stmt_summary_max_digests", value, 1)
        self._require_global_grant("tidb_tpu_stmt_summary_max_digests")
        from tidb_tpu import perfschema
        perfschema.perf_for(self.store).digest_summary.set_max_digests(n)

    def apply_stmt_summary_refresh_interval(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_stmt_summary_refresh_interval = seconds —
        the summary window length (TOP-SQL's time-bucket width)."""
        n = self._int_sysvar("tidb_tpu_stmt_summary_refresh_interval",
                             value, 1)
        self._require_global_grant("tidb_tpu_stmt_summary_refresh_interval")
        from tidb_tpu import perfschema
        perfschema.perf_for(self.store).digest_summary \
            .set_refresh_interval(float(n))

    def apply_stmt_summary_history_size(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_stmt_summary_history_size = N — rotated
        windows kept in _history (a bounded ring)."""
        n = self._int_sysvar("tidb_tpu_stmt_summary_history_size", value, 1)
        self._require_global_grant("tidb_tpu_stmt_summary_history_size")
        from tidb_tpu import perfschema
        perfschema.perf_for(self.store).digest_summary.set_history_size(n)

    def apply_perfschema_history_cap(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_perfschema_history_cap = N — the
        events_statements_history ring size (long-running sessions must
        not grow it without limit; a shrink keeps the newest events)."""
        n = self._int_sysvar("tidb_tpu_perfschema_history_cap", value, 1)
        self._require_global_grant("tidb_tpu_perfschema_history_cap")
        from tidb_tpu import perfschema
        perfschema.perf_for(self.store).set_history_cap(n)

    def apply_flight_recorder(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_flight_recorder = 0|1 — the slow-trace
        flight recorder: off stops building the always-on span trees and
        clears the retained ring (tidb_trace_enabled / EXPLAIN ANALYZE
        still trace explicitly)."""
        from tidb_tpu import flight
        from tidb_tpu.sessionctx import parse_bool_sysvar
        if value.strip().lower() not in ("0", "1", "on", "off", "true",
                                         "false"):
            raise errors.ExecError(
                f"tidb_tpu_flight_recorder must be 0 or 1, got {value!r}")
        self._require_global_grant("tidb_tpu_flight_recorder")
        flight.recorder_for(self.store).set_enabled(
            parse_bool_sysvar(value))

    def apply_slow_trace_cap(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_slow_trace_cap = N — retained slow traces
        kept per store (shrink drops the oldest immediately)."""
        n = self._int_sysvar("tidb_tpu_slow_trace_cap", value, 1)
        self._require_global_grant("tidb_tpu_slow_trace_cap")
        from tidb_tpu import flight
        flight.recorder_for(self.store).set_cap(n)

    def apply_metrics_interval(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_metrics_interval_ms = N — the metrics
        time-series sampling interval (process-wide, like the registry
        it samples)."""
        n = self._int_sysvar("tidb_tpu_metrics_interval_ms", value, 10)
        self._require_global_grant("tidb_tpu_metrics_interval_ms")
        from tidb_tpu.metrics.timeseries import recorder
        recorder.set_interval(n / 1000.0)

    def apply_metrics_history_cap(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_metrics_history_cap = N — samples the
        metrics time-series ring retains (shrink keeps the newest)."""
        n = self._int_sysvar("tidb_tpu_metrics_history_cap", value, 2)
        self._require_global_grant("tidb_tpu_metrics_history_cap")
        from tidb_tpu.metrics.timeseries import recorder
        recorder.set_cap(n)

    def apply_inspection_threshold(self, name: str, value: str) -> None:
        """SET GLOBAL tidb_tpu_inspection_<rule-key> = N — one
        inspection-rule threshold (tidb_tpu.inspection), applied live to
        the process rule engine (the metrics registry it judges is
        process-wide too)."""
        self._require_global_grant(name)
        from tidb_tpu import inspection
        try:
            inspection.set_threshold(name, value)
        except ValueError as e:
            raise errors.ExecError(str(e))

    def apply_conn_queue_timeout(self, value: str) -> None:
        """SET GLOBAL tidb_tpu_conn_queue_timeout_ms = N — the admission
        queue's server-side wait deadline (0 = wait forever; the server
        reads it live per sweep, nothing to flip here)."""
        self._int_sysvar("tidb_tpu_conn_queue_timeout_ms", value)
        self._require_global_grant("tidb_tpu_conn_queue_timeout_ms")

    def persist_global_var(self, name: str, value: str) -> None:
        """Write-through to mysql.global_variables (session.go globalVars)."""
        if self.store.uuid() not in _BOOTSTRAPPED_STORES:
            return  # called from inside bootstrap itself
        esc_n = name.lower().replace("'", "''")
        esc_v = value.replace("'", "''")
        self.execute(
            "update mysql.global_variables set variable_value = "
            f"'{esc_v}' where variable_name = '{esc_n}'")
        if self.vars.affected_rows == 0:
            # affected counts CHANGED rows (MySQL), so 0 also means "row
            # exists with this exact value" — insert only a missing row
            try:
                self.execute(
                    f"insert into mysql.global_variables values ('{esc_n}', "
                    f"'{esc_v}')")
            except errors.DupEntryError:
                pass

    def close(self) -> None:
        self.rollback_txn()


class _PreparedStmt:
    """One PREPAREd statement: parsed AST + param count + cached physical
    plan (executor/prepared.go Prepared)."""

    __slots__ = ("stmt", "param_count", "text", "plan", "plan_key",
                 "digest_pair")

    def __init__(self, stmt, param_count: int, text: str):
        self.stmt = stmt
        self.param_count = param_count
        self.text = text
        self.plan = None
        self.plan_key = None
        self.digest_pair: tuple[str, str] | None = None  # lazy, once


class _MetricHandles:
    """Resolved-once metric objects for the per-statement hot path (the
    registry lock + name lookup would otherwise run 3-4× per statement)."""

    def __init__(self):
        from tidb_tpu import metrics
        self.parse = metrics.histogram("session.parse_seconds")
        self.compile = metrics.histogram("session.compile_seconds")
        self.run = metrics.histogram("session.run_seconds")
        self._stmt: dict[type, object] = {}
        self._metrics = metrics

    def stmt_counter(self, tp: type):
        c = self._stmt.get(tp)
        if c is None:
            c = self._stmt[tp] = self._metrics.counter(
                f"session.statements.{tp.__name__}")
        return c


_metric_handles_obj: _MetricHandles | None = None


def _metric_handles() -> _MetricHandles:
    global _metric_handles_obj
    if _metric_handles_obj is None:
        _metric_handles_obj = _MetricHandles()
    return _metric_handles_obj


def _is_simple(stmt) -> bool:
    return isinstance(stmt, (
        ast.UseStmt, ast.SetStmt, ast.BeginStmt, ast.CommitStmt,
        ast.RollbackStmt, ast.CreateDatabaseStmt, ast.DropDatabaseStmt,
        ast.CreateTableStmt, ast.DropTableStmt, ast.TruncateTableStmt,
        ast.CreateIndexStmt, ast.DropIndexStmt, ast.AlterTableStmt,
        ast.AdminStmt, ast.AnalyzeTableStmt, ast.GrantStmt, ast.RevokeStmt,
        ast.CreateUserStmt, ast.DropUserStmt, ast.LoadDataStmt,
        ast.DoStmt, ast.KillStmt, ast.FlushStmt))


# ---------------------------------------------------------------------------
# bootstrap (bootstrap.go:121,288,309)
# ---------------------------------------------------------------------------

_BOOTSTRAPPED_STORES: set[str] = set()

CREATE_USER_TABLE = """
create table if not exists mysql.user (
    Host char(64), User char(16), Password char(41),
    Select_priv char(1) default 'N', Insert_priv char(1) default 'N',
    Update_priv char(1) default 'N', Delete_priv char(1) default 'N',
    Create_priv char(1) default 'N', Drop_priv char(1) default 'N',
    Grant_priv char(1) default 'N', Alter_priv char(1) default 'N',
    Index_priv char(1) default 'N', Execute_priv char(1) default 'N',
    Show_db_priv char(1) default 'N', Super_priv char(1) default 'N',
    Create_user_priv char(1) default 'N', Trigger_priv char(1) default 'N'
)"""

CREATE_DB_TABLE = """
create table if not exists mysql.db (
    Host char(60), DB char(64), User char(16),
    Select_priv char(1) default 'N', Insert_priv char(1) default 'N',
    Update_priv char(1) default 'N', Delete_priv char(1) default 'N',
    Create_priv char(1) default 'N', Drop_priv char(1) default 'N',
    Grant_priv char(1) default 'N', Index_priv char(1) default 'N',
    Alter_priv char(1) default 'N', Execute_priv char(1) default 'N'
)"""

CREATE_TABLES_PRIV_TABLE = """
create table if not exists mysql.tables_priv (
    Host char(60), DB char(64), User char(16), Table_name char(64),
    Grantor char(77), Table_priv char(128), Column_priv char(128)
)"""

CREATE_COLUMNS_PRIV_TABLE = """
create table if not exists mysql.columns_priv (
    Host char(60), DB char(64), User char(16), Table_name char(64),
    Column_name char(64), Column_priv char(128)
)"""

CREATE_GLOBAL_VARIABLES_TABLE = """
create table if not exists mysql.global_variables (
    variable_name char(64) not null,
    variable_value char(255),
    primary key (variable_name)
)"""

CREATE_TIDB_TABLE = """
create table if not exists mysql.tidb (
    variable_name char(64) not null,
    variable_value char(255),
    comment char(255),
    primary key (variable_name)
)"""


def bootstrap(session: Session) -> None:
    """Create mysql.* system tables and the default root user on first use
    of a store (bootstrap.go doDDLWorks/doDMLWorks)."""
    uuid = session.store.uuid()
    if uuid in _BOOTSTRAPPED_STORES:
        return
    with _bootstrap_lock:
        if uuid in _BOOTSTRAPPED_STORES:
            return
        if session.info_schema().schema_exists("mysql"):
            _BOOTSTRAPPED_STORES.add(uuid)
            # persisted store already bootstrapped: hydrate the in-memory
            # global-var cache from mysql.global_variables so SET GLOBALs
            # survive a process restart (session.go loadCommonGlobalVars)
            try:
                rows = session.execute(
                    "select variable_name, variable_value "
                    "from mysql.global_variables")[0].values()
            except errors.TiDBError:
                return  # pre-sysvar-table store: defaults stand
            gv = session.global_vars
            for name, value in rows:
                name = name.decode() if isinstance(name, bytes) else name
                value = value.decode() if isinstance(value, bytes) else value
                if value is not None and name.lower() in gv.values:
                    gv.values[name.lower()] = value
            # a hydrated engine choice must be APPLIED, not just reported —
            # @@tidb_copr_backend mirrors the client actually installed
            from tidb_tpu.sessionctx import parse_bool_sysvar
            if gv.values.get("tidb_copr_backend", "").strip().lower() \
                    == "tpu":
                session.apply_copr_backend("tpu")
            else:
                # a client installed BEFORE the first session
                # (store.set_client embed pattern, or the cluster store's
                # default DistCoprClient fan-out) must also pick up the
                # persisted routing knobs, not their defaults
                client = session.store.get_client()
                for target in (client, getattr(client, "cpu", None)):
                    if target is None:
                        continue
                    for var, attr in (
                            ("tidb_tpu_device_join", "device_join"),
                            ("tidb_tpu_device_dict", "device_dict"),
                            ("tidb_tpu_columnar_scan", "columnar_scan"),
                            ("tidb_tpu_micro_batch", "micro_batch"),
                            ("tidb_tpu_plane_cache",
                             "plane_cache_enabled")):
                        v = gv.values.get(var)
                        if v is not None and hasattr(target, attr):
                            setattr(target, attr, parse_bool_sysvar(v))
                    v = gv.values.get("tidb_tpu_dict_max_ndv")
                    try:
                        if v is not None and hasattr(target,
                                                     "dict_max_ndv"):
                            target.dict_max_ndv = float(v.strip())
                    except ValueError:
                        pass
                    for var, attr in (
                            ("tidb_tpu_dispatch_floor",
                             "dispatch_floor_rows"),
                            ("tidb_tpu_batch_window_ms",
                             "batch_window_ms")):
                        fl = gv.values.get(var)
                        try:
                            if fl is not None and hasattr(target, attr):
                                setattr(target, attr,
                                        max(0, int(fl.strip())))
                        except ValueError:
                            pass
            # the region plane cache hangs off the store's RPC handler,
            # not a client — hydrate it directly, on EVERY backend path
            # (the 'tpu' branch above installs a TpuClient but must not
            # silently revert the cache's persisted kill switch/budget)
            from tidb_tpu.copr.plane_cache import cache_for
            pc = cache_for(session.store)
            if pc is not None:
                v = gv.values.get("tidb_tpu_plane_cache")
                if v is not None:
                    pc.enabled = parse_bool_sysvar(v)
                b = gv.values.get("tidb_tpu_plane_cache_bytes")
                try:
                    if b:
                        pc.set_budget(max(0, int(b.strip())))
                except ValueError:
                    pass
            # the region dictionary registry hangs off the RPC handler
            # like the plane cache — hydrate its kill switch + NDV gate
            # on every backend path
            from tidb_tpu.copr.dictionary import registry_for
            reg = registry_for(session.store)
            if reg is not None:
                v = gv.values.get("tidb_tpu_device_dict")
                if v is not None:
                    reg.enabled = parse_bool_sysvar(v)
                v = gv.values.get("tidb_tpu_dict_max_ndv")
                try:
                    if v:
                        reg.max_ndv_ratio = float(v.strip())
                except ValueError:
                    pass
            # the shared drain pool's size is process-level like the mesh
            # switch — hydrate on every backend path
            v = gv.values.get("tidb_tpu_drain_pool_size")
            if v is not None:
                try:
                    from tidb_tpu.cluster.pool import set_pool_size
                    set_pool_size(max(1, int(v.strip())))
                except ValueError:
                    pass
            # the mesh tier switch is a process-level ops.mesh flag —
            # hydrate it on every backend path, like the plane cache
            v = gv.values.get("tidb_tpu_mesh")
            if v is not None:
                try:
                    from tidb_tpu.ops import mesh as _mesh_mod
                    _mesh_mod.set_enabled(parse_bool_sysvar(v))
                except ImportError:   # retryable-ok: jax-free process
                    pass
            # the HBM budget ledger is a process-level ops.membudget
            # account like the mesh switch — hydrate on every backend
            # path (jax-free processes have no ledger to set)
            v = gv.values.get("tidb_tpu_hbm_budget_bytes")
            if v is not None:
                try:
                    from tidb_tpu.ops import membudget as _membudget
                    _membudget.set_budget(v)
                except (ImportError, ValueError):  # retryable-ok: jax-free
                    pass
            # digest-summary / history-ring knobs live on the per-store
            # PerfSchema — hydrate them like the plane cache's
            from tidb_tpu import perfschema
            perfschema.apply_sysvars(session.store, gv.values)
            # flight-recorder knobs live on the per-store recorder;
            # metrics-recorder knobs are process-wide like the drain pool
            from tidb_tpu import flight
            fr = flight.recorder_for(session.store)
            v = gv.values.get("tidb_tpu_flight_recorder")
            if v is not None:
                fr.set_enabled(parse_bool_sysvar(v))
            v = gv.values.get("tidb_tpu_slow_trace_cap")
            try:
                if v:
                    fr.set_cap(max(1, int(v.strip())))
            except ValueError:
                pass
            v = gv.values.get("tidb_tpu_slow_trace_max_spans")
            try:
                if v:
                    fr.set_max_spans(max(0, int(v.strip())))
            except ValueError:
                pass
            # the delta-pack tier hangs off the store's RPC handler like
            # the plane cache — hydrate on every backend path
            from tidb_tpu.copr.delta import delta_for
            ds = delta_for(session.store)
            if ds is not None:
                v = gv.values.get("tidb_tpu_delta_pack")
                if v is not None:
                    ds.set_enabled(parse_bool_sysvar(v))
                v = gv.values.get("tidb_tpu_delta_budget_rows")
                try:
                    if v:
                        ds.budget_rows = max(1, int(v.strip()))
                except ValueError:
                    pass
            from tidb_tpu.metrics.timeseries import recorder as _tsrec
            v = gv.values.get("tidb_tpu_metrics_interval_ms")
            try:
                if v:
                    _tsrec.set_interval(max(10, int(v.strip())) / 1000.0)
            except ValueError:
                pass
            v = gv.values.get("tidb_tpu_metrics_history_cap")
            try:
                if v:
                    _tsrec.set_cap(max(2, int(v.strip())))
            except ValueError:
                pass
            # inspection-rule thresholds are process-level like the
            # metrics recorder — hydrate the whole persisted family
            from tidb_tpu import inspection as _inspection
            for var, val in gv.values.items():
                if var.startswith(_inspection.SYSVAR_PREFIX) and val:
                    try:
                        _inspection.set_threshold(var, val)
                    except ValueError:
                        pass
            # the kernel profiler is process-level like the dispatch
            # lock it rides — hydrate its kill switch + cardinality cap
            from tidb_tpu import profiler as _profiler
            v = gv.values.get("tidb_tpu_kernel_profile")
            if v is not None:
                _profiler.set_enabled(parse_bool_sysvar(v))
            v = gv.values.get("tidb_tpu_profile_max_signatures")
            try:
                if v:
                    _profiler.set_max_signatures(max(1, int(v.strip())))
            except ValueError:
                pass
            return
        session.execute("create database if not exists mysql")
        for ddl in (CREATE_USER_TABLE, CREATE_DB_TABLE,
                    CREATE_TABLES_PRIV_TABLE, CREATE_COLUMNS_PRIV_TABLE,
                    CREATE_GLOBAL_VARIABLES_TABLE, CREATE_TIDB_TABLE):
            session.execute(ddl)
        session.execute(
            "insert into mysql.user (Host, User, Password, Select_priv, "
            "Insert_priv, Update_priv, Delete_priv, Create_priv, Drop_priv, "
            "Grant_priv, Alter_priv, Index_priv, Execute_priv, Show_db_priv, "
            "Super_priv, Create_user_priv, Trigger_priv) values "
            "('%', 'root', '', 'Y','Y','Y','Y','Y','Y','Y','Y','Y','Y','Y',"
            "'Y','Y','Y')")
        from tidb_tpu.sessionctx import SYSVAR_DEFAULTS
        values = ", ".join(f"('{k}', '{v}')"
                           for k, v in sorted(SYSVAR_DEFAULTS.items()))
        session.execute(
            f"insert into mysql.global_variables values {values}")
        session.execute(
            "insert into mysql.tidb values ('bootstrapped', 'True', "
            "'Bootstrap flag. Do not delete.')")
        # only a fully-completed bootstrap marks the store (a failure above
        # propagates and the next Session retries)
        _BOOTSTRAPPED_STORES.add(uuid)
