"""Column statistics: equi-depth histograms + row-count estimation.

Reference: plan/statistics/statistics.go — Column (:44) with equi-depth
buckets, EqualRowCount/LessRowCount/GreaterRowCount/BetweenRowCount
(:76-143), NewTable (:314), PseudoTable (:372) with the pseudo estimation
rates; built by ANALYZE TABLE (executor/executor_simple.go:253-310).

Values are compared through their order-preserving codec encoding, so one
histogram implementation serves every column kind the codec covers.
"""

from __future__ import annotations

import json

from tidb_tpu.codec import codec
from tidb_tpu.types import Datum

# pseudo estimation rates (statistics.go:366-370)
PSEUDO_ROW_COUNT = 10_000
PSEUDO_EQUAL_RATE = 1000
PSEUDO_LESS_RATE = 3
PSEUDO_BETWEEN_RATE = 40

DEFAULT_BUCKET_COUNT = 256


def _enc(d: Datum) -> bytes:
    return codec.encode_key([d])


class Bucket:
    """One equi-depth bucket: cumulative row count up to and including this
    bucket, the (encoded) upper bound value, and how often that exact upper
    value repeats (statistics.go bucket struct)."""

    __slots__ = ("count", "upper", "repeats")

    def __init__(self, count: int, upper: bytes, repeats: int):
        self.count = count
        self.upper = upper
        self.repeats = repeats


class ColumnStats:
    """Histogram for one column (statistics.Column)."""

    def __init__(self, col_id: int, ndv: int, null_count: int,
                 buckets: list[Bucket]):
        self.col_id = col_id
        self.ndv = ndv
        self.null_count = null_count
        self.buckets = buckets

    @property
    def total(self) -> int:
        return self.buckets[-1].count if self.buckets else 0

    # ---- estimation (statistics.go:76-143) ----

    def _bucket_index(self, key: bytes) -> int:
        """First bucket whose upper >= key (binary search)."""
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.buckets[mid].upper < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def equal_row_count(self, value: Datum) -> float:
        if not self.buckets:
            return 0.0
        key = _enc(value)
        i = self._bucket_index(key)
        if i >= len(self.buckets):
            return 0.0
        if self.buckets[i].upper == key:
            return float(self.buckets[i].repeats)
        if self.ndv > 0:
            return self.total / self.ndv
        return 0.0

    def less_row_count(self, value: Datum) -> float:
        if not self.buckets:
            return 0.0
        key = _enc(value)
        i = self._bucket_index(key)
        if i >= len(self.buckets):
            return float(self.total)
        prev = self.buckets[i - 1].count if i > 0 else 0
        in_bucket = self.buckets[i].count - prev
        if self.buckets[i].upper == key:
            # everything in the bucket except the repeats of the bound
            return prev + max(0.0, in_bucket - self.buckets[i].repeats)
        return prev + in_bucket / 2.0

    def greater_row_count(self, value: Datum) -> float:
        return max(0.0, self.total - self.less_row_count(value)
                   - self.equal_row_count(value))

    def between_row_count(self, low: Datum, high: Datum) -> float:
        return max(0.0, self.less_row_count(high)
                   - self.less_row_count(low))

    # ---- serialization ----

    def to_obj(self) -> dict:
        return {"id": self.col_id, "ndv": self.ndv,
                "nulls": self.null_count,
                "buckets": [[b.count, b.upper.hex(), b.repeats]
                            for b in self.buckets]}

    @staticmethod
    def from_obj(o: dict) -> "ColumnStats":
        return ColumnStats(o["id"], o["ndv"], o.get("nulls", 0),
                           [Bucket(c, bytes.fromhex(u), r)
                            for c, u, r in o["buckets"]])


def build_column_stats(col_id: int, values: list[Datum],
                       bucket_count: int = DEFAULT_BUCKET_COUNT) -> ColumnStats:
    """Equi-depth histogram from a full value sample
    (statistics.go buildColumn)."""
    null_count = sum(1 for v in values if v.is_null())
    keys = sorted(_enc(v) for v in values if not v.is_null())
    if not keys:
        return ColumnStats(col_id, 0, null_count, [])
    per_bucket = max(1, (len(keys) + bucket_count - 1) // bucket_count)
    buckets: list[Bucket] = []
    ndv = 0
    prev_key = None
    for k in keys:
        if k != prev_key:
            ndv += 1
        if buckets and (buckets[-1].count - (buckets[-2].count if
                        len(buckets) > 1 else 0)) < per_bucket:
            b = buckets[-1]
            b.count += 1
            if k == b.upper:
                b.repeats += 1
            else:
                b.upper = k
                b.repeats = 1
        elif buckets and k == buckets[-1].upper:
            # a value never splits across buckets (equi-depth invariant)
            buckets[-1].count += 1
            buckets[-1].repeats += 1
        else:
            base = buckets[-1].count if buckets else 0
            buckets.append(Bucket(base + 1, k, 1))
        prev_key = k
    return ColumnStats(col_id, ndv, null_count, buckets)


class TableStats:
    """Per-table statistics (statistics.Table)."""

    def __init__(self, table_id: int, count: int,
                 columns: dict[int, ColumnStats], pseudo: bool = False):
        self.table_id = table_id
        self.count = count
        self.columns = columns
        self.pseudo = pseudo

    def col(self, col_id: int) -> ColumnStats | None:
        return self.columns.get(col_id)

    # ---- pseudo estimation (statistics.go:372 PseudoTable) ----

    def equal_row_count(self, col_id: int, value: Datum) -> float:
        c = self.col(col_id)
        if self.pseudo or c is None or not c.buckets:
            return self.count / PSEUDO_EQUAL_RATE
        return c.equal_row_count(value) * self.count / max(c.total, 1)

    def less_row_count(self, col_id: int, value: Datum) -> float:
        c = self.col(col_id)
        if self.pseudo or c is None or not c.buckets:
            return self.count / PSEUDO_LESS_RATE
        return c.less_row_count(value) * self.count / max(c.total, 1)

    def greater_row_count(self, col_id: int, value: Datum) -> float:
        c = self.col(col_id)
        if self.pseudo or c is None or not c.buckets:
            return self.count / PSEUDO_LESS_RATE
        return c.greater_row_count(value) * self.count / max(c.total, 1)

    def between_row_count(self, col_id: int, low: Datum,
                          high: Datum) -> float:
        c = self.col(col_id)
        if self.pseudo or c is None or not c.buckets:
            return self.count / PSEUDO_BETWEEN_RATE
        return c.between_row_count(low, high) * self.count / max(c.total, 1)

    # ---- serialization (statistics.proto equivalent) ----

    def serialize(self) -> bytes:
        return json.dumps({
            "tid": self.table_id, "count": self.count,
            "cols": [c.to_obj() for c in self.columns.values()],
        }).encode()

    @staticmethod
    def deserialize(raw: bytes) -> "TableStats":
        o = json.loads(raw.decode())
        cols = {c["id"]: ColumnStats.from_obj(c) for c in o["cols"]}
        return TableStats(o["tid"], o["count"], cols)


def pseudo_table(table_id: int) -> TableStats:
    return TableStats(table_id, PSEUDO_ROW_COUNT, {}, pseudo=True)


DEFAULT_SAMPLE_SIZE = 100_000


def analyze_table(table, retriever,
                  max_samples: int = DEFAULT_SAMPLE_SIZE) -> TableStats:
    """ANALYZE: one histogram per public column, reservoir-sampled at
    max_samples rows so memory stays bounded on huge tables
    (executor/executor_simple.go:253-310; the reference reservoir is 10k —
    a larger default trades a still-small footprint for better buckets)."""
    import random
    info = table.info
    cols = info.public_columns()
    rng = random.Random(table.id)  # deterministic per table for stable plans
    sample_rows: list[list[Datum]] = []
    count = 0
    for _handle, row in table.iter_records(retriever):
        if count < max_samples:
            sample_rows.append(row)
        else:
            j = rng.randint(0, count)
            if j < max_samples:
                sample_rows[j] = row
        count += 1
    # histograms stay in sample units: every TableStats estimator already
    # normalizes by the histogram total and rescales by self.count
    columns = {c.id: build_column_stats(c.id, [r[i] for r in sample_rows])
               for i, c in enumerate(cols)}
    return TableStats(table.id, count, columns)
