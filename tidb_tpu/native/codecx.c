/* Native datum codec: the hot host-side encode path.
 *
 * Reference: util/codec/codec.go (EncodeKey/EncodeValue), number.go,
 * bytes.go — the same flag+payload layout tidb_tpu/codec implements in
 * Python; this module is a drop-in accelerator for the write path
 * (tablecodec.encode_row, index key encoding) where per-datum Python
 * dispatch dominates bulk-load cost. Falls back to the Python codec by
 * raising Unsupported for kinds it does not handle (DECIMAL, INTERFACE).
 *
 * Exposes:
 *   encode_row(col_ids, datums)        -> bytes   (value encoding)
 *   encode_datums(datums, comparable)  -> bytes
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *Unsupported;

/* flag bytes — must mirror tidb_tpu/codec/codec.py */
enum {
    NIL_FLAG = 0x00,
    BYTES_FLAG = 0x01,
    COMPACT_BYTES_FLAG = 0x02,
    INT_FLAG = 0x03,
    UINT_FLAG = 0x04,
    FLOAT_FLAG = 0x05,
    DURATION_FLAG = 0x07,
    TIME_FLAG = 0x08,
    VARINT_FLAG = 0x09,
    UVARINT_FLAG = 0x0A,
    MAX_FLAG = 0xFA,
};

/* Kind enum values — must mirror tidb_tpu/types/datum.py */
enum {
    K_NULL = 0, K_I64 = 1, K_U64 = 2, K_F64 = 3, K_STR = 4, K_BYTES = 5,
    K_DEC = 6, K_DUR = 7, K_TIME = 8, K_MIN = 100, K_MAX = 101,
};

#define SIGN_MASK 0x8000000000000000ULL

typedef struct {
    uint8_t *p;
    size_t len, cap;
} Buf;

static int buf_reserve(Buf *b, size_t extra) {
    if (b->len + extra <= b->cap) return 0;
    size_t cap = b->cap ? b->cap : 256;
    while (cap < b->len + extra) cap <<= 1;
    uint8_t *np = PyMem_Realloc(b->p, cap);
    if (!np) { PyErr_NoMemory(); return -1; }
    b->p = np;
    b->cap = cap;
    return 0;
}

static inline int buf_putc(Buf *b, uint8_t c) {
    if (buf_reserve(b, 1) < 0) return -1;
    b->p[b->len++] = c;
    return 0;
}

static inline int buf_put(Buf *b, const void *src, size_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->p + b->len, src, n);
    b->len += n;
    return 0;
}

static inline int put_u64be(Buf *b, uint64_t v) {
    uint8_t tmp[8];
    for (int i = 7; i >= 0; i--) { tmp[i] = (uint8_t)(v & 0xFF); v >>= 8; }
    return buf_put(b, tmp, 8);
}

static inline int put_uvarint(Buf *b, uint64_t v) {
    uint8_t tmp[10];
    int n = 0;
    while (v >= 0x80) { tmp[n++] = (uint8_t)(v & 0x7F) | 0x80; v >>= 7; }
    tmp[n++] = (uint8_t)v;
    return buf_put(b, tmp, n);
}

static inline int put_varint(Buf *b, int64_t v) {
    uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
    return put_uvarint(b, u);
}

static inline uint64_t float_cmp_bits(double d) {
    if (d == 0.0) d = 0.0;  /* normalize -0.0 */
    uint64_t u;
    memcpy(&u, &d, 8);
    if (u & SIGN_MASK) u = ~u;
    else u |= SIGN_MASK;
    return u;
}

/* memcomparable bytes: 8-byte groups, 0x00 pad, marker = 0xFF - pad */
static int put_cmp_bytes(Buf *b, const uint8_t *d, Py_ssize_t n) {
    Py_ssize_t i;
    for (i = 0; i <= n; i += 8) {
        Py_ssize_t rem = n - i;
        if (rem >= 8) {
            if (buf_put(b, d + i, 8) < 0 || buf_putc(b, 0xFF) < 0) return -1;
            if (rem == 8) { /* loop emits trailing empty group next */ }
        } else {
            uint8_t grp[9];
            memset(grp, 0, 9);
            memcpy(grp, d + i, (size_t)rem);
            grp[8] = (uint8_t)(0xFF - (8 - rem));
            return buf_put(b, grp, 9);
        }
    }
    return 0;
}

/* cached attr name objects */
static PyObject *s_kind, *s_val, *s_nanos, *s_to_packed_int;

static int encode_one(Buf *b, PyObject *datum, int comparable) {
    PyObject *kobj = PyObject_GetAttr(datum, s_kind);
    if (!kobj) return -1;
    long k = PyLong_AsLong(kobj);  /* Kind is an IntEnum (PyLong subclass) */
    Py_DECREF(kobj);
    if (k == -1 && PyErr_Occurred()) return -1;

    if (k == K_NULL) return buf_putc(b, NIL_FLAG);
    if (k == K_MIN) return buf_putc(b, BYTES_FLAG);
    if (k == K_MAX) return buf_putc(b, MAX_FLAG);

    PyObject *val = PyObject_GetAttr(datum, s_val);
    if (!val) return -1;
    int rc = -1;

    switch (k) {
    case K_I64: {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(val, &overflow);
        if (overflow || (v == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(Unsupported, "int64 overflow");
            break;
        }
        if (comparable) {
            if (buf_putc(b, INT_FLAG) == 0)
                rc = put_u64be(b, (uint64_t)v ^ SIGN_MASK);
        } else {
            if (buf_putc(b, VARINT_FLAG) == 0)
                rc = put_varint(b, v);
        }
        break;
    }
    case K_U64: {
        unsigned long long v = PyLong_AsUnsignedLongLong(val);
        if (v == (unsigned long long)-1 && PyErr_Occurred()) {
            /* out-of-range raises OverflowError; downgrade to Unsupported so
               callers fall back to the Python codec (which masks) */
            if (PyErr_ExceptionMatches(PyExc_OverflowError)) {
                PyErr_Clear();
                PyErr_SetString(Unsupported, "u64 out of range");
            }
            break;
        }
        if (comparable) {
            if (buf_putc(b, UINT_FLAG) == 0) rc = put_u64be(b, v);
        } else {
            if (buf_putc(b, UVARINT_FLAG) == 0) rc = put_uvarint(b, v);
        }
        break;
    }
    case K_F64: {
        double d = PyFloat_AsDouble(val);
        if (d == -1.0 && PyErr_Occurred()) break;
        if (buf_putc(b, FLOAT_FLAG) == 0)
            rc = put_u64be(b, float_cmp_bits(d));
        break;
    }
    case K_STR:
    case K_BYTES: {
        const char *data;
        Py_ssize_t n;
        if (k == K_STR) {
            data = PyUnicode_AsUTF8AndSize(val, &n);
            if (!data) break;
        } else {
            if (PyBytes_AsStringAndSize(val, (char **)&data, &n) < 0) break;
        }
        if (comparable) {
            if (buf_putc(b, BYTES_FLAG) == 0)
                rc = put_cmp_bytes(b, (const uint8_t *)data, n);
        } else {
            /* compact: zig-zag varint length + raw bytes */
            if (buf_putc(b, COMPACT_BYTES_FLAG) == 0 &&
                put_varint(b, (int64_t)n) == 0)
                rc = buf_put(b, data, (size_t)n);
        }
        break;
    }
    case K_DUR: {
        PyObject *nanos = PyObject_GetAttr(val, s_nanos);
        if (!nanos) break;
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(nanos, &overflow);
        Py_DECREF(nanos);
        if (overflow || (v == -1 && PyErr_Occurred())) break;
        if (buf_putc(b, DURATION_FLAG) == 0)
            rc = put_u64be(b, (uint64_t)v ^ SIGN_MASK);
        break;
    }
    case K_TIME: {
        PyObject *packed = PyObject_CallMethodNoArgs(val, s_to_packed_int);
        if (!packed) break;
        unsigned long long v = PyLong_AsUnsignedLongLong(packed);
        Py_DECREF(packed);
        if (v == (unsigned long long)-1 && PyErr_Occurred()) {
            if (PyErr_ExceptionMatches(PyExc_OverflowError)) {
                PyErr_Clear();
                PyErr_SetString(Unsupported, "time packed value out of range");
            }
            break;
        }
        if (buf_putc(b, TIME_FLAG) == 0) rc = put_u64be(b, v);
        break;
    }
    default:
        PyErr_Format(Unsupported, "kind %ld not encodable natively", k);
        break;
    }
    Py_DECREF(val);
    return rc;
}

static PyObject *py_encode_row(PyObject *self, PyObject *args) {
    PyObject *cids_obj, *datums_obj;
    if (!PyArg_ParseTuple(args, "OO", &cids_obj, &datums_obj)) return NULL;
    PyObject *cids = PySequence_Fast(cids_obj, "col_ids not a sequence");
    if (!cids) return NULL;
    PyObject *datums = PySequence_Fast(datums_obj, "datums not a sequence");
    if (!datums) { Py_DECREF(cids); return NULL; }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(cids);
    if (PySequence_Fast_GET_SIZE(datums) != n) {
        Py_DECREF(cids); Py_DECREF(datums);
        PyErr_SetString(PyExc_ValueError, "column/value count mismatch");
        return NULL;
    }
    Buf b = {0};
    if (n == 0) {
        if (buf_putc(&b, NIL_FLAG) < 0) goto fail;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        long long cid = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(cids, i));
        if (cid == -1 && PyErr_Occurred()) goto fail;
        if (buf_putc(&b, VARINT_FLAG) < 0 || put_varint(&b, cid) < 0)
            goto fail;
        if (encode_one(&b, PySequence_Fast_GET_ITEM(datums, i), 0) < 0)
            goto fail;
    }
    Py_DECREF(cids); Py_DECREF(datums);
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.p,
                                              (Py_ssize_t)b.len);
    PyMem_Free(b.p);
    return out;
fail:
    Py_DECREF(cids); Py_DECREF(datums);
    PyMem_Free(b.p);
    return NULL;
}

static PyObject *py_encode_datums(PyObject *self, PyObject *args) {
    PyObject *datums_obj;
    int comparable;
    if (!PyArg_ParseTuple(args, "Op", &datums_obj, &comparable)) return NULL;
    PyObject *datums = PySequence_Fast(datums_obj, "datums not a sequence");
    if (!datums) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(datums);
    Buf b = {0};
    for (Py_ssize_t i = 0; i < n; i++) {
        if (encode_one(&b, PySequence_Fast_GET_ITEM(datums, i),
                       comparable) < 0) {
            Py_DECREF(datums);
            PyMem_Free(b.p);
            return NULL;
        }
    }
    Py_DECREF(datums);
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.p,
                                              (Py_ssize_t)b.len);
    PyMem_Free(b.p);
    return out;
}

/* ------------------------------------------------------------------ */
/* pack_rows: batched row decode → columnar planes (the read-path hot   */
/* loop; reverse of encode_row). Reference: the per-row decode in       */
/* store/localstore/local_region.go:617 getRowData — here one C pass    */
/* fills value/valid planes for the TPU columnar batch directly.        */
/* ------------------------------------------------------------------ */

typedef struct {
    const uint8_t *p;
    Py_ssize_t len, pos;
} Rd;

static inline int rd_u64be(Rd *r, uint64_t *out) {
    if (r->pos + 8 > r->len) return -1;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | r->p[r->pos + i];
    r->pos += 8;
    *out = v;
    return 0;
}

static inline int rd_uvarint(Rd *r, uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    while (r->pos < r->len && shift < 70) {
        uint8_t c = r->p[r->pos++];
        v |= ((uint64_t)(c & 0x7F)) << shift;
        if (!(c & 0x80)) { *out = v; return 0; }
        shift += 7;
    }
    return -1;
}

static inline int rd_varint(Rd *r, int64_t *out) {
    uint64_t u;
    if (rd_uvarint(r, &u) < 0) return -1;
    *out = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    return 0;
}

/* decoded scalar: kind 0=null, 1=int-ish(i64), 2=float(f64), 3=bytes */
typedef struct {
    int kind;
    int64_t i;
    double f;
    const uint8_t *bytes;   /* COMPACT only: borrowed pointer into value */
    Py_ssize_t blen;
    uint8_t *owned;         /* BYTES (memcomparable): decoded copy */
} Dec;

static int decode_value_datum(Rd *r, Dec *d) {
    d->owned = NULL;
    if (r->pos >= r->len) return -1;
    uint8_t flag = r->p[r->pos++];
    uint64_t u;
    int64_t v;
    switch (flag) {
    case NIL_FLAG:
        d->kind = 0;
        return 0;
    case VARINT_FLAG:
        if (rd_varint(r, &v) < 0) return -1;
        d->kind = 1; d->i = v;
        return 0;
    case UVARINT_FLAG:
        if (rd_uvarint(r, &u) < 0) return -1;
        d->kind = 1; d->i = (int64_t)u;
        return 0;
    case INT_FLAG:
    case DURATION_FLAG:  /* cmp-int payload (nanos) */
        if (rd_u64be(r, &u) < 0) return -1;
        d->kind = 1; d->i = (int64_t)(u ^ SIGN_MASK);
        return 0;
    case UINT_FLAG:
    case TIME_FLAG:      /* packed time uint */
        if (rd_u64be(r, &u) < 0) return -1;
        d->kind = 1; d->i = (int64_t)u;
        return 0;
    case FLOAT_FLAG: {
        if (rd_u64be(r, &u) < 0) return -1;
        if (u & SIGN_MASK) u &= ~SIGN_MASK; else u = ~u;
        double f;
        memcpy(&f, &u, 8);
        d->kind = 2; d->f = f;
        return 0;
    }
    case COMPACT_BYTES_FLAG: {
        if (rd_varint(r, &v) < 0 || v < 0 || r->pos + v > r->len) return -1;
        d->kind = 3;
        d->bytes = r->p + r->pos;
        d->blen = (Py_ssize_t)v;
        r->pos += v;
        return 0;
    }
    case BYTES_FLAG: {
        /* memcomparable 9-byte groups: 8 data + marker(0xFF - pad) */
        size_t cap = 0, n = 0;
        uint8_t *out = NULL;
        for (;;) {
            if (r->pos + 9 > r->len) { PyMem_Free(out); return -1; }
            const uint8_t *grp = r->p + r->pos;
            r->pos += 9;
            int pad = 0xFF - grp[8];
            if (pad < 0 || pad > 8) { PyMem_Free(out); return -1; }
            int take = 8 - pad;
            if (n + 8 > cap) {
                cap = cap ? cap * 2 : 32;
                uint8_t *np2 = PyMem_Realloc(out, cap);
                if (!np2) { PyMem_Free(out); return -1; }
                out = np2;
            }
            memcpy(out + n, grp, (size_t)take);
            n += (size_t)take;
            if (pad > 0) break;
        }
        d->kind = 3;
        d->owned = out;
        d->bytes = out ? out : (const uint8_t *)"";
        d->blen = (Py_ssize_t)n;
        return 0;
    }
    default:
        return -1;  /* DECIMAL etc.: caller falls back to Python */
    }
}

static int skip_value_datum(Rd *r) {
    Dec tmp;
    if (decode_value_datum(r, &tmp) < 0) return -1;
    PyMem_Free(tmp.owned);
    return 0;
}

/* pack_rows(keys, values, col_ids, kinds, pk_idx)
 *   keys/values: sequences of bytes (one KV pair per row)
 *   kinds: bytes, one of 'i'/'f'/'s' per column
 *   pk_idx: column index taking the handle, or -1
 * → (n_rows, handles_le64, per-col value buffer | list, valid_u8, present_u8)
 *   numeric value buffers are little-endian i64/f64 for np.frombuffer. */
static PyObject *py_pack_rows(PyObject *self, PyObject *args) {
    PyObject *keys_obj, *vals_obj, *cids_obj;
    const char *kinds;
    Py_ssize_t kinds_len;
    int pk_idx;
    if (!PyArg_ParseTuple(args, "OOOy#i", &keys_obj, &vals_obj, &cids_obj,
                          &kinds, &kinds_len, &pk_idx))
        return NULL;
    PyObject *keys = PySequence_Fast(keys_obj, "keys not a sequence");
    if (!keys) return NULL;
    PyObject *vals = PySequence_Fast(vals_obj, "values not a sequence");
    if (!vals) { Py_DECREF(keys); return NULL; }
    PyObject *cids = PySequence_Fast(cids_obj, "col_ids not a sequence");
    if (!cids) { Py_DECREF(keys); Py_DECREF(vals); return NULL; }

    Py_ssize_t n = PySequence_Fast_GET_SIZE(keys);
    Py_ssize_t m = PySequence_Fast_GET_SIZE(cids);
    if (PySequence_Fast_GET_SIZE(vals) != n || m != kinds_len || m > 256) {
        PyErr_SetString(PyExc_ValueError, "pack_rows shape mismatch");
        goto fail_seqs;
    }
    /* the handle store below writes an int64 into col_out[pk_idx]: an
     * out-of-range index or a non-numeric ('s') column would scribble over
     * a PyList object header — reject at the boundary */
    if (pk_idx >= 0 && (pk_idx >= m || kinds[pk_idx] == 's')) {
        PyErr_SetString(PyExc_ValueError,
                        "pack_rows: pk_idx out of range or not numeric");
        goto fail_seqs;
    }
    int64_t cid_arr[256];
    for (Py_ssize_t j = 0; j < m; j++) {
        long long c = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(cids, j));
        if (c == -1 && PyErr_Occurred()) goto fail_seqs;
        cid_arr[j] = c;
    }

    PyObject *handles = PyBytes_FromStringAndSize(NULL, n * 8);
    PyObject **col_out = PyMem_Calloc((size_t)m, sizeof(PyObject *));
    PyObject **valid_out = PyMem_Calloc((size_t)m, sizeof(PyObject *));
    PyObject **present_out = PyMem_Calloc((size_t)m, sizeof(PyObject *));
    if (!handles || !col_out || !valid_out || !present_out) goto fail_alloc;
    for (Py_ssize_t j = 0; j < m; j++) {
        if (kinds[j] == 's') col_out[j] = PyList_New(n);
        else col_out[j] = PyBytes_FromStringAndSize(NULL, n * 8);
        valid_out[j] = PyBytes_FromStringAndSize(NULL, n);
        present_out[j] = PyBytes_FromStringAndSize(NULL, n);
        if (!col_out[j] || !valid_out[j] || !present_out[j]) goto fail_alloc;
        if (kinds[j] != 's')  /* invalid slots must read as 0, like the
                                 Python path */
            memset(PyBytes_AS_STRING(col_out[j]), 0, (size_t)(n * 8));
        memset(PyBytes_AS_STRING(valid_out[j]), 0, (size_t)n);
        memset(PyBytes_AS_STRING(present_out[j]), 0, (size_t)n);
    }

    int64_t *hbuf = (int64_t *)PyBytes_AS_STRING(handles);
    Py_ssize_t out_i = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        const uint8_t *kp;
        Py_ssize_t klen;
        {
            PyObject *ko = PySequence_Fast_GET_ITEM(keys, i);
            if (PyBytes_AsStringAndSize(ko, (char **)&kp, &klen) < 0)
                goto fail_alloc;
        }
        /* record key: 't' + INT(9) + "_r" + INT(9) */
        if (klen != 21 || kp[0] != 't' || kp[10] != '_' || kp[11] != 'r'
            || kp[12] != INT_FLAG)
            continue;  /* not a row key: skip like the Python path */
        uint64_t hu = 0;
        for (int b8 = 0; b8 < 8; b8++) hu = (hu << 8) | kp[13 + b8];
        int64_t handle = (int64_t)(hu ^ SIGN_MASK);
        hbuf[out_i] = handle;

        const uint8_t *vp;
        Py_ssize_t vlen;
        {
            PyObject *vo = PySequence_Fast_GET_ITEM(vals, i);
            if (PyBytes_AsStringAndSize(vo, (char **)&vp, &vlen) < 0)
                goto fail_alloc;
        }
        Rd r = {vp, vlen, 0};
        if (!(vlen == 1 && vp[0] == NIL_FLAG)) {  /* empty-row sentinel */
            while (r.pos < r.len) {
                int64_t cid;
                if (r.p[r.pos] != VARINT_FLAG) {
                    PyErr_SetString(Unsupported, "row col-id not varint");
                    goto fail_alloc;
                }
                r.pos++;
                if (rd_varint(&r, &cid) < 0) {
                    PyErr_SetString(Unsupported, "truncated row value");
                    goto fail_alloc;
                }
                Py_ssize_t j = -1;
                for (Py_ssize_t jj = 0; jj < m; jj++)
                    if (cid_arr[jj] == cid) { j = jj; break; }
                if (j < 0) {
                    if (skip_value_datum(&r) < 0) {
                        PyErr_SetString(Unsupported, "undecodable datum");
                        goto fail_alloc;
                    }
                    continue;
                }
                Dec d;
                if (decode_value_datum(&r, &d) < 0) {
                    PyErr_SetString(Unsupported, "undecodable datum");
                    goto fail_alloc;
                }
                PyBytes_AS_STRING(present_out[j])[out_i] = 1;
                char kind = kinds[j];
                if (d.kind == 0) {
                    /* NULL: valid stays 0 */
                    if (kind == 's') {
                        Py_INCREF(Py_None);
                        PyList_SET_ITEM(col_out[j], out_i, Py_None);
                    }
                } else if (kind == 'i') {
                    int64_t v = d.kind == 1 ? d.i : 0;
                    if (d.kind == 3) {
                        PyMem_Free(d.owned);
                        PyErr_SetString(Unsupported, "bytes in int column");
                        goto fail_alloc;
                    }
                    if (d.kind == 2) {
                        /* float datum in an int plane: the Python pack
                         * path raises Unsupported (CPU fallback) rather
                         * than silently truncating — keep parity */
                        PyErr_SetString(Unsupported, "float in int column");
                        goto fail_alloc;
                    }
                    ((int64_t *)PyBytes_AS_STRING(col_out[j]))[out_i] = v;
                    PyBytes_AS_STRING(valid_out[j])[out_i] = 1;
                } else if (kind == 'f') {
                    double v = d.kind == 2 ? d.f
                             : d.kind == 1 ? (double)d.i : 0.0;
                    if (d.kind == 3) {
                        PyMem_Free(d.owned);
                        PyErr_SetString(Unsupported, "bytes in float column");
                        goto fail_alloc;
                    }
                    ((double *)PyBytes_AS_STRING(col_out[j]))[out_i] = v;
                    PyBytes_AS_STRING(valid_out[j])[out_i] = 1;
                } else {  /* 's' */
                    if (d.kind != 3) {
                        PyErr_SetString(Unsupported,
                                        "non-bytes in string column");
                        goto fail_alloc;
                    }
                    PyObject *bs = PyBytes_FromStringAndSize(
                        (const char *)d.bytes, d.blen);
                    PyMem_Free(d.owned);
                    if (!bs) goto fail_alloc;
                    PyList_SET_ITEM(col_out[j], out_i, bs);
                    PyBytes_AS_STRING(valid_out[j])[out_i] = 1;
                }
            }
        }
        if (pk_idx >= 0) {
            ((int64_t *)PyBytes_AS_STRING(col_out[pk_idx]))[out_i] = handle;
            PyBytes_AS_STRING(valid_out[pk_idx])[out_i] = 1;
            PyBytes_AS_STRING(present_out[pk_idx])[out_i] = 1;
        }
        out_i++;
    }

    /* unfilled string slots (absent column) must hold None, not NULL ptr */
    for (Py_ssize_t j = 0; j < m; j++) {
        if (kinds[j] != 's') continue;
        for (Py_ssize_t i2 = 0; i2 < n; i2++) {
            if (!PyList_GET_ITEM(col_out[j], i2)) {
                Py_INCREF(Py_None);
                PyList_SET_ITEM(col_out[j], i2, Py_None);
            }
        }
    }

    PyObject *cols_t = PyTuple_New(m);
    PyObject *valid_t = PyTuple_New(m);
    PyObject *present_t = PyTuple_New(m);
    if (!cols_t || !valid_t || !present_t) {
        Py_XDECREF(cols_t); Py_XDECREF(valid_t); Py_XDECREF(present_t);
        goto fail_alloc;
    }
    for (Py_ssize_t j = 0; j < m; j++) {
        PyTuple_SET_ITEM(cols_t, j, col_out[j]);
        PyTuple_SET_ITEM(valid_t, j, valid_out[j]);
        PyTuple_SET_ITEM(present_t, j, present_out[j]);
        col_out[j] = valid_out[j] = present_out[j] = NULL;
    }
    PyMem_Free(col_out); PyMem_Free(valid_out); PyMem_Free(present_out);
    Py_DECREF(keys); Py_DECREF(vals); Py_DECREF(cids);
    PyObject *res = Py_BuildValue("nNNNN", out_i, handles, cols_t, valid_t,
                                  present_t);
    return res;

fail_alloc:
    Py_XDECREF(handles);
    if (col_out) for (Py_ssize_t j = 0; j < m; j++) Py_XDECREF(col_out[j]);
    if (valid_out) for (Py_ssize_t j = 0; j < m; j++) Py_XDECREF(valid_out[j]);
    if (present_out) for (Py_ssize_t j = 0; j < m; j++) Py_XDECREF(present_out[j]);
    PyMem_Free(col_out); PyMem_Free(valid_out); PyMem_Free(present_out);
fail_seqs:
    Py_DECREF(keys); Py_DECREF(vals); Py_DECREF(cids);
    if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "pack_rows failed");
    return NULL;
}

/* ------------------------------------------------------------------ */
/* decode_row_datums: row value bytes → {col_id: Datum} — the row-scan  */
/* hot loop (tablecodec.decode_row). Builds real Datum objects with the */
/* EXACT kinds the Python decoder produces (i64/u64/f64/bytes/Duration/ */
/* Time); DECIMAL and anything unknown raises Unsupported so the caller */
/* redoes the whole value in Python. Reference: tablecodec.DecodeRow    */
/* (tablecodec.go:198).                                                 */
/* ------------------------------------------------------------------ */

static PyObject *dx_datum_cls, *dx_null, *dx_duration_cls,
    *dx_time_from_packed, *dx_kinds[16];

static int dx_init(void) {
    /* readiness is keyed on the LAST global assigned: imports below can
     * release the GIL, so a concurrent caller observing a half-built
     * state must see "not ready" and run the (idempotent) init itself.
     * All globals are written together at the end, between which the
     * GIL is never released. */
    if (dx_time_from_packed) return 0;
    PyObject *datum_cls = NULL, *null_obj = NULL, *duration_cls = NULL,
        *from_packed = NULL, *kinds[16] = {0};
    PyObject *dm = PyImport_ImportModule("tidb_tpu.types.datum");
    if (!dm) return -1;
    datum_cls = PyObject_GetAttrString(dm, "Datum");
    null_obj = PyObject_GetAttrString(dm, "NULL");
    PyObject *kind = PyObject_GetAttrString(dm, "Kind");
    Py_DECREF(dm);
    if (!datum_cls || !null_obj || !kind) goto fail;
    for (int i = 0; i < 16; i++) {
        PyObject *k = PyObject_CallFunction(kind, "i", i);
        if (!k) { PyErr_Clear(); k = PyLong_FromLong(i); }
        kinds[i] = k;
    }
    Py_DECREF(kind);
    kind = NULL;
    PyObject *tm = PyImport_ImportModule("tidb_tpu.types.time_types");
    if (!tm) goto fail;
    duration_cls = PyObject_GetAttrString(tm, "Duration");
    PyObject *time_cls = PyObject_GetAttrString(tm, "Time");
    Py_DECREF(tm);
    if (!duration_cls || !time_cls) goto fail;
    from_packed = PyObject_GetAttrString(time_cls, "from_packed_int");
    Py_DECREF(time_cls);
    if (!from_packed) goto fail;
    if (dx_time_from_packed) {
        /* another thread completed while an import had the GIL released */
        Py_DECREF(datum_cls); Py_DECREF(null_obj);
        Py_DECREF(duration_cls); Py_DECREF(from_packed);
        for (int i = 0; i < 16; i++) Py_XDECREF(kinds[i]);
        return 0;
    }
    dx_datum_cls = datum_cls;
    dx_null = null_obj;
    dx_duration_cls = duration_cls;
    for (int i = 0; i < 16; i++) dx_kinds[i] = kinds[i];
    dx_time_from_packed = from_packed;   /* readiness flag: LAST */
    return 0;
fail:
    Py_XDECREF(datum_cls); Py_XDECREF(null_obj);
    Py_XDECREF(duration_cls); Py_XDECREF(from_packed);
    Py_XDECREF(kind);
    for (int i = 0; i < 16; i++) Py_XDECREF(kinds[i]);
    return -1;
}

static PyObject *dx_make(int kind, PyObject *val /* stolen */) {
    if (!val) return NULL;
    PyObject *d = PyObject_CallFunctionObjArgs(dx_datum_cls,
                                               dx_kinds[kind], val, NULL);
    Py_DECREF(val);
    return d;
}

static PyObject *dx_decode_value(Rd *r) {
    if (r->pos >= r->len) {
        PyErr_SetString(Unsupported, "truncated row value");
        return NULL;
    }
    uint8_t flag = r->p[r->pos++];
    uint64_t u;
    int64_t v;
    switch (flag) {
    case NIL_FLAG:
        Py_INCREF(dx_null);
        return dx_null;
    case VARINT_FLAG:
        if (rd_varint(r, &v) < 0) goto bad;
        return dx_make(K_I64, PyLong_FromLongLong(v));
    case INT_FLAG:
        if (rd_u64be(r, &u) < 0) goto bad;
        return dx_make(K_I64, PyLong_FromLongLong((int64_t)(u ^ SIGN_MASK)));
    case UVARINT_FLAG:
        if (rd_uvarint(r, &u) < 0) goto bad;
        return dx_make(K_U64, PyLong_FromUnsignedLongLong(u));
    case UINT_FLAG:
        if (rd_u64be(r, &u) < 0) goto bad;
        return dx_make(K_U64, PyLong_FromUnsignedLongLong(u));
    case FLOAT_FLAG: {
        if (rd_u64be(r, &u) < 0) goto bad;
        if (u & SIGN_MASK) u &= ~SIGN_MASK; else u = ~u;
        double f;
        memcpy(&f, &u, 8);
        return dx_make(K_F64, PyFloat_FromDouble(f));
    }
    case COMPACT_BYTES_FLAG: {
        if (rd_varint(r, &v) < 0 || v < 0 || r->pos + v > r->len) goto bad;
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)(r->p + r->pos), (Py_ssize_t)v);
        r->pos += v;
        return dx_make(K_BYTES, b);
    }
    case DURATION_FLAG: {
        if (rd_u64be(r, &u) < 0) goto bad;
        PyObject *nanos = PyLong_FromLongLong((int64_t)(u ^ SIGN_MASK));
        if (!nanos) return NULL;
        PyObject *dur = PyObject_CallFunctionObjArgs(dx_duration_cls,
                                                     nanos, NULL);
        Py_DECREF(nanos);
        return dx_make(K_DUR, dur);
    }
    case TIME_FLAG: {
        if (rd_u64be(r, &u) < 0) goto bad;
        PyObject *packed = PyLong_FromUnsignedLongLong(u);
        if (!packed) return NULL;
        PyObject *t = PyObject_CallFunctionObjArgs(dx_time_from_packed,
                                                   packed, NULL);
        Py_DECREF(packed);
        return dx_make(K_TIME, t);
    }
    default:
        /* DECIMAL, memcomparable BYTES (never in row values), unknown */
        PyErr_SetString(Unsupported, "datum flag not handled natively");
        return NULL;
    }
bad:
    PyErr_SetString(Unsupported, "truncated row value");
    return NULL;
}

static PyObject *py_decode_row_datums(PyObject *self, PyObject *args) {
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) return NULL;
    if (dx_init() < 0) { PyBuffer_Release(&buf); return NULL; }
    PyObject *out = PyDict_New();
    if (!out) { PyBuffer_Release(&buf); return NULL; }
    Rd r = {(const uint8_t *)buf.buf, buf.len, 0};
    if (r.len == 0 || (r.len == 1 && r.p[0] == NIL_FLAG)) {
        PyBuffer_Release(&buf);
        return out;
    }
    while (r.pos < r.len) {
        /* column id: always VARINT-encoded by encode_row */
        int64_t cid;
        if (r.p[r.pos] != VARINT_FLAG) {
            PyErr_SetString(Unsupported, "row col-id not varint");
            goto fail;
        }
        r.pos++;
        if (rd_varint(&r, &cid) < 0) {
            PyErr_SetString(Unsupported, "truncated row value");
            goto fail;
        }
        PyObject *d = dx_decode_value(&r);
        if (!d) goto fail;
        PyObject *key = PyLong_FromLongLong(cid);
        if (!key) { Py_DECREF(d); goto fail; }
        int rc = PyDict_SetItem(out, key, d);
        Py_DECREF(key);
        Py_DECREF(d);
        if (rc < 0) goto fail;
    }
    PyBuffer_Release(&buf);
    return out;
fail:
    PyBuffer_Release(&buf);
    Py_DECREF(out);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* join_rows: batch-assemble joined executor rows from device-join     */
/* match index pairs. out[i] = lrows[l_idx[i]] + rrows[r_idx[i]], with */
/* r_idx[i] == -1 emitting a LEFT OUTER NULL pad of right_width — the  */
/* columnar join's row materialization tail in one C pass instead of a */
/* per-row Python generator (the per-row dispatch tax the coprocessor  */
/* model exists to avoid).                                             */
/* ------------------------------------------------------------------ */

static PyObject *py_join_rows(PyObject *self, PyObject *args) {
    PyObject *lrows, *rrows;
    Py_buffer lbuf, rbuf;
    Py_ssize_t right_width;
    if (!PyArg_ParseTuple(args, "O!O!y*y*n", &PyList_Type, &lrows,
                          &PyList_Type, &rrows, &lbuf, &rbuf, &right_width))
        return NULL;
    PyObject *out = NULL;
    if (lbuf.len != rbuf.len || lbuf.len % 8 != 0 || right_width < 0) {
        PyErr_SetString(Unsupported, "join_rows: bad index buffers");
        goto done;
    }
    if (dx_init() < 0) goto done;   /* for the NULL pad singleton */
    Py_ssize_t n = lbuf.len / 8;
    const int64_t *li = (const int64_t *)lbuf.buf;
    const int64_t *ri = (const int64_t *)rbuf.buf;
    Py_ssize_t nl = PyList_GET_SIZE(lrows), nr = PyList_GET_SIZE(rrows);
    out = PyList_New(n);
    if (!out) goto done;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (li[i] < 0 || li[i] >= nl || ri[i] >= nr) {
            PyErr_SetString(Unsupported, "join_rows: index out of range");
            Py_CLEAR(out);
            goto done;
        }
        PyObject *lrow = PyList_GET_ITEM(lrows, li[i]);
        PyObject *rrow = ri[i] >= 0 ? PyList_GET_ITEM(rrows, ri[i]) : NULL;
        if (!PyList_Check(lrow) || (rrow && !PyList_Check(rrow))) {
            PyErr_SetString(Unsupported, "join_rows: rows must be lists");
            Py_CLEAR(out);
            goto done;
        }
        Py_ssize_t lw = PyList_GET_SIZE(lrow);
        Py_ssize_t rw = rrow ? PyList_GET_SIZE(rrow) : right_width;
        PyObject *row = PyList_New(lw + rw);
        if (!row) { Py_CLEAR(out); goto done; }
        for (Py_ssize_t j = 0; j < lw; j++) {
            PyObject *v = PyList_GET_ITEM(lrow, j);
            Py_INCREF(v);
            PyList_SET_ITEM(row, j, v);
        }
        for (Py_ssize_t j = 0; j < rw; j++) {
            PyObject *v = rrow ? PyList_GET_ITEM(rrow, j) : dx_null;
            Py_INCREF(v);
            PyList_SET_ITEM(row, lw + j, v);
        }
        PyList_SET_ITEM(out, i, row);
    }
done:
    PyBuffer_Release(&lbuf);
    PyBuffer_Release(&rbuf);
    return out;
}

/* ------------------------------------------------------------------ */
/* num_plane: one numeric column of materialized executor rows → value */
/* + validity planes in one C pass — the join key-array fast path      */
/* (columnar.rows_plane). Only {NULL, INT64, FLOAT64} columns qualify, */
/* and int/float may not mix (the dict join path's codec keys treat    */
/* int 1 and float 1.0 as distinct); anything else raises Unsupported  */
/* and the caller's Python scan decides.                               */
/* ------------------------------------------------------------------ */

static PyObject *py_num_plane(PyObject *self, PyObject *args) {
    PyObject *rows;
    Py_ssize_t idx;
    if (!PyArg_ParseTuple(args, "O!n", &PyList_Type, &rows, &idx))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(rows);
    union { int64_t i; double f; } *vals = NULL;
    uint8_t *valid = NULL;
    PyObject *vbytes = NULL, *mbytes = NULL, *out = NULL;
    int is_f64 = -1;   /* -1 = undecided (only NULLs so far) */
    vals = PyMem_Malloc(n ? n * 8 : 8);
    valid = PyMem_Malloc(n ? n : 1);
    if (!vals || !valid) { PyErr_NoMemory(); goto done; }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *row = PyList_GET_ITEM(rows, i);
        if (!PyList_Check(row) || idx < 0 || idx >= PyList_GET_SIZE(row)) {
            PyErr_SetString(Unsupported, "num_plane: bad row shape");
            goto done;
        }
        PyObject *d = PyList_GET_ITEM(row, idx);
        PyObject *kobj = PyObject_GetAttr(d, s_kind);
        if (!kobj) goto done;
        long k = PyLong_AsLong(kobj);
        Py_DECREF(kobj);
        if (k == -1 && PyErr_Occurred()) goto done;
        if (k == K_NULL) {
            valid[i] = 0;
            vals[i].i = 0;
            continue;
        }
        if (k != K_I64 && k != K_F64) {
            PyErr_SetString(Unsupported, "num_plane: non-numeric kind");
            goto done;
        }
        int f = (k == K_F64);
        if (is_f64 == -1) is_f64 = f;
        else if (is_f64 != f) {
            PyErr_SetString(Unsupported, "num_plane: mixed int/float");
            goto done;
        }
        PyObject *val = PyObject_GetAttr(d, s_val);
        if (!val) goto done;
        if (f) {
            double v = PyFloat_AsDouble(val);
            Py_DECREF(val);
            if (v == -1.0 && PyErr_Occurred()) goto done;
            vals[i].f = v;
        } else {
            int overflow = 0;
            long long v = PyLong_AsLongLongAndOverflow(val, &overflow);
            Py_DECREF(val);
            if (overflow || (v == -1 && PyErr_Occurred())) {
                if (!PyErr_Occurred())
                    PyErr_SetString(Unsupported, "num_plane: i64 overflow");
                goto done;
            }
            vals[i].i = v;
        }
        valid[i] = 1;
    }
    vbytes = PyBytes_FromStringAndSize((const char *)vals, n * 8);
    mbytes = PyBytes_FromStringAndSize((const char *)valid, n);
    if (vbytes && mbytes)
        out = Py_BuildValue("sOO", is_f64 == 1 ? "f" : "i", vbytes, mbytes);
done:
    PyMem_Free(vals);
    PyMem_Free(valid);
    Py_XDECREF(vbytes);
    Py_XDECREF(mbytes);
    return out;
}

static PyMethodDef methods[] = {
    {"decode_row_datums", py_decode_row_datums, METH_VARARGS,
     "decode_row_datums(value) -> {col_id: Datum} (row-scan fast path)"},
    {"join_rows", py_join_rows, METH_VARARGS,
     "join_rows(lrows, rrows, l_idx, r_idx, right_width) -> "
     "list[list] (device-join row materialization)"},
    {"num_plane", py_num_plane, METH_VARARGS,
     "num_plane(rows, idx) -> (kind, values, valid) numeric column plane"},
    {"encode_row", py_encode_row, METH_VARARGS,
     "encode_row(col_ids, datums) -> bytes (compact row value layout)"},
    {"encode_datums", py_encode_datums, METH_VARARGS,
     "encode_datums(datums, comparable) -> bytes"},
    {"pack_rows", py_pack_rows, METH_VARARGS,
     "pack_rows(keys, values, col_ids, kinds, pk_idx) -> "
     "(n, handles, cols, valids, presents)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "codecx", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit_codecx(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    Unsupported = PyErr_NewException("codecx.Unsupported", NULL, NULL);
    if (!Unsupported || PyModule_AddObject(m, "Unsupported", Unsupported) < 0)
        return NULL;
    s_kind = PyUnicode_InternFromString("kind");
    s_val = PyUnicode_InternFromString("val");
    s_nanos = PyUnicode_InternFromString("nanos");
    s_to_packed_int = PyUnicode_InternFromString("to_packed_int");
    if (!s_kind || !s_val || !s_nanos || !s_to_packed_int) return NULL;
    return m;
}
