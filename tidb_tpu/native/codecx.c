/* Native datum codec: the hot host-side encode path.
 *
 * Reference: util/codec/codec.go (EncodeKey/EncodeValue), number.go,
 * bytes.go — the same flag+payload layout tidb_tpu/codec implements in
 * Python; this module is a drop-in accelerator for the write path
 * (tablecodec.encode_row, index key encoding) where per-datum Python
 * dispatch dominates bulk-load cost. Falls back to the Python codec by
 * raising Unsupported for kinds it does not handle (DECIMAL, INTERFACE).
 *
 * Exposes:
 *   encode_row(col_ids, datums)        -> bytes   (value encoding)
 *   encode_datums(datums, comparable)  -> bytes
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *Unsupported;

/* flag bytes — must mirror tidb_tpu/codec/codec.py */
enum {
    NIL_FLAG = 0x00,
    BYTES_FLAG = 0x01,
    COMPACT_BYTES_FLAG = 0x02,
    INT_FLAG = 0x03,
    UINT_FLAG = 0x04,
    FLOAT_FLAG = 0x05,
    DURATION_FLAG = 0x07,
    TIME_FLAG = 0x08,
    VARINT_FLAG = 0x09,
    UVARINT_FLAG = 0x0A,
    MAX_FLAG = 0xFA,
};

/* Kind enum values — must mirror tidb_tpu/types/datum.py */
enum {
    K_NULL = 0, K_I64 = 1, K_U64 = 2, K_F64 = 3, K_STR = 4, K_BYTES = 5,
    K_DEC = 6, K_DUR = 7, K_TIME = 8, K_MIN = 100, K_MAX = 101,
};

#define SIGN_MASK 0x8000000000000000ULL

typedef struct {
    uint8_t *p;
    size_t len, cap;
} Buf;

static int buf_reserve(Buf *b, size_t extra) {
    if (b->len + extra <= b->cap) return 0;
    size_t cap = b->cap ? b->cap : 256;
    while (cap < b->len + extra) cap <<= 1;
    uint8_t *np = PyMem_Realloc(b->p, cap);
    if (!np) { PyErr_NoMemory(); return -1; }
    b->p = np;
    b->cap = cap;
    return 0;
}

static inline int buf_putc(Buf *b, uint8_t c) {
    if (buf_reserve(b, 1) < 0) return -1;
    b->p[b->len++] = c;
    return 0;
}

static inline int buf_put(Buf *b, const void *src, size_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->p + b->len, src, n);
    b->len += n;
    return 0;
}

static inline int put_u64be(Buf *b, uint64_t v) {
    uint8_t tmp[8];
    for (int i = 7; i >= 0; i--) { tmp[i] = (uint8_t)(v & 0xFF); v >>= 8; }
    return buf_put(b, tmp, 8);
}

static inline int put_uvarint(Buf *b, uint64_t v) {
    uint8_t tmp[10];
    int n = 0;
    while (v >= 0x80) { tmp[n++] = (uint8_t)(v & 0x7F) | 0x80; v >>= 7; }
    tmp[n++] = (uint8_t)v;
    return buf_put(b, tmp, n);
}

static inline int put_varint(Buf *b, int64_t v) {
    uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
    return put_uvarint(b, u);
}

static inline uint64_t float_cmp_bits(double d) {
    if (d == 0.0) d = 0.0;  /* normalize -0.0 */
    uint64_t u;
    memcpy(&u, &d, 8);
    if (u & SIGN_MASK) u = ~u;
    else u |= SIGN_MASK;
    return u;
}

/* memcomparable bytes: 8-byte groups, 0x00 pad, marker = 0xFF - pad */
static int put_cmp_bytes(Buf *b, const uint8_t *d, Py_ssize_t n) {
    Py_ssize_t i;
    for (i = 0; i <= n; i += 8) {
        Py_ssize_t rem = n - i;
        if (rem >= 8) {
            if (buf_put(b, d + i, 8) < 0 || buf_putc(b, 0xFF) < 0) return -1;
            if (rem == 8) { /* loop emits trailing empty group next */ }
        } else {
            uint8_t grp[9];
            memset(grp, 0, 9);
            memcpy(grp, d + i, (size_t)rem);
            grp[8] = (uint8_t)(0xFF - (8 - rem));
            return buf_put(b, grp, 9);
        }
    }
    return 0;
}

/* cached attr name objects */
static PyObject *s_kind, *s_val, *s_nanos, *s_to_packed_int;

static int encode_one(Buf *b, PyObject *datum, int comparable) {
    PyObject *kobj = PyObject_GetAttr(datum, s_kind);
    if (!kobj) return -1;
    long k = PyLong_AsLong(kobj);  /* Kind is an IntEnum (PyLong subclass) */
    Py_DECREF(kobj);
    if (k == -1 && PyErr_Occurred()) return -1;

    if (k == K_NULL) return buf_putc(b, NIL_FLAG);
    if (k == K_MIN) return buf_putc(b, BYTES_FLAG);
    if (k == K_MAX) return buf_putc(b, MAX_FLAG);

    PyObject *val = PyObject_GetAttr(datum, s_val);
    if (!val) return -1;
    int rc = -1;

    switch (k) {
    case K_I64: {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(val, &overflow);
        if (overflow || (v == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(Unsupported, "int64 overflow");
            break;
        }
        if (comparable) {
            if (buf_putc(b, INT_FLAG) == 0)
                rc = put_u64be(b, (uint64_t)v ^ SIGN_MASK);
        } else {
            if (buf_putc(b, VARINT_FLAG) == 0)
                rc = put_varint(b, v);
        }
        break;
    }
    case K_U64: {
        unsigned long long v = PyLong_AsUnsignedLongLong(val);
        if (v == (unsigned long long)-1 && PyErr_Occurred()) {
            /* out-of-range raises OverflowError; downgrade to Unsupported so
               callers fall back to the Python codec (which masks) */
            if (PyErr_ExceptionMatches(PyExc_OverflowError)) {
                PyErr_Clear();
                PyErr_SetString(Unsupported, "u64 out of range");
            }
            break;
        }
        if (comparable) {
            if (buf_putc(b, UINT_FLAG) == 0) rc = put_u64be(b, v);
        } else {
            if (buf_putc(b, UVARINT_FLAG) == 0) rc = put_uvarint(b, v);
        }
        break;
    }
    case K_F64: {
        double d = PyFloat_AsDouble(val);
        if (d == -1.0 && PyErr_Occurred()) break;
        if (buf_putc(b, FLOAT_FLAG) == 0)
            rc = put_u64be(b, float_cmp_bits(d));
        break;
    }
    case K_STR:
    case K_BYTES: {
        const char *data;
        Py_ssize_t n;
        if (k == K_STR) {
            data = PyUnicode_AsUTF8AndSize(val, &n);
            if (!data) break;
        } else {
            if (PyBytes_AsStringAndSize(val, (char **)&data, &n) < 0) break;
        }
        if (comparable) {
            if (buf_putc(b, BYTES_FLAG) == 0)
                rc = put_cmp_bytes(b, (const uint8_t *)data, n);
        } else {
            /* compact: zig-zag varint length + raw bytes */
            if (buf_putc(b, COMPACT_BYTES_FLAG) == 0 &&
                put_varint(b, (int64_t)n) == 0)
                rc = buf_put(b, data, (size_t)n);
        }
        break;
    }
    case K_DUR: {
        PyObject *nanos = PyObject_GetAttr(val, s_nanos);
        if (!nanos) break;
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(nanos, &overflow);
        Py_DECREF(nanos);
        if (overflow || (v == -1 && PyErr_Occurred())) break;
        if (buf_putc(b, DURATION_FLAG) == 0)
            rc = put_u64be(b, (uint64_t)v ^ SIGN_MASK);
        break;
    }
    case K_TIME: {
        PyObject *packed = PyObject_CallMethodNoArgs(val, s_to_packed_int);
        if (!packed) break;
        unsigned long long v = PyLong_AsUnsignedLongLong(packed);
        Py_DECREF(packed);
        if (v == (unsigned long long)-1 && PyErr_Occurred()) {
            if (PyErr_ExceptionMatches(PyExc_OverflowError)) {
                PyErr_Clear();
                PyErr_SetString(Unsupported, "time packed value out of range");
            }
            break;
        }
        if (buf_putc(b, TIME_FLAG) == 0) rc = put_u64be(b, v);
        break;
    }
    default:
        PyErr_Format(Unsupported, "kind %ld not encodable natively", k);
        break;
    }
    Py_DECREF(val);
    return rc;
}

static PyObject *py_encode_row(PyObject *self, PyObject *args) {
    PyObject *cids_obj, *datums_obj;
    if (!PyArg_ParseTuple(args, "OO", &cids_obj, &datums_obj)) return NULL;
    PyObject *cids = PySequence_Fast(cids_obj, "col_ids not a sequence");
    if (!cids) return NULL;
    PyObject *datums = PySequence_Fast(datums_obj, "datums not a sequence");
    if (!datums) { Py_DECREF(cids); return NULL; }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(cids);
    if (PySequence_Fast_GET_SIZE(datums) != n) {
        Py_DECREF(cids); Py_DECREF(datums);
        PyErr_SetString(PyExc_ValueError, "column/value count mismatch");
        return NULL;
    }
    Buf b = {0};
    if (n == 0) {
        if (buf_putc(&b, NIL_FLAG) < 0) goto fail;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        long long cid = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(cids, i));
        if (cid == -1 && PyErr_Occurred()) goto fail;
        if (buf_putc(&b, VARINT_FLAG) < 0 || put_varint(&b, cid) < 0)
            goto fail;
        if (encode_one(&b, PySequence_Fast_GET_ITEM(datums, i), 0) < 0)
            goto fail;
    }
    Py_DECREF(cids); Py_DECREF(datums);
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.p,
                                              (Py_ssize_t)b.len);
    PyMem_Free(b.p);
    return out;
fail:
    Py_DECREF(cids); Py_DECREF(datums);
    PyMem_Free(b.p);
    return NULL;
}

static PyObject *py_encode_datums(PyObject *self, PyObject *args) {
    PyObject *datums_obj;
    int comparable;
    if (!PyArg_ParseTuple(args, "Op", &datums_obj, &comparable)) return NULL;
    PyObject *datums = PySequence_Fast(datums_obj, "datums not a sequence");
    if (!datums) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(datums);
    Buf b = {0};
    for (Py_ssize_t i = 0; i < n; i++) {
        if (encode_one(&b, PySequence_Fast_GET_ITEM(datums, i),
                       comparable) < 0) {
            Py_DECREF(datums);
            PyMem_Free(b.p);
            return NULL;
        }
    }
    Py_DECREF(datums);
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.p,
                                              (Py_ssize_t)b.len);
    PyMem_Free(b.p);
    return out;
}

static PyMethodDef methods[] = {
    {"encode_row", py_encode_row, METH_VARARGS,
     "encode_row(col_ids, datums) -> bytes (compact row value layout)"},
    {"encode_datums", py_encode_datums, METH_VARARGS,
     "encode_datums(datums, comparable) -> bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "codecx", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit_codecx(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    Unsupported = PyErr_NewException("codecx.Unsupported", NULL, NULL);
    if (!Unsupported || PyModule_AddObject(m, "Unsupported", Unsupported) < 0)
        return NULL;
    s_kind = PyUnicode_InternFromString("kind");
    s_val = PyUnicode_InternFromString("val");
    s_nanos = PyUnicode_InternFromString("nanos");
    s_to_packed_int = PyUnicode_InternFromString("to_packed_int");
    if (!s_kind || !s_val || !s_nanos || !s_to_packed_int) return NULL;
    return m;
}
