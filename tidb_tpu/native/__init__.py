"""Native (C) runtime components, built on demand.

The C sources live next to this file; the extension is compiled once into
this directory with the host toolchain (cc -O2 -shared) and imported from
there. Every consumer must treat the import as optional — the pure-Python
implementations remain the semantic definition and the fallback (the
driver environment guarantees a toolchain, but portability is free).

Components:
    codecx — datum codec encode fast path (tidb_tpu/codec parity)
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))


def _build(name: str):
    src = os.path.join(_DIR, f"{name}.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_DIR, f"{name}{suffix}")
    try:
        stale = (not os.path.exists(out)
                 or os.path.getmtime(out) < os.path.getmtime(src))
    except OSError:
        stale = False  # source missing: use a prebuilt .so if present
        if not os.path.exists(out):
            return None
    if stale:
        cc = os.environ.get("CC", "cc")
        include = sysconfig.get_paths()["include"]
        # compile to a temp name and os.replace() so concurrent interpreters
        # never dlopen a half-written .so
        tmp = os.path.join(_DIR, f".{name}.{os.getpid()}{suffix}")
        cmd = [cc, "-O2", "-fPIC", "-shared", "-o", tmp, src,
               f"-I{include}"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    spec = importlib.util.spec_from_file_location(
        f"tidb_tpu.native.{name}", out)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except Exception:
        return None
    sys.modules[f"tidb_tpu.native.{name}"] = mod
    return mod


codecx = None if os.environ.get("TIDB_TPU_NO_NATIVE") else _build("codecx")
