"""Serializable schema metadata.

Reference: model/model.go (DBInfo/TableInfo/ColumnInfo/IndexInfo),
model/ddl.go (Job, schema states for online DDL).
"""

from tidb_tpu.model.model import (  # noqa: F401
    SchemaState,
    ColumnInfo,
    IndexColumn,
    IndexInfo,
    FKInfo,
    TableInfo,
    DBInfo,
)
from tidb_tpu.model.ddl_job import DDLJob, JobState, ActionType  # noqa: F401
