"""DDL job records for the asynchronous schema-change queue.

Reference: model/ddl.go (Job, JobState) and ddl/ddl_worker.go queue protocol.
Jobs are enqueued by any server and processed by the elected owner, stepping
schema objects through SchemaState transitions.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any


class ActionType(enum.IntEnum):
    NONE = 0
    CREATE_SCHEMA = 1
    DROP_SCHEMA = 2
    CREATE_TABLE = 3
    DROP_TABLE = 4
    ADD_COLUMN = 5
    DROP_COLUMN = 6
    ADD_INDEX = 7
    DROP_INDEX = 8
    TRUNCATE_TABLE = 9
    MODIFY_COLUMN = 10
    ADD_FOREIGN_KEY = 11
    DROP_FOREIGN_KEY = 12


class JobState(enum.IntEnum):
    NONE = 0
    RUNNING = 1
    ROLLBACK = 2
    DONE = 3
    CANCELLED = 4
    SYNCED = 5


@dataclass
class DDLJob:
    id: int
    tp: ActionType
    schema_id: int
    table_id: int = 0
    state: JobState = JobState.NONE
    error: str = ""
    error_code: int = 0
    # action-specific payload (column def json, index def json, names…)
    args: list[Any] = field(default_factory=list)
    # reorg progress checkpoint (ddl/reorg.go reorgInfo.UpdateHandle)
    reorg_handle: int | None = None
    schema_state: int = 0
    snapshot_ver: int = 0

    def serialize(self) -> bytes:
        return json.dumps({
            "id": self.id, "tp": int(self.tp), "schema_id": self.schema_id,
            "table_id": self.table_id, "state": int(self.state),
            "error": self.error, "error_code": self.error_code, "args": self.args,
            "reorg_handle": self.reorg_handle,
            "schema_state": self.schema_state,
            "snapshot_ver": self.snapshot_ver,
        }, separators=(",", ":")).encode()

    @staticmethod
    def deserialize(b: bytes) -> "DDLJob":
        d = json.loads(b)
        return DDLJob(d["id"], ActionType(d["tp"]), d["schema_id"], d["table_id"],
                      JobState(d["state"]), d.get("error", ""),
                      d.get("error_code", 0), d.get("args", []),
                      d.get("reorg_handle"), d.get("schema_state", 0),
                      d.get("snapshot_ver", 0))

    def is_finished(self) -> bool:
        return self.state in (JobState.DONE, JobState.CANCELLED, JobState.SYNCED)
