"""Schema structs with JSON serialization for meta storage.

Reference: model/model.go. Schema states implement F1-style online schema
change (None → DeleteOnly → WriteOnly → WriteReorganization → Public); every
reader/writer consults column/index state so concurrent servers at adjacent
schema versions stay consistent.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any

from tidb_tpu import mysqldef as my
from tidb_tpu.types.field_type import FieldType


class SchemaState(enum.IntEnum):
    NONE = 0
    DELETE_ONLY = 1
    WRITE_ONLY = 2
    WRITE_REORG = 3
    PUBLIC = 4


def _ft_to_json(ft: FieldType) -> dict:
    return {"tp": ft.tp, "flag": ft.flag, "flen": ft.flen, "decimal": ft.decimal,
            "charset": ft.charset, "collate": ft.collate, "elems": ft.elems}


def _ft_from_json(d: dict) -> FieldType:
    return FieldType(d["tp"], d["flag"], d["flen"], d["decimal"],
                     d.get("charset", "utf8"), d.get("collate", "utf8_bin"),
                     d.get("elems"))


@dataclass
class ColumnInfo:
    id: int
    name: str
    offset: int
    field_type: FieldType
    default_value: Any = None      # string form; None = no default
    has_default: bool = False
    # value returned for rows written before this column existed
    # (reference: column.go original default; avoids ADD COLUMN backfill)
    original_default: Any = None
    comment: str = ""
    state: SchemaState = SchemaState.PUBLIC

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name, "offset": self.offset,
                "type": _ft_to_json(self.field_type),
                "default": self.default_value, "has_default": self.has_default,
                "orig_default": self.original_default,
                "comment": self.comment, "state": int(self.state)}

    @staticmethod
    def from_json(d: dict) -> "ColumnInfo":
        return ColumnInfo(d["id"], d["name"], d["offset"], _ft_from_json(d["type"]),
                          d.get("default"), d.get("has_default", False),
                          d.get("orig_default"),
                          d.get("comment", ""), SchemaState(d.get("state", 4)))

    @property
    def lower_name(self) -> str:
        return self.name.lower()

    def original_default_datum(self):
        """Typed Datum for rows written before this column existed
        (column.go original default); NULL when the column had no default.
        Single source for the table-read path and the copr protocol."""
        from tidb_tpu.types.convert import convert_datum
        from tidb_tpu.types.datum import NULL, datum_from_py
        if self.original_default is None:
            return NULL
        return convert_datum(datum_from_py(self.original_default),
                             self.field_type)


@dataclass
class IndexColumn:
    name: str
    offset: int
    length: int = -1  # prefix length; -1 = whole column

    def to_json(self) -> dict:
        return {"name": self.name, "offset": self.offset, "length": self.length}

    @staticmethod
    def from_json(d: dict) -> "IndexColumn":
        return IndexColumn(d["name"], d["offset"], d.get("length", -1))


@dataclass
class IndexInfo:
    id: int
    name: str
    columns: list[IndexColumn]
    unique: bool = False
    primary: bool = False
    state: SchemaState = SchemaState.PUBLIC

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name,
                "columns": [c.to_json() for c in self.columns],
                "unique": self.unique, "primary": self.primary,
                "state": int(self.state)}

    @staticmethod
    def from_json(d: dict) -> "IndexInfo":
        return IndexInfo(d["id"], d["name"],
                         [IndexColumn.from_json(c) for c in d["columns"]],
                         d.get("unique", False), d.get("primary", False),
                         SchemaState(d.get("state", 4)))


@dataclass
class FKInfo:
    """Foreign-key metadata (model.FKInfo, reference model/model.go).
    2016 semantics are metadata-only — the reference records the key and
    never enforces referential integrity (ddl/foreign_key.go:46 "We just
    support record the foreign key"); same contract here."""
    id: int
    name: str
    cols: list[str]
    ref_table: str
    ref_cols: list[str]
    on_delete: str = ""     # "" | RESTRICT | CASCADE | SET NULL | NO ACTION
    on_update: str = ""
    state: SchemaState = SchemaState.PUBLIC

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name, "cols": self.cols,
                "ref_table": self.ref_table, "ref_cols": self.ref_cols,
                "on_delete": self.on_delete, "on_update": self.on_update,
                "state": int(self.state)}

    @staticmethod
    def from_json(d: dict) -> "FKInfo":
        return FKInfo(d["id"], d["name"], list(d["cols"]), d["ref_table"],
                      list(d["ref_cols"]), d.get("on_delete", ""),
                      d.get("on_update", ""),
                      SchemaState(d.get("state", 4)))


@dataclass
class TableInfo:
    id: int
    name: str
    columns: list[ColumnInfo] = field(default_factory=list)
    indices: list[IndexInfo] = field(default_factory=list)
    pk_is_handle: bool = False     # single int PK stored as the row handle
    auto_increment_offset: int = 0
    charset: str = "utf8"
    collate: str = "utf8_bin"
    comment: str = ""
    state: SchemaState = SchemaState.PUBLIC
    foreign_keys: list[FKInfo] = field(default_factory=list)
    # high-water mark of every index id EVER allocated on this table.
    # Index ids must never be reused: a transaction planned against an
    # older schema (where a since-dropped index was still writable) can
    # commit AFTER the drop's data deletion, orphaning entries under the
    # dead id — a new index reusing that id would inherit them as
    # corrupt rows (model.TableInfo MaxIndexID in the reference).
    max_index_id: int = 0

    def to_json(self) -> dict:
        return {"id": self.id, "name": self.name,
                "columns": [c.to_json() for c in self.columns],
                "indices": [i.to_json() for i in self.indices],
                "pk_is_handle": self.pk_is_handle,
                "charset": self.charset, "collate": self.collate,
                "comment": self.comment, "state": int(self.state),
                "foreign_keys": [f.to_json() for f in self.foreign_keys],
                "max_index_id": self.max_index_id}

    @staticmethod
    def from_json(d: dict) -> "TableInfo":
        return TableInfo(d["id"], d["name"],
                         [ColumnInfo.from_json(c) for c in d["columns"]],
                         [IndexInfo.from_json(i) for i in d.get("indices", [])],
                         d.get("pk_is_handle", False), 0,
                         d.get("charset", "utf8"), d.get("collate", "utf8_bin"),
                         d.get("comment", ""), SchemaState(d.get("state", 4)),
                         [FKInfo.from_json(f)
                          for f in d.get("foreign_keys", [])],
                         d.get("max_index_id", 0))

    def alloc_index_id(self) -> int:
        """Next never-before-used index id (monotonic per table; stores
        written before max_index_id existed resume from max(existing))."""
        self.max_index_id = max(self.max_index_id,
                                max((i.id for i in self.indices),
                                    default=0)) + 1
        return self.max_index_id

    def serialize(self) -> bytes:
        return json.dumps(self.to_json(), separators=(",", ":")).encode()

    @staticmethod
    def deserialize(b: bytes) -> "TableInfo":
        return TableInfo.from_json(json.loads(b))

    # ---- helpers ----
    def find_column(self, name: str) -> ColumnInfo | None:
        lname = name.lower()
        for c in self.columns:
            if c.lower_name == lname:
                return c
        return None

    def pk_handle_column(self) -> ColumnInfo | None:
        if not self.pk_is_handle:
            return None
        for c in self.columns:
            if my.has_pri_key_flag(c.field_type.flag):
                return c
        return None

    def public_columns(self) -> list[ColumnInfo]:
        return [c for c in self.columns if c.state == SchemaState.PUBLIC]

    def writable_columns(self) -> list[ColumnInfo]:
        return [c for c in self.columns
                if c.state in (SchemaState.WRITE_ONLY, SchemaState.WRITE_REORG,
                               SchemaState.PUBLIC)]

    def find_index(self, name: str) -> IndexInfo | None:
        lname = name.lower()
        for idx in self.indices:
            if idx.name.lower() == lname:
                return idx
        return None


@dataclass
class DBInfo:
    id: int
    name: str
    charset: str = "utf8"
    collate: str = "utf8_bin"
    state: SchemaState = SchemaState.PUBLIC

    def serialize(self) -> bytes:
        return json.dumps({"id": self.id, "name": self.name, "charset": self.charset,
                           "collate": self.collate, "state": int(self.state)},
                          separators=(",", ":")).encode()

    @staticmethod
    def deserialize(b: bytes) -> "DBInfo":
        d = json.loads(b)
        return DBInfo(d["id"], d["name"], d.get("charset", "utf8"),
                      d.get("collate", "utf8_bin"), SchemaState(d.get("state", 4)))
