"""Scheduled MVCC garbage collection.

Reference: store/localstore/compactor.go (background compactor, policy
{SafePoint: 20min, TriggerInterval: 1s}) and store/tikv/gc_worker.go:375
(one leader-elected GC worker per cluster, 1min tick, safepoint = now −
10min). Here both run as daemon tick threads owned by the Domain; the
cluster worker takes a lease on a meta key so that when several Domains
(servers) share one cluster store, exactly one runs GC per tick —
the same single-leader discipline as saveValueToSysTable/leader checks in
the reference.
"""

from __future__ import annotations

import threading
import time
import uuid as uuidlib

from tidb_tpu import metrics
from tidb_tpu.structure import TxStructure

GC_LEASE_KEY = b"GCLease"


def _clamp_to_active(store, safe_point: int) -> int:
    """Never reclaim versions a live snapshot/txn may still read: the
    effective safepoint is min(age-based point, oldest active start_ts - 1)
    — the reference's early design lacks this and a statement running
    longer than the safe age silently loses versions mid-scan; our own
    benchmarks run in that duration range."""
    oldest_fn = getattr(store, "oldest_active_ts", None)
    oldest = oldest_fn() if oldest_fn is not None else None
    if oldest is not None:
        return min(safe_point, oldest - 1)
    return safe_point

# safepoint ages (ms): localstore compactor 20min, cluster gc 10min
LOCAL_SAFE_AGE_MS = 20 * 60 * 1000
CLUSTER_SAFE_AGE_MS = 10 * 60 * 1000


class _TickThread:
    """Shared scaffolding: daemon thread calling tick() every interval,
    stoppable, with a synchronous tick for tests."""

    def __init__(self, name: str, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # GC must never take the server down; next tick retries
                metrics.counter("gc.tick_errors").inc()

    def tick(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError


class Compactor(_TickThread):
    """Periodic localstore MVCC compaction (compactor.go). Skips ticks
    with no new writes since the last one — the reference triggers off
    write notifications; the data-version probe is our equivalent."""

    def __init__(self, store, interval_s: float = 1.0,
                 safe_age_ms: int = LOCAL_SAFE_AGE_MS):
        super().__init__("tidb-compactor", interval_s)
        self.store = store
        self.safe_age_ms = safe_age_ms
        self._last_version = -1

    def tick(self) -> int:
        # commit count, NOT the clock TSO (which always advances):
        # no new commits since the last tick → nothing to reclaim
        cur = self.store.data_version_at(self.store.current_version())
        if cur == self._last_version:
            return 0
        from tidb_tpu.kv.kv import ms_to_version
        safe = ms_to_version(int(time.time() * 1000) - self.safe_age_ms)
        clamped = _clamp_to_active(self.store, safe)
        removed = self.store.compact(safe_point_ts=clamped)
        # only after a SUCCESSFUL compact — a raise must leave the version
        # probe stale so the next tick retries. A CLAMPED tick also stays
        # unconsumed: once the pinning reader departs, the next tick must
        # reclaim what it protected even on a write-idle store
        if clamped >= safe:
            self._last_version = cur
        metrics.counter("compactor.runs").inc()
        if removed:
            metrics.counter("compactor.versions_removed").inc(removed)
        return removed


class GCWorker(_TickThread):
    """Cluster GC under a lease: the meta key GCLease holds
    `uuid:expiry_ms`; a worker runs GC only while it owns (or can take
    over) the lease (gc_worker.go checkLeader via system table)."""

    def __init__(self, store, interval_s: float = 60.0,
                 safe_age_ms: int = CLUSTER_SAFE_AGE_MS,
                 lease_ms: int = 120_000):
        super().__init__("tidb-gc-worker", interval_s)
        self.store = store
        self.safe_age_ms = safe_age_ms
        self.lease_ms = lease_ms
        self.uuid = uuidlib.uuid4().hex[:12]

    def _try_lease(self) -> bool:
        now = int(time.time() * 1000)
        txn = self.store.begin()
        try:
            t = TxStructure(txn, txn, prefix=b"m")
            raw = t.get(GC_LEASE_KEY)
            if raw:
                holder, _, expiry = raw.decode().partition(":")
                if holder != self.uuid and int(expiry or 0) > now:
                    txn.rollback()
                    return False  # someone else holds a live lease
            t.set(GC_LEASE_KEY,
                  f"{self.uuid}:{now + self.lease_ms}".encode())
            txn.commit()
            return True
        except Exception:
            txn.rollback()
            return False

    def tick(self) -> int:
        if not self._try_lease():
            metrics.counter("gc.lease_lost").inc()
            return 0
        safe_point = _clamp_to_active(self.store, self._safe_point())
        removed = self.store.run_gc(safe_point)
        metrics.counter("gc.runs").inc()
        if removed:
            metrics.counter("gc.versions_removed").inc(removed)
        return removed

    def _safe_point(self) -> int:
        from tidb_tpu.kv.kv import ms_to_version
        return ms_to_version(int(time.time() * 1000) - self.safe_age_ms)
