"""Statement + plan digests: the workload-aggregation identity.

Reference: the reference's parser normalization (util/sqlexec /
parser.Normalize + parser.DigestNormalized in later TiDB: literals fold
to '?', whitespace collapses, keywords/identifiers case-fold, IN-lists
collapse to one marker) and plan digests (util/plancodec.NormalizePlan:
the physical tree SHAPE, not its per-run constants). A digest is the key
every workload-level surface aggregates on —
performance_schema.events_statements_summary_by_digest, the TOP-SQL
view, SHOW PROCESSLIST's DIGEST column — so two statements differing
only in literals MUST map to one digest and two different plan shapes
must not.

The normalizer rides the SQL lexer's token stream (parser.lexer), not a
second hand-rolled scanner, so anything the parser accepts normalizes
consistently; a statement the lexer rejects still gets a stable digest
from its folded raw text (errors are workload too). Cost discipline:
one tokenize pass per statement (same order of work as the parse that
already ran) — the tier-1 overhead guard holds the whole digest +
summary pipeline under 2 ms per statement.
"""

from __future__ import annotations

import hashlib

from tidb_tpu.parser import lexer as lx

# literal-ish token types that fold to the '?' marker (PARAM itself is
# already the marker, so prepared text and literal text share digests)
_LITERALS = frozenset((lx.STRING, lx.INT, lx.DECIMAL, lx.FLOAT, lx.HEX,
                       lx.BIT, lx.PARAM))

# no space BEFORE these punctuation tokens when rendering the
# normalized text (cosmetic only — the digest is over the rendered text,
# so the rules just need to be deterministic)
_TIGHT_BEFORE = frozenset((",", ")", ".", ";"))
_TIGHT_AFTER = frozenset(("(", "."))


def _hash(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()


def _ends_operand(t) -> bool:
    """Can this token END an operand? Decides whether a following +/- is
    a binary operator (`a - 1`, `(x) - 1`) or a unary sign (`= -1`,
    `select -1`, `(-1`) whose literal folds to one '?'."""
    return (t.tp == lx.IDENT or t.tp == lx.SYS_VAR or t.tp == lx.USER_VAR
            or t.tp in _LITERALS or (t.tp == lx.OP and t.val == ")"))


def normalize(sql: str) -> str:
    """Canonical statement text: literals → '?', IN (?, ?, …) → (...),
    keywords/identifiers lower-cased, whitespace/comments folded.
    Lexer-rejected text falls back to a whitespace/case fold of the raw
    statement so every statement — even a syntax error — normalizes."""
    try:
        toks = lx.tokenize(sql)
    except Exception:  # noqa: BLE001 — unlexable input still digests
        return " ".join(sql.split()).lower()
    words: list[str] = []
    i = 0
    n = len(toks)
    while i < n:
        t = toks[i]
        if t.tp == lx.EOF:
            break
        if t.tp in _LITERALS:
            # a unary sign folds into the literal's '?' so text `-1` and
            # a prepared param bound to -1 share a digest; a BINARY +/-
            # (operand on its left) keeps its shape
            if words and words[-1] in ("-", "+") \
                    and (i < 2 or not _ends_operand(toks[i - 2])):
                words.pop()
            words.append("?")
            i += 1
            continue
        if t.tp == lx.OP and t.val == "(":
            j = i + 1
            items = commas = 0
            while j < n:
                tj = toks[j]
                if tj.tp in _LITERALS:
                    items += 1
                elif tj.tp == lx.OP and tj.val == ",":
                    commas += 1
                elif tj.tp == lx.OP and tj.val in ("-", "+"):
                    pass       # signed literal item
                else:
                    break
                j += 1
            # collapse when it IS a list (>=2 literal items) — or a
            # single-literal parens directly after IN, so `in (1)` and
            # `in (1, 2, 3)` share a digest ("any arity" contract); a
            # bare parenthesized literal elsewhere keeps its shape
            is_list = items >= 2 and commas >= 1
            if (is_list or (items == 1 and commas == 0 and words
                            and words[-1] == "in")) \
                    and j < n and toks[j].tp == lx.OP and toks[j].val == ")":
                words.append("(...)")
                i = j + 1
                continue
            words.append("(")
            i += 1
            continue
        if t.tp == lx.KEYWORD:
            words.append(str(t.val).lower())
        elif t.tp == lx.IDENT:
            words.append(str(t.val).lower())
        elif t.tp == lx.SYS_VAR:
            words.append("@@" + str(t.val).lower())
        elif t.tp == lx.USER_VAR:
            words.append("@" + str(t.val).lower())
        else:  # operators / punctuation
            words.append(str(t.val))
        i += 1
    # render with light spacing so DIGEST_TEXT reads like SQL
    out: list[str] = []
    for w in words:
        if out and w not in _TIGHT_BEFORE and out[-1] not in _TIGHT_AFTER:
            out.append(" ")
        out.append(w)
    return "".join(out)


def sql_digest(sql: str) -> tuple[str, str]:
    """(digest hex, normalized text) for one statement."""
    norm = normalize(sql)
    return _hash(norm), norm


# ---------------------------------------------------------------------------
# plan digest: the physical tree's SHAPE
# ---------------------------------------------------------------------------

def _plan_label(p) -> str:
    """One node's shape-relevant identity: operator type plus the
    attributes that change how it executes (table/index, pushed-down
    payload kinds, join keys count) — never per-run constants (range
    bounds, literal filters), which belong to the SQL digest."""
    parts = [p.tp]
    tp = p.tp
    if tp in ("tscan", "iscan"):
        ti = getattr(p, "table_info", None)
        if ti is not None:
            parts.append(f"t={ti.name.lower()}")
        idx = getattr(p, "index", None)
        if idx is not None:
            parts.append(f"i={idx.name.lower()}")
        if getattr(p, "double_read", False):
            parts.append("dr")
        if getattr(p, "pushed_where", None) is not None:
            parts.append("w")
        if getattr(p, "aggregates", None):
            parts.append(f"agg={len(p.aggregates)}")
        if getattr(p, "topn_pb", None):
            parts.append("topn")
        if getattr(p, "limit", None) is not None:
            parts.append("lim")
        if getattr(p, "desc", False):
            parts.append("desc")
    elif tp == "phashjoin":
        parts.append(f"jt={getattr(p, 'join_type', 0)}")
        parts.append(f"eq={len(getattr(p, 'eq_conditions', ()))}")
    elif tp in ("phashagg", "pstreamagg"):
        parts.append(f"f={len(getattr(p, 'agg_funcs', ()))}")
        parts.append(f"g={len(getattr(p, 'group_by', ()))}")
    elif tp == "ptopn":
        parts.append(f"by={len(getattr(p, 'by_items', ()))}")
    elif tp == "insert":
        t = getattr(p, "table", None)
        info = getattr(t, "info", None)
        if info is not None:
            parts.append(f"t={info.name.lower()}")
    return ":".join(parts)


def plan_digest(plan) -> tuple[str, str]:
    """(digest hex, normalized plan text) from a physical plan tree.
    The text is the indented shape rendering the digest hashes — kept
    as the summary's PLAN_SAMPLE so a digest is explainable."""
    lines: list[str] = []

    def walk(p, depth: int) -> None:
        lines.append("  " * depth + _plan_label(p))
        for c in getattr(p, "children", ()):
            walk(c, depth + 1)
        inner = getattr(p, "inner_plan", None)
        if inner is not None:
            walk(inner, depth + 1)
        sel = getattr(p, "select_plan", None)
        if sel is not None:
            walk(sel, depth + 1)

    walk(plan, 0)
    text = "\n".join(lines)
    return _hash(text), text
