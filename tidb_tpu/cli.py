"""tidb-tpu server daemon + interactive shell.

Reference: tidb-server/main.go:44-62 — flags for store engine/path, ports,
and runtime toggles; the process serves the MySQL wire protocol until
interrupted. `--repl` additionally runs an interactive SQL shell on the
same store (the reference ships no shell, but a CLI is the zero-dependency
way to poke a running engine; mysql-client compatible via the server).

Run:  python -m tidb_tpu.cli --store memory --port 4000
      python -m tidb_tpu.cli --repl            (shell only, no listener)
"""

from __future__ import annotations

import argparse
import sys
import time


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tidb-tpu",
        description="TPU-native MySQL-compatible SQL engine")
    ap.add_argument("--store", default="memory",
                    choices=["memory", "local", "cluster"],
                    help="storage engine (tidb-server -store)")
    ap.add_argument("--path", default="tidb",
                    help="storage path / cluster spec (-path)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("-P", "--port", type=int, default=4000)
    ap.add_argument("--token-limit", type=int, default=100,
                    help="max concurrent connections (tokenlimiter.go)")
    ap.add_argument("--copr", default="cpu", choices=["cpu", "tpu"],
                    help="coprocessor engine backend")
    ap.add_argument("--repl", action="store_true",
                    help="interactive SQL shell instead of serving")
    ap.add_argument("--lease", type=float, default=1.0,
                    help="schema lease seconds (tidb-server -lease); "
                         "enables the schema-validity kill-switch at "
                         "2x lease, 0 disables")
    ap.add_argument("--status-port", type=int, default=10080,
                    help="HTTP status/metrics port (server.go:213); "
                         "-1 disables")
    ap.add_argument("--metrics-addr", default="",
                    help="Prometheus Pushgateway host:port; empty "
                         "disables the push client (tidb-server "
                         "-metrics-addr)")
    ap.add_argument("--metrics-interval", type=float, default=15.0,
                    help="push interval seconds; 0 disables "
                         "(tidb-server -metrics-interval)")
    ap.add_argument("--binlog-path", default="",
                    help="append binlog events (prewrite/commit/"
                         "rollback JSONL) to this file; the pluggable "
                         "pump equivalent of tidb-server -binlog-socket")
    return ap


def open_store(args):
    from tidb_tpu.session import new_store
    url = f"{args.store}://{args.path}"
    store = new_store(url)
    if args.copr == "tpu":
        from tidb_tpu.session import Session
        # the swap path reads the persisted tidb_tpu_dispatch_floor global
        # (mysql.global_variables) into the new client, so an operator's
        # SET GLOBAL survives a server restart
        Session(store, internal=True).apply_copr_backend("tpu")
    return store


def repl(store) -> int:
    from tidb_tpu import errors
    from tidb_tpu.session import Session
    s = Session(store)
    print("tidb-tpu shell; end statements with ';', exit with \\q")
    buf = ""
    while True:
        try:
            prompt = "tidb> " if not buf else "   -> "
            line = input(prompt)
        except EOFError:
            print()
            return 0
        if line.strip() in ("\\q", "exit", "quit"):
            return 0
        buf += line + "\n"
        if ";" not in line:
            continue
        sql, buf = buf, ""
        t0 = time.time()
        try:
            results = s.execute(sql)
        except errors.TiDBError as e:
            print(f"ERROR {getattr(e, 'code', 0)}: {e}")
            continue
        for rs in results:
            names = rs.field_names()
            rows = [[_cell(v) for v in row] for row in rs.values()]
            _print_table(names, rows)
        n = (len(results[-1].rows) if results
             else s.vars.affected_rows)
        kind = "rows in set" if results else "rows affected"
        print(f"{n} {kind} ({time.time() - t0:.2f} sec)\n")


def _cell(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


def _print_table(names, rows) -> None:
    widths = [len(n) for n in names]
    for row in rows:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    print(sep)
    print("|" + "|".join(f" {n:<{w}} " for n, w in zip(names, widths)) + "|")
    print(sep)
    for row in rows:
        print("|" + "|".join(f" {v:<{w}} "
                             for v, w in zip(row, widths)) + "|")
    print(sep)


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.binlog_path:
        from tidb_tpu import binloginfo
        binloginfo.set_pump(binloginfo.FilePump(args.binlog_path))
    from tidb_tpu.metrics.push import start_push_client
    start_push_client(args.metrics_addr, args.metrics_interval)
    store = open_store(args)
    if args.repl:
        return repl(store)
    from tidb_tpu.server import Server
    if args.lease > 0:
        from tidb_tpu.domain import get_domain
        dom = get_domain(store)
        dom.ddl.schema_lease_s = args.lease
        # reload every lease/2 (started here so Server.start()'s default
        # loop call no-ops) and kill in-flight statements when no reload
        # succeeds for 2x lease (domain.go:474)
        dom.start_reload_loop(interval_s=args.lease / 2)
        dom.schema_validity_lease_s = 2 * args.lease
    srv = Server(store, host=args.host, port=args.port,
                 token_limit=args.token_limit,
                 status_port=None if args.status_port < 0
                 else args.status_port)
    srv.start()
    print(f"tidb-tpu listening on {args.host}:{srv.port} "
          f"(store={args.store}://{args.path}, copr={args.copr}, "
          f"status={srv.status_port})",
          file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()
        return 0


if __name__ == "__main__":
    sys.exit(main())
