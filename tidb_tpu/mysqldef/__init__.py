"""MySQL protocol/semantic constants (subset).

Reference: mysql/type.go, mysql/const.go, mysql/errcode.go in /root/reference.
Only the constants the engine actually consults are defined; the wire server
(tidb_tpu.server) will extend this as protocol support widens.
"""

# ---- column type codes (mysql/type.go) ----
TypeDecimal = 0x00
TypeTiny = 0x01
TypeShort = 0x02
TypeLong = 0x03
TypeFloat = 0x04
TypeDouble = 0x05
TypeNull = 0x06
TypeTimestamp = 0x07
TypeLonglong = 0x08
TypeInt24 = 0x09
TypeDate = 0x0A
TypeDuration = 0x0B
TypeDatetime = 0x0C
TypeYear = 0x0D
TypeNewDate = 0x0E
TypeVarchar = 0x0F
TypeBit = 0x10
TypeNewDecimal = 0xF6
TypeEnum = 0xF7
TypeSet = 0xF8
TypeTinyBlob = 0xF9
TypeMediumBlob = 0xFA
TypeLongBlob = 0xFB
TypeBlob = 0xFC
TypeVarString = 0xFD
TypeString = 0xFE
TypeGeometry = 0xFF

STRING_TYPES = frozenset(
    (TypeVarchar, TypeVarString, TypeString, TypeBlob, TypeTinyBlob,
     TypeMediumBlob, TypeLongBlob)
)
INTEGER_TYPES = frozenset((TypeTiny, TypeShort, TypeInt24, TypeLong, TypeLonglong, TypeYear))
FLOAT_TYPES = frozenset((TypeFloat, TypeDouble))
TIME_TYPES = frozenset((TypeDate, TypeNewDate, TypeDatetime, TypeTimestamp))

# ---- column flags (mysql/const.go) ----
NotNullFlag = 1
PriKeyFlag = 2
UniqueKeyFlag = 4
MultipleKeyFlag = 8
BlobFlag = 16
UnsignedFlag = 32
ZerofillFlag = 64
BinaryFlag = 128
AutoIncrementFlag = 512
OnUpdateNowFlag = 8192


def has_unsigned_flag(flag: int) -> bool:
    return bool(flag & UnsignedFlag)


def has_not_null_flag(flag: int) -> bool:
    return bool(flag & NotNullFlag)


def has_auto_increment_flag(flag: int) -> bool:
    return bool(flag & AutoIncrementFlag)


def has_pri_key_flag(flag: int) -> bool:
    return bool(flag & PriKeyFlag)


# ---- default lengths (mysql/type.go GetDefaultFieldLength equivalents) ----
def default_field_length(tp: int) -> int:
    return {
        TypeTiny: 4, TypeShort: 6, TypeInt24: 9, TypeLong: 11, TypeLonglong: 21,
        TypeFloat: 12, TypeDouble: 22, TypeNewDecimal: 11, TypeDuration: 10,
        TypeDate: 10, TypeDatetime: 19, TypeTimestamp: 19, TypeYear: 4,
    }.get(tp, -1)


# ---- integer bounds ----
MaxInt64 = (1 << 63) - 1
MinInt64 = -(1 << 63)
MaxUint64 = (1 << 64) - 1

SIGNED_BOUNDS = {
    TypeTiny: (-128, 127),
    TypeShort: (-32768, 32767),
    TypeInt24: (-8388608, 8388607),
    TypeLong: (-2147483648, 2147483647),
    TypeLonglong: (MinInt64, MaxInt64),
    TypeYear: (1901, 2155),
}
UNSIGNED_BOUNDS = {
    TypeTiny: 255,
    TypeShort: 65535,
    TypeInt24: 16777215,
    TypeLong: 4294967295,
    TypeLonglong: MaxUint64,
    TypeYear: 2155,
}

# ---- error codes (subset of mysql/errcode.go) ----
ErrDupEntry = 1062
ErrBadDB = 1049
ErrNoSuchTable = 1146
ErrTableExists = 1050
ErrBadField = 1054
ErrParse = 1064
ErrUnknown = 1105
ErrDivisionByZero = 1365
ErrDataTooLong = 1406
ErrTruncated = 1265
ErrNonUniq = 1052
ErrWrongValueCount = 1136
ErrCantDropFieldOrKey = 1091
ErrDupKeyName = 1061
ErrDBCreateExists = 1007
ErrDBDropExists = 1008
ErrAccessDenied = 1045
ErrConCount = 1040          # "Too many connections" (admission gate)

# THE server version string: version() builtin, @@version sysvar, and the
# wire handshake must all agree — drivers version-gate features on it
# (reference: mysql/const.go ServerVersion)
SERVER_VERSION = "5.7.25-TiDB-TPU-1.0"
