"""Hierarchical query tracing: per-statement span trees with per-region
and device-kernel attribution.

Reference: the reference's util/tracing (opentracing spans around each
Execute, session.go:454) and executor runtime stats
(executor/executor.go RuntimeStats / distsql metrics) — here one
lightweight span tree per statement, built only when a consumer asked
for it (EXPLAIN ANALYZE, TRACE, or SET tidb_trace_enabled = 1), plus a
set of always-on per-thread counters cheap enough for every statement
(the slow-query log / performance_schema execution-detail source).

Design rules:

* OFF must cost ~nothing: `current()` is one thread-local read; every
  span operation on the shared NOOP sentinel is a constant-returning
  method. With both `tidb_trace_enabled` and the flight recorder
  (tidb_tpu.flight) disabled, no Span object is ever allocated
  (`span_allocations` counts real allocations so tests can assert
  exactly that). With the flight recorder live — its default — every
  top-level statement builds a SCRATCH span tree, but a healthy
  statement retains none of it: the tree is dropped at statement end
  unless the statement crossed the slow-log threshold, died on its
  deadline, or degraded through a tier (the extended guard asserts
  < 2 ms/statement and zero retained allocations on that fast path).
* Worker threads (the cluster fan-out) attach explicitly: a span
  created on the statement thread is handed to the worker, which
  `attach()`es it so nested `trace(...)` blocks land under the right
  region task. CPython list.append/dict assignment make the child/attr
  writes safe without a lock.
* Span times are perf_counter_ns; rendered durations are microseconds.
"""

from __future__ import annotations

import threading
import time

_tls = threading.local()

# real Span allocations since process start — the overhead guard asserts
# this stays flat across untraced statements
span_allocations = 0


class Span:
    """One node of a statement's span tree."""

    __slots__ = ("name", "start_ns", "end_ns", "attrs", "children", "tid")

    def __init__(self, name: str):
        global span_allocations
        span_allocations += 1
        self.name = name
        self.start_ns = time.perf_counter_ns()
        self.end_ns = 0
        self.attrs: dict = {}
        self.children: list[Span] = []
        # creating thread's lane for the cross-thread trace-event
        # export; fan-out workers RE-STAMP the region-task span they
        # execute so the exported timeline shows real worker lanes
        self.tid = threading.get_ident()

    is_noop = False

    def child(self, name: str) -> "Span":
        sp = Span(name)
        self.children.append(sp)
        return sp

    def set(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def inc(self, key: str, n: int = 1) -> None:
        self.attrs[key] = self.attrs.get(key, 0) + n

    def finish(self) -> None:
        if self.end_ns == 0:
            self.end_ns = time.perf_counter_ns()

    # ---- introspection ----

    def duration_us(self) -> float:
        end = self.end_ns or time.perf_counter_ns()
        return (end - self.start_ns) / 1e3

    def walk(self):
        """Yield self and every descendant, depth-first."""
        yield self
        for c in list(self.children):
            yield from c.walk()

    def find(self, name: str) -> list["Span"]:
        return [s for s in self.walk() if s.name == name]

    def attr_sum(self, key: str) -> int:
        """Sum of an attr over the whole subtree (0 where absent)."""
        return sum(s.attrs.get(key, 0) for s in self.walk()
                   if isinstance(s.attrs.get(key, 0), (int, float)))

    def to_dict(self) -> dict:
        # snapshot attrs/children FIRST: an abandoned fan-out worker
        # (LIMIT stopped the consumer early) may still be mutating this
        # span while the statement thread renders it. dict()/list() are
        # single C-level copies under the GIL — atomic, never the
        # RuntimeError a Python-level iteration over a live dict risks;
        # a late write is simply absent from the snapshot.
        attrs = dict(self.attrs)
        children = list(self.children)
        d: dict = {"name": self.name,
                   "duration_us": round(self.duration_us(), 3),
                   # perf_counter timeline + lane: what the Chrome
                   # trace-event export needs to place this span
                   "start_us": round(self.start_ns / 1e3, 3),
                   "tid": self.tid}
        if attrs:
            d["attrs"] = attrs
        if children:
            d["children"] = [c.to_dict() for c in children]
        return d

    def __repr__(self):
        return f"<Span {self.name} {self.duration_us():.1f}us " \
               f"{self.attrs!r} children={len(self.children)}>"


class _NoopSpan:
    """Shared do-nothing span: every operation returns a constant, so an
    untraced statement pays one thread-local read per instrumentation
    point and zero allocations."""

    __slots__ = ()
    is_noop = True
    name = "noop"
    attrs: dict = {}
    children: list = []

    def child(self, name: str) -> "_NoopSpan":
        return self

    def set(self, key: str, value) -> "_NoopSpan":
        return self

    def inc(self, key: str, n: int = 1) -> None:
        return None

    def finish(self) -> None:
        return None

    def duration_us(self) -> float:
        return 0.0

    def walk(self):
        return iter(())

    def find(self, name: str) -> list:
        return []

    def attr_sum(self, key: str) -> int:
        return 0

    def to_dict(self) -> dict:
        return {"name": "noop"}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP = _NoopSpan()


def current():
    """The thread's active span, or the NOOP sentinel when tracing is
    off — callers chain `.child()/.set()/.inc()` unconditionally."""
    sp = getattr(_tls, "span", None)
    return sp if sp is not None else NOOP


def attach(span) -> object:
    """Make `span` the thread's active span (worker threads attach the
    region-task span handed to them; the statement thread attaches its
    root). Returns a token for detach()."""
    prev = getattr(_tls, "span", None)
    _tls.span = None if span is None or span.is_noop else span
    return prev


def detach(token) -> None:
    _tls.span = token


class trace:
    """Context manager: a child span of the thread's current span, made
    current for the block. On an untraced thread this is a no-op that
    allocates nothing but this tiny context object."""

    __slots__ = ("name", "_span", "_tok")

    def __init__(self, name: str, **attrs):
        self.name = name
        self._span = None
        if attrs:
            parent = current()
            if not parent.is_noop:
                self._span = parent.child(name)
                self._span.attrs.update(attrs)

    def __enter__(self):
        sp = self._span
        if sp is None:
            parent = current()
            if parent.is_noop:
                return NOOP
            sp = self._span = parent.child(self.name)
        self._tok = attach(sp)
        return sp

    def __exit__(self, *exc):
        if self._span is not None:
            self._span.finish()
            detach(self._tok)
        return False


# ---------------------------------------------------------------------------
# always-on per-thread statement counters — the cheap (dict-increment)
# attribution the slow-query log and perfschema execution-detail read
# even when no span tree is being built. Same monotonic-per-thread
# contract as distsql.thread_columnar_counts: snapshot before a
# statement, diff after.
# ---------------------------------------------------------------------------

# the counter keys every consumer renders, in display order (plane-cache
# tallies arrive from distsql's per-partial attribution of the region
# responses; see copr.plane_cache)
COUNTER_KEYS = ("kernel_dispatches", "kernel_dispatch_us",
                "readbacks", "readback_bytes",
                "jit_hits", "jit_misses", "batched",
                "plane_cache_hits", "plane_cache_misses",
                "plane_cache_evictions", "plane_cache_invalidations_epoch",
                "plane_cache_invalidations_version",
                "backoff_retries", "backoff_ms", "session_retries",
                "degraded_device", "degraded_join", "degraded_combine",
                "degraded_mesh", "degraded_batch")


def _tally() -> dict:
    d = getattr(_tls, "tally", None)
    if d is None:
        d = _tls.tally = {}
    return d


def count(name: str, n: int = 1) -> None:
    d = _tally()
    d[name] = d.get(name, 0) + n


def counters_snapshot() -> dict:
    """Copy of this thread's monotonic tallies (diff two snapshots to
    attribute a statement)."""
    return dict(_tally())


def counters_delta(before: dict) -> dict:
    now = _tally()
    keys = set(before) | set(now)
    return {k: now.get(k, 0) - before.get(k, 0) for k in keys
            if now.get(k, 0) != before.get(k, 0)}


def record_dispatch(dispatches: int = 1, readbacks: int = 1,
                    readback_bytes: int = 0,
                    dispatch_us: float = 0.0) -> None:
    """THE device-dispatch tally: per-thread statement counters + the
    ops.* process metrics, in one place so the slow-log, perfschema and
    /metrics surfaces can never drift apart. Called by every kernel
    dispatch site (TpuClient._dispatch_kernel, the join kernels, the
    region-partial combine). `dispatch_us` is the host-observed device
    time of the dispatch (µs, tallied integral) — the statement summary
    rolls it up per digest and TOP-SQL ranks on it."""
    from tidb_tpu import metrics
    count("kernel_dispatches", dispatches)
    metrics.counter("ops.kernel_dispatches").inc(dispatches)
    if dispatch_us:
        us = int(dispatch_us)
        count("kernel_dispatch_us", us)
        metrics.counter("ops.kernel_dispatch_us").inc(us)
    if readbacks:
        count("readbacks", readbacks)
        count("readback_bytes", readback_bytes)
        metrics.counter("ops.readbacks").inc(readbacks)
        metrics.counter("ops.readback_bytes").inc(readback_bytes)


# degradation-chain attribution: fallback kind → the statement-tally key
# the slow log / perfschema render (the copr.degraded_* process counters
# are the /metrics-facing names)
_DEGRADED_TALLY = {"device_to_cpu": "degraded_device",
                   "join_to_numpy": "degraded_join",
                   "combine_to_host": "degraded_combine",
                   "mesh": "degraded_mesh"}


def record_degraded(kind: str, tally: bool = True) -> None:
    """THE degradation tally: one call per tier fallback (device→CPU
    request rerouting, device join→numpy, mesh combine→single-device
    ("mesh" → copr.degraded_mesh), device combine→host, region
    columnar→rows), feeding the copr.degraded_* process counters so
    every fallback is accounted on /metrics and — for statement-thread
    sites — the per-statement thread tallies. Fan-out WORKER threads
    pass tally=False: their per-thread counter would attribute to the
    wrong statement (the process counter stays exact either way)."""
    from tidb_tpu import metrics
    if tally:
        count(_DEGRADED_TALLY.get(kind, f"degraded_{kind}"))
    metrics.counter(f"copr.degraded_{kind}").inc()


def kernel_profile_note(label: str, us: int) -> None:
    """Per-thread per-signature device-time tally — written ONLY by
    profiler.publish (the metered lock's exit), so the statement-level
    `profile:` clause reads the exact figures the global registry got:
    one accounting path, two aggregation scopes."""
    d = getattr(_tls, "kprof", None)
    if d is None:
        d = _tls.kprof = {}
    d[label] = d.get(label, 0) + us


def kernel_profile_snapshot() -> dict:
    d = getattr(_tls, "kprof", None)
    return dict(d) if d else {}


def kernel_profile_delta(before: dict) -> dict:
    """label → device_us this thread accrued since `before` (empty when
    the profiler is off or nothing dispatched)."""
    now = getattr(_tls, "kprof", None)
    if not now:
        return {}
    return {k: v - before.get(k, 0) for k, v in now.items()
            if v != before.get(k, 0)}


def record_jit_cache(hit: bool) -> None:
    """Jit-cache attribution for a compiled-kernel cache lookup."""
    from tidb_tpu import metrics
    if hit:
        count("jit_hits")
        metrics.counter("ops.jit_cache_hits").inc()
    else:
        count("jit_misses")
        metrics.counter("ops.jit_cache_misses").inc()
