"""AST node hierarchy.

Reference: ast/ (ast.go Node/Visitor/ExprNode/StmtNode, expressions.go,
dml.go, ddl.go, functions.go, misc.go). Python version uses dataclass nodes
with an accept(visitor) protocol; visitors mutate in place and return the
(possibly replaced) node, mirroring the reference's mutating visitor.
"""

from tidb_tpu.sqlast.base import Node, ExprNode, StmtNode, Visitor  # noqa: F401
from tidb_tpu.sqlast.opcode import Op  # noqa: F401
from tidb_tpu.sqlast.expressions import (  # noqa: F401
    Literal, ColumnName, BinaryOp, UnaryOp, FuncCall, AggregateFunc,
    WindowFunc,
    Between, InExpr, IntervalExpr, PatternLike, PatternRegexp, IsNull,
    CaseExpr, WhenClause,
    ParamMarker, RowExpr, DefaultExpr, VariableExpr, CastExpr,
    SubqueryExpr, ExistsSubquery,
)
from tidb_tpu.sqlast.dml import (  # noqa: F401
    SelectStmt, SelectField, TableSource, Join, TableName, ByItem, Limit,
    UnionStmt,
    InsertStmt, UpdateStmt, DeleteStmt, Assignment,
)
from tidb_tpu.sqlast.ddl import (  # noqa: F401
    CreateDatabaseStmt, DropDatabaseStmt, CreateTableStmt, DropTableStmt,
    ColumnDef, ColumnOption, ColumnOptionType, Constraint, ConstraintType,
    CreateIndexStmt, DropIndexStmt, AlterTableStmt, AlterTableSpec,
    AlterTableType, TruncateTableStmt, ReferenceDef,
)
from tidb_tpu.sqlast.misc import (  # noqa: F401
    BeginStmt, CommitStmt, RollbackStmt, UseStmt, SetStmt, VariableAssignment,
    ShowStmt, ShowType, ExplainStmt, TraceStmt, AdminStmt, AdminType,
    AnalyzeTableStmt, PrepareStmt, ExecuteStmt, DeallocateStmt,
    UserSpec, GrantStmt, RevokeStmt, CreateUserStmt, DropUserStmt,
    LoadDataStmt, DoStmt, KillStmt, FlushStmt,
)
