"""DDL statement AST nodes.

Reference: ast/ddl.go (CreateTableStmt, ColumnDef, ColumnOption, Constraint,
AlterTableStmt/AlterTableSpec, CreateIndexStmt…).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any

from tidb_tpu.sqlast.base import ExprNode, Node, StmtNode
from tidb_tpu.sqlast.dml import TableName


class ColumnOptionType(enum.IntEnum):
    NOT_NULL = 1
    NULL = 2
    DEFAULT = 3
    AUTO_INCREMENT = 4
    PRIMARY_KEY = 5
    UNIQUE_KEY = 6
    COMMENT = 7
    ON_UPDATE = 8


@dataclass
class ColumnOption(Node):
    tp: ColumnOptionType
    expr: ExprNode | None = None
    comment: str = ""


@dataclass
class ColumnDef(Node):
    name: str
    tp: Any = None  # FieldType
    options: list[ColumnOption] = field(default_factory=list)
    # CHARACTER SET / COLLATE given explicitly (table defaults don't apply)
    charset_explicit: bool = False


class ConstraintType(enum.IntEnum):
    PRIMARY_KEY = 1
    KEY = 2
    INDEX = 3
    UNIQUE = 4
    UNIQUE_KEY = 5
    UNIQUE_INDEX = 6
    FOREIGN_KEY = 7


@dataclass
class ReferenceDef(Node):
    """REFERENCES table (cols) [ON DELETE opt] [ON UPDATE opt]
    (parser.y:1181 ReferDef)."""
    table: "TableName" = None  # type: ignore[assignment]
    columns: list[str] = field(default_factory=list)
    on_delete: str = ""
    on_update: str = ""


@dataclass
class Constraint(Node):
    tp: ConstraintType
    name: str = ""
    keys: list[str] = field(default_factory=list)
    refer: ReferenceDef | None = None   # FOREIGN KEY only


@dataclass
class CreateDatabaseStmt(StmtNode):
    name: str
    if_not_exists: bool = False
    charset: str = "utf8"
    collate: str = "utf8_bin"


@dataclass
class DropDatabaseStmt(StmtNode):
    name: str
    if_exists: bool = False


@dataclass
class CreateTableStmt(StmtNode):
    table: TableName
    cols: list[ColumnDef] = field(default_factory=list)
    constraints: list[Constraint] = field(default_factory=list)
    if_not_exists: bool = False
    charset: str = "utf8"       # table default charset/collation options
    collate: str = "utf8_bin"
    charset_explicit: bool = False   # options given (vs database default)


@dataclass
class DropTableStmt(StmtNode):
    tables: list[TableName] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class TruncateTableStmt(StmtNode):
    table: TableName = None  # type: ignore[assignment]


@dataclass
class CreateIndexStmt(StmtNode):
    index_name: str = ""
    table: TableName = None  # type: ignore[assignment]
    columns: list[str] = field(default_factory=list)
    unique: bool = False


@dataclass
class DropIndexStmt(StmtNode):
    index_name: str = ""
    table: TableName = None  # type: ignore[assignment]
    if_exists: bool = False


class AlterTableType(enum.IntEnum):
    ADD_COLUMN = 1
    DROP_COLUMN = 2
    ADD_CONSTRAINT = 3  # add index/key
    DROP_INDEX = 4
    DROP_PRIMARY_KEY = 5
    MODIFY_COLUMN = 6   # ast.AlterTableModifyColumn
    ADD_FOREIGN_KEY = 7   # via ADD_CONSTRAINT w/ FOREIGN_KEY constraint
    DROP_FOREIGN_KEY = 8  # ast.AlterTableDropForeignKey


@dataclass
class AlterTableSpec(Node):
    tp: AlterTableType
    column: ColumnDef | None = None
    constraint: Constraint | None = None
    name: str = ""


@dataclass
class AlterTableStmt(StmtNode):
    table: TableName = None  # type: ignore[assignment]
    specs: list[AlterTableSpec] = field(default_factory=list)
