"""Transaction-control / session / admin statement AST nodes.

Reference: ast/misc.go (BeginStmt, CommitStmt, SetStmt, UseStmt, ShowStmt…)
and ast/stats.go.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from tidb_tpu.sqlast.base import ExprNode, Node, StmtNode
from tidb_tpu.sqlast.dml import TableName


@dataclass
class BeginStmt(StmtNode):
    pass


@dataclass
class CommitStmt(StmtNode):
    pass


@dataclass
class RollbackStmt(StmtNode):
    pass


@dataclass
class UseStmt(StmtNode):
    db: str = ""


@dataclass
class VariableAssignment(Node):
    name: str
    value: ExprNode | None = None
    is_global: bool = False
    is_system: bool = True


@dataclass
class SetStmt(StmtNode):
    variables: list[VariableAssignment] = field(default_factory=list)


class ShowType(enum.IntEnum):
    DATABASES = 1
    TABLES = 2
    COLUMNS = 3
    CREATE_TABLE = 4
    VARIABLES = 5
    INDEXES = 6
    WARNINGS = 7
    STATUS = 8        # metrics registry (SHOW STATUS)
    GRANTS = 9
    PROCESSLIST = 10
    CHARSET = 11      # SHOW CHARACTER SET (executor/show.go fetchShowCharset)
    COLLATION = 12    # SHOW COLLATION


@dataclass
class ShowStmt(StmtNode):
    tp: ShowType = ShowType.DATABASES
    table: TableName | None = None
    db: str = ""
    full: bool = False
    pattern: str = ""
    host: str = ""     # SHOW GRANTS FOR 'u'@'host' ('' = unspecified)


@dataclass
class ExplainStmt(StmtNode):
    stmt: StmtNode = None  # type: ignore[assignment]
    # EXPLAIN ANALYZE: execute the statement and annotate the plan tree
    # with per-operator runtime stats (ast/misc.go ExplainStmt.Analyze)
    analyze: bool = False


@dataclass
class TraceStmt(StmtNode):
    """TRACE [FORMAT = 'json'] <stmt>: execute the statement under the
    hierarchical tracer and return its span tree (ast/misc.go
    TraceStmt)."""
    stmt: StmtNode = None  # type: ignore[assignment]
    format: str = "json"


class AdminType(enum.IntEnum):
    SHOW_DDL = 1
    CHECK_TABLE = 2
    # ADMIN TPU PROFILE EXPORT: Chrome trace-event JSON of the most
    # recently retained statement trace (Perfetto-loadable)
    TPU_PROFILE_EXPORT = 3


@dataclass
class AdminStmt(StmtNode):
    tp: AdminType = AdminType.SHOW_DDL
    tables: list[TableName] = field(default_factory=list)


@dataclass
class UserSpec(Node):
    """'user'@'host' [IDENTIFIED BY 'password'] (ast/misc.go UserSpec)."""
    user: str = ""
    host: str = "%"
    password: str | None = None


@dataclass
class GrantStmt(StmtNode):
    """GRANT privs ON level TO users (ast/misc.go GrantStmt). Level:
    db=''/table='' → *.* ; table='' → db.* ; else db.table."""
    privs: list[str] = field(default_factory=list)  # names or "ALL"
    db: str = ""
    table: str = ""
    users: list[UserSpec] = field(default_factory=list)
    grant_option: bool = False


@dataclass
class RevokeStmt(StmtNode):
    privs: list[str] = field(default_factory=list)
    db: str = ""
    table: str = ""
    users: list[UserSpec] = field(default_factory=list)


@dataclass
class CreateUserStmt(StmtNode):
    users: list[UserSpec] = field(default_factory=list)
    if_not_exists: bool = False


@dataclass
class DropUserStmt(StmtNode):
    users: list[UserSpec] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class LoadDataStmt(StmtNode):
    """LOAD DATA [LOCAL] INFILE 'file' INTO TABLE t ... (ast/dml.go
    LoadDataStmt). fields/lines options mirror FieldsClause/LinesClause."""
    path: str = ""
    local: bool = False
    table: TableName = None  # type: ignore[assignment]
    columns: list[str] = field(default_factory=list)
    field_term: str = "\t"
    field_enclosed: str = ""
    field_escaped: str = "\\"
    line_term: str = "\n"
    line_starting: str = ""
    ignore_lines: int = 0


@dataclass
class FlushStmt(StmtNode):
    """FLUSH PRIVILEGES / TABLES / STATUS (ast/misc.go FlushTablesStmt)."""
    what: str = "privileges"


@dataclass
class DoStmt(StmtNode):
    """DO expr[, expr…] (ast/misc.go:412 DoStmt): expressions evaluate
    for their side effects; results are discarded."""
    exprs: list = field(default_factory=list)


@dataclass
class KillStmt(StmtNode):
    """KILL [QUERY | CONNECTION] id (ast/misc.go KillStmt)."""
    conn_id: int = 0
    query_only: bool = False


@dataclass
class AnalyzeTableStmt(StmtNode):
    """ANALYZE TABLE t1 [, t2] — builds column histograms
    (ast/stats.go AnalyzeTableStmt; executor/executor_simple.go:253)."""
    tables: list[TableName] = field(default_factory=list)


@dataclass
class PrepareStmt(StmtNode):
    """PREPARE name FROM 'text' | @var (ast/misc.go PrepareStmt)."""
    name: str = ""
    sql_text: str = ""
    from_var: str = ""   # user variable holding the text, if given


@dataclass
class ExecuteStmt(StmtNode):
    """EXECUTE name [USING @a, @b, ...] (ast/misc.go ExecuteStmt)."""
    name: str = ""
    using: list[str] = field(default_factory=list)  # user variable names


@dataclass
class DeallocateStmt(StmtNode):
    """DEALLOCATE | DROP PREPARE name (ast/misc.go DeallocateStmt)."""
    name: str = ""
