"""Node/Visitor base protocol.

Reference: ast/ast.go:26 (Node.Accept), :181 (Visitor.Enter/Leave).
accept() walks children depth-first; Visitor.enter can skip children,
Visitor.leave can replace the node.
"""

from __future__ import annotations

import dataclasses
from typing import Any


class Visitor:
    def enter(self, node: "Node") -> tuple["Node", bool]:
        """Return (node, skip_children)."""
        return node, False

    def leave(self, node: "Node") -> tuple["Node", bool]:
        """Return (possibly replaced node, ok). ok=False aborts the walk."""
        return node, True


class Node:
    """Base AST node. Subclasses are dataclasses; children are discovered
    from fields holding Node / list[Node]."""

    def accept(self, v: Visitor) -> tuple["Node", bool]:
        node, skip = v.enter(self)
        if node is not self:
            return node.accept(v)
        if not skip:
            for f in dataclasses.fields(self):  # type: ignore[arg-type]
                val = getattr(self, f.name)
                if isinstance(val, Node):
                    new, ok = val.accept(v)
                    if not ok:
                        return self, False
                    setattr(self, f.name, new)
                elif isinstance(val, list):
                    for i, item in enumerate(val):
                        if isinstance(item, Node):
                            new, ok = item.accept(v)
                            if not ok:
                                return self, False
                            val[i] = new
        return v.leave(self)

    def children(self) -> list["Node"]:
        out: list[Node] = []
        for f in dataclasses.fields(self):  # type: ignore[arg-type]
            val = getattr(self, f.name)
            if isinstance(val, Node):
                out.append(val)
            elif isinstance(val, list):
                out.extend(x for x in val if isinstance(x, Node))
        return out


class ExprNode(Node):
    """Expression node; `ftype` is filled by type inference.
    Reference: ast/ast.go:57 ExprNode (GetType/SetType)."""
    ftype: Any = None


class StmtNode(Node):
    """Statement node. Reference: ast/ast.go:88."""
    text: str = ""
