"""Operator codes shared by AST, evaluator, and the coprocessor protocol.

Reference: parser/opcode/opcodes.go. The same Op values appear in
copr.select Expr nodes so expression trees cross the pushdown boundary
without re-mapping.
"""

from __future__ import annotations

import enum


class Op(enum.IntEnum):
    # logic
    AndAnd = 1
    OrOr = 2
    Not = 3
    Xor = 4
    # comparison
    EQ = 10
    NE = 11
    LT = 12
    LE = 13
    GT = 14
    GE = 15
    NullEQ = 16     # <=>
    # arithmetic
    Plus = 20
    Minus = 21
    Mul = 22
    Div = 23
    IntDiv = 24
    Mod = 25
    # bit
    BitAnd = 30
    BitOr = 31
    BitXor = 32
    LeftShift = 33
    RightShift = 34
    BitNeg = 35
    # unary
    UnaryNot = 40
    UnaryMinus = 41
    UnaryPlus = 42

    def sql(self) -> str:
        return _SQL[self]


_SQL = {
    Op.AndAnd: "AND", Op.OrOr: "OR", Op.Not: "NOT", Op.Xor: "XOR",
    Op.EQ: "=", Op.NE: "!=", Op.LT: "<", Op.LE: "<=", Op.GT: ">", Op.GE: ">=",
    Op.NullEQ: "<=>",
    Op.Plus: "+", Op.Minus: "-", Op.Mul: "*", Op.Div: "/", Op.IntDiv: "DIV",
    Op.Mod: "%",
    Op.BitAnd: "&", Op.BitOr: "|", Op.BitXor: "^", Op.LeftShift: "<<",
    Op.RightShift: ">>", Op.BitNeg: "~",
    Op.UnaryNot: "NOT", Op.UnaryMinus: "-", Op.UnaryPlus: "+",
}

COMPARISON_OPS = frozenset((Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.NullEQ))
ARITH_OPS = frozenset((Op.Plus, Op.Minus, Op.Mul, Op.Div, Op.IntDiv, Op.Mod))
LOGIC_OPS = frozenset((Op.AndAnd, Op.OrOr, Op.Xor))
BIT_OPS = frozenset((Op.BitAnd, Op.BitOr, Op.BitXor, Op.LeftShift, Op.RightShift))
