"""Expression AST nodes.

Reference: ast/expressions.go (ValueExpr, ColumnNameExpr, BinaryOperationExpr,
PatternInExpr, PatternLikeExpr, BetweenExpr, CaseExpr, IsNullExpr, RowExpr…)
and ast/functions.go (FuncCallExpr, AggregateFuncExpr).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from tidb_tpu.sqlast.base import ExprNode
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum


@dataclass
class Literal(ExprNode):
    """Constant value (ast.ValueExpr)."""
    value: Datum
    ftype: Any = None


@dataclass
class IntervalExpr(ExprNode):
    """INTERVAL <value> <unit> — only legal as a +/- operand or a
    DATE_ADD/DATE_SUB argument (parser.y TimeUnit productions)."""
    value: ExprNode = None  # type: ignore[assignment]
    unit: str = "day"


@dataclass
class ColumnName(ExprNode):
    """Possibly-qualified column reference; resolver fills offset/ftype.
    Reference: ast.ColumnName + ColumnNameExpr + ResultField binding."""
    name: str
    table: str = ""
    db: str = ""
    # resolution results (plan/resolver.go equivalent):
    offset: int = -1          # offset in the input row schema
    col_id: int = 0           # column id in the table (for pushdown)
    ftype: Any = None

    def qualified(self) -> str:
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class BinaryOp(ExprNode):
    op: Op
    left: ExprNode
    right: ExprNode
    ftype: Any = None


@dataclass
class UnaryOp(ExprNode):
    op: Op
    operand: ExprNode
    ftype: Any = None


@dataclass
class FuncCall(ExprNode):
    """Scalar builtin call (ast.FuncCallExpr)."""
    name: str
    args: list[ExprNode] = field(default_factory=list)
    ftype: Any = None


@dataclass
class AggregateFunc(ExprNode):
    """Aggregate call (ast.AggregateFuncExpr): count/sum/avg/min/max/
    first_row/group_concat, optionally DISTINCT."""
    name: str
    args: list[ExprNode] = field(default_factory=list)
    distinct: bool = False
    ftype: Any = None


@dataclass
class WindowFunc(ExprNode):
    """Window function call (ast.WindowFuncExpr):
    name(args) OVER (PARTITION BY exprs ORDER BY by_items). Ranking
    functions (row_number/rank/dense_rank) carry no args; the frame
    reductions (sum/count/min/max) carry exactly one. The frame is the
    MySQL default: the whole partition without ORDER BY, RANGE UNBOUNDED
    PRECEDING..CURRENT ROW (peer-inclusive) with it."""
    name: str
    args: list[ExprNode] = field(default_factory=list)
    partition_by: list[ExprNode] = field(default_factory=list)
    order_by: list[Any] = field(default_factory=list)   # dml.ByItem
    ftype: Any = None


@dataclass
class Between(ExprNode):
    expr: ExprNode
    low: ExprNode
    high: ExprNode
    not_: bool = False
    ftype: Any = None


@dataclass
class InExpr(ExprNode):
    """expr [NOT] IN (list | subquery) (ast.PatternInExpr). When `sel` is
    set the right side is a subquery (SelectStmt/UnionStmt)."""
    expr: ExprNode
    items: list[ExprNode] = field(default_factory=list)
    not_: bool = False
    sel: Any = None
    ftype: Any = None


@dataclass
class SubqueryExpr(ExprNode):
    """(SELECT ...) used as a scalar value (ast.SubqueryExpr)."""
    query: Any = None  # SelectStmt | UnionStmt
    ftype: Any = None


@dataclass
class ExistsSubquery(ExprNode):
    """EXISTS (SELECT ...) (ast.ExistsSubqueryExpr)."""
    query: Any = None
    not_: bool = False
    ftype: Any = None


@dataclass
class PatternLike(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    not_: bool = False
    escape: str = "\\"
    ftype: Any = None


@dataclass
class PatternRegexp(ExprNode):
    """expr REGEXP/RLIKE pattern (ast.PatternRegexpExpr,
    evaluator/evaluator_like.go:165 patternRegexp)."""
    expr: ExprNode
    pattern: ExprNode
    not_: bool = False
    ftype: Any = None


@dataclass
class IsNull(ExprNode):
    expr: ExprNode
    not_: bool = False
    ftype: Any = None


@dataclass
class WhenClause(ExprNode):
    when: ExprNode
    result: ExprNode
    ftype: Any = None


@dataclass
class CaseExpr(ExprNode):
    """CASE [value] WHEN ... THEN ... [ELSE ...] END."""
    value: ExprNode | None = None
    when_clauses: list[WhenClause] = field(default_factory=list)
    else_clause: ExprNode | None = None
    ftype: Any = None


@dataclass
class ParamMarker(ExprNode):
    """? placeholder in prepared statements."""
    order: int = 0
    value: Datum | None = None
    ftype: Any = None


@dataclass
class RowExpr(ExprNode):
    values: list[ExprNode] = field(default_factory=list)
    ftype: Any = None


@dataclass
class DefaultExpr(ExprNode):
    """DEFAULT / DEFAULT(col) in INSERT/UPDATE values."""
    name: str = ""
    ftype: Any = None


@dataclass
class VariableExpr(ExprNode):
    """@@sysvar / @uservar reference."""
    name: str
    is_global: bool = False
    is_system: bool = True
    ftype: Any = None


@dataclass
class CastExpr(ExprNode):
    """CAST(expr AS type) / CONVERT."""
    expr: ExprNode
    cast_type: Any = None  # FieldType
    ftype: Any = None
