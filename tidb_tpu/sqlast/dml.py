"""DML statement AST nodes.

Reference: ast/dml.go (SelectStmt, Join, TableSource, InsertStmt,
UpdateStmt, DeleteStmt, Limit, ByItem…).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tidb_tpu.sqlast.base import ExprNode, Node, StmtNode


@dataclass
class TableName(Node):
    name: str
    db: str = ""
    # USE/FORCE INDEX and IGNORE INDEX hints (parser.y IndexHint
    # productions, :505-507); empty = no hint
    use_index: list = field(default_factory=list)
    ignore_index: list = field(default_factory=list)


@dataclass
class TableSource(Node):
    """Table reference with optional alias; source may later be a subquery."""
    source: Node
    as_name: str = ""


@dataclass
class Join(Node):
    """Join tree; right None = single table. tp: 'cross'|'inner'|'left'|'right'."""
    left: Node
    right: Node | None = None
    tp: str = "cross"
    on: ExprNode | None = None


@dataclass
class SelectField(Node):
    """One item of the select list; wildcard if wild_table is not None
    (empty string = bare '*')."""
    expr: ExprNode | None = None
    as_name: str = ""
    wild_table: str | None = None


@dataclass
class ByItem(Node):
    expr: ExprNode
    desc: bool = False


@dataclass
class Limit(Node):
    count: int
    offset: int = 0


@dataclass
class SelectStmt(StmtNode):
    fields: list[SelectField] = field(default_factory=list)
    from_: Join | None = None
    where: ExprNode | None = None
    group_by: list[ByItem] = field(default_factory=list)
    having: ExprNode | None = None
    order_by: list[ByItem] = field(default_factory=list)
    limit: Limit | None = None
    distinct: bool = False
    for_update: bool = False
    lock_in_share_mode: bool = False
    straight_join: bool = False   # SELECT STRAIGHT_JOIN: keep FROM order


@dataclass
class Assignment(Node):
    column: Node  # ColumnName
    expr: ExprNode


@dataclass
class InsertStmt(StmtNode):
    table: TableName = None  # type: ignore[assignment]
    columns: list[str] = field(default_factory=list)
    values: list[list[ExprNode]] = field(default_factory=list)
    setlist: list[Assignment] = field(default_factory=list)
    select: SelectStmt | None = None
    is_replace: bool = False
    ignore: bool = False
    on_duplicate: list[Assignment] = field(default_factory=list)


@dataclass
class UpdateStmt(StmtNode):
    table: TableName = None  # type: ignore[assignment]
    assignments: list[Assignment] = field(default_factory=list)
    where: ExprNode | None = None
    order_by: list[ByItem] = field(default_factory=list)
    limit: Limit | None = None


@dataclass
class DeleteStmt(StmtNode):
    table: TableName = None  # type: ignore[assignment]
    where: ExprNode | None = None
    order_by: list[ByItem] = field(default_factory=list)
    limit: Limit | None = None


@dataclass
class UnionStmt(StmtNode):
    """SELECT ... UNION [ALL] SELECT ... (ast/dml.go UnionStmt)."""
    selects: list[SelectStmt] = field(default_factory=list)
    distinct: bool = True  # UNION implies DISTINCT unless ALL
    order_by: list[ByItem] = field(default_factory=list)
    limit: Limit | None = None
