"""SQL-side expression system.

Reference: expression/ (Expression/Column/Constant/ScalarFunction/Schema/
AggregationFunction) + evaluator/ (builtin function library). The scalar
compute core (ops.py) is shared with the coprocessor's xeval so both sides
of the pushdown boundary agree exactly.
"""

from tidb_tpu.expression.expression import (
    Expression, Column, Constant, CorrelatedColumn, ParamExpr, ScalarFunction, Schema,
    new_op, compose_cnf, split_cnf, TRUE_EXPR, FALSE_EXPR, NULL_EXPR,
)
from tidb_tpu.expression.aggregation import (
    AggregationFunction, AggFunctionMode, AggEvaluateContext,
)
from tidb_tpu.expression import ops, builtin

__all__ = [
    "Expression", "Column", "Constant", "CorrelatedColumn", "ParamExpr", "ScalarFunction", "Schema",
    "new_op", "compose_cnf", "split_cnf",
    "TRUE_EXPR", "FALSE_EXPR", "NULL_EXPR",
    "AggregationFunction", "AggFunctionMode", "AggEvaluateContext",
    "ops", "builtin",
]
