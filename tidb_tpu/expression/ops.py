"""Scalar compute core: binary/unary ops, LIKE, IN over Datums.

Reference: evaluator/binop.go, evaluator/unaryop.go, distsql/xeval's
eval_compare_ops.go / eval_arithmetic_ops.go / eval_logic_ops.go /
eval_bit_ops.go. This one module is shared by the SQL-side evaluator
(expression.ScalarFunction) and the CPU coprocessor (copr.xeval) so both
sides of the pushdown boundary agree exactly on semantics — the parity
oracle for the TPU kernels depends on that.

NULL rules (three-valued logic):
  - comparisons with a NULL operand yield NULL (except <=> which treats
    NULL = NULL as true);
  - AND: false dominates NULL; OR: true dominates NULL; XOR/NOT propagate;
  - arithmetic and bit ops propagate NULL.
"""

from __future__ import annotations

from decimal import Decimal, ROUND_HALF_UP
import re

from tidb_tpu import errors
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL, Kind, compare_datum

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1
_U64_MAX = (1 << 64) - 1

TRUE = Datum.i64(1)
FALSE = Datum.i64(0)


def casefold_datum(d: Datum) -> Datum:
    """Casefolded copy for *_ci collation compare (string kinds only)."""
    if d.kind == Kind.STRING:
        return Datum.string(d.val.casefold())
    if d.kind == Kind.BYTES:
        return Datum.bytes_(d.val.decode("utf-8", "replace").casefold()
                            .encode("utf-8"))
    return d


def bool_datum(b: bool) -> Datum:
    return TRUE if b else FALSE


def datum_truth(d: Datum) -> bool | None:
    """SQL truthiness: NULL→None, else number != 0."""
    if d.is_null():
        return None
    n = d.as_number()
    return n != 0


def _check_int_range(v: int, unsigned: bool = False) -> int:
    if unsigned:
        if 0 <= v <= _U64_MAX:
            return v
    elif _I64_MIN <= v <= _I64_MAX:
        return v
    raise errors.OverflowError_(f"BIGINT value is out of range: {v}")


def compute_arith(op: Op, a: Datum, b: Datum) -> Datum:
    """Reference: evaluator ComputeArithmetic (used by local_aggregate.go:233)."""
    if a.is_null() or b.is_null():
        return NULL
    x, y = a.as_number(), b.as_number()
    if op == Op.Plus:
        return _num_result(_coerced(x, y, lambda p, q: p + q), a, b)
    if op == Op.Minus:
        return _num_result(_coerced(x, y, lambda p, q: p - q), a, b)
    if op == Op.Mul:
        return _num_result(_coerced(x, y, lambda p, q: p * q), a, b)
    if op == Op.Div:
        # MySQL `/`: exact operands → decimal, any float → float; x/0 → NULL
        if isinstance(x, float) or isinstance(y, float):
            if float(y) == 0.0:
                return NULL
            return Datum.f64(float(x) / float(y))
        if y == 0:
            return NULL
        return Datum.dec(Decimal(x) / Decimal(y))
    if op == Op.IntDiv:
        if isinstance(x, float) or isinstance(y, float) or \
                isinstance(x, Decimal) or isinstance(y, Decimal):
            if float(y) == 0.0:
                return NULL
            q = Decimal(str(x)) / Decimal(str(y))
            return Datum.i64(_check_int_range(int(q.to_integral_value(rounding="ROUND_DOWN"))))
        if y == 0:
            return NULL
        # Go integer division truncates toward zero
        q = abs(x) // abs(y)
        if (x < 0) != (y < 0):
            q = -q
        return Datum.i64(_check_int_range(q))
    if op == Op.Mod:
        if float(y) == 0.0:
            return NULL
        if isinstance(x, float) or isinstance(y, float):
            import math
            return Datum.f64(math.fmod(float(x), float(y)))
        if isinstance(x, Decimal) or isinstance(y, Decimal):
            dx, dy = Decimal(str(x)), Decimal(str(y))
            return Datum.dec(dx - dy * (dx / dy).to_integral_value(rounding="ROUND_DOWN"))
        # MySQL % keeps the sign of the dividend (Go semantics)
        r = abs(x) % abs(y)
        return Datum.i64(-r if x < 0 else r)
    raise errors.TypeError_(f"unknown arithmetic op {op!r}")


def _coerced(x, y, fn):
    if isinstance(x, float) or isinstance(y, float):
        return fn(float(x), float(y))
    if isinstance(x, Decimal) or isinstance(y, Decimal):
        return fn(Decimal(str(x)) if not isinstance(x, Decimal) else x,
                  Decimal(str(y)) if not isinstance(y, Decimal) else y)
    return fn(x, y)


def _num_result(v, a: Datum, b: Datum) -> Datum:
    if isinstance(v, float):
        return Datum.f64(v)
    if isinstance(v, Decimal):
        return Datum.dec(v)
    unsigned = a.kind == Kind.UINT64 and b.kind == Kind.UINT64
    return Datum.u64(_check_int_range(v, True)) if unsigned \
        else Datum.i64(_check_int_range(v))


def compute_compare(op: Op, a: Datum, b: Datum) -> Datum:
    if op == Op.NullEQ:
        if a.is_null() and b.is_null():
            return TRUE
        if a.is_null() or b.is_null():
            return FALSE
        return bool_datum(compare_datum(a, b) == 0)
    if a.is_null() or b.is_null():
        return NULL
    c = compare_datum(a, b)
    if op == Op.EQ:
        return bool_datum(c == 0)
    if op == Op.NE:
        return bool_datum(c != 0)
    if op == Op.LT:
        return bool_datum(c < 0)
    if op == Op.LE:
        return bool_datum(c <= 0)
    if op == Op.GT:
        return bool_datum(c > 0)
    if op == Op.GE:
        return bool_datum(c >= 0)
    raise errors.TypeError_(f"unknown comparison op {op!r}")


def compute_logic(op: Op, a: Datum, b: Datum) -> Datum:
    ta, tb = datum_truth(a), datum_truth(b)
    if op == Op.AndAnd:
        if ta is False or tb is False:
            return FALSE
        if ta is None or tb is None:
            return NULL
        return TRUE
    if op == Op.OrOr:
        if ta is True or tb is True:
            return TRUE
        if ta is None or tb is None:
            return NULL
        return FALSE
    if op == Op.Xor:
        if ta is None or tb is None:
            return NULL
        return bool_datum(ta != tb)
    raise errors.TypeError_(f"unknown logic op {op!r}")


def _to_uint64(d: Datum) -> int:
    n = d.as_number()
    if isinstance(n, (float, Decimal)):
        n = int(Decimal(str(n)).to_integral_value(rounding=ROUND_HALF_UP))
    return n & _U64_MAX


def compute_bit(op: Op, a: Datum, b: Datum) -> Datum:
    """MySQL bit ops operate on uint64."""
    if a.is_null() or b.is_null():
        return NULL
    x, y = _to_uint64(a), _to_uint64(b)
    if op == Op.BitAnd:
        return Datum.u64(x & y)
    if op == Op.BitOr:
        return Datum.u64(x | y)
    if op == Op.BitXor:
        return Datum.u64(x ^ y)
    if op == Op.LeftShift:
        return Datum.u64((x << y) & _U64_MAX if y < 64 else 0)
    if op == Op.RightShift:
        return Datum.u64(x >> y if y < 64 else 0)
    raise errors.TypeError_(f"unknown bit op {op!r}")


def compute_binary(op: Op, a: Datum, b: Datum) -> Datum:
    if op in (Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.NullEQ):
        return compute_compare(op, a, b)
    if op in (Op.Plus, Op.Minus, Op.Mul, Op.Div, Op.IntDiv, Op.Mod):
        return compute_arith(op, a, b)
    if op in (Op.AndAnd, Op.OrOr, Op.Xor):
        return compute_logic(op, a, b)
    return compute_bit(op, a, b)


def compute_unary(op: Op, a: Datum) -> Datum:
    if a.is_null():
        return NULL
    if op in (Op.UnaryNot, Op.Not):
        t = datum_truth(a)
        return NULL if t is None else bool_datum(not t)
    if op == Op.UnaryMinus:
        n = a.as_number()
        if isinstance(n, float):
            return Datum.f64(-n)
        if isinstance(n, Decimal):
            return Datum.dec(-n)
        return Datum.i64(_check_int_range(-n))
    if op == Op.UnaryPlus:
        return a
    if op == Op.BitNeg:
        return Datum.u64(~_to_uint64(a) & _U64_MAX)
    raise errors.TypeError_(f"unknown unary op {op!r}")


# ---- LIKE ----

_like_cache: dict[tuple[str, str], re.Pattern] = {}


def _like_regex(pattern: str, escape: str) -> re.Pattern:
    key = (pattern, escape)
    pat = _like_cache.get(key)
    if pat is None:
        out, i = [], 0
        while i < len(pattern):
            ch = pattern[i]
            if escape and ch == escape and i + 1 < len(pattern):
                out.append(re.escape(pattern[i + 1]))
                i += 2
                continue
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(re.escape(ch))
            i += 1
        # MySQL LIKE on the default collation is case-insensitive
        pat = re.compile("^" + "".join(out) + "$", re.IGNORECASE | re.DOTALL)
        _like_cache[key] = pat
    return pat


def compute_like(target: Datum, pattern: Datum, escape: str = "\\",
                 negated: bool = False) -> Datum:
    if target.is_null() or pattern.is_null():
        return NULL
    s = target.get_string() if target.kind in (Kind.STRING, Kind.BYTES) \
        else _datum_to_str(target)
    p = pattern.get_string()
    matched = _like_regex(p, escape).match(s) is not None
    return bool_datum(matched != negated)


def _datum_to_str(d: Datum) -> str:
    if d.kind in (Kind.STRING, Kind.BYTES):
        return d.get_string()
    if d.kind == Kind.FLOAT64:
        v = d.val
        return str(int(v)) if v == int(v) else repr(v)
    if d.kind in (Kind.ENUM, Kind.SET, Kind.BIT, Kind.HEX):
        return d.get_string()   # enum/set names; bit/hex binary string
    return str(d.val)


def compute_in(v: Datum, items: list[Datum], negated: bool = False) -> Datum:
    """IN list semantics: match → true; no match and any NULL → NULL."""
    if v.is_null():
        return NULL
    has_null = False
    for it in items:
        if it.is_null():
            has_null = True
            continue
        if compare_datum(v, it) == 0:
            return bool_datum(not negated)
    if has_null:
        return NULL
    return bool_datum(negated)
