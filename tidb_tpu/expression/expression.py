"""Evaluable expression objects used by plans and executors.

Reference: expression/expression.go:30 (Expression interface with Eval(row)),
expression/column.go (Column, offset-resolved), expression/constant.go,
expression/scalar_function.go:62 (dispatch into evaluator.Funcs),
expression/schema.go.
"""

from __future__ import annotations

import abc

from tidb_tpu import errors
from tidb_tpu import mysqldef as my
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL
from tidb_tpu.types.field_type import FieldType, new_field_type

from tidb_tpu.expression import ops as xops


class Expression(abc.ABC):
    ret_type: FieldType

    @abc.abstractmethod
    def eval(self, row: list[Datum]) -> Datum: ...

    @abc.abstractmethod
    def clone(self) -> "Expression": ...

    def equal(self, other: "Expression") -> bool:
        return self is other

    # structural helpers (plan/expression traversal)
    def columns(self) -> list["Column"]:
        out: list[Column] = []
        _collect_columns(self, out)
        return out


def _collect_columns(e: Expression, out: list["Column"]) -> None:
    if isinstance(e, Column):
        out.append(e)
    elif isinstance(e, ScalarFunction):
        for a in e.args:
            _collect_columns(a, out)
    elif isinstance(e, Cast):
        _collect_columns(e.arg, out)


class Column(Expression):
    """A resolved column reference.

    `index` is the offset into the executor row (set by ResolveIndices);
    `col_id` is the table column id (for pushdown / tablecodec);
    `from_id`/`position` identify the producing plan node + output slot.
    """

    def __init__(self, col_name: str = "", tbl_name: str = "", db_name: str = "",
                 ret_type: FieldType | None = None, index: int = -1,
                 col_id: int = 0, from_id: str = "", position: int = 0,
                 is_agg: bool = False):
        self.col_name = col_name
        self.tbl_name = tbl_name
        self.db_name = db_name
        self.ret_type = ret_type or new_field_type(my.TypeNull)
        self.index = index
        self.col_id = col_id
        self.from_id = from_id
        self.position = position
        self.is_agg = is_agg  # aggregate output column (not a real table col)

    def eval(self, row: list[Datum]) -> Datum:
        if self.index < 0:
            raise errors.PlanError(f"column {self} not resolved to an offset")
        return row[self.index]

    def clone(self) -> "Column":
        return Column(self.col_name, self.tbl_name, self.db_name,
                      self.ret_type, self.index, self.col_id,
                      self.from_id, self.position, self.is_agg)

    def equal(self, other: Expression) -> bool:
        return (isinstance(other, Column) and other.from_id == self.from_id
                and other.position == self.position)

    def __repr__(self):
        parts = [p for p in (self.db_name, self.tbl_name, self.col_name) if p]
        return ".".join(parts) or f"col#{self.position}"


class CorrelatedColumn(Expression):
    """A reference to a column of an enclosing query, evaluated against the
    current outer row stored in a shared cell (set per outer row by
    ApplyExec). Deliberately NOT a Column subclass: the planner's rules and
    the pushdown converter treat it as an opaque (constant-per-outer-row)
    leaf, so correlated conditions never cross the coprocessor boundary.
    Reference: expression/schema.go + plan/expression_rewriter.go
    (correlated column handling)."""

    def __init__(self, col: Column, cell: list):
        self.col = col          # outer-scope identity (from_id/position)
        self.cell = cell        # [outer_row] shared with the owning Apply
        self.ret_type = col.ret_type
        self.idx = -1           # outer-row slot, bound at Apply resolve time

    def eval(self, row=None) -> Datum:
        outer = self.cell[0]
        if outer is None or self.idx < 0:
            raise errors.PlanError(f"correlated column {self!r} unbound")
        return outer[self.idx]

    def clone(self) -> "CorrelatedColumn":
        c = CorrelatedColumn(self.col, self.cell)
        c.idx = self.idx
        return c

    def equal(self, other: Expression) -> bool:
        return (isinstance(other, CorrelatedColumn)
                and other.col.equal(self.col) and other.cell is self.cell)

    def __repr__(self):
        return f"corr({self.col!r})"


class ParamExpr(Expression):
    """A prepared-statement parameter slot. Evaluates the session's
    CURRENT parameter binding, so a cached plan is reusable across
    EXECUTEs with different values (reference executor/prepared.go param
    markers). Never crosses the coprocessor boundary (expr_to_pb returns
    None for it) — parameterized filters stay SQL-side."""

    def __init__(self, ctx, order: int, ret_type: FieldType | None = None):
        self.ctx = ctx
        self.order = order
        self.ret_type = ret_type or new_field_type(my.TypeNull)

    def eval(self, row=None) -> Datum:
        params = getattr(self.ctx, "params", None) or []
        if self.order >= len(params):
            raise errors.ExecError(
                f"missing prepared statement parameter {self.order}")
        return params[self.order]

    def clone(self) -> "ParamExpr":
        return ParamExpr(self.ctx, self.order, self.ret_type)

    def __repr__(self):
        return f"?{self.order}"


class Constant(Expression):
    def __init__(self, value: Datum, ret_type: FieldType | None = None):
        self.value = value
        self.ret_type = ret_type or _infer_const_type(value)

    def eval(self, row=None) -> Datum:
        return self.value

    def clone(self) -> "Constant":
        return Constant(self.value, self.ret_type)

    def equal(self, other: Expression) -> bool:
        from tidb_tpu.types.datum import compare_datum
        if not isinstance(other, Constant):
            return False
        if self.value.is_null() or other.value.is_null():
            return self.value.is_null() and other.value.is_null()
        try:
            return compare_datum(self.value, other.value) == 0
        except errors.TiDBError:
            return False

    def __repr__(self):
        return repr(self.value.val) if not self.value.is_null() else "NULL"


def _infer_const_type(d: Datum) -> FieldType:
    from tidb_tpu.types.datum import Kind
    m = {Kind.NULL: my.TypeNull, Kind.INT64: my.TypeLonglong,
         Kind.UINT64: my.TypeLonglong, Kind.FLOAT64: my.TypeDouble,
         Kind.STRING: my.TypeVarString, Kind.BYTES: my.TypeBlob,
         Kind.DECIMAL: my.TypeNewDecimal, Kind.DURATION: my.TypeDuration,
         Kind.TIME: my.TypeDatetime}
    ft = new_field_type(m.get(d.kind, my.TypeNull))
    if d.kind == Kind.UINT64:
        ft.flag |= my.UnsignedFlag
    return ft


class ScalarFunction(Expression):
    """Named function over child expressions.

    Operator expressions are ScalarFunctions with reserved names carrying an
    `op` (so expr→copr conversion is uniform); builtins dispatch by name into
    expression.builtin.FUNCS, mirroring evaluator.Funcs (evaluator/builtin.go:43).
    """

    def __init__(self, func_name: str, args: list[Expression],
                 ret_type: FieldType | None = None, op: Op | None = None):
        self.func_name = func_name
        self.args = args
        self.op = op
        self.ret_type = ret_type or new_field_type(my.TypeNull)

    _CMP_OPS = (Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.NullEQ)

    def eval(self, row: list[Datum]) -> Datum:
        from tidb_tpu.expression import builtin
        op = self.op
        if op is not None:
            if len(self.args) == 1:
                return xops.compute_unary(op, self.args[0].eval(row))
            a = self.args[0].eval(row)
            # short-circuit AND/OR without evaluating the right side on
            # a determined left (matches evaluator lazy logic eval)
            if op == Op.AndAnd and xops.datum_truth(a) is False:
                return xops.FALSE
            if op == Op.OrOr and xops.datum_truth(a) is True:
                return xops.TRUE
            b = self.args[1].eval(row)
            if op in self._CMP_OPS and self._ci_compare():
                a, b = xops.casefold_datum(a), xops.casefold_datum(b)
            return xops.compute_binary(op, a, b)
        name = self.func_name
        if self._ci_compare() and name in ("in", "not_in", "like",
                                           "not_like"):
            # IN and LIKE must agree with `=` on *_ci columns
            if name in ("in", "not_in"):
                vals = [xops.casefold_datum(a.eval(row)) for a in self.args]
                return xops.compute_in(vals[0], vals[1:],
                                       negated=name == "not_in")
            esc = self.args[2].eval(row)
            return xops.compute_like(
                xops.casefold_datum(self.args[0].eval(row)),
                xops.casefold_datum(self.args[1].eval(row)),
                esc.get_string() if not esc.is_null() else "\\",
                negated=name == "not_like")
        return builtin.call(name, self.args, row)

    def _ci_compare(self) -> bool:
        """True when any operand is a column with a case-insensitive
        collation (*_ci): MySQL compares such strings casefolded. Decided
        once per expression node (collation is compile-time metadata)."""
        ci = getattr(self, "_ci_cached", None)
        if ci is None:
            ci = self._ci_cached = any(
                isinstance(arg, Column) and arg.ret_type.is_ci_collation()
                for arg in self.args)
        return ci

    def clone(self) -> "ScalarFunction":
        return ScalarFunction(self.func_name, [a.clone() for a in self.args],
                              self.ret_type, self.op)

    def equal(self, other: Expression) -> bool:
        return (isinstance(other, ScalarFunction)
                and other.func_name == self.func_name and other.op == self.op
                and len(other.args) == len(self.args)
                and all(a.equal(b) for a, b in zip(self.args, other.args)))

    def __repr__(self):
        if self.op is not None and len(self.args) == 2:
            return f"({self.args[0]!r} {self.op.sql()} {self.args[1]!r})"
        if self.op is not None and len(self.args) == 1:
            return f"({self.op.sql()} {self.args[0]!r})"
        return f"{self.func_name}({', '.join(map(repr, self.args))})"


def new_op(op: Op, *args: Expression, ret_type: FieldType | None = None) -> ScalarFunction:
    rt = ret_type
    if rt is None:
        if op in (Op.EQ, Op.NE, Op.LT, Op.LE, Op.GT, Op.GE, Op.NullEQ,
                  Op.AndAnd, Op.OrOr, Op.Xor, Op.Not, Op.UnaryNot):
            rt = new_field_type(my.TypeLonglong)
        elif op in (Op.Plus, Op.Minus, Op.Mul, Op.Div, Op.IntDiv, Op.Mod):
            from tidb_tpu.types.field_type import merge_numeric
            if len(args) == 2:
                rt = merge_numeric(args[0].ret_type, args[1].ret_type)
                if op == Op.Div and rt.tp not in (my.TypeDouble, my.TypeFloat):
                    rt = new_field_type(my.TypeNewDecimal)
            else:
                rt = args[0].ret_type.clone()
        elif op in (Op.UnaryMinus, Op.UnaryPlus):
            rt = args[0].ret_type.clone()
        else:
            rt = new_field_type(my.TypeLonglong)
            rt.flag |= my.UnsignedFlag
    return ScalarFunction(f"op_{op.name.lower()}", list(args), rt, op=op)


class Cast(Expression):
    """CAST(expr AS type); evaluates via types.convert.convert_datum."""

    def __init__(self, arg: Expression, to_type: FieldType):
        self.arg = arg
        self.ret_type = to_type

    def eval(self, row: list[Datum]) -> Datum:
        from tidb_tpu.types.convert import convert_datum
        return convert_datum(self.arg.eval(row), self.ret_type)

    def clone(self) -> "Cast":
        return Cast(self.arg.clone(), self.ret_type)

    def equal(self, other: Expression) -> bool:
        return (isinstance(other, Cast) and other.ret_type == self.ret_type
                and other.arg.equal(self.arg))

    def columns(self) -> list[Column]:
        return self.arg.columns()

    def __repr__(self):
        return f"cast({self.arg!r} as {self.ret_type.compact_str()})"


TRUE_EXPR = Constant(Datum.i64(1))
FALSE_EXPR = Constant(Datum.i64(0))
NULL_EXPR = Constant(NULL)


def compose_cnf(conditions: list[Expression]) -> Expression | None:
    """AND a condition list into one expression (pushdown wire format)."""
    if not conditions:
        return None
    out = conditions[0]
    for c in conditions[1:]:
        out = new_op(Op.AndAnd, out, c)
    return out


def split_cnf(expr: Expression | None) -> list[Expression]:
    """Flatten nested ANDs (plan/util SplitCNFItems equivalent)."""
    if expr is None:
        return []
    if isinstance(expr, ScalarFunction) and expr.op == Op.AndAnd:
        return split_cnf(expr.args[0]) + split_cnf(expr.args[1])
    return [expr]


class Schema:
    """Output column list of a plan node. Reference: expression/schema.go."""

    def __init__(self, columns: list[Column] | None = None):
        self.columns: list[Column] = columns or []

    def __len__(self):
        return len(self.columns)

    def __iter__(self):
        return iter(self.columns)

    def __getitem__(self, i) -> Column:
        return self.columns[i]

    def append(self, col: Column) -> None:
        self.columns.append(col)

    def clone(self) -> "Schema":
        return Schema([c.clone() for c in self.columns])

    def column_index(self, col: Column) -> int:
        for i, c in enumerate(self.columns):
            if c.equal(col):
                return i
        return -1

    def find_column(self, db: str, tbl: str, name: str) -> Column | None:
        """Name-based lookup with ambiguity detection (resolver rules)."""
        name = name.lower()
        found: Column | None = None
        for c in self.columns:
            if c.col_name.lower() != name:
                continue
            if tbl and c.tbl_name.lower() != tbl.lower():
                continue
            if db and c.db_name.lower() != db.lower():
                continue
            if found is not None:
                raise errors.PlanError(f"column '{name}' is ambiguous")
            found = c
        return found

    def retrieve_positions(self) -> None:
        """Renumber to the current layout. Invariant: a schema column's
        `index` (offset for evaluation against this node's output rows)
        always equals its `position`."""
        for i, c in enumerate(self.columns):
            c.position = i
            c.index = i

    def set_from(self, from_id: str) -> None:
        for c in self.columns:
            c.from_id = from_id
