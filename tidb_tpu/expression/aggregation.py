"""Aggregate functions with partial/final modes.

Reference: expression/aggregation.go:33 (AggregationFunction interface),
AggFunctionMode (:111), per-func implementations (sum/count/avg/first/max/
min/concat/distinct) and the partial-row protocol the coprocessor speaks:
a pushed-down aggregate emits `[cnt?, val?]` pairs per group
(plan/physical_plans.go:171-178 needCount/needValue;
store/localstore/local_region.go:357-391), and the upper FinalMode
aggregate merges them (executor/executor.go:989-1080).
"""

from __future__ import annotations

import enum
from decimal import Decimal

from tidb_tpu import errors
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL, Kind, compare_datum
from tidb_tpu.types.field_type import FieldType, agg_field_type

from tidb_tpu.expression import ops as xops
from tidb_tpu.expression.expression import Expression


class AggFunctionMode(enum.IntEnum):
    COMPLETE = 0   # raw rows in, final value out
    FINAL = 1      # partial rows in ([cnt?, val?] columns), final value out


def _sum_exact(acc: Datum, v: Datum) -> Datum:
    """Accumulate preserving exactness: ints sum as Decimal so SUM never
    silently wraps or loses precision (local_aggregate.go:149-161)."""
    if v.is_null():
        return acc
    n = v.as_number()
    if not isinstance(n, float):
        n = Decimal(n) if not isinstance(n, Decimal) else n
    if acc.is_null():
        return Datum.f64(n) if isinstance(n, float) else Datum.dec(n)
    cur = acc.as_number()
    if isinstance(cur, float) or isinstance(n, float):
        return Datum.f64(float(cur) + float(n))
    if not isinstance(cur, Decimal):
        cur = Decimal(cur)
    return Datum.dec(cur + n)


class AggEvaluateContext:
    __slots__ = ("count", "value", "buffer", "distinct_set", "evaluated")

    def __init__(self):
        self.count = 0
        self.value = NULL
        self.buffer: list | None = None     # group_concat parts
        self.distinct_set: set | None = None
        self.evaluated = False


class AggregationFunction:
    """One aggregate call site. Stateless w.r.t. groups — per-group state
    lives in AggEvaluateContext objects owned by the executor."""

    def __init__(self, name: str, args: list[Expression],
                 distinct: bool = False,
                 mode: AggFunctionMode = AggFunctionMode.COMPLETE,
                 separator: str = ","):
        name = name.lower()
        if name not in AGG_IMPLS:
            raise errors.PlanError(f"unknown aggregate function {name!r}")
        self.name = name
        self.args = args
        self.distinct = distinct
        self.mode = mode
        self.separator = separator

    # --- pushdown metadata (plan/physical_plans.go:171-178) ---
    def need_count(self) -> bool:
        return self.name in ("count", "avg")

    def need_value(self) -> bool:
        return self.name in ("sum", "avg", "first_row", "max", "min",
                             "group_concat")

    def ret_type(self) -> FieldType:
        arg_ft = self.args[0].ret_type if self.args else FieldType()
        return agg_field_type(self.name, arg_ft)

    def clone(self) -> "AggregationFunction":
        return AggregationFunction(self.name, [a.clone() for a in self.args],
                                   self.distinct, self.mode, self.separator)

    def create_context(self) -> AggEvaluateContext:
        ctx = AggEvaluateContext()
        if self.distinct:
            ctx.distinct_set = set()
        if self.name == "group_concat":
            ctx.buffer = []
        return ctx

    # --- update ---
    def update(self, ctx: AggEvaluateContext, row: list[Datum]) -> None:
        if self.mode == AggFunctionMode.FINAL:
            self._update_final(ctx, row)
        else:
            vals = [a.eval(row) for a in self.args]
            if self.distinct and self.name in ("count", "sum", "avg"):
                # COUNT(DISTINCT a) over a *_ci column dedups casefolded
                # (only counting aggs: min/max must keep the original case)
                from tidb_tpu.expression.ops import casefold_datum
                vals = [casefold_datum(v)
                        if getattr(a, "ret_type", None) is not None
                        and a.ret_type.is_ci_collation() else v
                        for a, v in zip(self.args, vals)]
            AGG_IMPLS[self.name](self, ctx, vals)

    def _update_final(self, ctx: AggEvaluateContext, row: list[Datum]) -> None:
        """Merge one partial row. Arg expressions are Columns pointing at the
        partial layout: count first if need_count, then value if need_value."""
        i = 0
        cnt = 0
        if self.need_count():
            d = self.args[i].eval(row)
            cnt = 0 if d.is_null() else int(d.as_number())
            i += 1
        if self.name == "count":
            ctx.count += cnt
            return
        val = self.args[i].eval(row)
        if self.name in ("sum", "avg"):
            ctx.value = _sum_exact(ctx.value, val)
            ctx.count += cnt if self.need_count() else 0
            return
        if self.name in ("max", "min"):
            _minmax_update(ctx, val, self.name == "max")
            return
        if self.name == "first_row":
            if not ctx.evaluated:
                ctx.value = val
                ctx.evaluated = True
            return
        if self.name == "group_concat":
            if not val.is_null():
                ctx.buffer.append(val.get_string())
            return
        raise errors.ExecError(f"final merge unsupported for {self.name}")

    # --- result ---
    def get_result(self, ctx: AggEvaluateContext) -> Datum:
        n = self.name
        if n == "count":
            return Datum.i64(ctx.count)
        if n == "sum":
            return ctx.value
        if n == "avg":
            if ctx.count == 0:
                return NULL
            s = ctx.value.as_number()
            if isinstance(s, float):
                return Datum.f64(s / ctx.count)
            return Datum.dec((Decimal(s) if not isinstance(s, Decimal) else s)
                             / Decimal(ctx.count))
        if n in ("max", "min", "first_row"):
            return ctx.value
        if n == "group_concat":
            if not ctx.buffer:
                return NULL
            return Datum.string(self.separator.join(ctx.buffer))
        raise errors.ExecError(f"unknown aggregate {n}")

    def get_partial_result(self, ctx: AggEvaluateContext) -> list[Datum]:
        """Emit the [cnt?, val?] partial row slice this func contributes."""
        out = []
        if self.need_count():
            out.append(Datum.i64(ctx.count))
        if self.need_value():
            if self.name == "group_concat":
                out.append(self.get_result(ctx))
            else:
                out.append(ctx.value)
        return out

    def __repr__(self):
        d = "distinct " if self.distinct else ""
        return f"{self.name}({d}{', '.join(map(repr, self.args))})"


def _seen(ctx: AggEvaluateContext, vals: list[Datum]) -> bool:
    """Distinct tracking; returns True if this tuple was already counted."""
    if ctx.distinct_set is None:
        return False
    key = tuple(_hashable(v) for v in vals)
    if key in ctx.distinct_set:
        return True
    ctx.distinct_set.add(key)
    return False


def _hashable(d: Datum):
    if d.is_null():
        return None
    n = d.kind
    if n in (Kind.STRING, Kind.BYTES):
        return d.get_bytes()
    if n in (Kind.INT64, Kind.UINT64, Kind.FLOAT64, Kind.DECIMAL):
        v = d.as_number()
        # cross-kind numeric identity: hash(1)==hash(1.0)==hash(Decimal(1))
        return v
    return (int(n), str(d.val))


def _minmax_update(ctx: AggEvaluateContext, v: Datum, is_max: bool) -> None:
    if v.is_null():
        return
    if ctx.value.is_null():
        ctx.value = v
        return
    c = compare_datum(v, ctx.value)
    if (c > 0) == is_max and c != 0:
        ctx.value = v


# ---- complete-mode updaters ----

def _agg_count(fn, ctx, vals):
    if any(v.is_null() for v in vals):
        return
    if _seen(ctx, vals):
        return
    ctx.count += 1


def _agg_sum(fn, ctx, vals):
    v = vals[0]
    if v.is_null() or _seen(ctx, vals):
        return
    ctx.value = _sum_exact(ctx.value, v)
    ctx.count += 1


def _agg_avg(fn, ctx, vals):
    _agg_sum(fn, ctx, vals)


def _agg_max(fn, ctx, vals):
    _minmax_update(ctx, vals[0], True)


def _agg_min(fn, ctx, vals):
    _minmax_update(ctx, vals[0], False)


def _agg_first_row(fn, ctx, vals):
    if not ctx.evaluated:
        ctx.value = vals[0] if vals else NULL
        ctx.evaluated = True


def _agg_group_concat(fn, ctx, vals):
    if any(v.is_null() for v in vals):
        return
    if _seen(ctx, vals):
        return
    ctx.buffer.append("".join(xops._datum_to_str(v) for v in vals))


AGG_IMPLS = {
    "count": _agg_count,
    "sum": _agg_sum,
    "avg": _agg_avg,
    "max": _agg_max,
    "min": _agg_min,
    "first_row": _agg_first_row,
    "group_concat": _agg_group_concat,
}
