"""Builtin scalar function library.

Reference: evaluator/builtin.go:43 (Funcs map) and the per-family files
builtin_math.go / builtin_string.go / builtin_time.go / builtin_control.go /
builtin_info.go. Functions take already-built arg Expressions plus the row,
so control functions (IF/IFNULL/CASE/COALESCE) can evaluate lazily.
"""

from __future__ import annotations

import math
import time as _time
from decimal import Decimal, ROUND_HALF_UP

from tidb_tpu import errors
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL, Kind, compare_datum

from tidb_tpu.expression import ops as xops

# name -> (min_args, max_args, impl(args, row)); max_args=-1 means variadic
FUNCS: dict[str, tuple[int, int, object]] = {}


def register(name: str, lo: int, hi: int):
    def deco(fn):
        FUNCS[name] = (lo, hi, fn)
        return fn
    return deco


def call(name: str, args: list, row) -> Datum:
    ent = FUNCS.get(name.lower())
    if ent is None:
        raise errors.ExecError(f"unknown function {name!r}")
    lo, hi, fn = ent
    if len(args) < lo or (hi != -1 and len(args) > hi):
        raise errors.ExecError(
            f"wrong argument count to {name}(): got {len(args)}")
    return fn(args, row)


def exists(name: str) -> bool:
    return name.lower() in FUNCS


def _vals(args, row):
    return [a.eval(row) for a in args]


def _str_or_none(d: Datum):
    return None if d.is_null() else d.get_string()


# ---- control (evaluator/builtin_control.go) ----

@register("if", 3, 3)
def _if(args, row):
    t = xops.datum_truth(args[0].eval(row))
    return args[1].eval(row) if t else args[2].eval(row)


@register("ifnull", 2, 2)
def _ifnull(args, row):
    v = args[0].eval(row)
    return args[1].eval(row) if v.is_null() else v


@register("nullif", 2, 2)
def _nullif(args, row):
    a = args[0].eval(row)
    if a.is_null():
        return NULL
    b = args[1].eval(row)
    if not b.is_null() and compare_datum(a, b) == 0:
        return NULL
    return a


@register("coalesce", 1, -1)
def _coalesce(args, row):
    for a in args:
        v = a.eval(row)
        if not v.is_null():
            return v
    return NULL


@register("isnull", 1, 1)
def _isnull(args, row):
    return xops.bool_datum(args[0].eval(row).is_null())


@register("case", 3, -1)
def _case(args, row):
    """Flattened CASE: [value?] (when, then)... else.

    The ELSE arm is MANDATORY in this layout — the planner's lowering always
    appends one (NULL when the SQL had no ELSE). That makes arity
    unambiguous: searched CASE is 2k+1 args (odd), compare-value CASE is
    value + 2k pairs + else = 2k+2 (even)."""
    i = 0
    n = len(args)
    has_value = n % 2 == 0
    value = args[0].eval(row) if has_value else None
    if has_value:
        i = 1
    while i + 1 < n:
        cond = args[i].eval(row)
        if value is not None:
            matched = (not cond.is_null()) and (not value.is_null()) \
                and compare_datum(value, cond) == 0
        else:
            matched = xops.datum_truth(cond) is True
        if matched:
            return args[i + 1].eval(row)
        i += 2
    if i < n:  # else arm
        return args[i].eval(row)
    return NULL


# ---- comparison-adjacent ----

@register("greatest", 2, -1)
def _greatest(args, row):
    best = None
    for d in _vals(args, row):
        if d.is_null():
            return NULL
        if best is None or compare_datum(d, best) > 0:
            best = d
    return best


@register("least", 2, -1)
def _least(args, row):
    best = None
    for d in _vals(args, row):
        if d.is_null():
            return NULL
        if best is None or compare_datum(d, best) < 0:
            best = d
    return best


# ---- math (evaluator/builtin_math.go) ----

def _num1(args, row):
    d = args[0].eval(row)
    return None if d.is_null() else d.as_number()


@register("abs", 1, 1)
def _abs(args, row):
    n = _num1(args, row)
    if n is None:
        return NULL
    r = abs(n)
    if isinstance(r, float):
        return Datum.f64(r)
    if isinstance(r, Decimal):
        return Datum.dec(r)
    return Datum.i64(r)


@register("ceil", 1, 1)
@register("ceiling", 1, 1)
def _ceil(args, row):
    n = _num1(args, row)
    return NULL if n is None else Datum.i64(math.ceil(n))


@register("floor", 1, 1)
def _floor(args, row):
    n = _num1(args, row)
    return NULL if n is None else Datum.i64(math.floor(n))


@register("round", 1, 2)
def _round(args, row):
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    places = 0
    if len(args) > 1:
        p = args[1].eval(row)
        if p.is_null():
            return NULL
        places = int(p.as_number())
    n = d.as_number()
    if isinstance(n, float):
        # MySQL rounds half away from zero, not banker's
        q = Decimal(str(n)).quantize(Decimal(1).scaleb(-places),
                                     rounding=ROUND_HALF_UP)
        return Datum.f64(float(q))
    q = Decimal(n).quantize(Decimal(1).scaleb(-places), rounding=ROUND_HALF_UP)
    if d.kind in (Kind.INT64, Kind.UINT64) and places >= 0:
        return Datum.i64(int(q))
    return Datum.dec(q)


@register("truncate", 2, 2)
def _truncate(args, row):
    d, p = args[0].eval(row), args[1].eval(row)
    if d.is_null() or p.is_null():
        return NULL
    places = int(p.as_number())
    n = d.as_number()
    q = Decimal(str(n)).quantize(Decimal(1).scaleb(-max(places, -30)),
                                 rounding="ROUND_DOWN") if places >= 0 else \
        (Decimal(str(n)) // Decimal(10) ** -places) * Decimal(10) ** -places
    if isinstance(n, float):
        return Datum.f64(float(q))
    if isinstance(n, Decimal):
        return Datum.dec(q)
    return Datum.i64(int(q))


@register("pow", 2, 2)
@register("power", 2, 2)
def _pow(args, row):
    a, b = _vals(args, row)
    if a.is_null() or b.is_null():
        return NULL
    return Datum.f64(float(a.as_number()) ** float(b.as_number()))


@register("sqrt", 1, 1)
def _sqrt(args, row):
    n = _num1(args, row)
    if n is None:
        return NULL
    f = float(n)
    return NULL if f < 0 else Datum.f64(math.sqrt(f))


@register("sign", 1, 1)
def _sign(args, row):
    n = _num1(args, row)
    if n is None:
        return NULL
    return Datum.i64((n > 0) - (n < 0))


@register("mod", 2, 2)
def _mod(args, row):
    from tidb_tpu.sqlast.opcode import Op
    a, b = _vals(args, row)
    return xops.compute_arith(Op.Mod, a, b)


@register("ln", 1, 1)
def _ln(args, row):
    n = _num1(args, row)
    if n is None or float(n) <= 0:
        return NULL
    return Datum.f64(math.log(float(n)))


@register("log", 1, 2)
def _log(args, row):
    vals = _vals(args, row)
    if any(v.is_null() for v in vals):
        return NULL
    if len(vals) == 1:
        x = float(vals[0].as_number())
        return NULL if x <= 0 else Datum.f64(math.log(x))
    base, x = float(vals[0].as_number()), float(vals[1].as_number())
    if base <= 0 or base == 1 or x <= 0:
        return NULL
    return Datum.f64(math.log(x, base))


@register("log2", 1, 1)
def _log2(args, row):
    n = _num1(args, row)
    if n is None or float(n) <= 0:
        return NULL
    return Datum.f64(math.log2(float(n)))


@register("log10", 1, 1)
def _log10(args, row):
    n = _num1(args, row)
    if n is None or float(n) <= 0:
        return NULL
    return Datum.f64(math.log10(float(n)))


@register("exp", 1, 1)
def _exp(args, row):
    n = _num1(args, row)
    return NULL if n is None else Datum.f64(math.exp(float(n)))


@register("pi", 0, 0)
def _pi(args, row):
    return Datum.f64(math.pi)


_rand_state = [0x5DEECE66D]


@register("rand", 0, 1)
def _rand(args, row):
    if args:
        seed = args[0].eval(row)
        if not seed.is_null():
            _rand_state[0] = int(seed.as_number()) & ((1 << 48) - 1)
    _rand_state[0] = (_rand_state[0] * 25214903917 + 11) & ((1 << 48) - 1)
    return Datum.f64(_rand_state[0] / float(1 << 48))


@register("crc32", 1, 1)
def _crc32(args, row):
    import zlib
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    return Datum.u64(zlib.crc32(xops._datum_to_str(d).encode()) & 0xFFFFFFFF)


# ---- strings (evaluator/builtin_string.go) ----

@register("length", 1, 1)
def _length(args, row):
    d = args[0].eval(row)
    return NULL if d.is_null() else Datum.i64(len(d.get_bytes()) if d.kind in (Kind.STRING, Kind.BYTES) else len(xops._datum_to_str(d)))


@register("char_length", 1, 1)
@register("character_length", 1, 1)
def _char_length(args, row):
    d = args[0].eval(row)
    return NULL if d.is_null() else Datum.i64(len(xops._datum_to_str(d)))


@register("concat", 1, -1)
def _concat(args, row):
    out = []
    for d in _vals(args, row):
        if d.is_null():
            return NULL
        out.append(xops._datum_to_str(d))
    return Datum.string("".join(out))


@register("concat_ws", 2, -1)
def _concat_ws(args, row):
    sep = args[0].eval(row)
    if sep.is_null():
        return NULL
    parts = [xops._datum_to_str(d) for d in _vals(args[1:], row)
             if not d.is_null()]
    return Datum.string(sep.get_string().join(parts))


@register("lower", 1, 1)
@register("lcase", 1, 1)
def _lower(args, row):
    s = _str_or_none(args[0].eval(row))
    return NULL if s is None else Datum.string(s.lower())


@register("upper", 1, 1)
@register("ucase", 1, 1)
def _upper(args, row):
    s = _str_or_none(args[0].eval(row))
    return NULL if s is None else Datum.string(s.upper())


@register("substring", 2, 3)
@register("substr", 2, 3)
def _substring(args, row):
    vals = _vals(args, row)
    if any(v.is_null() for v in vals):
        return NULL
    s = xops._datum_to_str(vals[0])
    pos = int(vals[1].as_number())
    if pos == 0:
        return Datum.string("")
    start = pos - 1 if pos > 0 else len(s) + pos
    if start < 0:
        return Datum.string("")
    if len(vals) == 3:
        ln = int(vals[2].as_number())
        if ln <= 0:
            return Datum.string("")
        return Datum.string(s[start:start + ln])
    return Datum.string(s[start:])


@register("left", 2, 2)
def _left(args, row):
    s, n = _vals(args, row)
    if s.is_null() or n.is_null():
        return NULL
    k = int(n.as_number())
    return Datum.string(xops._datum_to_str(s)[:max(k, 0)])


@register("right", 2, 2)
def _right(args, row):
    s, n = _vals(args, row)
    if s.is_null() or n.is_null():
        return NULL
    k = int(n.as_number())
    txt = xops._datum_to_str(s)
    return Datum.string(txt[-k:] if k > 0 else "")


@register("trim", 1, 3)
def _trim(args, row):
    # trim(s) | trim(s, remstr, direction:{0 both,1 leading,2 trailing})
    vals = _vals(args, row)
    if vals[0].is_null():
        return NULL
    s = xops._datum_to_str(vals[0])
    rem = " "
    direction = 0
    if len(vals) >= 2 and not vals[1].is_null():
        rem = xops._datum_to_str(vals[1])
    if len(vals) == 3:
        direction = int(vals[2].as_number())
    if rem:
        if direction in (0, 1):
            while s.startswith(rem):
                s = s[len(rem):]
        if direction in (0, 2):
            while s.endswith(rem):
                s = s[:-len(rem)]
    return Datum.string(s)


@register("ltrim", 1, 1)
def _ltrim(args, row):
    s = _str_or_none(args[0].eval(row))
    return NULL if s is None else Datum.string(s.lstrip(" "))


@register("rtrim", 1, 1)
def _rtrim(args, row):
    s = _str_or_none(args[0].eval(row))
    return NULL if s is None else Datum.string(s.rstrip(" "))


@register("replace", 3, 3)
def _replace(args, row):
    vals = _vals(args, row)
    if any(v.is_null() for v in vals):
        return NULL
    s, frm, to = (xops._datum_to_str(v) for v in vals)
    return Datum.string(s.replace(frm, to) if frm else s)


@register("repeat", 2, 2)
def _repeat(args, row):
    s, n = _vals(args, row)
    if s.is_null() or n.is_null():
        return NULL
    k = int(n.as_number())
    return Datum.string(xops._datum_to_str(s) * max(k, 0))


@register("reverse", 1, 1)
def _reverse(args, row):
    s = _str_or_none(args[0].eval(row))
    return NULL if s is None else Datum.string(s[::-1])


@register("space", 1, 1)
def _space(args, row):
    n = args[0].eval(row)
    return NULL if n.is_null() else Datum.string(" " * max(int(n.as_number()), 0))


@register("locate", 2, 3)
def _locate(args, row):
    vals = _vals(args, row)
    if vals[0].is_null() or vals[1].is_null():
        return NULL
    sub, s = xops._datum_to_str(vals[0]), xops._datum_to_str(vals[1])
    start = 0
    if len(vals) == 3:
        if vals[2].is_null():
            return NULL
        start = max(int(vals[2].as_number()) - 1, 0)
    return Datum.i64(s.lower().find(sub.lower(), start) + 1)


@register("instr", 2, 2)
def _instr(args, row):
    s, sub = _vals(args, row)
    if s.is_null() or sub.is_null():
        return NULL
    return Datum.i64(xops._datum_to_str(s).lower().find(
        xops._datum_to_str(sub).lower()) + 1)


@register("ascii", 1, 1)
def _ascii(args, row):
    s = _str_or_none(args[0].eval(row))
    if s is None:
        return NULL
    return Datum.i64(s.encode()[0] if s else 0)


@register("hex", 1, 1)
def _hex(args, row):
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    if d.kind in (Kind.STRING, Kind.BYTES):
        return Datum.string(d.get_bytes().hex().upper())
    return Datum.string(format(int(d.as_number()) & ((1 << 64) - 1), "X"))


@register("unhex", 1, 1)
def _unhex(args, row):
    s = _str_or_none(args[0].eval(row))
    if s is None:
        return NULL
    try:
        return Datum.bytes_(bytes.fromhex(s))
    except ValueError:
        return NULL


@register("lpad", 3, 3)
def _lpad(args, row):
    vals = _vals(args, row)
    if any(v.is_null() for v in vals):
        return NULL
    s, n, pad = xops._datum_to_str(vals[0]), int(vals[1].as_number()), \
        xops._datum_to_str(vals[2])
    if n < 0 or (len(s) < n and not pad):
        return NULL
    if len(s) >= n:
        return Datum.string(s[:n])
    fill = (pad * n)[:n - len(s)]
    return Datum.string(fill + s)


@register("rpad", 3, 3)
def _rpad(args, row):
    vals = _vals(args, row)
    if any(v.is_null() for v in vals):
        return NULL
    s, n, pad = xops._datum_to_str(vals[0]), int(vals[1].as_number()), \
        xops._datum_to_str(vals[2])
    if n < 0 or (len(s) < n and not pad):
        return NULL
    if len(s) >= n:
        return Datum.string(s[:n])
    fill = (pad * n)[:n - len(s)]
    return Datum.string(s + fill)


@register("strcmp", 2, 2)
def _strcmp(args, row):
    a, b = _vals(args, row)
    if a.is_null() or b.is_null():
        return NULL
    x, y = xops._datum_to_str(a), xops._datum_to_str(b)
    return Datum.i64((x > y) - (x < y))


@register("field", 2, -1)
def _field(args, row):
    vals = _vals(args, row)
    if vals[0].is_null():
        return Datum.i64(0)
    for i, v in enumerate(vals[1:], 1):
        if not v.is_null() and compare_datum(vals[0], v) == 0:
            return Datum.i64(i)
    return Datum.i64(0)


@register("bin", 1, 1)
def _bin(args, row):
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    return Datum.string(format(int(d.as_number()) & ((1 << 64) - 1), "b"))


@register("char", 1, -1)
def _char(args, row):
    out = bytearray()
    for d in _vals(args, row):
        if d.is_null():
            continue
        v = int(d.as_number()) & 0xFFFFFFFF
        chunk = bytearray()
        while v:
            chunk.insert(0, v & 0xFF)
            v >>= 8
        out.extend(chunk or b"\x00")
    return Datum.string(out.decode("utf-8", "replace"))


# ---- time (evaluator/builtin_time.go; subset over types.time_types) ----

def _eval_fsp(args, row) -> int:
    """Optional fractional-seconds-precision argument (0..6, default 0)."""
    if not args:
        return 0
    fd = args[0].eval(row)
    if fd.is_null():
        return 0
    fsp = int(fd.get_int())
    if not 0 <= fsp <= 6:
        raise errors.ExecError(
            f"Too-big precision {fsp} specified; maximum is 6", code=1426)
    return fsp


def _now_time(fsp: int = 0):
    import datetime as _dt
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.time_types import Time
    now = _dt.datetime.now()
    if fsp < 6:  # truncate micros to the requested precision
        step = 10 ** (6 - fsp)
        now = now.replace(microsecond=now.microsecond - now.microsecond % step
                          if fsp else 0)
    return Time(now, my.TypeDatetime, fsp)


@register("now", 0, 1)
@register("current_timestamp", 0, 1)
@register("sysdate", 0, 1)
def _now(args, row):
    return Datum(Kind.TIME, _now_time(_eval_fsp(args, row)))


@register("curdate", 0, 0)
@register("current_date", 0, 0)
def _curdate(args, row):
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.time_types import Time
    t = _now_time()
    return Datum(Kind.TIME, Time(t.dt.replace(hour=0, minute=0, second=0),
                                 my.TypeDate, 0))


@register("unix_timestamp", 0, 1)
def _unix_ts(args, row):
    if not args:
        return Datum.i64(int(_time.time()))
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    t = _as_time(d)
    if t is None:
        return Datum.i64(0)  # MySQL returns 0 for unparseable input
    return Datum.i64(int(t.dt.timestamp()))


def _as_time(d: Datum):
    from tidb_tpu.types.time_types import parse_time
    if d.kind == Kind.TIME:
        return d.val
    if d.kind in (Kind.STRING, Kind.BYTES):
        try:
            return parse_time(d.get_string())
        except errors.TiDBError:
            return None
    return None


def _time_part(args, row, attr):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64(getattr(t.dt, attr))


@register("year", 1, 1)
def _year(args, row):
    return _time_part(args, row, "year")


@register("month", 1, 1)
def _month(args, row):
    return _time_part(args, row, "month")


@register("day", 1, 1)
@register("dayofmonth", 1, 1)
def _day(args, row):
    return _time_part(args, row, "day")


@register("hour", 1, 1)
def _hour(args, row):
    return _time_part(args, row, "hour")


@register("minute", 1, 1)
def _minute(args, row):
    return _time_part(args, row, "minute")


@register("second", 1, 1)
def _second(args, row):
    return _time_part(args, row, "second")


@register("microsecond", 1, 1)
def _microsecond(args, row):
    """MICROSECOND(expr) — the last entry of the reference Funcs map
    (evaluator/builtin.go) to gain a counterpart here."""
    return _time_part(args, row, "microsecond")


@register("date", 1, 1)
def _date(args, row):
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.time_types import Time
    t = _as_time(args[0].eval(row))
    if t is None:
        return NULL
    return Datum(Kind.TIME, Time(t.dt.replace(hour=0, minute=0, second=0,
                                              microsecond=0), my.TypeDate, 0))


@register("weekday", 1, 1)
def _weekday(args, row):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64(t.dt.weekday())


@register("dayofweek", 1, 1)
def _dayofweek(args, row):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64((t.dt.weekday() + 1) % 7 + 1)


@register("dayofyear", 1, 1)
def _dayofyear(args, row):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64(t.dt.timetuple().tm_yday)


# ---- info (evaluator/builtin_info.go; ctx-bound ones are rebound by session) ----

@register("version", 0, 0)
def _version(args, row):
    from tidb_tpu import mysqldef as my
    return Datum.string(my.SERVER_VERSION)


@register("database", 0, 0)
@register("schema", 0, 0)
def _database(args, row):
    return NULL  # session layer substitutes a bound closure


@register("current_user", 0, 0)
@register("user", 0, 0)
def _user(args, row):
    return NULL  # session layer substitutes


@register("connection_id", 0, 0)
def _connection_id(args, row):
    return Datum.u64(0)


@register("found_rows", 0, 0)
def _found_rows(args, row):
    return Datum.u64(0)


@register("last_insert_id", 0, 1)
def _last_insert_id(args, row):
    return Datum.u64(0)


# ---- predicate-shaped builtins used by the planner's lowering ----
# (IN/LIKE become ScalarFunctions so the executor path and the expr→pb
# conversion both dispatch by name)

@register("in", 2, -1)
def _in(args, row):
    v = args[0].eval(row)
    return xops.compute_in(v, [a.eval(row) for a in args[1:]])


@register("not_in", 2, -1)
def _not_in(args, row):
    v = args[0].eval(row)
    return xops.compute_in(v, [a.eval(row) for a in args[1:]], negated=True)


@register("like", 3, 3)
def _like(args, row):
    esc = args[2].eval(row)
    return xops.compute_like(args[0].eval(row), args[1].eval(row),
                             esc.get_string() if not esc.is_null() else "\\")


@register("not_like", 3, 3)
def _not_like(args, row):
    esc = args[2].eval(row)
    return xops.compute_like(args[0].eval(row), args[1].eval(row),
                             esc.get_string() if not esc.is_null() else "\\",
                             negated=True)


@register("is_not_null", 1, 1)
def _is_not_null(args, row):
    return xops.bool_datum(not args[0].eval(row).is_null())


# ---- interval arithmetic (evaluator/builtin_time.go DATE_ADD/DATE_SUB) ----

_UNIT_SECONDS = {"microsecond": 1e-6, "second": 1, "minute": 60,
                 "hour": 3600, "day": 86400, "week": 7 * 86400}


def _interval_count(d: Datum) -> int | float:
    """Interval magnitude: MySQL coerces strings/decimals numerically
    (a non-numeric string coerces to 0, with a warning in MySQL)."""
    if d.kind in (Kind.STRING, Kind.BYTES):
        s = d.get_string().strip()
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                return 0
    if d.kind == Kind.FLOAT64:
        return float(d.val)
    if d.kind == Kind.DECIMAL:
        f = float(d.val)
        return int(f) if f == int(f) else f
    return int(d.get_int())


def _date_arith(args, row, sign: int) -> Datum:
    import datetime as _dt

    from tidb_tpu import mysqldef as _my
    from tidb_tpu.types.time_types import Time

    t = _as_time(args[0].eval(row))
    nd = args[1].eval(row)
    if t is None or nd.is_null():
        return NULL
    unit = args[2].eval(row).get_string().lower()
    n = _interval_count(nd) * sign
    dt = t.dt
    try:
        if unit in ("year", "quarter", "month"):
            months = int(n) * {"year": 12, "quarter": 3, "month": 1}[unit]
            total = (dt.year * 12 + dt.month - 1) + months
            y, m = divmod(total, 12)
            import calendar
            day = min(dt.day, calendar.monthrange(y, m + 1)[1])
            dt = dt.replace(year=y, month=m + 1, day=day)
        elif unit in _UNIT_SECONDS:
            dt = dt + _dt.timedelta(seconds=n * _UNIT_SECONDS[unit])
        else:
            raise errors.ExecError(f"unsupported interval unit {unit!r}")
    except (ValueError, OverflowError):
        # out-of-range datetime (year < 1 / > 9999): MySQL yields NULL
        # with a warning rather than an error
        return NULL
    # DATE stays DATE for whole-day units; any time-precision unit
    # promotes to DATETIME (builtin_time.go dateArithmetic)
    tp = t.tp
    if tp == _my.TypeDate and unit not in ("year", "quarter", "month",
                                           "week", "day"):
        tp = _my.TypeDatetime
    return Datum(Kind.TIME, Time(dt, tp, t.fsp))


@register("date_add", 3, 3)
def _date_add(args, row):
    return _date_arith(args, row, 1)


@register("date_sub", 3, 3)
def _date_sub(args, row):
    return _date_arith(args, row, -1)


@register("extract", 2, 2)
def _extract(args, row):
    """EXTRACT(unit FROM t): unit arrives as the first (string) arg."""
    unit = args[0].eval(row).get_string().lower()
    t = _as_time(args[1].eval(row))
    if t is None:
        return NULL
    d = t.dt
    if unit == "microsecond":
        return Datum.i64(d.microsecond)
    if unit == "quarter":
        return Datum.i64((d.month - 1) // 3 + 1)
    if unit == "week":
        return Datum.i64(int(d.strftime("%U")))   # mode 0: Sunday-based
    if unit in ("year", "month", "day", "hour", "minute", "second"):
        return Datum.i64(getattr(d, unit))
    raise errors.ExecError(f"unsupported EXTRACT unit {unit!r}")


@register("quarter", 1, 1)
def _quarter(args, row):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64((t.dt.month - 1) // 3 + 1)


@register("week", 1, 2)
def _week(args, row):
    """WEEK(d[, mode]): mode 0/2 Sunday-based (%U), odd modes
    Monday-based with the >=4-day rule (ISO week) — the two families
    MySQL's 8 modes collapse into for week-of-year numbering."""
    t = _as_time(args[0].eval(row))
    if t is None:
        return NULL
    mode = 0
    if len(args) > 1:
        md = args[1].eval(row)
        if not md.is_null():
            mode = int(md.get_int())
    if mode % 2:
        return Datum.i64(t.dt.isocalendar()[1])
    return Datum.i64(int(t.dt.strftime("%U")))


@register("datediff", 2, 2)
def _datediff(args, row):
    a = _as_time(args[0].eval(row))
    b = _as_time(args[1].eval(row))
    if a is None or b is None:
        return NULL
    return Datum.i64((a.dt.date() - b.dt.date()).days)


# ---- round-4 breadth: remaining reference-registry functions ----
# (evaluator/builtin.go Funcs rows not yet covered above)

@register("curtime", 0, 1)
@register("current_time", 0, 1)
def _curtime(args, row):
    from tidb_tpu.types.time_types import Duration
    fsp = _eval_fsp(args, row)
    t = _now_time(fsp).dt
    nanos = (t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000_000 \
        + t.microsecond * 1_000
    return Datum(Kind.DURATION, Duration(nanos, fsp))


@register("utc_date", 0, 0)
def _utc_date(args, row):
    import datetime as _dt
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.time_types import Time
    now = _dt.datetime.now(_dt.timezone.utc).replace(
        hour=0, minute=0, second=0, microsecond=0, tzinfo=None)
    return Datum(Kind.TIME, Time(now, my.TypeDate, 0))


@register("time", 1, 1)
def _time_fn(args, row):
    """TIME(expr): the time part, as a Duration (builtin_time.go)."""
    from tidb_tpu.types.time_types import Duration, parse_duration
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    if d.kind == Kind.DURATION:
        return d
    if d.kind in (Kind.STRING, Kind.BYTES):
        # bare clock strings are durations; full datetimes fall through
        try:
            return Datum(Kind.DURATION, parse_duration(d.get_string()))
        except errors.TiDBError:
            pass
    t = _as_time(d)
    if t is None:
        return NULL
    nanos = ((t.dt.hour * 3600 + t.dt.minute * 60 + t.dt.second)
             * 1_000_000_000 + t.dt.microsecond * 1000)
    return Datum(Kind.DURATION, Duration(nanos,
                                         6 if t.dt.microsecond else 0))


_DAY_NAMES = ("Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
              "Saturday", "Sunday")
_MONTH_NAMES = ("January", "February", "March", "April", "May", "June",
                "July", "August", "September", "October", "November",
                "December")


@register("dayname", 1, 1)
def _dayname(args, row):
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.string(_DAY_NAMES[t.dt.weekday()])


@register("monthname", 1, 1)
def _monthname(args, row):
    t = _as_time(args[0].eval(row))
    if t is None or t.dt.month == 0:
        return NULL
    return Datum.string(_MONTH_NAMES[t.dt.month - 1])


@register("weekofyear", 1, 1)
def _weekofyear(args, row):
    """WEEKOFYEAR(d) = WEEK(d, 3): ISO-8601 week."""
    t = _as_time(args[0].eval(row))
    return NULL if t is None else Datum.i64(t.dt.isocalendar()[1])


@register("yearweek", 1, 2)
def _yearweek(args, row):
    t = _as_time(args[0].eval(row))
    if t is None:
        return NULL
    mode = 0
    if len(args) > 1:
        md = args[1].eval(row)
        if not md.is_null():
            mode = int(md.get_int())
    if mode % 2:
        iso = t.dt.isocalendar()
        return Datum.i64(iso[0] * 100 + iso[1])
    # Sunday-based %U with the year of that week's Sunday
    wk = int(t.dt.strftime("%U"))
    yr = t.dt.year
    if wk == 0:
        import datetime as _dt
        prev = t.dt.replace(month=1, day=1) - _dt.timedelta(days=1)
        return Datum.i64(prev.year * 100 + int(prev.strftime("%U")))
    return Datum.i64(yr * 100 + wk)


@register("from_unixtime", 1, 2)
def _from_unixtime(args, row):
    import datetime as _dt
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.time_types import Time
    d = args[0].eval(row)
    if d.is_null():
        return NULL
    try:
        ts = float(d.get_string()) if d.kind in (Kind.STRING, Kind.BYTES) \
            else (float(d.val) if d.kind in (Kind.FLOAT64, Kind.DECIMAL)
                  else float(d.get_int()))
    except (ValueError, errors.TiDBError):
        return NULL
    if ts < 0:
        return NULL
    try:
        t = Time(_dt.datetime.fromtimestamp(ts), my.TypeDatetime,
                 6 if ts % 1 else 0)
    except (OSError, OverflowError, ValueError):
        return NULL   # out of the platform epoch range (MySQL: NULL)
    if len(args) > 1:
        fmt = args[1].eval(row)
        if fmt.is_null():
            return NULL
        return Datum.string(_mysql_strftime(t.dt, fmt.get_string()))
    return Datum(Kind.TIME, t)


# MySQL DATE_FORMAT specifiers → computed fields (builtin_time.go
# mysqlTimeFormat; %x/%v ISO pair, %X/%U Sunday pair)
def _mysql_strftime(dt, fmt: str) -> str:
    out = []
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%" or i + 1 >= n:
            out.append(c)
            i += 1
            continue
        s = fmt[i + 1]
        i += 2
        if s == "Y":
            out.append(f"{dt.year:04d}")
        elif s == "y":
            out.append(f"{dt.year % 100:02d}")
        elif s == "m":
            out.append(f"{dt.month:02d}")
        elif s == "c":
            out.append(str(dt.month))
        elif s == "M":
            out.append(_MONTH_NAMES[dt.month - 1] if dt.month else "")
        elif s == "b":
            out.append(_MONTH_NAMES[dt.month - 1][:3] if dt.month else "")
        elif s == "d":
            out.append(f"{dt.day:02d}")
        elif s == "e":
            out.append(str(dt.day))
        elif s == "D":
            d = dt.day
            sfx = "th" if 11 <= d % 100 <= 13 else \
                {1: "st", 2: "nd", 3: "rd"}.get(d % 10, "th")
            out.append(f"{d}{sfx}")
        elif s == "j":
            out.append(f"{dt.timetuple().tm_yday:03d}")
        elif s == "H":
            out.append(f"{dt.hour:02d}")
        elif s == "k":
            out.append(str(dt.hour))
        elif s in ("h", "I"):
            out.append(f"{(dt.hour % 12) or 12:02d}")
        elif s == "l":
            out.append(str((dt.hour % 12) or 12))
        elif s == "i":
            out.append(f"{dt.minute:02d}")
        elif s in ("s", "S"):
            out.append(f"{dt.second:02d}")
        elif s == "f":
            out.append(f"{dt.microsecond:06d}")
        elif s == "p":
            out.append("AM" if dt.hour < 12 else "PM")
        elif s == "r":
            h = (dt.hour % 12) or 12
            ap = "AM" if dt.hour < 12 else "PM"
            out.append(f"{h:02d}:{dt.minute:02d}:{dt.second:02d} {ap}")
        elif s == "T":
            out.append(f"{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}")
        elif s == "W":
            out.append(_DAY_NAMES[dt.weekday()])
        elif s == "a":
            out.append(_DAY_NAMES[dt.weekday()][:3])
        elif s == "w":
            out.append(str((dt.weekday() + 1) % 7))
        elif s in ("U", "X"):
            out.append(f"{int(dt.strftime('%U')):02d}" if s == "U"
                       else f"{dt.year:04d}")
        elif s in ("v", "x"):
            iso = dt.isocalendar()
            out.append(f"{iso[1]:02d}" if s == "v" else f"{iso[0]:04d}")
        elif s == "%":
            out.append("%")
        else:
            out.append(s)   # unknown specifier: literal char (MySQL)
    return "".join(out)


@register("date_format", 2, 2)
def _date_format(args, row):
    t = _as_time(args[0].eval(row))
    if t is None:
        return NULL
    fmt = args[1].eval(row)
    if fmt.is_null():
        return NULL
    return Datum.string(_mysql_strftime(t.dt, fmt.get_string()))


@register("substring_index", 3, 3)
def _substring_index(args, row):
    vs = _vals(args, row)
    if any(v.is_null() for v in vs):
        return NULL
    s, delim = vs[0].get_string(), vs[1].get_string()
    count = int(vs[2].get_int())
    if not delim:
        return Datum.string("")
    parts = s.split(delim)
    if count > 0:
        return Datum.string(delim.join(parts[:count]))
    if count < 0:
        return Datum.string(delim.join(parts[count:]))
    return Datum.string("")


def _regexp_match(args, row) -> bool | None:
    import re as _re
    vs = _vals(args, row)
    if any(v.is_null() for v in vs):
        return None
    try:
        return _re.search(vs[1].get_string(), vs[0].get_string()) is not None
    except _re.error as e:
        raise errors.ExecError(f"invalid regexp: {e}")


@register("regexp", 2, 2)
def _regexp(args, row):
    m = _regexp_match(args, row)
    return NULL if m is None else xops.bool_datum(m)


@register("not_regexp", 2, 2)
def _not_regexp(args, row):
    m = _regexp_match(args, row)
    return NULL if m is None else xops.bool_datum(not m)


# ---- misc utility (evaluator/builtin_other.go: advisory no-ops) ----

@register("sleep", 1, 1)
def _sleep(args, row):
    d = args[0].eval(row)
    if not d.is_null():
        try:
            _time.sleep(min(max(float(d.get_string()
                                      if d.kind in (Kind.STRING, Kind.BYTES)
                                      else d.val), 0.0), 5.0))
        except (TypeError, ValueError):
            pass
    return Datum.i64(0)


@register("get_lock", 2, 2)
def _get_lock(args, row):
    # single-process advisory lock: always granted (builtin_other.go)
    return Datum.i64(1)


@register("release_lock", 1, 1)
def _release_lock(args, row):
    return Datum.i64(1)
