"""Mock cluster topology: stores, regions, leaders — manipulable mid-test.

Reference: store/tikv/mock-tikv/cluster.go (:33 Cluster, :142-201
Split/Merge/ChangeLeader/GiveUpLeader) — the machinery that lets tests
force NotLeader / StaleEpoch / region-miss retries without real hardware.
Also plays the PD role (region routing + id allocation), like
mock-tikv/pd.go.
"""

from __future__ import annotations

import bisect
import itertools
import threading
from dataclasses import dataclass, field


@dataclass
class Peer:
    peer_id: int
    store_id: int


@dataclass
class Region:
    region_id: int
    start: bytes
    end: bytes | None
    peers: list[Peer]
    leader_peer_id: int
    conf_ver: int = 1
    version: int = 1          # bumped on split/merge (epoch)

    @property
    def leader_store_id(self) -> int:
        for p in self.peers:
            if p.peer_id == self.leader_peer_id:
                return p.store_id
        return 0

    def epoch(self) -> tuple[int, int]:
        return (self.conf_ver, self.version)

    def contains(self, key: bytes) -> bool:
        return key >= self.start and (self.end is None or key < self.end)

    def clone(self) -> "Region":
        return Region(self.region_id, self.start, self.end,
                      [Peer(p.peer_id, p.store_id) for p in self.peers],
                      self.leader_peer_id, self.conf_ver, self.version)


class Cluster:
    def __init__(self, n_stores: int = 3, replicas: int = 3):
        self._id = itertools.count(1)
        self._lock = threading.RLock()
        self.stores: dict[int, str] = {}
        for _ in range(n_stores):
            sid = next(self._id)
            self.stores[sid] = f"store{sid}"
        self.replicas = min(replicas, n_stores)
        first = self._new_region(b"", None)
        self.regions: list[Region] = [first]

    def _new_region(self, start: bytes, end: bytes | None) -> Region:
        rid = next(self._id)
        store_ids = list(self.stores)
        peers = [Peer(next(self._id), store_ids[i % len(store_ids)])
                 for i in range(self.replicas)]
        return Region(rid, start, end, peers, peers[0].peer_id)

    # ---- routing (PD GetRegion) ----

    def region_by_key(self, key: bytes) -> Region:
        with self._lock:
            i = self._locate(key)
            return self.regions[i].clone()

    def region_by_id(self, rid: int) -> Region | None:
        with self._lock:
            for r in self.regions:
                if r.region_id == rid:
                    return r.clone()
            return None

    def _locate(self, key: bytes) -> int:
        starts = [r.start for r in self.regions]
        return max(bisect.bisect_right(starts, key) - 1, 0)

    # ---- test manipulation (cluster_manipulate.go) ----

    def split(self, key: bytes) -> None:
        with self._lock:
            i = self._locate(key)
            r = self.regions[i]
            if r.start == key:
                return
            right = self._new_region(key, r.end)
            r.end = key
            r.version += 1
            right.version = r.version
            self.regions.insert(i + 1, right)

    def split_keys(self, keys: list[bytes]) -> None:
        for k in sorted(keys):
            self.split(k)

    def merge(self, rid_left: int, rid_right: int) -> None:
        with self._lock:
            li = next(i for i, r in enumerate(self.regions)
                      if r.region_id == rid_left)
            ri = next(i for i, r in enumerate(self.regions)
                      if r.region_id == rid_right)
            assert ri == li + 1, "can only merge adjacent regions"
            left, right = self.regions[li], self.regions[ri]
            left.end = right.end
            left.version = max(left.version, right.version) + 1
            del self.regions[ri]

    def change_leader(self, region_id: int, store_id: int) -> None:
        with self._lock:
            for r in self.regions:
                if r.region_id == region_id:
                    for p in r.peers:
                        if p.store_id == store_id:
                            r.leader_peer_id = p.peer_id
                            return
                    # no peer on that store: add one (conf change)
                    p = Peer(next(self._id), store_id)
                    r.peers.append(p)
                    r.conf_ver += 1
                    r.leader_peer_id = p.peer_id
                    return

    def give_up_leader(self, region_id: int) -> None:
        """No leader until changed — every request bounces NotLeader."""
        with self._lock:
            for r in self.regions:
                if r.region_id == region_id:
                    r.leader_peer_id = 0
                    return
