"""In-process RPC layer: request envelopes with region-epoch checking.

Reference: store/tikv/mock-tikv/rpc.go — every KV/coprocessor request
carries a region context (id, epoch, peer); the handler rejects stale
clients with NotLeader / StaleEpoch / RegionMiss region errors exactly the
way a real storage node does, which is what exercises the client's retry
ladder (store/tikv/coprocessor.go:412-496).
"""

from __future__ import annotations

from dataclasses import dataclass

from tidb_tpu import errors, failpoint
from tidb_tpu.cluster.mvcc import KeyIsLockedError, MvccStore
from tidb_tpu.cluster.topology import Cluster, Region


class RegionError(errors.RetryableError):
    pass


class RpcTimeoutError(RegionError):
    """A request (or its response) was lost on the wire — the client
    cannot tell which, so the ladder invalidates the region and retries
    (store/tikv: send errors route through onSendFail)."""

    def __init__(self, region_id: int):
        super().__init__(f"region {region_id}: rpc timeout")
        self.region_id = region_id


class NotLeaderError(RegionError):
    def __init__(self, region_id: int, leader_store_id: int = 0):
        super().__init__(f"region {region_id}: not leader")
        self.region_id = region_id
        self.leader_store_id = leader_store_id


class StaleEpochError(RegionError):
    def __init__(self, region_id: int, current: Region | None):
        super().__init__(f"region {region_id}: stale epoch")
        self.current = current


class RegionMissError(RegionError):
    def __init__(self, region_id: int):
        super().__init__(f"region {region_id}: not found")


class ServerIsBusyError(RegionError):
    pass


@dataclass
class RegionCtx:
    region_id: int
    epoch: tuple[int, int]
    store_id: int           # the store the client thinks is leader


class RpcHandler:
    """One logical endpoint serving every store (in-proc mock); per-store
    failure injection via `down_stores`."""

    def __init__(self, cluster: Cluster, mvcc: MvccStore):
        self.cluster = cluster
        self.mvcc = mvcc
        self.down_stores: set[int] = set()
        self.busy_stores: set[int] = set()
        # per-region columnar plane cache (server-side, like TiKV's copr
        # cache): keyed by (region id, epoch, data version, table,
        # columns, range bounds) so a hit is provably snapshot-consistent
        from tidb_tpu.copr.plane_cache import PlaneCache
        self.plane_cache = PlaneCache()
        # HTAP freshness tier (copr.delta): commits whose table has live
        # cached base planes append region-side delta packs instead of
        # orphaning the cache; scans merge base+delta device-side
        from tidb_tpu.copr.delta import DeltaStore
        self.delta_store = DeltaStore(self.plane_cache)
        # device dictionary execution tier (copr.dictionary): the
        # per-(table, column) versioned global string dictionaries live
        # beside the plane cache — low-NDV string columns register at
        # pack time, so codes are stable across regions and responses
        # ship dictionary deltas instead of whole dictionaries
        from tidb_tpu.copr.dictionary import DictRegistry
        self.dict_registry = DictRegistry()
        # per-region access heat (server-side, like TiKV's hot-region
        # flow statistics): time-decayed read/write row+byte windows fed
        # from request completion — the placement signal
        # information_schema.TIDB_TPU_HOT_REGIONS and the mesh
        # region→shard item read
        from tidb_tpu.cluster.heat import RegionHeat
        self.region_heat = RegionHeat()
        # oldest-active-reader probe (the owning store wires its
        # oldest_active_ts here): lets the plane cache's version sweep
        # KEEP generations a live old snapshot still reads verbatim,
        # instead of re-packing that snapshot's planes on every read
        self.oldest_active_ts_fn = None

    # ---- region context validation ----

    def _inject(self, ctx: RegionCtx) -> None:
        """Failpoint seam for every KV/coprocessor request: each site
        raises the REAL region error the retry ladder handles, built
        from live cluster state (an injected stale-epoch carries the
        server's current region exactly like a natural one)."""
        failpoint.eval("rpc/hang")
        failpoint.eval("rpc/timeout",
                       lambda: RpcTimeoutError(ctx.region_id))
        failpoint.eval("rpc/server_busy", lambda: ServerIsBusyError(
            f"store {ctx.store_id} busy (injected)"))
        failpoint.eval("rpc/region_miss",
                       lambda: RegionMissError(ctx.region_id))
        region = self.cluster.region_by_id(ctx.region_id)
        failpoint.eval("rpc/not_leader", lambda: NotLeaderError(
            ctx.region_id, region.leader_store_id if region else 0))
        failpoint.eval("rpc/stale_epoch",
                       lambda: StaleEpochError(ctx.region_id, region))

    def _check(self, ctx: RegionCtx) -> Region:
        if failpoint._active:
            self._inject(ctx)
        if ctx.store_id in self.down_stores:
            raise errors.KVError(f"store {ctx.store_id} unreachable")
        if ctx.store_id in self.busy_stores:
            raise ServerIsBusyError(f"store {ctx.store_id} busy")
        region = self.cluster.region_by_id(ctx.region_id)
        if region is None:
            raise RegionMissError(ctx.region_id)
        if region.leader_store_id != ctx.store_id or region.leader_peer_id == 0:
            raise NotLeaderError(ctx.region_id, region.leader_store_id)
        if region.epoch() != ctx.epoch:
            raise StaleEpochError(ctx.region_id, region)
        return region

    def _clip(self, region: Region, start: bytes, end: bytes | None):
        lo = max(start, region.start)
        if region.end is None:
            return lo, end
        return lo, region.end if end is None else min(end, region.end)

    # ---- KV commands (kvrpcpb equivalents) ----

    def kv_get(self, ctx: RegionCtx, key: bytes, read_ts: int):
        region = self._check(ctx)
        if not region.contains(key):
            raise StaleEpochError(ctx.region_id, region)
        v = self.mvcc.get(key, read_ts)
        if v is not None:
            self.region_heat.record_read(ctx.region_id, 1,
                                         len(key) + len(v))
        return v

    def kv_scan(self, ctx: RegionCtx, start: bytes, end: bytes | None,
                read_ts: int, limit: int | None = None):
        region = self._check(ctx)
        lo, hi = self._clip(region, start, end)
        out = self.mvcc.scan(lo, hi, read_ts, limit)
        if out:
            self.region_heat.record_read(
                ctx.region_id, len(out),
                sum(len(k) + len(v) for k, v in out))
        return out

    def kv_prewrite(self, ctx: RegionCtx, mutations, primary: bytes,
                    start_ts: int, ttl_ms: int):
        self._check(ctx)
        failpoint.eval("twopc/prewrite", lambda: ServerIsBusyError(
            "injected prewrite fault"))
        self.mvcc.prewrite(mutations, primary, start_ts, ttl_ms)
        # write heat lands at prewrite (where the data bytes arrive);
        # commit only flips lock records, so counting it too would
        # double-attribute every row
        self.region_heat.record_write(
            ctx.region_id, len(mutations),
            sum(len(k) + (len(v) if v else 0) for _op, k, v in mutations))

    def kv_commit(self, ctx: RegionCtx, keys, start_ts: int, commit_ts: int):
        region = self._check(ctx)
        failpoint.eval("twopc/commit", lambda: ServerIsBusyError(
            "injected commit fault"))
        applied = self.mvcc.commit(keys, start_ts, commit_ts)
        # delta tier: the commit's row mutations land as append-only
        # delta entries over any live cached base planes (instead of the
        # per-table version bump above orphaning them) — after the MVCC
        # apply, so a racing scan that sees the new version but not yet
        # the delta entry simply re-packs (never a wrong answer)
        self.delta_store.on_commit(region, keys, applied or [], commit_ts)

    def kv_rollback(self, ctx: RegionCtx, keys, start_ts: int):
        self._check(ctx)
        self.mvcc.rollback(keys, start_ts)

    def kv_txn_status(self, primary: bytes, start_ts: int):
        # status check goes wherever the primary lives; epoch-free
        return self.mvcc.txn_status(primary, start_ts)

    def kv_scan_locks(self, ctx: RegionCtx, max_ts: int):
        region = self._check(ctx)
        return self.mvcc.scan_locks(max_ts, region.start, region.end)

    def kv_gc(self, ctx: RegionCtx, safe_point: int) -> int:
        self._check(ctx)
        return self.mvcc.gc(safe_point)

    # ---- coprocessor (cop_handler.go) ----

    def cop_request(self, ctx: RegionCtx, sel, ranges, read_ts: int):
        from tidb_tpu.copr.region_handler import handle_request
        from tidb_tpu.kv.kv import KeyRange
        region = self._check(ctx)
        # region-scan seams: a hang/sleep here stalls ONE fan-out worker
        # (the statement deadline bounds it); a timeout drives the
        # client's invalidate-and-retry
        failpoint.eval("copr/region_scan")
        failpoint.eval("copr/region_timeout",
                       lambda: RpcTimeoutError(ctx.region_id))
        clipped = []
        for rg in ranges:
            lo, hi = self._clip(region, rg.start, rg.end)
            if hi is None or lo < hi:
                clipped.append(KeyRange(lo, hi))
        snapshot = _MvccSnapshotView(self.mvcc, read_ts)
        if getattr(sel, "columnar_hint", False):
            # columnar channel across the fan-out: THIS region packs its
            # clipped ranges into planes and answers with a columnar
            # partial (copr.columnar_region); shapes it cannot express
            # exactly fall through to the row handler for this region
            # only — the client counts the channel per PARTIAL
            from tidb_tpu.copr.columnar_region import handle_columnar_scan
            oldest = (self.oldest_active_ts_fn()
                      if self.oldest_active_ts_fn is not None else None)
            resp = handle_columnar_scan(
                snapshot, sel, clipped,
                region=(ctx.region_id, region.epoch()),
                cache=self.plane_cache, delta=self.delta_store,
                dicts=self.dict_registry, oldest_ts=oldest)
            if resp is not None:
                self._record_copr_heat(ctx.region_id, resp)
                return resp
        resp = handle_request(snapshot, sel, clipped)
        self._record_copr_heat(ctx.region_id, resp)
        return resp

    def _record_copr_heat(self, region_id: int, resp) -> None:
        """Read-heat attribution for one coprocessor response — at
        request completion, off the retry ladder (a retried request
        counts once per attempt that actually produced data, the same
        way TiKV's flow stats count served reads). Cost: a row count the
        response already knows plus one heat update."""
        col = resp.columnar
        if col is not None:
            # columnar partial: the region scanned the whole pack (the
            # filter ran over every plane row); bytes are the plane
            # footprint (8-byte values + 1-byte valid per column)
            batch = getattr(col, "batch", None)
            if batch is None:
                # deferred states/filter payload: its pending pass knows
                # the scanned pack — len(col) here would force the
                # serial resolution the statement finisher exists to
                # batch (and un-defer the whole near-data channel)
                batch = getattr(getattr(col, "_pending", None),
                                "batch", None)
            rows = batch.n_rows if batch is not None else len(col)
            ncols = len(batch.columns) if batch is not None else 1
            self.region_heat.record_read(region_id, rows, rows * 9 * ncols)
            return
        if resp.chunks:
            self.region_heat.record_read(
                region_id, sum(len(c.rows_meta) for c in resp.chunks),
                sum(len(c.rows_data) for c in resp.chunks))
            return
        rows = resp.row_count()
        if rows:
            self.region_heat.record_read(region_id, rows, rows * 16)


class _MvccSnapshotView:
    """kv.Snapshot-shaped view over the Percolator store at read_ts —
    what the CPU coprocessor engine scans. Locks surface as
    KeyIsLockedError for the client's resolve-and-retry."""

    def __init__(self, mvcc: MvccStore, read_ts: int):
        self.mvcc = mvcc
        self.read_ts = read_ts

    def get(self, key: bytes) -> bytes:
        v = self.mvcc.get(key, self.read_ts)
        if v is None:
            raise errors.KeyNotExistsError(f"key not found: {key!r}")
        return v

    def get_or_none(self, key: bytes):
        return self.mvcc.get(key, self.read_ts)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        return iter(self.mvcc.scan(start, end, self.read_ts))

    def iterate_reverse(self, start: bytes = b"", end: bytes | None = None):
        return iter(self.mvcc.scan(start, end, self.read_ts, reverse=True))
