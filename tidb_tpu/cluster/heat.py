"""Region access-heat tracking: time-decayed per-region read/write
row and byte counters, maintained on the cluster RpcHandler.

Reference: TiKV's hotspot statistics (pd's hot-region scheduler reads
per-region read/write flow reported with store heartbeats) and PD's
`pd-ctl hot read/write` surface — the placement signal the ROADMAP's
mesh-sharded region→shard item consumes, and the model Taurus' near-data
design presumes ("know per-partition access heat before placing work
near data", PAPERS.md).

Design rules:

* The hot path pays near nothing: one dict lookup + a few float ops per
  RPC, under a plain lock (the RPCs already serialize on Python dict
  ops; contention is the fan-out worker count at most). No timers, no
  background threads — decay is applied lazily, at update and at
  snapshot time.
* Two views of every counter: the DECAYED window (exponential half-life
  decay, default 60 s — "what is hot NOW", what the HOT_REGIONS table
  ranks on) and the FLAT total (monotonic, exact — what reconciles
  against the `copr.region_heat.*` process counters).
* Region ids survive splits/merges the way PD's do: a new region id
  starts cold; the old id's heat decays away instead of being
  reassigned (heat is an access signal, not a topology mirror).
"""

from __future__ import annotations

import threading
import time


class _HeatEntry:
    __slots__ = ("read_rows", "read_bytes", "write_rows", "write_bytes",
                 "total_read_rows", "total_read_bytes",
                 "total_write_rows", "total_write_bytes",
                 "last_ts", "last_access")

    def __init__(self, now: float):
        self.read_rows = 0.0
        self.read_bytes = 0.0
        self.write_rows = 0.0
        self.write_bytes = 0.0
        self.total_read_rows = 0
        self.total_read_bytes = 0
        self.total_write_rows = 0
        self.total_write_bytes = 0
        self.last_ts = now
        self.last_access = now

    def decay(self, now: float, half_life_s: float) -> None:
        dt = now - self.last_ts
        if dt > 0:
            f = 0.5 ** (dt / half_life_s)
            self.read_rows *= f
            self.read_bytes *= f
            self.write_rows *= f
            self.write_bytes *= f
            self.last_ts = now


class RegionHeat:
    """Per-region access heat for one cluster's RpcHandler."""

    HALF_LIFE_S = 60.0
    MAX_REGIONS = 4096          # dead-region entries age out past this

    def __init__(self, half_life_s: float = HALF_LIFE_S):
        self.half_life_s = half_life_s
        self._lock = threading.Lock()
        self._entries: dict[int, _HeatEntry] = {}

    def _entry(self, region_id: int, now: float) -> _HeatEntry:
        e = self._entries.get(region_id)
        if e is None:
            e = self._entries[region_id] = _HeatEntry(now)
            if len(self._entries) > self.MAX_REGIONS:
                # evict the longest-untouched id (a merged-away region)
                dead = min(self._entries,
                           key=lambda r: self._entries[r].last_access)
                self._entries.pop(dead, None)
        return e

    def record_read(self, region_id: int, rows: int, nbytes: int) -> None:
        if not rows and not nbytes:
            return
        from tidb_tpu import metrics
        now = time.monotonic()
        with self._lock:
            e = self._entry(region_id, now)
            e.decay(now, self.half_life_s)
            e.read_rows += rows
            e.read_bytes += nbytes
            e.total_read_rows += rows
            e.total_read_bytes += nbytes
            e.last_access = now
        metrics.counter("copr.region_heat.read_rows").inc(rows)
        metrics.counter("copr.region_heat.read_bytes").inc(nbytes)

    def record_write(self, region_id: int, rows: int, nbytes: int) -> None:
        if not rows and not nbytes:
            return
        from tidb_tpu import metrics
        now = time.monotonic()
        with self._lock:
            e = self._entry(region_id, now)
            e.decay(now, self.half_life_s)
            e.write_rows += rows
            e.write_bytes += nbytes
            e.total_write_rows += rows
            e.total_write_bytes += nbytes
            e.last_access = now
        metrics.counter("copr.region_heat.write_rows").inc(rows)
        metrics.counter("copr.region_heat.write_bytes").inc(nbytes)

    def snapshot(self) -> list[dict]:
        """Decayed per-region heat, hottest first. Refreshes the
        `copr.region_heat.*` gauges as a side effect (same lazy-refresh
        contract as the plane-cache gauges: reading the surface is what
        keeps /metrics current)."""
        from tidb_tpu import metrics
        now = time.monotonic()
        out = []
        with self._lock:
            for rid, e in self._entries.items():
                e.decay(now, self.half_life_s)
                heat = (e.read_rows + e.write_rows
                        + (e.read_bytes + e.write_bytes) / 1024.0)
                out.append({
                    "region_id": rid,
                    "read_rows": e.read_rows,
                    "read_bytes": e.read_bytes,
                    "write_rows": e.write_rows,
                    "write_bytes": e.write_bytes,
                    "total_read_rows": e.total_read_rows,
                    "total_read_bytes": e.total_read_bytes,
                    "total_write_rows": e.total_write_rows,
                    "total_write_bytes": e.total_write_bytes,
                    "heat": heat,
                })
        out.sort(key=lambda d: (-d["heat"], d["region_id"]))
        metrics.gauge("copr.region_heat.regions").set(len(out))
        metrics.gauge("copr.region_heat.top_region").set(
            out[0]["region_id"] if out else 0)
        metrics.gauge("copr.region_heat.top_score").set(
            round(out[0]["heat"], 3) if out else 0)
        return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
