"""Shared bounded drain pool for the coprocessor fan-out.

Before this tier, EVERY statement's per-region fan-out spawned its own
worker threads (cluster.store._PipelinedResponse) — under heavy traffic
with thousands of concurrent sessions that is thousands of short-lived
threads per second, and the spawn cost + scheduler churn lands directly
on statement latency. This module owns ONE process-wide bounded pool
(the Taurus near-data design keeps the drain pool shared rather than
per-query; PAPERS.md): fan-outs submit region tasks here, workers are
reused across statements, and the pool size caps total drain
concurrency no matter how many statements are in flight.

Per-statement context (the statement's Backoffer/deadline and its trace
span) does NOT ride the pool — each submitted task closure attaches its
own span and backoffer explicitly (cluster.store's run()), so pooled
workers serve interleaved statements without cross-attributing.

Size: tidb_tpu_drain_pool_size (GLOBAL-only, process-wide like
tidb_tpu_mesh). Shrinking takes effect as workers finish their current
task; growing spawns on demand. Idle workers exit after a timeout so a
quiet process holds no threads.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time

_IDLE_EXIT_S = 30.0


class DrainPool:
    def __init__(self, size: int):
        self._size = max(1, int(size))
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._threads = 0          # live workers
        self._idle = 0             # workers parked in wait()
        self._seq = itertools.count()
        from tidb_tpu import metrics
        metrics.gauge("copr.drain_pool.size").set(self._size)

    @property
    def size(self) -> int:
        return self._size

    def set_size(self, n: int) -> None:
        from tidb_tpu import metrics
        with self._cv:
            self._size = max(1, int(n))
            metrics.gauge("copr.drain_pool.size").set(self._size)
            self._cv.notify_all()   # over-target idle workers exit

    def submit(self, fn) -> None:
        """Run fn() on a pool worker. fn must route its own errors (the
        fan-out stores them on the response and re-raises on the
        consumer thread) — the pool never propagates."""
        from tidb_tpu import metrics
        with self._cv:
            # enqueue time rides the entry: the worker turns it into the
            # queue-wait histogram (host-stall attribution — time a
            # region drain waited for a worker, not for data)
            self._q.append((fn, time.perf_counter()))
            metrics.counter("copr.drain_pool.tasks").inc()
            metrics.gauge("copr.drain_pool.queue_depth").set(len(self._q))
            # spawn whenever the queue outruns the idlers: a notified
            # worker only decrements _idle once it reacquires the lock,
            # so a burst of submits cannot credit the same idler N
            # times (that would serialize the whole burst on one
            # worker). A mild over-spawn just idles out.
            if self._threads < self._size and len(self._q) > self._idle:
                self._threads += 1
                threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"tidb-drain-{next(self._seq)}").start()
            elif self._idle > 0:
                self._cv.notify()

    def stats(self) -> dict:
        with self._cv:
            return {"threads": self._threads, "idle": self._idle,
                    "queued": len(self._q), "size": self._size}

    def _worker(self) -> None:
        from tidb_tpu import metrics, profiler
        profiler.register_thread()   # lane name for trace-event export
        qd = metrics.gauge("copr.drain_pool.queue_depth")
        workers = metrics.gauge("copr.drain_pool.workers")
        wait_h = metrics.histogram("copr.drain_pool.queue_wait_seconds")
        task_h = metrics.histogram("copr.drain_pool.task_seconds")
        busy_us = metrics.counter("copr.drain_pool.busy_us")
        workers.set(self._threads)
        while True:
            with self._cv:
                while not self._q:
                    if self._threads > self._size:
                        self._threads -= 1
                        workers.set(self._threads)
                        return          # shrink target reached
                    self._idle += 1
                    got = self._cv.wait(timeout=_IDLE_EXIT_S)
                    self._idle -= 1
                    if not got and not self._q:
                        self._threads -= 1
                        workers.set(self._threads)
                        return          # idle exit
                if self._threads > self._size:
                    self._threads -= 1
                    workers.set(self._threads)
                    self._cv.notify()   # someone else serves the queue
                    return
                fn, t_enq = self._q.popleft()
                qd.set(len(self._q))
            t_run = time.perf_counter()
            wait_h.observe(t_run - t_enq)
            try:
                fn()
            except BaseException:  # retryable-ok: fan-out task closures
                # route their errors onto the response object and the
                # consumer thread re-raises; a closure that leaks here is
                # a bug but must never kill a shared worker
                import logging
                logging.getLogger(__name__).exception(
                    "drain-pool task leaked an exception")
            finally:
                dt = time.perf_counter() - t_run
                task_h.observe(dt)
                busy_us.inc(int(dt * 1e6))


_pool: DrainPool | None = None
_pool_lock = threading.Lock()


def get_pool() -> DrainPool:
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                from tidb_tpu.sessionctx import SYSVAR_DEFAULTS
                _pool = DrainPool(
                    int(SYSVAR_DEFAULTS["tidb_tpu_drain_pool_size"]))
    return _pool


def set_pool_size(n: int) -> None:
    """Process-wide resize (SET GLOBAL tidb_tpu_drain_pool_size and
    bootstrap hydration apply through this)."""
    get_pool().set_size(n)
