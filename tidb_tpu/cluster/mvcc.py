"""Percolator-style MVCC store: the storage node's transactional core.

Reference: store/tikv/mock-tikv/mvcc.go (the in-proc stand-in for real
TiKV's storage layer). Three logical columns per key:
  data:  committed versions [(commit_ts, start_ts, value|None)]
  lock:  at most one uncommitted lock (primary, start_ts, ttl, kind, value)
  write: folded into data here (commit records carry start_ts)

Writes follow the Percolator protocol driven by the client's 2PC
(cluster/twopc.py): prewrite takes locks + buffers values, commit moves the
buffered value into the data column at commit_ts, rollback clears the lock.
Reads at ts block on (raise) any lock with lock.start_ts <= ts, surfacing
LockInfo so the client's resolver can decide commit-or-rollback.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field

from tidb_tpu import errors


@dataclass
class LockInfo:
    key: bytes
    primary: bytes
    start_ts: int
    ttl_ms: int
    kind: str               # 'put' | 'delete' | 'lock'
    value: bytes | None
    created_at: float = field(default_factory=time.monotonic)

    def expired(self) -> bool:
        return (time.monotonic() - self.created_at) * 1000.0 > self.ttl_ms


class KeyIsLockedError(errors.RetryableError):
    def __init__(self, lock: LockInfo):
        super().__init__(f"key {lock.key!r} locked by txn {lock.start_ts}")
        self.lock = lock


class WriteConflict(errors.WriteConflictError):
    pass


class TxnAborted(errors.TiDBError):
    """Commit attempted but the lock is gone and a rollback record exists."""


@dataclass
class _Versions:
    # parallel sorted-by-commit_ts lists (ascending)
    commit_ts: list[int] = field(default_factory=list)
    start_ts: list[int] = field(default_factory=list)
    values: list[bytes | None] = field(default_factory=list)  # None=delete


# the granularity of per-table commit filtering: a commit bumps the data
# version only of the tables whose keyspace it touched, so the plane
# cache keyed on the TABLE's version survives unrelated writes. The
# bucketing rule itself lives with the key layout (tablecodec).
from tidb_tpu.tablecodec import table_prefix_of  # noqa: E402


class MvccStore:
    """One per mock cluster (mock-tikv shares a single store too)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._data: dict[bytes, _Versions] = {}
        self._locks: dict[bytes, LockInfo] = {}
        # start_ts of explicitly rolled-back txns (rollback records)
        self._rollbacks: set[int] = set()
        self._sorted_keys: list[bytes] | None = []
        # ascending commit_ts of every commit batch (data_version_at)
        self._commit_log: list[int] = []
        self._max_commit_ts = 0
        # per-table-prefix twins of the commit log (HTAP freshness tier):
        # commits append their commit_ts under every table prefix they
        # touch, so data_version_at(ts, prefix) answers "how many commits
        # touched THIS table" — the plane cache's per-table version key
        self._table_log: dict[bytes, list[int]] = {}
        self._table_max: dict[bytes, int] = {}

    def data_version_at(self, read_ts: int, prefix: bytes | None = None
                        ) -> int:
        """Count of commit events visible at read_ts: equal versions imply
        identical visible data — the columnar plane-cache key (mirrors
        localstore.LocalStore.data_version_at). The plane cache consults
        this 2-3× per region task (lookup + post-pack stabilization), so
        the common fresh-snapshot case (read_ts at/above every commit)
        answers O(1) without the bisect.

        With `prefix` (a table_prefix_of bucket) only commits that touched
        that table's keyspace count — equal TABLE versions imply identical
        visible data for any range inside the table's prefix, which is all
        a per-region pack ever reads. A commit to table B then never moves
        table A's version (the per-table commit filter)."""
        with self._lock:
            if prefix is None:
                if read_ts >= self._max_commit_ts:
                    return len(self._commit_log)
                return bisect.bisect_right(self._commit_log, read_ts)
            log = self._table_log.get(prefix)
            if log is None:
                return 0
            if read_ts >= self._table_max.get(prefix, 0):
                return len(log)
            return bisect.bisect_right(log, read_ts)

    def table_commits_between(self, prefix: bytes, v0: int,
                              v1: int) -> list[int]:
        """The commit_ts values of table-prefix commits (v0, v1] —
        positions v0..v1 of the sorted per-table log. The delta-merge
        validity check: a cached base at table version v0 serves a reader
        at version v1 iff its delta pack holds an entry for EVERY one of
        these commits (missing ts ⇒ the pack has a gap ⇒ re-pack)."""
        with self._lock:
            log = self._table_log.get(prefix, [])
            return list(log[v0:v1])

    # ---- reads ----

    def get(self, key: bytes, read_ts: int) -> bytes | None:
        with self._lock:
            self._check_lock(key, read_ts)
            return self._get_committed(key, read_ts)

    def _check_lock(self, key: bytes, read_ts: int) -> None:
        lock = self._locks.get(key)
        if lock is not None and lock.start_ts <= read_ts \
                and lock.kind != "lock":
            raise KeyIsLockedError(lock)

    def _get_committed(self, key: bytes, read_ts: int) -> bytes | None:
        vs = self._data.get(key)
        if vs is None:
            return None
        i = bisect.bisect_right(vs.commit_ts, read_ts) - 1
        if i < 0:
            return None
        return vs.values[i]

    def scan(self, start: bytes, end: bytes | None, read_ts: int,
             limit: int | None = None, reverse: bool = False):
        """Committed (key, value) pairs in [start, end) visible at read_ts;
        raises KeyIsLockedError on a blocking lock."""
        with self._lock:
            keys = self._keys_in_range(start, end)
            if reverse:
                keys = list(reversed(keys))
            out = []
            for k in keys:
                self._check_lock(k, read_ts)
                v = self._get_committed(k, read_ts)
                if v is not None:
                    out.append((k, v))
                    if limit is not None and len(out) >= limit:
                        break
            return out

    def _keys_in_range(self, start: bytes, end: bytes | None) -> list[bytes]:
        if self._sorted_keys is None:
            self._sorted_keys = sorted(set(self._data) | set(self._locks))
        keys = self._sorted_keys
        lo = bisect.bisect_left(keys, start)
        hi = bisect.bisect_left(keys, end) if end is not None else len(keys)
        return keys[lo:hi]

    # ---- percolator writes ----

    def prewrite(self, mutations: list[tuple[str, bytes, bytes | None]],
                 primary: bytes, start_ts: int, ttl_ms: int = 3000) -> None:
        """mutations: (op, key, value). Reference: mock-tikv mvcc.Prewrite —
        lock conflict → KeyIsLocked; newer committed write → WriteConflict."""
        with self._lock:
            # validate all first: prewrite is atomic per batch
            for op, key, value in mutations:
                lock = self._locks.get(key)
                if lock is not None and lock.start_ts != start_ts:
                    raise KeyIsLockedError(lock)
                vs = self._data.get(key)
                if vs and vs.commit_ts and vs.commit_ts[-1] >= start_ts:
                    raise WriteConflict(
                        f"write conflict on {key!r}: committed "
                        f"{vs.commit_ts[-1]} >= start_ts {start_ts}")
                if start_ts in self._rollbacks:
                    raise TxnAborted(f"txn {start_ts} already rolled back")
            for op, key, value in mutations:
                self._locks[key] = LockInfo(key, primary, start_ts, ttl_ms,
                                            op, value)
            self._sorted_keys = None

    def commit(self, keys: list[bytes], start_ts: int,
               commit_ts: int) -> list[tuple[bytes, bytes | None]]:
        """Commit the prewritten keys; returns the DATA mutations applied
        as (key, value|None) pairs (None = delete; SELECT FOR UPDATE
        'lock' records apply nothing) — the region-side delta-pack tier
        appends these over cached base planes (copr.delta)."""
        with self._lock:
            for key in keys:
                lock = self._locks.get(key)
                if lock is None or lock.start_ts != start_ts:
                    # already committed (idempotent retry) or rolled back
                    if self._committed_at(key, start_ts) is not None:
                        continue
                    raise TxnAborted(
                        f"commit of {key!r}@{start_ts}: lock missing")
            # visible-data version log: any commit advances the version
            # seen by readers at ts >= commit_ts (columnar cache key) —
            # plus the per-table twins, so only the TOUCHED tables'
            # versions move (the per-table commit filter)
            i = bisect.bisect_left(self._commit_log, commit_ts)
            self._commit_log.insert(i, commit_ts)
            if commit_ts > self._max_commit_ts:
                self._max_commit_ts = commit_ts
            for prefix in {table_prefix_of(k) for k in keys}:
                log = self._table_log.setdefault(prefix, [])
                log.insert(bisect.bisect_left(log, commit_ts), commit_ts)
                if commit_ts > self._table_max.get(prefix, 0):
                    self._table_max[prefix] = commit_ts
            applied: list[tuple[bytes, bytes | None]] = []
            for key in keys:
                lock = self._locks.pop(key, None)
                if lock is None or lock.start_ts != start_ts:
                    continue
                if lock.kind == "lock":
                    continue  # SELECT FOR UPDATE lock: no data write
                vs = self._data.setdefault(key, _Versions())
                i = bisect.bisect_left(vs.commit_ts, commit_ts)
                vs.commit_ts.insert(i, commit_ts)
                vs.start_ts.insert(i, start_ts)
                value = None if lock.kind == "delete" else lock.value
                vs.values.insert(i, value)
                applied.append((key, value))
            self._sorted_keys = None
            return applied

    def rollback(self, keys: list[bytes], start_ts: int) -> None:
        with self._lock:
            for key in keys:
                lock = self._locks.get(key)
                if lock is not None and lock.start_ts == start_ts:
                    del self._locks[key]
                elif self._committed_at(key, start_ts) is not None:
                    raise TxnAborted(
                        f"cannot roll back {key!r}@{start_ts}: committed")
            self._rollbacks.add(start_ts)
            self._sorted_keys = None

    def _committed_at(self, key: bytes, start_ts: int) -> int | None:
        vs = self._data.get(key)
        if vs is None:
            return None
        for cts, sts in zip(vs.commit_ts, vs.start_ts):
            if sts == start_ts:
                return cts
        return None

    # ---- lock resolution support (cluster/lock_resolver.py) ----

    def txn_status(self, primary: bytes, start_ts: int) -> tuple[str, int]:
        """('committed', commit_ts) | ('rolled_back', 0) | ('locked', 0) —
        checked on the PRIMARY key (the Percolator source of truth)."""
        with self._lock:
            cts = self._committed_at(primary, start_ts)
            if cts is not None:
                return "committed", cts
            lock = self._locks.get(primary)
            if lock is not None and lock.start_ts == start_ts:
                return "locked", 0
            return "rolled_back", 0

    def has_blocking_lock(self, read_ts: int, start: bytes = b"",
                          end: bytes | None = None) -> bool:
        """Any READ-blocking lock (kind != 'lock') in [start, end)
        visible to a reader at read_ts — the plane cache's hit-side lock
        gate: a pending lock's commit_ts may have been allocated BEFORE
        read_ts, so serving cached planes past it could hide a commit
        the scan path would block on, resolve, and include. O(1) when no
        locks exist (the common case)."""
        with self._lock:
            if not self._locks:
                return False
            for k, lock in self._locks.items():
                if lock.start_ts <= read_ts and lock.kind != "lock" \
                        and k >= start and (end is None or k < end):
                    return True
            return False

    def scan_locks(self, max_ts: int, start: bytes = b"",
                   end: bytes | None = None) -> list[LockInfo]:
        with self._lock:
            return [l for k, l in sorted(self._locks.items())
                    if l.start_ts <= max_ts
                    and k >= start and (end is None or k < end)]

    # ---- GC ----

    def gc(self, safe_point: int) -> int:
        """Drop versions no snapshot at/after safe_point can see.
        Reference: gc_worker.DoGC."""
        removed = 0
        with self._lock:
            for key, vs in list(self._data.items()):
                keep_from = bisect.bisect_right(vs.commit_ts, safe_point) - 1
                if keep_from > 0:
                    # versions before keep_from are shadowed at safe_point
                    removed += keep_from
                    vs.commit_ts = vs.commit_ts[keep_from:]
                    vs.start_ts = vs.start_ts[keep_from:]
                    vs.values = vs.values[keep_from:]
                # tombstone visible at safepoint with no newer versions:
                # the key is gone for every future reader
                if len(vs.commit_ts) == 1 and vs.values[0] is None \
                        and vs.commit_ts[0] <= safe_point:
                    del self._data[key]
                    removed += 1
            self._sorted_keys = None
        return removed
