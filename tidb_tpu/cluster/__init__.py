"""Distributed KV: mock cluster + Percolator client (store/tikv equivalent).

The full SQL engine runs unchanged over this storage via the same
kv.Storage/kv.Client contracts as the single-node localstore; the
differences live entirely below the KV boundary — region routing, 2PC,
lock resolution, retry ladders. See SURVEY.md §2.7.
"""

from tidb_tpu.cluster.mvcc import KeyIsLockedError, LockInfo, MvccStore
from tidb_tpu.cluster.rpc import (
    NotLeaderError, RegionError, RpcHandler, StaleEpochError,
)
from tidb_tpu.cluster.store import ClusterDriver, DistStore
from tidb_tpu.cluster.topology import Cluster

__all__ = [
    "Cluster", "ClusterDriver", "DistStore", "MvccStore",
    "KeyIsLockedError", "LockInfo", "NotLeaderError", "RegionError",
    "RpcHandler", "StaleEpochError",
]
