"""Distributed KV client: region cache, backoff ladder, lock resolver,
snapshot reads, coprocessor fan-out.

Reference: store/tikv/ — region_cache.go (:30 LLRB cache, :245
OnRegionStale), backoff.go (typed exponential backoffs with budget),
lock_resolver.go (TTL-based rollback-or-commit), snapshot.go (:38-233
batched gets with lock resolution), scan.go, coprocessor.go (:74 CopClient
with the full retry ladder).
"""

from __future__ import annotations

import bisect
import threading

from tidb_tpu import errors
from tidb_tpu.cluster.mvcc import KeyIsLockedError, LockInfo
from tidb_tpu.cluster.rpc import (
    NotLeaderError, RegionCtx, RegionError, RpcHandler, ServerIsBusyError,
    StaleEpochError,
)
from tidb_tpu.cluster.topology import Cluster, Region
from tidb_tpu.kv import kv
# the backoff ladder (store/tikv/backoff.go) lives in kv/backoff.py now:
# ONE statement-scoped Backoffer with per-kind budgets and the
# tidb_tpu_max_execution_time deadline, shared by every retry loop of a
# statement (this module's ladders pick it up via backoff.current_or())
from tidb_tpu.kv import backoff as kvbackoff
from tidb_tpu.kv.backoff import Backoffer  # noqa: F401 — historical home


# ---------------------------------------------------------------------------
# region cache (store/tikv/region_cache.go)
# ---------------------------------------------------------------------------

class RegionCache:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._lock = threading.RLock()
        self._regions: list[Region] = []   # sorted by start

    def locate(self, key: bytes) -> Region:
        with self._lock:
            i = self._find(key)
            if i is not None:
                return self._regions[i]
        region = self.cluster.region_by_key(key)  # "PD" lookup
        with self._lock:
            self._insert(region)
        return region

    def _find(self, key: bytes):
        starts = [r.start for r in self._regions]
        i = bisect.bisect_right(starts, key) - 1
        if i >= 0 and self._regions[i].contains(key):
            return i
        return None

    def _insert(self, region: Region) -> None:
        # drop overlapping stale entries, insert fresh
        self._regions = [r for r in self._regions
                         if r.end is not None and r.end <= region.start
                         or (region.end is not None and r.start >= region.end)]
        starts = [r.start for r in self._regions]
        self._regions.insert(bisect.bisect_left(starts, region.start), region)

    def invalidate(self, region_id: int) -> None:
        with self._lock:
            self._regions = [r for r in self._regions
                             if r.region_id != region_id]

    def on_stale(self, err: StaleEpochError) -> None:
        """Reference: OnRegionStale — replace with the server's view."""
        with self._lock:
            if err.current is not None:
                self._regions = [r for r in self._regions
                                 if r.region_id != err.current.region_id]
                self._insert(err.current)

    def on_not_leader(self, err: NotLeaderError) -> None:
        with self._lock:
            for r in self._regions:
                if r.region_id == err.region_id and err.leader_store_id:
                    for p in r.peers:
                        if p.store_id == err.leader_store_id:
                            r.leader_peer_id = p.peer_id
                            return
            self.invalidate(err.region_id)

    def group_keys_by_region(self, keys: list[bytes]):
        """Reference: GroupKeysByRegion (region_cache.go:80)."""
        groups: dict[int, tuple[Region, list[bytes]]] = {}
        for k in sorted(keys):
            r = self.locate(k)
            groups.setdefault(r.region_id, (r, []))[1].append(k)
        return list(groups.values())

    def split_range_by_region(self, start: bytes, end: bytes | None):
        out = []
        key = start
        while True:
            r = self.locate(key)
            seg_end = r.end if end is None else (
                min(r.end, end) if r.end is not None else end)
            out.append((r, key, seg_end))
            if r.end is None or (end is not None and r.end >= end):
                return out
            key = r.end


# ---------------------------------------------------------------------------
# RPC with retry ladder
# ---------------------------------------------------------------------------

class RegionRequestSender:
    """Wraps one RPC with the NotLeader/StaleEpoch/busy retry ladder
    (store/tikv coprocessor.go handleTask / kv.go SendKVReq)."""

    def __init__(self, cache: RegionCache, rpc: RpcHandler):
        self.cache = cache
        self.rpc = rpc

    def send(self, key_for_region: bytes, op, bo: Backoffer | None = None):
        """op(ctx, region) → result; region re-resolved per attempt."""
        bo = bo or kvbackoff.current_or()
        while True:
            bo.check_deadline("region rpc")
            region = self.cache.locate(key_for_region)
            ctx = RegionCtx(region.region_id, region.epoch(),
                            region.leader_store_id)
            try:
                return op(ctx, region)
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff("rpc", e)
            except StaleEpochError as e:
                self.cache.on_stale(e)
                bo.backoff("region_miss", e)
            except ServerIsBusyError as e:
                bo.backoff("server_busy", e)
            except RegionError as e:
                self.cache.invalidate(region.region_id)
                bo.backoff("region_miss", e)


# ---------------------------------------------------------------------------
# lock resolver (store/tikv/lock_resolver.go)
# ---------------------------------------------------------------------------

class LockResolver:
    def __init__(self, sender: RegionRequestSender, rpc: RpcHandler):
        self.sender = sender
        self.rpc = rpc
        self._status_cache: dict[int, tuple[str, int]] = {}

    def resolve(self, locks: list[LockInfo], bo: Backoffer) -> bool:
        """Try to clear the given locks. Returns True if all cleared (the
        read can retry immediately); False → caller should back off."""
        all_cleared = True
        for lock in locks:
            status = self._get_status(lock)
            if status[0] == "locked":
                if lock.expired():
                    # crashed writer: roll back the primary, then this key
                    self._rollback(lock.primary, lock.start_ts)
                    self._status_cache[lock.start_ts] = ("rolled_back", 0)
                    if lock.key != lock.primary:
                        self._rollback(lock.key, lock.start_ts)
                else:
                    all_cleared = False
                continue
            if status[0] == "committed":
                self._commit_key(lock.key, lock.start_ts, status[1])
            else:
                self._rollback(lock.key, lock.start_ts)
        return all_cleared

    def _get_status(self, lock: LockInfo) -> tuple[str, int]:
        cached = self._status_cache.get(lock.start_ts)
        if cached is not None:
            return cached
        status = self.rpc.kv_txn_status(lock.primary, lock.start_ts)
        if status[0] != "locked":
            self._status_cache[lock.start_ts] = status
        return status

    def _commit_key(self, key: bytes, start_ts: int, commit_ts: int) -> None:
        self.sender.send(
            key, lambda ctx, r: self.rpc.kv_commit(ctx, [key], start_ts,
                                                   commit_ts))

    def _rollback(self, key: bytes, start_ts: int) -> None:
        self.sender.send(
            key, lambda ctx, r: self.rpc.kv_rollback(ctx, [key], start_ts))


# ---------------------------------------------------------------------------
# snapshot / scanner
# ---------------------------------------------------------------------------

class DistSnapshot(kv.Snapshot):
    SCAN_BATCH = 256  # store/tikv/scan.go batch size

    def __init__(self, store: "DistStore", version: int):
        self.store = store
        self.version = version

    def _resolve_and_retry(self, fn):
        bo = kvbackoff.current_or()
        while True:
            try:
                return fn()
            except KeyIsLockedError as e:
                cleared = self.store.resolver.resolve([e.lock], bo)
                if not cleared:
                    bo.backoff("txn_lock", e)

    def get(self, key: bytes) -> bytes:
        v = self.get_or_none(key)
        if v is None:
            raise errors.KeyNotExistsError(f"key not found: {key!r}")
        return v

    def get_or_none(self, key: bytes):
        return self._resolve_and_retry(
            lambda: self.store.sender.send(
                key, lambda ctx, r: self.store.rpc.kv_get(ctx, key,
                                                          self.version)))

    def batch_get(self, keys) -> dict[bytes, bytes]:
        out: dict[bytes, bytes] = {}
        for region, group in self.store.cache.group_keys_by_region(list(keys)):
            for k in group:
                v = self.get_or_none(k)
                if v is not None:
                    out[k] = v
        return out

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        key = start
        while True:
            batch = self._resolve_and_retry(
                lambda: self.store.sender.send(
                    key, lambda ctx, r: self.store.rpc.kv_scan(
                        ctx, key, end, self.version, self.SCAN_BATCH)))
            for k, v in batch:
                yield k, v
            region = self.store.cache.locate(key)
            if len(batch) >= self.SCAN_BATCH:
                key = batch[-1][0] + b"\x00"
            elif region.end is not None and (end is None or region.end < end):
                key = region.end
            else:
                return

    def iterate_reverse(self, start: bytes = b"", end: bytes | None = None):
        rows = list(self.iterate(start, end))
        return iter(reversed(rows))
