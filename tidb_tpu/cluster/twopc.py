"""Percolator two-phase commit.

Reference: store/tikv/2pc.go — twoPhaseCommitter (:51): group mutations by
region (:143), size-capped batches (:514, ≤512KiB), prewrite with the
primary lock first (:248), TSO commit timestamp, commit the primary batch
synchronously then the rest (:310, async in the reference), cleanup on
failure; prewrite lock conflicts go through the lock resolver.
"""

from __future__ import annotations

from tidb_tpu import errors
from tidb_tpu.cluster.client import Backoffer
from tidb_tpu.cluster.mvcc import KeyIsLockedError
from tidb_tpu.kv import backoff as kvbackoff

MAX_BATCH_BYTES = 512 * 1024  # appendBatchBySize (2pc.go:514)
LOCK_TTL_MS = 3000


class TwoPhaseCommitter:
    def __init__(self, store, start_ts: int,
                 mutations: dict[bytes, bytes | None]):
        """mutations: key → value (None = delete)."""
        self.store = store
        self.start_ts = start_ts
        self.mutations = mutations
        self.keys = sorted(mutations)
        self.primary = self.keys[0]
        self.committed = False

    # ---- batching ----

    def _batches(self, keys: list[bytes]):
        """Group by region, then cap batches by byte size."""
        for region, group in self.store.cache.group_keys_by_region(keys):
            batch: list[bytes] = []
            size = 0
            for k in group:
                v = self.mutations.get(k)
                ksize = len(k) + (len(v) if v else 0)
                if batch and size + ksize > MAX_BATCH_BYTES:
                    yield batch
                    batch, size = [], 0
                batch.append(k)
                size += ksize
            if batch:
                yield batch

    # ---- phases ----

    def _prewrite_batch(self, keys: list[bytes], bo: Backoffer) -> None:
        muts = []
        for k in keys:
            v = self.mutations[k]
            muts.append(("delete", k, None) if v is None else ("put", k, v))
        while True:
            try:
                self.store.sender.send(
                    keys[0],
                    lambda ctx, r: self.store.rpc.kv_prewrite(
                        ctx, muts, self.primary, self.start_ts, LOCK_TTL_MS),
                    bo)
                return
            except KeyIsLockedError as e:
                cleared = self.store.resolver.resolve([e.lock], bo)
                if not cleared:
                    bo.backoff("txn_lock", e)

    def _commit_batch(self, keys: list[bytes], commit_ts: int,
                      bo: Backoffer) -> None:
        self.store.sender.send(
            keys[0],
            lambda ctx, r: self.store.rpc.kv_commit(ctx, keys, self.start_ts,
                                                    commit_ts),
            bo)

    def _cleanup(self) -> None:
        from tidb_tpu import binloginfo
        # standalone ladder on purpose: cleanup runs AFTER a failure, when
        # the statement's shared budget may already be exhausted — it must
        # still make its best effort to release the locks
        bo = Backoffer()
        for batch in self._batches(self.keys):
            try:
                self.store.sender.send(
                    batch[0],
                    lambda ctx, r: self.store.rpc.kv_rollback(
                        ctx, batch, self.start_ts),
                    bo)
            except errors.TiDBError:  # retryable-ok: best-effort cleanup,
                pass  # leftover locks resolve via TTL later
        # finish binlog: rollback (writeFinishBinlog, 2pc.go:486)
        binloginfo.write_binlog({"tp": "rollback",
                                 "start_ts": self.start_ts,
                                 "commit_ts": 0})

    def execute(self) -> int:
        """Returns commit_ts. Reference: execute (2pc.go:406)."""
        from tidb_tpu import binloginfo
        # commit sleeps against the statement's unified budget/deadline
        # when one is attached (autocommit/COMMIT run inside a statement)
        bo = kvbackoff.current_or()
        # binlog: the prewrite record ships alongside phase 1
        # (2pc.go:462 prewriteBinlog — concurrent there, inline here;
        # the pump never fails the txn either way)
        if binloginfo.get_pump() is not None:
            binloginfo.write_binlog({
                "tp": "prewrite", "start_ts": self.start_ts,
                "prewrite_key": self.primary,
                "mutations": [(k, self.mutations[k]) for k in self.keys],
            })
        # phase 1: prewrite — primary's batch first (it IS the txn record)
        try:
            primary_done = False
            for batch in self._batches(self.keys):
                if not primary_done and self.primary in batch:
                    self._prewrite_batch(batch, bo)
                    primary_done = True
            for batch in self._batches(self.keys):
                if self.primary not in batch:
                    self._prewrite_batch(batch, bo)
        except errors.TiDBError:
            self._cleanup()
            raise

        commit_ts = self.store.oracle.current_version()

        # phase 2: commit the primary first — once it lands the txn IS
        # committed; secondary failures leave resolvable locks
        try:
            for batch in self._batches(self.keys):
                if self.primary in batch:
                    self._commit_batch([self.primary], commit_ts, bo)
                    # the flag flips HERE, not after the loop: a failure
                    # on the same batch's remainder must never roll back
                    # (or binlog-rollback) a transaction whose primary —
                    # the commit record — already landed
                    self.committed = True
                    rest = [k for k in batch if k != self.primary]
                    if rest:
                        self._commit_batch(rest, commit_ts, bo)
                    break
        except errors.TiDBError:
            if not self.committed:
                self._cleanup()
                raise
            # primary landed: committed despite the error; same-batch
            # stragglers resolve via LockResolver like any secondary
            # ("2PC succeed with error", 2pc.go:456)
        # finish binlog: the txn IS committed once the primary lands
        # (writeFinishBinlog, 2pc.go:480)
        binloginfo.write_binlog({"tp": "commit",
                                 "start_ts": self.start_ts,
                                 "commit_ts": commit_ts})
        for batch in self._batches(self.keys):
            if self.primary in batch:
                continue
            try:
                self._commit_batch(batch, commit_ts, bo)
            except errors.TiDBError:  # retryable-ok: txn already decided,
                # committed state is decided by the primary; stragglers
                # resolve via LockResolver on next read
                break
        return commit_ts
