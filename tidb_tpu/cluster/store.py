"""DistStore: kv.Storage over the mock distributed cluster.

Reference: store/tikv/kv.go (:44 Driver.Open → tikvStore, :114
NewMockTikvStore), txn.go (:32 tikvTxn = UnionStore overlay + 2PC commit),
coprocessor.go (:74 CopClient per-region fan-out with the retry ladder),
gc_worker.go (safepoint GC with lock resolution).

The SQL tier (session/executor/planner) runs unchanged over this storage —
same kv.Storage/Client contracts as the single-node LocalStore; only the
plumbing underneath becomes a cluster. `cluster://n_stores` registers as a
URL scheme.
"""

from __future__ import annotations

import threading

from tidb_tpu import errors, failpoint
from tidb_tpu.cluster.client import (
    Backoffer, DistSnapshot, LockResolver, RegionCache, RegionRequestSender,
)
from tidb_tpu.kv import backoff as kvbackoff
from tidb_tpu.cluster.mvcc import KeyIsLockedError, MvccStore
from tidb_tpu.cluster.rpc import (
    RegionError, RpcHandler, StaleEpochError,
)
from tidb_tpu.cluster.topology import Cluster
from tidb_tpu.cluster.twopc import TwoPhaseCommitter
from tidb_tpu.copr.proto import Expr, SelectRequest
from tidb_tpu.copr.xeval import supported_expr
from tidb_tpu.kv import kv
from tidb_tpu.kv.membuffer import TOMBSTONE
from tidb_tpu.kv.union_store import UnionStore
from tidb_tpu.localstore.store import VersionProvider


class DistTxn(kv.Transaction):
    """Reference: tikvTxn (store/tikv/txn.go:32)."""

    def __init__(self, store: "DistStore", start_ts: int):
        self._store = store
        self._start_ts = start_ts
        self._us = UnionStore(DistSnapshot(store, start_ts))
        self._valid = True
        self._dirty = False

    def start_ts(self) -> int:
        return self._start_ts

    def valid(self) -> bool:
        return self._valid

    def is_readonly(self) -> bool:
        return not self._dirty

    def get(self, key: bytes) -> bytes:
        self._check()
        return self._us.get(key)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        self._check()
        return self._us.iterate(start, end)

    def iterate_reverse(self, start: bytes = b"", end: bytes | None = None):
        self._check()
        return self._us.iterate_reverse(start, end)

    def dirty_iterate(self, start: bytes = b"", end: bytes | None = None):
        self._check()
        return self._us.buffer.iterate(start, end, include_tombstones=True)

    def set(self, key: bytes, value: bytes) -> None:
        self._check()
        if not value:
            raise errors.KVError("cannot set empty value")
        self._dirty = True
        self._us.set(key, value)

    def set_many(self, pairs: list[tuple[bytes, bytes]]) -> None:
        self._check()
        self._dirty = True
        self._us.set_many(pairs)

    def delete(self, key: bytes) -> None:
        self._check()
        self._dirty = True
        self._us.delete(key)

    def set_option(self, opt: str, val=True) -> None:
        self._us.set_option(opt, val)

    def del_option(self, opt: str) -> None:
        self._us.del_option(opt)

    def commit(self) -> None:
        self._check()
        self._valid = False
        if not self._dirty:
            return
        self._us.check_lazy_conditions()
        mutations: dict[bytes, bytes | None] = {}
        for k, v in self._us.buffer.iterate(include_tombstones=True):
            mutations[k] = None if v == TOMBSTONE else v
        if not mutations:
            return
        committer = TwoPhaseCommitter(self._store, self._start_ts, mutations)
        committer.execute()

    def rollback(self) -> None:
        self._check()
        self._valid = False

    def _check(self):
        if not self._valid:
            raise errors.KVError("transaction already committed or rolled back")


class DistCoprClient(kv.Client):
    """Coprocessor fan-out per region with the retry ladder
    (store/tikv/coprocessor.go CopClient)."""

    def __init__(self, store: "DistStore"):
        self.store = store
        # columnar result channel across the fan-out: with the hint set
        # each region answers a ColumnarScanResult PARTIAL instead of
        # chunk rows (copr.columnar_region). SET GLOBAL
        # tidb_tpu_columnar_scan = 0 pins every region back to the row
        # protocol — same store-level resolution contract as TpuClient.
        from tidb_tpu.sessionctx import store_bool_sysvar, store_int_sysvar
        self.columnar_scan = store_bool_sysvar(store,
                                               "tidb_tpu_columnar_scan")
        # executor-layer join routing over the fan-out's columnar planes:
        # HashJoinExec reads these (the same contract as TpuClient) so a
        # cluster-store join at/above the floor runs the device
        # build/probe kernels straight off plane-cache-pinned region
        # planes — no TpuClient install required. The TPU tier must
        # already be live in the process (HashJoinExec gates on
        # tidb_tpu.ops.client being imported); a jax-free cluster
        # deployment keeps the numpy path unconditionally.
        self.device_join = store_bool_sysvar(store, "tidb_tpu_device_join")
        self.dispatch_floor_rows = store_int_sysvar(
            store, "tidb_tpu_dispatch_floor")
        # dictionary execution tier: the same executor-layer contract as
        # device_join — HashJoinExec reads these off the store client so
        # string/multi-key joins over the fan-out's columnar planes ride
        # composite key-tuple codes (kill switch + NDV ratio gate)
        from tidb_tpu.sessionctx import store_float_sysvar
        self.device_dict = store_bool_sysvar(store, "tidb_tpu_device_dict")
        self.dict_max_ndv = store_float_sysvar(store,
                                               "tidb_tpu_dict_max_ndv")

    @property
    def mesh(self):
        """The process device mesh for executor-layer sharded kernels
        (mesh join probe, fused-aggregate ICI combine): present only
        when the TPU tier is already live in this process (sys.modules
        gate — a jax-free cluster deployment never imports jax to
        answer this) and the mesh tier is on (SET GLOBAL
        tidb_tpu_mesh). A 1-device rig answers a 1-shard mesh — the
        same code path, no collectives."""
        import sys
        if "tidb_tpu.ops.client" not in sys.modules:
            return None
        try:
            from tidb_tpu.ops import mesh as mesh_mod
        except ImportError:   # retryable-ok: routing probe, not a retry
            return None
        return mesh_mod.get_mesh()

    def support_request_type(self, req_type: int, sub_type) -> bool:
        if req_type not in (kv.REQ_TYPE_SELECT, kv.REQ_TYPE_INDEX):
            return False
        if isinstance(sub_type, Expr):
            return supported_expr(sub_type)
        return sub_type in (kv.REQ_SUB_TYPE_BASIC, kv.REQ_SUB_TYPE_DESC,
                            kv.REQ_SUB_TYPE_GROUP_BY, kv.REQ_SUB_TYPE_TOPN)

    def send(self, req: kv.Request) -> kv.Response:
        sel: SelectRequest = req.data
        if getattr(sel, "columnar_hint", False) and not self.columnar_scan:
            # kill switch: strip the hint so every region answers rows —
            # on a COPY, the executor's request object is not ours to edit
            import dataclasses
            sel = dataclasses.replace(sel, columnar_hint=False)
        ranges = list(req.key_ranges)
        desc = bool(req.desc or sel.desc)
        # buildCopTasks (store/tikv/coprocessor.go:216): pre-split each
        # range into per-REGION segments so the worker pool fans out one
        # task per region instead of one per client range (a whole-table
        # scan is ONE range — without the split it would serve all
        # regions sequentially). Region boundaries may go stale between
        # split and execution; each task's worklist re-resolves per
        # attempt, so a mid-scan split/merge only changes how many
        # partials a task emits, never their combined coverage.
        import time as _time

        from tidb_tpu import tracing
        build_t0 = _time.perf_counter_ns()
        ranges_split = []
        for rg in ranges:
            for _region, lo, hi in self.store.cache.split_range_by_region(
                    rg.start, rg.end):
                ranges_split.append(kv.KeyRange(lo, hi))
        # per-range results still come back low→high per region; the desc
        # ordering applies across tasks
        if desc:
            ranges_split = list(reversed(ranges_split))
        # tracing: one region_task span per task (NOOP when untraced),
        # created at BUILD time so queue wait (build → worker pickup) is
        # attributable; workers attach their span so the region-side
        # engine's pack/filter/topn spans nest under the right task
        parent = tracing.current()
        parent.set("task_build_us",
                   (_time.perf_counter_ns() - build_t0) / 1e3)
        parent.set("tasks", len(ranges_split))
        tasks = [(rg, parent.child("region_task").set("task", i))
                 for i, rg in enumerate(ranges_split)]
        complete_seq = __import__("itertools").count()
        # the statement's Backoffer (unified budget + deadline) crosses
        # onto the fan-out worker threads here: every per-task ladder
        # sleeps against the SAME budget, and hang-style faults inside a
        # worker observe the statement deadline
        stmt_bo = kvbackoff.current()

        def run(task):
            rg, sp = task
            if not sp.is_noop:
                sp.set("queue_us",
                       (_time.perf_counter_ns() - sp.start_ns) / 1e3)
                # the span was built on the statement thread; re-stamp
                # it with the EXECUTING thread so the trace-event export
                # shows real worker lanes
                sp.tid = __import__("threading").get_ident()
            run_t0 = _time.perf_counter_ns()
            tok = tracing.attach(sp)
            bo_tok = kvbackoff.attach(stmt_bo) \
                if stmt_bo is not None else None
            try:
                out = self._exec_range(rg, sel, sp)
            finally:
                if stmt_bo is not None:
                    kvbackoff.detach(bo_tok)
                tracing.detach(tok)
            if not sp.is_noop:
                sp.set("run_us", (_time.perf_counter_ns() - run_t0) / 1e3)
                # mid-scan split/merge re-emits one partial per region
                # segment the worklist served — visible here
                sp.set("segments", len(out))
                sp.set("complete_seq", next(complete_seq))
                sp.finish()
            return list(reversed(out)) if desc else out

        concurrency = max(1, getattr(req, "concurrency", 1) or 1)
        if len(tasks) <= 1 or concurrency <= 1:
            responses = []
            for task in tasks:
                responses.extend(run(task))
            return _ListResponse(responses)
        # copIterator (store/tikv/coprocessor.go:305): worker threads fan
        # out per task, results stream back IN TASK ORDER so keep_order
        # scans stay sorted while later regions fetch in the background.
        # Scalar-aggregate responses whose FINAL merge is provably
        # arrival-order independent stream in COMPLETION order instead —
        # the consumer never waits on a straggler region it doesn't need
        # first ("region order only when the consumer needs sorted rows")
        ordered = bool(req.keep_order
                       or not _commutative_scalar_agg(sel))
        return _PipelinedResponse(tasks, run,
                                  min(concurrency, len(tasks)),
                                  ordered=ordered)

    def _exec_range(self, rg: kv.KeyRange, sel: SelectRequest, span=None):
        """Worklist execution of one key range: each step serves the prefix
        owned by the current region, re-splitting whenever the cache learns
        a new region shape (rebuildCurrentTask, coprocessor.go:500). The
        clipped segment is recomputed every attempt so a success always
        served exactly [cursor, seg_end) — the server's epoch check
        guarantees the cached bounds matched. `span`, when given, counts
        the ladder's retries per error kind (mid-scan split/merge shows
        up as retry_stale_epoch/retry_region_miss plus extra segments)."""
        from tidb_tpu import tracing
        from tidb_tpu.cluster.rpc import (
            NotLeaderError, RegionCtx, ServerIsBusyError,
        )
        if span is None:
            span = tracing.NOOP
        # the statement's ambient Backoffer (attached onto this worker by
        # send()'s run()): every task of the fan-out sleeps against ONE
        # budget/deadline instead of a private 2-second ladder each
        bo = kvbackoff.current_or()
        out = []
        cursor, end = rg.start, rg.end

        def retried(kind: str) -> None:
            span.inc("retries")
            span.inc(f"retry_{kind}")

        while True:
            bo.check_deadline("copr worklist")
            if failpoint._active:
                failpoint.eval("copr/worklist")
            if end is not None and cursor >= end:
                return out
            region = self.store.cache.locate(cursor)
            seg_end = region.end if end is None else (
                end if region.end is None else min(region.end, end))
            ctx = RegionCtx(region.region_id, region.epoch(),
                            region.leader_store_id)
            try:
                resp = self.store.rpc.cop_request(
                    ctx, sel, [kv.KeyRange(cursor, seg_end)], sel.start_ts)
            except NotLeaderError as e:
                self.store.cache.on_not_leader(e)
                retried("not_leader")
                bo.backoff("rpc", e)
                continue
            except StaleEpochError as e:
                self.store.cache.on_stale(e)
                retried("stale_epoch")
                bo.backoff("region_miss", e)
                continue
            except ServerIsBusyError as e:
                retried("server_busy")
                bo.backoff("server_busy", e)
                continue
            except RegionError as e:
                self.store.cache.invalidate(region.region_id)
                retried("region_miss")
                bo.backoff("region_miss", e)
                continue
            except KeyIsLockedError as e:
                cleared = self.store.resolver.resolve([e.lock], bo)
                if not cleared:
                    bo.backoff("txn_lock", e)
                retried("lock")
                continue
            out.append(resp)
            if seg_end is None or seg_end == end:
                return out
            cursor = seg_end


def _commutative_scalar_agg(sel: SelectRequest) -> bool:
    """True only for no-group-by aggregate requests whose FinalMode merge
    cannot observe partial ARRIVAL order: COUNT, and SUM/AVG/MIN/MAX over
    integer columns. Everything else stays in task order — float partial
    sums re-associate the rounding sequence; MIN/MAX keep the FIRST-SEEN
    value on compare-equal ties, so kinds with distinct-but-equal
    representations (-0.0 vs 0.0 floats, decimal scales 1.0 vs 1.00,
    *_ci strings) are order-sensitive too; first_row keeps the first
    partial seen; group_concat appends buffers in arrival order; and
    distinct merges are kept conservative."""
    from tidb_tpu import mysqldef as my
    from tidb_tpu.copr.proto import ExprType
    if not sel.aggregates or sel.group_by or sel.having is not None:
        return False
    src = sel.table_info if sel.table_info is not None else sel.index_info
    cols = {c.column_id: c for c in src.columns} if src is not None else {}
    for e in sel.aggregates:
        if e.distinct:
            return False
        if e.tp == ExprType.AGG_COUNT:
            continue
        if e.tp in (ExprType.AGG_SUM, ExprType.AGG_AVG,
                    ExprType.AGG_MIN, ExprType.AGG_MAX):
            arg = e.children[0] if e.children else None
            if arg is not None and arg.tp == ExprType.COLUMN_REF:
                c = cols.get(arg.val)
                if c is not None and (c.tp in my.INTEGER_TYPES
                                      or c.tp == my.TypeBit):
                    continue   # exact, representation-unique: any order
        return False
    return True


class _ListResponse(kv.Response):
    def __init__(self, responses):
        self._responses = list(responses)
        self._i = 0

    def next(self):
        if self._i >= len(self._responses):
            return None
        r = self._responses[self._i]
        self._i += 1
        return r

    def drain_all(self):
        """Every remaining partial, in task order."""
        out = self._responses[self._i:]
        self._i = len(self._responses)
        return out


class _PipelinedResponse(kv.Response):
    """Streaming fan-out over the SHARED drain pool (cluster.pool): the
    consumer receives completed task results in TASK ORDER (the reference's
    ordered copIterator.Next with its buffered channel,
    store/tikv/coprocessor.go:348) — or, with ordered=False (scalar
    aggregates, whose partials merge commutatively), in COMPLETION order
    so no consumer stalls on a straggler region. A worker error surfaces
    on next().

    No per-statement threads are spawned: tasks are SCHEDULED onto the
    process-wide bounded pool only while they sit inside the statement's
    backpressure window AND under its inflight cap (per-statement
    backpressure — a slow consumer holds results proportional to its own
    concurrency, and one statement cannot flood the shared pool past its
    distsql concurrency). Pooled tasks never block on consumer progress:
    scheduling advances from completion/consumption instead, so a parked
    consumer can never wedge a shared worker. The statement's Backoffer
    and trace span cross onto pooled workers inside run() itself."""

    def __init__(self, tasks, run, concurrency: int, ordered: bool = True):
        self._tasks = tasks
        self._run = run
        self._results: dict[int, list] = {}
        self._next_task = 0
        self._consumed = 0
        self._ordered = ordered
        self._remaining = set(range(len(tasks)))   # not yet consumed
        self._n = len(tasks)
        self._cv = threading.Condition()
        self._err: BaseException | None = None
        self._buf: list = []
        self._cursor = 0
        # backpressure: tasks are only scheduled inside a sliding window
        # ahead of the consumer, so completed-but-unconsumed results stay
        # proportional to concurrency instead of the whole region set (the
        # reference's bounded channel, coprocessor.go:317)
        self._window = max(2 * concurrency, 4)
        self._max_inflight = concurrency
        self._scheduled = 0
        self._inflight = 0
        self._abandoned = False
        from tidb_tpu.cluster.pool import get_pool
        self._pool = get_pool()
        with self._cv:
            self._schedule_locked()

    def _schedule_locked(self) -> None:
        """Push eligible tasks onto the shared pool (caller holds _cv)."""
        while (self._scheduled < self._n
               and self._err is None and not self._abandoned
               and self._inflight < self._max_inflight
               and self._scheduled < self._consumed + self._window):
            idx = self._scheduled
            self._scheduled += 1
            self._inflight += 1
            self._pool.submit(lambda idx=idx: self._run_one(idx))

    def _wait_or_deadline(self) -> None:
        """Consumer-side wait (caller holds _cv) that still honors the
        statement deadline while this fan-out's tasks sit QUEUED behind
        other statements in the shared pool — running tasks enforce the
        Backoffer themselves, but an unscheduled task has no thread to
        check it. Expiry abandons the fan-out (scheduled tasks no-op at
        pickup) and fails the statement typed."""
        bo = kvbackoff.current()
        if bo is None or bo.deadline is None:
            self._cv.wait()
            return
        self._cv.wait(timeout=0.05)
        try:
            bo.check_deadline("copr fan-out wait")
        except Exception:
            self._abandoned = True
            self._cv.notify_all()
            raise

    def _run_one(self, idx: int) -> None:
        with self._cv:
            if self._err is not None or self._abandoned:
                self._inflight -= 1
                self._cv.notify_all()
                return
        try:
            out = self._run(self._tasks[idx])
        except BaseException as e:  # retryable-ok: stored and
            # RE-RAISED on the consumer thread (next/drain_all) —
            # routed, not swallowed
            with self._cv:
                if self._err is None:
                    self._err = e
                self._inflight -= 1
                self._cv.notify_all()
            return
        with self._cv:
            self._results[idx] = out
            self._inflight -= 1
            self._schedule_locked()
            self._cv.notify_all()

    def close(self) -> None:
        """Abandon the fan-out: unscheduled tasks never reach the pool
        and scheduled ones exit at pickup instead of running for a
        consumer that stopped early (LIMIT). Idempotent."""
        with self._cv:
            self._abandoned = True
            self._cv.notify_all()

    def drain_all(self):
        """Block until every remaining task completes and return ALL
        their partials in TASK order. The backpressure window lifts for
        the duration — the consumer wants everything, so the schedule
        runs free (still under the statement's inflight cap, which IS
        its distsql concurrency); completion order does not matter
        because partials are reassembled by task index (this is how the
        columnar channel collects per-region partials concurrently while
        the stacked plane order stays the row protocol's scan order)."""
        out = self._buf[self._cursor:]
        self._buf, self._cursor = [], 0
        with self._cv:
            self._window = self._n + 1     # lift backpressure
            self._schedule_locked()
            self._cv.notify_all()
            while True:
                if self._err is not None:
                    raise self._err
                if self._abandoned or \
                        all(i in self._results for i in self._remaining):
                    break
                self._wait_or_deadline()
            for i in sorted(self._remaining):
                got = self._results.pop(i, None)
                if got is not None:   # abandoned fan-outs return what ran
                    out.extend(got)
            self._remaining.clear()
            self._next_task = self._consumed = self._n
        return out

    def next(self):
        if self._cursor < len(self._buf):
            r = self._buf[self._cursor]
            self._cursor += 1
            return r
        with self._cv:
            while True:
                if self._err is not None:
                    raise self._err
                if not self._remaining:
                    return None
                take = None
                if self._ordered:
                    if self._next_task in self._results:
                        take = self._next_task
                        self._next_task += 1
                elif self._results:
                    # completion order: dict preserves insertion order
                    take = next(iter(self._results))
                if take is not None:
                    self._buf = self._results.pop(take)
                    self._cursor = 0
                    self._remaining.discard(take)
                    self._consumed += 1
                    self._schedule_locked()  # window advanced: next tasks
                    self._cv.notify_all()
                    break
                self._wait_or_deadline()
        return self.next()


class DistStore(kv.Storage):
    def __init__(self, n_stores: int = 3, cluster: Cluster | None = None):
        self.cluster = cluster or Cluster(n_stores)
        self.mvcc = MvccStore()
        self.rpc = RpcHandler(self.cluster, self.mvcc)
        self.rpc.oldest_active_ts_fn = self.oldest_active_ts
        self.cache = RegionCache(self.cluster)
        self.sender = RegionRequestSender(self.cache, self.rpc)
        self.resolver = LockResolver(self.sender, self.rpc)
        self.oracle = VersionProvider()
        self._client: kv.Client | None = None
        self._commit_log_lock = threading.Lock()
        # live readers — GC clamps to the oldest (see kv.ActiveReads)
        self._active_reads = kv.ActiveReads()

    def begin(self) -> kv.Transaction:
        txn = DistTxn(self, self.oracle.current_version())
        self._active_reads.add(txn)
        return txn

    def get_snapshot(self, version: int | None = None) -> kv.Snapshot:
        snap = DistSnapshot(self, version if version is not None
                            else self.oracle.current_version())
        self._active_reads.add(snap)
        return snap

    def oldest_active_ts(self) -> int | None:
        return self._active_reads.oldest()

    def get_client(self) -> kv.Client:
        if self._client is None:
            self._client = DistCoprClient(self)
        return self._client

    def set_client(self, client: kv.Client) -> None:
        self._client = client

    def current_version(self) -> int:
        return self.oracle.current_version()

    def data_version_at(self, start_ts: int,
                        prefix: bytes | None = None) -> int:
        """Visible-data version for snapshot reads at start_ts — the TPU
        columnar cache key (splits/leader changes do NOT bump it: topology
        moves no data). With `prefix` (mvcc.table_prefix_of) only commits
        touching that table count — the per-table commit filter."""
        return self.mvcc.data_version_at(start_ts, prefix)

    def copr_cpu_client(self) -> kv.Client:
        """CPU coprocessor engine for this storage — the TpuClient's
        fallback path (region fan-out with the full retry ladder)."""
        return DistCoprClient(self)

    def uuid(self) -> str:
        return f"cluster-{id(self.cluster):x}"

    # ---- GC (store/tikv/gc_worker.go) ----

    def run_gc(self, safe_point: int | None = None) -> int:
        """Resolve pre-safepoint locks, then GC old versions per region."""
        if safe_point is None:
            safe_point = self.oracle.current_version()
        bo = Backoffer()
        locks = self.mvcc.scan_locks(safe_point)
        if locks:
            self.resolver.resolve(locks, bo)
        removed = 0
        for region in list(self.cluster.regions):
            key = region.start
            removed += self.sender.send(
                key, lambda ctx, r: self.rpc.kv_gc(ctx, safe_point), bo)
        return removed


class ClusterDriver(kv.Driver):
    """URL scheme: cluster://<n_stores> (default 3)."""

    def open(self, path: str) -> kv.Storage:
        n = 3
        part = path.split("/")[0] if path else ""
        if part.isdigit():
            n = int(part)
        return DistStore(n_stores=n)
