"""tidb_tpu — a TPU-native distributed SQL engine.

Capability target: the 2016-era TiDB beta at /root/reference (MySQL-compatible
frontend, cost-based planner with coprocessor pushdown, MVCC transactions over a
KV core, online schema change).  The coprocessor execution tier is rebuilt for
TPUs: eligible scan/filter/projection/aggregation subtrees are routed to a
JAX columnar engine (``tidb_tpu.ops``) instead of a row-at-a-time interpreter,
with per-region partial aggregates combined via collectives over a device mesh
(``tidb_tpu.parallel``).

Layer map (mirrors SURVEY.md §1; reference files cited per-module):

  session.py      — Parse/Compile/runStmt, txn lifecycle   (ref: tidb.go, session.go)
  parser/ sqlast/ — SQL frontend                            (ref: parser/, ast/)
  plan/           — logical/physical planner + pushdown     (ref: plan/)
  executor/       — volcano operators + distsql executors   (ref: executor/)
  distsql/        — coprocessor request/result framework    (ref: distsql/)
  copr/           — coprocessor protocol + CPU xeval        (ref: distsql/xeval,
                                                             store/localstore/local_region.go)
  ops/            — TPU columnar coprocessor (JAX/Pallas)   (new: the north star)
  parallel/       — device mesh, sharded scan, psum combine (new)
  kv/ localstore/ — txn KV abstraction + MVCC store         (ref: kv/, store/localstore/)
  model/ meta/ table/ tablecodec/ — schema & row codec      (ref: model/, meta/, table/)
  types/ codec/   — Datum values, order-preserving codec    (ref: util/types, util/codec)
"""

__version__ = "0.1.0"
