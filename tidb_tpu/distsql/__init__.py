"""distsql: the executor-side coprocessor request/result framework.

Reference: distsql/distsql.go — Select() (:277) wraps kv.Client.Send into a
SelectResult (:43): an iterator over per-region partial results, each
decoding codec-encoded chunk rows back into typed Datums
(partialResult.Next :192, getChunk :253, FieldTypeFromPBColumn :362).
"""

from __future__ import annotations

from tidb_tpu import errors
from tidb_tpu.copr.proto import SelectRequest, SelectResponse, iter_response_rows
from tidb_tpu.kv import kv
from tidb_tpu.types import Datum
from tidb_tpu.types.convert import unflatten_datum
from tidb_tpu.types.field_type import FieldType


class SelectResult:
    """Iterates (handle, typed row) across all regions of one request."""

    def __init__(self, resp: kv.Response, field_types: list[FieldType]):
        self._resp = resp
        self._types = field_types
        self._rows = iter(())
        self._done = False

    def __iter__(self):
        return self

    def close(self) -> None:
        self._resp.close()

    def __next__(self):
        while True:
            for handle, datums in self._rows:
                return handle, self._decode(datums)
            if self._done:
                raise StopIteration
            part = self._resp.next()
            if part is None:
                self._done = True
                raise StopIteration
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            self._rows = iter_response_rows(part)

    def _decode(self, datums: list[Datum]) -> list[Datum]:
        if len(datums) != len(self._types):
            raise errors.ExecError(
                f"coprocessor row has {len(datums)} columns, "
                f"schema wants {len(self._types)}")
        return [unflatten_datum(d, ft) for d, ft in zip(datums, self._types)]

    def partials(self):
        """Yield one region's SelectResponse per call (for partial-aware
        consumers like the final aggregator)."""
        while True:
            part = self._resp.next()
            if part is None:
                return
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            yield part


def select(client: kv.Client, req: SelectRequest,
           key_ranges: list[kv.KeyRange], field_types: list[FieldType],
           concurrency: int = 10, keep_order: bool = False,
           req_type: int = kv.REQ_TYPE_SELECT) -> SelectResult:
    """Reference: distsql.Select (distsql/distsql.go:277)."""
    import time as _time
    from tidb_tpu import metrics
    kreq = kv.Request(tp=req_type, data=req, key_ranges=key_ranges,
                      keep_order=keep_order, desc=req.desc,
                      concurrency=concurrency)
    kind = "index" if req_type == kv.REQ_TYPE_INDEX else "select"
    metrics.counter(f"distsql.queries.{kind}").inc()
    t0 = _time.perf_counter()
    try:
        resp = client.send(kreq)
    except Exception:
        metrics.counter("distsql.errors").inc()
        raise
    metrics.histogram("distsql.send_seconds").observe(
        _time.perf_counter() - t0)
    return SelectResult(resp, field_types)
