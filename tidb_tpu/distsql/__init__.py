"""distsql: the executor-side coprocessor request/result framework.

Reference: distsql/distsql.go — Select() (:277) wraps kv.Client.Send into a
SelectResult (:43): an iterator over per-region partial results, each
decoding codec-encoded chunk rows back into typed Datums
(partialResult.Next :192, getChunk :253, FieldTypeFromPBColumn :362).
"""

from __future__ import annotations

import threading

from tidb_tpu import errors
from tidb_tpu.copr.proto import SelectRequest, SelectResponse, iter_response_rows
from tidb_tpu.kv import kv
from tidb_tpu.types import Datum
from tidb_tpu.types.convert import unflatten_datum
from tidb_tpu.types.field_type import FieldType

# monotonic per-THREAD columnar counts: connections execute statements on
# their own threads, so deltas of these attribute hits/fallbacks to the
# right statement in the slow-query log (the process-global metrics
# counters stay authoritative for SHOW STATUS / bench)
_thread_columnar = threading.local()


def thread_columnar_counts() -> tuple[int, int]:
    """(hits, fallbacks) tallied on this thread so far — snapshot before
    a statement and diff after."""
    return (getattr(_thread_columnar, "hits", 0),
            getattr(_thread_columnar, "fallbacks", 0))


class SelectResult:
    """Iterates (handle, typed row) across all regions of one request.

    Plane-aware consumers ask columnar() FIRST: a single-partial response
    carrying a columnar payload (TpuClient answering a columnar_hint
    request) hands the scan's planes over without any row ever being
    encoded or decoded; everything else falls back to the row iterator.
    """

    def __init__(self, resp: kv.Response, field_types: list[FieldType],
                 columnar_hinted: bool = False):
        self._resp = resp
        self._types = field_types
        self._rows = iter(())
        self._done = False
        self._hinted = columnar_hinted
        self._decode_info = None

    def __iter__(self):
        return self

    def close(self) -> None:
        self._resp.close()

    def columnar(self):
        """The response's columnar plane payload (ops.columnar.
        ColumnarScanResult), or None — rows then flow through the
        iterator as usual. Counts distsql.columnar_hits /
        distsql.columnar_fallbacks (a fallback is a hinted request the
        responder answered with rows: CPU engine, below-floor route,
        kill switch)."""
        from tidb_tpu import metrics
        if not self._done:
            part = self._resp.next()
            if part is None:
                self._done = True
            elif part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            else:
                payload = getattr(part, "columnar", None)
                if payload is not None:
                    # single-partial contract: the TPU engine answers one
                    # response per request, and only it emits payloads
                    self._done = True
                    metrics.counter("distsql.columnar_hits").inc()
                    _thread_columnar.hits = getattr(
                        _thread_columnar, "hits", 0) + 1
                    return payload
                self._rows = iter_response_rows(part)
        if self._hinted:
            metrics.counter("distsql.columnar_fallbacks").inc()
            _thread_columnar.fallbacks = getattr(
                _thread_columnar, "fallbacks", 0) + 1
        return None

    def __next__(self):
        while True:
            for handle, datums in self._rows:
                return handle, self._decode(datums)
            if self._done:
                raise StopIteration
            part = self._resp.next()
            if part is None:
                self._done = True
                raise StopIteration
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            self._rows = iter_response_rows(part)

    def _decode(self, datums: list[Datum]) -> list[Datum]:
        if len(datums) != len(self._types):
            raise errors.ExecError(
                f"coprocessor row has {len(datums)} columns, "
                f"schema wants {len(self._types)}")
        info = self._decode_info
        if info is None:
            from tidb_tpu.types.convert import unflatten_identity_kinds
            info = self._decode_info = [
                (ft, unflatten_identity_kinds(ft)) for ft in self._types]
        # identity fast path: most cells arrive already in their column's
        # final kind — skip the per-cell unflatten call for those
        return [d if d.kind in idk else unflatten_datum(d, ft)
                for d, (ft, idk) in zip(datums, info)]

    def partials(self):
        """Yield one region's SelectResponse per call (for partial-aware
        consumers like the final aggregator)."""
        while True:
            part = self._resp.next()
            if part is None:
                return
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            yield part


def select(client: kv.Client, req: SelectRequest,
           key_ranges: list[kv.KeyRange], field_types: list[FieldType],
           concurrency: int = 10, keep_order: bool = False,
           req_type: int = kv.REQ_TYPE_SELECT) -> SelectResult:
    """Reference: distsql.Select (distsql/distsql.go:277)."""
    import time as _time
    from tidb_tpu import metrics
    kreq = kv.Request(tp=req_type, data=req, key_ranges=key_ranges,
                      keep_order=keep_order, desc=req.desc,
                      concurrency=concurrency)
    kind = "index" if req_type == kv.REQ_TYPE_INDEX else "select"
    metrics.counter(f"distsql.queries.{kind}").inc()
    t0 = _time.perf_counter()
    try:
        resp = client.send(kreq)
    except Exception:
        metrics.counter("distsql.errors").inc()
        raise
    metrics.histogram("distsql.send_seconds").observe(
        _time.perf_counter() - t0)
    return SelectResult(resp, field_types,
                        columnar_hinted=getattr(req, "columnar_hint", False))
