"""distsql: the executor-side coprocessor request/result framework.

Reference: distsql/distsql.go — Select() (:277) wraps kv.Client.Send into a
SelectResult (:43): an iterator over per-region partial results, each
decoding codec-encoded chunk rows back into typed Datums
(partialResult.Next :192, getChunk :253, FieldTypeFromPBColumn :362).
"""

from __future__ import annotations

import threading

from tidb_tpu import errors
from tidb_tpu.copr.proto import SelectRequest, SelectResponse, iter_response_rows
from tidb_tpu.kv import kv
from tidb_tpu.types import Datum
from tidb_tpu.types.convert import unflatten_datum
from tidb_tpu.types.field_type import FieldType

# monotonic per-THREAD columnar counts: connections execute statements on
# their own threads, so deltas of these attribute hits/fallbacks to the
# right statement in the slow-query log (the process-global metrics
# counters stay authoritative for SHOW STATUS / bench). Counting is per
# PARTIAL, not per request: a multi-region response where some regions
# answered planes and some fell back to rows shows BOTH sides in the
# same statement's tallies.
_thread_columnar = threading.local()


def thread_columnar_counts() -> tuple[int, int, int]:
    """(hits, fallbacks, partials) tallied on this thread so far —
    snapshot before a statement and diff after. hits/fallbacks count
    per PARTIAL which channel answered; partials counts the region
    partials of fully-columnar responses (1 for in-proc single-partial
    responses, ≥ the region count across a cluster fan-out)."""
    return (getattr(_thread_columnar, "hits", 0),
            getattr(_thread_columnar, "fallbacks", 0),
            getattr(_thread_columnar, "partials", 0))


def _count(attr: str, n: int, span=None) -> None:
    if n:
        from tidb_tpu import metrics
        metrics.counter(f"distsql.columnar_{attr}").inc(n)
        setattr(_thread_columnar, attr,
                getattr(_thread_columnar, attr, 0) + n)
        if span is not None:
            span.inc(f"columnar_{attr}", n)


def _count_plane_cache(payload, span) -> None:
    """Roll one columnar partial's plane-cache attribution (hit/miss/
    eviction/invalidation counts the region recorded on the response)
    into the STATEMENT thread's monotonic tallies — the fan-out packs on
    worker threads, so the cache site itself cannot attribute to the
    statement; process metrics count at the cache and stay exact."""
    info = getattr(payload, "cache_info", None)
    if not info:
        return
    from tidb_tpu import tracing
    for k, v in info.items():
        if v:
            tracing.count(f"plane_cache_{k}", v)
            span.inc(f"plane_cache_{k}", v)


class SelectResult:
    """Iterates (handle, typed row) across all regions of one request.

    Plane-aware consumers ask columnar() FIRST: a response whose partials
    all carry columnar payloads (the in-proc TpuClient's single partial,
    or one ColumnarScanResult per region of a cluster fan-out) hands the
    scan's planes over without any row ever being encoded or decoded;
    everything else falls back to the row iterator."""

    def __init__(self, resp: kv.Response, field_types: list[FieldType],
                 columnar_hinted: bool = False, span=None):
        self._resp = resp
        self._types = field_types
        self._rows = iter(())
        self._done = False
        self._hinted = columnar_hinted
        self._attribute_parts = False   # row-fallback: count per partial
        self._decode_info = None
        # the request's trace span (tracing.NOOP when untraced): per-
        # partial channel attribution and the fan-out's region-task
        # spans hang off it
        from tidb_tpu import tracing
        self.span = span if span is not None else tracing.NOOP

    def __iter__(self):
        return self

    def close(self) -> None:
        self.span.finish()
        self._resp.close()

    def columnar(self):
        """The response's columnar plane payload — a single partial's
        ops.columnar.ColumnarScanResult, or a ColumnarPartialSet stacking
        the per-region partials of a cluster fan-out — or None: rows then
        flow through the iterator as usual (including any columnar
        partials of a MIXED response, materialized from their planes).

        Counts distsql.columnar_hits / columnar_fallbacks per PARTIAL (a
        fallback is a hinted partial the region answered with rows: CPU
        engine, below-floor route, kill switch, shapes the region engine
        can't plane) and distsql.columnar_partials for fully-columnar
        responses. Region partials are collected CONCURRENTLY
        (Response.drain_all lifts the fan-out's backpressure window) and
        reassembled in task order, so the stacked row order equals the
        row protocol's scan order."""
        if self._done:
            if self._hinted:
                _count("fallbacks", 1, self.span)
            return None
        first = self._resp.next()
        if first is None:
            # zero partials (empty range set): nothing answered rows, so
            # per-partial attribution counts nothing
            self._done = True
            self.span.finish()
            return None
        if first.error:
            raise errors.ExecError(f"coprocessor error: {first.error}")
        if getattr(first, "columnar", None) is None:
            # row-protocol first partial (CPU engine, below-floor route,
            # kill switch): keep PR-2's STREAMING row path — remaining
            # partials arrive one per __next__ fetch under the fan-out's
            # bounded window (and close() can still abandon workers on
            # an early LIMIT); __next__ attributes those per partial
            if self._hinted:
                _count("fallbacks", 1, self.span)
                self._attribute_parts = True
            self._rows = iter_response_rows(first)
            return None
        # columnar first partial: the consumer wants planes, which need
        # the full region set — drain the rest concurrently (the window
        # lifts) and stack in task order
        drain = getattr(self._resp, "drain_all", None)
        parts = [first] + (drain() if drain is not None else
                           list(iter(self._resp.next, None)))
        self._done = True
        self.span.finish()
        for part in parts:
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
        payloads = [getattr(p, "columnar", None) for p in parts]
        for p in payloads:
            if p is not None:
                _count_plane_cache(p, self.span)
        n_col = sum(1 for p in payloads if p is not None)
        _count("hits", n_col, self.span)
        n_states = sum(1 for p in payloads
                       if getattr(p, "is_agg_states", False))
        if n_states:
            # pushed-down aggregates answered as grouped partial STATES
            # (ColumnarAggStates) instead of partial rows — counted so
            # the bench/tests can assert states, not rows, crossed the
            # wire
            _count("states", n_states, self.span)
            # states partials whose aggregate arguments are EXPRESSIONS
            # (arg-plane programs evaluated inside the states dispatch,
            # PR 18) — counted so the bench/tests can assert the real-q1
            # shape rode the fused arg-plane path, not the row protocol
            from tidb_tpu.copr.proto import ExprType as _ET
            _count("arg_planes",
                   sum(1 for p in payloads
                       if getattr(p, "is_agg_states", False)
                       and any(e.children
                               and e.children[0].tp == _ET.OPERATOR
                               for e in (getattr(p, "_aggregates", None)
                                         or ()))), self.span)
            # regions that deferred their FILTER too (the batched filter
            # channel) — counted before the finisher fulfills them, so
            # the span shows how much of the statement rode the
            # filter+states deferred pipeline
            _count("filter_deferred",
                   sum(1 for p in payloads
                       if getattr(p, "filter_pending", None) is not None
                       and p.filter_pending()), self.span)
            # statement-level finisher of the near-data channel: regions
            # shipped their states PENDING; fulfill all of them from one
            # batched segmented dispatch before any consumer fans out
            from tidb_tpu.copr.columnar_region import finish_states_batch
            finish_states_batch(
                [p for p in payloads if getattr(p, "is_agg_states", False)])
        if n_col == len(parts):
            _count("partials", n_col, self.span)
            if n_col == 1:
                return payloads[0]
            if n_states == n_col:
                from tidb_tpu.ops.columnar import ColumnarStatesSet
                return ColumnarStatesSet(payloads)
            if n_states:
                # states and scan planes in one response cannot stack —
                # the row iterator serves everything (states materialize
                # their exact partial rows)
                import itertools
                self._rows = itertools.chain.from_iterable(
                    iter_response_rows(p) for p in parts)
                return None
            from tidb_tpu.ops.columnar import ColumnarPartialSet
            return ColumnarPartialSet(payloads)
        # MIXED response (some regions columnar, some row-fallback): the
        # row iterator serves everything — columnar partials materialize
        # from their planes; attribution stays per partial
        if self._hinted:
            _count("fallbacks", len(parts) - n_col, self.span)
        import itertools
        self._rows = itertools.chain.from_iterable(
            iter_response_rows(p) for p in parts)
        return None

    def __next__(self):
        while True:
            for handle, datums in self._rows:
                return handle, self._decode(datums)
            if self._done:
                raise StopIteration
            part = self._resp.next()
            if part is None:
                self._done = True
                self.span.finish()
                raise StopIteration
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            if self._attribute_parts:
                # columnar() fell back on a row-answered first partial;
                # later partials stream through here — keep the
                # per-PARTIAL channel attribution as they arrive
                payload = getattr(part, "columnar", None)
                _count("fallbacks" if payload is None else "hits", 1,
                       self.span)
                if payload is not None:
                    _count_plane_cache(payload, self.span)
            self._rows = iter_response_rows(part)

    def _decode(self, datums: list[Datum]) -> list[Datum]:
        if len(datums) != len(self._types):
            raise errors.ExecError(
                f"coprocessor row has {len(datums)} columns, "
                f"schema wants {len(self._types)}")
        info = self._decode_info
        if info is None:
            from tidb_tpu.types.convert import unflatten_identity_kinds
            info = self._decode_info = [
                (ft, unflatten_identity_kinds(ft)) for ft in self._types]
        # identity fast path: most cells arrive already in their column's
        # final kind — skip the per-cell unflatten call for those
        return [d if d.kind in idk else unflatten_datum(d, ft)
                for d, (ft, idk) in zip(datums, info)]

    def partials(self):
        """Yield one region's SelectResponse per call (for partial-aware
        consumers like the final aggregator)."""
        while True:
            part = self._resp.next()
            if part is None:
                return
            if part.error:
                raise errors.ExecError(f"coprocessor error: {part.error}")
            yield part


def select(client: kv.Client, req: SelectRequest,
           key_ranges: list[kv.KeyRange], field_types: list[FieldType],
           concurrency: int = 10, keep_order: bool = False,
           req_type: int = kv.REQ_TYPE_SELECT) -> SelectResult:
    """Reference: distsql.Select (distsql/distsql.go:277)."""
    import time as _time
    from tidb_tpu import metrics, tracing
    kreq = kv.Request(tp=req_type, data=req, key_ranges=key_ranges,
                      keep_order=keep_order, desc=req.desc,
                      concurrency=concurrency)
    kind = "index" if req_type == kv.REQ_TYPE_INDEX else "select"
    metrics.counter(f"distsql.queries.{kind}").inc()
    # the request's copr span: the fan-out client hangs per-region task
    # spans off it (worker threads attach it explicitly), the in-proc
    # engines hang kernel spans; it finishes when the result drains.
    # NOOP when the statement is untraced — one thread-local read.
    span = tracing.current().child("copr") \
        .set("kind", kind).set("ranges", len(key_ranges)) \
        .set("columnar_hint", bool(getattr(req, "columnar_hint", False)))
    t0 = _time.perf_counter()
    tok = tracing.attach(span)
    try:
        resp = client.send(kreq)
    except Exception:
        metrics.counter("distsql.errors").inc()
        raise
    finally:
        tracing.detach(tok)
    metrics.histogram("distsql.send_seconds").observe(
        _time.perf_counter() - t0)
    return SelectResult(resp, field_types,
                        columnar_hinted=getattr(req, "columnar_hint", False),
                        span=span)
