"""Failpoint fault injection: a process-wide, thread-safe registry of
named fault sites threaded through every boundary of the coprocessor
path.

Reference: the reference hardens its storage tier with gofail-style
failpoints (`// gofail:` markers compiled into injectable sites) and
exercises the client retry ladder with them; here the same idea is a
plain registry — production code calls `failpoint.eval("site/name")` at
each seam, which is a no-op (one global dict truth-test) until a test or
operator enables that name.

Catalog discipline: a site name is `<layer>/<fault>` (e.g.
`rpc/server_busy`, `device/readback`). The call site supplies the
default exception factory, so an injected `rpc/stale_epoch` raises a
REAL StaleEpochError carrying the server's current region — the ladder
being tested cannot tell injection from nature. See README "Robustness"
for the full catalog.

Trigger policies (per enabled failpoint):

* ``always``          — every evaluation fires
* ``("every", n)``    — every n-th evaluation fires (n, 2n, …)
* ``("first", n)``    — the first n evaluations fire, then never again
* ``("prob", p)``     — each evaluation fires with probability p, from a
                        PER-FAILPOINT ``random.Random(seed)`` so chaos
                        schedules replay exactly

Actions:

* ``error``  — raise: `exc` (instance, class, or zero-arg callable), else
               the call site's `default_exc`, else FailpointError
* ``sleep``  — block `seconds` then continue
* ``hang``   — block until `release(name)` / `disable(name)`; while
               hanging, the AMBIENT statement deadline (kv.backoff) is
               honored: a hung statement under `tidb_tpu_max_execution_time`
               fails with DeadlineExceededError instead of wedging
* ``return`` — eval returns `value` (sites use this for data-shape
               faults: corrupt-partial row drops, cache-admission drops)

Disabled-path cost: `eval()` is one module-global load and truth test —
the zero-failpoint bench figures must be indistinguishable from a build
without the framework.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_active: dict[str, "_Failpoint"] = {}


class FailpointError(Exception):
    """Default injected error when neither the enable() nor the call site
    supplied a typed one."""


class _Failpoint:
    __slots__ = ("name", "action", "exc", "value", "seconds", "when",
                 "rng", "evals", "triggers", "release_event")

    def __init__(self, name: str, action: str, exc, value, seconds: float,
                 when, seed):
        if action not in ("error", "sleep", "hang", "return"):
            raise ValueError(f"unknown failpoint action {action!r}")
        norm = ("always",) if when == "always" else tuple(when)
        if norm[0] not in ("always", "every", "first", "prob"):
            raise ValueError(f"unknown failpoint policy {when!r}")
        self.name = name
        self.action = action
        self.exc = exc
        self.value = value
        self.seconds = seconds
        self.when = norm
        self.rng = random.Random(seed)
        self.evals = 0
        self.triggers = 0
        self.release_event = threading.Event()

    def should_fire(self) -> bool:
        """Policy decision for one evaluation; caller holds _lock."""
        self.evals += 1
        kind = self.when[0]
        if kind == "always":
            return True
        if kind == "every":
            return self.evals % int(self.when[1]) == 0
        if kind == "first":
            return self.evals <= int(self.when[1])
        return self.rng.random() < float(self.when[1])


def enable(name: str, action: str = "error", *, exc=None, value=None,
           seconds: float = 0.0, when="always", seed=None) -> None:
    """Enable one failpoint (replacing any previous state under `name`)."""
    fp = _Failpoint(name, action, exc, value, seconds, when, seed)
    with _lock:
        old = _active.get(name)
        if old is not None:
            old.release_event.set()   # never strand a hung thread
        _active[name] = fp


def disable(name: str) -> None:
    with _lock:
        fp = _active.pop(name, None)
    if fp is not None:
        fp.release_event.set()


def disable_all() -> None:
    with _lock:
        fps = list(_active.values())
        _active.clear()
    for fp in fps:
        fp.release_event.set()


def release(name: str) -> None:
    """Unblock threads parked on a `hang` failpoint (it stays enabled —
    later evaluations hang again on a fresh event)."""
    with _lock:
        fp = _active.get(name)
        if fp is not None:
            fp.release_event.set()
            fp.release_event = threading.Event()


def enabled(name: str) -> bool:
    return name in _active


def counters(name: str) -> dict:
    """{"evals": n, "triggers": n} for an enabled failpoint (zeros when
    not enabled) — tests assert schedules through this."""
    with _lock:
        fp = _active.get(name)
        if fp is None:
            return {"evals": 0, "triggers": 0}
        return {"evals": fp.evals, "triggers": fp.triggers}


@contextmanager
def failpoints(spec: dict):
    """Enable a schedule of failpoints for a block, disabling every one
    (and releasing any hangs) on exit:

        with failpoint.failpoints({
                "rpc/server_busy": {"when": ("first", 1)},
                "device/readback": {"action": "error"}}):
            ...
    """
    names = []
    try:
        for name, kw in spec.items():
            enable(name, **(kw if isinstance(kw, dict)
                            else {"action": kw}))
            names.append(name)
        yield
    finally:
        for name in names:
            disable(name)


def eval(name: str, default_exc=None):
    """Evaluate one fault site. Returns None when the failpoint is not
    enabled or its policy does not fire this time; `return`-action
    failpoints return their configured value; `error`/`sleep`/`hang`
    act as documented above. `default_exc` is a zero-arg callable the
    call site provides so injected errors are the REAL typed errors its
    retry ladder handles."""
    if not _active:
        return None
    with _lock:
        fp = _active.get(name)
        if fp is None or not fp.should_fire():
            return None
        fp.triggers += 1
        event = fp.release_event
    from tidb_tpu import metrics
    metrics.counter("failpoint.triggers."
                    + name.replace("/", ".")).inc()
    if fp.action == "return":
        return fp.value
    if fp.action == "sleep":
        time.sleep(fp.seconds)
        return None
    if fp.action == "hang":
        _hang(fp, event)
        return None
    exc = fp.exc
    if exc is None and default_exc is not None:
        exc = default_exc
    if exc is None:
        raise FailpointError(f"injected failpoint {name}")
    if isinstance(exc, BaseException):
        raise exc
    raise exc()


def _hang(fp: _Failpoint, event: threading.Event) -> None:
    """Block until released/disabled — but honor the ambient statement
    deadline so a hung statement fails typed-and-bounded instead of
    wedging its worker thread forever."""
    from tidb_tpu.kv import backoff as _backoff
    while not event.wait(0.02):
        if _active.get(fp.name) is not fp:
            return
        bo = _backoff.current()
        if bo is not None and bo.deadline is not None \
                and time.monotonic() >= bo.deadline:
            raise bo.deadline_error(f"failpoint {fp.name} hang")
