"""Multi-chip coprocessor execution: shard rows across a device mesh,
combine partial aggregates over ICI.

The reference's scale-out unit is the region: one coprocessor task per
region, partial aggregates merged upstream (store/tikv/coprocessor.go:305,
SURVEY §2.10 rows 1-2). The TPU-native equivalent keeps the same
partial/final algebra but moves the combine into the chip interconnect:
rows are sharded across the mesh with shard_map, every chip runs the SAME
fused filter+agg kernel on its shard, and the monoid combine (count/sum →
lax.psum, min → pmin, max → pmax) rides ICI instead of a TCP merge loop.

On real hardware the mesh axis spans physical chips; tests and the driver
dry-run span 8 virtual CPU devices (xla_force_host_platform_device_count).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tidb_tpu.ops import kernels as _kernels
from tidb_tpu.ops.exprc import Unsupported

AXIS = "copr"


def available_devices(n: int | None = None):
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return devs


class CoprMesh:
    """A 1-D mesh over which coprocessor batches are row-sharded."""

    def __init__(self, devices=None, n_devices: int | None = None):
        devices = devices or available_devices(n_devices)
        self.n = len(devices)
        self.mesh = Mesh(np.array(devices), (AXIS,))
        self._jit_cache: dict = {}

    # ------------------------------------------------------------------

    def _combined(self, fn):
        combiners = fn.combiners
        if any(c is None for c in combiners):
            raise Unsupported("aggregate not mesh-combinable")

        def local(planes, live):
            outs = fn(planes, live)
            merged = []
            for o, c in zip(outs, combiners):
                if c == "sum":
                    merged.append(jax.lax.psum(o, AXIS))
                elif c == "min":
                    merged.append(jax.lax.pmin(o, AXIS))
                else:
                    merged.append(jax.lax.pmax(o, AXIS))
            return tuple(merged)
        return local

    def _run(self, fn, planes, live):
        if live.shape[0] % self.n != 0:
            raise Unsupported(
                f"batch capacity {live.shape[0]} not divisible by mesh "
                f"size {self.n}")
        ent = self._jit_cache.get(id(fn))
        miss = ent is None or ent[0] is not fn
        if ent is None or ent[0] is not fn:
            if self.n == 1:
                # axis of one: partials are already totals — no shard_map,
                # no collectives (single-chip tunnels may only lower Sum
                # all-reduce anyway); still validates mesh-combinability
                self._combined(fn)
                sharded = lambda planes, live: tuple(fn(planes, live))
            else:
                local = self._combined(fn)
                sharded = shard_map(
                    local, mesh=self.mesh,
                    in_specs=(P(AXIS), P(AXIS)),  # rows sharded on the axis
                    out_specs=P())                # combined results replicated
            # pack combined outputs into one transfer per dtype — on
            # tunneled platforms every D2H is a full round trip
            wrapper = _kernels.pack_outputs(sharded)
            # pin fn in the entry so its id can't be reused while cached
            ent = (fn, wrapper, jax.jit(wrapper))
            self._jit_cache[id(fn)] = ent
            if len(self._jit_cache) > 256:
                self._jit_cache.pop(next(iter(self._jit_cache)))
        live_d = jnp.asarray(live)
        cap = int(live.shape[0])
        with _kernels.dispatch_serial:
            packed = np.asarray(ent[2](planes, live_d))
            _kernels.dispatch_serial.annotate(
                "mesh_run", f"{self.n}sh/{len(planes)}pl/{cap}",
                rows=cap, readback_bytes=int(packed.nbytes),
                jit_miss=miss)
        return _kernels.unpack_outputs(ent[1], packed)

    # the client calls these; signatures match the single-chip jit path
    def run_scalar(self, fn, planes, live):
        return self._run(fn, planes, live)

    def run_grouped(self, fn, planes, live):
        return self._run(fn, planes, live)

    def run_sharded(self, fn, planes, live):
        """Row-sharded execution with PER-SHARD outputs (out_specs along
        the axis, no collectives): each device computes over its row
        block and the outputs come back concatenated in shard order —
        filter masks (full row length) and per-shard top-k candidate
        sets ride this path; the host does the final (tiny) merge, the
        same split as the reference's per-region coprocessor fan-out +
        SQL-side merge (store/tikv/coprocessor.go:305)."""
        return self._run_shardmajor(("sharded", id(fn)), fn, planes, live)

    def run_states(self, fn, planes, live):
        """Per-shard grouped-STATES channel (the near-data execution
        tier, ops.mesh.region_states_sharded): identical mechanics to
        run_sharded — rows sharded over the axis, per-shard state blocks
        back shard-major, NO collectives (each region lives wholly on
        its home shard, so an all-reduce would only fold monoid
        identities) — under its own cache key so statement-signature
        states kernels and filter/top-k kernels can never collide on a
        recycled fn id. This is what lets the in-proc mesh TpuClient and
        the fan-out drain ship per-shard STATES instead of raw columnar
        rows."""
        return self._run_shardmajor(("states", id(fn)), fn, planes, live)

    def _run_shardmajor(self, key, fn, planes, live):
        if live.shape[0] % self.n != 0:
            raise Unsupported(
                f"batch capacity {live.shape[0]} not divisible by mesh "
                f"size {self.n}")
        ent = self._jit_cache.get(key)
        miss = ent is None or ent[0] is not fn
        if ent is None or ent[0] is not fn:
            if self.n == 1:
                sharded = lambda planes, live: tuple(fn(planes, live))
            else:
                sharded = shard_map(
                    lambda p, l: tuple(fn(p, l)), mesh=self.mesh,
                    in_specs=(P(AXIS), P(AXIS)),
                    out_specs=P(AXIS))   # outputs stay shard-major
            wrapper = _kernels.pack_outputs(sharded)
            ent = (fn, wrapper, jax.jit(wrapper))
            self._jit_cache[key] = ent
            if len(self._jit_cache) > 256:
                self._jit_cache.pop(next(iter(self._jit_cache)))
        live_d = jnp.asarray(live)
        cap = int(live.shape[0])
        with _kernels.dispatch_serial:
            packed = np.asarray(ent[2](planes, live_d))
            _kernels.dispatch_serial.annotate(
                f"mesh_{key[0]}", f"{self.n}sh/{len(planes)}pl/{cap}",
                rows=cap, readback_bytes=int(packed.nbytes),
                jit_miss=miss)
        return _kernels.unpack_outputs(ent[1], packed)
