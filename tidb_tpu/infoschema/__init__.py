"""Immutable schema snapshot with by-name/by-ID maps.

Reference: infoschema/infoschema.go (InfoSchema + Handle), builder.go.
Each DDL-induced version produces a fresh immutable InfoSchema; sessions pin
one for a statement's lifetime. INFORMATION_SCHEMA virtual tables attach in
the executor layer (executor/show.py) rather than as memory tables for now.
"""

from __future__ import annotations

import threading

from tidb_tpu import errors
from tidb_tpu.meta import Meta
from tidb_tpu.model import DBInfo, TableInfo
from tidb_tpu.table import Table


class InfoSchema:
    def __init__(self, version: int, dbs: list[DBInfo],
                 tables_by_db: dict[int, list[TableInfo]], store=None):
        self.version = version
        self._db_by_name: dict[str, DBInfo] = {d.name.lower(): d for d in dbs}
        self._db_by_id: dict[int, DBInfo] = {d.id: d for d in dbs}
        self._tbl_by_name: dict[tuple[str, str], Table] = {}
        self._tbl_by_id: dict[int, Table] = {}
        for db_id, tbls in tables_by_db.items():
            db = self._db_by_id[db_id]
            for ti in tbls:
                t = Table(ti, store=store, db_id=db_id)
                self._tbl_by_name[(db.name.lower(), ti.name.lower())] = t
                self._tbl_by_id[ti.id] = t
        if store is not None:
            self._attach_virtual(store)

    def _attach_virtual(self, store) -> None:
        """Virtual databases (perfschema/init.go:205,
        infoschema/tables.go); reserved negative ids keep them off the
        KV/meta paths."""
        from tidb_tpu import perfschema as ps
        db = DBInfo(id=ps.DB_ID, name="performance_schema")
        self._db_by_name[db.name] = db
        self._db_by_id[db.id] = db
        for ti in ps.table_infos():
            vt = ps.VirtualTable(ti, store)
            self._tbl_by_name[(db.name, ti.name.lower())] = vt
            self._tbl_by_id[ti.id] = vt
        from tidb_tpu.infoschema import tables as it
        idb = DBInfo(id=it.DB_ID, name="INFORMATION_SCHEMA")
        self._db_by_name[idb.name.lower()] = idb
        self._db_by_id[idb.id] = idb
        for ti in it.table_infos():
            ivt = it.InfoVirtualTable(ti, self)
            self._tbl_by_name[(idb.name.lower(), ti.name.lower())] = ivt
            self._tbl_by_id[ti.id] = ivt
        for ti in it.store_table_infos():
            svt = it.StoreVirtualTable(ti, store)
            self._tbl_by_name[(idb.name.lower(), ti.name.lower())] = svt
            self._tbl_by_id[ti.id] = svt

    # ---- lookups ----
    def schema_by_name(self, name: str) -> DBInfo | None:
        return self._db_by_name.get(name.lower())

    def schema_exists(self, name: str) -> bool:
        return name.lower() in self._db_by_name

    def table_by_name(self, db: str, table: str) -> Table:
        t = self._tbl_by_name.get((db.lower(), table.lower()))
        if t is None:
            if not self.schema_exists(db):
                raise errors.BadDBError(f"Unknown database '{db}'")
            raise errors.NoSuchTableError(f"Table '{db}.{table}' doesn't exist")
        return t

    def table_exists(self, db: str, table: str) -> bool:
        return (db.lower(), table.lower()) in self._tbl_by_name

    def table_by_id(self, tid: int) -> Table | None:
        return self._tbl_by_id.get(tid)

    def all_schema_names(self) -> list[str]:
        return [d.name for d in self._db_by_name.values()]

    def schema_tables(self, db: str) -> list[Table]:
        dbl = db.lower()
        return [t for (d, _n), t in self._tbl_by_name.items() if d == dbl]


class Handle:
    """Atomically-swapped current InfoSchema. Reference: infoschema.Handle."""

    def __init__(self, store):
        self.store = store
        self._lock = threading.Lock()
        self._schema: InfoSchema | None = None

    def get(self) -> InfoSchema:
        s = self._schema
        if s is None:
            raise errors.TiDBError("schema not loaded yet")
        return s

    def load(self) -> InfoSchema:
        """Full load from meta at the current KV version.
        Reference: domain.loadInfoSchema (domain/domain.go:50)."""
        txn = self.store.begin()
        try:
            m = Meta(txn)
            version = m.schema_version()
            dbs = m.list_databases()
            tables = {db.id: m.list_tables(db.id) for db in dbs}
        finally:
            txn.rollback()
        schema = InfoSchema(version, dbs, tables, store=self.store)
        with self._lock:
            self._schema = schema
        return schema
