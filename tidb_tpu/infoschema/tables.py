"""INFORMATION_SCHEMA virtual tables.

Reference: infoschema/tables.go — SCHEMATA (dataForSchemata :323), TABLES
(:338), COLUMNS (:371), STATISTICS (:428). Rows are synthesized from the
CURRENT schema snapshot on every read, through the same virtual-table
machinery performance_schema uses: reserved negative ids, MemTableExec,
SQL-side filtering.
"""

from __future__ import annotations

from tidb_tpu import mysqldef as my
from tidb_tpu.model import ColumnInfo, TableInfo
from tidb_tpu.table.virtual import VirtualTableBase
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import NULL
from tidb_tpu.types.field_type import FieldType

DB_ID = -200
T_SCHEMATA = -201
T_TABLES = -202
T_KEY_COLUMN_USAGE = -207
T_REFERENTIAL_CONSTRAINTS = -208
T_COLUMNS = -203
T_STATISTICS = -204
T_CHARACTER_SETS = -205
T_COLLATIONS = -206
# workload-observability tables (store-bound, not snapshot-bound):
# TOP-SQL by device time per time bucket, and region access heat
T_TPU_TOP_SQL = -210
T_TPU_HOT_REGIONS = -211
# diagnostics tier: queryable metrics (current + time series), the
# slow-statement flight recorder, and the inspection rule findings
T_TPU_METRICS = -212
T_TPU_METRICS_HISTORY = -213
T_TPU_SLOW_TRACES = -214
T_TPU_INSPECTION_RESULT = -215
# kernel-level continuous profiler: windowed per-signature roofline
T_TPU_KERNEL_PROFILE = -216


def _col(i: int, name: str, tp: int = my.TypeVarchar,
         flen: int = 64) -> ColumnInfo:
    return ColumnInfo(id=i + 1, name=name, offset=i,
                      field_type=FieldType(tp, 0, flen, -1))


def _tbl(tid: int, name: str, cols: list[tuple]) -> TableInfo:
    return TableInfo(id=tid, name=name,
                     columns=[_col(i, *c) for i, c in enumerate(cols)])


def table_infos() -> list[TableInfo]:
    return [
        _tbl(T_SCHEMATA, "SCHEMATA", [
            ("CATALOG_NAME",), ("SCHEMA_NAME",),
            ("DEFAULT_CHARACTER_SET_NAME",), ("DEFAULT_COLLATION_NAME",)]),
        _tbl(T_TABLES, "TABLES", [
            ("TABLE_CATALOG",), ("TABLE_SCHEMA",), ("TABLE_NAME",),
            ("TABLE_TYPE",), ("ENGINE",),
            ("TABLE_ROWS", my.TypeLonglong, 21),
            ("AUTO_INCREMENT", my.TypeLonglong, 21), ("TABLE_COLLATION",),
            ("TABLE_COMMENT", my.TypeVarchar, 256)]),
        _tbl(T_COLUMNS, "COLUMNS", [
            ("TABLE_CATALOG",), ("TABLE_SCHEMA",), ("TABLE_NAME",),
            ("COLUMN_NAME",), ("ORDINAL_POSITION", my.TypeLonglong, 21),
            ("COLUMN_DEFAULT",), ("IS_NULLABLE",), ("DATA_TYPE",),
            ("COLUMN_TYPE",), ("COLUMN_KEY",), ("EXTRA",),
            ("COLUMN_COMMENT", my.TypeVarchar, 256)]),
        _tbl(T_STATISTICS, "STATISTICS", [
            ("TABLE_CATALOG",), ("TABLE_SCHEMA",), ("TABLE_NAME",),
            ("NON_UNIQUE",), ("INDEX_SCHEMA",), ("INDEX_NAME",),
            ("SEQ_IN_INDEX", my.TypeLonglong, 21), ("COLUMN_NAME",),
            ("COMMENT", my.TypeVarchar, 256)]),
        _tbl(T_CHARACTER_SETS, "CHARACTER_SETS", [
            ("CHARACTER_SET_NAME",), ("DEFAULT_COLLATE_NAME",),
            ("DESCRIPTION",), ("MAXLEN", my.TypeLonglong, 21)]),
        _tbl(T_COLLATIONS, "COLLATIONS", [
            ("COLLATION_NAME",), ("CHARACTER_SET_NAME",),
            ("ID", my.TypeLonglong, 21), ("IS_DEFAULT",),
            ("IS_COMPILED",), ("SORTLEN", my.TypeLonglong, 21)]),
        # the reference registers these two but leaves them empty
        # (infoschema/tables.go:576 — empty case arms); here they carry
        # real rows from PRIMARY/UNIQUE indexes and FK metadata
        _tbl(T_KEY_COLUMN_USAGE, "KEY_COLUMN_USAGE", [
            ("CONSTRAINT_CATALOG",), ("CONSTRAINT_SCHEMA",),
            ("CONSTRAINT_NAME",), ("TABLE_CATALOG",), ("TABLE_SCHEMA",),
            ("TABLE_NAME",), ("COLUMN_NAME",),
            ("ORDINAL_POSITION", my.TypeLonglong, 21),
            ("POSITION_IN_UNIQUE_CONSTRAINT", my.TypeLonglong, 21),
            ("REFERENCED_TABLE_SCHEMA",), ("REFERENCED_TABLE_NAME",),
            ("REFERENCED_COLUMN_NAME",)]),
        _tbl(T_REFERENTIAL_CONSTRAINTS, "REFERENTIAL_CONSTRAINTS", [
            ("CONSTRAINT_CATALOG",), ("CONSTRAINT_SCHEMA",),
            ("CONSTRAINT_NAME",), ("UNIQUE_CONSTRAINT_CATALOG",),
            ("UNIQUE_CONSTRAINT_SCHEMA",), ("UNIQUE_CONSTRAINT_NAME",),
            ("MATCH_OPTION",), ("UPDATE_RULE",), ("DELETE_RULE",),
            ("TABLE_NAME",), ("REFERENCED_TABLE_NAME",)]),
    ]


def store_table_infos() -> list[TableInfo]:
    """Tables whose rows come from live STORE state (perfschema digest
    summary, cluster region heat) rather than the schema snapshot."""
    return [
        _tbl(T_TPU_TOP_SQL, "TIDB_TPU_TOP_SQL", [
            ("TIME_BUCKET_BEGIN", my.TypeLonglong, 21),
            ("TIME_BUCKET_END", my.TypeLonglong, 21),
            ("RANK", my.TypeLonglong, 21),
            ("DIGEST",), ("DIGEST_TEXT", my.TypeVarchar, 1024),
            ("EXEC_COUNT", my.TypeLonglong, 21),
            ("DEVICE_TIME_MS", my.TypeDouble, 22),
            ("KERNEL_DISPATCHES", my.TypeLonglong, 21),
            ("READBACK_BYTES", my.TypeLonglong, 21),
            ("SUM_LATENCY_MS", my.TypeDouble, 22),
            ("AVG_LATENCY_MS", my.TypeDouble, 22),
            ("ROWS_SENT", my.TypeLonglong, 21)]),
        _tbl(T_TPU_HOT_REGIONS, "TIDB_TPU_HOT_REGIONS", [
            ("RANK", my.TypeLonglong, 21),
            ("REGION_ID", my.TypeLonglong, 21),
            ("START_KEY", my.TypeVarchar, 128),
            ("END_KEY", my.TypeVarchar, 128),
            ("LEADER_STORE", my.TypeLonglong, 21),
            ("READ_ROWS", my.TypeLonglong, 21),
            ("READ_BYTES", my.TypeLonglong, 21),
            ("WRITE_ROWS", my.TypeLonglong, 21),
            ("WRITE_BYTES", my.TypeLonglong, 21),
            ("TOTAL_READ_ROWS", my.TypeLonglong, 21),
            ("TOTAL_WRITE_ROWS", my.TypeLonglong, 21),
            ("HEAT", my.TypeDouble, 22)]),
        # column names dodge lexer keywords (VALUE, TIME) so bare
        # projections parse: METRIC_VALUE / TS / ITEM_VALUE
        _tbl(T_TPU_METRICS, "TIDB_TPU_METRICS", [
            ("NAME", my.TypeVarchar, 128),
            ("TYPE", my.TypeVarchar, 16),
            ("LABELS", my.TypeVarchar, 64),
            ("METRIC_VALUE", my.TypeDouble, 22),
            ("HELP", my.TypeVarchar, 256)]),
        _tbl(T_TPU_METRICS_HISTORY, "TIDB_TPU_METRICS_HISTORY", [
            ("TS", my.TypeDouble, 22),
            ("NAME", my.TypeVarchar, 128),
            ("TYPE", my.TypeVarchar, 16),
            ("LABELS", my.TypeVarchar, 64),
            ("METRIC_VALUE", my.TypeDouble, 22),
            ("DELTA", my.TypeDouble, 22),
            ("RATE_PER_SEC", my.TypeDouble, 22)]),
        _tbl(T_TPU_SLOW_TRACES, "TIDB_TPU_SLOW_TRACES", [
            ("TS", my.TypeDouble, 22),
            ("CONN_ID", my.TypeLonglong, 21),
            ("DIGEST",),
            ("REASON", my.TypeVarchar, 32),
            ("DURATION_MS", my.TypeDouble, 22),
            ("SPAN_COUNT", my.TypeLonglong, 21),
            ("KERNEL_DISPATCHES", my.TypeLonglong, 21),
            ("READBACK_BYTES", my.TypeLonglong, 21),
            ("ERROR", my.TypeVarchar, 512),
            ("SQL_TEXT", my.TypeVarchar, 2048),
            ("TRACE_JSON", my.TypeVarchar, 1 << 20),
            ("TRACE_EVENT_JSON", my.TypeVarchar, 1 << 20)]),
        _tbl(T_TPU_INSPECTION_RESULT, "TIDB_TPU_INSPECTION_RESULT", [
            ("RULE", my.TypeVarchar, 64),
            ("ITEM", my.TypeVarchar, 64),
            ("SEVERITY", my.TypeVarchar, 16),
            ("ITEM_VALUE", my.TypeVarchar, 64),
            ("REFERENCE", my.TypeVarchar, 128),
            ("DETAILS", my.TypeVarchar, 512),
            ("WINDOW_BEGIN", my.TypeDouble, 22),
            ("WINDOW_END", my.TypeDouble, 22)]),
        _tbl(T_TPU_KERNEL_PROFILE, "TIDB_TPU_KERNEL_PROFILE", [
            ("WINDOW_BEGIN", my.TypeDouble, 22),
            ("WINDOW_END", my.TypeDouble, 22),
            ("KIND", my.TypeVarchar, 64),
            ("SIGNATURE", my.TypeVarchar, 128),
            ("DISPATCHES", my.TypeLonglong, 21),
            ("RETRACES", my.TypeLonglong, 21),
            ("DEVICE_US", my.TypeLonglong, 21),
            ("TRACE_US", my.TypeLonglong, 21),
            ("EXECUTE_US", my.TypeLonglong, 21),
            ("READBACK_BYTES", my.TypeLonglong, 21),
            ("H2D_BYTES", my.TypeLonglong, 21),
            ("PROCESSED_ROWS", my.TypeLonglong, 21),
            ("BYTES_PER_DEVICE_SEC", my.TypeDouble, 22),
            ("ROWS_PER_SEC", my.TypeDouble, 22),
            ("BOUND", my.TypeVarchar, 16)]),
    ]


_TYPE_WORDS = {"c": "counter", "g": "gauge", "h": "histogram"}


def _metrics_rows() -> list[list[Datum]]:
    """Current registry values with type/labels/help — `SELECT` replaces
    scraping /metrics and grepping. Histograms expand to count/sum/avg
    rows (LABELS carries the stat)."""
    from tidb_tpu import metrics
    from tidb_tpu.metrics import Counter, Gauge, catalog
    with metrics.registry._lock:
        items = sorted(metrics.registry._metrics.items())
    out: list[list[Datum]] = []
    for name, m in items:
        hit = catalog.lookup(name)
        help_ = hit[1] if hit is not None else ""
        if isinstance(m, (Counter, Gauge)):
            tp = "counter" if isinstance(m, Counter) else "gauge"
            # dynamic-family members split into family NAME + a kind
            # LABEL (copr.degraded_mesh → copr.degraded, kind="mesh"),
            # so GROUP BY NAME aggregates across kinds
            fam, labels = catalog.split_labels(name)
            out.append([_s(fam), _s(tp), _s(labels),
                        Datum.f64(float(m.value)), _s(help_)])
            continue
        _b, _c, total_sum, total_count = m.snapshot_buckets()
        avg = total_sum / total_count if total_count else 0.0
        for stat, v in (("count", float(total_count)),
                        ("sum", total_sum), ("avg", avg)):
            out.append([_s(name), _s("histogram"), _s(f'stat="{stat}"'),
                        Datum.f64(v), _s(help_)])
    return out


def _metrics_history_rows() -> list[list[Datum]]:
    """Time-bucketed samples with delta/rate — the recorder takes a
    fresh sample at read time when a full interval has elapsed, so a
    SELECT sees a bucket no older than the configured cadence without
    a poll loop compressing the ring."""
    from tidb_tpu.metrics import catalog, timeseries
    timeseries.recorder.sample(
        min_interval_s=timeseries.recorder.interval_s)
    out: list[list[Datum]] = []
    for ts, name, tc, v, delta, rate in timeseries.history_rows():
        fam, labels = catalog.split_labels(name)
        out.append([Datum.f64(round(ts, 3)), _s(fam),
                    _s(_TYPE_WORDS.get(tc, tc)), _s(labels), Datum.f64(v),
                    Datum.f64(round(delta, 6)) if delta is not None
                    else NULL,
                    Datum.f64(round(rate, 6)) if rate is not None
                    else NULL])
    return out


def _slow_trace_rows(store) -> list[list[Datum]]:
    from tidb_tpu import flight
    fr = flight.recorder_for(store)
    out: list[list[Datum]] = []
    for e in fr.entries():
        res = e["resources"]
        out.append([
            Datum.f64(round(e["ts"], 3)), Datum.i64(e["conn_id"]),
            _s(e["digest"]), _s(e["reason"]),
            Datum.f64(e["duration_ms"]), Datum.i64(e["span_count"]),
            Datum.i64(res.get("kernel_dispatches", 0)),
            Datum.i64(res.get("readback_bytes", 0)),
            _s(e["error"]), _s(e["sql"]), _s(flight.trace_json(e)),
            _s(flight.trace_event_json(e))])
    return out


def _kernel_profile_rows() -> list[list[Datum]]:
    from tidb_tpu import inspection, profiler
    window = int(inspection.threshold("window_samples"))
    out: list[list[Datum]] = []
    for r in profiler.profile_rows(window):
        out.append([
            Datum.f64(round(r["window_begin"], 3)),
            Datum.f64(round(r["window_end"], 3)),
            _s(r["kind"]), _s(r["signature"]),
            Datum.i64(r["dispatches"]), Datum.i64(r["retraces"]),
            Datum.i64(r["device_us"]), Datum.i64(r["trace_us"]),
            Datum.i64(r["execute_us"]),
            Datum.i64(r["readback_bytes"]), Datum.i64(r["h2d_bytes"]),
            Datum.i64(r["rows"]),
            Datum.f64(round(r["bytes_per_device_sec"], 3)),
            Datum.f64(round(r["rows_per_sec"], 3)),
            _s(r["bound"])])
    return out


def _inspection_rows() -> list[list[Datum]]:
    from tidb_tpu import inspection
    out: list[list[Datum]] = []
    for r in inspection.inspect():
        out.append([
            _s(r["rule"]), _s(r["item"]), _s(r["severity"]),
            _s(str(r["value"])), _s(r["reference"]), _s(r["details"]),
            Datum.f64(round(r["window_begin"], 3)),
            Datum.f64(round(r["window_end"], 3))])
    return out


def rows_for_store(store, table_id: int) -> list[list[Datum]]:
    """Synthesize one store-bound table's rows from live store state."""
    if table_id == T_TPU_METRICS:
        return _metrics_rows()
    if table_id == T_TPU_METRICS_HISTORY:
        return _metrics_history_rows()
    if table_id == T_TPU_SLOW_TRACES:
        return _slow_trace_rows(store)
    if table_id == T_TPU_INSPECTION_RESULT:
        return _inspection_rows()
    if table_id == T_TPU_KERNEL_PROFILE:
        return _kernel_profile_rows()
    if table_id == T_TPU_TOP_SQL:
        from tidb_tpu import perfschema as ps
        out: list[list[Datum]] = []
        for begin, end, entries, _ed, _ee in \
                ps.perf_for(store).digest_summary.windows():
            ranked = sorted(entries.values(),
                            key=lambda e: (-e.device_time_us(),
                                           -e.sum_latency_ms, e.digest))
            for rank, e in enumerate(ranked[:32], start=1):
                out.append([
                    Datum.i64(int(begin)),
                    Datum.i64(int(end)) if end is not None else NULL,
                    Datum.i64(rank), _s(e.digest),
                    _s(e.norm_sql[:1024]), Datum.i64(e.exec_count),
                    Datum.f64(round(e.device_time_us() / 1e3, 3)),
                    Datum.i64(e.res.get("kernel_dispatches", 0)),
                    Datum.i64(e.res.get("readback_bytes", 0)),
                    Datum.f64(round(e.sum_latency_ms, 3)),
                    Datum.f64(round(e.sum_latency_ms
                                    / max(e.exec_count, 1), 3)),
                    Datum.i64(e.rows_sent)])
        return out
    if table_id == T_TPU_HOT_REGIONS:
        rpc = getattr(store, "rpc", None)
        heat = getattr(rpc, "region_heat", None)
        if heat is None:
            return []   # single-node store: no regions, no heat
        cluster = getattr(store, "cluster", None)
        out = []
        for rank, h in enumerate(heat.snapshot(), start=1):
            region = cluster.region_by_id(h["region_id"]) \
                if cluster is not None else None
            out.append([
                Datum.i64(rank), Datum.i64(h["region_id"]),
                _s(region.start.hex()) if region is not None else NULL,
                _s(region.end.hex()) if region is not None
                and region.end is not None else NULL,
                Datum.i64(region.leader_store_id)
                if region is not None else NULL,
                # decayed windows round (not truncate): one fresh access
                # decays to 0.99… within the same statement and must not
                # render as zero
                Datum.i64(round(h["read_rows"])),
                Datum.i64(round(h["read_bytes"])),
                Datum.i64(round(h["write_rows"])),
                Datum.i64(round(h["write_bytes"])),
                Datum.i64(h["total_read_rows"]),
                Datum.i64(h["total_write_rows"]),
                Datum.f64(round(h["heat"], 3))])
        return out
    return []


class StoreVirtualTable(VirtualTableBase):
    """information_schema table bound to the live store (digest
    summaries, region heat) instead of the schema snapshot."""

    def __init__(self, info: TableInfo, store):
        super().__init__(info, "information_schema")
        self.store = store

    def rows(self):
        return rows_for_store(self.store, self.id)


def _s(v: str) -> Datum:
    return Datum.bytes_(v.encode())


def _real_schemas(snapshot):
    """User + system databases, not the virtual ones (ids >= 0)."""
    out = []
    for name in sorted(snapshot.all_schema_names(), key=str.lower):
        db = snapshot.schema_by_name(name)
        if db is not None and db.id >= 0:
            out.append(db)
    return out


def rows_for(snapshot, table_id: int) -> list[list[Datum]]:
    """Synthesize one table's rows from an InfoSchema snapshot."""
    if table_id == T_SCHEMATA:
        return [[_s("def"), _s(db.name), _s(db.charset), _s(db.collate)]
                for db in _real_schemas(snapshot)]
    if table_id == T_TABLES:
        out = []
        for db in _real_schemas(snapshot):
            for t in sorted(snapshot.schema_tables(db.name),
                            key=lambda t: t.info.name.lower()):
                out.append([_s("def"), _s(db.name), _s(t.info.name),
                            _s("BASE TABLE"), _s("tidb-tpu"), NULL, NULL,
                            _s(t.info.collate), _s(t.info.comment)])
        return out
    if table_id == T_COLUMNS:
        out = []
        for db in _real_schemas(snapshot):
            for t in sorted(snapshot.schema_tables(db.name),
                            key=lambda t: t.info.name.lower()):
                for i, c in enumerate(t.info.public_columns()):
                    ft = c.field_type
                    nullable = "NO" if my.has_not_null_flag(ft.flag) \
                        else "YES"
                    key = "PRI" if my.has_pri_key_flag(ft.flag) else (
                        "UNI" if ft.flag & my.UniqueKeyFlag else (
                            "MUL" if ft.flag & my.MultipleKeyFlag else ""))
                    extra = "auto_increment" \
                        if my.has_auto_increment_flag(ft.flag) else ""
                    default = NULL if c.default_value is None \
                        else _s(str(c.default_value))
                    out.append([
                        _s("def"), _s(db.name), _s(t.info.name),
                        _s(c.name), Datum.i64(i + 1), default,
                        _s(nullable), _s(ft.type_name()),
                        _s(ft.compact_str()), _s(key), _s(extra),
                        _s(c.comment)])
        return out
    if table_id == T_STATISTICS:
        out = []
        for db in _real_schemas(snapshot):
            for t in sorted(snapshot.schema_tables(db.name),
                            key=lambda t: t.info.name.lower()):
                for idx in t.info.indices:
                    for seq, ic in enumerate(idx.columns):
                        out.append([
                            _s("def"), _s(db.name), _s(t.info.name),
                            _s("0" if idx.unique else "1"), _s(db.name),
                            _s(idx.name), Datum.i64(seq + 1), _s(ic.name),
                            _s("")])
        return out
    if table_id == T_KEY_COLUMN_USAGE:
        out = []
        for db in _real_schemas(snapshot):
            for t in sorted(snapshot.schema_tables(db.name),
                            key=lambda t: t.info.name.lower()):
                pk = t.info.pk_handle_column()
                if pk is not None:
                    out.append([_s("def"), _s(db.name), _s("PRIMARY"),
                                _s("def"), _s(db.name), _s(t.info.name),
                                _s(pk.name), Datum.i64(1), NULL, NULL,
                                NULL, NULL])
                for idx in t.info.indices:
                    if not idx.unique:
                        continue
                    cname = "PRIMARY" if idx.primary else idx.name
                    for seq, ic in enumerate(idx.columns):
                        out.append([_s("def"), _s(db.name), _s(cname),
                                    _s("def"), _s(db.name),
                                    _s(t.info.name), _s(ic.name),
                                    Datum.i64(seq + 1), NULL, NULL, NULL,
                                    NULL])
                for fk in t.info.foreign_keys:
                    for seq, (c, rc) in enumerate(zip(fk.cols,
                                                      fk.ref_cols)):
                        out.append([_s("def"), _s(db.name), _s(fk.name),
                                    _s("def"), _s(db.name),
                                    _s(t.info.name), _s(c),
                                    Datum.i64(seq + 1),
                                    Datum.i64(seq + 1), _s(db.name),
                                    _s(fk.ref_table), _s(rc)])
        return out
    if table_id == T_REFERENTIAL_CONSTRAINTS:
        out = []
        for db in _real_schemas(snapshot):
            for t in sorted(snapshot.schema_tables(db.name),
                            key=lambda t: t.info.name.lower()):
                for fk in t.info.foreign_keys:
                    out.append([
                        _s("def"), _s(db.name), _s(fk.name), _s("def"),
                        _s(db.name), _s("PRIMARY"), _s("NONE"),
                        _s(fk.on_update or "RESTRICT"),
                        _s(fk.on_delete or "RESTRICT"),
                        _s(t.info.name), _s(fk.ref_table)])
        return out
    if table_id == T_CHARACTER_SETS:
        from tidb_tpu import charset as cset
        return [[_s(c.name), _s(c.default_collation.name), _s(c.desc),
                 Datum.i64(c.maxlen)] for c in cset.get_all_charsets()]
    if table_id == T_COLLATIONS:
        from tidb_tpu import charset as cset
        return [[_s(c.name), _s(c.charset_name), Datum.i64(c.id),
                 _s("Yes" if c.is_default else ""), _s("Yes"),
                 Datum.i64(1)] for c in cset.get_collations()]
    return []


class InfoVirtualTable(VirtualTableBase):
    """information_schema table bound to its owning snapshot — reads are
    self-consistent with the statement's schema view."""

    def __init__(self, info: TableInfo, snapshot_ref):
        super().__init__(info, "information_schema")
        self._snapshot_ref = snapshot_ref  # the owning InfoSchema

    def rows(self):
        return rows_for(self._snapshot_ref, self.id)
