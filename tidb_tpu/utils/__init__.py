"""Small shared utilities (reference: util/ grab-bag, only what's needed)."""

from __future__ import annotations


def prefix_next(prefix: bytes) -> bytes:
    """Smallest key strictly greater than every key with this prefix.
    Reference: kv/key.go Key.PrefixNext — increment with carry; if all bytes
    are 0xFF there is no upper bound (caller treats b'' suffix as +inf)."""
    b = bytearray(prefix)
    for i in range(len(b) - 1, -1, -1):
        if b[i] != 0xFF:
            b[i] += 1
            del b[i + 1:]
            return bytes(b)
    return bytes(prefix) + b"\xff"  # degenerate: unbounded tail sentinel


def escape_string(s: str) -> str:
    """Escape a value for embedding in a single-quoted SQL literal — ONE
    implementation shared by the auth lookup and the grant executors so
    the two paths can never diverge."""
    return s.replace("\\", "\\\\").replace("'", "\\'")
