"""Statement validation pass, run BEFORE planning.

Reference: plan/preprocess.go:24 (Preprocess) → plan/validator.go:28
(Validate): structural checks that belong to the statement itself, not to
name resolution or costing — nested aggregates, CREATE TABLE grammar
(auto_increment rules, multiple primary keys, CHAR length), CREATE INDEX
duplicate columns, stray param markers outside PREPARE.
"""

from __future__ import annotations

from dataclasses import fields as _dc_fields, is_dataclass

from tidb_tpu import errors, mysqldef as my, sqlast as ast


def validate(stmt, in_prepare: bool = False) -> None:
    """Raise on structurally invalid statements (validator.go Validate)."""
    if isinstance(stmt, ast.CreateTableStmt):
        _check_create_table(stmt)
    elif isinstance(stmt, ast.CreateIndexStmt):
        _check_dup_index_columns(stmt.columns)
    _walk_exprs(stmt, in_prepare, in_agg=False, top=True)


def _is_agg_node(node) -> bool:
    return isinstance(node, ast.AggregateFunc)


def _walk_exprs(node, in_prepare: bool, in_agg: bool,
                top: bool = False) -> None:
    """Generic dataclass walk: nested-aggregate and param-marker checks
    (validator.go Enter: ast.AggregateFuncExpr / ast.ParamMarkerExpr).

    A nested query block (scalar subquery, EXISTS, derived table) is its
    own aggregate scope: `sum((select count(c) from u))` is legal — the
    inner count belongs to the inner block."""
    if isinstance(node, ast.ParamMarker):
        # a marker with a bound value is an EXECUTE re-run of a prepared
        # statement; an unbound one outside PREPARE is a syntax error
        if not in_prepare and node.value is None:
            raise errors.ParseError("syntax error, unexpected '?'")
        return
    if not top and isinstance(node, (ast.SelectStmt, ast.UnionStmt)):
        in_agg = False   # fresh scope for the inner block
    entering_agg = _is_agg_node(node)
    if entering_agg and in_agg:
        raise errors.TiDBError(
            "Invalid use of group function", code=1111)
    inner = in_agg or entering_agg
    if is_dataclass(node):
        for f in _dc_fields(node):
            _walk_exprs(getattr(node, f.name), in_prepare, inner)
    elif isinstance(node, (list, tuple)):
        for item in node:
            _walk_exprs(item, in_prepare, inner)


def _check_create_table(stmt: ast.CreateTableStmt) -> None:
    """validator.go checkCreateTableGrammar + checkAutoIncrement."""
    primary_defs = 0
    auto_cols = []
    key_cols = set()
    for cons in stmt.constraints:
        if cons.tp == ast.ConstraintType.PRIMARY_KEY:
            primary_defs += 1
        if cons.keys:
            key_cols.add(cons.keys[0].lower())
    for cd in stmt.cols:
        opts = {o.tp for o in cd.options}
        if ast.ColumnOptionType.PRIMARY_KEY in opts:
            primary_defs += 1
            key_cols.add(cd.name.lower())
        if ast.ColumnOptionType.UNIQUE_KEY in opts:
            key_cols.add(cd.name.lower())
        if cd.tp.tp == my.TypeString and cd.tp.flen > 255:
            raise errors.TiDBError(
                f"Column length too big for column '{cd.name}' (max = "
                "255); use BLOB or TEXT instead", code=1074)
        if ast.ColumnOptionType.AUTO_INCREMENT in opts:
            auto_cols.append(cd)
            if ast.ColumnOptionType.DEFAULT in opts:
                raise errors.TiDBError(
                    f"Invalid default value for '{cd.name}'", code=1067)
    if primary_defs > 1:
        raise errors.TiDBError("Multiple primary key defined", code=1068)
    if len(auto_cols) > 1:
        raise errors.TiDBError(
            "Incorrect table definition; there can be only one auto "
            "column and it must be defined as a key", code=1075)
    if auto_cols:
        cd = auto_cols[0]
        if cd.name.lower() not in key_cols:
            raise errors.TiDBError(
                "Incorrect table definition; there can be only one auto "
                "column and it must be defined as a key", code=1075)
        if cd.tp.tp not in (my.TypeTiny, my.TypeShort, my.TypeInt24,
                            my.TypeLong, my.TypeLonglong):
            raise errors.TiDBError(
                f"Incorrect column specifier for column '{cd.name}'",
                code=1063)
    # duplicate column names inside any key spec
    for cons in stmt.constraints:
        if cons.keys:
            _check_dup_index_columns(cons.keys)


def _check_dup_index_columns(names) -> None:
    """validator.go checkCreateIndexGrammar / checkIndexInfo."""
    seen = set()
    for n in names:
        low = n.lower()
        if low in seen:
            raise errors.TiDBError(f"Duplicate column name '{n}'",
                                   code=1060)
        seen.add(low)
